"""Pallas TPU kernel: fused fixed-exponent Fp power chains.

Why: the ingest pipeline's sqrt/inverse chains are ~381-step
square-and-multiply loops. As XLA scans, every step round-trips the
(batch, 40)-limb state through HBM (~0.15 ms/step at batch 2048 —
bandwidth-bound), so one chain costs ~60+ ms and the ingest stages
stack up ~16 of them. This kernel runs the WHOLE chain with the limb
state resident in VMEM: per step only register/VMEM traffic, turning
the chain compute-bound (~100 vector ops per modular multiply).

Layout: limbs on SUBLANES (40 rows, statically indexed — no lane
shuffles, the failure mode of earlier Pallas attempts), batch on
LANES (128 per grid block). The exponent is a static python int baked
into the kernel via an SMEM bit array + fori_loop.

Used by ops/ingest.py when running on a real TPU; the XLA scan
(fq.pow_const) remains the fallback and the differential oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls.fields import P
from . import limbs as L

NLIMB = 39  # value limbs (see ops/limbs.py)
ROWS = 40  # canonical row count (39 + carry)
PAD_ROWS = 80  # product accumulator rows (79 used, padded to 8k)
LANES = 128  # batch elements per grid block


FOLD_ROWS = 48  # 41 used (limbs 40..80), padded to a sublane multiple


@functools.lru_cache(maxsize=None)
def _fold_rows() -> np.ndarray:
    """(48, 40) int32: row k = canonical limbs of 2^(10*(40+k)) mod P.
    Rows 0..39 fold product limbs 40..79; row 40 folds the explicit
    carry captured out of accumulator row 79 (weight 2^800)."""
    out = np.zeros((FOLD_ROWS, ROWS), np.int32)
    for k in range(41):
        out[k, :NLIMB] = L.int_to_limbs(pow(2, L.BITS * (40 + k), P))
    return out


def _carry(acc, passes: int):
    """Parallel carry passes: limb = limb&1023 + incoming carry.
    Non-negative inputs only. Keeps shape; carries out of the top row
    are folded by the caller's fold step (values stay < 2^31)."""
    for _ in range(passes):
        hi = acc >> L.BITS
        lo = acc - (hi << L.BITS)
        shifted = jnp.concatenate(
            [jnp.zeros((1, acc.shape[1]), jnp.int32), hi[:-1, :]],
            axis=0,
        )
        acc = lo + shifted
    return acc


def _fold_contract_vpu(lo, hi, extra, fold_const):
    """Fold rows 40..79 + the explicit top carry through the constant
    2^(10k) mod P rows as 41 broadcast MACs on the VPU."""
    for k in range(ROWS):
        lo = lo + fold_const[k].reshape(ROWS, 1) * hi[k : k + 1, :]
    return lo + fold_const[ROWS].reshape(ROWS, 1) * extra


def _fold_contract_mxu(lo, hi, extra, f_lo8, f_hi8):
    """The same fold as THREE int8 x int8 -> int32 dot_generals on the
    MXU (quantized-GEMM shape; contraction over the 48-row axis of the
    CONSTANT fold matrix, so the systolic array sees shared weights).

    Exactness (static): hi rows are <= ~1088 and the captured top
    carry <= 64 (see _modmul_core's carry analysis), so the value-side
    hi slice is <= 8; fold rows are canonical limbs < 2^10, so the
    matrix-side hi slice is <= 7. All three accumulations stay far
    inside int32 (<= 96*127*127 < 2^21 per column) and the shifted
    recombination peaks below 2^26 — the same bound as the VPU fold
    sum. The per-lane schoolbook product CANNOT move to the MXU (both
    operands vary per lane — there is no shared contraction matrix);
    the fold is the kernel's one matmul-shaped contraction."""
    s = L.MXU_SLICE_BITS
    W = hi.shape[-1]
    # value side: rows 40..79 + explicit carry, zero-padded to the
    # fold matrix's 48 rows (rows 41..47 of the matrix are zero too)
    hi_all = jnp.concatenate(
        [hi, extra, jnp.zeros((FOLD_ROWS - ROWS - 1, W), jnp.int32)],
        axis=0,
    )
    h_lo, h_hi = L._slice8(hi_all)

    def dg(m8, v8):
        return jax.lax.dot_general(
            m8,
            v8,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )

    c0 = dg(f_lo8, h_lo)
    c1 = dg(
        jnp.concatenate([f_lo8, f_hi8], axis=0),
        jnp.concatenate([h_hi, h_lo], axis=0),
    )
    c2 = dg(f_hi8, h_hi)
    return lo + c0 + ((c1 + (c2 << s)) << s)


def make_modmul(fold_const):
    """Modular-multiply closure over a loaded fold-constant block,
    with the fold contraction picked by the limb backend at TRACE
    time (ops/limbs.get_backend): int8 MXU dots for "mxu", broadcast
    VPU MACs for "vpu". The int8 constant slices are hoisted out of
    the returned closure so chained calls (power chains run hundreds)
    share them."""
    if L.get_backend() == "mxu":
        f_lo8, f_hi8 = L._slice8(fold_const)

        def fold(lo, hi, extra):
            return _fold_contract_mxu(lo, hi, extra, f_lo8, f_hi8)

    else:

        def fold(lo, hi, extra):
            return _fold_contract_vpu(lo, hi, extra, fold_const)

    def mm(a, b):
        return _modmul_core(a, b, fold_const, fold)

    return mm


def _modmul_core(a, b, fold_const, fold):
    """(40, W) x (40, W) canonical non-negative limbs -> (40, W) for
    any lane width W (128 for full blocks; the lane-halving product
    reduction calls at 64..1).

    Schoolbook product into an 80-row accumulator via 40 broadcast
    MACs (static sublane slices), parallel carries, `fold` contraction
    of limbs 40..78 (VPU MACs or MXU int8 dots — see make_modmul),
    final carry + one-row refold."""
    W = b.shape[-1]
    # Schoolbook accumulation as a sum of zero-padded shifted terms:
    # Mosaic lowers neither scatter-add nor value dynamic_slice, but
    # static concatenation + adds vectorize cleanly.
    acc = jnp.zeros((PAD_ROWS, W), jnp.int32)
    for i in range(ROWS):
        term = a[i : i + 1, :] * b  # (40, W)
        parts = []
        if i:
            parts.append(jnp.zeros((i, W), jnp.int32))
        parts.append(term)
        parts.append(
            jnp.zeros((PAD_ROWS - ROWS - i, W), jnp.int32)
        )
        acc = acc + jnp.concatenate(parts, axis=0)
    # limbs <= 40 * 1025^2 < 2^26. Pass 1 brings them <= 1023 + 2^16
    # without losing anything (row 79 only RECEIVES carry in pass 1).
    acc = _carry(acc, 1)
    # Pass 2 with the row-79 outgoing carry captured explicitly: its
    # weight is limb 80 and it folds through fold row 40.
    hi2 = acc >> L.BITS
    lo2 = acc - (hi2 << L.BITS)
    extra = hi2[PAD_ROWS - 1 : PAD_ROWS, :]  # <= 64, weight 2^800
    acc = lo2 + jnp.concatenate(
        [jnp.zeros((1, W), jnp.int32), hi2[:-1, :]], axis=0
    )
    lo = acc[:ROWS, :]
    hi = acc[ROWS:, :]  # rows 40..79, limbs <= ~1088
    lo = fold(lo, hi, extra)
    # fold sum < 41 * 1088 * 1023 < 2^26. Reduce with capture-and-fold
    # rounds: every carry pass captures the row-39 outgoing carry
    # (weight = limb 40) and folds it straight back through fold row 0
    # — a plain carry would silently DROP it. Four rounds bring the
    # worst case down to a canonical-profile value.
    fold0 = fold_const[0].reshape(ROWS, 1)
    for _ in range(4):
        hi_ = lo >> L.BITS
        lo = lo - (hi_ << L.BITS)
        top = hi_[ROWS - 1 : ROWS, :]
        lo = (
            lo
            + jnp.concatenate(
                [jnp.zeros((1, W), jnp.int32), hi_[:-1, :]],
                axis=0,
            )
            + fold0 * top
        )
    return lo


WINDOW = 4  # fixed-window width for pow chains (15-entry table)


def window_schedule(e: int, w: int) -> np.ndarray:
    """MSB-first `w`-bit windows of e, zero-padded at the top so the
    first window is the leading 1..w bits (always nonzero)."""
    nb = e.bit_length()
    nwin = -(-nb // w)
    padded = nwin * w
    return np.array(
        [(e >> (padded - w * (i + 1))) & ((1 << w) - 1)
         for i in range(nwin)],
        np.int32,
    )


def make_windowed_powc(mm, window: int):
    """Windowed fixed-exponent power chain for in-kernel use.

    Square-and-multiply costs 2 modmuls per exponent bit (the multiply
    runs even for 0 bits, then a select drops it). Fixed `window`-bit
    windows cost `window` squarings + ONE table multiply per window:
    ~1.25 modmuls/bit at window 4 — a ~1.55x cut on the chain-dominated
    ingest stages. The table select is a (2^w-1)-way jnp.where chain on
    (ROWS, W) planes, trivial next to a modmul; table[0] is the
    canonical 1 so zero windows multiply by one instead of branching.

    Returns powc(base, win_ref, n_windows) where win_ref holds the
    int32 window values (SMEM) computed by window_schedule()."""

    def powc(base, win_ref, n_windows):
        W = base.shape[-1]
        one = jnp.concatenate(
            [jnp.ones((1, W), jnp.int32),
             jnp.zeros((base.shape[0] - 1, W), jnp.int32)],
            axis=0,
        )
        table = [one, base]
        for _ in range(2, 1 << window):
            table.append(mm(table[-1], base))

        def sel(wv):
            acc = table[0]
            for k in range(1, 1 << window):
                acc = jnp.where(wv == k, table[k], acc)
            return acc

        def body(i, acc):
            for _ in range(window):
                acc = mm(acc, acc)
            return mm(acc, sel(win_ref[i]))

        return jax.lax.fori_loop(1, n_windows, body, sel(win_ref[0]))

    return powc


def _chain_kernel(win_ref, fold_ref, base_ref, out_ref, *, nwin: int):
    fold_const = fold_ref[:]
    base = base_ref[:]
    mm = make_modmul(fold_const)
    powc = make_windowed_powc(mm, WINDOW)
    out_ref[:] = powc(base, win_ref, nwin)


@functools.lru_cache(maxsize=None)
def _chain_call(e: int, n_blocks: int):
    wins = window_schedule(e, WINDOW)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_chain_kernel, nwin=len(wins))

    @jax.jit
    def run(base):  # base: (40, n_blocks*128), limbs on sublanes
        return pl.pallas_call(
            kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct(
                (ROWS, n_blocks * LANES), jnp.int32
            ),
        )(jnp.asarray(wins), jnp.asarray(_fold_rows()), base)

    return run


def pow_const(a: L.Lv, e: int) -> L.Lv:
    """Drop-in for fq.pow_const on TPU: a^(e) for batched canonical
    values, whole chain fused in one Pallas kernel. Batch must be 1-D;
    padded to a multiple of 128 lanes."""
    assert e > 0
    x = L.normalize(a)
    v = x.v  # (batch, NCANON)
    batch = v.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    vt = jnp.transpose(
        jnp.pad(v, ((0, padded - batch), (0, 0)))
    )  # (40, padded) limbs-on-sublanes
    out = _chain_call(e, n_blocks)(vt)
    res = jnp.transpose(out)[:batch, :]
    # HONEST bounds: the kernel's final capture-and-fold rounds leave
    # limbs <= ~1025 everywhere INCLUDING row 39 (fold rows have zero
    # top limbs, but row 39 still receives ordinary carries), so the
    # value can exceed the canonical-profile claim. Downstream
    # normalize()/canon_digits stay sound because the interval
    # machinery sees these wider bounds and reduces accordingly.
    hi = tuple([L.B + 2] * L.NCANON)
    return L.Lv(res, tuple([0] * L.NCANON), hi)

"""Pallas TPU kernels: SSWU map + 3-isogeny (hash-to-G2 field core).

After the pairing/ladder/product kernels, the SSWU+isogeny stage was
the largest remaining device cost (~164 ms of the ~440 ms 2048-set
bucket): ~120 Fq2 multiplies of XLA glue around the already-fused
power chains, each materializing the (batch, 40, 79) banded matrix
through HBM.

The exact-arithmetic split (is_zero / eq / sgn0 need canonical
digits, which stay in XLA where they are cheap):

  host XLA pre :  u^2, Z*u^2, tv = (Z u^2)^2 + Z u^2, tv_zero mask
  KERNEL S     :  tv inverse chain, x1/x2, g(x1), g(x2), and BOTH
                  sqrt-candidate chains per g (general delta bases
                  AND the a1==0 fallback bases — computing all four
                  avoids in-kernel exact zero tests), plus the
                  1/(2t) chains for the y1 assembly
  host XLA mid :  exact selects (a1_zero / QR check / sgn0), picking
                  (x, y) per map — a handful of elementwise ops
  KERNEL I     :  3-isogeny Horner ladders + the shared denominator
                  inverse chain for both maps
  host XLA post:  one complete jacobian add of the two mapped points

Correctness oracle: ops/ingest._sswu + _iso_map (XLA scan path), which
itself is differentially tested against crypto/bls/hash_to_curve.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls import fields as OF
from ..crypto.bls.fields import P
from . import limbs as L
from .pallas_chain import (
    LANES, ROWS, _fold_rows, make_windowed_powc,
    window_schedule,
)

SSWU_WINDOW = 3  # 3-bit windows: 6-entry table, low VMEM pressure
from .pallas_ladder import _norm2, _sub_offset
from .pallas_pairing import _mk_tower

E_SQRT = (P + 1) // 4
E_INV = P - 2


@functools.lru_cache(maxsize=None)
def _bits(e: int) -> np.ndarray:
    n = e.bit_length()
    return np.array(
        [(e >> (n - 1 - i)) & 1 for i in range(n)], np.int32
    )


def _const_plane(x: int) -> np.ndarray:
    limbs = np.zeros((ROWS, 1), np.int32)
    limbs[: L.NLIMB, 0] = L.int_to_limbs(x % P)
    return np.broadcast_to(limbs, (ROWS, LANES)).copy()


@functools.lru_cache(maxsize=None)
def _sswu_consts():
    """Constant planes: -B'/A', B'/(Z A'), A', B', 1/2 (as in
    ops/ingest: SSWU on E2' with the hash_to_curve constants)."""
    from .ingest import A_PRIME, B_PRIME, Z_SSWU

    nba = OF.fq2_mul(OF.fq2_neg(B_PRIME), OF.fq2_inv(A_PRIME))
    x1e = OF.fq2_mul(B_PRIME, OF.fq2_inv(OF.fq2_mul(Z_SSWU, A_PRIME)))
    inv2 = (P + 1) // 2
    return {
        "nba0": _const_plane(nba[0]),
        "nba1": _const_plane(nba[1]),
        "x1e0": _const_plane(x1e[0]),
        "x1e1": _const_plane(x1e[1]),
        "a0": _const_plane(A_PRIME[0]),
        "a1": _const_plane(A_PRIME[1]),
        "b0": _const_plane(B_PRIME[0]),
        "b1": _const_plane(B_PRIME[1]),
        "inv2": _const_plane(inv2),
        "one": _const_plane(1),
    }


_CONST_KEYS = (
    "nba0", "nba1", "x1e0", "x1e1", "a0", "a1", "b0", "b1", "inv2",
    "one",
)

# kernel S output order (per lane): see _sswu_kernel tail
S_OUTS = (
    "x1_0", "x1_1", "x2_0", "x2_1",
    "g1_0", "g1_1", "g2_0", "g2_1",
    "s_1", "ta_gen_1", "tb_gen_1", "ta_z_1", "tb_z_1",
    "y1a_1", "y1b_1",
    "s_2", "ta_gen_2", "tb_gen_2", "ta_z_2", "tb_z_2",
    "y1a_2", "y1b_2",
)


def _sswu_kernel(sqrt_bits, inv_bits, fold_ref, off_ref, *refs):
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    n_in = len(_CONST_KEYS) + 3  # consts + zu2_0,zu2_1,tvz
    ins = [r[:] for r in refs[:n_in]]
    outs = refs[n_in:]
    consts = dict(zip(_CONST_KEYS, ins))
    z_u2 = (ins[-3], ins[-2])
    tvz = ins[-1]  # (ROWS, LANES) broadcast 0/1 mask

    # windowed chains (~1.3 modmuls/bit vs 2 for square-and-multiply);
    # SSWU_WINDOW=3 keeps the 6-entry table's VMEM footprint small in
    # this many-live-plane kernel (pallas_chain.make_windowed_powc)
    powc = make_windowed_powc(F.mm, SSWU_WINDOW)

    n_sqrt = len(window_schedule(E_SQRT, SSWU_WINDOW))
    n_inv = len(window_schedule(E_INV, SSWU_WINDOW))

    # tv = (Z u^2)^2 + Z u^2 over Fq2, recomputed in-kernel (cheaper
    # than 2 more input planes); exceptional-case select via the
    # host-computed exact-zero mask
    zu2sq = F.f2_sqr(z_u2)
    tv = F.f2_add(zu2sq, z_u2)
    tv = (
        jnp.where(tvz != 0, consts["one"], tv[0]),
        jnp.where(tvz != 0, jnp.zeros_like(tv[1]), tv[1]),
    )
    n_tv = F.nrm(
        F.add(F.mm(tv[0], tv[0]), F.mm(tv[1], tv[1]))
    )
    n_tv_inv = powc(n_tv, inv_bits, n_inv)
    tv1 = (
        F.mm(tv[0], n_tv_inv),
        F.mm(F.neg(tv[1]), n_tv_inv),
    )
    # x1 = (-B'/A') * (1 + tv1), exceptional -> B'/(Z A')
    nba = (consts["nba0"], consts["nba1"])
    one_p_tv1 = (F.add(tv1[0], consts["one"]), tv1[1])
    x1_gen = F.f2_mul(nba, one_p_tv1)
    x1 = F.f2_sel(tvz, (consts["x1e0"], consts["x1e1"]), x1_gen)
    x2 = F.f2_mul(z_u2, x1)

    a_p = (consts["a0"], consts["a1"])
    b_p = (consts["b0"], consts["b1"])

    def g_prime(x):
        x2_ = F.f2_sqr(x)
        x3_ = F.f2_mul(x2_, x)
        return F.f2_add(F.f2_add(x3_, F.f2_mul(a_p, x)), b_p)

    gx1 = g_prime(x1)
    gx2 = g_prime(x2)

    def sqrt_parts(g):
        """All candidate chains of the complex sqrt for one Fq2 g."""
        g0, g1 = g
        n = F.nrm(F.add(F.mm(g0, g0), F.mm(g1, g1)))
        s = powc(n, sqrt_bits, n_sqrt)
        delta = F.mm(F.add(g0, s), consts["inv2"])
        delta2 = F.mm(F.sub(g0, s), consts["inv2"])
        ta_gen = powc(delta, sqrt_bits, n_sqrt)
        tb_gen = powc(delta2, sqrt_bits, n_sqrt)
        ta_z = powc(g0, sqrt_bits, n_sqrt)
        tb_z = powc(F.neg(g0), sqrt_bits, n_sqrt)
        # y1 = g1 / (2 t) for both general candidates. t == 0 needs
        # no guard: 0^(P-2) = 0 gives y1 = 0, which simply fails the
        # host's exact y^2 == g verification (fail-closed, same
        # semantics as the scan path's flag)
        def y1_of(t):
            inv = powc(F.small(t, 2), inv_bits, n_inv)
            return F.mm(g1, inv)

        y1a = y1_of(ta_gen)
        y1b = y1_of(tb_gen)
        return [s, ta_gen, tb_gen, ta_z, tb_z, y1a, y1b]

    p1 = sqrt_parts(gx1)
    p2 = sqrt_parts(gx2)
    planes = [
        x1[0], x1[1], x2[0], x2[1],
        gx1[0], gx1[1], gx2[0], gx2[1],
        *p1, *p2,
    ]
    for ref, plane in zip(outs, planes):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _sswu_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    cvec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(zu2_0, zu2_1, tvz):
        n = n_blocks * LANES
        consts = _sswu_consts()
        return pl.pallas_call(
            _sswu_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
            ]
            + [cvec() for _ in _CONST_KEYS]
            + [vec() for _ in range(3)],
            out_specs=[vec() for _ in S_OUTS],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in S_OUTS
            ],
        )(
            jnp.asarray(window_schedule(E_SQRT, SSWU_WINDOW)),
            jnp.asarray(window_schedule(E_INV, SSWU_WINDOW)),
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            *[jnp.asarray(consts[k]) for k in _CONST_KEYS],
            zu2_0, zu2_1, tvz,
        )

    return run


def _prep(v, padded, batch):
    return jnp.transpose(jnp.pad(v, ((0, padded - batch), (0, 0))))


def _out_lv(plane, batch):
    return L.Lv(
        jnp.transpose(plane)[:batch, :],
        tuple([0] * L.NCANON),
        tuple([L.B + 2] * L.NCANON),
    )


def sswu_candidates(u):
    """Run kernel S for a batch of Fq2 draws; returns a dict of Lv
    per S_OUTS name. The caller (ingest._sswu_tpu) finishes the exact
    selects in XLA."""
    from . import fq, tower
    from .ingest import Z_SSWU

    u = tower.fq2_norm(u)
    z = tower.fq2_const(Z_SSWU)
    u2 = tower.fq2_sqr(u)
    z_u2 = tower.fq2_norm(tower.fq2_mul(z, u2))
    tv = tower.fq2_norm(
        tower.fq2_add(tower.fq2_sqr(z_u2), z_u2)
    )
    tv_zero = tower.fq2_is_zero(tv)
    batch = u[0].v.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    tvz_plane = jnp.broadcast_to(
        jnp.pad(tv_zero.astype(jnp.int32), (0, padded - batch))[
            None, :
        ],
        (ROWS, padded),
    )
    outs = _sswu_call(n_blocks)(
        _prep(z_u2[0].v, padded, batch),
        _prep(z_u2[1].v, padded, batch),
        tvz_plane,
    )
    d = {
        name: _out_lv(p, batch) for name, p in zip(S_OUTS, outs)
    }
    d["tv_zero"] = tv_zero
    return d


# ---------------------------------------------------------------------------
# Kernel I: 3-isogeny for both maps + shared denominator inversion
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _iso_const_rows() -> np.ndarray:
    """(32, 40) int32: rows = Fq components of K1(4)+K2(3)+K3(4)+K4(4)
    Fq2 isogeny coefficients, c0 then c1 per coefficient."""
    from ..crypto.bls.hash_to_curve import _K1, _K2, _K3, _K4

    rows = []
    for k in (_K1, _K2, _K3, _K4):
        for c in k:
            rows.append(L.int_to_limbs(c[0] % P))
            rows.append(L.int_to_limbs(c[1] % P))
    out = np.zeros((32, ROWS), np.int32)
    for i, r in enumerate(rows):
        out[i, : L.NLIMB] = r
    return out


def _iso_kernel(inv_bits, fold_ref, off_ref, const_ref, *refs):
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    ins = [r[:] for r in refs[:8]]
    outs = refs[8:]
    consts = const_ref[:]  # (32, 40)

    def kc(i):
        # row i -> (40, LANES) broadcast constant plane
        return jnp.broadcast_to(
            consts[i].reshape(ROWS, 1), (ROWS, LANES)
        )

    def kc2(i):
        return (kc(2 * i), kc(2 * i + 1))

    # coefficient index bases: K1 at 0..3, K2 at 4..6, K3 at 7..10,
    # K4 at 11..14 (fq2 units)
    K1 = [kc2(i) for i in range(0, 4)]
    K2 = [kc2(i) for i in range(4, 7)]
    K3 = [kc2(i) for i in range(7, 11)]
    K4 = [kc2(i) for i in range(11, 15)]

    n_inv = len(window_schedule(E_INV, SSWU_WINDOW))
    powc = make_windowed_powc(F.mm, SSWU_WINDOW)

    def horner(coeffs, x):
        acc = coeffs[-1]
        for c in reversed(coeffs[:-1]):
            acc = F.f2_add(F.f2_mul(acc, x), c)
        return acc

    def f2_inv(a):
        n = F.nrm(F.add(F.mm(a[0], a[0]), F.mm(a[1], a[1])))
        ninv = powc(n, inv_bits, n_inv)
        return (F.mm(a[0], ninv), F.mm(F.neg(a[1]), ninv))

    def iso(x, y):
        x_num = horner(K1, x)
        x_den = horner(K2, x)
        y_num = horner(K3, x)
        y_den = horner(K4, x)
        prod = F.f2_mul(x_den, y_den)
        ip = f2_inv(prod)
        xo = F.f2_mul(x_num, F.f2_mul(ip, y_den))
        yo = F.f2_mul(y, F.f2_mul(y_num, F.f2_mul(ip, x_den)))
        return xo, yo

    xa = (ins[0], ins[1])
    ya = (ins[2], ins[3])
    xb = (ins[4], ins[5])
    yb = (ins[6], ins[7])
    xo_a, yo_a = iso(xa, ya)
    xo_b, yo_b = iso(xb, yb)
    planes = [
        xo_a[0], xo_a[1], yo_a[0], yo_a[1],
        xo_b[0], xo_b[1], yo_b[0], yo_b[1],
    ]
    for ref, plane in zip(outs, planes):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _iso_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(*planes):
        n = n_blocks * LANES
        return pl.pallas_call(
            _iso_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (32, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
            ]
            + [vec() for _ in range(8)],
            out_specs=[vec() for _ in range(8)],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(8)
            ],
        )(
            jnp.asarray(window_schedule(E_INV, SSWU_WINDOW)),
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            jnp.asarray(_iso_const_rows()),
            *planes,
        )

    return run


def iso_map_pair(xa, ya, xb, yb):
    """3-isogeny for two (x, y) Fq2 pairs in one kernel pass; returns
    ((xo_a, yo_a), (xo_b, yo_b)) as canonical-widened Lv tuples."""
    batch = xa[0].v.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    planes = []
    for t in (xa, ya, xb, yb):
        for lv in t:
            planes.append(_prep(L.normalize(lv).v, padded, batch))
    outs = _iso_call(n_blocks)(*planes)
    lvs = [_out_lv(p, batch) for p in outs]
    return (
        ((lvs[0], lvs[1]), (lvs[2], lvs[3])),
        ((lvs[4], lvs[5]), (lvs[6], lvs[7])),
    )

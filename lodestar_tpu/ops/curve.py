"""Vectorized BLS12-381 G1/G2 point arithmetic on TPU limb values.

Reference analog: blst's point ops behind @chainsafe/blst (SURVEY.md
§2.1) — serial Jacobian ladders in C. Here the same Jacobian formulas
are expressed as branch-free jnp ops over batched limb tensors so vmap /
pjit can spread point batches across TPU lanes and chips:

  - Points are (X, Y, Z) Jacobian triples plus an explicit `inf` boolean
    (no Z==0 probing: field equality needs full canonicalization, a bool
    select is ~free).
  - Doubling is unconditional: on prime-order subgroups no point has
    Y == 0, and infinity propagates through the flag.
  - Mixed add assumes T != +-Q, which scalar ladders guarantee for
    scalars k with partial prefixes never congruent to +-1 mod r (true
    for any k < 2^255 fed MSB-first after the explicit-infinity start);
    the T == infinity case is handled by the flag select.
  - Scalar multiplication is an MSB-first double-and-add `lax.scan` over
    the (secret-independent-shape) bit tensor; per-element bits select
    between T and T+Q, so one compiled ladder serves the whole batch.

The generic `_Ops` indirection instantiates the same formulas for G1
(coords in Fq) and G2 (coords in Fq2 on the twist). Correctness oracle:
lodestar_tpu/crypto/bls/curve.py (blst-KAT-validated).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls.fields import P
from . import fq, tower
from . import limbs as L


@dataclass(frozen=True)
class _Ops:
    """Field-op table: same Jacobian formulas for Fq (G1) and Fq2 (G2)."""

    add: Callable
    sub: Callable
    neg: Callable
    mul: Callable
    sqr: Callable
    mul_small: Callable
    norm: Callable  # reduce to canonical profile (scan-carry stable)
    select: Callable
    const: Callable  # (int-or-pair, batch_shape) -> element
    eq: Callable
    is_zero: Callable


def _fq_norm(a):
    return L.normalize(a)


def _fq2_norm(a):
    return (L.normalize(a[0]), L.normalize(a[1]))


FQ_OPS = _Ops(
    add=L.add,
    sub=L.sub,
    neg=L.neg,
    mul=fq.mul,
    sqr=fq.sqr,
    mul_small=L.mul_small,
    norm=_fq_norm,
    select=fq.select,
    const=lambda x, batch=(): L.const(x, batch),
    eq=fq.eq,
    is_zero=fq.is_zero,
)

FQ2_OPS = _Ops(
    add=tower.fq2_add,
    sub=tower.fq2_sub,
    neg=tower.fq2_neg,
    mul=tower.fq2_mul,
    sqr=tower.fq2_sqr,
    mul_small=lambda a, k: tower.fq2_mul_small(a, k),
    norm=_fq2_norm,
    select=tower.fq2_select,
    const=lambda x, batch=(): tower.fq2_const(x, batch),
    eq=tower.fq2_eq,
    is_zero=tower.fq2_is_zero,
)


@jax.tree_util.register_pytree_node_class
@dataclass
class JacPoint:
    """Batched Jacobian point: coords of one field, inf flag per element."""

    x: Any
    y: Any
    z: Any
    inf: jax.Array

    def tree_flatten(self):
        return (self.x, self.y, self.z, self.inf), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def jac_normalize(ops: _Ops, p: JacPoint) -> JacPoint:
    """Canonical limb profile on all coords (stable scan carry type)."""
    return JacPoint(ops.norm(p.x), ops.norm(p.y), ops.norm(p.z), p.inf)


def jac_select(ops: _Ops, mask, a: JacPoint, b: JacPoint) -> JacPoint:
    return JacPoint(
        ops.select(mask, a.x, b.x),
        ops.select(mask, a.y, b.y),
        ops.select(mask, a.z, b.z),
        jnp.where(mask, a.inf, b.inf),
    )


def jac_infinity(ops: _Ops, batch_shape=()) -> JacPoint:
    one = ops.norm(ops.const(_one_of(ops), batch_shape))
    return JacPoint(
        one, one, one, jnp.ones(batch_shape, jnp.bool_)
    )


def _one_of(ops: _Ops):
    return 1 if ops is FQ_OPS else (1, 0)


def jac_from_affine(ops: _Ops, x, y, inf=None) -> JacPoint:
    batch = jnp.shape(inf) if inf is not None else _batch_shape(ops, x)
    one = ops.norm(ops.const(_one_of(ops), batch))
    if inf is None:
        inf = jnp.zeros(batch, jnp.bool_)
    return JacPoint(ops.norm(x), ops.norm(y), one, inf)


def _batch_shape(ops: _Ops, x):
    v = x.v if ops is FQ_OPS else x[0].v
    return v.shape[:-1]


def jac_double(ops: _Ops, p: JacPoint) -> JacPoint:
    """dbl-2009-l (a = 0). Unconditional: Y == 0 never occurs on the
    prime-order subgroup; infinity rides the flag."""
    A = ops.sqr(p.x)
    Bv = ops.sqr(p.y)
    C = ops.sqr(Bv)
    t = ops.sqr(ops.add(p.x, Bv))
    D = ops.mul_small(ops.norm(ops.sub(ops.sub(t, A), C)), 2)
    E = ops.mul_small(A, 3)
    F = ops.sqr(E)
    x3 = ops.norm(ops.sub(F, ops.mul_small(D, 2)))
    y3 = ops.norm(
        ops.sub(ops.mul(E, ops.norm(ops.sub(D, x3))), ops.mul_small(C, 8))
    )
    z3 = ops.norm(ops.mul_small(ops.mul(p.y, p.z), 2))
    return JacPoint(x3, y3, z3, p.inf)


def jac_mixed_add(ops: _Ops, p: JacPoint, qx, qy, q_inf=None) -> JacPoint:
    """p + (qx, qy) with q affine. Requires p != +-q (see module doc);
    p == infinity and q == infinity handled via flags."""
    z2 = ops.sqr(p.z)
    z3 = ops.mul(z2, p.z)
    mu = ops.norm(ops.sub(ops.mul(qx, z2), p.x))  # x_q*Z^2 - X
    th = ops.norm(ops.sub(ops.mul(qy, z3), p.y))  # y_q*Z^3 - Y
    mu2 = ops.sqr(mu)
    mu3 = ops.mul(mu2, mu)
    xmu2 = ops.mul(p.x, mu2)
    x3 = ops.norm(
        ops.sub(ops.sub(ops.sqr(th), mu3), ops.mul_small(xmu2, 2))
    )
    y3 = ops.norm(
        ops.sub(
            ops.mul(th, ops.norm(ops.sub(xmu2, x3))), ops.mul(p.y, mu3)
        )
    )
    z3v = ops.norm(ops.mul(p.z, mu))
    out = JacPoint(x3, y3, z3v, jnp.zeros_like(p.inf))
    # p at infinity -> q
    q_as_jac = jac_from_affine(ops, qx, qy)
    out = jac_select(ops, p.inf, JacPoint(q_as_jac.x, q_as_jac.y, q_as_jac.z, jnp.zeros_like(p.inf)), out)
    if q_inf is not None:
        out = jac_select(ops, q_inf, p, out)
    return out


def jac_add(ops: _Ops, p: JacPoint, q: JacPoint) -> JacPoint:
    """Complete Jacobian+Jacobian addition (add-2007-bl shape) with
    select fallbacks for p == q (double) and p == -q (infinity). Used in
    MSM reduction trees where operand equality is data-dependent."""
    z1z1 = ops.sqr(p.z)
    z2z2 = ops.sqr(q.z)
    u1 = ops.mul(p.x, z2z2)
    u2 = ops.mul(q.x, z1z1)
    s1 = ops.mul(ops.mul(p.y, q.z), z2z2)
    s2 = ops.mul(ops.mul(q.y, p.z), z1z1)
    h = ops.norm(ops.sub(u2, u1))
    r = ops.norm(ops.sub(s2, s1))
    h_zero = ops.is_zero(h)
    r_zero = ops.is_zero(r)
    h2 = ops.sqr(h)
    h3 = ops.mul(h2, h)
    u1h2 = ops.mul(u1, h2)
    x3 = ops.norm(
        ops.sub(ops.sub(ops.sqr(r), h3), ops.mul_small(u1h2, 2))
    )
    y3 = ops.norm(
        ops.sub(ops.mul(r, ops.norm(ops.sub(u1h2, x3))), ops.mul(s1, h3))
    )
    z3 = ops.norm(ops.mul(ops.mul(p.z, q.z), h))
    generic = JacPoint(x3, y3, z3, p.inf | q.inf)
    doubled = jac_double(ops, p)
    out = jac_select(ops, h_zero & r_zero & ~p.inf & ~q.inf, doubled, generic)
    # p == -q -> infinity
    both = ~p.inf & ~q.inf
    out_inf = jnp.where(both & h_zero & ~r_zero, True, out.inf)
    out = JacPoint(out.x, out.y, out.z, out_inf)
    out = jac_select(ops, p.inf, q, out)
    out = jac_select(ops, q.inf, p, out)
    return out


def jac_add_incomplete(ops: _Ops, p: JacPoint, q: JacPoint) -> JacPoint:
    """Jacobian addition WITHOUT the p == ±q fallback paths.

    Sound for reduction trees over random-linear-combination terms: the
    weights r_i are secret verifier randomness, so an adversary cannot
    force equal partial sums except with negligible probability — and a
    collision yields garbage coordinates, which fail the final pairing
    check (fail-closed; the caller's per-set retry path takes over).
    Infinity inputs are still handled exactly via the flags. Dropping
    the is_zero/doubling selects halves the compiled body size — the
    add is the scan body of jac_sum_scan, so compile time matters.
    """
    z1z1 = ops.sqr(p.z)
    z2z2 = ops.sqr(q.z)
    u1 = ops.mul(p.x, z2z2)
    u2 = ops.mul(q.x, z1z1)
    s1 = ops.mul(ops.mul(p.y, q.z), z2z2)
    s2 = ops.mul(ops.mul(q.y, p.z), z1z1)
    h = ops.norm(ops.sub(u2, u1))
    r = ops.norm(ops.sub(s2, s1))
    h2 = ops.sqr(h)
    h3 = ops.mul(h2, h)
    u1h2 = ops.mul(u1, h2)
    x3 = ops.norm(
        ops.sub(ops.sub(ops.sqr(r), h3), ops.mul_small(u1h2, 2))
    )
    y3 = ops.norm(
        ops.sub(ops.mul(r, ops.norm(ops.sub(u1h2, x3))), ops.mul(s1, h3))
    )
    z3 = ops.norm(ops.mul(ops.mul(p.z, q.z), h))
    out = JacPoint(x3, y3, z3, p.inf | q.inf)
    out = jac_select(ops, p.inf, q, out)
    out = jac_select(ops, q.inf, p, out)
    return out


def jac_sum_scan(ops: _Ops, p: JacPoint, par: int = 8) -> JacPoint:
    """Batch-sum via a two-level reduction tuned for XLA compile time:
    a `lax.scan` of par-wide incomplete adds over n/par chunks (ONE
    compiled body regardless of n) followed by a log2(par)-deep unrolled
    tree. Replaces the fully unrolled log2(n) tree whose every level
    compiled its own large add (VERDICT r1: fused-kernel compile blowup).
    The `par` axis is also the natural mesh-sharding axis multi-chip."""
    p = jac_normalize(ops, p)
    n = _batch_shape(ops, p.x)[0]
    if n <= par:
        return jac_sum(ops, p)
    chunks = -(-n // par)
    pad = chunks * par - n
    if pad:
        pad_inf = jac_infinity(ops, (pad,) + _batch_shape(ops, p.x)[1:])
        p = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], 0), p, pad_inf
        )

    def reshape(t):
        return t.reshape((chunks, par) + t.shape[1:])

    stacked = jax.tree.map(reshape, p)
    acc0 = jac_infinity(ops, (par,) + _batch_shape(ops, p.x)[1:])

    def body(acc, q):
        return jac_normalize(ops, jac_add_incomplete(ops, acc, q)), None

    acc, _ = jax.lax.scan(body, jac_normalize(ops, acc0), stacked)
    # unrolled log2(par) tree over the accumulator lanes
    m = par
    while m > 1:
        half = m // 2
        bot = jax.tree.map(lambda t: t[:half], acc)
        top = jax.tree.map(lambda t: t[half:m], acc)
        acc = jac_add_incomplete(ops, bot, top)
        m = half
    return acc


def scalar_mul(ops: _Ops, qx, qy, bits: jax.Array, q_inf=None) -> JacPoint:
    """[k]Q for per-element scalars given as a bit tensor.

    bits: (..., nbits) bool, MSB first, broadcast-compatible with the
    point batch. One `lax.scan` over the bit axis; per element the add is
    applied under a select. Reference analog: blst scalar mult used by
    aggregateWithRandomness (SURVEY.md §2.2 same-message aggregation).
    """
    qx, qy = ops.norm(qx), ops.norm(qy)
    batch = jnp.broadcast_shapes(
        _batch_shape(ops, qx), bits.shape[:-1]
    )
    acc0 = jac_infinity(ops, batch)
    bits_t = jnp.moveaxis(
        jnp.broadcast_to(bits, batch + (bits.shape[-1],)), -1, 0
    )

    def body(acc, bit):
        acc = jac_double(ops, acc)
        added = jac_mixed_add(ops, acc, qx, qy, q_inf)
        acc = jac_select(ops, bit, added, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, acc0, bits_t)
    return acc


def scalars_to_bits(ks, nbits: int) -> jax.Array:
    """Host: python ints -> (len(ks), nbits) bool tensor, MSB first.
    Vectorized via byte packing + np.unpackbits."""
    nbytes = (nbits + 7) // 8
    assert all(0 <= int(k) < (1 << nbits) for k in ks), "scalar out of range"
    raw = b"".join(int(k).to_bytes(nbytes, "big") for k in ks)
    mat = np.frombuffer(raw, np.uint8).reshape(len(ks), nbytes)
    bits = np.unpackbits(mat, axis=1, bitorder="big")
    return jnp.asarray(bits[:, -nbits:].astype(np.bool_))


def jac_sum(ops: _Ops, p: JacPoint) -> JacPoint:
    """Reduce a batch of points (leading axis) to one by a log-depth tree
    of complete adds — the device-side analog of blst aggregate()."""
    n = _batch_shape(ops, p.x)[0]
    while n > 1:
        half = (n + 1) // 2
        top = jax.tree.map(lambda t: t[half : half + (n - half)], p)
        bot = jax.tree.map(lambda t: t[:half], p)
        if n - half < half:  # odd: pad top with infinity
            # canonical profiles on both sides -> identical treedefs
            pad_inf = jac_infinity(
                ops, (half - (n - half),) + _batch_shape(ops, p.x)[1:]
            )
            top = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b], 0), top, pad_inf
            )
        p = jac_add(ops, bot, top)
        n = half
    return p


# ---------------------------------------------------------------------------
# Host conversions (affine ints <-> device Jacobian batches)
# ---------------------------------------------------------------------------


def g1_batch_from_ints(pts) -> JacPoint:
    """[(x, y) | None]  ->  batched G1 JacPoint (None = infinity)."""
    xs = [p[0] if p else 0 for p in pts]
    ys = [p[1] if p else 1 for p in pts]
    inf = jnp.asarray([p is None for p in pts])
    return jac_from_affine(FQ_OPS, L.from_ints(xs), L.from_ints(ys), inf)


def g2_batch_from_ints(pts) -> JacPoint:
    """[((x0,x1), (y0,y1)) | None] -> batched G2 JacPoint on the twist."""
    xs = tower.fq2_from_ints([p[0] if p else (0, 0) for p in pts])
    ys = tower.fq2_from_ints([p[1] if p else (1, 0) for p in pts])
    inf = jnp.asarray([p is None for p in pts])
    return jac_from_affine(FQ2_OPS, xs, ys, inf)


def _to_affine_ints_one(ops, x, y, z, inf):
    if inf:
        return None
    if ops is FQ_OPS:
        zi = F_inv_int(z)
        return (x * zi * zi % P, y * zi * zi * zi % P)
    from ..crypto.bls import fields as OF

    zi = OF.fq2_inv(z)
    zi2 = OF.fq2_sqr(zi)
    zi3 = OF.fq2_mul(zi2, zi)
    return (OF.fq2_mul(x, zi2), OF.fq2_mul(y, zi3))


def F_inv_int(a: int) -> int:
    return pow(a, P - 2, P)


def jac_to_affine_ints(ops: _Ops, p: JacPoint):
    """Host: batched device point -> list of affine int tuples (None=inf)."""
    inf = np.asarray(jax.device_get(p.inf)).reshape(-1)
    if ops is FQ_OPS:
        xs = fq.to_int(p.x).reshape(-1)
        ys = fq.to_int(p.y).reshape(-1)
        zs = fq.to_int(p.z).reshape(-1)
        return [
            _to_affine_ints_one(ops, int(x), int(y), int(z), i)
            for x, y, z, i in zip(xs, ys, zs, inf)
        ]
    xs = tower.fq2_to_ints(p.x)
    ys = tower.fq2_to_ints(p.y)
    zs = tower.fq2_to_ints(p.z)
    return [
        _to_affine_ints_one(
            ops,
            tuple(int(v) for v in x),
            tuple(int(v) for v in y),
            tuple(int(v) for v in z),
            i,
        )
        for x, y, z, i in zip(xs, ys, zs, inf)
    ]

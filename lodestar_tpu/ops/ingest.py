"""Device-side signature-set ingestion: G2 decompression + hash-to-G2.

Why this exists: the deployment host has ONE CPU core; decompressing a
signature there costs ~0.5 ms and hashing a message to G2 ~1.8 ms, so
host prep caps the verifier at a few hundred sets/s no matter how fast
the pairing kernels get (VERDICT r2 weak #2 follow-up). Both steps are
pure field arithmetic, so they move onto the TPU as batched programs;
the host keeps only byte parsing, canonicality checks, and
expand_message_xmd (SHA-256, microseconds).

Reference analog: blst's sgn0/decompress + hash_to_curve
(@chainsafe/blst; consensus p2p spec BLS12-381 G2 point encoding;
RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_). Correctness oracles:
crypto/bls/curve.g2_from_bytes and crypto/bls/hash_to_curve.

Algorithms, chosen for chain economy (fixed-exponent Fp scans are the
dominant cost; Fp chains are ~3x cheaper than Fq2 chains):

- fq2 sqrt by the complex method: for a = a0 + a1*u with u^2 = -1,
  sqrt(a) = t + (a1/(2t))*u where t^2 = (a0 ± sqrt(a0^2+a1^2))/2.
  Four Fp chains (norm sqrt, two delta sqrts with the a1==0 special
  case folded into the bases by selects, one inversion), all
  candidates verified by squaring — the validity flag doubles as the
  QR test, so adversarial non-points are rejected on device.
- subgroup check via psi: Q in G2 iff psi(Q) == [x]Q (Bowe);
  the 64-bit |x| ladder is a scan with CONSTANT bits.
- cofactor clearing via the psi decomposition (RFC 9380 App. G.4),
  same as the host C backend (csrc/bls381.c).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls import fields as OF
from ..crypto.bls.fields import P
from . import curve as C
from . import fq
from . import limbs as L
from . import tower

# BLS parameter |x| (the curve's generator parameter, not a coordinate)
X_ABS = 0xD201000000010000

# SSWU constants on E2': y^2 = x^3 + A'x + B' (hash_to_curve.py:22-24)
A_PRIME = (0, 240)
B_PRIME = (1012, 1012)
Z_SSWU = (-2 % P, -1 % P)

# psi coefficients — derived from the oracle at import (curve.py:187)
_PSI_X = OF.fq2_inv(OF.fq2_pow(OF.XI, (P - 1) // 3))
_PSI_Y = OF.fq2_inv(OF.fq2_pow(OF.XI, (P - 1) // 2))

_HALF_MODP = (P - 1) // 2


def _c2(v, batch=()):
    return tower.fq2_const(v, batch)


@functools.lru_cache(maxsize=None)
def _x_bits():
    # numpy, not jnp: a cached device array created during a jit trace
    # would leak that trace's tracer (same pitfall as fq._ladder)
    return np.array(
        [(X_ABS >> (63 - i)) & 1 for i in range(64)], np.bool_
    )


# ---------------------------------------------------------------------------
# fq2 square root (flagged)
# ---------------------------------------------------------------------------


def _sqrt_bases(a0, a1_zero, delta, delta2):
    """Fold the a1==0 special case into the candidate bases:
      base_a = a0    (y = (sqrt(a0), 0) when a0 is a QR)
      base_b = -a0   (y = (0, sqrt(-a0)) otherwise; -1 is a non-QR)
    SHARED by the XLA scan path and the Pallas finisher — this select
    tree must never drift between them."""
    base_a = fq.select(a1_zero, fq.normalize(a0), delta)
    base_b = fq.select(
        a1_zero, fq.normalize(fq.neg(a0)), delta2
    )
    return base_a, base_b


def _sqrt_assemble(a, a1_zero, ok_a, ta, tb, y1_gen):
    """Candidate assembly + exact verification (the validity flag
    doubles as the QR test). SHARED by both sqrt paths."""
    a0, a1 = a
    zero = fq.const(0, ())
    t = fq.select(ok_a, ta, tb)
    cand_y0 = fq.select(a1_zero, fq.select(ok_a, ta, zero), t)
    cand_y1 = fq.select(a1_zero, fq.select(ok_a, zero, tb), y1_gen)
    y = (fq.normalize(cand_y0), fq.normalize(cand_y1))
    sq = tower.fq2_sqr(y)
    is_square = jnp.logical_and(
        fq.eq(sq[0], a0), fq.eq(sq[1], a1)
    )
    return y, is_square


def fq2_sqrt_flagged(a):
    """(y, is_square): y with y^2 == a when is_square; branch-free.

    Complex method over u^2 = -1; the a1 == 0 case folds into the two
    delta chains by selecting the bases (see module docstring)."""
    a0, a1 = a
    a1_zero = fq.is_zero(a1)
    n = fq.add(fq.sqr(a0), fq.sqr(a1))
    s = fq.pow_const(n, (P + 1) // 4)
    inv2 = fq.const((P + 1) // 2, ())  # 1/2 mod P
    delta = fq.mul(fq.add(a0, s), inv2)
    delta2 = fq.mul(fq.sub(a0, s), inv2)
    base_a, base_b = _sqrt_bases(a0, a1_zero, delta, delta2)
    ta = fq.pow_const(base_a, (P + 1) // 4)
    tb = fq.pow_const(base_b, (P + 1) // 4)
    # one inversion serves y1 = a1 / (2t) for both t candidates;
    # select the t that squares to its base (guard zero with 1)
    ok_a = fq.eq(fq.sqr(ta), base_a)
    t = fq.select(ok_a, ta, tb)
    one = fq.const(1, ())
    t_guard = fq.select(fq.is_zero(t), one, t)
    y1_gen = fq.mul(a1, fq.inv(fq.mul_small(t_guard, 2)))
    return _sqrt_assemble(a, a1_zero, ok_a, ta, tb, y1_gen)


# ---------------------------------------------------------------------------
# lexicographic "greater than (P-1)/2" for the compression sign bit
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _half_digits():
    return fq._digits_of(_HALF_MODP)  # numpy (see _x_bits note)


def _gt_half(x: L.Lv) -> jax.Array:
    """value(x) mod P > (P-1)/2, elementwise."""
    d = fq.canon_digits(x)
    diff = d - jnp.asarray(_half_digits())
    nz = diff != 0
    ndig = d.shape[-1]
    idx = (ndig - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    msd = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    return msd > 0


def _sgn0(y) -> jax.Array:
    """RFC 9380 sgn0 for m=2 (fields.py:104)."""
    d0 = fq.canon_digits(y[0])
    s0 = (d0[..., 0] & 1).astype(bool)
    z0 = jnp.all(d0 == 0, axis=-1)
    d1 = fq.canon_digits(y[1])
    s1 = (d1[..., 0] & 1).astype(bool)
    return jnp.logical_or(s0, jnp.logical_and(z0, s1))


# ---------------------------------------------------------------------------
# psi endomorphism + subgroup check + cofactor clearing
# ---------------------------------------------------------------------------


def _fq2_conj(a):
    return (a[0], fq.normalize(fq.neg(a[1])))


def jac_psi(p: C.JacPoint) -> C.JacPoint:
    """(X, Y, Z) -> (CX*conj(X), CY*conj(Y), conj(Z))."""
    batch = ()
    cx = _c2(_PSI_X, batch)
    cy = _c2(_PSI_Y, batch)
    return C.JacPoint(
        tower.fq2_mul(_fq2_conj(p.x), cx),
        tower.fq2_mul(_fq2_conj(p.y), cy),
        _fq2_conj(p.z),
        p.inf,
    )


def jac_neg(p: C.JacPoint) -> C.JacPoint:
    return C.JacPoint(
        p.x,
        (fq.normalize(fq.neg(p.y[0])), fq.normalize(fq.neg(p.y[1]))),
        p.z,
        p.inf,
    )


def _mul_x_abs(p: C.JacPoint, batch) -> C.JacPoint:
    """[|x|]P via the constant-bit scan ladder."""
    bits = jnp.broadcast_to(
        jnp.asarray(_x_bits()), tuple(batch) + (64,)
    )
    # scalar_mul takes affine inputs; p is jacobian from upstream.
    # Use a dedicated jacobian ladder instead.
    bits_t = jnp.moveaxis(bits, -1, 0)
    acc0 = C.jac_infinity(C.FQ2_OPS, tuple(batch))

    def body(acc, bit):
        acc = C.jac_double(C.FQ2_OPS, acc)
        added = C.jac_add(C.FQ2_OPS, acc, p)
        return C.jac_select(C.FQ2_OPS, bit, added, acc), None

    acc, _ = jax.lax.scan(body, acc0, bits_t)
    return acc


def _jac_to_affine(p: C.JacPoint):
    """Batched jacobian -> affine via one Fermat inversion (a single
    fused Pallas chain on TPU). Infinity slots produce garbage coords
    (Z may be 0 -> inv gives 0) — callers carry p.inf."""
    zinv = tower.fq2_inv(p.z)
    zinv2 = tower.fq2_sqr(zinv)
    x = tower.fq2_mul(p.x, zinv2)
    y = tower.fq2_mul(p.y, tower.fq2_mul(zinv2, zinv))
    return tower.fq2_norm(x), tower.fq2_norm(y)


def _mul_x(p: C.JacPoint, batch) -> C.JacPoint:
    """[x]P for the (negative) parameter x.

    TPU: one Fermat inversion to affine (fused Pallas chain), then the
    VMEM-resident Pallas ladder — the XLA scan ladder round-trips the
    jacobian state through HBM on all 64 steps and measured ~550 ms at
    batch 2048 (two of them dominated the cofactor stage, round-4
    profile). Elsewhere: the jacobian scan ladder."""
    if jax.default_backend() == "tpu" and len(tuple(batch)) == 1:
        from . import pallas_ladder as PL

        ax, ay = _jac_to_affine(p)
        return jac_neg(
            PL.g2_scalar_mul_static(ax, ay, X_ABS, p.inf)
        )
    return jac_neg(_mul_x_abs(p, batch))


def jac_eq(a: C.JacPoint, b: C.JacPoint) -> jax.Array:
    """Jacobian equality (cross-multiplied), infinity-aware."""
    za2 = tower.fq2_sqr(a.z)
    zb2 = tower.fq2_sqr(b.z)
    xl = tower.fq2_mul(a.x, zb2)
    xr = tower.fq2_mul(b.x, za2)
    za3 = tower.fq2_mul(za2, a.z)
    zb3 = tower.fq2_mul(zb2, b.z)
    yl = tower.fq2_mul(a.y, zb3)
    yr = tower.fq2_mul(b.y, za3)
    eq_xy = jnp.logical_and(
        jnp.logical_and(fq.eq(xl[0], xr[0]), fq.eq(xl[1], xr[1])),
        jnp.logical_and(fq.eq(yl[0], yr[0]), fq.eq(yl[1], yr[1])),
    )
    both_inf = jnp.logical_and(a.inf, b.inf)
    either_inf = jnp.logical_or(a.inf, b.inf)
    return jnp.where(either_inf, both_inf, eq_xy)


def g2_in_subgroup(p: C.JacPoint, batch) -> jax.Array:
    """psi(Q) == [x]Q (Bowe's fast check; csrc analog). Callers pass
    an AFFINE-constructed point (jac_from_affine), so on TPU the |x|
    ladder runs as the fused Pallas kernel."""
    if jax.default_backend() == "tpu" and len(tuple(batch)) == 1:
        from . import pallas_ladder as PL

        xq = jac_neg(
            PL.g2_scalar_mul_static(p.x, p.y, X_ABS, p.inf)
        )
        return jac_eq(jac_psi(p), xq)
    return jac_eq(jac_psi(p), _mul_x(p, batch))


def g2_clear_cofactor(p: C.JacPoint, batch) -> C.JacPoint:
    """RFC 9380 App. G.4: (x^2-x-1)P + (x-1)psi(P) + psi^2(2P)."""
    ops = C.FQ2_OPS
    t1 = _mul_x(p, batch)
    t2 = jac_psi(p)
    t3 = jac_psi(jac_psi(C.jac_double(ops, p)))
    t3 = C.jac_add(ops, t3, jac_neg(t2))
    t2 = _mul_x(C.jac_add(ops, t1, t2), batch)
    t3 = C.jac_add(ops, t3, t2)
    t3 = C.jac_add(ops, t3, jac_neg(t1))
    return C.jac_add(ops, t3, jac_neg(p))


# ---------------------------------------------------------------------------
# G2 decompression
# ---------------------------------------------------------------------------


def g2_sqrt_with_sign(x, sign_bit):
    """First half of decompression: y from the curve equation + QR
    flag, sign selected per the spec's lexicographic rule. Shared by
    g2_decompress and the kernels stage split (bls/kernels.py
    _stage_g2_sqrt) so the sign rule cannot drift between copies."""
    x = tower.fq2_norm(x)
    b = _c2((4, 4))  # rhs = x^3 + 4(1+u)
    rhs = tower.fq2_add(
        tower.fq2_mul(tower.fq2_sqr(x), x), b
    )
    y, is_qr = fq2_sqrt_flagged(tower.fq2_norm(rhs))
    # spec sign: flag == (y_im > half) unless y_im == 0, then y_re
    im_zero = fq.is_zero(y[1])
    computed = jnp.where(im_zero, _gt_half(y[0]), _gt_half(y[1]))
    flip = computed != sign_bit
    y_neg = (fq.normalize(fq.neg(y[0])), fq.normalize(fq.neg(y[1])))
    y = tower.fq2_select(flip, y_neg, y)
    return x, y, is_qr


def g2_decompress(x, sign_bit, batch):
    """x: fq2 limb batch (canonical, already checked < P on host);
    sign_bit: (batch,) bool (the compressed encoding's a_flag).
    Returns (JacPoint, valid): valid covers on-curve (QR) and G2
    subgroup membership."""
    x, y, is_qr = g2_sqrt_with_sign(x, sign_bit)
    q = C.jac_from_affine(C.FQ2_OPS, x, y)
    valid = jnp.logical_and(is_qr, g2_in_subgroup(q, batch))
    return q, valid


# ---------------------------------------------------------------------------
# hash-to-G2 (device part: SSWU + isogeny + cofactor; host does
# expand_message_xmd -> u0, u1)
# ---------------------------------------------------------------------------


def _g_prime(x):
    """g(x) on E2': x^3 + A'x + B'."""
    a = _c2(A_PRIME)
    b = _c2(B_PRIME)
    return tower.fq2_add(
        tower.fq2_add(
            tower.fq2_mul(tower.fq2_sqr(x), x), tower.fq2_mul(a, x)
        ),
        b,
    )


def _sswu(u):
    """u -> (x, y) on E2' (hash_to_curve.py map_to_curve_sswu),
    branch-free: both gx1 and gx2 square roots computed, selects pick
    the square one. The tv==0 exceptional case selects the constant
    x1 = B'/(Z*A')."""
    z = _c2(Z_SSWU)
    u2 = tower.fq2_sqr(u)
    z_u2 = tower.fq2_mul(z, u2)
    tv = tower.fq2_norm(tower.fq2_add(tower.fq2_sqr(z_u2), z_u2))
    tv_zero = tower.fq2_is_zero(tv)
    tv_guard = tower.fq2_select(
        tv_zero, _c2((1, 0)), tv
    )
    tv1 = tower.fq2_inv(tv_guard)
    # x1 = (-B/A)(1 + tv1); exceptional: B/(Z A)
    neg_b_over_a = _c2(
        OF.fq2_mul(OF.fq2_neg(B_PRIME), OF.fq2_inv(A_PRIME))
    )
    x1_gen = tower.fq2_mul(
        neg_b_over_a, tower.fq2_add(_c2((1, 0)), tv1)
    )
    x1_exc = _c2(
        OF.fq2_mul(B_PRIME, OF.fq2_inv(OF.fq2_mul(Z_SSWU, A_PRIME)))
    )
    x1 = tower.fq2_select(tv_zero, x1_exc, x1_gen)
    gx1 = tower.fq2_norm(_g_prime(x1))
    y1, ok1 = fq2_sqrt_flagged(gx1)
    x2 = tower.fq2_mul(z_u2, x1)
    gx2 = tower.fq2_norm(_g_prime(x2))
    y2, _ok2 = fq2_sqrt_flagged(gx2)
    x = tower.fq2_select(ok1, x1, x2)
    y = tower.fq2_select(ok1, y1, y2)
    # sgn0 correction
    flip = _sgn0(u) != _sgn0(y)
    y = tower.fq2_select(
        flip,
        (fq.normalize(fq.neg(y[0])), fq.normalize(fq.neg(y[1]))),
        y,
    )
    return x, y


def _iso_consts():
    # constants materialize per trace (cached jnp would leak tracers)
    from ..crypto.bls.hash_to_curve import _K1, _K2, _K3, _K4

    return tuple(
        tuple(_c2(c) for c in k) for k in (_K1, _K2, _K3, _K4)
    )


def _horner(coeffs, x):
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = tower.fq2_add(tower.fq2_mul(acc, x), c)
    return tower.fq2_norm(acc)


def _iso_map(x, y):
    """3-isogeny E2' -> E2 with ONE shared inversion for both
    denominators (hash_to_curve.py iso_map_g2)."""
    k1, k2, k3, k4 = _iso_consts()
    x_num = _horner(k1, x)
    x_den = _horner(k2, x)
    y_num = _horner(k3, x)
    y_den = _horner(k4, x)
    prod = tower.fq2_mul(x_den, y_den)
    inv_prod = tower.fq2_inv(prod)
    xo = tower.fq2_mul(x_num, tower.fq2_mul(inv_prod, y_den))
    yo = tower.fq2_mul(
        y, tower.fq2_mul(y_num, tower.fq2_mul(inv_prod, x_den))
    )
    return tower.fq2_norm(xo), tower.fq2_norm(yo)


def _cat_lv(a: L.Lv, b: L.Lv) -> L.Lv:
    a, b = L.normalize(a), L.normalize(b)
    return L.Lv(jnp.concatenate([a.v, b.v], 0), a.lo, a.hi)


def _split_lv(lv: L.Lv, n: int):
    return (
        L.Lv(lv.v[:n], lv.lo, lv.hi),
        L.Lv(lv.v[n:], lv.lo, lv.hi),
    )


def _finish_sswu_from_candidates(u, x1, x2, gx1, gx2, parts1, parts2):
    """Exact-arithmetic tail of the SSWU map over kernel-computed
    candidates: the select tree of fq2_sqrt_flagged (a1==0 folding, QR
    candidate check, sgn0 correction) — only is_zero/eq/sgn0 and a few
    elementwise selects run here."""

    def sqrt_sel(g, parts):
        # same base-fold + assembly trees as fq2_sqrt_flagged (shared
        # helpers); only the candidate POWERS came from the kernel
        g0, g1v = tower.fq2_norm(g)
        s, ta_gen, tb_gen, ta_z, tb_z, y1a, y1b = parts
        a1_zero = fq.is_zero(g1v)
        inv2 = fq.const((P + 1) // 2, ())
        delta = fq.mul(fq.add(g0, s), inv2)
        delta2 = fq.mul(fq.sub(g0, s), inv2)
        base_a, _base_b = _sqrt_bases(g0, a1_zero, delta, delta2)
        ta = fq.select(a1_zero, ta_z, ta_gen)
        tb = fq.select(a1_zero, tb_z, tb_gen)
        ok_a = fq.eq(fq.sqr(ta), base_a)
        y1_gen = fq.select(ok_a, y1a, y1b)
        return _sqrt_assemble(
            (g0, g1v), a1_zero, ok_a, ta, tb, y1_gen
        )

    y1_, ok1 = sqrt_sel(gx1, parts1)
    y2_, _ok2 = sqrt_sel(gx2, parts2)
    x = tower.fq2_select(ok1, x1, x2)
    y = tower.fq2_select(ok1, y1_, y2_)
    flip = _sgn0(u) != _sgn0(y)
    y = tower.fq2_select(
        flip,
        (fq.normalize(fq.neg(y[0])), fq.normalize(fq.neg(y[1]))),
        y,
    )
    return x, y


def _sswu_iso_sum_tpu(u0, u1) -> C.JacPoint:
    """Pallas path: both draws batched through kernel S (chains +
    candidate field work VMEM-resident), the exact select tree in XLA,
    both isogenies through kernel I, one complete jacobian add."""
    from . import pallas_sswu as PS

    u0 = tower.fq2_norm(u0)
    u1 = tower.fq2_norm(u1)
    n = u0[0].v.shape[0]
    ucat = (_cat_lv(u0[0], u1[0]), _cat_lv(u0[1], u1[1]))
    d = PS.sswu_candidates(ucat)

    def half(i: int, name: str) -> L.Lv:
        return _split_lv(d[name], n)[i]

    def fin(i: int, u):
        x1 = (half(i, "x1_0"), half(i, "x1_1"))
        x2 = (half(i, "x2_0"), half(i, "x2_1"))
        gx1 = (half(i, "g1_0"), half(i, "g1_1"))
        gx2 = (half(i, "g2_0"), half(i, "g2_1"))
        parts1 = [
            half(i, k)
            for k in (
                "s_1", "ta_gen_1", "tb_gen_1", "ta_z_1", "tb_z_1",
                "y1a_1", "y1b_1",
            )
        ]
        parts2 = [
            half(i, k)
            for k in (
                "s_2", "ta_gen_2", "tb_gen_2", "ta_z_2", "tb_z_2",
                "y1a_2", "y1b_2",
            )
        ]
        return _finish_sswu_from_candidates(
            u, x1, x2, gx1, gx2, parts1, parts2
        )

    xa, ya = fin(0, u0)
    xb, yb = fin(1, u1)
    (xo_a, yo_a), (xo_b, yo_b) = PS.iso_map_pair(xa, ya, xb, yb)
    q0 = C.jac_from_affine(
        C.FQ2_OPS, tower.fq2_norm(xo_a), tower.fq2_norm(yo_a)
    )
    q1 = C.jac_from_affine(
        C.FQ2_OPS, tower.fq2_norm(xo_b), tower.fq2_norm(yo_b)
    )
    return C.jac_add(C.FQ2_OPS, q0, q1)


def sswu_iso_sum(u0, u1) -> C.JacPoint:
    """Both SSWU maps + isogeny + point add (pre-cofactor half of
    hash-to-G2; shared with bls/kernels.py _stage_sswu_iso). On TPU
    with 1-D batches the field core runs as the fused Pallas kernels
    (ops/pallas_sswu.py)."""
    if (
        jax.default_backend() == "tpu"
        and u0[0].v.ndim == 2
    ):
        return _sswu_iso_sum_tpu(u0, u1)
    x0, y0 = _sswu(tower.fq2_norm(u0))
    x1, y1 = _sswu(tower.fq2_norm(u1))
    q0 = C.jac_from_affine(C.FQ2_OPS, *_iso_map(x0, y0))
    q1 = C.jac_from_affine(C.FQ2_OPS, *_iso_map(x1, y1))
    return C.jac_add(C.FQ2_OPS, q0, q1)


def hash_to_g2_device(u0, u1, batch) -> C.JacPoint:
    """(u0, u1) field draws -> G2 point (jacobian). The two SSWU maps
    and the isogeny run batched; the result is cofactor-cleared."""
    return g2_clear_cofactor(sswu_iso_sum(u0, u1), batch)


# ---------------------------------------------------------------------------
# host-side byte parsing (the only CPU work left per signature/message)
# ---------------------------------------------------------------------------


def parse_g2_compressed(raw: bytes):
    """96-byte compressed G2 -> (x_c0, x_c1, sign, ok). Pure int work,
    ~microseconds; rejects bad flag bits, non-canonical coordinates,
    and the infinity encoding (an identity signature is invalid for
    verification — api.decompress_signature semantics)."""
    if len(raw) != 96:
        return (0, 0, False, False)
    b0 = raw[0]
    if not (b0 & 0x80):  # compression bit must be set
        return (0, 0, False, False)
    if b0 & 0x40:  # infinity
        return (0, 0, False, False)
    sign = bool(b0 & 0x20)
    xc1 = int.from_bytes(
        bytes([b0 & 0x1F]) + raw[1:48], "big"
    )
    xc0 = int.from_bytes(raw[48:96], "big")
    if xc1 >= P or xc0 >= P:
        return (0, 0, False, False)
    return (xc0, xc1, sign, True)


def message_to_field_draws(message: bytes, dst: bytes):
    """expand_message_xmd + reduction: the host half of hash-to-G2
    (RFC 9380 hash_to_field, m=2, count=2)."""
    from ..crypto.bls.hash_to_curve import hash_to_field_fq2

    u0, u1 = hash_to_field_fq2(message, dst, 2)
    return u0, u1

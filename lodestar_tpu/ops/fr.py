"""Device-side Fr (BLS12-381 scalar field) arithmetic in 10-bit limbs.

ISSUE 16 seam 3: the KZG native tier spends 184.5 ms/block in pure-
Python Fr barycentric math (`crypto/kzg.py` — 4096-point evaluation +
Montgomery batch inversion per blob). This module ports exactly that
math to limb-representation device kernels so `verify_blob_kzg_proof_
batch`'s scalar work rides the same async dispatch as the MSM it
feeds, bit-exact against the Python ints.

Representation. `ops/limbs.py` is hardwired to the Fq prime, so Fr
gets its own small engine: a field element is NC=27 int32 limbs of
BITS=10 bits each, little-endian, always NON-NEGATIVE (subtraction
adds a multiple-of-r offset vector instead of borrowing). 26 limbs
cover 260 bits >= the 255-bit modulus; the 27th is a small carry limb
that lets a just-carried value park without a final fold. Every
operation threads a static per-limb BOUND list (python ints) through
a reduce schedule that is fully decided at TRACE time: carry splits
run while any limb bound exceeds B+1, fold steps multiply the limbs
at index >= 26 by precomputed rows (the 10-bit decomposition of
2^(10k) mod r — r < 2^255 keeps every row's top limb <= 31, which is
what makes the schedule converge), and an iteration cap asserts at
trace time if a bound chain ever fails to settle. All intermediate
bounds are proven < 2^31, so int32 accumulation never overflows.

The public surface is the barycentric batch evaluator
(`eval_barycentric_batch`, wrapped in instrument_stage("fr_eval") so
the device telemetry sees it like any BLS stage) plus the primitive
field ops (`fr_mul`/`fr_add`/`fr_sub`/`fr_pow`/`fr_inv`/
`fr_batch_inv`) and the host converters (`fr_from_ints`/`fr_to_ints`)
the differential tests drive. Batch inversion is the Montgomery
scan pair (two lax.scans + ONE Fermat inversion) rather than a
batched Fermat pow — ~100x fewer modular multiplications for a
4096-wide denominator vector. Zero inputs are precluded by the
caller's z-not-in-roots precondition (the host special-cases
z == root before dispatch, mirroring the Python oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import device as _telemetry

# BLS12-381 scalar field modulus (the KZG BLS_MODULUS)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

BITS = 10
B = 1 << BITS  # limb base
NL = 26  # value limbs: 260 bits >= 255-bit r
NC = NL + 1  # canonical length: one small carry limb on top

# canonical per-limb bounds: what _reduce guarantees on its output and
# what every op may assume of its inputs
CANON_HI = [B + 1] * NL + [2]

_I32_MAX = (1 << 31) - 1


def _int_to_limbs(x: int, n: int) -> list[int]:
    return [(x >> (BITS * i)) & (B - 1) for i in range(n)]


@functools.lru_cache(maxsize=64)
def _fold_rows(n_extra: int) -> tuple:
    """Rows folding limbs NL..NL+n_extra-1 back into NL limbs: row k
    is the 10-bit decomposition of 2^(10(NL+k)) mod r. r < 2^255, so
    each row's limbs are < B with row[25] <= 31 — the shrinking top
    limb is what makes the reduce schedule terminate."""
    rows = []
    for k in range(n_extra):
        rows.append(_int_to_limbs(pow(2, BITS * (NL + k), R), NL))
    return tuple(tuple(r) for r in rows)


def _carry(v, hi):
    """One carry-propagation pass: limb i keeps its low 10 bits and
    passes the rest up. Output limb bounds min(hi,B-1) + (hi_below >>
    10); trailing limbs whose bound is 0 are trimmed."""
    lo = v & (B - 1)
    c = v >> BITS
    pad = jnp.zeros_like(v[..., :1])
    new = (
        jnp.concatenate([lo, pad], axis=-1)
        + jnp.concatenate([pad, c], axis=-1)
    )
    new_hi = []
    for i in range(len(hi) + 1):
        keep = min(hi[i], B - 1) if i < len(hi) else 0
        up = hi[i - 1] >> BITS if i >= 1 else 0
        new_hi.append(keep + up)
    while len(new_hi) > 1 and new_hi[-1] == 0:
        new_hi.pop()
    return new[..., : len(new_hi)], new_hi


def _fold(v, hi):
    """Fold limbs at index >= NL back into the low NL limbs via the
    precomputed 2^(10k) mod r rows. Caller guarantees per-limb bounds
    <= B+1 so the folded contribution stays far below 2^31."""
    n_extra = len(hi) - NL
    rows = _fold_rows(n_extra)
    rows_np = np.array(rows, dtype=np.int32)  # (n_extra, NL)
    base = v[..., :NL]
    tail = v[..., NL:]
    out = base + jnp.einsum(
        "...k,kj->...j", tail, jnp.asarray(rows_np)
    )
    new_hi = []
    for j in range(NL):
        b = hi[j] + sum(
            hi[NL + k] * int(rows_np[k, j]) for k in range(n_extra)
        )
        new_hi.append(b)
    assert max(new_hi) <= _I32_MAX, new_hi
    return out, new_hi


def _is_canonical(hi) -> bool:
    if len(hi) > NC:
        return False
    if any(h > B + 1 for h in hi[:NL]):
        return False
    if len(hi) == NC and hi[NL] > 2:
        return False
    return True


def _pad_to_nc(v, hi):
    if len(hi) == NC:
        return v
    pad = jnp.zeros(v.shape[:-1] + (NC - len(hi),), dtype=v.dtype)
    return jnp.concatenate([v, pad], axis=-1)


def _reduce(v, hi):
    """Normalize an arbitrary-bound limb vector to canonical NC-limb
    form. The schedule (carry vs fold) is driven entirely by the
    static bound list, so it unrolls at trace time into a fixed op
    sequence; the cap asserts (at trace time) if the bounds ever fail
    to converge — a construction error, not a data condition."""
    assert max(hi) <= _I32_MAX, hi
    for _ in range(64):
        if _is_canonical(hi):
            return _pad_to_nc(v, hi)
        if any(h > B + 1 for h in hi):
            v, hi = _carry(v, hi)
            continue
        v, hi = _fold(v, hi)
        v, hi = _carry(v, hi)
    raise AssertionError(f"fr reduce did not converge: {hi}")


# --- offset vector for borrow-free subtraction ------------------------------
#
# OFFSET is a multiple of r whose limb vector dominates CANON_HI
# pointwise, so (OFFSET - b) is non-negative per limb for any
# canonical b and a + (OFFSET - b) === a - b (mod r).


def _make_offset() -> list[int]:
    need_sum = sum(h << (BITS * i) for i, h in enumerate(CANON_HI))
    k = need_sum // R + 1
    rem = k * R - need_sum
    digits = _int_to_limbs(rem, NC)
    assert rem < 1 << (BITS * NC)
    off = [CANON_HI[i] + digits[i] for i in range(NC)]
    assert sum(o << (BITS * i) for i, o in enumerate(off)) % R == 0
    return off


_OFFSET = _make_offset()
_OFFSET_ARR = np.array(_OFFSET, dtype=np.int32)

# banded convolution tensor for schoolbook limb multiplication:
# out[k] = sum_{i+j=k} a[i]*b[j]
_CONV = np.zeros((2 * NC - 1, NC, NC), dtype=np.int32)
for _i in range(NC):
    for _j in range(NC):
        _CONV[_i + _j, _i, _j] = 1
# worst-case conv bound: <= NC terms of (B+1)^2 each — fits int32
assert NC * (B + 1) * (B + 1) <= _I32_MAX


def fr_const(x: int):
    """Canonical device constant (shape (NC,))."""
    return jnp.asarray(
        np.array(_int_to_limbs(x % R, NC), dtype=np.int32)
    )


def fr_mul(a, b):
    """Canonical x canonical -> canonical (elementwise over leading
    batch dims, which broadcast)."""
    conv = jnp.einsum("...i,...j,kij->...k", a, b, jnp.asarray(_CONV))
    hi = [
        min(k + 1, NC, 2 * NC - 1 - k) * (B + 1) * (B + 1)
        for k in range(2 * NC - 1)
    ]
    return _reduce(conv, hi)


def fr_add(a, b):
    return _reduce(a + b, [2 * h for h in CANON_HI])


def fr_sub(a, b):
    """a - b via the borrow-free offset: a + (OFFSET - b)."""
    d = a + (jnp.asarray(_OFFSET_ARR) - b)
    return _reduce(d, [CANON_HI[i] + _OFFSET[i] for i in range(NC)])


def fr_sum(t, axis=-2):
    """Masked-free modular sum of canonical vectors along `axis`."""
    n = t.shape[axis]
    assert n * (B + 1) <= _I32_MAX
    return _reduce(jnp.sum(t, axis=axis), [n * h for h in CANON_HI])


def fr_pow(a, e: int):
    """a**e for a STATIC python-int exponent, via an LSB-first
    square-and-multiply lax.scan (255 iterations for Fermat, compiled
    once; the exponent is part of the trace)."""
    e = int(e)
    assert e >= 0
    if e == 0:
        return jnp.broadcast_to(fr_const(1), a.shape)
    nbits = e.bit_length()
    bits = jnp.asarray(
        [(e >> i) & 1 for i in range(nbits)], dtype=jnp.bool_
    )
    one = jnp.broadcast_to(fr_const(1), a.shape)

    def body(carry, bit):
        acc, base = carry
        acc = jnp.where(bit, fr_mul(acc, base), acc)
        base = fr_mul(base, base)
        return (acc, base), None

    (acc, _), _ = jax.lax.scan(body, (one, a), bits)
    return acc


def fr_inv(a):
    """Fermat inversion (a nonzero)."""
    return fr_pow(a, R - 2)


def fr_batch_inv(x):
    """Montgomery batch inversion over the LEADING axis: two scans
    emitting exclusive prefix products + one Fermat inversion of the
    total — the device analog of crypto/kzg._fr_batch_inv. x must be
    nonzero in every slot (the barycentric caller guarantees z is not
    a domain root)."""
    one = jnp.broadcast_to(fr_const(1), x.shape[1:])

    def fwd(carry, xi):
        return fr_mul(carry, xi), carry  # emit prefix EXCLUDING xi

    total, pre = jax.lax.scan(fwd, one, x)
    inv_total = fr_inv(total)

    def bwd(carry, inp):
        xi, pre_i = inp
        # carry = inv(prod_{j<=i}); inv_i = carry * prod_{j<i}
        return fr_mul(carry, xi), fr_mul(carry, pre_i)

    _, invs = jax.lax.scan(bwd, inv_total, (x, pre), reverse=True)
    return invs


# --- barycentric evaluation --------------------------------------------------


@functools.lru_cache(maxsize=8)
def _bary_program(width: int):
    """Jitted batched barycentric evaluator for a fixed domain width:
    (m, width, NC) polys + (width, NC) roots + (m, NC) zs -> (m, NC)
    evaluations. y = (z^width - 1)/width * sum_i f_i * w_i / (z - w_i)
    — exactly crypto/kzg.evaluate_polynomial_in_evaluation_form for
    z outside the domain (the caller special-cases z == root on
    host)."""
    inv_width = pow(width, R - 2, R)

    def run(polys, roots, zs):
        with jax.named_scope("fr_barycentric"):
            zb = jnp.broadcast_to(zs[:, None, :], polys.shape)
            d = fr_sub(zb, roots[None, :, :])
            # scan over the width axis: move it leading
            inv = jnp.moveaxis(
                fr_batch_inv(jnp.moveaxis(d, 1, 0)), 0, 1
            )
            terms = fr_mul(fr_mul(polys, roots[None, :, :]), inv)
            acc = fr_sum(terms, axis=1)
            zw = fr_sub(
                fr_pow(zs, width),
                jnp.broadcast_to(fr_const(1), zs.shape),
            )
            return fr_mul(fr_mul(acc, zw), fr_const(inv_width))

    return _telemetry.instrument_stage("fr_eval", jax.jit(run))


def eval_barycentric_batch(polys, roots, zs):
    """Dispatch the fused barycentric program (async — returns device
    (m, NC) limbs without readback). polys (m, width, NC), roots
    (width, NC), zs (m, NC), all canonical."""
    width = polys.shape[1]
    return _bary_program(width)(polys, roots, zs)


# --- host interop ------------------------------------------------------------


def fr_from_ints(xs) -> np.ndarray:
    """list[int] -> (n, NC) int32 canonical limbs (vectorized: bytes
    -> unpacked bits -> 10-bit groups)."""
    xs = list(xs)
    n = len(xs)
    if n == 0:
        return np.zeros((0, NC), dtype=np.int32)
    nbytes = (NC * BITS + 7) // 8  # 34 bytes >= 270 bits
    buf = b"".join((x % R).to_bytes(nbytes, "little") for x in xs)
    u8 = np.frombuffer(buf, dtype=np.uint8).reshape(n, nbytes)
    bits = np.unpackbits(u8, axis=1, bitorder="little")
    bits = bits[:, : NC * BITS]
    w = (1 << np.arange(BITS, dtype=np.int32)).astype(np.int32)
    return (
        bits.reshape(n, NC, BITS).astype(np.int32) @ w
    ).astype(np.int32)


def fr_to_ints(a) -> list[int]:
    """Device/host limb array (..., NC) -> python ints mod r (the
    bit-exact readback the differential tests compare)."""
    arr = np.asarray(a)
    flat = arr.reshape(-1, arr.shape[-1])
    return [
        sum(int(v) << (BITS * i) for i, v in enumerate(row)) % R
        for row in flat
    ]

"""Pallas TPU kernels: fused Miller loop + cyclotomic exponentiation.

Round-3 verdict: with ladders and ingest already fused (pallas_chain,
pallas_ladder), the batch-verify device time is dominated by the two
remaining `lax.scan`s — the 63-step Miller loop and the five 63-step
cyclotomic ladders of the final exponentiation (ops/pairing.py). Each
scan step round-trips the full Fq12 limb state (12 x (batch, 40) int32
~ 2 KB/element) plus the G2 accumulator through HBM, the exact
bandwidth pathology pallas_chain killed for the ingest power chains
(0.6 ms vs 452 ms). These kernels run the WHOLE loop with the tower
state resident in VMEM.

Layout (shared with pallas_chain/pallas_ladder): limbs on SUBLANES
(40 statically-indexed rows), batch on LANES (128 per grid block).
An Fq2 element is two (40, 128) planes; Fq12 is twelve. The Miller
bit-vector of |x| is an SMEM array indexed by the fori_loop counter —
one kernel invocation per 128-lane block runs all 63
double(+select add) iterations.

Formulas mirror ops/pairing.py (_dbl_step/_add_step sparse M-twist
lines, tower.fq12_mul_sparse_line, tower.fq12_cyclotomic_sqr) exactly;
that module is the differential oracle (itself validated against the
blst-KAT-checked crypto/bls/pairing.py). Reference analog: blst's
miller_loop_n / final_exp used by every Lodestar signature check
(SURVEY.md §2.1, §2.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import limbs as L
from . import tower
from .curve import JacPoint
from .pallas_chain import LANES, ROWS, _fold_rows, make_modmul
from .pallas_ladder import _norm2, _sub_offset
from .pairing import _U_BITS

NBITS = len(_U_BITS)  # 63 post-MSB bits of |x|


def _mk_tower(fold_const, off_const):
    """In-kernel Fq/Fq2/Fq6/Fq12 ops on (40, 128) limb planes, bound to
    the fold/offset constants. Discipline (validated at scale by
    pallas_ladder): every `mm` operand is the output of `mm` or `_norm2`."""
    fold0 = fold_const[0].reshape(ROWS, 1)
    off = off_const.reshape(ROWS, 1)

    mm = make_modmul(fold_const)

    def nrm(x):
        return _norm2(x, fold0)

    def add(a, b):
        return nrm(a + b)

    def sub(a, b):
        # off >= 1025 per limb; 2*off dominates post-norm limbs (~1030)
        return nrm(a + 2 * off - b)

    def small(a, k):
        assert k > 0
        return nrm(a * k)

    def neg(a):
        return nrm(2 * off - a)

    # --- Fq2: pairs of planes, c0 + c1*u, u^2 = -1 -----------------------
    def f2_mul(a, b):
        m0 = mm(a[0], b[0])
        m1 = mm(a[1], b[1])
        s = mm(nrm(a[0] + a[1]), nrm(b[0] + b[1]))
        return (sub(m0, m1), sub(sub(s, m0), m1))

    def f2_sqr(a):
        # (a0+a1)(a0-a1) + 2 a0 a1 u: 2 mm instead of 3
        c0 = mm(add(a[0], a[1]), sub(a[0], a[1]))
        c1 = small(mm(a[0], a[1]), 2)
        return (c0, c1)

    def f2_add(a, b):
        return (add(a[0], b[0]), add(a[1], b[1]))

    def f2_sub(a, b):
        return (sub(a[0], b[0]), sub(a[1], b[1]))

    def f2_neg(a):
        return (neg(a[0]), neg(a[1]))

    def f2_small(a, k):
        return (small(a[0], k), small(a[1], k))

    def f2_xi(a):
        # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
        return (sub(a[0], a[1]), add(a[0], a[1]))

    def f2_mul_fq(a, k):
        return (mm(a[0], k), mm(a[1], k))

    def f2_sel(m, a, b):
        return (
            jnp.where(m != 0, a[0], b[0]),
            jnp.where(m != 0, a[1], b[1]),
        )

    # --- Fq6 = Fq2[v]/(v^3 - xi): karatsuba as tower.fq6_mul -------------
    def f6_mul(a, b):
        a0, a1, a2 = a
        b0, b1, b2 = b
        t0 = f2_mul(a0, b0)
        t1 = f2_mul(a1, b1)
        t2 = f2_mul(a2, b2)
        c0 = f2_add(
            t0,
            f2_xi(
                f2_sub(
                    f2_sub(
                        f2_mul(f2_add(a1, a2), f2_add(b1, b2)), t1
                    ),
                    t2,
                )
            ),
        )
        c1 = f2_add(
            f2_sub(
                f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), t0), t1
            ),
            f2_xi(t2),
        )
        c2 = f2_add(
            f2_sub(
                f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), t0), t2
            ),
            t1,
        )
        return (c0, c1, c2)

    def f6_add(a, b):
        return tuple(f2_add(x, y) for x, y in zip(a, b))

    def f6_sub(a, b):
        return tuple(f2_sub(x, y) for x, y in zip(a, b))

    def f6_mul_by_v(a):
        return (f2_xi(a[2]), a[0], a[1])

    def f6_mul_b01(a, b0, b1):
        # a * (b0, b1, 0): 5 f2 muls (tower.fq6_mul_b01)
        a0, a1, a2 = a
        t0 = f2_mul(a0, b0)
        t1 = f2_mul(a1, b1)
        c0 = f2_add(
            t0, f2_xi(f2_sub(f2_mul(f2_add(a1, a2), b1), t1))
        )
        c1 = f2_sub(
            f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), t0), t1
        )
        c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), b0), t0), t1)
        return (c0, c1, c2)

    def f6_mul_b1(a, b1):
        # a * (0, b1, 0): 3 f2 muls
        a0, a1, a2 = a
        return (f2_xi(f2_mul(a2, b1)), f2_mul(a0, b1), f2_mul(a1, b1))

    def f6_sel(m, a, b):
        return tuple(f2_sel(m, x, y) for x, y in zip(a, b))

    # --- Fq12 = Fq6[w]/(w^2 - v) -----------------------------------------
    def f12_mul(a, b):
        a0, a1 = a
        b0, b1 = b
        t0 = f6_mul(a0, b0)
        t1 = f6_mul(a1, b1)
        c0 = f6_add(t0, f6_mul_by_v(t1))
        c1 = f6_sub(
            f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1
        )
        return (c0, c1)

    def f12_sqr(a):
        a0, a1 = a
        t1 = f6_mul(a0, a1)
        t = f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_by_v(a1)))
        c0 = f6_sub(f6_sub(t, t1), f6_mul_by_v(t1))
        c1 = tuple(f2_small(c, 2) for c in t1)
        return (c0, c1)

    def f12_sparse_line(f, l0, l2, l3):
        # f * (l0 + l2 w^2 + l3 w^3): 13 f2 muls (tower analog)
        a0, a1 = f
        t0 = f6_mul_b01(a0, l0, l2)
        t1 = f6_mul_b1(a1, l3)
        c0 = f6_add(t0, f6_mul_by_v(t1))
        c1 = f6_sub(
            f6_sub(
                f6_mul_b01(f6_add(a0, a1), l0, f2_add(l2, l3)), t0
            ),
            t1,
        )
        return (c0, c1)

    def f12_sel(m, a, b):
        return tuple(f6_sel(m, x, y) for x, y in zip(a, b))

    def _fq4_sqr(x0, x1):
        s0 = f2_sqr(x0)
        s1 = f2_sqr(x1)
        sx = f2_sqr(f2_add(x0, x1))
        return (f2_add(s0, f2_xi(s1)), f2_sub(f2_sub(sx, s0), s1))

    def f12_cyclotomic_sqr(a):
        # Granger-Scott (tower.fq12_cyclotomic_sqr derivation)
        (g0, g1, g2), (h0, h1, h2) = a

        def tm2(t, z):  # 3t - 2z
            return f2_sub(f2_small(t, 3), f2_small(z, 2))

        def tp2(t, z):  # 3t + 2z
            return f2_add(f2_small(t, 3), f2_small(z, 2))

        a0, a1 = _fq4_sqr(g0, h1)
        b0, b1 = _fq4_sqr(h0, g2)
        c0, c1 = _fq4_sqr(g1, h2)
        return (
            (tm2(a0, g0), tm2(b0, g1), tm2(c0, g2)),
            (tp2(f2_xi(c1), h0), tp2(a1, h1), tp2(b1, h2)),
        )

    import types

    return types.SimpleNamespace(
        mm=mm, nrm=nrm, add=add, sub=sub, small=small, neg=neg,
        f2_mul=f2_mul, f2_sqr=f2_sqr, f2_add=f2_add, f2_sub=f2_sub,
        f2_neg=f2_neg, f2_small=f2_small, f2_xi=f2_xi,
        f2_mul_fq=f2_mul_fq, f2_sel=f2_sel,
        f6_mul=f6_mul, f6_add=f6_add, f6_sub=f6_sub,
        f6_mul_by_v=f6_mul_by_v, f6_mul_b01=f6_mul_b01,
        f6_mul_b1=f6_mul_b1, f6_sel=f6_sel,
        f12_mul=f12_mul, f12_sqr=f12_sqr,
        f12_sparse_line=f12_sparse_line, f12_sel=f12_sel,
        f12_cyclotomic_sqr=f12_cyclotomic_sqr,
    )


def _one_plane():
    return jnp.concatenate(
        [
            jnp.ones((1, LANES), jnp.int32),
            jnp.zeros((ROWS - 1, LANES), jnp.int32),
        ],
        axis=0,
    )


def _zero_plane():
    return jnp.zeros((ROWS, LANES), jnp.int32)


def _f12_one():
    z2 = (_zero_plane(), _zero_plane())
    one2 = (_one_plane(), _zero_plane())
    return ((one2, z2, z2), (z2, z2, z2))


# ---------------------------------------------------------------------------
# Miller loop kernel
# ---------------------------------------------------------------------------


def _miller_kernel(bits_ref, fold_ref, off_ref, px_ref, py_ref,
                   qx0_ref, qx1_ref, qy0_ref, qy1_ref, *out_refs):
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    px = px_ref[:]
    py = py_ref[:]
    qx = (qx0_ref[:], qx1_ref[:])
    qy = (qy0_ref[:], qy1_ref[:])

    def dbl_step(X, Y, Z):
        # ops/pairing._dbl_step: tangent line slots + dbl-2009-l
        A = F.f2_sqr(X)
        Bv = F.f2_sqr(Y)
        C = F.f2_sqr(Bv)
        Z2 = F.f2_sqr(Z)
        XA = F.f2_mul(X, A)
        YZ = F.f2_mul(Y, Z)
        l0 = F.f2_sub(F.f2_small(XA, 3), F.f2_small(Bv, 2))
        l2c = F.f2_neg(F.f2_small(F.f2_mul(A, Z2), 3))
        l3c = F.f2_small(F.f2_mul(YZ, Z2), 2)
        l2 = F.f2_mul_fq(l2c, px)
        l3 = F.f2_mul_fq(l3c, py)
        t = F.f2_sqr(F.f2_add(X, Bv))
        D = F.f2_small(F.f2_sub(F.f2_sub(t, A), C), 2)
        E = F.f2_small(A, 3)
        Fv = F.f2_sqr(E)
        x3 = F.f2_sub(Fv, F.f2_small(D, 2))
        y3 = F.f2_sub(
            F.f2_mul(E, F.f2_sub(D, x3)), F.f2_small(C, 8)
        )
        z3 = F.f2_small(YZ, 2)
        return (x3, y3, z3), (l0, l2, l3)

    def add_step(X, Y, Z):
        # ops/pairing._add_step: chord line slots + mixed add
        Z2 = F.f2_sqr(Z)
        Z3c = F.f2_mul(Z2, Z)
        mu = F.f2_sub(F.f2_mul(qx, Z2), X)
        th = F.f2_sub(F.f2_mul(qy, Z3c), Y)
        Zmu = F.f2_mul(Z, mu)
        l0 = F.f2_sub(F.f2_mul(th, qx), F.f2_mul(Zmu, qy))
        l2 = F.f2_mul_fq(F.f2_neg(th), px)
        l3 = F.f2_mul_fq(Zmu, py)
        mu2 = F.f2_sqr(mu)
        mu3 = F.f2_mul(mu2, mu)
        xmu2 = F.f2_mul(X, mu2)
        x3 = F.f2_sub(
            F.f2_sub(F.f2_sqr(th), mu3), F.f2_small(xmu2, 2)
        )
        y3 = F.f2_sub(
            F.f2_mul(th, F.f2_sub(xmu2, x3)), F.f2_mul(Y, mu3)
        )
        return (x3, y3, Zmu), (l0, l2, l3)

    one2 = (_one_plane(), _zero_plane())
    T0 = (qx, qy, one2)
    f0 = _f12_one()

    def body(i, carry):
        (X, Y, Z), f = carry
        T2, (d0, d2, d3) = dbl_step(X, Y, Z)
        f2v = F.f12_sparse_line(F.f12_sqr(f), d0, d2, d3)
        bit = bits_ref[i]

        # |x| = 0xD201000000010000 has hamming weight 6: computing the
        # add-step unconditionally (the select pattern) would waste
        # ~40% of the kernel on the ~58 zero bits — branch instead
        def with_add(_):
            T3, (a0, a2, a3) = add_step(*T2)
            return (T3, F.f12_sparse_line(f2v, a0, a2, a3))

        def no_add(_):
            return (T2, f2v)

        return jax.lax.cond(bit == 1, with_add, no_add, None)

    _, f = jax.lax.fori_loop(0, NBITS, body, (T0, f0))
    flat = [p for c6 in f for c2 in c6 for p in c2]
    for ref, plane in zip(out_refs, flat):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _miller_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(px, py, qx0, qx1, qy0, qy1):
        n = n_blocks * LANES
        bits = jnp.asarray(_U_BITS.astype(np.int32))
        return pl.pallas_call(
            _miller_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
                vec(), vec(), vec(), vec(), vec(), vec(),
            ],
            out_specs=[vec() for _ in range(12)],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(12)
            ],
        )(
            bits,
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            px, py, qx0, qx1, qy0, qy1,
        )

    return run


def _prep(v, padded, batch):
    return jnp.transpose(jnp.pad(v, ((0, padded - batch), (0, 0))))


def _out_lv(plane, batch):
    # HONEST bounds (see pallas_chain.pow_const): kernel output limbs
    # can reach ~B+2 in every row including the top one.
    return L.Lv(
        jnp.transpose(plane)[:batch, :],
        tuple([0] * L.NCANON),
        tuple([L.B + 2] * L.NCANON),
    )


def miller_loop(px, py, qx, qy):
    """Drop-in for ops/pairing.miller_loop on TPU: f_{|x|,Q}(P)
    conjugated, the whole 63-step ladder fused in one kernel per
    128-lane block. 1-D equal batch shapes only (the kernels.py call
    shape); infinity slots are masked downstream, as in the scan path."""
    px, py = L.normalize(px), L.normalize(py)
    qx = tower.fq2_norm(qx)
    qy = tower.fq2_norm(qy)
    batch = px.v.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    outs = _miller_call(n_blocks)(
        _prep(px.v, padded, batch),
        _prep(py.v, padded, batch),
        _prep(qx[0].v, padded, batch),
        _prep(qx[1].v, padded, batch),
        _prep(qy[0].v, padded, batch),
        _prep(qy[1].v, padded, batch),
    )
    lvs = [_out_lv(p, batch) for p in outs]
    f = (
        ((lvs[0], lvs[1]), (lvs[2], lvs[3]), (lvs[4], lvs[5])),
        ((lvs[6], lvs[7]), (lvs[8], lvs[9]), (lvs[10], lvs[11])),
    )
    return tower.fq12_conj(f)


# ---------------------------------------------------------------------------
# Cyclotomic f^|x| kernel (final-exponentiation ladder)
# ---------------------------------------------------------------------------


def _pow_u_kernel(bits_ref, fold_ref, off_ref, *io_refs):
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    planes = [r[:] for r in io_refs[:12]]
    out_refs = io_refs[12:]

    def pack(ps):
        return (
            ((ps[0], ps[1]), (ps[2], ps[3]), (ps[4], ps[5])),
            ((ps[6], ps[7]), (ps[8], ps[9]), (ps[10], ps[11])),
        )

    f = pack(planes)

    def body(i, c):
        c2 = F.f12_cyclotomic_sqr(c)
        bit = bits_ref[i]
        # low-hamming-weight |x|: skip the multiply on zero bits
        return jax.lax.cond(
            bit == 1,
            lambda _: F.f12_mul(c2, f),
            lambda _: c2,
            None,
        )

    r = jax.lax.fori_loop(0, NBITS, body, f)
    flat = [p for c6 in r for c2 in c6 for p in c2]
    for ref, plane in zip(out_refs, flat):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _pow_u_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(*planes):
        n = n_blocks * LANES
        bits = jnp.asarray(_U_BITS.astype(np.int32))
        return pl.pallas_call(
            _pow_u_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
            ]
            + [vec() for _ in range(12)],
            out_specs=[vec() for _ in range(12)],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(12)
            ],
        )(
            bits,
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            *planes,
        )

    return run


def pow_u(f):
    """Drop-in for ops/pairing._pow_u on TPU: f^|x| on the cyclotomic
    subgroup, the whole 63-bit square-and-multiply ladder in one kernel.
    Accepts batch shape () or (n,); returns the same shape."""
    f = tower.fq12_norm(f)
    lvs = [lv for c6 in f for c2 in c6 for lv in c2]
    scalar = lvs[0].v.ndim == 1
    if scalar:
        lvs = [L.Lv(lv.v[None, :], lv.lo, lv.hi) for lv in lvs]
    batch = lvs[0].v.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    outs = _pow_u_call(n_blocks)(
        *[_prep(lv.v, padded, batch) for lv in lvs]
    )
    out_lvs = [_out_lv(p, batch) for p in outs]
    if scalar:
        out_lvs = [
            L.Lv(lv.v[0], lv.lo, lv.hi) for lv in out_lvs
        ]
    return (
        (
            (out_lvs[0], out_lvs[1]),
            (out_lvs[2], out_lvs[3]),
            (out_lvs[4], out_lvs[5]),
        ),
        (
            (out_lvs[6], out_lvs[7]),
            (out_lvs[8], out_lvs[9]),
            (out_lvs[10], out_lvs[11]),
        ),
    )


def final_exponentiation(f):
    """ops/pairing.final_exponentiation with the five |x|-ladders fused
    as Pallas kernels; the O(1) Frobenius/inverse glue stays XLA."""
    from . import pairing

    return pairing.final_exponentiation(f, pow_u=pow_u)


# ---------------------------------------------------------------------------
# G2 jacobian sum reduction (lane-halving tree)
# ---------------------------------------------------------------------------


def _g2_sum_kernel(fold_ref, off_ref, *io_refs):
    """Reduce each 128-lane block of G2 jacobian points to 8 partial
    sums via 4 lane-rotation halving levels of the INCOMPLETE add
    (jac_add_incomplete's soundness argument: random-weight terms,
    collisions fail closed at the pairing check). Infinity flags ride
    an int32 plane. Replaces the 256-step jac_sum_scan whose every
    step round-trips the accumulator through HBM."""
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    x0, x1, y0, y1, z0, z1, inf = [r[:] for r in io_refs[:7]]
    out_refs = io_refs[7:]

    def add(P1, P2):
        (X1, Y1, Z1, i1) = P1
        (X2, Y2, Z2, i2) = P2
        z1z1 = F.f2_sqr(Z1)
        z2z2 = F.f2_sqr(Z2)
        u1 = F.f2_mul(X1, z2z2)
        u2 = F.f2_mul(X2, z1z1)
        s1 = F.f2_mul(F.f2_mul(Y1, Z2), z2z2)
        s2 = F.f2_mul(F.f2_mul(Y2, Z1), z1z1)
        h = F.f2_sub(u2, u1)
        r = F.f2_sub(s2, s1)
        h2 = F.f2_sqr(h)
        h3 = F.f2_mul(h2, h)
        u1h2 = F.f2_mul(u1, h2)
        x3 = F.f2_sub(
            F.f2_sub(F.f2_sqr(r), h3), F.f2_small(u1h2, 2)
        )
        y3 = F.f2_sub(
            F.f2_mul(r, F.f2_sub(u1h2, x3)), F.f2_mul(s1, h3)
        )
        z3 = F.f2_mul(F.f2_mul(Z1, Z2), h)
        # p inf -> q; q inf -> p (exact flag semantics)
        x3 = F.f2_sel(i1, X2, x3)
        y3 = F.f2_sel(i1, Y2, y3)
        z3 = F.f2_sel(i1, Z2, z3)
        x3 = F.f2_sel(i2, X1, x3)
        y3 = F.f2_sel(i2, Y1, y3)
        z3 = F.f2_sel(i2, Z1, z3)
        return (x3, y3, z3, i1 * i2)

    P = ((x0, x1), (y0, y1), (z0, z1), inf)
    for w in (64, 32, 16, 8):
        rolled = (
            tuple(jnp.roll(c, -w, axis=1) for c in P[0]),
            tuple(jnp.roll(c, -w, axis=1) for c in P[1]),
            tuple(jnp.roll(c, -w, axis=1) for c in P[2]),
            jnp.roll(P[3], -w, axis=1),
        )
        P = add(P, rolled)
    flat = [
        P[0][0], P[0][1], P[1][0], P[1][1], P[2][0], P[2][1], P[3]
    ]
    for ref, plane in zip(out_refs, flat):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _g2_sum_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(*planes):
        n = n_blocks * LANES
        return pl.pallas_call(
            _g2_sum_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
            ]
            + [vec() for _ in range(7)],
            out_specs=[vec() for _ in range(7)],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(7)
            ],
        )(
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            *planes,
        )

    return run


def g2_sum(p):
    """Drop-in for curve.jac_sum_scan(FQ2_OPS, ...) on TPU: reduce a
    1-D batch of jacobian G2 points to their sum. The kernel collapses
    each 128-lane block to 8 partials; the small tail finishes through
    the XLA scan."""
    from . import curve as C

    batch = p.x[0].v.shape[0]
    if batch < 2 * LANES:
        return C.jac_sum_scan(C.FQ2_OPS, p)
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    pad = padded - batch

    def prep(lv):
        v = L.normalize(lv).v
        return jnp.transpose(jnp.pad(v, ((0, pad), (0, 0))))

    inf_plane = jnp.pad(
        p.inf.astype(jnp.int32), (0, pad), constant_values=1
    ).reshape(1, padded)
    inf_full = jnp.broadcast_to(inf_plane, (ROWS, padded))
    outs = _g2_sum_call(n_blocks)(
        prep(p.x[0]), prep(p.x[1]),
        prep(p.y[0]), prep(p.y[1]),
        prep(p.z[0]), prep(p.z[1]),
        inf_full,
    )

    def partials(plane):
        t = jnp.transpose(plane).reshape(n_blocks, LANES, ROWS)
        return L.Lv(
            t[:, :8, :].reshape(n_blocks * 8, ROWS),
            tuple([0] * L.NCANON),
            tuple([L.B + 2] * L.NCANON),
        )

    inf_out = (
        jnp.transpose(outs[6])
        .reshape(n_blocks, LANES, ROWS)[:, :8, 0]
        .reshape(n_blocks * 8)
        != 0
    )
    small = C.JacPoint(
        (partials(outs[0]), partials(outs[1])),
        (partials(outs[2]), partials(outs[3])),
        (partials(outs[4]), partials(outs[5])),
        inf_out,
    )
    return C.jac_sum_scan(C.FQ2_OPS, small)


# ---------------------------------------------------------------------------
# Fq12 product reduction (lane-halving tree)
# ---------------------------------------------------------------------------


def _product_kernel(fold_ref, off_ref, *io_refs):
    """Reduce each 128-lane block's Fq12 elements to 8 partial
    products via 4 in-VMEM halving levels: each level multiplies the
    block by its lane-rotation (roll keeps every operand at lane
    offset 0 — Mosaic rejects concats of offset-shifted lane slices),
    so after level w lanes [0, w) hold pair products. Lanes 8.. of the
    output are garbage; the host multiplies the n_blocks*8 partials
    with the small XLA tree."""
    F = _mk_tower(fold_ref[:], off_ref[0:1, :].reshape(ROWS))
    planes = [r[:] for r in io_refs[:12]]
    out_refs = io_refs[12:]

    def pack(ps):
        return (
            ((ps[0], ps[1]), (ps[2], ps[3]), (ps[4], ps[5])),
            ((ps[6], ps[7]), (ps[8], ps[9]), (ps[10], ps[11])),
        )

    def tmap(fn, f12):
        return tuple(
            tuple((fn(c2[0]), fn(c2[1])) for c2 in c6) for c6 in f12
        )

    f = pack(planes)
    for w in (64, 32, 16, 8):
        rolled = tmap(lambda p, w=w: jnp.roll(p, -w, axis=1), f)
        f = F.f12_mul(f, rolled)
    flat = [p for c6 in f for c2 in c6 for p in c2]
    for ref, plane in zip(out_refs, flat):
        ref[:] = plane


@functools.lru_cache(maxsize=None)
def _product_call(n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(*planes):
        n = n_blocks * LANES
        return pl.pallas_call(
            _product_kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
            ]
            + [vec() for _ in range(12)],
            out_specs=[vec() for _ in range(12)],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(12)
            ],
        )(
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            *planes,
        )

    return run


def fq12_masked_product(f, mask, par: int = 8):
    """Drop-in for ops/pairing._fq12_masked_product on TPU: the bulk
    of the reduction (128->8 per block) runs lane-halving in VMEM; the
    remaining n_blocks*8 partials finish through the XLA scan+tree
    (which also serves as the final () -> scalar shape)."""
    from . import pairing

    f = tower.fq12_norm(
        tower.fq12_select(mask, f, tower.fq12_one(mask.shape))
    )
    lvs = [lv for c6 in f for c2 in c6 for lv in c2]
    batch = lvs[0].v.shape[0]
    if batch < 2 * LANES:
        # small buckets: the scan path is already cheap
        return pairing._fq12_masked_product(f, mask, par)
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES
    # padding lanes multiply as one
    one = tower.fq12_one((padded - batch,))
    ones = [lv for c6 in one for c2 in c6 for lv in c2]
    outs = _product_call(n_blocks)(
        *[
            jnp.transpose(
                jnp.concatenate(
                    [L.normalize(lv).v, L.normalize(o).v], axis=0
                )
            )
            for lv, o in zip(lvs, ones)
        ]
    )

    def partials(plane):
        # lanes [b*128, b*128+8) of each block hold the partials
        t = jnp.transpose(plane).reshape(n_blocks, LANES, ROWS)
        return L.Lv(
            t[:, :8, :].reshape(n_blocks * 8, ROWS),
            tuple([0] * L.NCANON),
            tuple([L.B + 2] * L.NCANON),
        )

    out_lvs = [partials(p) for p in outs]
    f8 = (
        (
            (out_lvs[0], out_lvs[1]),
            (out_lvs[2], out_lvs[3]),
            (out_lvs[4], out_lvs[5]),
        ),
        (
            (out_lvs[6], out_lvs[7]),
            (out_lvs[8], out_lvs[9]),
            (out_lvs[10], out_lvs[11]),
        ),
    )
    return pairing._fq12_masked_product(
        f8, jnp.ones(n_blocks * 8, bool), par
    )

"""Multi-precision Fq arithmetic primitives for BLS12-381 on TPU.

Reference analog: the blst C library's 384-bit field arithmetic
(@chainsafe/blst, SURVEY.md §2.1). blst uses 6x64-bit limbs with carry
chains and Montgomery multiplication — a serial-CPU design. TPUs have no
64-bit scalar units, no carry flags, and want wide, branch-free, static-
shape vector code. This module therefore uses a *redundant signed limb*
representation designed for the TPU VPU:

  - An Fq element is 40 int32 limbs in radix 2^10 (39 limbs cover 390
    bits >= 382; limb 39 is a small redundant carry limb), batched over
    arbitrary leading dims.
  - Multiplication is a plain schoolbook convolution: products of 10-bit
    limbs and their 40-term column sums stay far below 2^31, so no carry
    propagation is needed *inside* the product loop (carry-free MAC).
  - Reduction mod P is a linear fold: 2^(10k) mod P for every overflow
    limb index k is a precomputed constant row; folding high limbs is a
    small constant matrix-multiply that XLA maps onto fused multiply-adds
    (the "mxu" backend emits an int8-decomposed version for the MXU).
  - Carry normalization is a handful of data-parallel shift/subtract
    passes (no sequential ripple), correct for signed limbs because the
    int32 right shift is arithmetic.

Overflow safety is *proved at trace time*: every value carries an exact
per-limb interval, and every op propagates intervals with exact interval
arithmetic, auto-normalizing operands when a column sum could leave
int32. Intervals are static Python data (pytree aux), so this costs
nothing at runtime, and `normalize()` lands on a fixed canonical profile
so `lax.scan` carries typecheck.

Two interchangeable backends emit the heavy contractions (see the
LimbBackend block below): "vpu" keeps conv/fold as int32 einsums for
the vector unit; "mxu" splits limbs into int8 slices and emits the
same math as int8 x int8 -> int32 dot_generals for the matrix unit,
with the slice/accumulator/recombination bounds folded into the same
trace-time proofs.
"""

from __future__ import annotations

import contextlib
import functools
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls.fields import P

BITS = 10
B = 1 << BITS  # limb radix
NLIMB = 39  # 390 bits >= 382 > log2(P)
NCANON = NLIMB + 1  # canonical length incl. redundant carry limb
INT32_MAX = 2**31 - 1

# ---------------------------------------------------------------------------
# Limb backend selection (VPU int32 vs MXU int8)
# ---------------------------------------------------------------------------
#
# "vpu": the original path — conv is a banded int32 einsum, the mod-P
#   fold an int32 matmul; both run on the TPU vector unit. Stays the
#   differential reference.
# "mxu": every limb is split into two int8 slices (lo = x mod 128 in
#   [0, 128), hi = x >> 7 arithmetic, exact for signed x since
#   x == lo + 128*hi), and conv/fold are emitted as int8 x int8
#   contractions with preferred_element_type=int32 — the quantized-GEMM
#   shape the TPU matrix unit executes natively at ~4x the int32 VPU
#   MAC rate. Exactness is *proved at trace time*: the interval
#   machinery bounds every partial contraction and every recombination
#   intermediate with exact python-int arithmetic and auto-normalizes
#   (or falls back to the VPU op) whenever a slice would leave int8 or
#   an accumulator would leave int32; the recombined column sums equal
#   the int32 path bit-for-bit.
#
# Select via LODESTAR_TPU_LIMB_BACKEND, set_backend(), or the
# limb_backend() context manager. NOTE: jitted pipelines trace once per
# input shape — select the backend before first use (process start /
# env var) or clear jit caches; the context manager is meant for
# direct-op differential tests.

LIMB_BACKENDS = ("vpu", "mxu")
MXU_SLICE_BITS = 7  # int8 slice split: lo in [0, 128), hi arithmetic
_SLICE_B = 1 << MXU_SLICE_BITS

_backend = os.environ.get("LODESTAR_TPU_LIMB_BACKEND", "vpu")
if _backend not in LIMB_BACKENDS:
    raise ValueError(
        f"LODESTAR_TPU_LIMB_BACKEND={_backend!r} not in {LIMB_BACKENDS}"
    )


def get_backend() -> str:
    return _backend


def set_backend(name: str, *, clear: bool = True, rewarm: bool = True) -> None:
    """Select the limb backend. The choice is read at TRACE time, so a
    switch drops every cached jit trace by default (XLA stages and
    Pallas kernel builders re-trace lazily and re-read the backend);
    the persistent compile cache keys on the emitted HLO, so both
    backends' compiled artifacts coexist on disk. clear=False skips
    the (process-wide, expensive to repopulate) cache drop — only
    sound for EAGER op use, which reads the backend per call.
    rewarm=False keeps the ingest warm-registry invalidation but
    suppresses its background warmup re-kick — for transient switches
    (the autotuner's probes) that would otherwise launch a compile
    storm for a candidate backend that may lose."""
    global _backend
    if name not in LIMB_BACKENDS:
        raise ValueError(f"unknown limb backend {name!r}; want {LIMB_BACKENDS}")
    if name != _backend:
        _backend = name
        if clear:
            jax.clear_caches()
            # every live jit trace just died: each (stage, shape) the
            # pipeline re-dispatches will recompile, which the device
            # telemetry counts as retraces — name the cause next to
            # the symptom on /metrics
            from ..metrics import device as _telemetry

            t = _telemetry.get_telemetry()
            if t is not None:
                t.note_backend_switch()
            # the ingest warm registry described the executables that
            # just died: a cold-fallback verifier trusting a stale
            # mark would dispatch a live bucket straight into the
            # recompile. Only when the kernels module is already
            # loaded — switching backends before any kernel import
            # has no marks to invalidate and must not pull the whole
            # kernel stack in here.
            import sys

            k = sys.modules.get("lodestar_tpu.bls.kernels")
            if k is not None:
                k.invalidate_ingest_warm(rewarm=rewarm)


@contextlib.contextmanager
def limb_backend(name: str, *, clear: bool = False):
    """Temporarily select a limb backend. Default clear=False: meant
    for eager differential tests/tools, which must not evict every
    other jitted pipeline's traces; pass clear=True when the block
    runs jitted/Pallas code that must re-trace under the backend."""
    prev = _backend
    set_backend(name, clear=clear)
    try:
        yield
    finally:
        set_backend(prev, clear=clear)

# Canonical interval profile: non-negative limbs in [0, B+1] plus a
# small redundant carry limb. Keeping the canonical domain non-negative
# makes the trace-time interval analysis tight (signed hulls are sticky
# at [-1, B] and would cycle); negative values are shifted into the
# non-negative cone by adding a limb-wise multiple-of-P offset first.
CANON_LO = tuple([0] * NCANON)
CANON_HI = tuple([B + 1] * NLIMB + [2])


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    """Canonical non-negative base-2^BITS limbs of x (< 2^(BITS*n))."""
    assert 0 <= x < (1 << (BITS * n))
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & (B - 1)
        x >>= BITS
    return out


def limbs_to_int(limbs) -> int:
    """Host-side exact value of a limb vector (any bounds, signed)."""
    return sum(int(v) << (BITS * i) for i, v in enumerate(np.asarray(limbs)))


@functools.lru_cache(maxsize=None)
def _fold_row(k: int) -> tuple:
    """Canonical limbs of 2^(BITS*k) mod P."""
    return tuple(int(v) for v in int_to_limbs(pow(2, BITS * k, P)))


@jax.tree_util.register_pytree_node_class
@dataclass
class Lv:
    """A limbed value: jnp int32 array (..., n) + exact static bounds."""

    v: jax.Array
    lo: tuple  # per-limb lower bounds (python ints)
    hi: tuple  # per-limb upper bounds

    def tree_flatten(self):
        return (self.v,), (self.lo, self.hi)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])

    @property
    def n(self) -> int:
        return len(self.lo)

    def widen(self, lo, hi) -> "Lv":
        """Declare looser bounds (sound; needed for scan fixed points)."""
        assert all(a <= b for a, b in zip(lo, self.lo)) and all(
            a <= b for a, b in zip(self.hi, hi)
        ), "widen() must enclose the current interval"
        return Lv(self.v, tuple(lo), tuple(hi))


def const(x: int, batch_shape=()) -> Lv:
    """Canonical constant (value reduced mod P), broadcastable."""
    limbs = int_to_limbs(x % P)
    arr = jnp.broadcast_to(
        jnp.asarray(np.concatenate([limbs, [0]]), jnp.int32),
        tuple(batch_shape) + (NCANON,),
    )
    bounds = tuple(int(v) for v in limbs) + (0,)
    return Lv(arr, bounds, bounds)


_BIT_WEIGHTS = (1 << np.arange(BITS, dtype=np.int32))


def from_ints(xs) -> Lv:
    """Batch of canonical field elements from python ints; shape
    (len(xs),). Vectorized: ints -> little-endian bytes (C-speed) ->
    numpy bit unpack -> 10-bit limb dot — the host-prep path must keep
    up with 1000+-set device batches (VERDICT r1 item 10)."""
    n = len(xs)
    if n == 0:
        return Lv(
            jnp.zeros((0, NCANON), jnp.int32),
            tuple([0] * NCANON),
            tuple([B - 1] * NLIMB + [0]),
        )
    raw = b"".join((x % P).to_bytes(49, "little") for x in xs)
    bytes_mat = np.frombuffer(raw, np.uint8).reshape(n, 49)
    bits = np.unpackbits(bytes_mat, axis=1, bitorder="little")
    limbs = (
        bits[:, : NLIMB * BITS]
        .reshape(n, NLIMB, BITS)
        .astype(np.int32)
        @ _BIT_WEIGHTS
    )
    mat = np.concatenate(
        [limbs, np.zeros((n, 1), np.int32)], axis=1
    )
    lo = tuple([0] * NCANON)
    hi = tuple([B - 1] * NLIMB + [0])
    return Lv(jnp.asarray(mat, jnp.int32), lo, hi)


def to_ints(x: Lv) -> np.ndarray:
    """Host: exact canonical ints mod P from a device value (any bounds)."""
    arr = np.asarray(jax.device_get(x.v))
    flat = arr.reshape(-1, x.n)
    vals = [limbs_to_int(r) % P for r in flat]
    return np.array(vals, dtype=object).reshape(arr.shape[:-1])


def _overflows(lo, hi) -> bool:
    return min(lo) < -INT32_MAX or max(hi) > INT32_MAX


# ---------------------------------------------------------------------------
# Raw ops (interval-tracked; auto-normalize operands on potential overflow)
# ---------------------------------------------------------------------------


def _pad_to(x: Lv, n: int) -> Lv:
    if x.n == n:
        return x
    assert x.n < n
    pad = [(0, 0)] * (x.v.ndim - 1) + [(0, n - x.n)]
    z = (0,) * (n - x.n)
    return Lv(jnp.pad(x.v, pad), x.lo + z, x.hi + z)


def add(a: Lv, b: Lv) -> Lv:
    n = max(a.n, b.n)
    a, b = _pad_to(a, n), _pad_to(b, n)
    lo = tuple(x + y for x, y in zip(a.lo, b.lo))
    hi = tuple(x + y for x, y in zip(a.hi, b.hi))
    if _overflows(lo, hi):
        return add(normalize(a), normalize(b))
    return Lv(a.v + b.v, lo, hi)


def sub(a: Lv, b: Lv) -> Lv:
    n = max(a.n, b.n)
    a, b = _pad_to(a, n), _pad_to(b, n)
    lo = tuple(x - y for x, y in zip(a.lo, b.hi))
    hi = tuple(x - y for x, y in zip(a.hi, b.lo))
    if _overflows(lo, hi):
        return sub(normalize(a), normalize(b))
    return Lv(a.v - b.v, lo, hi)


def neg(a: Lv) -> Lv:
    return Lv(-a.v, tuple(-h for h in a.hi), tuple(-l for l in a.lo))


def mul_small(a: Lv, k: int) -> Lv:
    """Multiply by a small python int (e.g. curve constants)."""
    lo = tuple(min(k * x, k * y) for x, y in zip(a.lo, a.hi))
    hi = tuple(max(k * x, k * y) for x, y in zip(a.lo, a.hi))
    if _overflows(lo, hi):
        return mul_small(normalize(a), k)
    return Lv(a.v * k, lo, hi)


@functools.lru_cache(maxsize=65536)
def _conv_bounds(alo, ahi, blo, bhi):
    """Exact per-column interval bounds of the convolution, plus an
    order-independent partial-sum bound (sum of |max product| per column):
    XLA may accumulate dot products in any order, so intermediate sums are
    only bounded by the absolute-value column sum, not the final interval."""
    na, nb = len(alo), len(blo)
    lo = [0] * (na + nb - 1)
    hi = [0] * (na + nb - 1)
    ab = [0] * (na + nb - 1)
    for i in range(na):
        for j in range(nb):
            cands = (
                alo[i] * blo[j],
                alo[i] * bhi[j],
                ahi[i] * blo[j],
                ahi[i] * bhi[j],
            )
            lo[i + j] += min(cands)
            hi[i + j] += max(cands)
            ab[i + j] += max(abs(c) for c in cands)
    return tuple(lo), tuple(hi), max(ab)


@functools.lru_cache(maxsize=None)
def _band_index(na: int, nb: int):
    """Static gather index + mask building the banded matrix of b:
    Bm[i, k] = b[k - i] for 0 <= k-i < nb, else 0."""
    nout = na + nb - 1
    idx = np.arange(nout)[None, :] - np.arange(na)[:, None]
    valid = (idx >= 0) & (idx < nb)
    return np.clip(idx, 0, nb - 1), valid.astype(np.int32)


def _slice_bounds(lo: tuple, hi: tuple):
    """Exact per-limb interval bounds of the int8 slice decomposition
    x = x_lo + 2^MXU_SLICE_BITS * x_hi (x_hi = x >> 7 arithmetic,
    x_lo = x - (x_hi << 7) in [0, 128))."""
    s = MXU_SLICE_BITS
    hi_b = tuple((l >> s, h >> s) for l, h in zip(lo, hi))
    lo_b = []
    for l, h in zip(lo, hi):
        if (l >> s) == (h >> s):  # one hi value: lo interval is exact
            lo_b.append((l - ((l >> s) << s), h - ((h >> s) << s)))
        else:
            lo_b.append((0, _SLICE_B - 1))
    return tuple(lo_b), hi_b


def _iv_ok(lo, hi) -> bool:
    return min(lo) >= -INT32_MAX and max(hi) <= INT32_MAX


def _recombine_ok(c0, clh, chl, c2) -> bool:
    """Shared int32 proof for the int8 recombination
    out = c0 + ((c1 + (c2 << s)) << s) with c1 = clh + chl emitted as
    ONE stacked dot (so its accumulation bound is the sum): checks the
    per-dot order-independent accumulation bounds and every shifted
    recombination intermediate. Args are (lo, hi, absmax) triples from
    _conv_bounds/_const_mat_bounds."""
    s = MXU_SLICE_BITS
    if max(c0[2], clh[2] + chl[2], c2[2]) > INT32_MAX:
        return False
    c1lo = tuple(x + y for x, y in zip(clh[0], chl[0]))
    c1hi = tuple(x + y for x, y in zip(clh[1], chl[1]))
    c2s = (tuple(x << s for x in c2[0]), tuple(x << s for x in c2[1]))
    if not _iv_ok(*c2s):
        return False
    t = (
        tuple(x + y for x, y in zip(c1lo, c2s[0])),
        tuple(x + y for x, y in zip(c1hi, c2s[1])),
    )
    ts = (tuple(x << s for x in t[0]), tuple(x << s for x in t[1]))
    if not (_iv_ok(*t) and _iv_ok(*ts)):
        return False
    out = (
        tuple(x + y for x, y in zip(c0[0], ts[0])),
        tuple(x + y for x, y in zip(c0[1], ts[1])),
    )
    return _iv_ok(*out)


@functools.lru_cache(maxsize=65536)
def _mxu_conv_plan(alo, ahi, blo, bhi) -> bool:
    """Trace-time proof that the int8-sliced conv of values with these
    interval profiles is exact: every slice fits int8, every partial
    contraction's order-independent accumulation bound fits int32, and
    every recombination intermediate fits int32. Returns False when the
    caller must normalize first (canonical profiles always pass)."""
    al_b, ah_b = _slice_bounds(alo, ahi)
    bl_b, bh_b = _slice_bounds(blo, bhi)
    for (l, h) in ah_b + bh_b:
        if l < -128 or h > 127:
            return False  # hi slice leaves int8
    unzip = lambda bs: (tuple(x[0] for x in bs), tuple(x[1] for x in bs))
    all_, alh = unzip(al_b)
    ahl, ahh = unzip(ah_b)
    bll, blh = unzip(bl_b)
    bhl, bhh = unzip(bh_b)
    return _recombine_ok(
        _conv_bounds(all_, alh, bll, blh),  # a_lo * b_lo
        _conv_bounds(all_, alh, bhl, bhh),  # a_lo * b_hi
        _conv_bounds(ahl, ahh, bll, blh),  # a_hi * b_lo
        _conv_bounds(ahl, ahh, bhl, bhh),  # a_hi * b_hi
    )


def _slice8(v):
    """Split int32 limbs into (lo8, hi8) with v == lo8 + (hi8 << 7).
    Caller must have proved both slices fit int8."""
    hi = v >> MXU_SLICE_BITS  # arithmetic: exact for signed v
    lo = v - (hi << MXU_SLICE_BITS)
    return lo.astype(jnp.int8), hi.astype(jnp.int8)


def _dot8(a8, m8):
    """int8 x int8 -> int32 contraction over the shared limb axis —
    the MXU's native quantized-GEMM shape (lax.dot_general with
    preferred_element_type=int32)."""
    return jnp.einsum(
        "...i,...ik->...k", a8, m8, preferred_element_type=jnp.int32
    )


def _conv_mxu(a: Lv, b: Lv, lo: tuple, hi: tuple) -> Lv:
    """int8-sliced schoolbook conv: three int8 contractions + a shifted
    recombination. Exact: with a = al + 128*ah, b = bl + 128*bh,
    conv(a,b) = conv(al,bl) + 128*(conv(al,bh)+conv(ah,bl))
              + 128^2*conv(ah,bh); the two cross terms share one
    stacked contraction. Bounds proved by _mxu_conv_plan."""
    s = MXU_SLICE_BITS
    idx, valid = _band_index(a.n, b.n)
    band = b.v[..., idx] * jnp.asarray(valid)  # (..., na, nout)
    bl8, bh8 = _slice8(band)
    al8, ah8 = _slice8(a.v)
    c0 = _dot8(al8, bl8)
    c1 = _dot8(
        jnp.concatenate([al8, ah8], axis=-1),
        jnp.concatenate([bh8, bl8], axis=-2),
    )
    c2 = _dot8(ah8, bh8)
    out = c0 + ((c1 + (c2 << s)) << s)
    return Lv(out, lo, hi)


def conv(a: Lv, b: Lv) -> Lv:
    """Schoolbook product (length na+nb-1), carry-free accumulation.

    VPU backend: one batched int32 matvec against a banded gather of
    b's limbs (3 XLA ops) rather than na slice-adds, keeping scan
    bodies that chain hundreds of field muls small enough to compile.
    MXU backend: the same banded gather, int8-sliced and emitted as
    three int8xint8->int32 contractions (see _conv_mxu)."""
    lo, hi, absmax = _conv_bounds(a.lo, a.hi, b.lo, b.hi)
    if _overflows(lo, hi) or absmax > INT32_MAX:
        a2, b2 = normalize(a), normalize(b)
        if (a2.lo, a2.hi, b2.lo, b2.hi) == (a.lo, a.hi, b.lo, b.hi):
            raise OverflowError("conv overflows even on canonical inputs")
        return conv(a2, b2)
    if _backend == "mxu":
        if _mxu_conv_plan(a.lo, a.hi, b.lo, b.hi):
            return _conv_mxu(a, b, lo, hi)
        a2, b2 = normalize(a), normalize(b)
        if (a2.lo, a2.hi, b2.lo, b2.hi) != (a.lo, a.hi, b.lo, b.hi):
            return conv(a2, b2)
        # canonical profiles always satisfy the int8 plan; anything
        # that still fails here is a non-normalizable profile — the
        # int32 VPU op below stays exact for it.
    na, nb = a.n, b.n
    idx, valid = _band_index(na, nb)
    band = b.v[..., idx] * jnp.asarray(valid)  # (..., na, nout)
    out = jnp.einsum(
        "...i,...ik->...k", a.v, band, preferred_element_type=jnp.int32
    )
    return Lv(out, lo, hi)


# ---------------------------------------------------------------------------
# Carry + fold normalization
# ---------------------------------------------------------------------------


def _carry_pass(x: Lv) -> Lv:
    """One data-parallel signed carry pass; extends length by 1."""
    x = _pad_to(x, x.n + 1)
    hi = x.v >> BITS  # arithmetic shift: floor division, signed-correct
    lo_v = x.v - (hi << BITS)  # in [0, B)
    zero = jnp.zeros(x.v.shape[:-1] + (1,), jnp.int32)
    shifted = jnp.concatenate([zero, hi[..., :-1]], axis=-1)
    hlo = [l >> BITS for l in x.lo]
    hhi = [h >> BITS for h in x.hi]
    new_lo, new_hi = [], []
    for i in range(x.n):
        c_lo, c_hi = (hlo[i - 1], hhi[i - 1]) if i > 0 else (0, 0)
        if hlo[i] == 0 and hhi[i] == 0:  # limb unsplit: hi==0, lo==value
            new_lo.append(x.lo[i] + c_lo)
            new_hi.append(x.hi[i] + c_hi)
        else:
            new_lo.append(0 + c_lo)
            new_hi.append(B - 1 + c_hi)
    return Lv(lo_v + shifted, tuple(new_lo), tuple(new_hi))


def _needs_carry(x: Lv) -> bool:
    return any(h > B + 1 for h in x.hi)


@functools.lru_cache(maxsize=65536)
def _offset_limbs(lo_bounds: tuple) -> tuple:
    """A limb vector o with o[i] >= -lo[i], value(o) = 0 mod P: adding it
    moves any value with these lower bounds into the non-negative cone
    without changing it mod P."""
    g = [max(0, -l) for l in lo_bounds]
    n = max(len(g), NLIMB)
    g += [0] * (n - len(g))
    G = sum(gi << (BITS * i) for i, gi in enumerate(g))
    if G == 0:
        return None
    K = -(-G // P)
    m = int_to_limbs(K * P - G)  # in [0, P)
    return tuple(g[i] + (int(m[i]) if i < NLIMB else 0) for i in range(n))


def _make_nonneg(x: Lv) -> Lv:
    """Shift into the non-negative cone (value preserved mod P)."""
    # shrink huge magnitudes first so the offset add cannot overflow
    while min(x.lo) < -(2**28) or max(x.hi) > 2**28:
        x = _carry_pass(x)
    off = _offset_limbs(x.lo)
    if off is None:
        return x
    x = _pad_to(x, len(off))
    arr = jnp.asarray(off, jnp.int32)
    lo = tuple(l + o for l, o in zip(x.lo, off))
    hi = tuple(h + o for h, o in zip(x.hi, off))
    if _overflows(lo, hi):
        raise OverflowError("offset overflow — magnitudes too large")
    return Lv(x.v + arr, lo, hi)


@functools.lru_cache(maxsize=None)
def _fold_plan(n: int, lo: tuple, hi: tuple):
    """Static fold matrix (n-NLIMB, NLIMB+1) and output bounds for folding
    high limbs of a value with the given interval profile. Column NLIMB is
    the canonical carry slot: the k==NLIMB limb passes through unchanged
    when its interval is already small."""
    mat = np.zeros((n - NLIMB, NLIMB + 1), np.int64)
    olo = [0] * (NLIMB + 1)
    ohi = [0] * (NLIMB + 1)
    oabs = [0] * (NLIMB + 1)
    for k in range(NLIMB, n):
        if lo[k] == 0 and hi[k] == 0:
            continue
        if k == NLIMB and 0 <= lo[k] and hi[k] <= 2:
            mat[0, NLIMB] = 1
            olo[NLIMB] += lo[k]
            ohi[NLIMB] += hi[k]
            oabs[NLIMB] += hi[k]
            continue
        row = _fold_row(k)
        for j in range(NLIMB):
            mat[k - NLIMB, j] = row[j]
            olo[j] += min(lo[k] * row[j], hi[k] * row[j])
            ohi[j] += max(lo[k] * row[j], hi[k] * row[j])
            oabs[j] += max(abs(lo[k]), abs(hi[k])) * row[j]
    return mat, tuple(olo), tuple(ohi), max(oabs)


def _const_mat_bounds(xlo: tuple, xhi: tuple, mat) -> tuple:
    """Exact per-column bounds + order-independent accumulation bound
    of x @ mat for a constant non-negative integer matrix."""
    nk, nj = mat.shape
    lo = [0] * nj
    hi = [0] * nj
    ab = [0] * nj
    for k in range(nk):
        for j in range(nj):
            m = int(mat[k, j])
            if m == 0:
                continue
            lo[j] += min(xlo[k] * m, xhi[k] * m)
            hi[j] += max(xlo[k] * m, xhi[k] * m)
            ab[j] += max(abs(xlo[k]), abs(xhi[k])) * m
    return tuple(lo), tuple(hi), max(ab)


@functools.lru_cache(maxsize=None)
def _fold_plan_mxu(n: int, lo: tuple, hi: tuple) -> bool:
    """Trace-time proof that the int8-sliced fold matmul is exact for
    this interval profile (mirrors _mxu_conv_plan; the fold matrix is
    a non-negative constant < 2^10 so only the value side can fail)."""
    s = MXU_SLICE_BITS
    mat = _fold_plan(n, lo, hi)[0]
    xl_b, xh_b = _slice_bounds(lo[NLIMB:], hi[NLIMB:])
    if any(l < -128 or h > 127 for l, h in xh_b):
        return False
    mat_hi = mat >> s  # entries < 8
    mat_lo = mat - (mat_hi << s)
    unzip = lambda bs: (tuple(x[0] for x in bs), tuple(x[1] for x in bs))
    xll, xlh = unzip(xl_b)
    xhl, xhh = unzip(xh_b)
    return _recombine_ok(
        _const_mat_bounds(xll, xlh, mat_lo),
        _const_mat_bounds(xll, xlh, mat_hi),
        _const_mat_bounds(xhl, xhh, mat_lo),
        _const_mat_bounds(xhl, xhh, mat_hi),
    )


def _fold_mxu(xs, mat) -> jax.Array:
    """int8-sliced x @ mat: batch on the GEMM M dimension, the constant
    fold matrix on N — the cleanest MXU mapping in the module (shared
    weights, unlike conv's per-element band)."""
    s = MXU_SLICE_BITS
    ml8, mh8 = _slice8(jnp.asarray(mat, jnp.int32))
    xl8, xh8 = _slice8(xs)

    def dot(v8, m8):
        return jnp.einsum(
            "...k,kj->...j", v8, m8, preferred_element_type=jnp.int32
        )

    c0 = dot(xl8, ml8)
    c1 = dot(
        jnp.concatenate([xl8, xh8], axis=-1),
        jnp.concatenate([mh8, ml8], axis=0),
    )
    c2 = dot(xh8, mh8)
    return c0 + ((c1 + (c2 << s)) << s)


def _fold_overflow(x: Lv) -> Lv:
    """Fold limbs at index >= NLIMB back below P's bit range via the
    precomputed 2^(10k) mod P rows (one static matmul — int32 on the
    VPU backend, int8-sliced on the MXU backend), except a small
    interval at the canonical carry slot (index NLIMB), which stays
    in place."""
    mat, flo, fhi, fabs = _fold_plan(x.n, x.lo, x.hi)
    lo = tuple(a + b for a, b in zip(x.lo[:NLIMB] + (0,), flo))
    hi = tuple(a + b for a, b in zip(x.hi[:NLIMB] + (0,), fhi))
    if _overflows(lo, hi) or fabs > INT32_MAX:
        raise OverflowError("fold overflow — carry before folding")
    keep = jnp.pad(x.v[..., :NLIMB], [(0, 0)] * (x.v.ndim - 1) + [(0, 1)])
    if _backend == "mxu" and _fold_plan_mxu(x.n, x.lo, x.hi):
        folded = _fold_mxu(x.v[..., NLIMB:], mat)
    else:
        folded = jnp.einsum(
            "...k,kj->...j",
            x.v[..., NLIMB:],
            jnp.asarray(mat, jnp.int32),
            preferred_element_type=jnp.int32,
        )
    return Lv(keep + folded, lo, hi)


def normalize(x: Lv) -> Lv:
    """Reduce to the canonical 40-limb profile (value preserved mod P).

    Trace-time-terminating loop: carry passes shrink limb magnitudes
    geometrically; folds remove high limbs. Exact intervals drive the
    loop, so the emitted op sequence is static per input profile.
    """
    if is_canonical_profile(x):
        return x.widen(CANON_LO, CANON_HI)
    x = _make_nonneg(x)
    for _ in range(64):
        if _needs_carry(x):
            x = _carry_pass(x)
            continue
        if x.n > NCANON or (
            x.n == NCANON and not (0 <= x.lo[-1] and x.hi[-1] <= 2)
        ):
            x = _fold_overflow(x)
            continue
        break
    else:
        raise RuntimeError("normalize() failed to converge — bounds bug")
    x = _pad_to(x, NCANON)
    return x.widen(CANON_LO, CANON_HI)


def is_canonical_profile(x: Lv) -> bool:
    return (
        x.n == NCANON
        and all(l >= c for l, c in zip(x.lo, CANON_LO))
        and all(h <= c for h, c in zip(x.hi, CANON_HI))
    )

"""Multi-precision Fq arithmetic primitives for BLS12-381 on TPU.

Reference analog: the blst C library's 384-bit field arithmetic
(@chainsafe/blst, SURVEY.md §2.1). blst uses 6x64-bit limbs with carry
chains and Montgomery multiplication — a serial-CPU design. TPUs have no
64-bit scalar units, no carry flags, and want wide, branch-free, static-
shape vector code. This module therefore uses a *redundant signed limb*
representation designed for the TPU VPU:

  - An Fq element is 40 int32 limbs in radix 2^10 (39 limbs cover 390
    bits >= 382; limb 39 is a small redundant carry limb), batched over
    arbitrary leading dims.
  - Multiplication is a plain schoolbook convolution: products of 10-bit
    limbs and their 40-term column sums stay far below 2^31, so no carry
    propagation is needed *inside* the product loop (carry-free MAC).
  - Reduction mod P is a linear fold: 2^(10k) mod P for every overflow
    limb index k is a precomputed constant row; folding high limbs is a
    small constant matrix-multiply that XLA maps onto fused multiply-adds
    (and later, Pallas can put an int8-decomposed version on the MXU).
  - Carry normalization is a handful of data-parallel shift/subtract
    passes (no sequential ripple), correct for signed limbs because the
    int32 right shift is arithmetic.

Overflow safety is *proved at trace time*: every value carries an exact
per-limb interval, and every op propagates intervals with exact interval
arithmetic, auto-normalizing operands when a column sum could leave
int32. Intervals are static Python data (pytree aux), so this costs
nothing at runtime, and `normalize()` lands on a fixed canonical profile
so `lax.scan` carries typecheck.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls.fields import P

BITS = 10
B = 1 << BITS  # limb radix
NLIMB = 39  # 390 bits >= 382 > log2(P)
NCANON = NLIMB + 1  # canonical length incl. redundant carry limb
INT32_MAX = 2**31 - 1

# Canonical interval profile: non-negative limbs in [0, B+1] plus a
# small redundant carry limb. Keeping the canonical domain non-negative
# makes the trace-time interval analysis tight (signed hulls are sticky
# at [-1, B] and would cycle); negative values are shifted into the
# non-negative cone by adding a limb-wise multiple-of-P offset first.
CANON_LO = tuple([0] * NCANON)
CANON_HI = tuple([B + 1] * NLIMB + [2])


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    """Canonical non-negative base-2^BITS limbs of x (< 2^(BITS*n))."""
    assert 0 <= x < (1 << (BITS * n))
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & (B - 1)
        x >>= BITS
    return out


def limbs_to_int(limbs) -> int:
    """Host-side exact value of a limb vector (any bounds, signed)."""
    return sum(int(v) << (BITS * i) for i, v in enumerate(np.asarray(limbs)))


@functools.lru_cache(maxsize=None)
def _fold_row(k: int) -> tuple:
    """Canonical limbs of 2^(BITS*k) mod P."""
    return tuple(int(v) for v in int_to_limbs(pow(2, BITS * k, P)))


@jax.tree_util.register_pytree_node_class
@dataclass
class Lv:
    """A limbed value: jnp int32 array (..., n) + exact static bounds."""

    v: jax.Array
    lo: tuple  # per-limb lower bounds (python ints)
    hi: tuple  # per-limb upper bounds

    def tree_flatten(self):
        return (self.v,), (self.lo, self.hi)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])

    @property
    def n(self) -> int:
        return len(self.lo)

    def widen(self, lo, hi) -> "Lv":
        """Declare looser bounds (sound; needed for scan fixed points)."""
        assert all(a <= b for a, b in zip(lo, self.lo)) and all(
            a <= b for a, b in zip(self.hi, hi)
        ), "widen() must enclose the current interval"
        return Lv(self.v, tuple(lo), tuple(hi))


def const(x: int, batch_shape=()) -> Lv:
    """Canonical constant (value reduced mod P), broadcastable."""
    limbs = int_to_limbs(x % P)
    arr = jnp.broadcast_to(
        jnp.asarray(np.concatenate([limbs, [0]]), jnp.int32),
        tuple(batch_shape) + (NCANON,),
    )
    bounds = tuple(int(v) for v in limbs) + (0,)
    return Lv(arr, bounds, bounds)


_BIT_WEIGHTS = (1 << np.arange(BITS, dtype=np.int32))


def from_ints(xs) -> Lv:
    """Batch of canonical field elements from python ints; shape
    (len(xs),). Vectorized: ints -> little-endian bytes (C-speed) ->
    numpy bit unpack -> 10-bit limb dot — the host-prep path must keep
    up with 1000+-set device batches (VERDICT r1 item 10)."""
    n = len(xs)
    if n == 0:
        return Lv(
            jnp.zeros((0, NCANON), jnp.int32),
            tuple([0] * NCANON),
            tuple([B - 1] * NLIMB + [0]),
        )
    raw = b"".join((x % P).to_bytes(49, "little") for x in xs)
    bytes_mat = np.frombuffer(raw, np.uint8).reshape(n, 49)
    bits = np.unpackbits(bytes_mat, axis=1, bitorder="little")
    limbs = (
        bits[:, : NLIMB * BITS]
        .reshape(n, NLIMB, BITS)
        .astype(np.int32)
        @ _BIT_WEIGHTS
    )
    mat = np.concatenate(
        [limbs, np.zeros((n, 1), np.int32)], axis=1
    )
    lo = tuple([0] * NCANON)
    hi = tuple([B - 1] * NLIMB + [0])
    return Lv(jnp.asarray(mat, jnp.int32), lo, hi)


def to_ints(x: Lv) -> np.ndarray:
    """Host: exact canonical ints mod P from a device value (any bounds)."""
    arr = np.asarray(jax.device_get(x.v))
    flat = arr.reshape(-1, x.n)
    vals = [limbs_to_int(r) % P for r in flat]
    return np.array(vals, dtype=object).reshape(arr.shape[:-1])


def _overflows(lo, hi) -> bool:
    return min(lo) < -INT32_MAX or max(hi) > INT32_MAX


# ---------------------------------------------------------------------------
# Raw ops (interval-tracked; auto-normalize operands on potential overflow)
# ---------------------------------------------------------------------------


def _pad_to(x: Lv, n: int) -> Lv:
    if x.n == n:
        return x
    assert x.n < n
    pad = [(0, 0)] * (x.v.ndim - 1) + [(0, n - x.n)]
    z = (0,) * (n - x.n)
    return Lv(jnp.pad(x.v, pad), x.lo + z, x.hi + z)


def add(a: Lv, b: Lv) -> Lv:
    n = max(a.n, b.n)
    a, b = _pad_to(a, n), _pad_to(b, n)
    lo = tuple(x + y for x, y in zip(a.lo, b.lo))
    hi = tuple(x + y for x, y in zip(a.hi, b.hi))
    if _overflows(lo, hi):
        return add(normalize(a), normalize(b))
    return Lv(a.v + b.v, lo, hi)


def sub(a: Lv, b: Lv) -> Lv:
    n = max(a.n, b.n)
    a, b = _pad_to(a, n), _pad_to(b, n)
    lo = tuple(x - y for x, y in zip(a.lo, b.hi))
    hi = tuple(x - y for x, y in zip(a.hi, b.lo))
    if _overflows(lo, hi):
        return sub(normalize(a), normalize(b))
    return Lv(a.v - b.v, lo, hi)


def neg(a: Lv) -> Lv:
    return Lv(-a.v, tuple(-h for h in a.hi), tuple(-l for l in a.lo))


def mul_small(a: Lv, k: int) -> Lv:
    """Multiply by a small python int (e.g. curve constants)."""
    lo = tuple(min(k * x, k * y) for x, y in zip(a.lo, a.hi))
    hi = tuple(max(k * x, k * y) for x, y in zip(a.lo, a.hi))
    if _overflows(lo, hi):
        return mul_small(normalize(a), k)
    return Lv(a.v * k, lo, hi)


@functools.lru_cache(maxsize=65536)
def _conv_bounds(alo, ahi, blo, bhi):
    """Exact per-column interval bounds of the convolution, plus an
    order-independent partial-sum bound (sum of |max product| per column):
    XLA may accumulate dot products in any order, so intermediate sums are
    only bounded by the absolute-value column sum, not the final interval."""
    na, nb = len(alo), len(blo)
    lo = [0] * (na + nb - 1)
    hi = [0] * (na + nb - 1)
    ab = [0] * (na + nb - 1)
    for i in range(na):
        for j in range(nb):
            cands = (
                alo[i] * blo[j],
                alo[i] * bhi[j],
                ahi[i] * blo[j],
                ahi[i] * bhi[j],
            )
            lo[i + j] += min(cands)
            hi[i + j] += max(cands)
            ab[i + j] += max(abs(c) for c in cands)
    return tuple(lo), tuple(hi), max(ab)


@functools.lru_cache(maxsize=None)
def _band_index(na: int, nb: int):
    """Static gather index + mask building the banded matrix of b:
    Bm[i, k] = b[k - i] for 0 <= k-i < nb, else 0."""
    nout = na + nb - 1
    idx = np.arange(nout)[None, :] - np.arange(na)[:, None]
    valid = (idx >= 0) & (idx < nb)
    return np.clip(idx, 0, nb - 1), valid.astype(np.int32)


def conv(a: Lv, b: Lv) -> Lv:
    """Schoolbook product (length na+nb-1), carry-free accumulation.

    Emitted as one batched int32 matvec against a banded gather of b's
    limbs (3 XLA ops) rather than na slice-adds, keeping scan bodies that
    chain hundreds of field muls small enough to compile."""
    lo, hi, absmax = _conv_bounds(a.lo, a.hi, b.lo, b.hi)
    if _overflows(lo, hi) or absmax > INT32_MAX:
        a2, b2 = normalize(a), normalize(b)
        if (a2.lo, a2.hi, b2.lo, b2.hi) == (a.lo, a.hi, b.lo, b.hi):
            raise OverflowError("conv overflows even on canonical inputs")
        return conv(a2, b2)
    na, nb = a.n, b.n
    idx, valid = _band_index(na, nb)
    band = b.v[..., idx] * jnp.asarray(valid)  # (..., na, nout)
    out = jnp.einsum(
        "...i,...ik->...k", a.v, band, preferred_element_type=jnp.int32
    )
    return Lv(out, lo, hi)


# ---------------------------------------------------------------------------
# Carry + fold normalization
# ---------------------------------------------------------------------------


def _carry_pass(x: Lv) -> Lv:
    """One data-parallel signed carry pass; extends length by 1."""
    x = _pad_to(x, x.n + 1)
    hi = x.v >> BITS  # arithmetic shift: floor division, signed-correct
    lo_v = x.v - (hi << BITS)  # in [0, B)
    zero = jnp.zeros(x.v.shape[:-1] + (1,), jnp.int32)
    shifted = jnp.concatenate([zero, hi[..., :-1]], axis=-1)
    hlo = [l >> BITS for l in x.lo]
    hhi = [h >> BITS for h in x.hi]
    new_lo, new_hi = [], []
    for i in range(x.n):
        c_lo, c_hi = (hlo[i - 1], hhi[i - 1]) if i > 0 else (0, 0)
        if hlo[i] == 0 and hhi[i] == 0:  # limb unsplit: hi==0, lo==value
            new_lo.append(x.lo[i] + c_lo)
            new_hi.append(x.hi[i] + c_hi)
        else:
            new_lo.append(0 + c_lo)
            new_hi.append(B - 1 + c_hi)
    return Lv(lo_v + shifted, tuple(new_lo), tuple(new_hi))


def _needs_carry(x: Lv) -> bool:
    return any(h > B + 1 for h in x.hi)


@functools.lru_cache(maxsize=65536)
def _offset_limbs(lo_bounds: tuple) -> tuple:
    """A limb vector o with o[i] >= -lo[i], value(o) = 0 mod P: adding it
    moves any value with these lower bounds into the non-negative cone
    without changing it mod P."""
    g = [max(0, -l) for l in lo_bounds]
    n = max(len(g), NLIMB)
    g += [0] * (n - len(g))
    G = sum(gi << (BITS * i) for i, gi in enumerate(g))
    if G == 0:
        return None
    K = -(-G // P)
    m = int_to_limbs(K * P - G)  # in [0, P)
    return tuple(g[i] + (int(m[i]) if i < NLIMB else 0) for i in range(n))


def _make_nonneg(x: Lv) -> Lv:
    """Shift into the non-negative cone (value preserved mod P)."""
    # shrink huge magnitudes first so the offset add cannot overflow
    while min(x.lo) < -(2**28) or max(x.hi) > 2**28:
        x = _carry_pass(x)
    off = _offset_limbs(x.lo)
    if off is None:
        return x
    x = _pad_to(x, len(off))
    arr = jnp.asarray(off, jnp.int32)
    lo = tuple(l + o for l, o in zip(x.lo, off))
    hi = tuple(h + o for h, o in zip(x.hi, off))
    if _overflows(lo, hi):
        raise OverflowError("offset overflow — magnitudes too large")
    return Lv(x.v + arr, lo, hi)


@functools.lru_cache(maxsize=None)
def _fold_plan(n: int, lo: tuple, hi: tuple):
    """Static fold matrix (n-NLIMB, NLIMB+1) and output bounds for folding
    high limbs of a value with the given interval profile. Column NLIMB is
    the canonical carry slot: the k==NLIMB limb passes through unchanged
    when its interval is already small."""
    mat = np.zeros((n - NLIMB, NLIMB + 1), np.int64)
    olo = [0] * (NLIMB + 1)
    ohi = [0] * (NLIMB + 1)
    oabs = [0] * (NLIMB + 1)
    for k in range(NLIMB, n):
        if lo[k] == 0 and hi[k] == 0:
            continue
        if k == NLIMB and 0 <= lo[k] and hi[k] <= 2:
            mat[0, NLIMB] = 1
            olo[NLIMB] += lo[k]
            ohi[NLIMB] += hi[k]
            oabs[NLIMB] += hi[k]
            continue
        row = _fold_row(k)
        for j in range(NLIMB):
            mat[k - NLIMB, j] = row[j]
            olo[j] += min(lo[k] * row[j], hi[k] * row[j])
            ohi[j] += max(lo[k] * row[j], hi[k] * row[j])
            oabs[j] += max(abs(lo[k]), abs(hi[k])) * row[j]
    return mat, tuple(olo), tuple(ohi), max(oabs)


def _fold_overflow(x: Lv) -> Lv:
    """Fold limbs at index >= NLIMB back below P's bit range via the
    precomputed 2^(10k) mod P rows (one static int32 matmul), except a
    small interval at the canonical carry slot (index NLIMB), which stays
    in place."""
    mat, flo, fhi, fabs = _fold_plan(x.n, x.lo, x.hi)
    lo = tuple(a + b for a, b in zip(x.lo[:NLIMB] + (0,), flo))
    hi = tuple(a + b for a, b in zip(x.hi[:NLIMB] + (0,), fhi))
    if _overflows(lo, hi) or fabs > INT32_MAX:
        raise OverflowError("fold overflow — carry before folding")
    keep = jnp.pad(x.v[..., :NLIMB], [(0, 0)] * (x.v.ndim - 1) + [(0, 1)])
    folded = jnp.einsum(
        "...k,kj->...j",
        x.v[..., NLIMB:],
        jnp.asarray(mat, jnp.int32),
        preferred_element_type=jnp.int32,
    )
    return Lv(keep + folded, lo, hi)


def normalize(x: Lv) -> Lv:
    """Reduce to the canonical 40-limb profile (value preserved mod P).

    Trace-time-terminating loop: carry passes shrink limb magnitudes
    geometrically; folds remove high limbs. Exact intervals drive the
    loop, so the emitted op sequence is static per input profile.
    """
    if is_canonical_profile(x):
        return x.widen(CANON_LO, CANON_HI)
    x = _make_nonneg(x)
    for _ in range(64):
        if _needs_carry(x):
            x = _carry_pass(x)
            continue
        if x.n > NCANON or (
            x.n == NCANON and not (0 <= x.lo[-1] and x.hi[-1] <= 2)
        ):
            x = _fold_overflow(x)
            continue
        break
    else:
        raise RuntimeError("normalize() failed to converge — bounds bug")
    x = _pad_to(x, NCANON)
    return x.widen(CANON_LO, CANON_HI)


def is_canonical_profile(x: Lv) -> bool:
    return (
        x.n == NCANON
        and all(l >= c for l, c in zip(x.lo, CANON_LO))
        and all(h <= c for h, c in zip(x.hi, CANON_HI))
    )

"""Pallas TPU kernel: fused G2 scalar-multiplication ladder.

Companion to pallas_chain.py (same layout: limbs on sublanes, batch on
lanes, whole loop VMEM-resident). A 64-step double-and-add over a G2
point in jacobian coordinates costs ~45 modular multiplies per step;
as XLA scan every step round-trips ~1 KB/element through HBM, which
makes the two random-weight ladders and the ingest subgroup/cofactor
ladders a large slice of the verify pipeline. Here the whole ladder is
one kernel invocation.

Field layout per fq2 element: two (40, 128) int32 planes (c0, c1).
Point state: affine base (qx, qy) + jacobian accumulator (X, Y, Z) +
an (1, 128) infinity mask. Formulas mirror ops/curve.py jac_double
(dbl-2009-l) and jac_mixed_add exactly — that module is the
differential oracle.

Signed values never appear: subtraction adds a limb-wise offset O with
per-limb O_i >= 1025 and value(O) == 0 mod P (ops/limbs._offset_limbs
construction), then a capture-and-fold carry round renormalizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls.fields import P
from . import limbs as L
from .pallas_chain import LANES, ROWS, _fold_rows, make_modmul

NBITS = 64  # random-weight ladder width (kernels.RAND_BITS)


@functools.lru_cache(maxsize=None)
def _sub_offset() -> np.ndarray:
    """(40,) int32: per-limb >= 1025, value == 0 mod P."""
    off = L._offset_limbs(tuple([-1025] * ROWS))
    arr = np.zeros(ROWS, np.int32)
    arr[: len(off)] = off[:ROWS]
    # _offset_limbs may produce >40 limbs; fold any excess back
    extra = sum(
        int(v) << (L.BITS * (ROWS + i)) for i, v in enumerate(off[ROWS:])
    )
    if extra:
        red = L.int_to_limbs(extra % P)
        arr[: len(red)] += red
    assert all(arr >= 1025), arr.min()
    return arr


def _norm2(x, fold0):
    """Two capture-and-fold carry rounds. The fold rows' top limbs are
    zero (residues < P < 2^381 have empty limb 39), so captured top
    carries do not feed back — two rounds bring post-add/sub limb
    magnitudes (~<2^13) back to ~1030 with row 39 small."""
    for _ in range(2):
        hi = x >> L.BITS
        lo = x - (hi << L.BITS)
        top = hi[ROWS - 1 : ROWS, :]
        x = (
            lo
            + jnp.concatenate(
                [jnp.zeros((1, x.shape[1]), jnp.int32), hi[:-1, :]],
                axis=0,
            )
            + fold0 * top
        )
    return x


def _mk_field(fold_const, off_const):
    """Field helpers bound to the in-kernel constants."""
    fold0 = fold_const[0].reshape(ROWS, 1)
    off = off_const.reshape(ROWS, 1)

    mm = make_modmul(fold_const)

    def sub(a, b):
        # a <= ~1100 per limb, off >= 1025 >= b's post-norm limbs...
        # b may reach ~1100 after adds: use 2*off to stay non-negative
        return _norm2(a + 2 * off - b, fold0)

    def add(a, b):
        return _norm2(a + b, fold0)

    def small(a, k):
        return _norm2(a * k, fold0)

    def f2_mul(a, b):
        m0 = mm(a[0], b[0])
        m1 = mm(a[1], b[1])
        s = mm(_norm2(a[0] + a[1], fold0), _norm2(b[0] + b[1], fold0))
        return (sub(m0, m1), sub(sub(s, m0), m1))

    def f2_sqr(a):
        return f2_mul(a, a)

    def f2_sub(a, b):
        return (sub(a[0], b[0]), sub(a[1], b[1]))

    def f2_add(a, b):
        return (add(a[0], b[0]), add(a[1], b[1]))

    def f2_small(a, k):
        return (small(a[0], k), small(a[1], k))

    def f2_sel(m, a, b):
        # m: (1, LANES) int32 0/1
        return (
            jnp.where(m != 0, a[0], b[0]),
            jnp.where(m != 0, a[1], b[1]),
        )

    return mm, f2_mul, f2_sqr, f2_sub, f2_add, f2_small, f2_sel


def _ladder_kernel(
    nbits,
    bits_ref,
    fold_ref,
    off_ref,
    qx0_ref, qx1_ref, qy0_ref, qy1_ref, qinf_ref,
    ox0_ref, ox1_ref, oy0_ref, oy1_ref, oz0_ref, oz1_ref, oinf_ref,
):
    fold_const = fold_ref[:]
    off_const = off_ref[0:1, :].reshape(ROWS)
    (mm, f2_mul, f2_sqr, f2_sub, f2_add, f2_small, f2_sel) = _mk_field(
        fold_const, off_const
    )
    qx = (qx0_ref[:], qx1_ref[:])
    qy = (qy0_ref[:], qy1_ref[:])
    q_inf = qinf_ref[:]  # (1, LANES) int32

    def jac_double(X, Y, Z):
        A = f2_sqr(X)
        Bv = f2_sqr(Y)
        Cv = f2_sqr(Bv)
        t = f2_sqr(f2_add(X, Bv))
        D = f2_small(f2_sub(f2_sub(t, A), Cv), 2)
        E = f2_small(A, 3)
        F = f2_sqr(E)
        x3 = f2_sub(F, f2_small(D, 2))
        y3 = f2_sub(f2_mul(E, f2_sub(D, x3)), f2_small(Cv, 8))
        z3 = f2_small(f2_mul(Y, Z), 2)
        return x3, y3, z3

    def jac_mixed_add(X, Y, Z, inf):
        z2 = f2_sqr(Z)
        z3 = f2_mul(z2, Z)
        mu = f2_sub(f2_mul(qx, z2), X)
        th = f2_sub(f2_mul(qy, z3), Y)
        mu2 = f2_sqr(mu)
        mu3 = f2_mul(mu2, mu)
        xmu2 = f2_mul(X, mu2)
        x3 = f2_sub(f2_sub(f2_sqr(th), mu3), f2_small(xmu2, 2))
        y3 = f2_sub(
            f2_mul(th, f2_sub(xmu2, x3)), f2_mul(Y, mu3)
        )
        z3v = f2_mul(Z, mu)
        # acc at infinity -> q (affine, Z = 1)
        one = jnp.concatenate(
            [jnp.ones((1, LANES), jnp.int32),
             jnp.zeros((ROWS - 1, LANES), jnp.int32)],
            axis=0,
        )
        x3 = f2_sel(inf, qx, x3)
        y3 = f2_sel(inf, qy, y3)
        z3v = f2_sel(inf, (one, jnp.zeros((ROWS, LANES), jnp.int32)), z3v)
        new_inf = inf * q_inf  # stay infinite only if q is too
        return x3, y3, z3v, new_inf

    zero = jnp.zeros((ROWS, LANES), jnp.int32)
    state = (
        zero, zero,  # X
        zero, zero,  # Y
        zero, zero,  # Z
        jnp.ones((1, LANES), jnp.int32),  # inf
    )

    def body(i, st):
        X = (st[0], st[1]); Y = (st[2], st[3]); Z = (st[4], st[5])
        inf = st[6]
        dX, dY, dZ = jac_double(X, Y, Z)
        # doubling infinity stays infinity: select old state
        dX = f2_sel(inf, X, dX)
        dY = f2_sel(inf, Y, dY)
        dZ = f2_sel(inf, Z, dZ)
        aX, aY, aZ, a_inf = jac_mixed_add(dX, dY, dZ, inf)
        bit = bits_ref[i, 0:1, :]  # (1, LANES)
        nX = f2_sel(bit, aX, dX)
        nY = f2_sel(bit, aY, dY)
        nZ = f2_sel(bit, aZ, dZ)
        n_inf = jnp.where(bit != 0, a_inf, inf)
        return (nX[0], nX[1], nY[0], nY[1], nZ[0], nZ[1], n_inf)

    st = jax.lax.fori_loop(0, nbits, body, state)
    ox0_ref[:] = st[0]
    ox1_ref[:] = st[1]
    oy0_ref[:] = st[2]
    oy1_ref[:] = st[3]
    oz0_ref[:] = st[4]
    oz1_ref[:] = st[5]
    oinf_ref[:] = st[6]


@functools.lru_cache(maxsize=None)
def _ladder_call(n_blocks: int, nbits: int = NBITS):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_ladder_kernel, nbits)
    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    flag = lambda: pl.BlockSpec(  # noqa: E731
        (1, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(bits, qx0, qx1, qy0, qy1, qinf):
        n = n_blocks * LANES
        return pl.pallas_call(
            kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(
                    (nbits, 1, LANES),
                    lambda i: (0, 0, i),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
                vec(), vec(), vec(), vec(), flag(),
            ],
            out_specs=[vec(), vec(), vec(), vec(), vec(), vec(), flag()],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(6)
            ]
            + [jax.ShapeDtypeStruct((1, n), jnp.int32)],
        )(
            bits,
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            qx0, qx1, qy0, qy1, qinf,
        )

    return run


def _g1_ladder_kernel(
    nbits,
    bits_ref,
    fold_ref,
    off_ref,
    qx_ref, qy_ref, qinf_ref,
    ox_ref, oy_ref, oz_ref, oinf_ref,
):
    """G1 double-and-add: the Fq (single-plane) rendition of the G2
    kernel above — same dbl-2009-l / mixed-add formulas, same
    capture-and-fold normalization discipline."""
    fold_const = fold_ref[:]
    off_const = off_ref[0:1, :].reshape(ROWS)
    fold0 = fold_const[0].reshape(ROWS, 1)
    off = off_const.reshape(ROWS, 1)

    mm = make_modmul(fold_const)

    def nrm(x):
        return _norm2(x, fold0)

    def sub(a, b):
        return nrm(a + 2 * off - b)

    def add(a, b):
        return nrm(a + b)

    def small(a, k):
        return nrm(a * k)

    def sel(m, a, b):
        return jnp.where(m != 0, a, b)

    qx = qx_ref[:]
    qy = qy_ref[:]
    q_inf = qinf_ref[:]

    def jac_double(X, Y, Z):
        A = mm(X, X)
        Bv = mm(Y, Y)
        Cv = mm(Bv, Bv)
        t = add(X, Bv)
        t = mm(t, t)
        D = small(sub(sub(t, A), Cv), 2)
        E = small(A, 3)
        F = mm(E, E)
        x3 = sub(F, small(D, 2))
        y3 = sub(mm(E, sub(D, x3)), small(Cv, 8))
        z3 = small(mm(Y, Z), 2)
        return x3, y3, z3

    def jac_mixed_add(X, Y, Z, inf):
        z2 = mm(Z, Z)
        z3 = mm(z2, Z)
        mu = sub(mm(qx, z2), X)
        th = sub(mm(qy, z3), Y)
        mu2 = mm(mu, mu)
        mu3 = mm(mu2, mu)
        xmu2 = mm(X, mu2)
        x3 = sub(sub(mm(th, th), mu3), small(xmu2, 2))
        y3 = sub(mm(th, sub(xmu2, x3)), mm(Y, mu3))
        z3v = mm(Z, mu)
        one = jnp.concatenate(
            [jnp.ones((1, LANES), jnp.int32),
             jnp.zeros((ROWS - 1, LANES), jnp.int32)],
            axis=0,
        )
        x3 = sel(inf, qx, x3)
        y3 = sel(inf, qy, y3)
        z3v = sel(inf, one, z3v)
        return x3, y3, z3v, inf * q_inf

    zero = jnp.zeros((ROWS, LANES), jnp.int32)
    state = (zero, zero, zero, jnp.ones((1, LANES), jnp.int32))

    def body(i, st):
        X, Y, Z, inf = st
        dX, dY, dZ = jac_double(X, Y, Z)
        dX = sel(inf, X, dX)
        dY = sel(inf, Y, dY)
        dZ = sel(inf, Z, dZ)
        aX, aY, aZ, a_inf = jac_mixed_add(dX, dY, dZ, inf)
        bit = bits_ref[i, 0:1, :]
        return (
            sel(bit, aX, dX),
            sel(bit, aY, dY),
            sel(bit, aZ, dZ),
            jnp.where(bit != 0, a_inf, inf),
        )

    st = jax.lax.fori_loop(0, nbits, body, state)
    ox_ref[:] = st[0]
    oy_ref[:] = st[1]
    oz_ref[:] = st[2]
    oinf_ref[:] = st[3]


@functools.lru_cache(maxsize=None)
def _g1_ladder_call(n_blocks: int, nbits: int = NBITS):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_g1_ladder_kernel, nbits)
    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    flag = lambda: pl.BlockSpec(  # noqa: E731
        (1, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(bits, qx, qy, qinf):
        n = n_blocks * LANES
        return pl.pallas_call(
            kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(
                    (nbits, 1, LANES),
                    lambda i: (0, 0, i),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
                vec(), vec(), flag(),
            ],
            out_specs=[vec(), vec(), vec(), flag()],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(3)
            ]
            + [jax.ShapeDtypeStruct((1, n), jnp.int32)],
        )(
            bits,
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            qx, qy, qinf,
        )

    return run


def g1_scalar_mul(qx, qy, bits, q_inf=None):
    """[k]Q on G1 for per-element scalars — drop-in for
    curve.scalar_mul(FQ_OPS, ...) on TPU (the Fq analog of
    g2_scalar_mul below)."""
    from . import curve as C

    x = L.normalize(qx).v
    y = L.normalize(qy).v
    batch = x.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES

    def prep(v):
        return jnp.transpose(jnp.pad(v, ((0, padded - batch), (0, 0))))

    nbits = bits.shape[-1]
    bits_arr = jnp.transpose(
        jnp.pad(bits.astype(jnp.int32), ((0, padded - batch), (0, 0)))
    ).reshape(nbits, 1, padded)
    if q_inf is None:
        qinf_arr = jnp.zeros((1, padded), jnp.int32)
    else:
        qinf_arr = jnp.pad(
            q_inf.astype(jnp.int32), (0, padded - batch),
            constant_values=1,
        ).reshape(1, padded)
    outs = _g1_ladder_call(n_blocks, nbits)(
        bits_arr, prep(x), prep(y), qinf_arr
    )

    def lv(v):
        return L.Lv(
            jnp.transpose(v)[:batch, :],
            tuple([0] * L.NCANON),
            tuple([L.B + 2] * L.NCANON),
        )

    return C.JacPoint(
        lv(outs[0]),
        lv(outs[1]),
        lv(outs[2]),
        jnp.transpose(outs[3])[:batch, 0] != 0,
    )


def g2_scalar_mul(qx, qy, bits, q_inf=None):
    """[k]Q for per-element 64-bit scalars — drop-in for
    curve.scalar_mul(FQ2_OPS, ...) on TPU.

    qx, qy: fq2 tuples of canonical Lv (batch, 40); bits: (batch, 64)
    bool MSB-first; q_inf: optional (batch,) bool. Returns a
    curve.JacPoint with canonical-profile coordinates."""
    from . import curve as C

    x0 = L.normalize(qx[0]).v
    x1 = L.normalize(qx[1]).v
    y0 = L.normalize(qy[0]).v
    y1 = L.normalize(qy[1]).v
    batch = x0.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES

    def prep(v):
        return jnp.transpose(jnp.pad(v, ((0, padded - batch), (0, 0))))

    nbits = bits.shape[-1]
    bits_arr = jnp.transpose(
        jnp.pad(
            bits.astype(jnp.int32), ((0, padded - batch), (0, 0))
        )
    ).reshape(nbits, 1, padded)
    if q_inf is None:
        qinf_arr = jnp.zeros((1, padded), jnp.int32)
    else:
        qinf_arr = jnp.pad(
            q_inf.astype(jnp.int32), (0, padded - batch),
            constant_values=1,
        ).reshape(1, padded)
    outs = _ladder_call(n_blocks, nbits)(
        bits_arr, prep(x0), prep(x1), prep(y0), prep(y1), qinf_arr
    )
    def unprep(v):
        return jnp.transpose(v)[:batch, :]

    def lv(v):
        # HONEST bounds (see pallas_chain.pow_const): kernel limbs can
        # reach ~1025 in every row including the top one, wider than
        # the canonical-profile claim — downstream interval-driven
        # reduction must see that or exact equality goes wrong.
        return L.Lv(
            unprep(v),
            tuple([0] * L.NCANON),
            tuple([L.B + 2] * L.NCANON),
        )

    return C.JacPoint(
        (lv(outs[0]), lv(outs[1])),
        (lv(outs[2]), lv(outs[3])),
        (lv(outs[4]), lv(outs[5])),
        jnp.transpose(outs[6])[:batch, 0] != 0,
    )


# ---------------------------------------------------------------------------
# Static-scalar ladder: [k]Q for a scalar known at trace time.
#
# The per-element ladder above computes double + mixed-add EVERY step
# and selects — right for random blinding scalars, 2x wasteful for the
# curve parameter |x| = 0xd201000000010000 (popcount 6) that the
# subgroup check and cofactor clearing multiply by (ingest._mul_x).
# Here the whole double/add schedule is baked from the static scalar:
# 63 doubles + 5 adds instead of 63 doubles + 63 adds.
# ---------------------------------------------------------------------------


def _static_ladder_kernel(
    e,
    fold_ref,
    off_ref,
    qx0_ref, qx1_ref, qy0_ref, qy1_ref, qinf_ref,
    ox0_ref, ox1_ref, oy0_ref, oy1_ref, oz0_ref, oz1_ref, oinf_ref,
):
    fold_const = fold_ref[:]
    off_const = off_ref[0:1, :].reshape(ROWS)
    (mm, f2_mul, f2_sqr, f2_sub, f2_add, f2_small, f2_sel) = _mk_field(
        fold_const, off_const
    )
    qx = (qx0_ref[:], qx1_ref[:])
    qy = (qy0_ref[:], qy1_ref[:])
    q_inf = qinf_ref[:]

    def jac_double(X, Y, Z):
        A = f2_sqr(X)
        Bv = f2_sqr(Y)
        Cv = f2_sqr(Bv)
        t = f2_sqr(f2_add(X, Bv))
        D = f2_small(f2_sub(f2_sub(t, A), Cv), 2)
        E = f2_small(A, 3)
        F = f2_sqr(E)
        x3 = f2_sub(F, f2_small(D, 2))
        y3 = f2_sub(f2_mul(E, f2_sub(D, x3)), f2_small(Cv, 8))
        z3 = f2_small(f2_mul(Y, Z), 2)
        return x3, y3, z3

    def jac_mixed_add(X, Y, Z, inf):
        z2 = f2_sqr(Z)
        z3 = f2_mul(z2, Z)
        mu = f2_sub(f2_mul(qx, z2), X)
        th = f2_sub(f2_mul(qy, z3), Y)
        mu2 = f2_sqr(mu)
        mu3 = f2_mul(mu2, mu)
        xmu2 = f2_mul(X, mu2)
        x3 = f2_sub(f2_sub(f2_sqr(th), mu3), f2_small(xmu2, 2))
        y3 = f2_sub(
            f2_mul(th, f2_sub(xmu2, x3)), f2_mul(Y, mu3)
        )
        z3v = f2_mul(Z, mu)
        one = jnp.concatenate(
            [jnp.ones((1, LANES), jnp.int32),
             jnp.zeros((ROWS - 1, LANES), jnp.int32)],
            axis=0,
        )
        x3 = f2_sel(inf, qx, x3)
        y3 = f2_sel(inf, qy, y3)
        z3v = f2_sel(inf, (one, jnp.zeros((ROWS, LANES), jnp.int32)), z3v)
        return x3, y3, z3v, inf * q_inf

    one = jnp.concatenate(
        [jnp.ones((1, LANES), jnp.int32),
         jnp.zeros((ROWS - 1, LANES), jnp.int32)],
        axis=0,
    )
    zero = jnp.zeros((ROWS, LANES), jnp.int32)
    # acc = Q (consumes the MSB); Z = 1, infinity tracked from q_inf
    X, Y, Z = (qx[0], qx[1]), (qy[0], qy[1]), (one, zero)
    inf = q_inf

    def dbl_body(_, st):
        X = (st[0], st[1]); Y = (st[2], st[3]); Z = (st[4], st[5])
        inf = st[6]
        dX, dY, dZ = jac_double(X, Y, Z)
        dX = f2_sel(inf, X, dX)
        dY = f2_sel(inf, Y, dY)
        dZ = f2_sel(inf, Z, dZ)
        return (dX[0], dX[1], dY[0], dY[1], dZ[0], dZ[1], inf)

    # static schedule: runs of doubles + adds at set bits
    bits = bin(e)[3:]  # MSB consumed by init
    i = 0
    while i < len(bits):
        # one segment = the doubles up to AND INCLUDING the next set
        # bit (or the trailing zero run), then one add if it was set
        nxt = bits.find("1", i)
        run = (nxt - i + 1) if nxt >= 0 else (len(bits) - i)
        add_here = nxt >= 0
        st = (X[0], X[1], Y[0], Y[1], Z[0], Z[1], inf)
        st = jax.lax.fori_loop(0, run, dbl_body, st)
        X = (st[0], st[1]); Y = (st[2], st[3]); Z = (st[4], st[5])
        inf = st[6]
        if add_here:
            X, Y, Z, inf = jac_mixed_add(X, Y, Z, inf)
        i += run

    ox0_ref[:] = X[0]
    ox1_ref[:] = X[1]
    oy0_ref[:] = Y[0]
    oy1_ref[:] = Y[1]
    oz0_ref[:] = Z[0]
    oz1_ref[:] = Z[1]
    oinf_ref[:] = inf


@functools.lru_cache(maxsize=None)
def _static_ladder_call(e: int, n_blocks: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_static_ladder_kernel, e)
    FOLD_ROWS = _fold_rows().shape[0]
    vec = lambda: pl.BlockSpec(  # noqa: E731
        (ROWS, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    flag = lambda: pl.BlockSpec(  # noqa: E731
        (1, LANES), lambda i: (0, i), memory_space=pltpu.VMEM
    )

    @jax.jit
    def run(qx0, qx1, qy0, qy1, qinf):
        n = n_blocks * LANES
        return pl.pallas_call(
            kernel,
            grid=(n_blocks,),
            in_specs=[
                pl.BlockSpec(
                    (FOLD_ROWS, ROWS),
                    lambda i: (0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, ROWS), lambda i: (0, 0), memory_space=pltpu.VMEM
                ),
                vec(), vec(), vec(), vec(), flag(),
            ],
            out_specs=[vec(), vec(), vec(), vec(), vec(), vec(), flag()],
            out_shape=[
                jax.ShapeDtypeStruct((ROWS, n), jnp.int32)
                for _ in range(6)
            ]
            + [jax.ShapeDtypeStruct((1, n), jnp.int32)],
        )(
            jnp.asarray(_fold_rows()),
            jnp.asarray(_sub_offset()).reshape(1, ROWS),
            qx0, qx1, qy0, qy1, qinf,
        )

    return run


def g2_scalar_mul_static(qx, qy, e: int, q_inf=None):
    """[e]Q for a trace-time scalar (drop-in for g2_scalar_mul with a
    shared static scalar such as the BLS parameter |x|)."""
    from . import curve as C

    x0 = L.normalize(qx[0]).v
    x1 = L.normalize(qx[1]).v
    y0 = L.normalize(qy[0]).v
    y1 = L.normalize(qy[1]).v
    batch = x0.shape[0]
    n_blocks = -(-batch // LANES)
    padded = n_blocks * LANES

    def prep(v):
        return jnp.transpose(jnp.pad(v, ((0, padded - batch), (0, 0))))

    if q_inf is None:
        qinf_arr = jnp.zeros((1, padded), jnp.int32)
    else:
        qinf_arr = jnp.pad(
            q_inf.astype(jnp.int32), (0, padded - batch),
            constant_values=1,
        ).reshape(1, padded)
    outs = _static_ladder_call(e, n_blocks)(
        prep(x0), prep(x1), prep(y0), prep(y1), qinf_arr
    )

    def lv(v):
        return L.Lv(
            jnp.transpose(v)[:batch, :],
            tuple([0] * L.NCANON),
            tuple([L.B + 2] * L.NCANON),
        )

    return C.JacPoint(
        (lv(outs[0]), lv(outs[1])),
        (lv(outs[2]), lv(outs[3])),
        (lv(outs[4]), lv(outs[5])),
        jnp.transpose(outs[6])[:batch, 0] != 0,
    )

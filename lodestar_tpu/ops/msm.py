"""Bucketed Pippenger multi-scalar multiplication on TPU limb values.

Reference analog: blst's Pippenger MSM behind c-kzg's lincombs
(SURVEY.md §2.1), mirrored host-side by `csrc/bls381.c blsn_g1_msm`.
That C path is serial: one core walks every (point, scalar) pair, which
leaves peak-DA blocks (EIP-4844 blob verification, `crypto/kzg.py`) on
the one pairing-heavy hot path the accelerator cannot help. This module
ports the bucket method to the device the way the scalar ladders went
(static trace-time schedules, batched limb tensors, interval-proved
accumulators — the `pallas_ladder`/`pallas_chain` design points), with
a batch axis over independent MSMs so one dispatch serves all the
lincombs of a blob batch.

Shape of the device program (one jit, one dispatch):

  1. **Signed-digit decomposition** (host, numpy): each scalar k < r
     becomes ceil(255/w)+1 signed base-2^w digits d_j in [-2^(w-1),
     2^(w-1)); signed digits halve the bucket table vs the textbook
     method because -d*P = d*(-P) and negating a G1 point is one field
     negation of y. Exact: k == sum_j d_j * 2^(w*j) by construction.
  2. **Bucket accumulation** (device): a `lax.scan` over point chunks.
     Buckets live as a JacPoint batch of shape (B, par, nwin, 2^(w-1)+1)
     — B independent MSMs, `par` parallel accumulator copies (the
     jac_sum_scan trick: n/par sequential steps instead of n), one
     bucket table per window lane. Each step gathers the target bucket
     per (B, par, nwin) lane, adds the (sign-selected) point with the
     COMPLETE Jacobian add, and scatters it back. The complete add is
     load-bearing, not caution: duplicate input points are legal (two
     identical blobs yield identical proofs), and when their digits
     coincide at some window the bucket add degenerates to a doubling —
     the incomplete add's "negligible collision" argument does not
     apply when the adversary controls the points.
  3. **Bucket reduction** (device): the running-sum identity
     sum_b b*bucket_b = sum of suffix sums, one scan of 2^(w-1)-1 steps
     with two adds per step, batched over (B, nwin).
  4. **Window combination** (device): MSB-first scan over windows, w
     doublings + one add per step — the unchanged double-and-add tail.

  Sequential depth is n/par + 2^(w-1) + ~255/w steps with (par*nwin)-
  wide vector parallelism per step: on a TPU (batch-flat per-step cost)
  small windows minimize latency; on CPU XLA (per-lane linear cost) the
  total-adds optimum sits near w = log2(n). The window is therefore a
  KNOB (`set_msm_window` / LODESTAR_TPU_MSM_WINDOW) on the autotune
  grid (device/autotune.py `msm_window`).

Entry layer mirrors `bls/kernels.py`: MSM size rungs pad inputs to a
small set of static shapes so every rung is ONE compile served by the
persistent cache; live dispatches mark their rung warm in the kernels
warm registry (kind "msm") so `crypto/kzg.py`'s auto backend can route
cold rungs to the host C path instead of stalling gossip on a compile;
the jit entry is wrapped in `instrument_stage("msm")` so compiles,
retraces and dispatch/device timings land on /metrics next to the BLS
stages. Grounding: the bucketed-MSM cost model of 2G2T MSM outsourcing
(PAPERS.md, arXiv 2602.23464); the batch-verify engine shape of the
FPGA ECDSA verifier (arXiv 2112.02229).

Correctness oracles: `crypto/bls/native.py g1_msm` (blst-shaped C
Pippenger) and the pure-Python `crypto/bls/curve.py` ops — differential
tests in tests/test_ops_msm.py, bit-exact including infinity and
zero-scalar edge cases.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import jaxcache
from . import curve as C
from . import fq
from . import limbs as L

R_ORDER = (
    0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
)

# The supported fixed-window widths. 4 exists for cheap CPU-backed
# tests and tiny inputs; 8/12/16 are the autotune grid. Larger windows
# shrink the window count (less vector work) but grow the bucket
# table and its reduction scan as 2^(w-1).
SUPPORTED_WINDOWS = (4, 8, 12, 16)

# MSM size rungs: every dispatch pads to the smallest rung >= n, so
# the whole DA workload compiles a handful of static shapes (the
# bucket-ladder discipline of bls/kernels.py). 64 carries the
# max-blobs batch-verify lincombs; 4096 carries the blob-width
# Lagrange lincombs of blob_to_kzg_commitment/compute_kzg_proof.
MSM_RUNGS = (64, 128, 256, 512, 1024, 2048, 4096)

# Parallel accumulator copies in the bucket-accumulation scan (the
# jac_sum_scan two-level trick): n/PAR sequential steps, merged by a
# log2(PAR) tree. Every rung is a multiple of PAR.
PAR = 8

_WINDOW = int(os.environ.get("LODESTAR_TPU_MSM_WINDOW", "8"))
if _WINDOW not in SUPPORTED_WINDOWS:
    raise ValueError(
        f"LODESTAR_TPU_MSM_WINDOW={_WINDOW} not in {SUPPORTED_WINDOWS}"
    )


def msm_window() -> int:
    """The live fixed-window width (module knob; autotune-settable)."""
    return _WINDOW


def set_msm_window(w: int, rewarm: bool = True) -> None:
    """Select the Pippenger window width. Compiled programs are keyed
    on the window (static jit arg), so no cache clearing is needed —
    but the kernels warm registry's "msm" marks described programs at
    the OLD window, and trusting them would route a live lincomb
    straight into a cold compile, so they drop. The window assignment
    and the mark invalidation happen under the registry lock as ONE
    step, so a completing dispatch's check-and-mark (_mark_warm)
    observes either the old world (mark later cleared here) or the new
    one (window mismatch, no mark) — never a half-switched state.
    When a warmup policy exists in this process (node start ran
    warmup_msm), the rungs re-warm on a background thread — otherwise
    an autotune window retune would strand the DA workload on the host
    fallback for the rest of the process (nothing else warms a rung
    the auto backend's cold fallback never dispatches). rewarm=False
    suppresses the kick (tests, tools that manage warmup themselves,
    apply_config's deferred single kick)."""
    global _WINDOW
    w = int(w)
    if w not in SUPPORTED_WINDOWS:
        raise ValueError(
            f"unknown msm window {w}; want {SUPPORTED_WINDOWS}"
        )
    if w == _WINDOW:
        return
    k = sys.modules.get("lodestar_tpu.bls.kernels")
    if k is None:
        _WINDOW = w
    else:
        with k._WARM_GEN_LOCK:
            _WINDOW = w
            k._INGEST_WARM.difference_update(
                {x for x in k._INGEST_WARM if x[0] == "msm"}
            )
    if rewarm:
        rewarm_async()


def rewarm_async() -> None:
    """Kick a background MSM rewarm — a no-op unless this process
    opted into warmup (warmup_msm ran). Called by the window setter
    and by the kernels registry invalidation: a limb-backend switch
    clears the jit caches, which kills the MSM executables exactly
    like the BLS ingest ones, and only a re-kick keeps the DA
    workload off a permanent host fallback."""
    if not _WARMUP_STARTED:
        return
    import threading

    threading.Thread(
        target=warmup_msm, name="kzg-msm-rewarm", daemon=True
    ).start()


def num_windows(window: int) -> int:
    """Signed digit count for scalars < r < 2^255: ceil(255/w) data
    windows plus one carry window (the signed rounding can push a +1
    past the top data window)."""
    return 255 // window + 2


def msm_rung(n: int) -> int:
    """Smallest rung >= n (n must not exceed the top rung — callers
    chunk above it, see g1_msm_many)."""
    for b in MSM_RUNGS:
        if n <= b:
            return b
    raise ValueError(f"MSM size {n} above the top rung {MSM_RUNGS[-1]}")


def default_warmup_rungs() -> tuple[int, ...]:
    """The rungs the DA hot paths actually dispatch: the batch-verify
    lincombs (n = blobs-per-block, rung 64) and the blob-width
    Lagrange lincombs (rung 4096). Warming all seven rungs would pay
    five compiles nothing dispatches."""
    return (MSM_RUNGS[0], MSM_RUNGS[-1])


# ---------------------------------------------------------------------------
# Host-side signed-digit decomposition
# ---------------------------------------------------------------------------


def signed_digits(scalars, window: int) -> np.ndarray:
    """(len(scalars), num_windows) int32 signed base-2^w digits, LSW
    first; exact (sum_j d_j 2^(wj) == k mod r) by construction. Scalars
    are reduced mod r first — the spec's scalar domain (native.g1_msm
    reduces the same way)."""
    nwin = num_windows(window)
    half = 1 << (window - 1)
    full = 1 << window
    out = np.zeros((len(scalars), nwin), np.int32)
    for i, k in enumerate(scalars):
        k = int(k) % R_ORDER
        j = 0
        while k:
            d = k & (full - 1)
            if d >= half:
                d -= full
            out[i, j] = d
            k = (k - d) >> window
            j += 1
        assert j <= nwin, "signed-digit carry overran the window count"
    return out


# ---------------------------------------------------------------------------
# Device program
# ---------------------------------------------------------------------------


def _gather_lv(x: L.Lv, idx: jax.Array) -> L.Lv:
    """x.v (..., nbuckets, nlimb) gathered at idx (...) -> (..., nlimb)."""
    g = jnp.take_along_axis(x.v, idx[..., None, None], axis=-2)
    return L.Lv(g[..., 0, :], x.lo, x.hi)


def _scatter_lv(x: L.Lv, idx: jax.Array, val: L.Lv) -> L.Lv:
    """Write val (..., nlimb) into x at bucket idx (...). Caller
    guarantees val shares x's (canonical) interval profile."""
    assert (val.lo, val.hi) == (x.lo, x.hi)
    ix = jnp.indices(idx.shape, sparse=True)
    return L.Lv(x.v.at[tuple(ix) + (idx,)].set(val.v), x.lo, x.hi)


def _gather_jac(b: C.JacPoint, idx: jax.Array) -> C.JacPoint:
    ix = jnp.indices(idx.shape, sparse=True)
    return C.JacPoint(
        _gather_lv(b.x, idx),
        _gather_lv(b.y, idx),
        _gather_lv(b.z, idx),
        b.inf[tuple(ix) + (idx,)],
    )


def _scatter_jac(
    b: C.JacPoint, idx: jax.Array, val: C.JacPoint
) -> C.JacPoint:
    ix = jnp.indices(idx.shape, sparse=True)
    return C.JacPoint(
        _scatter_lv(b.x, idx, val.x),
        _scatter_lv(b.y, idx, val.y),
        _scatter_lv(b.z, idx, val.z),
        b.inf.at[tuple(ix) + (idx,)].set(val.inf),
    )


def _bcast_lv(x: L.Lv, shape: tuple) -> L.Lv:
    return L.Lv(
        jnp.broadcast_to(x.v[..., None, :], shape + (x.v.shape[-1],)),
        x.lo,
        x.hi,
    )


def _norm_add(p: C.JacPoint, q: C.JacPoint) -> C.JacPoint:
    """Complete add + canonical profile (stable scan carry type)."""
    return C.jac_normalize(C.FQ_OPS, C.jac_add(C.FQ_OPS, p, q))


@functools.partial(jax.jit, static_argnames=("window",))
def _msm_program(
    px: L.Lv, py: L.Lv, inf: jax.Array, digits: jax.Array, *, window: int
) -> C.JacPoint:
    """Batched Pippenger: px/py (B, n) canonical affine limb batches,
    inf (B, n) bool, digits (B, n, nwin) int32 signed. Returns the B
    MSM results as a JacPoint batch (B,). n must be a multiple of PAR
    (entry pads to a rung)."""
    B, n = inf.shape
    nwin = digits.shape[-1]
    half = 1 << (window - 1)
    nbuckets = half + 1  # slot 0 is the zero-digit trash bucket
    chunks = n // PAR

    def chunked(t):
        return jnp.moveaxis(
            t.reshape((B, chunks, PAR) + t.shape[2:]), 1, 0
        )

    px_c = L.Lv(chunked(px.v), px.lo, px.hi)
    py_c = L.Lv(chunked(py.v), py.lo, py.hi)
    inf_c = chunked(inf)
    dig_c = chunked(digits)

    buckets = C.jac_infinity(C.FQ_OPS, (B, PAR, nwin, nbuckets))

    def accumulate(bkts, xs):
        qx_v, qy_v, q_inf, digs = xs
        qx = L.Lv(qx_v, px.lo, px.hi)
        qy = L.Lv(qy_v, py.lo, py.hi)
        idx = jnp.abs(digs)  # (B, PAR, nwin); 0 -> trash slot
        lane = idx.shape
        bx = _bcast_lv(qx, lane)
        by = _bcast_lv(qy, lane)
        by = fq.select(digs < 0, L.neg(by), by)
        q = C.jac_from_affine(
            C.FQ_OPS,
            bx,
            by,
            jnp.broadcast_to(q_inf[..., None], lane),
        )
        cur = _gather_jac(bkts, idx)
        new = _norm_add(cur, q)
        return _scatter_jac(bkts, idx, new), None

    buckets, _ = jax.lax.scan(
        accumulate, buckets, (px_c.v, py_c.v, inf_c, dig_c)
    )

    # merge the PAR accumulator copies: log2(PAR) complete adds
    m = PAR
    while m > 1:
        h = m // 2
        bot = jax.tree.map(lambda t: t[:, :h], buckets)
        top = jax.tree.map(lambda t: t[:, h:m], buckets)
        buckets = _norm_add(bot, top)
        m = h
    buckets = jax.tree.map(lambda t: t[:, 0], buckets)  # (B, nwin, nbuckets)

    # bucket reduction: sum_b b*bucket_b via running suffix sums,
    # scanned from the top bucket down (slot 0 never enters; leaves
    # differ in trailing dims — coords carry a limb axis, inf does
    # not — so the bucket axis is sliced positionally)
    def bucket_stack(t):
        sl = [slice(None)] * t.ndim
        sl[2] = slice(1, None)
        return jnp.flip(jnp.moveaxis(t[tuple(sl)], 2, 0), 0)

    stack = jax.tree.map(bucket_stack, buckets)
    zero = C.jac_infinity(C.FQ_OPS, (B, nwin))

    def reduce_body(carry, bkt):
        acc, tot = carry
        acc = _norm_add(acc, bkt)
        tot = _norm_add(tot, acc)
        return (acc, tot), None

    (_, windows), _ = jax.lax.scan(reduce_body, (zero, zero), stack)

    # window combination, MSB first: tot = 2^w * tot + S_j
    win_stack = jax.tree.map(
        lambda t: jnp.flip(jnp.moveaxis(t, 1, 0), 0), windows
    )
    total = C.jac_infinity(C.FQ_OPS, (B,))

    def combine_body(tot, s_j):
        for _ in range(window):
            tot = C.jac_double(C.FQ_OPS, tot)
        return _norm_add(tot, s_j), None

    total, _ = jax.lax.scan(combine_body, total, win_stack)
    return total


# ---------------------------------------------------------------------------
# Telemetry + warm-registry seam
# ---------------------------------------------------------------------------

from ..metrics import device as _telemetry  # noqa: E402

_stage_msm = _telemetry.instrument_stage("msm", _msm_program)


def msm_is_warm(rung: int) -> bool:
    """Has this rung's program (at the live window) been compiled in
    this process / marked warm? Rides the kernels warm registry under
    kind "msm" so one registry answers every cold-fallback question."""
    from ..bls import kernels

    return kernels.ingest_is_warm(rung, "msm")


def _mark_warm(rung: int, window: int, gen: int) -> None:
    """Mark a rung warm — only when the dispatch that just completed
    (a) ran at the LIVE window (the registry is keyed on rung alone,
    so an explicit-window dispatch — tests, tools — or one that raced
    a set_msm_window retune must not land a mark describing a program
    the live window will never dispatch), and (b) started under the
    CURRENT registry generation (a limb-backend switch mid-dispatch
    bumped _WARM_GEN and killed the executable this dispatch compiled
    — the BLS warmup's warm_one_marked guard, applied here too). The
    check-and-mark runs under the same lock the setter's invalidation
    and the generation bump take, so neither can interleave."""
    from ..bls import kernels

    with kernels._WARM_GEN_LOCK:
        if window == _WINDOW and gen == kernels._WARM_GEN:
            kernels._INGEST_WARM.add(("msm", rung))


def warmup_progress() -> tuple[int, int]:
    """(warm, eligible) over default_warmup_rungs() — feeds the
    pipeline="msm" warmup gauges (metrics/device.py)."""
    rungs = default_warmup_rungs()
    return (sum(1 for b in rungs if msm_is_warm(b)), len(rungs))


# has warmup_msm ever run in this process? Gates the automatic rewarm
# on a live msm_window retune: processes that never opted into warmup
# (tests, benches) must not get background compiles sprung on them by
# a knob change (the kernels._WARMUP_STARTED discipline).
_WARMUP_STARTED = False


def warmup_msm(rungs: tuple[int, ...] | None = None) -> None:
    """Pre-compile (or cache-load) the MSM program for the given rungs
    by running one tiny dispatch to completion per rung, at the batch
    width the live path uses there: the batch-verify rung dispatches
    B=3 (the three verification lincombs of verify_blob_kzg_proof_
    batch), larger rungs B=1 (the blob-width Lagrange lincombs).
    Blocking — callers own the threading (node start wraps it in a
    thread)."""
    global _WARMUP_STARTED
    from ..crypto.bls import curve as oc

    _WARMUP_STARTED = True
    for rung in rungs or default_warmup_rungs():
        if msm_is_warm(rung):
            continue
        b = 3 if rung == MSM_RUNGS[0] else 1
        outs = g1_msm_many(
            [([oc.G1_GEN], [i + 1]) for i in range(b)], _pad_to=rung
        )
        if outs[0] != oc.G1_GEN:
            raise RuntimeError(f"msm warmup self-check failed at {rung}")


# ---------------------------------------------------------------------------
# Host entry points
# ---------------------------------------------------------------------------


def g1_msm(points, scalars, window: int | None = None, _pad_to=None):
    """sum_i scalars[i] * points[i] on the device. Points are oracle
    affine int tuples (None = infinity); scalars python ints (reduced
    mod r). Returns an affine tuple or None — the native.g1_msm
    contract, bit-exact."""
    return g1_msm_many(
        [(points, scalars)], window=window, _pad_to=_pad_to
    )[0]


def g1_msm_many(tasks, window: int | None = None, _pad_to=None):
    """Batched MSMs in ONE device dispatch: tasks is a list of
    (points, scalars) pairs, each padded to the shared rung (infinity
    points, zero scalars — both exact no-ops in the bucket method).
    This is how a blob batch's three verification lincombs ride one
    dispatch (crypto/kzg.py verify_blob_kzg_proof_batch)."""
    if not tasks:
        return []
    window = int(window) if window is not None else msm_window()
    if window not in SUPPORTED_WINDOWS:
        raise ValueError(
            f"unknown msm window {window}; want {SUPPORTED_WINDOWS}"
        )
    for pts, ks in tasks:
        if len(pts) != len(ks):
            raise ValueError("MSM points/scalars length mismatch")
    n_max = max(len(pts) for pts, _ in tasks)
    if n_max == 0:
        return [None] * len(tasks)
    if n_max > MSM_RUNGS[-1]:
        return _chunked_msm_many(tasks, window)
    rung = msm_rung(max(n_max, _pad_to or 0))
    nwin = num_windows(window)
    B = len(tasks)
    flat_pts: list = []
    digits = np.zeros((B, rung, nwin), np.int32)
    for b, (pts, ks) in enumerate(tasks):
        flat_pts.extend(pts)
        flat_pts.extend([None] * (rung - len(pts)))
        if ks:
            digits[b, : len(ks)] = signed_digits(ks, window)
    jaxcache.enable()
    from ..bls import kernels as _k

    gen = _k._WARM_GEN  # registry generation this dispatch compiles under
    jac = C.g1_batch_from_ints(flat_pts)  # (B*rung,)
    jac = jax.tree.map(
        lambda t: t.reshape((B, rung) + t.shape[1:]), jac
    )
    out = _stage_msm(
        jac.x, jac.y, jac.inf, jnp.asarray(digits), window=window
    )
    res = C.jac_to_affine_ints(C.FQ_OPS, out)
    _mark_warm(rung, window, gen)
    return res


def _chunked_msm_many(tasks, window: int):
    """Inputs beyond the top rung split into top-rung chunks whose
    partial results combine on host — the top rung covers the blob
    width, so this is a guard rail, not a hot path."""
    from ..crypto.bls import curve as oc

    top = MSM_RUNGS[-1]
    out = []
    for pts, ks in tasks:
        acc = None
        for i in range(0, len(pts), top):
            part = g1_msm(pts[i : i + top], ks[i : i + top], window)
            acc = oc.g1_add(acc, part)
        out.append(acc)
    return out

"""Verified eth_getBlockByHash / eth_getBlockByNumber support.

Reference analog: prover/src/utils/verification.ts verifyBlock +
validation.ts isValidBlock — the reference checks the RPC block's
hash/parentHash against the LC-verified execution payload and
validates the transactions trie.

This implementation is stricter than the reference: instead of
trusting individual response fields, it re-encodes the ENTIRE header
returned by the RPC and requires keccak(rlp(header)) to equal the
LC-verified block hash — authenticating every header field at once —
then recomputes the transactions and withdrawals tries from the
hydrated lists against the (now-authenticated) transactionsRoot and
withdrawalsRoot.
"""

from __future__ import annotations

from . import rlp
from .keccak import keccak256
from .mpt import ordered_trie_root


class BlockVerificationError(Exception):
    pass


def _b(hex_str: str | None) -> bytes:
    if hex_str is None:
        return b""
    return bytes.fromhex(hex_str.removeprefix("0x"))


def _i(hex_str: str | int | None) -> int:
    if hex_str is None:
        return 0
    if isinstance(hex_str, int):
        return hex_str
    return int(hex_str, 16)


def _int_be(hex_str) -> bytes:
    """Quantity -> minimal big-endian bytes (RLP integer form)."""
    v = _i(hex_str)
    return v.to_bytes((v.bit_length() + 7) // 8, "big") if v else b""


def header_fields(block: dict) -> list:
    """Ordered header field list for RLP encoding. Post-London fields
    are included when present in the response; since the final hash
    must match the verified anchor, a lying server cannot add or drop
    fields without detection."""
    fields = [
        _b(block["parentHash"]),
        _b(block["sha3Uncles"]),
        _b(block["miner"]),
        _b(block["stateRoot"]),
        _b(block["transactionsRoot"]),
        _b(block["receiptsRoot"]),
        _b(block["logsBloom"]),
        _int_be(block.get("difficulty")),
        _int_be(block["number"]),
        _int_be(block["gasLimit"]),
        _int_be(block["gasUsed"]),
        _int_be(block["timestamp"]),
        _b(block.get("extraData", "0x")),
        _b(block["mixHash"]),
        _b(block["nonce"]),
    ]
    for key, conv in (
        ("baseFeePerGas", _int_be),
        ("withdrawalsRoot", _b),
        ("blobGasUsed", _int_be),
        ("excessBlobGas", _int_be),
        ("parentBeaconBlockRoot", _b),
        ("requestsHash", _b),
    ):
        if block.get(key) is not None:
            fields.append(conv(block[key]))
        else:
            # Header fields are append-only across forks: absence of an
            # earlier field with a later one present cannot hash right,
            # so simply stop at the first absent field.
            break
    return fields


def header_hash(block: dict) -> bytes:
    return keccak256(rlp.encode(header_fields(block)))


def _access_list_rlp(access_list) -> list:
    return [
        [_b(e["address"]), [_b(k) for k in e.get("storageKeys", [])]]
        for e in (access_list or [])
    ]


def encode_transaction(tx: dict) -> bytes:
    """Canonical network encoding of a hydrated RPC transaction object
    (the trie leaf value; its keccak is the tx hash)."""
    typ = _i(tx.get("type", "0x0"))
    to = _b(tx["to"]) if tx.get("to") else b""
    data = _b(tx.get("input") or tx.get("data") or "0x")
    if typ == 0:
        return rlp.encode([
            _int_be(tx["nonce"]), _int_be(tx["gasPrice"]),
            _int_be(tx["gas"]), to, _int_be(tx.get("value")),
            data, _int_be(tx["v"]), _int_be(tx["r"]), _int_be(tx["s"]),
        ])
    y_parity = tx.get("yParity", tx.get("v"))
    if typ == 1:
        body = [
            _int_be(tx["chainId"]), _int_be(tx["nonce"]),
            _int_be(tx["gasPrice"]), _int_be(tx["gas"]), to,
            _int_be(tx.get("value")), data,
            _access_list_rlp(tx.get("accessList")),
            _int_be(y_parity), _int_be(tx["r"]), _int_be(tx["s"]),
        ]
    elif typ == 2:
        body = [
            _int_be(tx["chainId"]), _int_be(tx["nonce"]),
            _int_be(tx["maxPriorityFeePerGas"]),
            _int_be(tx["maxFeePerGas"]), _int_be(tx["gas"]), to,
            _int_be(tx.get("value")), data,
            _access_list_rlp(tx.get("accessList")),
            _int_be(y_parity), _int_be(tx["r"]), _int_be(tx["s"]),
        ]
    elif typ == 3:
        body = [
            _int_be(tx["chainId"]), _int_be(tx["nonce"]),
            _int_be(tx["maxPriorityFeePerGas"]),
            _int_be(tx["maxFeePerGas"]), _int_be(tx["gas"]), to,
            _int_be(tx.get("value")), data,
            _access_list_rlp(tx.get("accessList")),
            _int_be(tx["maxFeePerBlobGas"]),
            [_b(h) for h in tx.get("blobVersionedHashes", [])],
            _int_be(y_parity), _int_be(tx["r"]), _int_be(tx["s"]),
        ]
    elif typ == 4:  # EIP-7702 set-code (Prague / electra-era EL)
        auth_list = [
            [
                _int_be(a["chainId"]), _b(a["address"]),
                _int_be(a["nonce"]),
                _int_be(a.get("yParity", a.get("v"))),
                _int_be(a["r"]), _int_be(a["s"]),
            ]
            for a in (tx.get("authorizationList") or [])
        ]
        body = [
            _int_be(tx["chainId"]), _int_be(tx["nonce"]),
            _int_be(tx["maxPriorityFeePerGas"]),
            _int_be(tx["maxFeePerGas"]), _int_be(tx["gas"]), to,
            _int_be(tx.get("value")), data,
            _access_list_rlp(tx.get("accessList")),
            auth_list,
            _int_be(y_parity), _int_be(tx["r"]), _int_be(tx["s"]),
        ]
    else:
        raise BlockVerificationError(f"unknown tx type {typ}")
    return bytes([typ]) + rlp.encode(body)


def transactions_root(txs: list[dict]) -> bytes:
    return ordered_trie_root([encode_transaction(t) for t in txs])


def withdrawals_root(withdrawals: list[dict]) -> bytes:
    return ordered_trie_root([
        rlp.encode([
            _int_be(w["index"]), _int_be(w["validatorIndex"]),
            _b(w["address"]), _int_be(w["amount"]),
        ])
        for w in withdrawals
    ])


def verify_block(block: dict, expected_hash: bytes) -> None:
    """Full authentication of a hydrated RPC block against an
    LC-verified block hash. Raises BlockVerificationError."""
    if _b(block.get("hash", "0x")) != bytes(expected_hash):
        raise BlockVerificationError("block hash field mismatch")
    computed = header_hash(block)
    if computed != bytes(expected_hash):
        raise BlockVerificationError(
            "header fields do not hash to the verified block hash")
    txs = block.get("transactions", [])
    if txs and not isinstance(txs[0], dict):
        raise BlockVerificationError(
            "block must be hydrated (full transaction objects)")
    if transactions_root(txs) != _b(block["transactionsRoot"]):
        raise BlockVerificationError("transactions trie mismatch")
    if block.get("withdrawalsRoot") is not None:
        got = withdrawals_root(block.get("withdrawals", []))
        if got != _b(block["withdrawalsRoot"]):
            raise BlockVerificationError("withdrawals trie mismatch")

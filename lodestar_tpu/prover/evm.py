"""Scoped EVM interpreter for verified eth_call / eth_estimateGas.

Reference analog: packages/prover/src/utils/evm.ts — the reference
seeds an @ethereumjs/vm instance with proof-verified accounts (state
fetched via eth_createAccessList + eth_getProof, every account and
storage slot checked against the LC-verified state root) and executes
the call locally. Trust model: every VALUE the RPC supplies is proven
against the verified state root, but state COMPLETENESS rests on the
RPC's eth_createAccessList response — an RPC that omits a touched
account or slot from the access list makes the local EVM read it as
empty. The reference shares this assumption; treat results as
"verified under the access-list completeness assumption", not as
unconditional proof.

This is a from-scratch interpreter, not a port. Scope (documented
boundary, VERDICT r4 item 5):

  * Full computational opcode set through Cancun: arithmetic,
    comparison/bitwise, KECCAK256, environment/block context, memory,
    storage (+ transient storage), PUSH0..PUSH32 / DUP / SWAP / LOG,
    control flow, CALL / STATICCALL / DELEGATECALL / CALLCODE,
    CREATE / CREATE2, RETURN / REVERT / SELFDESTRUCT (post-Cancun
    semantics: no account deletion, balance move only).
  * Gas: Shanghai/Cancun schedule for the implemented ops — memory
    expansion, copy costs, EIP-2929 warm/cold access, EIP-2200-shaped
    SSTORE (refund counter tracked; applied per EIP-3529 cap), 63/64
    call forwarding, CREATE deposit cost. Accurate enough for
    eth_estimateGas on ordinary transfers and contract calls.
  * Precompiles: ecrecover (0x01, pure-python secp256k1), sha256
    (0x02), identity (0x04), modexp (0x05). ripemd160 when the local
    OpenSSL provides it. NOT implemented: bn128 pairing ops
    (0x06-0x08), blake2f (0x09), point evaluation (0x0a) — calls to
    those raise UnsupportedFeatureError, which propagates uncaught
    through the CALL-family handlers and aborts the whole execution
    as a verification failure rather than a wrong answer.
  * State: partial — only proof-verified accounts are seeded; absent
    accounts read as empty (the access list is expected to cover every
    touched address, matching the reference's state manager defaults).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from .keccak import keccak256
from . import rlp

U256 = (1 << 256) - 1
SIGN_BIT = 1 << 255


class EvmError(Exception):
    """Execution failed in a way that consumes all gas (invalid op,
    stack underflow, out of gas, bad jump)."""


class UnsupportedFeatureError(Exception):
    """The bytecode needs a feature this interpreter does not
    implement (bn128 pairing, blake2f, point evaluation). Deliberately
    NOT an EvmError subclass: EvmError is a defined in-EVM outcome
    (call failure, push 0) that contracts can branch on, while this
    must abort the whole verification — it propagates uncaught through
    the CALL/STATICCALL/DELEGATECALL handlers so the provider surfaces
    a VerificationError instead of a divergent 'verified' result."""


class Revert(Exception):
    def __init__(self, data: bytes):
        super().__init__("execution reverted")
        self.data = data


@dataclass
class Account:
    nonce: int = 0
    balance: int = 0
    code: bytes = b""
    storage: dict[int, int] = field(default_factory=dict)


class EvmState:
    """Partial world state seeded from verified proofs."""

    def __init__(self):
        self.accounts: dict[bytes, Account] = {}

    def put(self, address: bytes, account: Account) -> None:
        self.accounts[bytes(address).rjust(20, b"\x00")[-20:]] = account

    def get(self, address: bytes) -> Account:
        a = bytes(address).rjust(20, b"\x00")[-20:]
        acct = self.accounts.get(a)
        if acct is None:
            acct = Account()
            self.accounts[a] = acct
        return acct

    def snapshot(self):
        return {
            a: Account(
                acct.nonce, acct.balance, acct.code, dict(acct.storage)
            )
            for a, acct in self.accounts.items()
        }

    def restore(self, snap) -> None:
        self.accounts = snap


@dataclass
class BlockContext:
    number: int = 0
    timestamp: int = 0
    coinbase: bytes = b"\x00" * 20
    gas_limit: int = 30_000_000
    base_fee: int = 0
    prevrandao: bytes = b"\x00" * 32
    chain_id: int = 1
    blob_base_fee: int = 1
    block_hashes: dict[int, bytes] = field(default_factory=dict)


@dataclass
class CallResult:
    success: bool
    output: bytes
    gas_used: int
    revert: bool = False


# -- gas schedule (Shanghai/Cancun) -----------------------------------------

G_ZERO = {0x00, 0x5B}  # STOP, JUMPDEST (JUMPDEST is 1 actually)
G_BASE = 2
G_VERYLOW = 3
G_LOW = 5
G_MID = 8
G_HIGH = 10
G_WARM = 100
G_COLD_SLOAD = 2100
G_COLD_ACCOUNT = 2600
G_KECCAK = 30
G_KECCAK_WORD = 6
G_COPY_WORD = 3
G_LOG = 375
G_LOG_DATA = 8
G_CALLVALUE = 9000
G_CALLSTIPEND = 2300
G_NEWACCOUNT = 25000
G_CREATE = 32000
G_CODEDEPOSIT = 200
G_SSET = 20000
G_SRESET = 2900
G_SELFDESTRUCT = 5000
G_TX = 21000
G_TXDATA_ZERO = 4
G_TXDATA_NONZERO = 16
G_INITCODE_WORD = 2
MAX_CALL_DEPTH = 1024
MAX_CODE_SIZE = 24576
MAX_INITCODE_SIZE = 2 * MAX_CODE_SIZE

_TIER: dict[int, int] = {}
for _op in (0x01, 0x02, 0x03, 0x10, 0x11, 0x12, 0x13, 0x14, 0x15,
            0x16, 0x17, 0x18, 0x19, 0x1A, 0x1B, 0x1C, 0x1D, 0x35,
            0x39, 0x3E, 0x51, 0x52, 0x53, 0x5E):
    _TIER[_op] = G_VERYLOW
for _op in (0x04, 0x05, 0x06, 0x07, 0x0B):
    _TIER[_op] = G_LOW
for _op in (0x08, 0x09, 0x56):
    _TIER[_op] = G_MID
_TIER[0x57] = G_HIGH
for _op in (0x30, 0x32, 0x33, 0x34, 0x36, 0x38, 0x3A, 0x3D, 0x41,
            0x42, 0x43, 0x44, 0x45, 0x46, 0x47, 0x48, 0x49, 0x4A,
            0x50, 0x58, 0x59, 0x5A):
    _TIER[_op] = G_BASE
_TIER[0x0A] = G_HIGH  # EXP: 10 + 50/exponent byte
_TIER[0x40] = 20  # BLOCKHASH
for _op in range(0x60, 0xA0):  # PUSH1..32, DUP, SWAP
    _TIER[_op] = G_VERYLOW
_TIER[0x5F] = G_BASE  # PUSH0
_TIER[0x5B] = 1  # JUMPDEST
_TIER[0x00] = 0  # STOP


def _mem_words(n: int) -> int:
    return (n + 31) // 32


def _mem_cost(words: int) -> int:
    return 3 * words + words * words // 512


def _signed(x: int) -> int:
    return x - (1 << 256) if x & SIGN_BIT else x


def _addr(x: int) -> bytes:
    return (x & ((1 << 160) - 1)).to_bytes(20, "big")


# -- precompiles -------------------------------------------------------------

_SECP_P = 2**256 - 2**32 - 977
_SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_SECP_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_SECP_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _secp_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    px, py = p
    qx, qy = q
    if px == qx:
        if (py + qy) % _SECP_P == 0:
            return None
        lam = (3 * px * px) * pow(2 * py, _SECP_P - 2, _SECP_P) % _SECP_P
    else:
        lam = (qy - py) * pow(qx - px, _SECP_P - 2, _SECP_P) % _SECP_P
    rx = (lam * lam - px - qx) % _SECP_P
    ry = (lam * (px - rx) - py) % _SECP_P
    return rx, ry


def _secp_mul(p, k):
    acc = None
    while k:
        if k & 1:
            acc = _secp_add(acc, p)
        p = _secp_add(p, p)
        k >>= 1
    return acc


def ecrecover(msg_hash: bytes, v: int, r: int, s: int) -> bytes | None:
    """Returns the 20-byte address, or None for an invalid signature."""
    if v not in (27, 28) or not (0 < r < _SECP_N) or not (0 < s < _SECP_N):
        return None
    x = r
    y_sq = (pow(x, 3, _SECP_P) + 7) % _SECP_P
    y = pow(y_sq, (_SECP_P + 1) // 4, _SECP_P)
    if y * y % _SECP_P != y_sq:
        return None
    if (y % 2) != (v - 27):
        y = _SECP_P - y
    e = int.from_bytes(msg_hash, "big")
    r_inv = pow(r, _SECP_N - 2, _SECP_N)
    # Q = r^-1 (s*R - e*G)
    point = _secp_add(
        _secp_mul((x, y), s),
        _secp_mul((_SECP_GX, _SECP_P - _SECP_GY), e % _SECP_N),
    )
    q = _secp_mul(point, r_inv)
    if q is None:
        return None
    qx, qy = q
    pub = qx.to_bytes(32, "big") + qy.to_bytes(32, "big")
    return keccak256(pub)[12:]


def _run_precompile(addr_int: int, data: bytes, gas: int):
    """-> (gas_cost, output) or raises EvmError for unsupported."""
    if addr_int == 1:
        cost = 3000
        if gas < cost:
            raise EvmError("out of gas (precompile)")
        d = data.ljust(128, b"\x00")[:128]
        h, v, r, s = d[:32], int.from_bytes(d[32:64], "big"), \
            int.from_bytes(d[64:96], "big"), int.from_bytes(d[96:128], "big")
        out = ecrecover(h, v, r, s)
        return cost, (b"" if out is None else out.rjust(32, b"\x00"))
    if addr_int == 2:
        cost = 60 + 12 * _mem_words(len(data))
        if gas < cost:
            raise EvmError("out of gas (precompile)")
        return cost, hashlib.sha256(data).digest()
    if addr_int == 3:
        cost = 600 + 120 * _mem_words(len(data))
        if gas < cost:
            raise EvmError("out of gas (precompile)")
        try:
            h = hashlib.new("ripemd160", data).digest()
        except ValueError as e:  # openssl without legacy provider
            # environment limitation, not an in-EVM outcome: must
            # abort verification, not fake a failed call
            raise UnsupportedFeatureError("ripemd160 unavailable") from e
        return cost, h.rjust(32, b"\x00")
    if addr_int == 4:
        cost = 15 + 3 * _mem_words(len(data))
        if gas < cost:
            raise EvmError("out of gas (precompile)")
        return cost, data
    if addr_int == 5:  # modexp (EIP-2565 pricing, simplified floor)
        d = data.ljust(96, b"\x00")
        bl = int.from_bytes(d[:32], "big")
        el = int.from_bytes(d[32:64], "big")
        ml = int.from_bytes(d[64:96], "big")
        if bl > 1024 or el > 1024 or ml > 1024:
            raise EvmError("modexp operand too large")
        rest = data[96:].ljust(bl + el + ml, b"\x00")
        b = int.from_bytes(rest[:bl], "big")
        e = int.from_bytes(rest[bl : bl + el], "big")
        m = int.from_bytes(rest[bl + el : bl + el + ml], "big")
        words = _mem_words(max(bl, ml))
        mult = words * words
        iters = max(1, el * 8)
        cost = max(200, mult * iters // 3)
        if gas < cost:
            raise EvmError("out of gas (precompile)")
        out = (0 if m == 0 else pow(b, e, m)).to_bytes(ml, "big") if ml else b""
        return cost, out
    raise UnsupportedFeatureError(
        f"unsupported precompile 0x{addr_int:02x}"
    )


# -- interpreter -------------------------------------------------------------


class Evm:
    def __init__(self, state: EvmState, block: BlockContext):
        self.state = state
        self.block = block
        self.warm_addresses: set[bytes] = set()
        self.warm_slots: set[tuple[bytes, int]] = set()
        self.transient: dict[tuple[bytes, int], int] = {}
        self.refund = 0
        self.original_storage: dict[tuple[bytes, int], int] = {}
        self.logs: list[tuple[bytes, list[int], bytes]] = []
        # debug: when capture_stack is set, the stack at an implicit
        # stop (running off the end of code) is kept for inspection —
        # adversarial-bytecode tests assert on values that are
        # otherwise dropped (e.g. truncated PUSH immediates)
        self.capture_stack = False
        self.last_stack: list[int] | None = None

    # -- public entry points -------------------------------------------

    def call(
        self,
        caller: bytes,
        to: bytes | None,
        data: bytes,
        value: int = 0,
        gas: int = 30_000_000,
        gas_price: int = 0,
    ) -> CallResult:
        """Message call (eth_call shape): no intrinsic tx gas."""
        self._warm_tx(caller, to)
        if to is None:
            # Deployment address derives from the pre-tx nonce;
            # _create_tx reads nonce-1, so mirror execute_tx's bump.
            self.state.get(caller).nonce += 1
            return self._create_tx(caller, data, value, gas)
        try:
            out, left = self._message(
                caller, to, to, value, data, gas, depth=0, static=False
            )
            return CallResult(True, out, gas - left)
        except Revert as r:
            return CallResult(False, r.data, gas, revert=True)
        except EvmError:
            return CallResult(False, b"", gas)

    def execute_tx(
        self,
        caller: bytes,
        to: bytes | None,
        data: bytes,
        value: int = 0,
        gas: int = 30_000_000,
    ) -> CallResult:
        """Transaction execution (eth_estimateGas shape): charges the
        21000 base + calldata intrinsic gas, applies the EIP-3529
        refund cap to gas_used."""
        intrinsic = G_TX
        for byte in data:
            intrinsic += G_TXDATA_ZERO if byte == 0 else G_TXDATA_NONZERO
        if to is None:
            intrinsic += G_CREATE + G_INITCODE_WORD * _mem_words(len(data))
        if gas < intrinsic:
            return CallResult(False, b"", gas)
        self._warm_tx(caller, to)
        sender = self.state.get(caller)
        sender.nonce += 1
        inner_gas = gas - intrinsic
        try:
            if to is None:
                res = self._create_tx(caller, data, value, inner_gas)
                used = intrinsic - G_CREATE + res.gas_used
            else:
                out, left = self._message(
                    caller, to, to, value, data, inner_gas,
                    depth=0, static=False,
                )
                used = intrinsic + (inner_gas - left)
                res = CallResult(True, out, used)
            # NOTE: EIP-3529 refunds (self.refund) are deliberately
            # NOT subtracted — refunds are credited after execution
            # and never reduce the limit a tx needs to run, so
            # estimate_gas must report the pre-refund requirement
            # (geth's estimator searches for the minimal succeeding
            # limit, which is likewise pre-refund).
            return CallResult(res.success, res.output, used,
                              revert=res.revert)
        except Revert as r:
            return CallResult(False, r.data, gas, revert=True)
        except EvmError:
            return CallResult(False, b"", gas)

    # -- internals ------------------------------------------------------

    def _warm_tx(self, caller: bytes, to: bytes | None) -> None:
        self.warm_addresses.add(bytes(caller))
        if to is not None:
            self.warm_addresses.add(bytes(to))
        self.warm_addresses.add(self.block.coinbase)
        for i in range(1, 0x0B):
            self.warm_addresses.add(i.to_bytes(20, "big"))

    def _create_tx(self, caller: bytes, init: bytes, value: int,
                   gas: int) -> CallResult:
        sender = self.state.get(caller)
        new_addr = keccak256(
            rlp.encode([caller, max(0, sender.nonce - 1)])
        )[12:]
        try:
            addr, left = self._create_at(
                caller, new_addr, init, value, gas, depth=0
            )
            return CallResult(True, addr, G_CREATE + (gas - left))
        except Revert as r:
            return CallResult(False, r.data, gas, revert=True)
        except EvmError:
            return CallResult(False, b"", gas)

    def _transfer(self, frm: bytes, to: bytes, value: int) -> None:
        if value == 0:
            return
        a, b = self.state.get(frm), self.state.get(to)
        if a.balance < value:
            raise EvmError("insufficient balance for transfer")
        a.balance -= value
        b.balance += value

    def _message(self, caller, code_addr, storage_addr, value, data,
                 gas, depth, static, code_override=None,
                 transfer=True):
        """Run code at code_addr with storage context storage_addr.
        Returns (output, gas_left). Raises Revert/EvmError."""
        if depth > MAX_CALL_DEPTH:
            raise EvmError("call depth exceeded")
        code_addr = bytes(code_addr)
        ai = int.from_bytes(code_addr, "big")
        if 0 < ai <= 0x0A:
            # Precompile addresses are special for EVERY message kind:
            # DELEGATECALL/CALLCODE to 0x01..0x0a run the precompile
            # too (their "code" is the builtin, never account code) —
            # the previous code_override guard made DELEGATECALL to a
            # precompile a silent empty success.
            cost, out = _run_precompile(ai, data, gas)
            if transfer:
                self._transfer(caller, code_addr, value)
            return out, gas - cost
        snap = self.state.snapshot()
        refund_snap = self.refund
        transient_snap = dict(self.transient)
        if transfer:
            self._transfer(caller, storage_addr, value)
        code = (code_override if code_override is not None
                else self.state.get(code_addr).code)
        if not code:
            return b"", gas
        try:
            return self._exec(
                code, caller, storage_addr, value, data, gas, depth,
                static,
            )
        except (Revert, EvmError):
            self.state.restore(snap)
            self.refund = refund_snap
            self.transient = transient_snap
            raise

    def _create_at(self, caller, new_addr, init, value, gas, depth):
        if depth > MAX_CALL_DEPTH:
            raise EvmError("call depth exceeded")
        if len(init) > MAX_INITCODE_SIZE:
            raise EvmError("initcode too large")
        existing = self.state.accounts.get(bytes(new_addr))
        if existing is not None and (existing.nonce or existing.code):
            raise EvmError("create collision")
        snap = self.state.snapshot()
        self.warm_addresses.add(bytes(new_addr))
        self._transfer(caller, new_addr, value)
        acct = self.state.get(new_addr)
        acct.nonce = 1
        try:
            out, left = self._exec(
                init, caller, new_addr, value, b"", gas, depth, False
            )
        except (Revert, EvmError):
            self.state.restore(snap)
            raise
        if len(out) > MAX_CODE_SIZE or (out and out[0] == 0xEF):
            self.state.restore(snap)
            raise EvmError("invalid deployed code")
        deposit = G_CODEDEPOSIT * len(out)
        if left < deposit:
            self.state.restore(snap)
            raise EvmError("out of gas (code deposit)")
        acct = self.state.get(new_addr)
        acct.code = out
        return bytes(new_addr), left - deposit

    # The interpreter proper. One python loop per opcode — host-side
    # code, never traced by JAX (proof verification is not a TPU
    # workload; the chain's hot paths are).
    def _exec(self, code, caller, self_addr, value, data, gas, depth,
              static):
        stack: list[int] = []
        mem = bytearray()
        pc = 0
        gas_left = gas
        ret_data = b""
        self_addr = bytes(self_addr)
        jumpdests = set()
        i = 0
        while i < len(code):
            op = code[i]
            if op == 0x5B:
                jumpdests.add(i)
            if 0x60 <= op <= 0x7F:
                i += op - 0x5F
            i += 1

        def use(n):
            nonlocal gas_left
            if gas_left < n:
                raise EvmError("out of gas")
            gas_left -= n

        def mem_extend(offset, size):
            nonlocal gas_left
            if size == 0:
                return
            if offset + size > (1 << 32):
                raise EvmError("memory offset too large")
            new_words = _mem_words(offset + size)
            old_words = _mem_words(len(mem))
            if new_words > old_words:
                use(_mem_cost(new_words) - _mem_cost(old_words))
                mem.extend(b"\x00" * (new_words * 32 - len(mem)))

        def push(x):
            if len(stack) >= 1024:
                raise EvmError("stack overflow")
            stack.append(x & U256)

        def pop():
            if not stack:
                raise EvmError("stack underflow")
            return stack.pop()

        def touch_account(a: bytes):
            nonlocal gas_left
            if a in self.warm_addresses:
                use(G_WARM)
            else:
                self.warm_addresses.add(a)
                use(G_COLD_ACCOUNT)

        while pc < len(code):
            op = code[pc]
            base = _TIER.get(op)
            if base is not None:
                use(base)

            if op == 0x00:  # STOP
                return b"", gas_left
            elif op == 0x01:
                push(pop() + pop())
            elif op == 0x02:
                push(pop() * pop())
            elif op == 0x03:
                a, b = pop(), pop()
                push(a - b)
            elif op == 0x04:
                a, b = pop(), pop()
                push(0 if b == 0 else a // b)
            elif op == 0x05:
                a, b = _signed(pop()), _signed(pop())
                if b == 0:
                    push(0)
                else:
                    q = abs(a) // abs(b)
                    push(-q if (a < 0) != (b < 0) else q)
            elif op == 0x06:
                a, b = pop(), pop()
                push(0 if b == 0 else a % b)
            elif op == 0x07:
                a, b = _signed(pop()), _signed(pop())
                if b == 0:
                    push(0)
                else:
                    r = abs(a) % abs(b)
                    push(-r if a < 0 else r)
            elif op == 0x08:
                a, b, n = pop(), pop(), pop()
                push(0 if n == 0 else (a + b) % n)
            elif op == 0x09:
                a, b, n = pop(), pop(), pop()
                push(0 if n == 0 else (a * b) % n)
            elif op == 0x0A:  # EXP
                a, e = pop(), pop()
                use(50 * ((e.bit_length() + 7) // 8))
                push(pow(a, e, 1 << 256))
            elif op == 0x0B:  # SIGNEXTEND
                k, x = pop(), pop()
                if k < 31:
                    bit = 8 * (k + 1) - 1
                    if x & (1 << bit):
                        x |= U256 ^ ((1 << (bit + 1)) - 1)
                    else:
                        x &= (1 << (bit + 1)) - 1
                push(x)
            elif op == 0x10:
                a, b = pop(), pop()
                push(1 if a < b else 0)
            elif op == 0x11:
                a, b = pop(), pop()
                push(1 if a > b else 0)
            elif op == 0x12:
                a, b = _signed(pop()), _signed(pop())
                push(1 if a < b else 0)
            elif op == 0x13:
                a, b = _signed(pop()), _signed(pop())
                push(1 if a > b else 0)
            elif op == 0x14:
                push(1 if pop() == pop() else 0)
            elif op == 0x15:
                push(1 if pop() == 0 else 0)
            elif op == 0x16:
                push(pop() & pop())
            elif op == 0x17:
                push(pop() | pop())
            elif op == 0x18:
                push(pop() ^ pop())
            elif op == 0x19:
                push(~pop())
            elif op == 0x1A:  # BYTE
                n, x = pop(), pop()
                push((x >> (8 * (31 - n))) & 0xFF if n < 32 else 0)
            elif op == 0x1B:  # SHL
                s, x = pop(), pop()
                push(0 if s >= 256 else x << s)
            elif op == 0x1C:  # SHR
                s, x = pop(), pop()
                push(0 if s >= 256 else x >> s)
            elif op == 0x1D:  # SAR
                s, x = pop(), _signed(pop())
                push((x >> s) if s < 256 else (0 if x >= 0 else U256))
            elif op == 0x20:  # KECCAK256
                off, size = pop(), pop()
                use(G_KECCAK + G_KECCAK_WORD * _mem_words(size))
                mem_extend(off, size)
                push(int.from_bytes(
                    keccak256(bytes(mem[off : off + size])), "big"))
            elif op == 0x30:
                push(int.from_bytes(self_addr, "big"))
            elif op == 0x31:  # BALANCE
                a = _addr(pop())
                touch_account(a)
                push(self.state.get(a).balance)
            elif op == 0x32:  # ORIGIN (approximated as caller)
                push(int.from_bytes(caller, "big"))
            elif op == 0x33:
                push(int.from_bytes(caller, "big"))
            elif op == 0x34:
                push(value)
            elif op == 0x35:  # CALLDATALOAD
                off = pop()
                push(int.from_bytes(
                    data[off : off + 32].ljust(32, b"\x00"), "big"))
            elif op == 0x36:
                push(len(data))
            elif op == 0x37:  # CALLDATACOPY
                dst, src, size = pop(), pop(), pop()
                use(G_VERYLOW + G_COPY_WORD * _mem_words(size))
                mem_extend(dst, size)
                mem[dst : dst + size] = data[src : src + size].ljust(
                    size, b"\x00")
            elif op == 0x38:
                push(len(code))
            elif op == 0x39:  # CODECOPY
                dst, src, size = pop(), pop(), pop()
                use(G_COPY_WORD * _mem_words(size))
                mem_extend(dst, size)
                mem[dst : dst + size] = code[src : src + size].ljust(
                    size, b"\x00")
            elif op == 0x3A:
                push(0)  # GASPRICE: eth_call runs at price 0
            elif op == 0x3B:  # EXTCODESIZE
                a = _addr(pop())
                touch_account(a)
                push(len(self.state.get(a).code))
            elif op == 0x3C:  # EXTCODECOPY
                a = _addr(pop())
                dst, src, size = pop(), pop(), pop()
                touch_account(a)
                use(G_COPY_WORD * _mem_words(size))
                mem_extend(dst, size)
                ext = self.state.get(a).code
                mem[dst : dst + size] = ext[src : src + size].ljust(
                    size, b"\x00")
            elif op == 0x3D:
                push(len(ret_data))
            elif op == 0x3E:  # RETURNDATACOPY
                dst, src, size = pop(), pop(), pop()
                if src + size > len(ret_data):
                    raise EvmError("returndatacopy out of bounds")
                use(G_COPY_WORD * _mem_words(size))
                mem_extend(dst, size)
                mem[dst : dst + size] = ret_data[src : src + size]
            elif op == 0x3F:  # EXTCODEHASH
                a = _addr(pop())
                touch_account(a)
                acct = self.state.accounts.get(a)
                if acct is None or (
                    not acct.code and not acct.balance and not acct.nonce
                ):
                    push(0)
                else:
                    push(int.from_bytes(keccak256(acct.code), "big"))
            elif op == 0x40:  # BLOCKHASH (20 charged via _TIER)
                n = pop()
                h = self.block.block_hashes.get(n, b"")
                push(int.from_bytes(h, "big") if h else 0)
            elif op == 0x41:
                push(int.from_bytes(self.block.coinbase, "big"))
            elif op == 0x42:
                push(self.block.timestamp)
            elif op == 0x43:
                push(self.block.number)
            elif op == 0x44:
                push(int.from_bytes(self.block.prevrandao, "big"))
            elif op == 0x45:
                push(self.block.gas_limit)
            elif op == 0x46:
                push(self.block.chain_id)
            elif op == 0x47:
                push(self.state.get(self_addr).balance)
            elif op == 0x48:
                push(self.block.base_fee)
            elif op == 0x49:  # BLOBHASH — no blob tx context in eth_call
                pop()
                push(0)
            elif op == 0x4A:
                push(self.block.blob_base_fee)
            elif op == 0x50:
                pop()
            elif op == 0x51:  # MLOAD
                off = pop()
                mem_extend(off, 32)
                push(int.from_bytes(mem[off : off + 32], "big"))
            elif op == 0x52:  # MSTORE
                off, val = pop(), pop()
                mem_extend(off, 32)
                mem[off : off + 32] = val.to_bytes(32, "big")
            elif op == 0x53:  # MSTORE8
                off, val = pop(), pop()
                mem_extend(off, 1)
                mem[off] = val & 0xFF
            elif op == 0x54:  # SLOAD
                slot = pop()
                key = (self_addr, slot)
                if key in self.warm_slots:
                    use(G_WARM)
                else:
                    self.warm_slots.add(key)
                    use(G_COLD_SLOAD)
                push(self.state.get(self_addr).storage.get(slot, 0))
            elif op == 0x55:  # SSTORE
                if static:
                    raise EvmError("SSTORE in static context")
                if gas_left <= G_CALLSTIPEND:
                    raise EvmError("SSTORE sentry")
                slot, val = pop(), pop()
                key = (self_addr, slot)
                storage = self.state.get(self_addr).storage
                current = storage.get(slot, 0)
                if key not in self.original_storage:
                    self.original_storage[key] = current
                original = self.original_storage[key]
                cold = 0
                if key not in self.warm_slots:
                    self.warm_slots.add(key)
                    cold = G_COLD_SLOAD
                if val == current:
                    use(G_WARM + cold)
                elif current == original:
                    use((G_SSET if original == 0 else G_SRESET) + cold)
                    if val == 0 and original != 0:
                        self.refund += 4800
                else:
                    use(G_WARM + cold)
                storage[slot] = val
            elif op == 0x56:  # JUMP
                dst = pop()
                if dst not in jumpdests:
                    raise EvmError("bad jump destination")
                pc = dst
                continue
            elif op == 0x57:  # JUMPI
                dst, cond = pop(), pop()
                if cond:
                    if dst not in jumpdests:
                        raise EvmError("bad jump destination")
                    pc = dst
                    continue
            elif op == 0x58:
                push(pc)
            elif op == 0x59:
                push(len(mem))
            elif op == 0x5A:
                push(gas_left)
            elif op == 0x5B:
                pass  # JUMPDEST
            elif op == 0x5C:  # TLOAD
                use(G_WARM)
                push(self.transient.get((self_addr, pop()), 0))
            elif op == 0x5D:  # TSTORE
                if static:
                    raise EvmError("TSTORE in static context")
                use(G_WARM)
                slot, val = pop(), pop()
                self.transient[(self_addr, slot)] = val
            elif op == 0x5E:  # MCOPY
                dst, src, size = pop(), pop(), pop()
                use(G_COPY_WORD * _mem_words(size))
                mem_extend(max(dst, src), size)
                mem[dst : dst + size] = bytes(mem[src : src + size])
            elif op == 0x5F:
                push(0)
            elif 0x60 <= op <= 0x7F:  # PUSH1..PUSH32
                n = op - 0x5F
                # immediates past the end of code zero-pad on the
                # RIGHT (yellow paper: code is implicitly zero-extended)
                push(int.from_bytes(
                    code[pc + 1 : pc + 1 + n].ljust(n, b"\x00"), "big"
                ))
                pc += n
            elif 0x80 <= op <= 0x8F:  # DUP
                n = op - 0x7F
                if len(stack) < n:
                    raise EvmError("stack underflow")
                push(stack[-n])
            elif 0x90 <= op <= 0x9F:  # SWAP
                n = op - 0x8F
                if len(stack) < n + 1:
                    raise EvmError("stack underflow")
                stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if static:
                    raise EvmError("LOG in static context")
                ntopics = op - 0xA0
                off, size = pop(), pop()
                topics = [pop() for _ in range(ntopics)]
                use(G_LOG * (1 + ntopics) + G_LOG_DATA * size)
                mem_extend(off, size)
                self.logs.append(
                    (self_addr, topics, bytes(mem[off : off + size])))
            elif op == 0xF0 or op == 0xF5:  # CREATE / CREATE2
                if static:
                    raise EvmError("CREATE in static context")
                val = pop()
                off, size = pop(), pop()
                salt = pop() if op == 0xF5 else None
                use(G_CREATE + G_INITCODE_WORD * _mem_words(size))
                if op == 0xF5:
                    use(G_KECCAK_WORD * _mem_words(size))
                mem_extend(off, size)
                init = bytes(mem[off : off + size])
                acct = self.state.get(self_addr)
                if salt is None:
                    new_addr = keccak256(
                        rlp.encode([self_addr, acct.nonce]))[12:]
                else:
                    new_addr = keccak256(
                        b"\xff" + self_addr
                        + salt.to_bytes(32, "big") + keccak256(init))[12:]
                acct.nonce += 1
                child_gas = gas_left - gas_left // 64
                try:
                    addr_out, left = self._create_at(
                        caller=self_addr, new_addr=new_addr, init=init,
                        value=val, gas=child_gas, depth=depth + 1)
                    gas_left -= child_gas - left
                    ret_data = b""
                    push(int.from_bytes(addr_out, "big"))
                except Revert as r:
                    gas_left -= child_gas
                    ret_data = r.data
                    push(0)
                except EvmError:
                    gas_left -= child_gas
                    ret_data = b""
                    push(0)
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):
                # CALL / CALLCODE / DELEGATECALL / STATICCALL
                gas_req = pop()
                target = _addr(pop())
                val = pop() if op in (0xF1, 0xF2) else 0
                in_off, in_size = pop(), pop()
                out_off, out_size = pop(), pop()
                if static and val and op == 0xF1:
                    raise EvmError("value CALL in static context")
                touch_account(target)
                extra = 0
                if val:
                    extra += G_CALLVALUE
                    if op == 0xF1 and target not in self.state.accounts:
                        extra += G_NEWACCOUNT
                use(extra)
                mem_extend(in_off, in_size)
                mem_extend(out_off, out_size)
                avail = gas_left - gas_left // 64
                child_gas = min(gas_req, avail)
                stipend = G_CALLSTIPEND if val else 0
                args = bytes(mem[in_off : in_off + in_size])
                try:
                    if op == 0xF1:  # CALL
                        out, left = self._message(
                            self_addr, target, target, val, args,
                            child_gas + stipend, depth + 1,
                            static)
                    elif op == 0xF2:  # CALLCODE
                        out, left = self._message(
                            self_addr, target, self_addr, val, args,
                            child_gas + stipend, depth + 1, static)
                    elif op == 0xF4:  # DELEGATECALL
                        out, left = self._message(
                            caller, target, self_addr, value, args,
                            child_gas, depth + 1, static,
                            code_override=self.state.get(target).code,
                            transfer=False)
                    else:  # STATICCALL
                        out, left = self._message(
                            self_addr, target, target, 0, args,
                            child_gas, depth + 1, True)
                    # Caller fronts child_gas; the child's full
                    # remainder (incl. unused stipend) returns to it.
                    gas_left -= child_gas - left
                    ret_data = out
                    n = min(out_size, len(out))
                    mem[out_off : out_off + n] = out[:n]
                    push(1)
                except Revert as r:
                    # Conservative: a real EVM refunds the reverting
                    # child's remaining gas; Revert doesn't carry it,
                    # so estimates involving reverting inner calls
                    # over-estimate (never under).
                    gas_left -= child_gas
                    ret_data = r.data
                    n = min(out_size, len(r.data))
                    mem[out_off : out_off + n] = r.data[:n]
                    push(0)
                except EvmError:
                    # Stipend gas was granted on top of the caller's
                    # balance; the caller loses only child_gas.
                    gas_left -= child_gas
                    ret_data = b""
                    push(0)
            elif op == 0xF3:  # RETURN
                off, size = pop(), pop()
                mem_extend(off, size)
                return bytes(mem[off : off + size]), gas_left
            elif op == 0xFD:  # REVERT
                off, size = pop(), pop()
                mem_extend(off, size)
                raise Revert(bytes(mem[off : off + size]))
            elif op == 0xFF:  # SELFDESTRUCT (EIP-6780: balance move)
                if static:
                    raise EvmError("SELFDESTRUCT in static context")
                use(G_SELFDESTRUCT)
                beneficiary = _addr(pop())
                touch_account(beneficiary)
                acct = self.state.get(self_addr)
                self.state.get(beneficiary).balance += acct.balance
                acct.balance = 0
                return b"", gas_left
            elif op == 0xFE:  # INVALID
                raise EvmError("invalid opcode")
            else:
                raise EvmError(f"unimplemented opcode 0x{op:02x}")
            pc += 1
        if self.capture_stack:
            self.last_stack = list(stack)
        return b"", gas_left

"""Prover: verified execution-layer access through beacon light-client
roots.

Reference analog: packages/prover — `createVerifiedExecutionProvider`
(web3_provider.ts) wraps an eth JSON-RPC endpoint and verifies the
responses (balances, nonces, code, storage) against execution state
roots obtained from light-client-verified beacon headers, via
eth_getProof merkle-patricia proofs (verified_requests/).
"""

from .mpt import verify_account_proof, verify_storage_proof
from .provider import ProofProvider, VerifiedExecutionProvider

__all__ = [
    "ProofProvider",
    "VerifiedExecutionProvider",
    "verify_account_proof",
    "verify_storage_proof",
]

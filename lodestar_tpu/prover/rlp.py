"""RLP encoding/decoding (execution-layer serialization).

Needed by the merkle-patricia proof verifier: account leaves, trie
nodes, and storage values are all RLP.
"""

from __future__ import annotations


class RlpError(ValueError):
    pass


def encode(item) -> bytes:
    """item: bytes | int | list (nested)."""
    if isinstance(item, int):
        if item == 0:
            payload = b""
        else:
            payload = item.to_bytes((item.bit_length() + 7) // 8, "big")
        return encode(payload)
    if isinstance(item, (bytes, bytearray)):
        b = bytes(item)
        if len(b) == 1 and b[0] < 0x80:
            return b
        return _len_prefix(len(b), 0x80) + b
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _len_prefix(len(payload), 0xC0) + payload
    raise RlpError(f"cannot RLP-encode {type(item)}")


def _len_prefix(n: int, offset: int) -> bytes:
    if n < 56:
        return bytes([offset + n])
    nb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(nb)]) + nb


def decode(data: bytes):
    item, rest = _decode_one(bytes(data))
    if rest:
        raise RlpError("trailing bytes after RLP item")
    return item


def _decode_one(data: bytes):
    if not data:
        raise RlpError("empty input")
    b0 = data[0]
    if b0 < 0x80:
        return data[:1], data[1:]
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        _check(data, 1 + n)
        if n == 1 and data[1] < 0x80:
            raise RlpError("non-canonical single byte")
        return data[1 : 1 + n], data[1 + n :]
    if b0 < 0xC0:  # long string
        ln = b0 - 0xB7
        _check(data, 1 + ln)
        n = int.from_bytes(data[1 : 1 + ln], "big")
        if n < 56 or data[1] == 0:
            raise RlpError("non-canonical length")
        _check(data, 1 + ln + n)
        return data[1 + ln : 1 + ln + n], data[1 + ln + n :]
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        _check(data, 1 + n)
        return _decode_list(data[1 : 1 + n]), data[1 + n :]
    ln = b0 - 0xF7
    _check(data, 1 + ln)
    n = int.from_bytes(data[1 : 1 + ln], "big")
    if n < 56 or data[1] == 0:
        raise RlpError("non-canonical length")
    _check(data, 1 + ln + n)
    return _decode_list(data[1 + ln : 1 + ln + n]), data[1 + ln + n :]


def _decode_list(payload: bytes) -> list:
    out = []
    while payload:
        item, payload = _decode_one(payload)
        out.append(item)
    return out


def _check(data: bytes, n: int) -> None:
    if len(data) < n:
        raise RlpError("truncated RLP")

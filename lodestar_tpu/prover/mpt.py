"""Merkle-Patricia trie proof verification (eth_getProof).

Reference analog: the account/storage verification inside
packages/prover's verified_requests/ (eth_getBalance etc. are answered
only after the returned proof checks out against the execution state
root taken from a light-client-verified header).

A proof is the list of RLP-encoded trie nodes from the root to the
key's leaf (or to the divergence showing exclusion). Node types:
branch (17 items), extension/leaf (2 items, hex-prefix encoded path).
"""

from __future__ import annotations

from . import rlp
from .keccak import keccak256


class ProofError(ValueError):
    pass


def _nibbles(b: bytes) -> list[int]:
    out = []
    for byte in b:
        out.append(byte >> 4)
        out.append(byte & 0x0F)
    return out


def _decode_path(hp: bytes) -> tuple[list[int], bool]:
    """Hex-prefix decode -> (nibbles, is_leaf)."""
    ns = _nibbles(hp)
    flag = ns[0]
    is_leaf = flag >= 2
    odd = flag % 2 == 1
    return (ns[1:] if odd else ns[2:]), is_leaf


def verify_proof(
    root: bytes, key: bytes, proof: list[bytes]
) -> bytes | None:
    """Verify an MPT proof; returns the RLP value at `key`, or None if
    the proof shows exclusion. Raises ProofError on any inconsistency."""
    if not proof:
        raise ProofError("empty proof")
    path = _nibbles(keccak256(key))
    expected = root
    i = 0
    node_idx = 0
    while True:
        if node_idx >= len(proof):
            raise ProofError("proof exhausted before terminal node")
        raw = proof[node_idx]
        node_idx += 1
        if keccak256(raw) != expected:
            raise ProofError("node hash mismatch")
        node = rlp.decode(raw)
        if not isinstance(node, list):
            raise ProofError("node is not a list")
        if len(node) == 17:  # branch
            if i == len(path):
                v = node[16]
                return bytes(v) if v else None
            nxt = node[path[i]]
            i += 1
            if nxt == b"":
                return None  # exclusion: empty slot
            if isinstance(nxt, list):
                # embedded (<32B) node appears inline in its parent
                return _walk_inline(nxt, path, i)
            expected = bytes(nxt)
            continue
        if len(node) == 2:  # extension or leaf
            nibs, is_leaf = _decode_path(bytes(node[0]))
            if is_leaf:
                if path[i:] == nibs:
                    return bytes(node[1])
                return None  # different leaf proves exclusion
            if path[i : i + len(nibs)] != nibs:
                return None  # divergent extension: exclusion
            i += len(nibs)
            nxt = node[1]
            if isinstance(nxt, list):
                return _walk_inline(nxt, path, i)
            expected = bytes(nxt)
            continue
        raise ProofError(f"bad node arity {len(node)}")


def _relist(x):
    if isinstance(x, list):
        return [_relist(v) for v in x]
    return bytes(x)


def _walk_inline(node, path, i):
    """Embedded nodes (RLP < 32 bytes) appear inline in their parent."""
    while True:
        if len(node) == 17:
            if i == len(path):
                return bytes(node[16]) or None
            nxt = node[path[i]]
            i += 1
            if nxt == b"":
                return None
            if isinstance(nxt, list):
                node = nxt
                continue
            raise ProofError("inline node references hash")
        if len(node) == 2:
            nibs, is_leaf = _decode_path(bytes(node[0]))
            if is_leaf:
                return bytes(node[1]) if path[i:] == nibs else None
            if path[i : i + len(nibs)] != nibs:
                return None
            i += len(nibs)
            nxt = node[1]
            if isinstance(nxt, list):
                node = nxt
                continue
            raise ProofError("inline node references hash")
        raise ProofError("bad inline node")


EMPTY_CODE_HASH = keccak256(b"")
EMPTY_TRIE_ROOT = keccak256(rlp.encode(b""))


def verify_account_proof(
    state_root: bytes, address: bytes, account_proof: list[bytes]
) -> dict:
    """Verify an eth_getProof accountProof; returns the account fields
    {nonce, balance, storage_root, code_hash} (zeroed when excluded)."""
    value = verify_proof(state_root, address, account_proof)
    if value is None:
        return {
            "nonce": 0,
            "balance": 0,
            "storage_root": EMPTY_TRIE_ROOT,
            "code_hash": EMPTY_CODE_HASH,
        }
    fields = rlp.decode(value)
    if not isinstance(fields, list) or len(fields) != 4:
        raise ProofError("bad account leaf")
    return {
        "nonce": int.from_bytes(fields[0], "big"),
        "balance": int.from_bytes(fields[1], "big"),
        "storage_root": bytes(fields[2]),
        "code_hash": bytes(fields[3]),
    }


def verify_storage_proof(
    storage_root: bytes, slot: bytes, proof: list[bytes]
) -> int:
    """Verify one eth_getProof storageProof entry; returns the slot
    value (0 when excluded). The slot is left-padded to the 32 bytes
    the trie actually keys on (short keys would silently 'prove' 0)."""
    slot = bytes(slot).rjust(32, b"\x00")
    if len(slot) != 32:
        raise ProofError("storage slot longer than 32 bytes")
    value = verify_proof(storage_root, slot, proof)
    if value is None:
        return 0
    return int.from_bytes(rlp.decode(value), "big")

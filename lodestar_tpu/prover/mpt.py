"""Merkle-Patricia trie proof verification (eth_getProof).

Reference analog: the account/storage verification inside
packages/prover's verified_requests/ (eth_getBalance etc. are answered
only after the returned proof checks out against the execution state
root taken from a light-client-verified header).

A proof is the list of RLP-encoded trie nodes from the root to the
key's leaf (or to the divergence showing exclusion). Node types:
branch (17 items), extension/leaf (2 items, hex-prefix encoded path).
"""

from __future__ import annotations

from . import rlp
from .keccak import keccak256


class ProofError(ValueError):
    pass


def _nibbles(b: bytes) -> list[int]:
    out = []
    for byte in b:
        out.append(byte >> 4)
        out.append(byte & 0x0F)
    return out


def _decode_path(hp: bytes) -> tuple[list[int], bool]:
    """Hex-prefix decode -> (nibbles, is_leaf)."""
    ns = _nibbles(hp)
    flag = ns[0]
    is_leaf = flag >= 2
    odd = flag % 2 == 1
    return (ns[1:] if odd else ns[2:]), is_leaf


def verify_proof(
    root: bytes, key: bytes, proof: list[bytes]
) -> bytes | None:
    """Verify an MPT proof; returns the RLP value at `key`, or None if
    the proof shows exclusion. Raises ProofError on any inconsistency."""
    if not proof:
        raise ProofError("empty proof")
    path = _nibbles(keccak256(key))
    expected = root
    i = 0
    node_idx = 0
    while True:
        if node_idx >= len(proof):
            raise ProofError("proof exhausted before terminal node")
        raw = proof[node_idx]
        node_idx += 1
        if keccak256(raw) != expected:
            raise ProofError("node hash mismatch")
        node = rlp.decode(raw)
        if not isinstance(node, list):
            raise ProofError("node is not a list")
        if len(node) == 17:  # branch
            if i == len(path):
                v = node[16]
                return bytes(v) if v else None
            nxt = node[path[i]]
            i += 1
            if nxt == b"":
                return None  # exclusion: empty slot
            if isinstance(nxt, list):
                # embedded (<32B) node appears inline in its parent
                return _walk_inline(nxt, path, i)
            expected = bytes(nxt)
            continue
        if len(node) == 2:  # extension or leaf
            nibs, is_leaf = _decode_path(bytes(node[0]))
            if is_leaf:
                if path[i:] == nibs:
                    return bytes(node[1])
                return None  # different leaf proves exclusion
            if path[i : i + len(nibs)] != nibs:
                return None  # divergent extension: exclusion
            i += len(nibs)
            nxt = node[1]
            if isinstance(nxt, list):
                return _walk_inline(nxt, path, i)
            expected = bytes(nxt)
            continue
        raise ProofError(f"bad node arity {len(node)}")


def _relist(x):
    if isinstance(x, list):
        return [_relist(v) for v in x]
    return bytes(x)


def _walk_inline(node, path, i):
    """Embedded nodes (RLP < 32 bytes) appear inline in their parent."""
    while True:
        if len(node) == 17:
            if i == len(path):
                return bytes(node[16]) or None
            nxt = node[path[i]]
            i += 1
            if nxt == b"":
                return None
            if isinstance(nxt, list):
                node = nxt
                continue
            raise ProofError("inline node references hash")
        if len(node) == 2:
            nibs, is_leaf = _decode_path(bytes(node[0]))
            if is_leaf:
                return bytes(node[1]) if path[i:] == nibs else None
            if path[i : i + len(nibs)] != nibs:
                return None
            i += len(nibs)
            nxt = node[1]
            if isinstance(nxt, list):
                node = nxt
                continue
            raise ProofError("inline node references hash")
        raise ProofError("bad inline node")


EMPTY_CODE_HASH = keccak256(b"")
EMPTY_TRIE_ROOT = keccak256(rlp.encode(b""))


def verify_account_proof(
    state_root: bytes, address: bytes, account_proof: list[bytes]
) -> dict:
    """Verify an eth_getProof accountProof; returns the account fields
    {nonce, balance, storage_root, code_hash} (zeroed when excluded)."""
    value = verify_proof(state_root, address, account_proof)
    if value is None:
        return {
            "nonce": 0,
            "balance": 0,
            "storage_root": EMPTY_TRIE_ROOT,
            "code_hash": EMPTY_CODE_HASH,
        }
    fields = rlp.decode(value)
    if not isinstance(fields, list) or len(fields) != 4:
        raise ProofError("bad account leaf")
    return {
        "nonce": int.from_bytes(fields[0], "big"),
        "balance": int.from_bytes(fields[1], "big"),
        "storage_root": bytes(fields[2]),
        "code_hash": bytes(fields[3]),
    }


def verify_storage_proof(
    storage_root: bytes, slot: bytes, proof: list[bytes]
) -> int:
    """Verify one eth_getProof storageProof entry; returns the slot
    value (0 when excluded). The slot is left-padded to the 32 bytes
    the trie actually keys on (short keys would silently 'prove' 0)."""
    slot = bytes(slot).rjust(32, b"\x00")
    if len(slot) != 32:
        raise ProofError("storage slot longer than 32 bytes")
    value = verify_proof(storage_root, slot, proof)
    if value is None:
        return 0
    return int.from_bytes(rlp.decode(value), "big")


# ---------------------------------------------------------------------------
# Trie construction (root computation from a key->value mapping).
#
# Needed to authenticate the transactions / withdrawals lists of an RPC
# block against the transactionsRoot / withdrawalsRoot fields of an
# LC-verified header (reference: isValidBlock's validateTransactionsTrie,
# prover/src/utils/validation.ts:96). Unlike the account/storage tries,
# these index tries key on rlp(index) with NO keccak pre-hash.


def _hexprefix(nibs: list[int], leaf: bool) -> bytes:
    flag = 2 if leaf else 0
    if len(nibs) % 2 == 1:
        packed = [((flag + 1) << 4) | nibs[0]]
        rest = nibs[1:]
    else:
        packed = [flag << 4]
        rest = nibs
    for i in range(0, len(rest), 2):
        packed.append((rest[i] << 4) | rest[i + 1])
    return bytes(packed)


def _node_ref(node) -> bytes:
    """Collapse a structural node to its reference: inline if the RLP
    is <32 bytes, else its keccak hash (yellow-paper c())."""
    raw = rlp.encode(node)
    return node if len(raw) < 32 else keccak256(raw)


def _build_node(items: list[tuple[list[int], bytes]], depth: int):
    """items: (remaining-nibble-path, value) pairs, paths distinct."""
    if not items:
        return b""
    if len(items) == 1:
        nibs, value = items[0]
        return [_hexprefix(list(nibs), True), value]
    # Longest common prefix across all paths at this depth.
    first = items[0][0]
    lcp = 0
    while all(
        len(p) > lcp and p[lcp] == first[lcp] for p, _ in items
    ):
        lcp += 1
    if lcp > 0:
        child = _build_node([(p[lcp:], v) for p, v in items], depth + lcp)
        return [_hexprefix(list(first[:lcp]), False), _node_ref(child)]
    branch: list = [b""] * 17
    buckets: dict[int, list[tuple[list[int], bytes]]] = {}
    for p, v in items:
        if not p:
            branch[16] = v
        else:
            buckets.setdefault(p[0], []).append((p[1:], v))
    for nib, group in buckets.items():
        branch[nib] = _node_ref(_build_node(group, depth + 1))
    return branch


def trie_root(items: list[tuple[bytes, bytes]]) -> bytes:
    """Root of the MPT holding {key: value}. Keys are used as-is
    (callers hash or rlp-index them per the trie's keying rule)."""
    if not items:
        return keccak256(rlp.encode(b""))
    pairs = [(_nibbles(k), v) for k, v in items]
    root_node = _build_node(pairs, 0)
    return keccak256(rlp.encode(root_node))


def ordered_trie_root(values: list[bytes]) -> bytes:
    """Root of an index trie (transactions/withdrawals/receipts):
    key i -> rlp(i), values stored raw (already-encoded payloads)."""
    return trie_root([(rlp.encode(i), v) for i, v in enumerate(values)])

"""Verified execution provider.

Reference analog: createVerifiedExecutionProvider (prover/src/
web3_provider.ts) + ProofProvider/PayloadStore (proof_provider/):
execution responses are only returned after verifying an eth_getProof
against the execution state root of a light-client-verified beacon
header. The ProofProvider tracks those verified roots (fed by the
light client's finality/optimistic updates).
"""

from __future__ import annotations

from .blocks import BlockVerificationError, verify_block
from .evm import (
    Account,
    BlockContext,
    Evm,
    EvmState,
    UnsupportedFeatureError,
)
from .keccak import keccak256
from .mpt import ProofError, verify_account_proof, verify_storage_proof


class VerificationError(Exception):
    pass


class ProofProvider:
    """Verified execution (block_hash -> state_root) anchors, fed from
    light-client updates (proof_provider/payload_store.ts)."""

    def __init__(self):
        # block_hash -> (state_root, block_number)
        self._roots: dict[bytes, tuple[bytes, int | None]] = {}
        # block_hash -> full verified payload header fields (dict with
        # parent_hash/number/timestamp/gas_limit/base_fee/prevrandao/
        # fee_recipient when fed from on_verified_payload)
        self._payloads: dict[bytes, dict] = {}
        self._by_number: dict[int, bytes] = {}
        self.latest_block_hash: bytes | None = None

    def on_verified_header(
        self,
        block_hash: bytes,
        state_root: bytes,
        block_number: int | None = None,
    ) -> None:
        self._roots[bytes(block_hash)] = (
            bytes(state_root),
            block_number,
        )
        if block_number is not None:
            self._by_number[block_number] = bytes(block_hash)
        self.latest_block_hash = bytes(block_hash)

    def on_verified_payload(self, payload: dict) -> None:
        """Record a full LC-verified execution payload header (the
        reference's PayloadStore.processLCHeader). `payload` carries
        block_hash/state_root plus whatever block-context fields the
        header exposes (number, timestamp, gas_limit, base_fee,
        prevrandao, fee_recipient)."""
        bh = bytes(payload["block_hash"])
        self._payloads[bh] = dict(payload)
        self.on_verified_header(
            bh, bytes(payload["state_root"]), payload.get("number")
        )

    def resolve(self, block=None) -> bytes:
        """block: None/'latest' -> newest verified anchor; int or hex
        quantity -> verified hash at that number; bytes/0x-hash -> the
        hash itself (must be verified)."""
        # 'finalized'/'safe'/'pending' collapse to the newest
        # LC-verified anchor: the ProofProvider is fed from verified
        # finality/optimistic updates, so "latest verified" is the
        # strongest statement this provider can make for any of them
        if block is None or block in (
            "latest", "finalized", "safe", "pending"
        ):
            if self.latest_block_hash is None:
                raise VerificationError("no verified execution header")
            return self.latest_block_hash
        if isinstance(block, str):
            if len(block) == 66 and block.startswith("0x"):
                block = bytes.fromhex(block[2:])
            else:
                try:
                    block = int(block, 16)
                except ValueError as e:
                    raise VerificationError(
                        f"unsupported block tag {block!r}"
                    ) from e
        if isinstance(block, int):
            bh = self._by_number.get(block)
            if bh is None:
                raise VerificationError(
                    f"no verified header at height {block}")
            return bh
        bh = bytes(block)
        if bh not in self._roots:
            raise VerificationError("block hash not LC-verified")
        return bh

    def payload(self, block=None) -> dict:
        bh = self.resolve(block)
        info = self._payloads.get(bh)
        if info is None:
            state_root, number = self._roots[bh]
            info = {
                "block_hash": bh,
                "state_root": state_root,
                "number": number,
            }
        return info

    def anchor(self, block_hash: bytes | None = None):
        """(state_root, rpc block tag) of a verified header. Proof
        queries must pin THIS block — 'latest' on the RPC side races
        ahead of light-client verification and every proof would
        mismatch."""
        bh = block_hash or self.latest_block_hash
        if bh is None or bh not in self._roots:
            raise VerificationError("no verified execution header")
        state_root, number = self._roots[bh]
        tag = hex(number) if number is not None else "0x" + bh.hex()
        return state_root, tag

    def state_root(self, block_hash: bytes | None = None) -> bytes:
        return self.anchor(block_hash)[0]


class VerifiedExecutionProvider:
    """eth_* facade that proves every answer (web3_provider.ts).

    rpc: object with async call(method, params) (e.g.
    execution.http.JsonRpcHttpClient)."""

    def __init__(self, rpc, proof_provider: ProofProvider):
        self.rpc = rpc
        self.proofs = proof_provider

    async def _account(self, address: bytes, slots=()):
        state_root, block_tag = self.proofs.anchor()
        out = await self.rpc.call(
            "eth_getProof",
            [
                "0x" + address.hex(),
                [
                    "0x" + bytes(s).rjust(32, b"\x00").hex()
                    for s in slots
                ],
                block_tag,
            ],
        )
        proof = [
            bytes.fromhex(n.removeprefix("0x"))
            for n in out["accountProof"]
        ]
        try:
            account = verify_account_proof(state_root, address, proof)
        except ProofError as e:
            raise VerificationError(f"account proof invalid: {e}") from e
        return account, out

    async def get_balance(self, address: bytes) -> int:
        account, _ = await self._account(address)
        return account["balance"]

    async def get_transaction_count(self, address: bytes) -> int:
        account, _ = await self._account(address)
        return account["nonce"]

    async def get_code(self, address: bytes) -> bytes:
        account, _ = await self._account(address)
        _, block_tag = self.proofs.anchor()
        code_hex = await self.rpc.call(
            "eth_getCode", ["0x" + address.hex(), block_tag]
        )
        code = bytes.fromhex(code_hex.removeprefix("0x"))
        if keccak256(code) != account["code_hash"]:
            raise VerificationError("code hash mismatch")
        return code

    async def get_storage_at(self, address: bytes, slot: bytes) -> int:
        account, out = await self._account(address, slots=[slot])
        entry = out["storageProof"][0]
        proof = [
            bytes.fromhex(n.removeprefix("0x")) for n in entry["proof"]
        ]
        try:
            return verify_storage_proof(
                account["storage_root"], bytes(slot), proof
            )
        except ProofError as e:
            raise VerificationError(f"storage proof invalid: {e}") from e

    # -- verified blocks (verified_requests/eth_getBlockByHash.ts,
    #    eth_getBlockByNumber.ts) --------------------------------------

    async def get_block_by_hash(self, block_hash) -> dict:
        """Hydrated block, authenticated field-by-field: the header
        must hash to the LC-verified block hash and the transaction /
        withdrawal tries must recompute."""
        bh = self.proofs.resolve(
            block_hash if not isinstance(block_hash, str)
            else bytes.fromhex(block_hash.removeprefix("0x"))
        )
        block = await self.rpc.call(
            "eth_getBlockByHash", ["0x" + bh.hex(), True]
        )
        if block is None:
            raise VerificationError("block not found on RPC")
        try:
            verify_block(block, bh)
        except BlockVerificationError as e:
            raise VerificationError(f"block invalid: {e}") from e
        return block

    async def get_block_by_number(self, number) -> dict:
        bh = self.proofs.resolve(number)
        return await self.get_block_by_hash(bh)

    # -- verified local execution (verified_requests/eth_call.ts,
    #    eth_estimateGas.ts; utils/evm.ts) -----------------------------

    async def _seed_evm(self, tx: dict, block=None):
        """Build an EVM whose entire state is proof-verified: ask the
        RPC which accounts/slots the call touches (eth_createAccessList),
        then verify each against the LC-verified state root."""
        info = self.proofs.payload(block)
        state_root = info["state_root"]
        tag = (hex(info["number"]) if info.get("number") is not None
               else "0x" + info["block_hash"].hex())

        def addr_bytes(x) -> bytes:
            return bytes.fromhex(x.removeprefix("0x")) if isinstance(
                x, str) else bytes(x)

        frm = tx.get("from") or "0x" + "00" * 20
        access: dict[str, list[str]] = {}
        access_list_ok = False
        acc_tx = {k: v for k, v in tx.items() if v is not None}
        acc_tx.setdefault("from", frm)
        try:
            resp = await self.rpc.call(
                "eth_createAccessList", [acc_tx, tag]
            )
            for entry in resp.get("accessList", []):
                access[entry["address"].lower()] = list(
                    entry.get("storageKeys", []))
            access_list_ok = True
        except VerificationError:
            raise
        except Exception:
            # RPC without createAccessList: proceed with only the
            # from/to accounts, but FAIL CLOSED below if the target
            # turns out to hold code — a contract call without a
            # storage access list would read unproven slots as zero
            # and launder a wrong answer as verified.
            pass
        if not access_list_ok and tx.get("to") is None:
            # Contract creation runs arbitrary init code from calldata
            # against state we cannot enumerate without an access list
            # — every external read would silently see zeros. The
            # code-bearing guard below never fires for to=None, so
            # fail closed here (reference getVMWithState throws on an
            # unusable createAccessList response).
            raise VerificationError(
                "RPC lacks eth_createAccessList; state coverage for a "
                "contract-creation tx cannot be verified"
            )
        access.setdefault(frm.lower(), [])
        if tx.get("to"):
            access.setdefault(tx["to"].lower(), [])

        state = EvmState()
        for addr_hex, keys in access.items():
            address = addr_bytes(addr_hex)
            out = await self.rpc.call(
                "eth_getProof", [addr_hex, keys, tag]
            )
            proof = [bytes.fromhex(n.removeprefix("0x"))
                     for n in out["accountProof"]]
            try:
                account = verify_account_proof(
                    state_root, address, proof)
            except ProofError as e:
                raise VerificationError(
                    f"account proof invalid for {addr_hex}: {e}"
                ) from e
            code = b""
            if account["code_hash"] != keccak256(b""):
                code_hex = await self.rpc.call(
                    "eth_getCode", [addr_hex, tag])
                code = bytes.fromhex(code_hex.removeprefix("0x"))
                if keccak256(code) != account["code_hash"]:
                    raise VerificationError(
                        f"code hash mismatch for {addr_hex}")
            storage: dict[int, int] = {}
            for i, entry in enumerate(out.get("storageProof", [])):
                sproof = [bytes.fromhex(n.removeprefix("0x"))
                          for n in entry["proof"]]
                slot = bytes.fromhex(
                    entry["key"].removeprefix("0x")).rjust(32, b"\x00")
                try:
                    val = verify_storage_proof(
                        account["storage_root"], slot, sproof)
                except ProofError as e:
                    raise VerificationError(
                        f"storage proof invalid for {addr_hex}: {e}"
                    ) from e
                storage[int.from_bytes(slot, "big")] = val
            # Every requested slot must come back with a proof — an
            # RPC that silently drops entries would otherwise make the
            # EVM read zeros and launder a wrong 'verified' answer.
            for key in keys:
                slot_int = int(key, 16) if isinstance(key, str) \
                    else int.from_bytes(bytes(key), "big")
                if slot_int not in storage:
                    raise VerificationError(
                        f"storage proof missing for {addr_hex} slot "
                        f"{key}")
            if code and not access_list_ok:
                raise VerificationError(
                    "RPC lacks eth_createAccessList; storage coverage "
                    "for a contract call cannot be verified"
                )
            state.put(address, Account(
                nonce=account["nonce"], balance=account["balance"],
                code=code, storage=storage))

        ctx = BlockContext(
            number=info.get("number") or 0,
            timestamp=info.get("timestamp") or 0,
            coinbase=bytes(info.get("fee_recipient") or b"\x00" * 20),
            gas_limit=info.get("gas_limit") or 30_000_000,
            base_fee=info.get("base_fee") or 0,
            prevrandao=bytes(info.get("prevrandao") or b"\x00" * 32),
            chain_id=info.get("chain_id") or 1,
        )
        evm = Evm(state, ctx)
        to = addr_bytes(tx["to"]) if tx.get("to") else None
        gas = (int(tx["gas"], 16) if isinstance(tx.get("gas"), str)
               else tx.get("gas")) or ctx.gas_limit
        val = (int(tx["value"], 16)
               if isinstance(tx.get("value"), str)
               else tx.get("value")) or 0
        data_hex = tx.get("input") or tx.get("data") or "0x"
        data = bytes.fromhex(data_hex.removeprefix("0x")) if isinstance(
            data_hex, str) else bytes(data_hex)
        return evm, addr_bytes(frm), to, data, val, gas

    async def call(self, tx: dict, block=None) -> bytes:
        """Proof-backed eth_call: execute locally, with every account,
        slot, and code byte the RPC contributed checked against the
        LC-verified state root. Trust model caveat: state COMPLETENESS
        rests on the RPC's eth_createAccessList answer — an omitted
        account/slot reads as empty locally (the reference shares this
        assumption). Touching an unimplemented feature aborts with
        VerificationError rather than returning a divergent result."""
        evm, frm, to, data, val, gas = await self._seed_evm(tx, block)
        try:
            res = evm.call(frm, to, data, value=val, gas=gas)
        except UnsupportedFeatureError as e:
            raise VerificationError(
                f"unverifiable execution: {e}"
            ) from e
        if not res.success:
            raise VerificationError(
                "execution reverted" if res.revert
                else "execution failed")
        return res.output

    async def estimate_gas(self, tx: dict, block=None) -> int:
        """Proof-backed eth_estimateGas: run the transaction locally
        with full gas metering (21000 base + calldata + execution,
        EIP-3529 refund cap). Same access-list completeness assumption
        and unsupported-feature behavior as `call`."""
        evm, frm, to, data, val, gas = await self._seed_evm(tx, block)
        try:
            res = evm.execute_tx(frm, to, data, value=val, gas=gas)
        except UnsupportedFeatureError as e:
            raise VerificationError(
                f"unverifiable execution: {e}"
            ) from e
        if not res.success:
            raise VerificationError(
                "execution reverted" if res.revert
                else "execution failed")
        return res.gas_used

"""Verified execution provider.

Reference analog: createVerifiedExecutionProvider (prover/src/
web3_provider.ts) + ProofProvider/PayloadStore (proof_provider/):
execution responses are only returned after verifying an eth_getProof
against the execution state root of a light-client-verified beacon
header. The ProofProvider tracks those verified roots (fed by the
light client's finality/optimistic updates).
"""

from __future__ import annotations

from .keccak import keccak256
from .mpt import ProofError, verify_account_proof, verify_storage_proof


class VerificationError(Exception):
    pass


class ProofProvider:
    """Verified execution (block_hash -> state_root) anchors, fed from
    light-client updates (proof_provider/payload_store.ts)."""

    def __init__(self):
        # block_hash -> (state_root, block_number)
        self._roots: dict[bytes, tuple[bytes, int | None]] = {}
        self.latest_block_hash: bytes | None = None

    def on_verified_header(
        self,
        block_hash: bytes,
        state_root: bytes,
        block_number: int | None = None,
    ) -> None:
        self._roots[bytes(block_hash)] = (
            bytes(state_root),
            block_number,
        )
        self.latest_block_hash = bytes(block_hash)

    def anchor(self, block_hash: bytes | None = None):
        """(state_root, rpc block tag) of a verified header. Proof
        queries must pin THIS block — 'latest' on the RPC side races
        ahead of light-client verification and every proof would
        mismatch."""
        bh = block_hash or self.latest_block_hash
        if bh is None or bh not in self._roots:
            raise VerificationError("no verified execution header")
        state_root, number = self._roots[bh]
        tag = hex(number) if number is not None else "0x" + bh.hex()
        return state_root, tag

    def state_root(self, block_hash: bytes | None = None) -> bytes:
        return self.anchor(block_hash)[0]


class VerifiedExecutionProvider:
    """eth_* facade that proves every answer (web3_provider.ts).

    rpc: object with async call(method, params) (e.g.
    execution.http.JsonRpcHttpClient)."""

    def __init__(self, rpc, proof_provider: ProofProvider):
        self.rpc = rpc
        self.proofs = proof_provider

    async def _account(self, address: bytes, slots=()):
        state_root, block_tag = self.proofs.anchor()
        out = await self.rpc.call(
            "eth_getProof",
            [
                "0x" + address.hex(),
                [
                    "0x" + bytes(s).rjust(32, b"\x00").hex()
                    for s in slots
                ],
                block_tag,
            ],
        )
        proof = [
            bytes.fromhex(n.removeprefix("0x"))
            for n in out["accountProof"]
        ]
        try:
            account = verify_account_proof(state_root, address, proof)
        except ProofError as e:
            raise VerificationError(f"account proof invalid: {e}") from e
        return account, out

    async def get_balance(self, address: bytes) -> int:
        account, _ = await self._account(address)
        return account["balance"]

    async def get_transaction_count(self, address: bytes) -> int:
        account, _ = await self._account(address)
        return account["nonce"]

    async def get_code(self, address: bytes) -> bytes:
        account, _ = await self._account(address)
        _, block_tag = self.proofs.anchor()
        code_hex = await self.rpc.call(
            "eth_getCode", ["0x" + address.hex(), block_tag]
        )
        code = bytes.fromhex(code_hex.removeprefix("0x"))
        if keccak256(code) != account["code_hash"]:
            raise VerificationError("code hash mismatch")
        return code

    async def get_storage_at(self, address: bytes, slot: bytes) -> int:
        account, out = await self._account(address, slots=[slot])
        entry = out["storageProof"][0]
        proof = [
            bytes.fromhex(n.removeprefix("0x")) for n in entry["proof"]
        ]
        try:
            return verify_storage_proof(
                account["storage_root"], bytes(slot), proof
            )
        except ProofError as e:
            raise VerificationError(f"storage proof invalid: {e}") from e

"""N-node local network simulation.

Reference analog: Simulation (cli/test/utils/crucible/simulation.ts) —
the reference spawns OS processes and docker EL clients; this harness
runs every node in one asyncio loop but keeps the REAL seams: each node
has its own BeaconChain (own state caches/fork choice/verifier) and its
own TCP Network (real sockets on localhost); blocks and attestations
travel only by gossip. Validator duties are split across nodes like a
real network: the proposer's node builds blocks from ITS attestation
pool; each node signs attestations only for its own key range with
partial aggregation bits, and pools aggregate what gossip delivers.
"""

from __future__ import annotations

import asyncio

from ..chain.chain import BeaconChain, _clone
from ..chain.oppools import AggregatedAttestationPool
from ..config.beacon_config import (
    BeaconConfig,
    compute_signing_root_from_roots,
)
from ..crypto.bls.signature import aggregate_signatures, sign
from ..network.facade import Network
from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    ForkSeq,
    preset,
)
from ..ssz import uint64 as ssz_uint64
from ..statetransition import (
    create_interop_genesis_state,
    interop_secret_key,
    state_transition,
    util,
)
from ..statetransition.block import compute_signing_root, get_domain
from ..statetransition.slot import process_slots


class SimNode:
    """One simulated node: chain + network + a validator key range."""

    def __init__(self, name, cfg, types, anchor, key_range, beacon_cfg):
        self.name = name
        self.cfg = cfg
        self.types = types
        self.chain = BeaconChain(cfg, types, anchor)
        self.keys = {i: interop_secret_key(i) for i in key_range}
        self.att_pool = AggregatedAttestationPool(types)
        # (slot, head_root) -> {validator_index: signature} — fed by
        # own duties + the sync_committee gossip topic
        self.sync_pool: dict[tuple, dict[int, bytes]] = {}
        self.network = Network(
            self.chain, beacon_cfg, types, peer_id=name
        )
        self._install_gossip_handlers()
        self.blocks_proposed = 0
        self.atts_published = 0
        # optional external-dependency seams (sim/faults.py wires
        # these): a builder relay behind a fault-inspection-window
        # breaker, and chain.execution_engine may carry a
        # ResilientEngine. Counters split production by payload source.
        self.builder = None
        self.blocks_via_builder = 0
        self.blocks_via_local = 0
        # cleared by sim/faults.kill_node: a dead node neither proposes
        # nor attests until restarted; restart_node records how many
        # blocks its catch-up actually imported
        self.alive = True
        self.caught_up_blocks = 0

    def _install_gossip_handlers(self) -> None:
        from ..network.gossip import ValidationResult

        async def on_att(peer_id, ssz_bytes):
            try:
                att = self.types.Attestation.deserialize(ssz_bytes)
            except Exception:
                return ValidationResult.REJECT
            self.att_pool.add(att)
            st = self.chain.get_state(self.chain.head_root)
            try:
                committee = util.get_beacon_committee(
                    st.state, int(att.data.slot), int(att.data.index)
                )
                bits = list(att.aggregation_bits)
                members = [
                    int(v)
                    for i, v in enumerate(committee)
                    if i < len(bits) and bits[i]
                ]
                self.chain.fork_choice.on_attestation(
                    members,
                    bytes(att.data.beacon_block_root),
                    int(att.data.target.epoch),
                )
            except Exception:
                pass
            return ValidationResult.ACCEPT

        # sim uses one attestation topic for simplicity (subnet fan-out
        # is exercised by facade tests)
        self.network.gossip.subscribe(
            self.network._t("beacon_attestation_0"), on_att
        )

        async def on_sync_msg(peer_id, ssz_bytes):
            try:
                msg = self.types.SyncCommitteeMessage.deserialize(
                    ssz_bytes
                )
            except Exception:
                return ValidationResult.REJECT
            self.sync_pool.setdefault(
                (int(msg.slot), bytes(msg.beacon_block_root)), {}
            )[int(msg.validator_index)] = bytes(msg.signature)
            return ValidationResult.ACCEPT

        self.network.gossip.subscribe(
            self.network._t("sync_committee_0"), on_sync_msg
        )

    # -- duties ----------------------------------------------------------

    async def maybe_propose(self, slot: int) -> bytes | None:
        head = self.chain.get_or_regen_state(self.chain.head_root)
        scratch = _clone(head, self.types)
        process_slots(self.cfg, scratch, slot, self.types)
        st = scratch.state
        proposer = util.get_beacon_proposer_index(
            st, electra=scratch.fork_seq >= ForkSeq.electra
        )
        if proposer not in self.keys:
            return None
        epoch = util.get_current_epoch(st)
        randao = sign(
            self.keys[proposer],
            compute_signing_root(
                ssz_uint64,
                epoch,
                get_domain(self.cfg, st, DOMAIN_RANDAO),
            ),
        )
        atts = self.att_pool.get_attestations_for_block(slot, state=st)
        sync_aggregate = self._sync_aggregate_for(st, slot)
        common = dict(attestations=atts, sync_aggregate=sync_aggregate)
        post_merge = scratch.fork_seq >= ForkSeq.bellatrix

        # builder race (produceBlockV3 analog, breaker-gated): a relay
        # fault falls back to local production and feeds the
        # fault-inspection-window breaker; while the breaker is open
        # the race is skipped entirely
        if post_merge and self.builder is not None and (
            self.builder.available(slot)
            if hasattr(self.builder, "available")
            else getattr(self.builder, "enabled", True)
        ):
            try:
                got = await self._propose_via_builder(
                    slot, scratch, proposer, randao, common
                )
            except Exception:
                got = None
                if hasattr(self.builder, "register_fault"):
                    self.builder.register_fault(slot)
            if got is not None:
                fork, signed = got
                await self.chain.process_block(signed, is_timely=True)
                await self.network.publish_block(fork, signed)
                if hasattr(self.builder, "register_success"):
                    self.builder.register_success(slot)
                self.blocks_proposed += 1
                self.blocks_via_builder += 1
                return self.chain.head_root

        # local production: engine payload when the engine is up,
        # dev payload otherwise (prepare_execution_payload degrades to
        # (None, ...) on engine faults / open breaker — fail-fast)
        execution_payload = None
        if post_merge and self.chain.execution_engine is not None:
            payload, _bundle, _value = (
                await self.chain.prepare_execution_payload(slot, scratch)
            )
            execution_payload = payload
        block, post = self.chain.produce_block(
            slot,
            randao,
            execution_payload=execution_payload,
            **common,
        )
        from ..params import DOMAIN_BEACON_PROPOSER

        ns = self.types.by_fork[post.fork]
        signed = ns.SignedBeaconBlock.default()
        signed.message = block
        domain = get_domain(self.cfg, post.state, DOMAIN_BEACON_PROPOSER)
        root = compute_signing_root(ns.BeaconBlock, block, domain)
        signed.signature = sign(self.keys[proposer], root)
        await self.chain.process_block(signed, is_timely=True)
        await self.network.publish_block(post.fork, signed)
        self.blocks_proposed += 1
        if post_merge:
            self.blocks_via_local += 1
        return self.chain.head_root

    async def _propose_via_builder(self, slot, scratch, proposer,
                                   randao, common):
        """Blinded-block flow against the attached relay: bid -> sign
        blinded -> reveal -> unblind (the produceBlockV3 +
        publish_blinded_block path, collapsed into the sim proposer).
        Returns (fork, SignedBeaconBlock) or None when no bid."""
        from ..execution.builder import unblind_signed_block
        from ..params import DOMAIN_BEACON_PROPOSER

        st = scratch.state
        parent_hash = bytes(
            st.latest_execution_payload_header.block_hash
        )
        pubkey = bytes(st.validators[proposer].pubkey)
        bid = await self.builder.get_header(slot, parent_hash, pubkey)
        if bid is None:
            return None
        block, post = self.chain.produce_block(
            slot,
            randao,
            execution_payload_header=bid.header,
            blob_kzg_commitments=bid.blob_kzg_commitments,
            **common,
        )
        ns = self.types.by_fork[post.fork]
        signed_blinded = ns.SignedBlindedBeaconBlock.default()
        signed_blinded.message = block
        domain = get_domain(self.cfg, post.state, DOMAIN_BEACON_PROPOSER)
        root = compute_signing_root(ns.BlindedBeaconBlock, block, domain)
        signed_blinded.signature = sign(self.keys[proposer], root)
        revealed = await self.builder.submit_blinded_block(
            post.fork, signed_blinded
        )
        payload = revealed[0] if isinstance(revealed, tuple) else revealed
        if bytes(payload.block_hash) != bytes(
            block.body.execution_payload_header.block_hash
        ):
            raise ValueError("revealed payload does not match bid header")
        return post.fork, unblind_signed_block(ns, signed_blinded, payload)

    def _sync_aggregate_for(self, st, block_slot: int):
        """SyncAggregate over the pooled messages for the parent root
        (SyncCommitteeMessagePool -> produceSyncAggregate analog, fed
        by every node's sync_commit duty over gossip)."""
        sc = getattr(st, "current_sync_committee", None)
        if sc is None:
            return None
        msgs = self.sync_pool.get(
            (block_slot - 1, self.chain.head_root), {}
        )
        pk2i = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        bits, sigs = [], []
        for pk in sc.pubkeys:
            idx = pk2i.get(bytes(pk))
            sig = msgs.get(idx) if idx is not None else None
            bits.append(sig is not None)
            if sig is not None:
                sigs.append(sig)
        sa = self.types.SyncAggregate.default()
        sa.sync_committee_bits = bits
        sa.sync_committee_signature = (
            aggregate_signatures(sigs) if sigs else b"\xc0" + b"\x00" * 95
        )
        return sa

    async def sync_commit(self, slot: int) -> None:
        """Sign sync-committee messages over the current head for OWN
        validators in the committee, pool + gossip them
        (SyncCommitteeService analog)."""
        head_root = self.chain.head_root
        st = self.chain.get_or_regen_state(head_root).state
        sc = getattr(st, "current_sync_committee", None)
        if sc is None:
            return
        domain = get_domain(
            self.cfg,
            st,
            DOMAIN_SYNC_COMMITTEE,
            util.compute_epoch_at_slot(slot),
        )
        root = compute_signing_root_from_roots(head_root, domain)
        # bound memory: only the previous slot's messages are ever
        # aggregated, so drop anything older
        self.sync_pool = {
            k: v for k, v in self.sync_pool.items() if k[0] >= slot - 2
        }
        pk2i = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        for pk in sc.pubkeys:
            idx = pk2i.get(bytes(pk))
            if idx is None or idx not in self.keys:
                continue
            sig = sign(self.keys[idx], root)
            self.sync_pool.setdefault((slot, head_root), {})[idx] = sig
            msg = self.types.SyncCommitteeMessage.default()
            msg.slot = slot
            msg.beacon_block_root = head_root
            msg.validator_index = idx
            msg.signature = sig
            await self.network.publish_sync_committee_message(
                msg, subnet=0
            )

    async def attest(self, slot: int) -> None:
        """Sign partial attestations for OWN validators only."""
        head_root = self.chain.head_root
        st = self.chain.get_or_regen_state(head_root).state
        epoch = util.compute_epoch_at_slot(slot)
        sh = util.get_shuffling(st, epoch)
        try:
            target_root = util.get_block_root(st, epoch)
        except ValueError:
            target_root = head_root
        for ci, committee in enumerate(sh.committees_at_slot(slot)):
            mine = [
                (pos, int(v))
                for pos, v in enumerate(committee)
                if int(v) in self.keys
            ]
            if not mine:
                continue
            data = self.types.AttestationData.default()
            data.slot = slot
            data.index = ci
            data.beacon_block_root = head_root
            data.source = st.current_justified_checkpoint
            tgt = self.types.Checkpoint.default()
            tgt.epoch = epoch
            tgt.root = target_root
            data.target = tgt
            domain = get_domain(
                self.cfg, st, DOMAIN_BEACON_ATTESTER, epoch
            )
            root = compute_signing_root(
                self.types.AttestationData, data, domain
            )
            bits = [False] * len(committee)
            sigs = []
            for pos, vidx in mine:
                bits[pos] = True
                sigs.append(sign(self.keys[vidx], root))
            att = self.types.Attestation.default()
            att.data = data
            att.aggregation_bits = bits
            att.signature = aggregate_signatures(sigs)
            self.att_pool.add(att)
            self.chain.fork_choice.on_attestation(
                [v for _, v in mine],
                bytes(data.beacon_block_root),
                epoch,
            )
            await self.network.publish_attestation(att, subnet=0)
            self.atts_published += 1


class Simulation:
    """Local N-node network with a shared slot clock."""

    def __init__(self, cfg, types, n_nodes: int, n_validators: int):
        assert n_validators % n_nodes == 0
        self.cfg = cfg
        self.types = types
        self.n_nodes = n_nodes
        self.n_validators = n_validators
        self.nodes: list[SimNode] = []
        self.slot = 0
        # slot hooks fire at the top of run_slot (before proposals) —
        # sim/faults.py schedules fault windows through these
        self.on_slot_hooks: list = []

    async def start(self) -> None:
        genesis = create_interop_genesis_state(
            self.cfg, self.types, self.n_validators
        )
        gvr = bytes(genesis.state.genesis_validators_root)
        bc = BeaconConfig(self.cfg, gvr)
        per = self.n_validators // self.n_nodes
        for i in range(self.n_nodes):
            anchor = _clone(genesis, self.types)
            node = SimNode(
                f"node{i}",
                self.cfg,
                self.types,
                anchor,
                range(i * per, (i + 1) * per),
                bc,
            )
            await node.network.start()
            self.nodes.append(node)
        # full mesh
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                await a.network.connect("127.0.0.1", b.network.host.port)
        await asyncio.sleep(0.05)

    async def stop(self) -> None:
        for node in self.nodes:
            await node.network.stop()
            await node.chain.close()

    async def run_slot(self) -> None:
        self.slot += 1
        for hook in self.on_slot_hooks:
            got = hook(self.slot)
            if asyncio.iscoroutine(got):
                await got
        proposed = None
        for node in self.nodes:
            if not node.alive:
                continue
            got = await node.maybe_propose(self.slot)
            if got is not None:
                proposed = got
                break
        # let the block propagate before attesting to it
        await asyncio.sleep(0.15 if proposed else 0.02)
        for node in self.nodes:
            if node.alive:
                await node.attest(self.slot)
        for node in self.nodes:
            if node.alive:
                await node.sync_commit(self.slot)
            # prune on the SLOT clock, not on finality: a sustained
            # non-finality regime must not grow the pool without bound
            # (scenario SLO: sim/assertions.op_pool_sizes stays flat)
            node.att_pool.prune(self.slot)
        await asyncio.sleep(0.1)

    async def run_until_slot(self, slot: int) -> None:
        while self.slot < slot:
            await self.run_slot()

"""Default sim assertions.

Reference analog: crucible's default assertions
(cli/test/utils/crucible/assertions/defaults/): finalized checkpoint,
head consistency across nodes, attestation participation.
"""

from __future__ import annotations

from ..params import preset


def assert_heads_consistent(sim) -> None:
    heads = {node.chain.head_root for node in sim.nodes}
    assert len(heads) == 1, (
        "heads diverged: "
        + ", ".join(
            f"{n.name}={n.chain.head_root.hex()[:12]}" for n in sim.nodes
        )
    )


def assert_finalized(sim, min_epoch: int) -> None:
    for node in sim.nodes:
        got = node.chain.finalized_checkpoint.epoch
        assert got >= min_epoch, (
            f"{node.name} finalized epoch {got} < {min_epoch}"
        )


def assert_participation(sim, min_ratio: float) -> None:
    """Previous-epoch target participation on every node's head state
    (crucible's attestationParticipation assertion)."""
    from ..statetransition.util import TIMELY_TARGET_FLAG_INDEX

    for node in sim.nodes:
        st = node.chain.get_or_regen_state(node.chain.head_root).state
        part = getattr(st, "previous_epoch_participation", None)
        if part is None:
            continue  # phase0: justification progress covers it
        n = len(part)
        hit = sum(
            1
            for f in part
            if (int(f) >> TIMELY_TARGET_FLAG_INDEX) & 1
        )
        ratio = hit / max(1, n)
        assert ratio >= min_ratio, (
            f"{node.name} participation {ratio:.2f} < {min_ratio}"
        )


def _canonical_blocks(node):
    """Canonical (root, block) pairs from head back to the anchor via
    fork choice parent links."""
    chain = node.chain
    out = []
    root = chain.head_root
    proto = chain.fork_choice.proto
    while root is not None:
        blk = chain.get_block(root)
        if blk is None:
            break
        out.append((root, blk))
        n = proto.get_node(root)
        if n is None or n.parent_root is None:
            break
        root = bytes(n.parent_root)
    out.reverse()
    return out


def assert_inclusion_delay(sim, max_avg: float = 1.1) -> None:
    """Average attestation inclusion distance over every canonical
    block (crucible inclusionDelayAssertion: regression that delays
    inclusion by a slot must fail the sim)."""
    for node in sim.nodes:
        delays = []
        for _, signed in _canonical_blocks(node):
            blk = getattr(signed, "message", signed)
            for att in blk.body.attestations:
                if len(getattr(att, "aggregation_bits", ())) == 0:
                    continue
                delays.append(int(blk.slot) - int(att.data.slot))
        if not delays:
            continue
        avg = sum(delays) / len(delays)
        assert avg <= max_avg, (
            f"{node.name} avg inclusion delay {avg:.2f} > {max_avg} "
            f"({len(delays)} attestations)"
        )


def assert_no_missed_blocks(sim, start_slot: int = 1, end_slot=None) -> None:
    """Every slot in [start_slot, end_slot] has a canonical block
    (crucible missedBlocksAssertion with 0 tolerated misses)."""
    for node in sim.nodes:
        blocks = _canonical_blocks(node)
        have = {
            int(getattr(s, "message", s).slot) for _, s in blocks
        }
        end = end_slot
        if end is None:
            end = max(have) if have else 0
        missing = [
            s for s in range(start_slot, end + 1) if s not in have
        ]
        assert not missing, (
            f"{node.name} missed proposals at slots {missing}"
        )


def assert_sync_committee_participation(
    sim, min_ratio: float = 0.9
) -> None:
    """Average SyncAggregate bit participation across canonical altair+
    blocks (crucible syncCommitteeParticipationAssertion)."""
    for node in sim.nodes:
        ratios = []
        for _, signed in _canonical_blocks(node):
            blk = getattr(signed, "message", signed)
            agg = getattr(blk.body, "sync_aggregate", None)
            if agg is None:
                continue
            bits = [bool(b) for b in agg.sync_committee_bits]
            if not bits:
                continue
            ratios.append(sum(bits) / len(bits))
        if not ratios:
            continue
        avg = sum(ratios) / len(ratios)
        assert avg >= min_ratio, (
            f"{node.name} sync participation {avg:.2f} < {min_ratio}"
        )

"""Default sim assertions.

Reference analog: crucible's default assertions
(cli/test/utils/crucible/assertions/defaults/): finalized checkpoint,
head consistency across nodes, attestation participation.
"""

from __future__ import annotations

from ..params import preset


def assert_heads_consistent(sim) -> None:
    heads = {node.chain.head_root for node in sim.nodes}
    assert len(heads) == 1, (
        "heads diverged: "
        + ", ".join(
            f"{n.name}={n.chain.head_root.hex()[:12]}" for n in sim.nodes
        )
    )


def assert_finalized(sim, min_epoch: int) -> None:
    for node in sim.nodes:
        got = node.chain.finalized_checkpoint.epoch
        assert got >= min_epoch, (
            f"{node.name} finalized epoch {got} < {min_epoch}"
        )


def assert_participation(sim, min_ratio: float) -> None:
    """Previous-epoch target participation on every node's head state
    (crucible's attestationParticipation assertion)."""
    from ..statetransition.util import TIMELY_TARGET_FLAG_INDEX

    for node in sim.nodes:
        st = node.chain.get_or_regen_state(node.chain.head_root).state
        part = getattr(st, "previous_epoch_participation", None)
        if part is None:
            continue  # phase0: justification progress covers it
        n = len(part)
        hit = sum(
            1
            for f in part
            if (int(f) >> TIMELY_TARGET_FLAG_INDEX) & 1
        )
        ratio = hit / max(1, n)
        assert ratio >= min_ratio, (
            f"{node.name} participation {ratio:.2f} < {min_ratio}"
        )

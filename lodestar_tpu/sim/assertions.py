"""Default sim assertions + scenario SLO evaluators.

Reference analog: crucible's default assertions
(cli/test/utils/crucible/assertions/defaults/): finalized checkpoint,
head consistency across nodes, attestation participation.

The non-asserting evaluators at the bottom (`heads_consistent`,
`missed_slots`, `finalized_epochs`, `op_pool_sizes`,
`state_cache_sizes`, `max_import_ms`) read the same telemetry surfaces
and return observations — sim/scenarios.py turns them into
machine-evaluated pass/fail SLO records instead of bare asserts.
"""

from __future__ import annotations

from ..params import preset


def assert_heads_consistent(sim) -> None:
    heads = {node.chain.head_root for node in sim.nodes}
    assert len(heads) == 1, (
        "heads diverged: "
        + ", ".join(
            f"{n.name}={n.chain.head_root.hex()[:12]}" for n in sim.nodes
        )
    )


def assert_finalized(sim, min_epoch: int) -> None:
    for node in sim.nodes:
        got = node.chain.finalized_checkpoint.epoch
        assert got >= min_epoch, (
            f"{node.name} finalized epoch {got} < {min_epoch}"
        )


def assert_participation(sim, min_ratio: float) -> None:
    """Previous-epoch target participation on every node's head state
    (crucible's attestationParticipation assertion)."""
    from ..statetransition.util import TIMELY_TARGET_FLAG_INDEX

    for node in sim.nodes:
        st = node.chain.get_or_regen_state(node.chain.head_root).state
        part = getattr(st, "previous_epoch_participation", None)
        if part is None:
            continue  # phase0: justification progress covers it
        n = len(part)
        hit = sum(
            1
            for f in part
            if (int(f) >> TIMELY_TARGET_FLAG_INDEX) & 1
        )
        ratio = hit / max(1, n)
        assert ratio >= min_ratio, (
            f"{node.name} participation {ratio:.2f} < {min_ratio}"
        )


def _canonical_blocks(node):
    """Canonical (root, block) pairs from head back to the anchor via
    fork choice parent links."""
    chain = node.chain
    out = []
    root = chain.head_root
    proto = chain.fork_choice.proto
    while root is not None:
        blk = chain.get_block(root)
        if blk is None:
            break
        out.append((root, blk))
        n = proto.get_node(root)
        if n is None or n.parent_root is None:
            break
        root = bytes(n.parent_root)
    out.reverse()
    return out


def assert_inclusion_delay(sim, max_avg: float = 1.1) -> None:
    """Average attestation inclusion distance over every canonical
    block (crucible inclusionDelayAssertion: regression that delays
    inclusion by a slot must fail the sim)."""
    for node in sim.nodes:
        delays = []
        for _, signed in _canonical_blocks(node):
            blk = getattr(signed, "message", signed)
            for att in blk.body.attestations:
                if len(getattr(att, "aggregation_bits", ())) == 0:
                    continue
                delays.append(int(blk.slot) - int(att.data.slot))
        if not delays:
            continue
        avg = sum(delays) / len(delays)
        assert avg <= max_avg, (
            f"{node.name} avg inclusion delay {avg:.2f} > {max_avg} "
            f"({len(delays)} attestations)"
        )


def missed_slots(sim, start_slot: int = 1, end_slot=None) -> dict:
    """Per-node list of slots in [start_slot, end_slot] without a
    canonical block. `end_slot=None` defaults to the sim's CURRENT
    slot — never to the newest canonical block, which would let a run
    whose trailing slots all missed look clean."""
    out = {}
    for node in sim.nodes:
        blocks = _canonical_blocks(node)
        have = {
            int(getattr(s, "message", s).slot) for _, s in blocks
        }
        end = end_slot if end_slot is not None else sim.slot
        out[node.name] = [
            s for s in range(start_slot, end + 1) if s not in have
        ]
    return out


def assert_no_missed_blocks(sim, start_slot: int = 1, end_slot=None) -> None:
    """Every slot in [start_slot, end_slot] has a canonical block
    (crucible missedBlocksAssertion with 0 tolerated misses).
    `end_slot=None` means "up to the sim's current slot" — trailing
    missed slots fail instead of passing vacuously."""
    for name, missing in missed_slots(sim, start_slot, end_slot).items():
        assert not missing, (
            f"{name} missed proposals at slots {missing}"
        )


def assert_sync_committee_participation(
    sim, min_ratio: float = 0.9
) -> None:
    """Average SyncAggregate bit participation across canonical altair+
    blocks (crucible syncCommitteeParticipationAssertion)."""
    for node in sim.nodes:
        ratios = []
        for _, signed in _canonical_blocks(node):
            blk = getattr(signed, "message", signed)
            agg = getattr(blk.body, "sync_aggregate", None)
            if agg is None:
                continue
            bits = [bool(b) for b in agg.sync_committee_bits]
            if not bits:
                continue
            ratios.append(sum(bits) / len(bits))
        if not ratios:
            continue
        avg = sum(ratios) / len(ratios)
        assert avg >= min_ratio, (
            f"{node.name} sync participation {avg:.2f} < {min_ratio}"
        )


# ---------------------------------------------------------------------------
# non-asserting SLO evaluators (sim/scenarios.py consumes these)
# ---------------------------------------------------------------------------


def heads_consistent(sim) -> bool:
    """True when every ALIVE node reports the same head root."""
    heads = {
        node.chain.head_root for node in sim.nodes if node.alive
    }
    return len(heads) <= 1


def finalized_epochs(sim) -> dict:
    """Per-node finalized checkpoint epoch."""
    return {
        node.name: int(node.chain.finalized_checkpoint.epoch)
        for node in sim.nodes
    }


def op_pool_sizes(sim) -> dict:
    """Per-node aggregated-attestation-pool entry count — the memory
    surface a sustained non-finality run must keep bounded (the pool
    prunes on the slot clock, not on finality)."""
    return {node.name: len(node.att_pool) for node in sim.nodes}


def state_cache_sizes(sim) -> dict:
    """Per-node (state_cache, block_cache) entry counts — bounded by
    MAX_CACHED_STATES / MAX_CACHED_BLOCKS regardless of how long
    finality has been stalled."""
    return {
        node.name: (
            len(node.chain._states), len(node.chain._blocks)
        )
        for node in sim.nodes
    }


def max_import_ms(node) -> float:
    """Slowest block-import total from the node's trace ring buffer
    (metrics/tracing.py), 0.0 when no tracer is attached or nothing
    was recorded. Attach a Tracer with slow_ms=0 to capture EVERY
    import, not just the slow ones."""
    tracer = getattr(node.chain, "tracer", None)
    if tracer is None:
        return 0.0
    items = tracer.buffer.snapshot()
    if not items:
        return 0.0
    return max(float(t.get("total_ms", 0.0)) for t in items)

"""Multi-node simulation harness.

Reference analog: the "crucible" sim framework
(cli/test/utils/crucible/simulation.ts + assertions/defaults) — spawn
N nodes as one process-local network, drive an epoch clock, and assert
whole-network behavior: finality advancing, head consistency across
nodes, attestation participation.

On top of the raw harness sits the scenario fleet (sim/scenarios.py):
named, deterministic adversity regimes with machine-evaluated SLO
contracts, driven by the fault injectors in sim/faults.py and
evaluated through sim/assertions.py. `tools/run_scenarios.py` is the
operator CLI; SCENARIOS.md tabulates the fleet.

NOTE: scenario-fleet symbols (run_scenario, SCENARIOS, ...) import
lazily from .scenarios to keep `import lodestar_tpu.sim` cheap for
the plain sim tests.
"""

from .simulation import Simulation, SimNode
from .faults import (
    FaultRegistry,
    FaultSchedule,
    FlakyEngine,
    FlakyRelay,
    GossipFaultInjector,
    LateBlockReplayer,
    SimBuilder,
    bind_sim_fault_collectors,
    catch_up,
    kill_node,
    propose_equivocation,
    republish_head_block,
    restart_node,
)
from .assertions import (
    assert_finalized,
    assert_heads_consistent,
    assert_inclusion_delay,
    assert_no_missed_blocks,
    assert_participation,
    assert_sync_committee_participation,
    finalized_epochs,
    heads_consistent,
    max_import_ms,
    missed_slots,
    op_pool_sizes,
    state_cache_sizes,
)

__all__ = [
    "FaultRegistry",
    "FaultSchedule",
    "FlakyEngine",
    "FlakyRelay",
    "GossipFaultInjector",
    "LateBlockReplayer",
    "SimBuilder",
    "Simulation",
    "SimNode",
    "bind_sim_fault_collectors",
    "catch_up",
    "kill_node",
    "propose_equivocation",
    "republish_head_block",
    "restart_node",
    "assert_finalized",
    "assert_heads_consistent",
    "assert_inclusion_delay",
    "assert_no_missed_blocks",
    "assert_participation",
    "assert_sync_committee_participation",
    "finalized_epochs",
    "heads_consistent",
    "max_import_ms",
    "missed_slots",
    "op_pool_sizes",
    "state_cache_sizes",
]

"""Multi-node simulation harness.

Reference analog: the "crucible" sim framework
(cli/test/utils/crucible/simulation.ts + assertions/defaults) — spawn
N nodes as one process-local network, drive an epoch clock, and assert
whole-network behavior: finality advancing, head consistency across
nodes, attestation participation.
"""

from .simulation import Simulation, SimNode
from .faults import (
    FaultSchedule,
    FlakyEngine,
    FlakyRelay,
    GossipFaultInjector,
    SimBuilder,
    catch_up,
    kill_node,
    restart_node,
)
from .assertions import (
    assert_finalized,
    assert_heads_consistent,
    assert_inclusion_delay,
    assert_no_missed_blocks,
    assert_participation,
    assert_sync_committee_participation,
)

__all__ = [
    "FaultSchedule",
    "FlakyEngine",
    "FlakyRelay",
    "GossipFaultInjector",
    "SimBuilder",
    "Simulation",
    "SimNode",
    "catch_up",
    "kill_node",
    "restart_node",
    "assert_finalized",
    "assert_heads_consistent",
    "assert_inclusion_delay",
    "assert_no_missed_blocks",
    "assert_participation",
    "assert_sync_committee_participation",
]

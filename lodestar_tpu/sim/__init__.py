"""Multi-node simulation harness.

Reference analog: the "crucible" sim framework
(cli/test/utils/crucible/simulation.ts + assertions/defaults) — spawn
N nodes as one process-local network, drive an epoch clock, and assert
whole-network behavior: finality advancing, head consistency across
nodes, attestation participation.
"""

from .simulation import Simulation, SimNode
from .assertions import (
    assert_finalized,
    assert_heads_consistent,
    assert_inclusion_delay,
    assert_no_missed_blocks,
    assert_participation,
    assert_sync_committee_participation,
)

__all__ = [
    "Simulation",
    "SimNode",
    "assert_finalized",
    "assert_heads_consistent",
    "assert_inclusion_delay",
    "assert_no_missed_blocks",
    "assert_participation",
    "assert_sync_committee_participation",
]

"""Fault injection for the multi-node simulation.

Reference analog: crucible's fault tooling (cli/test/utils/crucible —
the sim harness kills nodes, stalls ELs, and asserts the network
recovers). Everything here wraps an existing seam rather than patching
internals:

* `FlakyEngine` — IExecutionEngine wrapper that raises transport-shaped
  errors while a fault window is active (engine timeout/error
  flapping). Wrapped in `ResilientEngine`, the chain's import path
  degrades to optimistic imports and block production falls back to
  local payloads; the engine breaker runs its open→half-open→closed
  cycle on recovery.
* `FlakyRelay` — builder relay wrapper with an outage switch
  (builder outage / relay errors).
* `SimBuilder` — relay + fault-inspection-window breaker, the object a
  `SimNode.builder` expects (`available`/`register_fault`/
  `register_success` + the relay API).
* `GossipFaultInjector` — drop / delay / duplicate outbound gossip
  frames of one node, by wrapping its GossipNode's mesh send.
* `kill_node` / `restart_node` — take a node's network down
  mid-run and bring it back, resyncing its chain from a healthy peer.
* `FaultSchedule` — slot-driven fault windows riding the simulation's
  `on_slot_hooks`.
"""

from __future__ import annotations

import asyncio

from ..execution.engine import ExecutionEngineError
from ..resilience import FaultInspectionWindow


class InjectedEngineError(ExecutionEngineError):
    """Transport-shaped (retryable) injected engine fault."""

    retryable = True


class FlakyEngine:
    """IExecutionEngine wrapper: while `failing`, every call raises an
    InjectedEngineError (the shape of a connect timeout)."""

    def __init__(self, inner):
        self.inner = inner
        self.failing = False
        self.injected_errors = 0
        self.calls_passed = 0

    def set_failing(self, failing: bool) -> None:
        self.failing = bool(failing)

    def _gate(self) -> None:
        if self.failing:
            self.injected_errors += 1
            raise InjectedEngineError("injected engine timeout")
        self.calls_passed += 1

    async def notify_new_payload(self, fork, payload, **kw):
        self._gate()
        return await self.inner.notify_new_payload(fork, payload, **kw)

    async def notify_forkchoice_update(self, fork, state, attributes=None):
        self._gate()
        return await self.inner.notify_forkchoice_update(
            fork, state, attributes
        )

    async def get_payload(self, fork, payload_id, *a, **kw):
        self._gate()
        return await self.inner.get_payload(fork, payload_id, *a, **kw)

    async def get_payload_bodies_by_hash(self, fork, block_hashes):
        self._gate()
        return await self.inner.get_payload_bodies_by_hash(
            fork, block_hashes
        )


class FlakyRelay:
    """Builder relay wrapper: while `outage`, bids and reveals fail
    with BuilderError (the relay is down / erroring)."""

    def __init__(self, inner):
        self.inner = inner
        self.outage = False
        self.injected_errors = 0

    def set_outage(self, outage: bool) -> None:
        self.outage = bool(outage)

    def _gate(self) -> None:
        from ..execution.builder import BuilderError

        if self.outage:
            self.injected_errors += 1
            raise BuilderError("injected relay outage")

    async def register_validators(self, registrations):
        self._gate()
        return await self.inner.register_validators(registrations)

    async def get_header(self, slot, parent_hash, pubkey):
        self._gate()
        return await self.inner.get_header(slot, parent_hash, pubkey)

    async def submit_blinded_block(self, fork, signed_blinded):
        self._gate()
        return await self.inner.submit_blinded_block(fork, signed_blinded)


class SimBuilder:
    """Relay + the builder circuit breaker, in the interface
    SimNode.maybe_propose consumes (mirrors ExecutionBuilderHttp's
    breaker surface without the HTTP layer)."""

    def __init__(self, relay, window: int = 8, allowed_faults: int = 2,
                 breaker: FaultInspectionWindow | None = None):
        self.relay = relay
        self.enabled = True
        # `breaker` lets several nodes share one inspection window
        # (they are all judging the same relay)
        self.circuit_breaker = breaker or FaultInspectionWindow(
            name="builder", window=window, allowed_faults=allowed_faults
        )

    def available(self, slot: int) -> bool:
        return self.enabled and self.circuit_breaker.available(slot)

    def register_fault(self, slot: int, kind: str = "relay_error") -> None:
        self.circuit_breaker.record_fault(slot)

    def register_success(self, slot: int) -> None:
        self.circuit_breaker.record_success(slot)

    async def get_header(self, slot, parent_hash, pubkey):
        return await self.relay.get_header(slot, parent_hash, pubkey)

    async def submit_blinded_block(self, fork, signed_blinded):
        return await self.relay.submit_blinded_block(fork, signed_blinded)


class GossipFaultInjector:
    """Wraps one node's GossipNode outbound mesh send with a lossy
    policy: fraction/flags for drop, delay (seconds), duplicate.
    Deterministic when given an rng."""

    def __init__(self, gossip_node, rng=None, drop: float = 0.0,
                 delay: float = 0.0, duplicate: float = 0.0):
        self.gossip = gossip_node
        self.rng = rng
        self.drop = drop
        self.delay = delay
        self.duplicate = duplicate
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0
        self._orig = gossip_node._send_to_mesh
        gossip_node._send_to_mesh = self._send

    def detach(self) -> None:
        self.gossip._send_to_mesh = self._orig

    def _roll(self) -> float:
        import random

        return (self.rng or random).random()

    async def _send(self, topic, data, exclude):
        if self.drop and self._roll() < self.drop:
            self.dropped += 1
            return 0  # message never leaves this node
        if self.duplicate and self._roll() < self.duplicate:
            self.duplicated += 1
            await self._orig(topic, data, exclude)
        if self.delay:
            self.delayed += 1

            async def later():
                await asyncio.sleep(self.delay)
                try:
                    await self._orig(topic, data, exclude)
                except Exception:
                    pass

            asyncio.ensure_future(later())
            return 1
        return await self._orig(topic, data, exclude)


async def kill_node(sim, index: int) -> None:
    """Take a node off the network mid-run (process kill analog: its
    chain state survives, its sockets don't, its duties stop)."""
    node = sim.nodes[index]
    node.alive = False
    await node.network.stop()


async def restart_node(sim, index: int, resync_from: int | None = None
                       ) -> None:
    """Bring a killed node back: restart its network, reconnect the
    mesh, and catch its chain up from a healthy peer's canonical chain
    (the range-sync step, collapsed to direct imports since both nodes
    live in this process)."""
    node = sim.nodes[index]
    node.alive = True
    await node.network.start()
    for i, other in enumerate(sim.nodes):
        if i == index:
            continue
        try:
            await node.network.connect(
                "127.0.0.1", other.network.host.port
            )
        except Exception:
            pass
    if resync_from is not None:
        await catch_up(node, sim.nodes[resync_from])
    await asyncio.sleep(0.05)


async def catch_up(node, healthy) -> None:
    """Import the healthy node's canonical blocks that `node` missed,
    oldest first (BeaconBlocksByRange over an in-process shortcut)."""
    chain = healthy.chain
    blocks = []
    root = chain.head_root
    proto = chain.fork_choice.proto
    while root is not None:
        if node.chain.get_block(root) is not None:
            break  # shared history reached
        blk = chain.get_block(root)
        if blk is None:
            break
        blocks.append(blk)
        n = proto.get_node(root)
        if n is None or n.parent_root is None:
            break
        root = bytes(n.parent_root)
    for blk in reversed(blocks):
        try:
            await node.chain.process_block(blk, is_timely=False)
        except Exception:
            pass  # already known / pre-anchor


class FaultSchedule:
    """Slot-scheduled fault windows for a Simulation: register
    (start_slot, end_slot, on_enter, on_exit) windows; tick() rides
    sim.on_slot_hooks."""

    def __init__(self, sim):
        self.sim = sim
        self.windows: list[dict] = []
        sim.on_slot_hooks.append(self.tick)

    def window(self, start_slot: int, end_slot: int, on_enter,
               on_exit=None) -> None:
        self.windows.append(
            {
                "start": start_slot,
                "end": end_slot,
                "enter": on_enter,
                "exit": on_exit,
                "active": False,
            }
        )

    def tick(self, slot: int):
        coros = []
        for w in self.windows:
            if not w["active"] and w["start"] <= slot <= w["end"]:
                w["active"] = True
                got = w["enter"]()
                if asyncio.iscoroutine(got):
                    coros.append(got)
            elif w["active"] and slot > w["end"]:
                w["active"] = False
                if w["exit"] is not None:
                    got = w["exit"]()
                    if asyncio.iscoroutine(got):
                        coros.append(got)
        if not coros:
            return None

        async def run():
            for c in coros:
                await c

        return run()

"""Fault injection for the multi-node simulation.

Reference analog: crucible's fault tooling (cli/test/utils/crucible —
the sim harness kills nodes, stalls ELs, and asserts the network
recovers). Everything here wraps an existing seam rather than patching
internals:

* `FlakyEngine` — IExecutionEngine wrapper that raises transport-shaped
  errors while a fault window is active (engine timeout/error
  flapping). Wrapped in `ResilientEngine`, the chain's import path
  degrades to optimistic imports and block production falls back to
  local payloads; the engine breaker runs its open→half-open→closed
  cycle on recovery.
* `FlakyRelay` — builder relay wrapper with an outage switch
  (builder outage / relay errors).
* `SimBuilder` — relay + fault-inspection-window breaker, the object a
  `SimNode.builder` expects (`available`/`register_fault`/
  `register_success` + the relay API).
* `GossipFaultInjector` — drop / delay / duplicate outbound gossip
  frames of one node (optionally only for selected topics), by
  wrapping its GossipNode's mesh send.
* `LateBlockReplayer` — holds one node's outbound block publications
  for a fixed delay so peers attest before the block arrives (the
  late-block half of a reorg storm).
* `propose_equivocation` / `republish_head_block` — proposer
  equivocation: a conflicting sibling of the current head block
  (same slot, same proposer, different body), plus a duplicate-block
  flood the peers' gossip seen-cache must absorb.
* `kill_node` / `restart_node` — take a node's network down
  mid-run and bring it back, resyncing its chain from a healthy peer.
* `DeviceFaultInjector` (+ `device_hang` / `device_error` /
  `device_oom` / `device_flaky` factories) — wraps the BLS kernel
  entry points with fabricated JAX-runtime-shaped failures so the
  device fault domain (device/health.py watchdog, taxonomy, breaker,
  host failover, probe reinstatement) is exercised end-to-end without
  a sick chip.
* `FaultSchedule` — slot-driven fault windows riding the simulation's
  `on_slot_hooks`.
* `FaultRegistry` — aggregates every injector's delivered-fault
  counters into one `{kind: count}` view, exported as
  `lodestar_sim_injected_faults_total{kind}` via
  `bind_sim_fault_collectors` so a scenario SLO can assert the fault
  actually FIRED instead of trusting the schedule.
"""

from __future__ import annotations

import asyncio
import threading

from ..execution.engine import ExecutionEngineError
from ..resilience import FaultInspectionWindow


class InjectedEngineError(ExecutionEngineError):
    """Transport-shaped (retryable) injected engine fault."""

    retryable = True


class FlakyEngine:
    """IExecutionEngine wrapper: while `failing`, every call raises an
    InjectedEngineError (the shape of a connect timeout)."""

    def __init__(self, inner):
        self.inner = inner
        self.failing = False
        self.injected_errors = 0
        self.calls_passed = 0

    def set_failing(self, failing: bool) -> None:
        self.failing = bool(failing)

    def injected_fault_counts(self) -> dict:
        return {"engine_error": self.injected_errors}

    def _gate(self) -> None:
        if self.failing:
            self.injected_errors += 1
            raise InjectedEngineError("injected engine timeout")
        self.calls_passed += 1

    async def notify_new_payload(self, fork, payload, **kw):
        self._gate()
        return await self.inner.notify_new_payload(fork, payload, **kw)

    async def notify_forkchoice_update(self, fork, state, attributes=None):
        self._gate()
        return await self.inner.notify_forkchoice_update(
            fork, state, attributes
        )

    async def get_payload(self, fork, payload_id, *a, **kw):
        self._gate()
        return await self.inner.get_payload(fork, payload_id, *a, **kw)

    async def get_payload_bodies_by_hash(self, fork, block_hashes):
        self._gate()
        return await self.inner.get_payload_bodies_by_hash(
            fork, block_hashes
        )


class FlakyRelay:
    """Builder relay wrapper: while `outage`, bids and reveals fail
    with BuilderError (the relay is down / erroring)."""

    def __init__(self, inner):
        self.inner = inner
        self.outage = False
        self.injected_errors = 0

    def set_outage(self, outage: bool) -> None:
        self.outage = bool(outage)

    def injected_fault_counts(self) -> dict:
        return {"relay_outage": self.injected_errors}

    def _gate(self) -> None:
        from ..execution.builder import BuilderError

        if self.outage:
            self.injected_errors += 1
            raise BuilderError("injected relay outage")

    async def register_validators(self, registrations):
        self._gate()
        return await self.inner.register_validators(registrations)

    async def get_header(self, slot, parent_hash, pubkey):
        self._gate()
        return await self.inner.get_header(slot, parent_hash, pubkey)

    async def submit_blinded_block(self, fork, signed_blinded):
        self._gate()
        return await self.inner.submit_blinded_block(fork, signed_blinded)


class SimBuilder:
    """Relay + the builder circuit breaker, in the interface
    SimNode.maybe_propose consumes (mirrors ExecutionBuilderHttp's
    breaker surface without the HTTP layer)."""

    def __init__(self, relay, window: int = 8, allowed_faults: int = 2,
                 breaker: FaultInspectionWindow | None = None):
        self.relay = relay
        self.enabled = True
        # `breaker` lets several nodes share one inspection window
        # (they are all judging the same relay)
        self.circuit_breaker = breaker or FaultInspectionWindow(
            name="builder", window=window, allowed_faults=allowed_faults
        )

    def available(self, slot: int) -> bool:
        return self.enabled and self.circuit_breaker.available(slot)

    def register_fault(self, slot: int, kind: str = "relay_error") -> None:
        self.circuit_breaker.record_fault(slot)

    def register_success(self, slot: int) -> None:
        self.circuit_breaker.record_success(slot)

    async def get_header(self, slot, parent_hash, pubkey):
        return await self.relay.get_header(slot, parent_hash, pubkey)

    async def submit_blinded_block(self, fork, signed_blinded):
        return await self.relay.submit_blinded_block(fork, signed_blinded)


class GossipFaultInjector:
    """Wraps one node's GossipNode outbound mesh send with a lossy
    policy: fraction/flags for drop, delay (seconds), duplicate.
    Deterministic when given an rng. `topics` (substrings matched
    against the full topic name) scopes the policy — e.g.
    ("beacon_attestation",) blacks out attestation gossip while
    blocks still flow, the sustained-non-finality shape."""

    def __init__(self, gossip_node, rng=None, drop: float = 0.0,
                 delay: float = 0.0, duplicate: float = 0.0,
                 topics=None):
        self.gossip = gossip_node
        self.rng = rng
        self.drop = drop
        self.delay = delay
        self.duplicate = duplicate
        self.topics = tuple(topics) if topics else None
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0
        self._orig = gossip_node._send_to_mesh
        gossip_node._send_to_mesh = self._send

    def detach(self) -> None:
        self.gossip._send_to_mesh = self._orig

    def injected_fault_counts(self) -> dict:
        return {
            "gossip_drop": self.dropped,
            "gossip_delay": self.delayed,
            "gossip_duplicate": self.duplicated,
        }

    def _roll(self) -> float:
        import random

        return (self.rng or random).random()

    def _matches(self, topic) -> bool:
        if self.topics is None:
            return True
        t = str(topic)
        return any(want in t for want in self.topics)

    async def _send(self, topic, data, exclude):
        if not self._matches(topic):
            return await self._orig(topic, data, exclude)
        if self.drop and self._roll() < self.drop:
            self.dropped += 1
            return 0  # message never leaves this node
        if self.duplicate and self._roll() < self.duplicate:
            self.duplicated += 1
            await self._orig(topic, data, exclude)
        if self.delay:
            self.delayed += 1

            async def later():
                await asyncio.sleep(self.delay)
                try:
                    await self._orig(topic, data, exclude)
                except Exception:
                    pass

            asyncio.ensure_future(later())
            return 1
        return await self._orig(topic, data, exclude)


class LateBlockReplayer:
    """Holds one node's outbound block publications for `delay_s`:
    peers have already attested to the previous head when the block
    lands, so the next proposer builds a sibling and the network
    reorgs — attach during a window for a reorg storm. Only the
    publish is delayed; the proposer's own import is untouched."""

    def __init__(self, node, delay_s: float = 0.35):
        self.node = node
        self.delay_s = delay_s
        self.held = 0
        self._orig = node.network.publish_block
        node.network.publish_block = self._publish

    def detach(self) -> None:
        self.node.network.publish_block = self._orig

    def injected_fault_counts(self) -> dict:
        return {"late_block": self.held}

    async def _publish(self, fork, signed_block):
        self.held += 1

        async def later():
            await asyncio.sleep(self.delay_s)
            try:
                await self._orig(fork, signed_block)
            except Exception:
                pass  # network stopped mid-delay

        asyncio.ensure_future(later())
        return 0


_DEVICE_ERROR_MESSAGES = {
    # messages are crafted to hit health.classify_device_error's
    # status-code markers — the same message-based routing a real
    # XlaRuntimeError takes, so injected and organic faults classify
    # identically (oom checked before compile before device_lost)
    "oom": (
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "2147483648 bytes (injected)"
    ),
    "compile": (
        "Mosaic compilation failed: unsupported lowering for fused "
        "pairing stage (injected)"
    ),
    "device_lost": (
        "INTERNAL: device lost: TPU runtime halted (injected)"
    ),
    "unknown": "injected device fault of no particular shape",
}


class InjectedDeviceError(RuntimeError):
    """Fabricated JAX-runtime-shaped device failure. The taxonomy
    buckets it by MESSAGE (status-code markers), exactly as it would
    a real runtime error whose type jaxlib keeps moving around."""


class DeviceFaultInjector:
    """Wraps the BLS kernel entry points (bls/kernels.py module
    attributes — the verifier binds them late, at dispatch time, so a
    module-attribute patch intercepts every device dispatch) with a
    fault policy:

    * ``hang``  — every dispatch blocks on an Event until `release()`
      or `detach()`, then raises; the wave watchdog must fire and the
      worker thread must not wedge the executor.
    * ``error`` — every dispatch raises an InjectedDeviceError whose
      message classifies as `kind` ('oom' | 'compile' | 'device_lost'
      | 'unknown').
    * ``flaky`` — each dispatch raises with probability `p`
      (deterministic when given an rng), else passes through.

    Use the `device_hang` / `device_error` / `device_oom` /
    `device_flaky` factories; `active` toggles the policy without
    unpatching (for FaultSchedule windows)."""

    ENTRY_POINTS = (
        "run_verify_batch_async",
        "run_verify_batch",
        "run_verify_same_message",
        "run_verify_batch_ingest_async",
        "run_verify_same_message_ingest_async",
        "run_verify_batch_mesh",
        "run_verify_same_message_mesh",
        "run_verify_batch_ingest_mesh",
    )

    def __init__(self, mode: str = "error", kind: str = "device_lost",
                 p: float = 1.0, rng=None, label: str | None = None):
        if mode not in ("hang", "error", "flaky"):
            raise ValueError(f"unknown device fault mode {mode!r}")
        if kind not in _DEVICE_ERROR_MESSAGES:
            raise ValueError(f"unknown device fault kind {kind!r}")
        from ..bls import kernels

        self._kernels = kernels
        self.mode = mode
        self.kind = kind
        self.p = float(p)
        self.rng = rng
        self.label = label or f"device_{mode}"
        self.active = True
        self.injected = 0
        self.passed = 0
        self._release = threading.Event()
        self._orig: dict = {}
        for name in self.ENTRY_POINTS:
            fn = getattr(kernels, name)
            self._orig[name] = fn
            setattr(kernels, name, self._wrap(fn))

    def set_active(self, active: bool) -> None:
        self.active = bool(active)

    def release(self) -> None:
        """Unblock every dispatch hung in `hang` mode (they raise on
        wake — by then the watchdog has already failed their futures,
        so the late error is discarded, not surfaced as a verdict)."""
        self._release.set()

    def detach(self) -> None:
        for name, fn in self._orig.items():
            setattr(self._kernels, name, fn)
        self._orig.clear()
        self._release.set()

    def injected_fault_counts(self) -> dict:
        return {self.label: self.injected}

    def _roll(self) -> float:
        import random

        return (self.rng or random).random()

    def _wrap(self, fn):
        def dispatch(*a, **kw):
            if not self.active:
                self.passed += 1
                return fn(*a, **kw)
            if self.mode == "flaky" and self._roll() >= self.p:
                self.passed += 1
                return fn(*a, **kw)
            self.injected += 1
            if self.mode == "hang":
                self._release.wait()
                raise InjectedDeviceError(
                    _DEVICE_ERROR_MESSAGES["device_lost"]
                    + " (released after hang)"
                )
            raise InjectedDeviceError(_DEVICE_ERROR_MESSAGES[self.kind])

        dispatch.__name__ = getattr(fn, "__name__", "dispatch")
        return dispatch


def device_hang() -> DeviceFaultInjector:
    """Every device dispatch hangs until release()/detach()."""
    return DeviceFaultInjector(mode="hang", label="device_hang")


def device_error(kind: str = "device_lost") -> DeviceFaultInjector:
    """Every device dispatch raises a `kind`-shaped runtime error."""
    return DeviceFaultInjector(
        mode="error", kind=kind, label="device_error"
    )


def device_oom() -> DeviceFaultInjector:
    """Every device dispatch raises RESOURCE_EXHAUSTED (the shrink-
    ladder-before-quarantine path)."""
    return DeviceFaultInjector(mode="error", kind="oom",
                               label="device_oom")


def device_flaky(p: float, rng=None,
                 kind: str = "device_lost") -> DeviceFaultInjector:
    """Each device dispatch fails with probability `p`."""
    return DeviceFaultInjector(mode="flaky", kind=kind, p=p, rng=rng,
                               label="device_flaky")


_EQUIVOCATION_GRAFFITI = b"equivocation".ljust(32, b"\x00")


async def propose_equivocation(node, graffiti: bytes | None = None):
    """Proposer equivocation: build, import, and publish a CONFLICTING
    sibling of the node's current head block — same slot, same
    proposer, same parent, different body. Returns the equivocating
    block's root, or None when this node does not hold the head
    proposer's key (or the head is the anchor)."""
    from ..params import (
        DOMAIN_BEACON_PROPOSER,
        DOMAIN_RANDAO,
        ForkSeq,
    )
    from ..ssz import uint64 as ssz_uint64
    from ..statetransition import util
    from ..statetransition.block import compute_signing_root, get_domain
    from ..statetransition.slot import process_slots
    from ..chain.chain import _clone
    from ..crypto.bls.signature import sign

    chain = node.chain
    signed = chain.get_block(chain.head_root)
    if signed is None:
        return None
    block = getattr(signed, "message", signed)
    slot = int(block.slot)
    parent = chain.get_or_regen_state(bytes(block.parent_root))
    if parent is None:
        return None
    work = _clone(parent, node.types)
    process_slots(node.cfg, work, slot, node.types)
    st = work.state
    proposer = util.get_beacon_proposer_index(
        st, electra=work.fork_seq >= ForkSeq.electra
    )
    if proposer not in node.keys:
        return None
    epoch = util.get_current_epoch(st)
    randao = sign(
        node.keys[proposer],
        compute_signing_root(
            ssz_uint64, epoch, get_domain(node.cfg, st, DOMAIN_RANDAO)
        ),
    )
    evil, post = chain.produce_block(
        slot,
        randao,
        graffiti=(graffiti or _EQUIVOCATION_GRAFFITI)[:32].ljust(
            32, b"\x00"
        ),
        work=work,
    )
    ns = node.types.by_fork[post.fork]
    signed_evil = ns.SignedBeaconBlock.default()
    signed_evil.message = evil
    domain = get_domain(node.cfg, post.state, DOMAIN_BEACON_PROPOSER)
    root = compute_signing_root(ns.BeaconBlock, evil, domain)
    signed_evil.signature = sign(node.keys[proposer], root)
    await chain.process_block(signed_evil, is_timely=False)
    await node.network.publish_block(post.fork, signed_evil)
    return ns.BeaconBlock.hash_tree_root(evil)


async def republish_head_block(node, times: int = 3) -> int:
    """Duplicate-block flood: re-publish the node's current head block
    `times` times. Peers' gossip seen-cache must absorb every copy
    (GossipNode.duplicates_received counts the containment)."""
    chain = node.chain
    signed = chain.get_block(chain.head_root)
    view = chain.get_state(chain.head_root)
    if signed is None or view is None:
        return 0
    for _ in range(times):
        await node.network.publish_block(view.fork, signed)
    return times


async def kill_node(sim, index: int) -> None:
    """Take a node off the network mid-run (process kill analog: its
    chain state survives, its sockets don't, its duties stop)."""
    node = sim.nodes[index]
    node.alive = False
    await node.network.stop()


async def restart_node(sim, index: int, resync_from: int | None = None
                       ) -> int:
    """Bring a killed node back: restart its network, reconnect the
    mesh, and catch its chain up from a healthy peer's canonical chain
    (the range-sync step, collapsed to direct imports since both nodes
    live in this process). Returns the number of blocks imported
    during catch-up (0 when no resync peer was given), also stored on
    the node as `caught_up_blocks` for scenario SLOs."""
    node = sim.nodes[index]
    node.alive = True
    await node.network.start()
    for i, other in enumerate(sim.nodes):
        if i == index:
            continue
        try:
            await node.network.connect(
                "127.0.0.1", other.network.host.port
            )
        except Exception:
            pass
    imported = 0
    if resync_from is not None:
        imported = await catch_up(node, sim.nodes[resync_from])
    node.caught_up_blocks = imported
    await asyncio.sleep(0.05)
    return imported


async def catch_up(node, healthy) -> int:
    """Import the healthy node's canonical blocks that `node` missed,
    oldest first (BeaconBlocksByRange over an in-process shortcut).
    Returns the number of blocks actually imported.

    Blocks `node` already holds are skipped without touching the
    import path; an unknown-parent failure before anything imported is
    the pre-anchor case (the healthy chain extends past this node's
    anchor) and ends the walk the same way checkpoint sync would. ANY
    other import failure re-raises — a node that cannot catch up must
    look failed, not caught-up."""
    from ..chain.chain import ChainError

    chain = healthy.chain
    blocks = []
    root = chain.head_root
    proto = chain.fork_choice.proto
    while root is not None:
        if node.chain.get_block(root) is not None:
            break  # shared history reached
        blk = chain.get_block(root)
        if blk is None:
            break
        blocks.append((root, blk))
        n = proto.get_node(root)
        if n is None or n.parent_root is None:
            break
        root = bytes(n.parent_root)
    imported = 0
    for root, blk in reversed(blocks):
        if node.chain.get_block(root) is not None:
            continue  # raced in via gossip while we walked
        try:
            await node.chain.process_block(blk, is_timely=False)
        except ChainError as e:
            if imported == 0 and "unknown parent" in str(e):
                # pre-anchor: nothing imported yet and the oldest
                # missing block's parent predates this node's anchor
                continue
            raise
        imported += 1
    return imported


class FaultSchedule:
    """Slot-scheduled fault windows for a Simulation: register
    (start_slot, end_slot, on_enter, on_exit) windows; tick() rides
    sim.on_slot_hooks."""

    def __init__(self, sim):
        self.sim = sim
        self.windows: list[dict] = []
        sim.on_slot_hooks.append(self.tick)

    def window(self, start_slot: int, end_slot: int, on_enter,
               on_exit=None) -> None:
        if end_slot < start_slot:
            # such a window would silently never enter — a scheduled
            # fault that never fires makes every downstream assertion
            # vacuous, so reject it at registration
            raise ValueError(
                f"fault window end_slot {end_slot} < start_slot "
                f"{start_slot} would never activate"
            )
        self.windows.append(
            {
                "start": start_slot,
                "end": end_slot,
                "enter": on_enter,
                "exit": on_exit,
                "active": False,
            }
        )

    def tick(self, slot: int):
        coros = []
        for w in self.windows:
            if not w["active"] and w["start"] <= slot <= w["end"]:
                w["active"] = True
                got = w["enter"]()
                if asyncio.iscoroutine(got):
                    coros.append(got)
            elif w["active"] and slot > w["end"]:
                w["active"] = False
                if w["exit"] is not None:
                    got = w["exit"]()
                    if asyncio.iscoroutine(got):
                        coros.append(got)
        if not coros:
            return None

        async def run():
            # every window's hook runs even when an earlier one fails
            # (an exit hook must still detach its injector if another
            # window's enter hook blew up mid-tick); failures surface
            # after the full sweep
            errors = []
            for c in coros:
                try:
                    await c
                except Exception as e:
                    errors.append(e)
            if errors:
                if len(errors) == 1:
                    raise errors[0]
                raise RuntimeError(
                    f"{len(errors)} fault window hooks failed: "
                    + "; ".join(repr(e) for e in errors)
                ) from errors[0]

        return run()


class FaultRegistry:
    """Delivered-fault accounting across every injector in a scenario.

    Injectors expose `injected_fault_counts() -> {kind: n}`
    (GossipFaultInjector, FlakyEngine, FlakyRelay, LateBlockReplayer);
    scripted faults without a wrapper object (equivocation, restarts)
    record through `record()`. Scenario SLOs call `assert_fired` so a
    run whose fault never actually fired FAILS instead of passing
    vacuously; `bind_sim_fault_collectors` exports the same view as
    `lodestar_sim_injected_faults_total{kind}`."""

    def __init__(self):
        self._injectors: list = []
        self._manual: dict[str, int] = {}

    def track(self, injector):
        """Register an injector; returned unchanged for inline use:
        `inj = registry.track(GossipFaultInjector(...))`."""
        self._injectors.append(injector)
        return injector

    def record(self, kind: str, n: int = 1) -> None:
        self._manual[kind] = self._manual.get(kind, 0) + int(n)

    def counts(self) -> dict[str, int]:
        out = dict(self._manual)
        for inj in self._injectors:
            for kind, n in inj.injected_fault_counts().items():
                out[kind] = out.get(kind, 0) + int(n)
        return out

    def assert_fired(self, *kinds: str) -> None:
        counts = self.counts()
        missing = [k for k in kinds if counts.get(k, 0) <= 0]
        assert not missing, (
            f"scheduled faults never fired: {missing} "
            f"(delivered counts: {counts})"
        )


def bind_sim_fault_collectors(metrics, registry: FaultRegistry) -> None:
    """Wire the m.sim namespace (metrics/beacon.py) to sample the
    registry's delivered-fault counts at scrape time."""

    def _sample(g):
        for kind, n in registry.counts().items():
            g.set(n, kind=kind)

    metrics.injected_faults_total.add_collect(_sample)

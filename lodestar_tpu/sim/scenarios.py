"""Scenario fleet: mainnet-shaped adversity with pass/fail SLO contracts.

Reference analog: crucible's multi-client sim matrix plus the ops
runbook regimes every mainnet node eventually meets. Each scenario is
a NAMED, DETERMINISTIC (seeded rng, bounded slot counts) adversity
run with an explicit SLO contract evaluated from real telemetry
surfaces — sim/assertions.py evaluators over chain state, the
block-import trace ring (metrics/tracing.py), the device executor's
shed ledger (lodestar_device_sheds_total operands), gossip
seen-cache counters, and the drift monitor's re-tune ledger — never
ad-hoc asserts sprinkled through the run.

Contract shape: a scenario records `SloResult` rows through its
`ScenarioContext`; `run_scenario` wraps the run into a
`ScenarioResult` whose `passed` is the conjunction. Every scenario
also asserts its faults actually FIRED (sim/faults.FaultRegistry) —
a fault window that never delivered makes every downstream SLO
vacuous, so delivery itself is an SLO row.

Regimes (SCENARIOS registry, also tabulated in SCENARIOS.md):

* sustained_nonfinality — attestation-gossip blackout stalls
  justification for whole epochs while blocks keep flowing; memory
  surfaces (op pools, state caches) must stay bounded and finality
  must resume promptly once attestations return.
* reorg_storm — a node's block publications arrive late so peers
  attest to the stale head; the network must re-converge within a
  bounded number of slots and propose cleanly afterwards.
* equivocation_flood — a faulty proposer emits a conflicting sibling
  of its own head plus a duplicate-block flood; peers' seen-caches
  absorb the copies, imports stay under the stage budget, and the
  honest chain keeps finalizing.
* mainnet_gossip_burst — an attestation firehose through the
  NetworkProcessor while the verifier briefly refuses work; every
  verdict future resolves, the deadline-class p99 stays bounded, and
  sheds land only on the bounded backpressure classes.
* blob_firehose_under_load — the PR-17 contention contract at the
  device executor: bulk blob work overflows its queue bound while
  deadline verdicts keep flowing; every shed is counted and fed a
  host fallback (never silent), deadline work preempts bulk, AND the
  cross-regime invariant: the drift monitor trips mid-incident but
  the autotuner HOLDS STILL (retunes_blocked grows, applied config
  unchanged) until the device quiesces.
* checkpoint_thundering_herd — most of the network restarts and
  catches up at once; catch-up completes (caught_up_blocks matches
  what was missed), the surviving node's duties never stop, and
  finality resumes.
* device_loss_under_load — the ISSUE-19 fault drill: a mid-wave
  device hang trips the wave watchdog, quarantines the device, and
  the remaining gossip fails over to the host path bit-identically;
  the autotuner freezes while quarantined, and known-answer probes
  reinstate the device live (warmup re-kicked).
* lightclient_flood — the ISSUE-20 serving drill: a light-client
  read flood + SSE subscriber swarm hits the REST tier while the
  chain keeps importing; duty-class p99 holds near its quiet
  baseline, every shed is a typed 429/503 + Retry-After on the
  cheap classes, the head-keyed cache absorbs the hot reads, and
  slow SSE consumers are evicted with their drops counted.

`tools/run_scenarios.py` is the operator CLI (runs the registry,
emits a provenance-stamped SCENARIOS.json); tests/test_scenarios.py
pins every smoke profile green and slow-marks the full profiles for
tier 2.
"""

from __future__ import annotations

import asyncio
import random
import time
import traceback
from dataclasses import dataclass, field

from ..params import preset

FAR = 2**64 - 1

_TYPES = None


def _types():
    """Process-cached ssz types: scenarios in one run share the
    (expensive) type build just like the test suite's module fixture."""
    global _TYPES
    if _TYPES is None:
        from ..types import ssz_types

        _TYPES = ssz_types()
    return _TYPES


def _cfg(**forks):
    from ..config.chain_config import ChainConfig

    base = dict(
        ALTAIR_FORK_EPOCH=FAR,
        BELLATRIX_FORK_EPOCH=FAR,
        CAPELLA_FORK_EPOCH=FAR,
        DENEB_FORK_EPOCH=FAR,
        ELECTRA_FORK_EPOCH=FAR,
        SHARD_COMMITTEE_PERIOD=0,
    )
    base.update(forks)
    return ChainConfig(**base)


# ---------------------------------------------------------------------------
# SLO records + scenario engine
# ---------------------------------------------------------------------------


@dataclass
class SloResult:
    """One machine-evaluated pass/fail row of a scenario's contract."""

    name: str
    passed: bool
    observed: object
    threshold: object
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "passed": bool(self.passed),
            "observed": repr(self.observed),
            "threshold": repr(self.threshold),
            "detail": self.detail,
        }


@dataclass
class ScenarioResult:
    name: str
    profile: str
    seed: int
    slos: list = field(default_factory=list)
    faults_injected: dict = field(default_factory=dict)
    duration_s: float = 0.0
    error: str | None = None

    @property
    def passed(self) -> bool:
        return self.error is None and all(s.passed for s in self.slos)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "profile": self.profile,
            "seed": self.seed,
            "passed": self.passed,
            "slos": [s.to_dict() for s in self.slos],
            "faults_injected": dict(self.faults_injected),
            "duration_s": round(self.duration_s, 3),
            "error": self.error,
        }

    def summary(self) -> str:
        mark = "PASS" if self.passed else "FAIL"
        lines = [
            f"{mark} {self.name} [{self.profile}] "
            f"({self.duration_s:.1f}s, "
            f"faults={self.faults_injected})"
        ]
        for s in self.slos:
            lines.append(
                f"  {'ok  ' if s.passed else 'FAIL'} {s.name}: "
                f"observed={s.observed!r} want={s.threshold!r}"
            )
        if self.error:
            lines.append(f"  ERROR {self.error.splitlines()[-1]}")
        return "\n".join(lines)


class ScenarioContext:
    """Per-run state a scenario body writes its contract through."""

    def __init__(self, profile: str, seed: int):
        from .faults import FaultRegistry

        self.profile = profile
        self.seed = seed
        self.rng = random.Random(seed)
        self.registry = FaultRegistry()
        self.slos: list[SloResult] = []

    @property
    def smoke(self) -> bool:
        return self.profile == "smoke"

    def slo(self, name, passed, observed, threshold, detail="") -> bool:
        self.slos.append(
            SloResult(name, bool(passed), observed, threshold, detail)
        )
        return bool(passed)

    def slo_le(self, name, observed, bound, detail="") -> bool:
        return self.slo(name, observed <= bound, observed,
                        f"<= {bound}", detail)

    def slo_ge(self, name, observed, bound, detail="") -> bool:
        return self.slo(name, observed >= bound, observed,
                        f">= {bound}", detail)

    def slo_true(self, name, observed, detail="") -> bool:
        return self.slo(name, bool(observed), observed, True, detail)

    def slo_faults_fired(self, *kinds: str) -> None:
        """Delivery-is-an-SLO: each scheduled fault kind must have a
        positive delivered count in the registry."""
        counts = self.registry.counts()
        for kind in kinds:
            self.slo_ge(f"fault_fired:{kind}", counts.get(kind, 0), 1,
                        "a fault that never fired makes the run vacuous")


@dataclass
class ScenarioSpec:
    name: str
    fn: object
    summary: str
    faults: tuple
    slo_names: tuple


SCENARIOS: dict[str, ScenarioSpec] = {}


def scenario(name: str, summary: str, faults=(), slos=()):
    def deco(fn):
        SCENARIOS[name] = ScenarioSpec(
            name=name, fn=fn, summary=summary, faults=tuple(faults),
            slo_names=tuple(slos),
        )
        return fn

    return deco


def run_scenario(name: str, profile: str = "smoke",
                 seed: int = 20260807) -> ScenarioResult:
    """Run one registered scenario to a ScenarioResult. Never raises
    for an SLO miss (that's a failed row); scenario-body crashes land
    in `error` with the traceback."""
    if name not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; registered: "
            f"{sorted(SCENARIOS)}"
        )
    if profile not in ("smoke", "full"):
        raise ValueError(f"profile must be smoke|full, got {profile!r}")
    spec = SCENARIOS[name]
    ctx = ScenarioContext(profile, seed)
    t0 = time.monotonic()
    error = None
    try:
        asyncio.run(spec.fn(ctx))
    except Exception:
        error = traceback.format_exc()
    return ScenarioResult(
        name=name,
        profile=profile,
        seed=seed,
        slos=ctx.slos,
        faults_injected=ctx.registry.counts(),
        duration_s=time.monotonic() - t0,
        error=error,
    )


def run_all(profile: str = "smoke", seed: int = 20260807,
            only=None) -> list[ScenarioResult]:
    names = list(SCENARIOS)
    if only:
        unknown = [n for n in only if n not in SCENARIOS]
        if unknown:
            raise KeyError(
                f"unknown scenario(s) {unknown}; registered: "
                f"{sorted(SCENARIOS)}"
            )
        names = [n for n in names if n in set(only)]
    return [run_scenario(n, profile=profile, seed=seed) for n in names]


# ---------------------------------------------------------------------------
# regime 1: sustained non-finality
# ---------------------------------------------------------------------------


@scenario(
    "sustained_nonfinality",
    "attestation-gossip blackout stalls finality for whole epochs; "
    "memory stays bounded and finality resumes on recovery",
    faults=("gossip_drop",),
    slos=("finality_frozen_during_outage", "op_pool_bounded",
          "state_caches_bounded", "blocks_flow_during_outage",
          "finality_resumes", "heads_consistent"),
)
async def sustained_nonfinality(ctx: ScenarioContext) -> None:
    from . import assertions as A
    from .faults import FaultSchedule, GossipFaultInjector
    from .simulation import Simulation
    from ..chain.chain import MAX_CACHED_BLOCKS, MAX_CACHED_STATES

    spe = preset().SLOTS_PER_EPOCH
    outage_epochs = 1 if ctx.smoke else 3
    sim = Simulation(_cfg(), _types(), n_nodes=2, n_validators=16)
    await sim.start()
    try:
        sched = FaultSchedule(sim)
        injectors: list = []
        start = spe + 1  # one healthy epoch first
        end = start + outage_epochs * spe - 1

        def enter():
            # both nodes lose attestation gossip only: each proposer
            # pools just its own partial attestations (~50% of stake)
            # so justification stalls while blocks still flow
            for node in sim.nodes:
                injectors.append(ctx.registry.track(GossipFaultInjector(
                    node.network.gossip, rng=ctx.rng, drop=1.0,
                    topics=("beacon_attestation",),
                )))

        def exit_():
            for inj in injectors:
                inj.detach()

        sched.window(start, end, enter, exit_)
        await sim.run_until_slot(start - 1)
        fin_before = max(A.finalized_epochs(sim).values())
        await sim.run_until_slot(end)

        fin_during = A.finalized_epochs(sim)
        ctx.slo(
            "finality_frozen_during_outage",
            max(fin_during.values()) <= fin_before + 1,
            fin_during,
            f"<= {fin_before + 1}",
            "at most one in-flight justification may land after the "
            "blackout starts; more means the regime never took hold",
        )
        # the memory contract: pools prune on the SLOT clock and the
        # state/block caches are hard-capped, so a finality stall
        # cannot grow either without bound
        ctx.slo_le("op_pool_bounded",
                   max(A.op_pool_sizes(sim).values()), 8 * spe,
                   "aggregated attestation pool prunes by slot, "
                   "not by finality")
        caches = A.state_cache_sizes(sim)
        ctx.slo(
            "state_caches_bounded",
            all(s <= MAX_CACHED_STATES and b <= MAX_CACHED_BLOCKS
                for s, b in caches.values()),
            caches,
            f"<= ({MAX_CACHED_STATES}, {MAX_CACHED_BLOCKS})",
        )
        missed = A.missed_slots(sim, start, end)
        ctx.slo(
            "blocks_flow_during_outage",
            all(len(m) <= outage_epochs for m in missed.values()),
            missed,
            f"<= {outage_epochs} missed per node",
            "non-finality must not stop block production",
        )

        recover_epochs = 2 if ctx.smoke else 3
        await sim.run_until_slot(end + recover_epochs * spe)
        fin_after = A.finalized_epochs(sim)
        ctx.slo(
            "finality_resumes",
            min(fin_after.values()) >= max(fin_during.values()) + 1,
            fin_after,
            f">= {max(fin_during.values()) + 1}",
            "two healthy epochs after the blackout must finalize",
        )
        ctx.slo_true("heads_consistent", A.heads_consistent(sim))
        ctx.slo_faults_fired("gossip_drop")
    finally:
        await sim.stop()


def _head_slot(node) -> int:
    """Slot of the node's head block; 0 while the head is still the
    (blockless) genesis anchor."""
    blk = node.chain.get_block(node.chain.head_root)
    if blk is None:
        return 0
    return int(getattr(blk, "message", blk).slot)


# ---------------------------------------------------------------------------
# regime 2: reorg storm
# ---------------------------------------------------------------------------


@scenario(
    "reorg_storm",
    "late-delivered blocks make peers attest to stale heads; the "
    "network re-converges within bounded slots and proposes cleanly",
    faults=("late_block",),
    slos=("head_reconvergence_slots", "no_missed_blocks_after_storm",
          "chain_advanced_through_storm"),
)
async def reorg_storm(ctx: ScenarioContext) -> None:
    from . import assertions as A
    from .faults import FaultSchedule, LateBlockReplayer
    from .simulation import Simulation

    spe = preset().SLOTS_PER_EPOCH
    storm_slots = 4 if ctx.smoke else 2 * spe
    sim = Simulation(_cfg(), _types(), n_nodes=2, n_validators=16)
    await sim.start()
    try:
        sched = FaultSchedule(sim)
        replayers: list = []
        start = spe + 1
        end = start + storm_slots - 1

        def enter():
            # every proposal arrives ~2 slots late at the peer: it has
            # already attested to the stale head, so competing forks
            # build up for the whole window
            for node in sim.nodes:
                replayers.append(ctx.registry.track(
                    LateBlockReplayer(node, delay_s=0.5)
                ))

        def exit_():
            for r in replayers:
                r.detach()

        sched.window(start, end, enter, exit_)
        await sim.run_until_slot(start - 1)
        head_before = max(_head_slot(n) for n in sim.nodes)
        await sim.run_until_slot(end)

        # convergence latency: run slot by slot until every alive
        # node reports one head (late blocks still in flight land
        # during the first extra slot)
        max_wait = 8 if ctx.smoke else 12
        converged_at = None
        for extra in range(1, max_wait + 1):
            await sim.run_slot()
            if A.heads_consistent(sim):
                converged_at = extra
                break
        ctx.slo(
            "head_reconvergence_slots",
            converged_at is not None and converged_at <= max_wait,
            converged_at,
            f"<= {max_wait} slots",
            "slots from storm end until every node reports one head",
        )

        # zero wrong-head proposals once converged: a proposer still
        # on a minority fork would orphan its own block and leave a
        # canonical gap
        mark = sim.slot
        await sim.run_until_slot(mark + spe)
        missing = A.missed_slots(sim, mark + 1)
        ctx.slo(
            "no_missed_blocks_after_storm",
            all(not m for m in missing.values()),
            missing,
            "no canonical gaps",
        )
        head_after = max(_head_slot(n) for n in sim.nodes)
        ctx.slo_ge("chain_advanced_through_storm",
                   head_after - head_before, storm_slots // 2,
                   "the storm may orphan blocks but must not halt "
                   "the chain")
        ctx.slo_faults_fired("late_block")
    finally:
        await sim.stop()


# ---------------------------------------------------------------------------
# regime 3: equivocation flood
# ---------------------------------------------------------------------------


@scenario(
    "equivocation_flood",
    "a faulty proposer emits conflicting siblings of its own head "
    "plus a duplicate-block flood; gossip absorbs it, imports stay "
    "under budget, the honest chain keeps finalizing",
    faults=("equivocating_block", "duplicate_block"),
    slos=("duplicates_absorbed_by_seen_cache", "import_under_budget",
          "heads_consistent", "finality_advances"),
)
async def equivocation_flood(ctx: ScenarioContext) -> None:
    from . import assertions as A
    from .faults import propose_equivocation, republish_head_block
    from .simulation import Simulation
    from ..metrics.tracing import Tracer

    spe = preset().SLOTS_PER_EPOCH
    flood_slots = 4 if ctx.smoke else 2 * spe
    sim = Simulation(_cfg(), _types(), n_nodes=2, n_validators=16)
    await sim.start()
    try:
        # slow_ms=0: EVERY import trace lands in the ring buffer, so
        # the budget SLO reads real per-import telemetry
        for node in sim.nodes:
            node.chain.tracer = Tracer(slow_ms=0.0, buffer_size=512)
        start = spe + 1
        end = start + flood_slots - 1

        async def flood(slot: int):
            # sibling of the previous slot's block: whichever node
            # holds that proposer's key equivocates against itself
            for node in sim.nodes:
                root = await propose_equivocation(node)
                if root is not None:
                    ctx.registry.record("equivocating_block")
                    break
            n = await republish_head_block(
                sim.nodes[slot % len(sim.nodes)], times=3
            )
            ctx.registry.record("duplicate_block", n)

        def hook(slot: int):
            if start <= slot <= end:
                return flood(slot)
            return None

        sim.on_slot_hooks.append(hook)
        # flood, then calm slots; end past the FOURTH epoch boundary.
        # phase0 finality needs two consecutive justified epochs, and
        # the flood forks split attestations across siblings for the
        # whole flood epoch — that epoch routinely misses
        # justification, so the first finalizable pair is the two
        # clean epochs after it (finalized lands at the next boundary)
        await sim.run_until_slot(max(end + 2 * spe, 4 * spe + 1))

        dups = sum(
            n.network.gossip.duplicates_received for n in sim.nodes
        )
        ctx.slo_ge(
            "duplicates_absorbed_by_seen_cache", dups, 1,
            "republished blocks must be counted (and dropped) by the "
            "peers' gossip seen-cache, not re-imported",
        )
        worst_ms = max(A.max_import_ms(n) for n in sim.nodes)
        ctx.slo_le(
            "import_under_budget", round(worst_ms, 1), 8000.0,
            "equivocating siblings are full imports and must not "
            "stall the import path (bound sized for the pure-python "
            "CPU sim: epoch-boundary imports run whole-state "
            "transitions; a flood-induced stall would blow far past "
            "it)",
        )
        ctx.slo_true("heads_consistent", A.heads_consistent(sim))
        fin = A.finalized_epochs(sim)
        ctx.slo_ge("finality_advances", min(fin.values()), 1,
                   "the honest chain outweighs the equivocator")
        ctx.slo_faults_fired("equivocating_block", "duplicate_block")
    finally:
        await sim.stop()


# ---------------------------------------------------------------------------
# regime 4: mainnet-rate gossip burst
# ---------------------------------------------------------------------------


class _GatedVerifier:
    """Backpressure shim for the burst scenario: the processor's
    can_accept_work gate flips to False for the incident phase, then
    reopens. Everything else proxies to the real verifier."""

    def __init__(self, inner):
        self._inner = inner
        self.accepting = True

    def can_accept_work(self) -> bool:
        if not self.accepting:
            return False
        probe = getattr(self._inner, "can_accept_work", None)
        return probe is None or bool(probe())

    def __getattr__(self, name):
        return getattr(self._inner, name)


@scenario(
    "mainnet_gossip_burst",
    "an attestation firehose through the NetworkProcessor while the "
    "verifier refuses work mid-burst; every verdict resolves, p99 "
    "stays bounded, sheds land only on bounded classes",
    faults=("gossip_burst", "verifier_stall"),
    slos=("all_verdicts_resolved", "verdict_p99_bounded",
          "no_rejects", "sheds_only_bounded_classes"),
)
async def mainnet_gossip_burst(ctx: ScenarioContext) -> None:
    from ..chain import DevNode
    from ..chain.validation import AttestationValidator, GossipAction
    from ..device.executor import DeviceExecutor
    from ..network import NetworkProcessor

    cfg = _cfg()
    types = _types()
    node = DevNode(cfg, types, 32, verify_attestations=False)
    executor = DeviceExecutor()
    loop = asyncio.get_running_loop()
    try:
        await node.run_until(2)
        validator = AttestationValidator(
            cfg, types, node.chain, node.chain.verifier
        )
        validator.on_slot(node.slot)
        gate = _GatedVerifier(node.chain.verifier)
        proc = NetworkProcessor(
            node.chain, validator, gate, executor=executor
        )
        proc.start()

        atts = _burst_attestations(node, types, node.slot)
        n_unique = len(atts)
        copies = 40 if ctx.smoke else 150
        stall_s = 0.25 if ctx.smoke else 0.6

        # incident: the verifier refuses work while the firehose
        # lands — the pump must defer (bounded shed classes), never
        # drop a verdict on the floor
        gate.accepting = False
        ctx.registry.record("verifier_stall")
        latencies: list[float] = []
        futs = []
        n_sent = 0
        for i in range(copies):
            for att in atts:
                fut = proc.on_gossip_attestation(att)
                t0 = loop.time()
                fut.add_done_callback(
                    lambda f, t0=t0: latencies.append(loop.time() - t0)
                )
                futs.append(fut)
                n_sent += 1
        ctx.registry.record("gossip_burst", n_sent)
        await asyncio.sleep(stall_s)
        gate.accepting = True
        results = await asyncio.gather(*futs)
        await proc.drain()
        await proc.stop()

        resolved = sum(1 for r in results if r is not None)
        ctx.slo(
            "all_verdicts_resolved",
            resolved == n_sent and len(latencies) == n_sent,
            resolved, n_sent,
            "every gossip verdict future must resolve, burst or not",
        )
        p99 = _quantile(latencies, 0.99)
        ctx.slo_le(
            "verdict_p99_bounded", round(p99, 3), stall_s + 2.0,
            "p99 verdict latency across the burst, including the "
            "stall the backpressure gate imposed",
        )
        rejects = sum(1 for r in results if r == GossipAction.REJECT)
        ctx.slo(
            "no_rejects",
            rejects == 0 and proc.accepted >= n_unique,
            {"rejected": rejects, "accepted": proc.accepted,
             "ignored": proc.ignored, "dropped": proc.dropped},
            f"0 rejects, >= {n_unique} accepted",
            "duplicates dedupe to IGNORE; nothing mis-classifies",
        )
        allowed = {("deadline", "work_queue_backpressure"),
                   ("deadline", "att_queue_overflow")}
        sheds = executor.shed_counts()
        ctx.slo(
            "sheds_only_bounded_classes",
            sum(sheds.values()) > 0 and set(sheds) <= allowed,
            dict(sheds),
            f"non-empty subset of {sorted(allowed)}",
            "the stall must surface as accounted deadline-class "
            "deferrals, nowhere else",
        )
        ctx.slo_faults_fired("gossip_burst", "verifier_stall")
    finally:
        executor.close()
        await node.close()


def _burst_attestations(node, types, slot):
    """All committee validators of `slot` as single-bit signed gossip
    attestations on the current head (the mainnet firehose shape)."""
    from ..chain.devnode import DOMAIN_BEACON_ATTESTER
    from ..crypto.bls.signature import sign
    from ..statetransition import util
    from ..statetransition.block import compute_signing_root, get_domain

    head_root = node.chain.head_root
    st = node.chain.get_state(head_root).state
    epoch = util.compute_epoch_at_slot(slot)
    sh = util.EpochShuffling(st, epoch)
    try:
        target_root = util.get_block_root(st, epoch)
    except ValueError:
        target_root = head_root
    out = []
    for ci, committee in enumerate(sh.committees_at_slot(slot)):
        if not len(committee):
            continue
        data = types.AttestationData.default()
        data.slot = slot
        data.index = ci
        data.beacon_block_root = head_root
        data.source = st.current_justified_checkpoint
        tgt = types.Checkpoint.default()
        tgt.epoch = epoch
        tgt.root = target_root
        data.target = tgt
        domain = get_domain(node.cfg, st, DOMAIN_BEACON_ATTESTER, epoch)
        root = compute_signing_root(types.AttestationData, data, domain)
        for pos, v in enumerate(committee):
            att = types.Attestation.default()
            att.data = data
            bits = [False] * len(committee)
            bits[pos] = True
            att.aggregation_bits = bits
            att.signature = sign(node.sks[int(v)], root)
            out.append(att)
    return out


def _quantile(values, q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[idx]


# ---------------------------------------------------------------------------
# regime 5: blob firehose under gossip load (+ the autotuner-holds-
# still cross-regime invariant)
# ---------------------------------------------------------------------------


@scenario(
    "blob_firehose_under_load",
    "bulk blob work overflows its executor bound while deadline "
    "verdicts flow: sheds counted + host fallbacks (never silent), "
    "deadline preempts bulk, and the drift monitor defers re-tunes "
    "until the device quiesces",
    faults=("bulk_overload", "drift_signal"),
    slos=("sheds_counted_never_silent", "deadline_preempts_bulk",
          "deadline_never_shed", "deadline_p99_bounded",
          "autotuner_holds_still", "config_unchanged_mid_incident",
          "retune_lands_after_quiesce"),
)
async def blob_firehose_under_load(ctx: ScenarioContext) -> None:
    from types import SimpleNamespace

    from ..bls import kernels as K
    from ..device import autotune as AT
    from ..device.executor import DeviceExecutor
    from ..ops import limbs as L
    from ..ops import msm as M

    # the re-tune at the end drives the REAL knob setters — snapshot
    # and restore so a scenario run leaves the process untouched
    # (the same discipline as test_autotune's _restore_knobs)
    gate = K.INGEST_MIN_BUCKET
    ladder = K.BUCKET_LADDER
    warm = set(K._INGEST_WARM)
    started = K._WARMUP_STARTED
    backend = L.get_backend()
    applied = AT._APPLIED
    window = M.msm_window()

    executor = DeviceExecutor(
        queue_bounds={"bulk": 8}, drain_timeout_s=0.15
    )
    try:
        deadline_busy = {"flag": True}
        executor.register_deadline_probe(lambda: deadline_busy["flag"])

        quiet_log = SimpleNamespace(
            info=lambda *a, **k: None, warn=lambda *a, **k: None
        )
        bench = lambda backend, bucket: AT.Measurement(
            backend=backend, bucket=bucket, pipeline="batch",
            seconds_per_dispatch=bucket / 400.0, sets_per_sec=400.0,
            runs=3, warm_seconds=0.0,
        )
        verifier = _KnobVerifier()
        tuner = AT.DeviceAutotuner(
            verifier=verifier, grid=AT.parse_grid("backend=vpu"),
            bench=bench, artifact_path=None, logger=quiet_log,
        )
        tel = _StageTelemetry()
        mon = AT.DriftMonitor(
            tuner, tel, verifier=verifier, windows=2, cooldown_s=0.0,
            executor=executor,
        )
        tel.add_window(dict(AT.budget_shares()))
        mon.sample()  # baseline window

        n_bulk = 60 if ctx.smoke else 300
        submitted = 0
        fallbacks = 0
        deadline_done: list[float] = []
        deadline_futs = []

        def bulk_blob_job():
            time.sleep(0.002)
            return "device"

        # firehose: bulk blob jobs slam the bounded lane while a
        # trickle of deadline verdicts keeps the device "mid-wave"
        # (the deadline probe holds the incident open)
        for i in range(n_bulk):
            fut = executor.submit("bulk", bulk_blob_job)
            if fut is None:
                # the PR-17 contract: a shed bulk job falls back to
                # the host tier, counted — never silently dropped
                fallbacks += 1
            else:
                submitted += 1
            if i % 4 == 0:
                t0 = time.monotonic()
                df = executor.submit("deadline", lambda: "verdict")
                if df is not None:
                    df.add_done_callback(
                        lambda f, t0=t0: deadline_done.append(
                            time.monotonic() - t0
                        )
                    )
                    deadline_futs.append(df)
            if i in (5, 10, 15):
                # drift windows sampled MID-INCIDENT: the pairing
                # stage departs its budget share past the threshold
                tel.add_window(_drifted_shares(AT))
                mon.sample()
            if i % 16 == 0:
                await asyncio.sleep(0)
        ctx.registry.record("bulk_overload", fallbacks)
        ctx.registry.record("drift_signal", 1)

        # cross-regime invariant: the monitor HAS a pending re-tune
        # but the device is mid-incident — the autotuner must hold
        # still (blocked + counted), with the applied config frozen
        pending = mon.pending_stage
        cfg_before = (K.ingest_min_bucket(), K.ladder_top(),
                      L.get_backend(), M.msm_window())
        fired = mon.maybe_retune()
        cfg_after = (K.ingest_min_bucket(), K.ladder_top(),
                     L.get_backend(), M.msm_window())
        ctx.slo(
            "autotuner_holds_still",
            pending is not None and fired is False
            and mon.retunes_blocked >= 1 and mon.retunes == 0,
            {"pending_stage": pending, "fired": fired,
             "retunes_blocked": mon.retunes_blocked,
             "retunes": mon.retunes},
            "pending re-tune deferred while the device is busy",
        )
        ctx.slo(
            "config_unchanged_mid_incident",
            cfg_before == cfg_after,
            {"before": cfg_before, "after": cfg_after},
            "knobs frozen mid-incident",
        )

        # incident ends: deadline lane quiets, bulk drains
        deadline_busy["flag"] = False
        end_by = time.monotonic() + 10.0
        while time.monotonic() < end_by:
            if (all(v == 0 for v in executor.queue_depths().values())
                    and all(f.done() for f in deadline_futs)):
                break
            await asyncio.sleep(0.01)

        sheds = executor.shed_counts()
        bulk_shed = sheds.get(("bulk", "queue_full"), 0)
        ctx.slo(
            "sheds_counted_never_silent",
            fallbacks > 0 and bulk_shed == fallbacks
            and submitted + fallbacks == n_bulk,
            {"fallbacks": fallbacks, "ledger": bulk_shed,
             "submitted": submitted},
            "every overflow is in the shed ledger AND ran a host "
            "fallback",
        )
        ctx.slo_ge(
            "deadline_preempts_bulk", executor.deadline_deferrals, 1,
            "bulk work deferred while deadline verdicts were due",
        )
        deadline_shed = [k for k in sheds if k[0] == "deadline"]
        ctx.slo(
            "deadline_never_shed", not deadline_shed, deadline_shed,
            "[]", "the deadline lane is never load-shed by bulk "
            "pressure",
        )
        ctx.slo_le(
            "deadline_p99_bounded",
            round(_quantile(deadline_done, 0.99), 3), 2.0,
            "deadline verdict turnaround under the blob firehose",
        )

        # quiesced: the SAME pending drift trigger must now land
        fired = mon.maybe_retune()
        ctx.slo(
            "retune_lands_after_quiesce",
            fired is True and mon.retunes == 1,
            {"fired": fired, "retunes": mon.retunes,
             "blocked": mon.retunes_blocked},
            "deferred re-tune fires once the device quiesces",
        )
        ctx.slo_faults_fired("bulk_overload", "drift_signal")
    finally:
        executor.close()
        K.INGEST_MIN_BUCKET = gate
        K.BUCKET_LADDER = ladder
        K._INGEST_WARM.clear()
        K._INGEST_WARM.update(warm)
        K._WARMUP_STARTED = started
        if L.get_backend() != backend:
            L.set_backend(backend)
        AT._APPLIED = applied
        M.set_msm_window(window)


class _KnobVerifier:
    """Verifier-shaped knob sink for the firehose scenario's tuner:
    accepts the real setters without owning a device pipeline (the
    executor, not the verifier, models busyness here)."""

    def __init__(self):
        self.budget_ms = 50.0
        self.depth = 0

    def set_latency_budget_ms(self, ms):
        self.budget_ms = ms

    def latency_budget_ms(self):
        return self.budget_ms

    def can_accept_work(self):
        return True

    def is_quiescent(self):
        return True

    def pipeline_depth(self):
        return self.depth

    def set_pipeline_depth(self, depth):
        self.depth = depth


class _StageTelemetry:
    """Cumulative per-stage device seconds in the snapshot shape the
    drift monitor consumes (telemetry.snapshot_stage_seconds)."""

    def __init__(self):
        self.dev: dict[str, float] = {}

    def snapshot_stage_seconds(self):
        return {}, dict(self.dev)

    def add_window(self, shares: dict, total_s: float = 1.0) -> None:
        for s, share in shares.items():
            self.dev[s] = self.dev.get(s, 0.0) + share * total_s


def _drifted_shares(AT, stage: str = "pairing", delta: float = 0.16):
    """One drift window: `stage` departs its budget share by +delta
    (past the 0.15 threshold); the loss spreads over the other stages
    capped below threshold so only `stage` trips the monitor."""
    shares = dict(AT.budget_shares())
    shares[stage] += delta
    remaining = delta
    for s in sorted((k for k in shares if k != stage),
                    key=lambda k: -shares[k]):
        give = min(0.13, shares[s], remaining)
        shares[s] -= give
        remaining -= give
    return shares


# ---------------------------------------------------------------------------
# regime 5b: device loss under live gossip load (the device fault
# domain end-to-end: watchdog -> taxonomy -> quarantine -> host
# failover -> probe reinstatement)
# ---------------------------------------------------------------------------


@scenario(
    "device_loss_under_load",
    "every device dispatch hangs mid-gossip: the wave watchdog trips, "
    "verdicts ride the bit-identical host oracle (zero lost, zero "
    "wrong), the autotuner freezes while QUARANTINED, and a "
    "known-answer probe sequence reinstates the device path",
    faults=("device_hang",),
    slos=("verdicts_none_lost", "verdicts_bit_identical",
          "watchdog_tripped", "device_quarantined",
          "failover_served_gossip", "failover_p99_bounded",
          "autotuner_frozen_while_quarantined",
          "probe_reinstates_device"),
)
async def device_loss_under_load(ctx: ScenarioContext) -> None:
    from types import SimpleNamespace

    from ..bls import SignatureSet, kernels as K
    from ..bls.verifier import TpuBlsVerifier
    from ..crypto.bls import signature as sig
    from ..device import autotune as AT
    from ..device.health import DeviceHealthTracker, HealthState
    from ..resilience.clock import ManualClock
    from .faults import device_hang

    def mk_sets(tag: int, n: int = 2, good: bool = True):
        out = []
        for i in range(n):
            sk = 4200 + tag * 8 + i
            msg = bytes([tag, i]) + b"\x00" * 30
            s = sig.sign(sk, msg)
            if not good and i == n - 1:
                b = bytearray(s)
                b[20] ^= 0xFF
                s = bytes(b)
            out.append(SignatureSet(sig.sk_to_pk(sk), msg, s))
        return out

    n_calls = 8 if ctx.smoke else 16
    bad_call = 3  # one tampered job proves failover verdicts can say NO
    calls = [
        (mk_sets(t, good=(t != bad_call)), t != bad_call)
        for t in range(n_calls)
    ]

    clock = ManualClock()
    kicked: list[int] = []
    tracker = DeviceHealthTracker(
        name="scenario-device",
        clock=clock,
        failure_threshold=2,
        quarantine_reset_s=0.05,
        probe_successes=2,
        ladder_shrink=lambda: False,  # no OOM here; never touch knobs
        warmup_kick=lambda: kicked.append(1),
        logger=SimpleNamespace(
            info=lambda *a, **k: None, warn=lambda *a, **k: None
        ),
    )
    verifier = TpuBlsVerifier(max_buffer_wait_ms=5, mesh=False)
    # short real-clock wave deadline: the hang must trip it, not the
    # test runner's patience (the watchdog rides asyncio.wait_for, so
    # the injected ManualClock only drives the probe backoff)
    verifier.attach_health(tracker, wave_timeout_s=0.35)
    injector = ctx.registry.track(device_hang())
    try:
        results: list[bool] = []
        failover_lat: list[float] = []
        saw_quarantined = False
        for sets, _want in calls:
            pre_failover = not tracker.device_allowed()
            t0 = time.monotonic()
            ok = await verifier.verify_signature_sets(sets)
            dt = time.monotonic() - t0
            results.append(bool(ok))
            if pre_failover:
                # post-quarantine calls short-circuit to the host
                # oracle — the failover latency the SLO bounds
                failover_lat.append(dt)
            saw_quarantined = (
                saw_quarantined
                or tracker.state is HealthState.quarantined
            )

        ctx.slo(
            "verdicts_none_lost",
            len(results) == n_calls,
            {"resolved": len(results), "submitted": n_calls},
            f"{n_calls} resolved",
            "every gossip verdict resolves despite the hung device",
        )
        expected = [want for _, want in calls]
        ctx.slo(
            "verdicts_bit_identical",
            results == expected,
            {"wrong": [i for i, (r, w) in
                       enumerate(zip(results, expected)) if r != w]},
            "[]",
            "host-failover verdicts match the known ground truth "
            "(including the tampered job's False)",
        )
        ctx.slo_ge(
            "watchdog_tripped",
            tracker.watchdog_trips.get("deadline", 0), 1,
            "the wave watchdog fired on the hung dispatch",
        )
        ctx.slo(
            "device_quarantined",
            saw_quarantined and tracker.quarantines >= 1,
            {"saw_quarantined": saw_quarantined,
             "quarantines": tracker.quarantines},
            "quarantined >= once",
            "consecutive watchdog trips opened the breaker",
        )
        ctx.slo(
            "failover_served_gossip",
            tracker.failover_dispatches.get("bls", 0) >= 1
            and verifier.metrics.dispatch_by_path["failover"] >= 1
            and len(failover_lat) >= 1,
            {"failovers": tracker.failover_dispatches,
             "path": dict(verifier.metrics.dispatch_by_path),
             "failover_calls": len(failover_lat)},
            "failover dispatches > 0",
            "post-quarantine buckets rode the host oracle",
        )
        ctx.slo_le(
            "failover_p99_bounded",
            round(_quantile(failover_lat, 0.99), 3), 2.0,
            "host-failover verdict turnaround (no watchdog wait)",
        )

        # frozen-config invariant: a tune attempted while QUARANTINED
        # must suspend — no probes, no knob movement
        quiet_log = SimpleNamespace(
            info=lambda *a, **k: None, warn=lambda *a, **k: None
        )
        bench = lambda backend, bucket: AT.Measurement(
            backend=backend, bucket=bucket, pipeline="batch",
            seconds_per_dispatch=bucket / 400.0, sets_per_sec=400.0,
            runs=3, warm_seconds=0.0,
        )
        tuner = AT.DeviceAutotuner(
            verifier=_KnobVerifier(), grid=AT.parse_grid("backend=vpu"),
            bench=bench, artifact_path=None, logger=quiet_log,
            health=tracker,
        )
        cfg_before = (K.ingest_min_bucket(), K.ladder_top())
        decision = tuner.tune(trigger="drift:scenario")
        cfg_after = (K.ingest_min_bucket(), K.ladder_top())
        ctx.slo(
            "autotuner_frozen_while_quarantined",
            decision.get("source") == "suspended"
            and tuner.suspended_runs >= 1
            and cfg_before == cfg_after,
            {"source": decision.get("source"),
             "suspended_runs": tuner.suspended_runs,
             "before": cfg_before, "after": cfg_after},
            "source=suspended, knobs frozen",
            "no probe and no knob movement while QUARANTINED",
        )

        # reinstatement: restore the kernels FIRST (the probe's device
        # would still hang), then drive the backoff + probe sequence
        injector.detach()
        clock.advance(0.06)  # past quarantine_reset_s
        first = tracker.maybe_probe(lambda: True)
        second = tracker.maybe_probe(lambda: True)
        ctx.slo(
            "probe_reinstates_device",
            first is True and second is True
            and tracker.state is HealthState.online
            and tracker.device_allowed()
            and tracker.reinstatements == 1
            and len(kicked) == 1,
            {"probes": tracker.probes, "state": tracker.state.value,
             "reinstatements": tracker.reinstatements,
             "warmup_kicks": len(kicked)},
            "2 probe successes -> ONLINE + warmup re-kick",
            "the known-answer probe sequence reopened the device path",
        )
        ctx.slo_faults_fired("device_hang")
    finally:
        # detach is idempotent; it also releases any dispatch still
        # hung in the default executor so asyncio.run can shut its
        # thread pool down instead of joining a wedged thread forever
        injector.detach()
        await verifier.close()


# ---------------------------------------------------------------------------
# regime 6: checkpoint-sync thundering herd
# ---------------------------------------------------------------------------


@scenario(
    "checkpoint_thundering_herd",
    "most of the network restarts and catches up at once; catch-up "
    "completes, the surviving node's duties never stop, finality "
    "resumes",
    faults=("node_kill", "node_restart"),
    slos=("survivor_duties_continue", "herd_catch_up_completes",
          "heads_consistent_after_recovery", "finality_resumes",
          "no_missed_blocks_after_recovery"),
)
async def checkpoint_thundering_herd(ctx: ScenarioContext) -> None:
    from . import assertions as A
    from .faults import kill_node, restart_node
    from .simulation import Simulation

    spe = preset().SLOTS_PER_EPOCH
    sim = Simulation(_cfg(), _types(), n_nodes=3, n_validators=24)
    await sim.start()
    try:
        await sim.run_until_slot(spe)

        # the herd goes down: 2 of 3 nodes at once
        for idx in (1, 2):
            await kill_node(sim, idx)
            ctx.registry.record("node_kill")
        survivor = sim.nodes[0]
        proposed_before = survivor.blocks_proposed
        outage_start = sim.slot
        max_outage = (2 if ctx.smoke else 4) * spe
        # run until the survivor demonstrably kept proposing (its 1/3
        # of proposer slots), bounded so a pathological shuffle can't
        # hang the scenario
        while (survivor.blocks_proposed == proposed_before
               and sim.slot < outage_start + max_outage):
            await sim.run_slot()
        await sim.run_slot()
        survivor_blocks = survivor.blocks_proposed - proposed_before
        ctx.slo_ge(
            "survivor_duties_continue", survivor_blocks, 1,
            "the healthy node's proposals must not miss while the "
            "herd is down",
        )

        # thundering herd: both nodes restart and catch up AT ONCE
        restart_slot = sim.slot
        imported = []
        for idx in (1, 2):
            imported.append(await restart_node(sim, idx, resync_from=0))
            ctx.registry.record("node_restart")
        ctx.slo(
            "herd_catch_up_completes",
            all(n == survivor_blocks for n in imported),
            imported, survivor_blocks,
            "each restarted node imports exactly the canonical blocks "
            "it missed (caught_up_blocks)",
        )

        # phase0 finality needs two consecutive fully-justified
        # epochs AFTER the herd returns, plus the epoch the restart
        # landed in (partial participation) — three epochs out is the
        # earliest slot finalized can have advanced past its
        # at-restart value
        recover_epochs = 3 if ctx.smoke else 4
        fin_restart = max(A.finalized_epochs(sim).values())
        target = ((restart_slot // spe) + recover_epochs) * spe + 1
        await sim.run_until_slot(target)
        ctx.slo_true("heads_consistent_after_recovery",
                     A.heads_consistent(sim))
        fin = A.finalized_epochs(sim)
        ctx.slo_ge("finality_resumes", min(fin.values()),
                   fin_restart + 1,
                   "full participation after the herd returns must "
                   "finalize again")
        missing = A.missed_slots(sim, restart_slot + 3)
        ctx.slo(
            "no_missed_blocks_after_recovery",
            all(not m for m in missing.values()),
            missing, "no canonical gaps",
        )
        ctx.slo_faults_fired("node_kill", "node_restart")
    finally:
        await sim.stop()


# ---------------------------------------------------------------------------
# regime 8: light-client read flood against the serving tier
# ---------------------------------------------------------------------------


class _StubScenarioVerifier:
    """Signature stub: the flood regime measures the SERVING tier, so
    block-import BLS (pure python off-device) is stubbed to keep the
    altair dev chain seconds-fast, same as tests/test_lightclient.py."""

    async def verify_signature_sets(self, sets, **kw):
        return True

    async def verify_signature_sets_same_message(self, sets, message):
        return [True] * len(sets)

    def can_accept_work(self):
        return True

    async def close(self):
        pass


def _http_get(url: str, timeout: float = 10.0):
    """(status, headers, body) — HTTPError is a response, not a crash."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, dict(e.headers), body


@scenario(
    "lightclient_flood",
    "a light-client read flood + SSE subscriber swarm against the "
    "REST serving tier while the chain keeps importing: duty p99 "
    "unharmed, sheds confined to cheap classes, zero 500s, cache "
    "hit-ratio floor, slow SSE consumers evicted",
    faults=("read_flood", "sse_slow_consumer"),
    slos=("duty_p99_unharmed", "sheds_only_cheap_classes",
          "zero_500s", "cache_hit_ratio_floor",
          "refusals_carry_retry_after",
          "sse_drops_counted_and_evicted"),
)
async def lightclient_flood(ctx: ScenarioContext) -> None:
    import threading
    import time as _time

    from ..api.impl import BeaconApiImpl
    from ..api.overload import (
        CLS_ADMIN,
        CLS_CONN,
        CLS_DUTY,
        CLS_LIGHT,
        ClassBudget,
        LoopLagProbe,
        ServingOverload,
    )
    from ..api.server import BeaconRestApiServer
    from ..chain import DevNode
    from ..lightclient import LightClientServer

    spe = preset().SLOTS_PER_EPOCH
    cfg = _cfg(ALTAIR_FORK_EPOCH=0)
    types = _types()
    node = DevNode(
        cfg, types, 32, verifier=_StubScenarioVerifier(),
        verify_attestations=False,
    )
    node.chain.light_client_server = LightClientServer(
        cfg, types, node.chain
    )
    # tight light-class budget so the flood's sheds are observable at
    # scenario scale; duty stays wide open — the contract under test
    budgets = {
        CLS_DUTY: ClassBudget(10000.0, 4000.0, 32, 5.0),
        CLS_LIGHT: ClassBudget(150.0, 30.0, 8, 0.05),
    }
    overload = ServingOverload(
        budgets=budgets, pool_workers=24, sse_max_subscribers=3
    )
    overload.cache.attach(node.chain.events)
    ladder = overload.ladder
    probe = LoopLagProbe(ladder, interval=0.05)
    impl = BeaconApiImpl(cfg, types, node.chain)
    server = BeaconRestApiServer(
        impl, port=0, loop=asyncio.get_running_loop(),
        overload=overload,
    )
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    probe.start(asyncio.get_running_loop())
    try:
        # altair from genesis: the optimistic update exists after the
        # first imported sync aggregate, so smoke only warms a few
        # slots (each costs pure-python sync-committee signing)
        warm_slots = 4 if ctx.smoke else 2 * spe + 2
        await node.run_until(warm_slots)

        def duty_url():
            return (
                f"{base}/eth/v1/validator/attestation_data"
                f"?slot={node.slot}&committee_index=0"
            )

        # -- quiet baseline: duty-class latency with nothing else on
        n_quiet = 30 if ctx.smoke else 120
        quiet: list[float] = []
        for _ in range(n_quiet):
            t0 = _time.monotonic()
            status, _h, _b = _http_get(duty_url())
            quiet.append(_time.monotonic() - t0)
            assert status == 200, f"quiet duty request got {status}"
        quiet_p99 = _quantile(quiet, 0.99)

        # prime the hot cacheable routes once while the bucket is full
        _http_get(f"{base}/eth/v1/beacon/light_client/optimistic_update")
        _http_get(f"{base}/eth/v1/beacon/headers/head")

        # -- the flood: reader threads + SSE swarm while slots import
        stop = threading.Event()
        statuses: list[tuple[int, bool]] = []  # (status, retry_after?)
        st_lock = threading.Lock()

        # fixed per-thread request counts with a tiny think time:
        # enough pressure to drain the light-class bucket, throttled
        # enough that the flood doesn't starve the import loop's GIL
        # share outright (the real adversary is remote; this one
        # shares a core with the node)
        reqs_per_thread = 150 if ctx.smoke else 500

        def flood_reader(i: int):
            rng = random.Random(1000 + i)
            for _ in range(reqs_per_thread):
                if stop.is_set():
                    break
                if rng.random() < 0.7:
                    # hot identical read: the cache's job
                    url = (f"{base}/eth/v1/beacon/light_client/"
                           "optimistic_update")
                else:
                    # varied historical read: misses the cache, lands
                    # on admission every time
                    vid = rng.randrange(32)
                    url = (f"{base}/eth/v1/beacon/states/head/"
                           f"validators/{vid}")
                status, headers, _b = _http_get(url)
                with st_lock:
                    statuses.append(
                        (status, "Retry-After" in headers)
                    )
                _time.sleep(0.002)

        duty_flood: list[float] = []

        def duty_reader():
            while not stop.is_set():
                t0 = _time.monotonic()
                status, _h, _b = _http_get(duty_url())
                duty_flood.append(_time.monotonic() - t0)
                with st_lock:
                    statuses.append((status, False))
                _time.sleep(0.01)

        # SSE swarm: the cap is 3, so the extras must be refused with
        # Retry-After, not queued
        sse_threads = []
        sse_refused: list = []

        def sse_stream(frames: list):
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=30
            )
            conn.request(
                "GET", "/eth/v1/events?topics=head,block"
            )
            resp = conn.getresponse()
            if resp.status != 200:
                sse_refused.append(
                    (resp.status,
                     resp.getheader("Retry-After") is not None)
                )
                conn.close()
                return
            try:
                while not stop.is_set():
                    chunk = resp.fp.readline()
                    if not chunk:
                        break
                    if chunk.startswith(b"event:"):
                        frames.append(chunk)
            except OSError:
                pass
            finally:
                conn.close()

        # a slow consumer on the same emitter with a tiny queue: it
        # never drains, so the broadcast fan-out must evict it and
        # count the drops instead of blocking block import
        node.chain.events.max_queued = 4
        slow_sub = node.chain.events.subscribe(("head", "block"))
        ctx.registry.record("sse_slow_consumer")
        assert slow_sub is not None

        sse_frames: list = []
        for _ in range(5):
            t = threading.Thread(
                target=sse_stream, args=(sse_frames,), daemon=True
            )
            t.start()
            sse_threads.append(t)
        _time.sleep(0.2)  # let streams attach before the flood

        n_flood_threads = 4 if ctx.smoke else 8
        readers = [
            threading.Thread(
                target=flood_reader, args=(i,), daemon=True
            )
            for i in range(n_flood_threads)
        ]
        duty_t = threading.Thread(target=duty_reader, daemon=True)
        for t in readers:
            t.start()
        duty_t.start()

        flood_slots = 3 if ctx.smoke else spe
        for _ in range(flood_slots):
            await node.advance_slot()
            await asyncio.sleep(0.05)
        # readers drain their fixed budgets, then everything stops
        while any(t.is_alive() for t in readers):
            await asyncio.sleep(0.1)
        stop.set()
        for t in readers:
            t.join(timeout=10)
        duty_t.join(timeout=10)
        n_reads = len(statuses)
        ctx.registry.record("read_flood", n_reads)

        # -- the contract -------------------------------------------
        flood_p99 = _quantile(duty_flood, 0.99)
        bound = max(2 * quiet_p99, 0.25)
        ctx.slo_le(
            "duty_p99_unharmed", round(flood_p99, 4), round(bound, 4),
            "duty-class p99 under flood within 2x of the quiet "
            "baseline (absolute floor absorbs timer noise)",
        )

        sheds = overload.shed_counts()
        total_sheds = sum(sheds.values())
        cheap = {CLS_LIGHT, CLS_ADMIN, CLS_CONN}
        cheap_sheds = sum(
            n for (cls, _r), n in sheds.items() if cls in cheap
        )
        duty_sheds = sum(
            n for (cls, _r), n in sheds.items() if cls == CLS_DUTY
        )
        ctx.slo(
            "sheds_only_cheap_classes",
            total_sheds > 0
            and duty_sheds == 0
            and cheap_sheds / total_sheds >= 0.95,
            {k: v for k, v in sorted(sheds.items())},
            ">= 95% of sheds on light/admin/conn, zero on duty",
            "the flood must land on the classes built to absorb it",
        )

        responses = overload.response_counts()
        server_5xx = sum(
            n for s, n in responses.items() if s in (500, 501, 502)
        )
        client_500 = sum(
            1 for s, _ra in statuses if s in (500, 501, 502)
        )
        ctx.slo(
            "zero_500s",
            server_5xx == 0 and client_500 == 0,
            {"server": server_5xx, "client": client_500,
             "responses": responses},
            "no internal errors — refusals are typed 429/503 sheds "
            "with Retry-After, 504 only on bridge timeout",
        )

        ratio = overload.cache.hit_ratio()
        floor = 0.5
        ctx.slo_ge(
            "cache_hit_ratio_floor", round(ratio, 3), floor,
            "hot identical reads must be served from the head-keyed "
            "cache (fresh or stale), not recomputed",
        )

        refused = [
            (s, ra) for s, ra in statuses if s in (429, 503)
        ] + [(s, ra) for s, ra in sse_refused]
        ctx.slo(
            "refusals_carry_retry_after",
            len(refused) > 0 and all(ra for _s, ra in refused),
            {"refusals": len(refused),
             "with_retry_after": sum(1 for _s, ra in refused if ra)},
            "every 429/503 carries Retry-After",
            "clients must learn the backoff from the wire",
        )

        emitter = node.chain.events
        dropped = sum(emitter.dropped.values())
        ctx.slo(
            "sse_drops_counted_and_evicted",
            dropped >= 1 and emitter.evictions >= 1
            and slow_sub.evicted and len(sse_frames) > 0,
            {"dropped": dropped, "evictions": emitter.evictions,
             "slow_sub_evicted": slow_sub.evicted,
             "frames_delivered": len(sse_frames)},
            "drops counted + slow consumer evicted while healthy "
            "subscribers keep their stream",
            "lossy-by-design is only acceptable when accounted",
        )
        ctx.slo_faults_fired("read_flood", "sse_slow_consumer")
    finally:
        probe.stop()
        server.stop()
        await node.close()

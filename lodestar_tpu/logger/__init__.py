"""Structured logger package.

Reference analog: packages/logger — `Logger` interface
(src/interface.ts) with winston implementation (src/winston.ts:41):
leveled logs, per-module child loggers with their own level overrides,
human console format `[module] level: message key=value`, optional
timestamped file output. Built on stdlib logging so host libraries
integrate, but with the reference's child/module semantics.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Any

LEVELS = {
    "error": logging.ERROR,
    "warn": logging.WARNING,
    "info": logging.INFO,
    "verbose": logging.INFO - 2,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG - 2,
}
logging.addLevelName(LEVELS["verbose"], "VERBOSE")
logging.addLevelName(LEVELS["trace"], "TRACE")


def _fmt_meta(meta: dict[str, Any]) -> str:
    if not meta:
        return ""
    return " " + ", ".join(f"{k}={_fmt_val(v)}" for k, v in meta.items())


def _fmt_val(v) -> str:
    if isinstance(v, bytes):
        return "0x" + v.hex()[:18] + ("…" if len(v) > 9 else "")
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


class _ConsoleFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        t = time.strftime("%b-%d %H:%M:%S", time.localtime(record.created))
        ms = int(record.msecs)
        module = getattr(record, "lodestar_module", record.name)
        meta = getattr(record, "lodestar_meta", {})
        lvl = record.levelname.lower()
        msg = record.getMessage()
        return f"{t}.{ms:03d}[{module:<12}] {lvl:<7}: {msg}{_fmt_meta(meta)}"


class Logger:
    """Leveled logger with reference-style (message, meta) calls and
    child() per-module loggers (logger/src/interface.ts)."""

    def __init__(self, module: str = "", level: str = "info", _base=None):
        self.module = module
        if _base is not None:
            self._log = _base
        else:
            self._log = logging.getLogger(f"lodestar.{module or 'root'}")
            self._log.setLevel(LEVELS.get(level, logging.INFO))
            self._log.propagate = False
            if not self._log.handlers:
                h = logging.StreamHandler(sys.stderr)
                h.setFormatter(_ConsoleFormatter())
                self._log.addHandler(h)

    def child(self, module: str, level: str | None = None) -> "Logger":
        name = f"{self.module}/{module}" if self.module else module
        c = Logger.__new__(Logger)
        c.module = name
        c._log = self._log
        if level is not None:
            # per-module override: wrap with an independent stdlib logger
            c._log = logging.getLogger(f"lodestar.{name}")
            c._log.setLevel(LEVELS.get(level, logging.INFO))
            c._log.propagate = False
            if not c._log.handlers:
                h = logging.StreamHandler(sys.stderr)
                h.setFormatter(_ConsoleFormatter())
                c._log.addHandler(h)
        return c

    def _emit(self, level: str, message: str, meta: dict | None) -> None:
        self._log.log(
            LEVELS[level],
            message,
            extra={
                "lodestar_module": self.module,
                "lodestar_meta": meta or {},
            },
        )

    def error(self, message: str, meta: dict | None = None, exc=None):
        if exc is not None:
            meta = dict(meta or {})
            meta["error"] = repr(exc)
        self._emit("error", message, meta)

    def warn(self, message: str, meta: dict | None = None):
        self._emit("warn", message, meta)

    def info(self, message: str, meta: dict | None = None):
        self._emit("info", message, meta)

    def verbose(self, message: str, meta: dict | None = None):
        self._emit("verbose", message, meta)

    def debug(self, message: str, meta: dict | None = None):
        self._emit("debug", message, meta)

    def trace(self, message: str, meta: dict | None = None):
        self._emit("trace", message, meta)

    def add_file_output(self, path: str, level: str = "debug") -> None:
        h = logging.FileHandler(path)
        h.setFormatter(_ConsoleFormatter())
        h.setLevel(LEVELS.get(level, logging.DEBUG))
        self._log.addHandler(h)


def get_logger(module: str = "", level: str = "info") -> Logger:
    return Logger(module, level)

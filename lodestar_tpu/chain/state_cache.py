"""Checkpoint-state cache with disk spill.

Reference analog: InMemoryCheckpointStateCache +
PersistentCheckpointStateCache (chain/stateCache/
persistentCheckpointsCache.ts:94 with Db/File datastores) — epoch-
boundary states are the regen seeds for attestation validation and
epoch processing; recent ones stay in memory, finalized-distant ones
spill to the checkpoint_state bucket and reload on demand.
"""

from __future__ import annotations

from ..statetransition.slot import BeaconStateView

MAX_IN_MEMORY = 8  # persistentCheckpointsCache maxCPStateEpochsInMemory


def _key(epoch: int, root: bytes) -> bytes:
    return int(epoch).to_bytes(8, "big") + bytes(root)


class CheckpointStateCache:
    def __init__(self, types, db=None, max_in_memory: int = MAX_IN_MEMORY):
        self.types = types
        self.db = db
        self.max_in_memory = max_in_memory
        self._mem: dict[bytes, BeaconStateView] = {}
        self._order: list[bytes] = []
        self.hits = 0
        self.misses = 0
        self.spills = 0
        self.reloads = 0

    def add(self, epoch: int, root: bytes, view: BeaconStateView) -> None:
        k = _key(epoch, root)
        if k in self._mem:
            return
        self._mem[k] = view
        self._order.append(k)
        while len(self._order) > self.max_in_memory:
            old = self._order.pop(0)
            view_old = self._mem.pop(old, None)
            if view_old is not None and self.db is not None:
                # spill instead of dropping (datastore/db.ts)
                self.db.checkpoint_state.put(
                    old, (view_old.fork, view_old.state)
                )
                self.spills += 1

    def get(self, epoch: int, root: bytes) -> BeaconStateView | None:
        k = _key(epoch, root)
        got = self._mem.get(k)
        if got is not None:
            self.hits += 1
            return got
        if self.db is not None:
            raw = self.db.checkpoint_state.get_binary(k)
            if raw is not None:
                fork, state = self.db.checkpoint_state.decode_value(raw)
                view = BeaconStateView(state=state, fork=fork)
                self.reloads += 1
                self.hits += 1
                # promote back into memory: bursts of validations for a
                # spilled checkpoint must not re-deserialize each time
                self.add(epoch, root, view)
                return view
        self.misses += 1
        return None

    def prune_finalized(self, finalized_epoch: int) -> int:
        """Drop entries below the finalized epoch (archiver takes over
        long-term storage). Returns entries removed."""
        removed = 0
        for k in list(self._mem):
            if int.from_bytes(k[:8], "big") < finalized_epoch:
                self._mem.pop(k)
                self._order.remove(k)
                removed += 1
        if self.db is not None:
            for k in list(self.db.checkpoint_state.keys()):
                kb = k if isinstance(k, bytes) else bytes(k)
                if int.from_bytes(kb[:8], "big") < finalized_epoch:
                    self.db.checkpoint_state.delete(kb)
                    removed += 1
        return removed

"""Slot clock.

Reference analog: beacon-node/src/chain/../util/clock.ts:66 — emits
slot/epoch events off genesis time. Supports real (asyncio) ticking and
manual stepping for dev chains/tests.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from ..params import preset


class Clock:
    def __init__(self, cfg, genesis_time: int, now: Callable[[], float] = time.time):
        self.cfg = cfg
        self.genesis_time = genesis_time
        self._now = now
        self._slot_handlers: list[Callable[[int], Awaitable[None] | None]] = []
        self._epoch_handlers: list[Callable[[int], Awaitable[None] | None]] = []
        self._task: asyncio.Task | None = None

    @property
    def current_slot(self) -> int:
        dt = self._now() - self.genesis_time
        if dt < 0:
            return 0
        return int(dt // self.cfg.SECONDS_PER_SLOT)

    @property
    def current_epoch(self) -> int:
        return self.current_slot // preset().SLOTS_PER_EPOCH

    def seconds_into_slot(self) -> float:
        dt = self._now() - self.genesis_time
        return dt % self.cfg.SECONDS_PER_SLOT if dt >= 0 else 0.0

    def on_slot(self, fn) -> None:
        self._slot_handlers.append(fn)

    def on_epoch(self, fn) -> None:
        self._epoch_handlers.append(fn)

    async def emit_slot(self, slot: int) -> None:
        p = preset()
        if slot % p.SLOTS_PER_EPOCH == 0:
            for fn in self._epoch_handlers:
                r = fn(slot // p.SLOTS_PER_EPOCH)
                if asyncio.iscoroutine(r):
                    await r
        for fn in self._slot_handlers:
            r = fn(slot)
            if asyncio.iscoroutine(r):
                await r

    async def run(self) -> None:
        """Real-time loop: sleep to each slot boundary, emit."""
        last = self.current_slot - 1
        while True:
            slot = self.current_slot
            if slot > last:
                last = slot
                await self.emit_slot(slot)
            next_boundary = (
                self.genesis_time + (last + 1) * self.cfg.SECONDS_PER_SLOT
            )
            await asyncio.sleep(max(0.01, next_boundary - self._now()))

    def start(self) -> None:
        self._task = asyncio.ensure_future(self.run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

"""Seen caches: first-seen dedup for gossip objects.

Reference analog: beacon-node/src/chain/seenCache/ —
`SeenAttesters`/`SeenAggregators` (seenAttesters.ts:20,49),
`SeenAttestationDatas` (seenAttestationData.ts:55) caching resolved
attestation data + committee per attData-key per slot for the batch
path, `SeenBlockProposers` (seenBlockProposers.ts:11),
`SeenSyncCommitteeMessages` (seenCommittee.ts:15). All prune by epoch/
slot advance so memory is bounded by a small window.
"""

from __future__ import annotations

from collections import OrderedDict


class SeenAttesters:
    """validator index -> last target epoch seen attesting.

    Gossip rule: at most one attestation per validator per target epoch
    (seenAttesters.ts)."""

    def __init__(self, lowest_kept_epoch: int = 0):
        self._by_epoch: dict[int, set[int]] = {}
        self.lowest_kept_epoch = lowest_kept_epoch

    def is_known(self, target_epoch: int, index: int) -> bool:
        s = self._by_epoch.get(target_epoch)
        return s is not None and index in s

    def add(self, target_epoch: int, index: int) -> None:
        if target_epoch < self.lowest_kept_epoch:
            raise ValueError("epoch below pruned window")
        self._by_epoch.setdefault(target_epoch, set()).add(index)

    def prune(self, finalized_epoch: int) -> None:
        self.lowest_kept_epoch = finalized_epoch
        for e in [e for e in self._by_epoch if e < finalized_epoch]:
            del self._by_epoch[e]


class SeenAggregators(SeenAttesters):
    """Same shape keyed on (target_epoch, committee_index) per
    aggregator index (seenAttesters.ts:49)."""

    def is_known_agg(self, epoch: int, committee: int, index: int) -> bool:
        return self.is_known(epoch, (committee << 40) | index)

    def add_agg(self, epoch: int, committee: int, index: int) -> None:
        self.add(epoch, (committee << 40) | index)


class AttDataCacheEntry:
    """Resolved per-attData context shared by every attestation in a
    same-message batch: committee indices, signing root, subnet."""

    __slots__ = ("data", "committee", "signing_root", "subnet")

    def __init__(self, data, committee, signing_root, subnet):
        self.data = data
        self.committee = committee
        self.signing_root = signing_root
        self.subnet = subnet


class SeenAttestationDatas:
    """slot -> attData-bytes -> AttDataCacheEntry, capped per slot
    (seenAttestationData.ts:55). Resolving committee + signing root
    once per key is what makes the 50k/slot firehose tractable."""

    def __init__(self, max_per_slot: int = 512, slot_window: int = 2):
        self.max_per_slot = max_per_slot
        self.slot_window = slot_window
        self._by_slot: dict[int, OrderedDict[bytes, AttDataCacheEntry]] = {}
        self.lowest_kept_slot = 0
        self.hits = 0
        self.misses = 0
        self.rejected_overflow = 0

    def get(self, slot: int, key: bytes) -> AttDataCacheEntry | None:
        entry = self._by_slot.get(slot, {}).get(key)
        if entry is None:
            self.misses += 1
        else:
            self.hits += 1
        return entry

    def put(self, slot: int, key: bytes, entry: AttDataCacheEntry) -> bool:
        if slot < self.lowest_kept_slot:
            return False
        m = self._by_slot.setdefault(slot, OrderedDict())
        if key not in m and len(m) >= self.max_per_slot:
            self.rejected_overflow += 1
            return False
        m[key] = entry
        return True

    def on_slot(self, clock_slot: int) -> None:
        self.lowest_kept_slot = max(0, clock_slot - self.slot_window)
        for s in [s for s in self._by_slot if s < self.lowest_kept_slot]:
            del self._by_slot[s]


class SeenBlockProposers:
    """(slot, proposer) pairs seen via gossip blocks; one block per
    proposer per slot (seenBlockProposers.ts:11)."""

    def __init__(self):
        self._by_slot: dict[int, set[int]] = {}
        self.finalized_slot = 0

    def is_known(self, slot: int, proposer: int) -> bool:
        return proposer in self._by_slot.get(slot, ())

    def add(self, slot: int, proposer: int) -> None:
        self._by_slot.setdefault(slot, set()).add(proposer)

    def prune(self, finalized_slot: int) -> None:
        self.finalized_slot = finalized_slot
        for s in [s for s in self._by_slot if s < finalized_slot]:
            del self._by_slot[s]


class SeenSyncCommitteeMessages:
    """(slot, subnet, validator) dedup (seenCommittee.ts:15)."""

    def __init__(self):
        self._by_slot: dict[int, set[tuple[int, int]]] = {}

    def is_known(self, slot: int, subnet: int, index: int) -> bool:
        return (subnet, index) in self._by_slot.get(slot, ())

    def add(self, slot: int, subnet: int, index: int) -> None:
        self._by_slot.setdefault(slot, set()).add((subnet, index))

    def prune(self, min_slot: int) -> None:
        for s in [s for s in self._by_slot if s < min_slot]:
            del self._by_slot[s]

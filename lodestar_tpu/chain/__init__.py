"""Chain orchestration: clock, op pools, block import/production, dev node.

Reference analog: beacon-node/src/chain (SURVEY.md §2.4) — BeaconChain
(chain.ts:112), block pipeline (chain/blocks/), op pools
(chain/opPools/), clock (util/clock.ts:66), `lodestar dev`.
"""

from .chain import BeaconChain, ChainError
from .clock import Clock
from .devnode import DevNode
from .oppools import AggregatedAttestationPool, AttestationPool, OpPool

__all__ = [
    "AggregatedAttestationPool",
    "AttestationPool",
    "BeaconChain",
    "ChainError",
    "Clock",
    "DevNode",
    "OpPool",
]

"""Reprocess controller: retry attestations that beat their block.

Reference analog: ReprocessController (chain/reprocess.ts:50) —
gossip attestations referencing an unknown head are parked (bounded,
with a deadline) and re-run as soon as the block arrives; unresolved
entries expire at the slot boundary.
"""

from __future__ import annotations

import asyncio

MAX_QUEUED_PER_ROOT = 16_384 // 64
WAIT_SLOTS = 1


class ReprocessController:
    def __init__(self, chain):
        self.chain = chain
        # block root -> [(att, committee, parked_at_slot)]
        self._waiting: dict[bytes, list] = {}
        self._slot = 0
        self.resolved = 0
        self.expired = 0

    def await_block(self, block_root: bytes, attestation, committee) -> bool:
        """Park an attestation until its head block arrives. Returns
        False when the per-root budget is exhausted (caller drops)."""
        q = self._waiting.setdefault(bytes(block_root), [])
        if len(q) >= MAX_QUEUED_PER_ROOT:
            return False
        q.append((attestation, committee, self._slot))
        return True

    async def on_block_imported(self, block_root: bytes) -> int:
        """Flush parked attestations for a just-imported block."""
        q = self._waiting.pop(bytes(block_root), None)
        if not q:
            return 0
        n = 0
        for att, committee, _parked in q:
            try:
                if await self.chain.on_attestation(att, committee):
                    n += 1
            except Exception:
                pass
        self.resolved += n
        return n

    def on_slot(self, slot: int) -> int:
        """Expire entries that have waited >= WAIT_SLOTS boundaries —
        NOT everything: an attestation parked just before the tick must
        survive into the next slot (reprocess.ts deadline semantics)."""
        self._slot = slot
        n = 0
        for root in list(self._waiting):
            kept = [
                e
                for e in self._waiting[root]
                if slot - e[2] <= WAIT_SLOTS
            ]
            n += len(self._waiting[root]) - len(kept)
            if kept:
                self._waiting[root] = kept
            else:
                del self._waiting[root]
        self.expired += n
        return n

"""BeaconChain: block import/production orchestration.

Reference analog: BeaconChain (beacon-node/src/chain/chain.ts:112) and
the block pipeline (chain/blocks/: verifyBlock.ts:38-100 runs state
transition and signature verification in parallel; importBlock.ts wires
fork choice, head update, pools). Here the signature sets go to the
TPU verifier service while the host runs the (signature-free) state
transition — the same split, with the worker pool replaced by device
batch dispatch.
"""

from __future__ import annotations

import asyncio

from ..bls import OracleBlsVerifier
from ..forkchoice import Checkpoint, ExecutionStatus, ForkChoice, ProtoArray, ProtoNode
from ..params import GENESIS_EPOCH, ForkSeq, preset
from ..statetransition import BeaconStateView, state_transition, util
from ..statetransition.block import BlockProcessError
from ..statetransition.epoch import compute_unrealized_checkpoints
from ..statetransition.signature_sets import get_block_signature_sets
from ..statetransition.slot import process_slots

MAX_CACHED_STATES = 48  # FIFOBlockStateCache-ish bound
MAX_CACHED_BLOCKS = 2048  # hot signed-block window feeding regen


class ChainError(Exception):
    pass


def _clone(view: BeaconStateView, types) -> BeaconStateView:
    # structural copy that keeps hash caches warm (ssz/cached.py) — the
    # ViewDU state.clone() analog; replaces serialize+deserialize
    from ..ssz.cached import clone_value

    t = view.state_type(types)
    return BeaconStateView(
        state=clone_value(t, view.state), fork=view.fork
    )


def _checkpoint(cp) -> Checkpoint:
    return Checkpoint(int(cp.epoch), bytes(cp.root))


class BeaconChain:
    def __init__(
        self,
        cfg,
        types,
        anchor: BeaconStateView,
        verifier=None,
        trusted_execution: bool = True,
        db=None,
    ):
        self.cfg = cfg
        self.types = types
        self.verifier = verifier or OracleBlsVerifier()
        # persistence (BeaconDb) — optional; when present, imported
        # blocks/states are written through and finality triggers the
        # archiver (reference: importBlock.ts db writes + archiver.ts)
        self.db = db
        self.archiver = None
        if db is not None:
            from .archiver import Archiver

            self.archiver = Archiver(db, self)
        # optional LightClientServer (lightclient/server.py), fed on
        # import with each block's sync aggregate
        self.light_client_server = None
        # optional IExecutionEngine (execution/): when attached, payload
        # blocks are verified via engine_newPayload and head updates
        # notify engine_forkchoiceUpdated (reference:
        # verifyBlocksExecutionPayloads + importBlock fcU)
        self.execution_engine = None
        # optional Eth1DepositDataTracker (eth1/) for block production
        self.eth1 = None
        # optional ValidatorMonitor (metrics/validator_monitor.py)
        self.validator_monitor = None
        # optional span Tracer (metrics/tracing.py): when attached,
        # every import produces a per-stage trace; slow ones land in
        # the tracer's ring buffer behind the admin debug route
        self.tracer = None
        # chain events -> SSE (api events route)
        from .events import ChainEventEmitter

        self.events = ChainEventEmitter()
        # Dev chains have no execution engine: self-built mock payloads
        # are trusted (valid). With a real engine attached this must be
        # False so payload blocks import optimistically (syncing) until
        # an engine verdict flips them via fork_choice.proto
        # set_execution_valid/invalid.
        self.trusted_execution = trusted_execution

        p = preset()
        state = anchor.state
        # anchor block root: latest header with state_root filled
        header_t = types.BeaconBlockHeader
        header = header_t.default()
        src = state.latest_block_header
        header.slot = src.slot
        header.proposer_index = src.proposer_index
        header.parent_root = src.parent_root
        header.body_root = src.body_root
        header.state_root = (
            bytes(src.state_root)
            if bytes(src.state_root) != b"\x00" * 32
            else anchor.hash_tree_root(types)
        )
        self.genesis_root = header_t.hash_tree_root(header)
        self.genesis_time = state.genesis_time

        anchor_epoch = util.compute_epoch_at_slot(state.slot)
        # The anchor block IS the initial justified+finalized checkpoint
        # (reference: forkChoice initialization from anchorState) — the
        # state's own checkpoint roots point below the anchor and are
        # unresolvable in a fresh proto array; imports pull the store's
        # checkpoints up as new blocks justify.
        anchor_cp = Checkpoint(anchor_epoch, self.genesis_root)
        justified = anchor_cp
        finalized = anchor_cp
        proto = ProtoArray(
            justified.epoch, finalized.epoch, finalized_root=finalized.root
        )
        proto.on_block(
            ProtoNode(
                slot=state.slot,
                block_root=self.genesis_root,
                parent_root=None,
                state_root=header.state_root,
                target_root=self.genesis_root,
                justified_epoch=justified.epoch,
                finalized_epoch=finalized.epoch,
                unrealized_justified_epoch=justified.epoch,
                unrealized_finalized_epoch=finalized.epoch,
                execution_status=ExecutionStatus.pre_merge,
            )
        )
        balances = [v.effective_balance for v in state.validators]
        self.fork_choice = ForkChoice(
            cfg, proto, finalized, justified, balances, state.slot
        )
        self.head_root: bytes = self.genesis_root
        self._states: dict[bytes, BeaconStateView] = {
            self.genesis_root: anchor
        }
        self._state_order: list[bytes] = [self.genesis_root]
        self._justified_root_seen = justified.root
        # in-memory signed-block store (db-independent) feeding regen;
        # bounded FIFO like the hot-block window the reference keeps in
        # its block repository before archival
        self._blocks: dict[bytes, object] = {}
        self._block_order: list[bytes] = []
        from .regen import StateRegenerator

        self.regen = StateRegenerator(self)
        if db is not None:
            from ..config.chain_config import chain_config_to_json

            db.meta.put_raw(
                "chain_config", chain_config_to_json(cfg).encode()
            )
            db.meta.put_int("genesis_time", int(state.genesis_time))
            db.meta.put_raw(
                "genesis_validators_root",
                bytes(state.genesis_validators_root),
            )
            db.meta.put_raw("anchor_root", self.genesis_root)
            db.meta.put_raw("head_root", self.head_root)
            if db.state.get_binary(self.genesis_root) is None:
                db.state.put(
                    self.genesis_root, (anchor.fork, anchor.state)
                )

    @classmethod
    async def from_db(
        cls, cfg, types, db, verifier=None, trusted_execution=True
    ):
        """Resume a chain from disk: anchor at the best persisted state
        (latest archived finalized state, else the original anchor),
        then replay hot blocks in slot order through the full import
        pipeline (reference: initStateFromDb + loadFromDisk,
        cli initBeaconState.ts / node/nodejs.ts:235)."""
        anchor_view = None
        archived = db.state_archive.values(reverse=True, limit=1)
        if archived:
            fork, state = archived[0]
            anchor_view = BeaconStateView(state=state, fork=fork)
        else:
            anchor_root = db.meta.get_raw("anchor_root")
            if anchor_root is None:
                raise ChainError("empty database: no anchor state")
            raw = db.state.get_binary(anchor_root)
            if raw is None:
                raise ChainError("anchor state missing from db")
            fork, state = db.state.decode_value(raw)
            anchor_view = BeaconStateView(state=state, fork=fork)
        chain = cls(
            cfg,
            types,
            anchor_view,
            verifier=verifier,
            trusted_execution=trusted_execution,
            db=db,
        )
        # replay hot blocks above the anchor in slot order
        anchor_slot = int(anchor_view.state.slot)
        hot = []
        for root, (fork, block) in db.block.entries():
            if int(block.message.slot) > anchor_slot:
                hot.append((int(block.message.slot), block))
        hot.sort(key=lambda t: t[0])
        for _, block in hot:
            try:
                await chain.process_block(block, is_timely=False)
            except ChainError:
                # non-canonical orphan whose parent was never persisted
                continue
        return chain

    # -- state access -----------------------------------------------------

    @property
    def head_state(self) -> BeaconStateView:
        return self._states[self.head_root]

    def get_state(self, block_root: bytes) -> BeaconStateView | None:
        return self._states.get(block_root)

    def get_or_regen_state(self, block_root: bytes) -> BeaconStateView:
        """Cached post-state, regenerating synchronously on eviction.

        Loop-thread callers should prefer `get_state_async`: a deep
        replay here (up to MAX_REPLAY_DEPTH transitions) blocks the
        event loop. The sync path is kept for executor-thread callers
        and for roots that are pinned in cache (head/genesis, which
        `_store_state` never evicts)."""
        st = self.get_state(block_root)
        if st is None:
            st = self.regen.replay_sync(block_root)
        return st

    async def get_state_async(self, block_root: bytes) -> BeaconStateView:
        """Post-state via the queued regen path: cache hit inline,
        replay on the executor thread so the event loop keeps serving
        gossip/reqresp/API during deep replays (advisor: chain.py
        get_or_regen_state on-loop replay stall)."""
        return await self.regen.get_state(
            block_root, caller="get_state_async"
        )

    def get_block(self, block_root: bytes):
        return self._blocks.get(block_root)

    def _store_block(self, root: bytes, signed_block) -> None:
        if root not in self._blocks:
            self._block_order.append(root)
        self._blocks[root] = signed_block
        while len(self._block_order) > MAX_CACHED_BLOCKS:
            self._blocks.pop(self._block_order.pop(0), None)

    def _store_state(self, root: bytes, view: BeaconStateView) -> None:
        if root not in self._states:
            self._state_order.append(root)
        self._states[root] = view
        while len(self._state_order) > MAX_CACHED_STATES:
            old = self._state_order.pop(0)
            if old != self.head_root and old != self.genesis_root:
                self._states.pop(old, None)
            else:
                self._state_order.append(old)
                if all(
                    r in (self.head_root, self.genesis_root)
                    for r in self._state_order
                ):
                    break

    # -- block import ------------------------------------------------------

    async def process_block(
        self,
        signed_block,
        is_timely: bool | None = None,
        blob_sidecars=None,
        trace=None,
    ) -> bytes:
        """Full import: state transition + TPU signature batch + fork
        choice + head update. Returns the block root.

        is_timely: proposer-boost eligibility. None derives it from the
        wall clock (seconds-into-slot < SECONDS_PER_SLOT /
        INTERVALS_PER_SLOT, reference importBlock.ts blockDelaySec
        check); the devnode passes True because its simulated clock
        produces exactly at the slot boundary.

        trace: an ImportTrace started upstream (the gossip handler
        seeds gossip_receive/decode); None starts one here when a
        tracer is attached."""
        from ..metrics.tracing import NULL_TRACE

        block = signed_block.message
        if trace is None:
            trace = (
                self.tracer.block_import_trace(int(block.slot))
                if self.tracer is not None
                else NULL_TRACE
            )
        try:
            root = await self._import_block(
                signed_block, is_timely, blob_sidecars, trace
            )
        except BaseException as e:
            trace.finish(error=e)
            raise
        trace.finish(block_root=root)
        return root

    async def _import_block(
        self, signed_block, is_timely, blob_sidecars, trace
    ) -> bytes:
        types = self.types
        block = signed_block.message
        parent = self.get_state(bytes(block.parent_root))
        if parent is None:
            # evicted from the state cache: rebuild by replay (timed as
            # its own non-canonical stage: replay storms show up in the
            # trace, not smeared into state_transition)
            from .regen import RegenError

            try:
                with trace.stage("parent_regen"):
                    parent = await self.regen.get_state(
                        bytes(block.parent_root),
                        caller="block_import",
                    )
            except RegenError as e:
                raise ChainError(f"unknown parent state: {e}") from e

        with trace.stage("state_transition"):
            work = _clone(parent, types)
            process_slots(self.cfg, work, block.slot, types)

        # signature sets against the advanced pre-state; the sig_verify
        # stage spans dispatch -> verdict and is contextvar-current when
        # the verifier task is spawned, so the verifier's own spans
        # (bls/verifier.py) nest under it in the trace tree
        sv = trace.begin_stage("sig_verify")
        sets = get_block_signature_sets(
            self.cfg, work, signed_block, types
        )
        verify_task = asyncio.ensure_future(
            self.verifier.verify_signature_sets(sets)
        )
        # the block transition overlaps the in-flight verification
        # (verifyBlock.ts parallel split) — both stages report wall
        # time, so their sum can exceed the total
        try:
            with trace.stage("state_transition"):
                state_transition(
                    self.cfg,
                    work,
                    signed_block,
                    types,
                    verify_state_root=True,
                    verify_proposer=False,
                    verify_signatures=False,
                )
        except BlockProcessError:
            verify_task.cancel()
            trace.end_stage(sv)
            raise
        ok = await verify_task
        trace.end_stage(sv)
        if not ok:
            raise ChainError("block signature verification failed")

        block_t = types.by_fork[work.fork].BeaconBlock
        block_root = block_t.hash_tree_root(block)

        # data availability (deneb+): every commitment needs a bound,
        # KZG-verified sidecar (verifyBlocksDataAvailability analog)
        if work.fork_seq >= ForkSeq.deneb:
            from .blobs import BlobError, validate_blob_sidecars

            with trace.stage("da"):
                n_comms = len(block.body.blob_kzg_commitments)
                if n_comms and blob_sidecars is None:
                    raise ChainError(
                        f"block carries {n_comms} blob commitments but no "
                        "sidecars were provided (data unavailable)"
                    )
                if blob_sidecars is not None:
                    try:
                        validate_blob_sidecars(
                            types, work.fork, block_root, block, blob_sidecars
                        )
                    except BlobError as e:
                        raise ChainError(
                            f"blob validation failed: {e}"
                        ) from e

        # execution verification via the engine when attached
        # (verifyBlocksExecutionPayloads analog); trusted_execution dev
        # chains skip straight to valid. Must run BEFORE any stores: an
        # INVALID payload's block/state must never enter the caches or
        # be served to peers.
        engine_status = None
        if (
            self.execution_engine is not None
            and work.fork_seq >= ForkSeq.bellatrix
        ):
            with trace.stage("engine_notify"):
                engine_status = await self._notify_new_payload(
                    work, block, block_root
                )

        self._store_state(block_root, work)
        self._store_block(block_root, signed_block)
        if blob_sidecars and self.db is not None:
            with trace.stage("db_write"):
                self.db.blob_sidecars.put(
                    block_root, (work.fork, list(blob_sidecars))
                )

        state = work.state
        epoch = util.compute_epoch_at_slot(block.slot)
        if block.slot % preset().SLOTS_PER_EPOCH == 0:
            target_root = block_root
        else:
            target_root = bytes(util.get_block_root(state, epoch))
        uj, uf = compute_unrealized_checkpoints(
            self.cfg, state, types, work.fork_seq
        )
        exec_hash = None
        if work.fork_seq >= ForkSeq.bellatrix:
            exec_hash = bytes(
                state.latest_execution_payload_header.block_hash
            )
        prev_finalized = self.fork_choice.finalized_checkpoint.epoch
        fc = trace.begin_stage("forkchoice")
        self.fork_choice.on_tick(max(self.fork_choice.current_slot, block.slot))
        self.fork_choice.on_block(
            slot=block.slot,
            block_root=block_root,
            parent_root=bytes(block.parent_root),
            state_root=bytes(block.state_root),
            target_root=target_root,
            justified_checkpoint=_checkpoint(
                state.current_justified_checkpoint
            ),
            finalized_checkpoint=_checkpoint(state.finalized_checkpoint),
            unrealized_justified=_checkpoint(uj),
            unrealized_finalized=_checkpoint(uf),
            execution_block_hash=exec_hash,
            execution_status=(
                (
                    engine_status
                    if engine_status is not None
                    else (
                        ExecutionStatus.valid
                        if self.trusted_execution
                        else ExecutionStatus.syncing
                    )
                )
                if exec_hash
                else None
            ),
            is_timely=(
                self._is_timely(block.slot) if is_timely is None else is_timely
            ),
        )
        self._refresh_justified_balances()
        prev_head = self.head_root
        self.head_root = self.fork_choice.update_head()
        trace.end_stage(fc)
        # events (importBlock.ts ChainEvent emissions)
        self.events.emit(
            "block",
            {
                "slot": str(int(block.slot)),
                "block": "0x" + block_root.hex(),
            },
        )
        if self.head_root != prev_head:
            head_node = self.fork_choice.proto.get_node(self.head_root)
            self.events.emit(
                "head",
                {
                    "slot": str(head_node.slot if head_node else 0),
                    "block": "0x" + self.head_root.hex(),
                    "state": "0x"
                    + (
                        head_node.state_root.hex()
                        if head_node
                        else "00" * 32
                    ),
                },
            )
            if (
                head_node is not None
                and prev_head != bytes(block.parent_root)
                and self.fork_choice.has_block(prev_head)
            ):
                self.events.emit(
                    "chain_reorg",
                    {
                        "slot": str(head_node.slot),
                        "old_head_block": "0x" + prev_head.hex(),
                        "new_head_block": "0x" + self.head_root.hex(),
                    },
                )
        fin = self.fork_choice.finalized_checkpoint
        if fin.epoch > prev_finalized:
            self.events.emit(
                "finalized_checkpoint",
                {
                    "epoch": str(fin.epoch),
                    "block": "0x" + fin.root.hex(),
                },
            )
        if self.db is not None:
            with trace.stage("db_write"):
                self._persist_import(block_root, signed_block, work)
                if (
                    self.fork_choice.finalized_checkpoint.epoch
                    > prev_finalized
                ):
                    self.archiver.on_finalized(
                        self.fork_choice.finalized_checkpoint
                    )
        if (
            self.light_client_server is not None
            and work.fork_seq >= ForkSeq.altair
        ):
            self.light_client_server.on_import_block(
                block_root, block.body.sync_aggregate, int(block.slot)
            )
        if self.validator_monitor is not None:
            vm = self.validator_monitor
            vm.on_block_imported(block)
            if vm.count and work.fork_seq >= ForkSeq.altair:
                # monitored sync-committee members included in this
                # block's SyncAggregate (registerSyncAggregateInBlock);
                # pubkey->index via the process-wide incremental view —
                # rebuilding a dict here would walk the registry per
                # imported block
                try:
                    from ..statetransition.util import PubkeyIndexView

                    st = work.state
                    pk2i = PubkeyIndexView(st)
                    agg = block.body.sync_aggregate
                    participants = []
                    for pk, bit in zip(
                        st.current_sync_committee.pubkeys,
                        agg.sync_committee_bits,
                    ):
                        if not bit:
                            continue
                        i = pk2i.get(bytes(pk))
                        if i is not None:
                            participants.append(i)
                    if participants:
                        vm.on_sync_aggregate_included(
                            participants, int(block.slot)
                        )
                except Exception:
                    pass  # monitoring must never fail an import
            if vm.count:
                try:
                    self._register_attestations_in_block(
                        vm, work, block
                    )
                except Exception:
                    pass  # monitoring must never fail an import
        return block_root

    def _register_attestations_in_block(self, vm, work, block) -> None:
        """Feed on-chain attestation performance for monitored
        validators from an imported block: inclusion distance plus
        head/target correctness judged against THIS chain's roots
        (reference validatorMonitor.registerAttestationInBlock,
        metrics/validatorMonitor.ts:255 family)."""
        from ..statetransition.block import BlockCtx, get_attesting_indices
        from ..statetransition.util import (
            get_block_root,
            get_block_root_at_slot,
        )

        p = preset()
        st = work.state
        ctx = BlockCtx(self.cfg, st, self.types, work.fork_seq, False)
        monitored = vm.validators.keys()
        for att in block.body.attestations:
            data = att.data
            try:
                indices = get_attesting_indices(ctx, att)
            except Exception:
                continue
            hit = [i for i in indices if i in monitored]
            if not hit:
                continue
            delay = int(block.slot) - int(data.slot)
            try:
                correct_target = bytes(data.target.root) == get_block_root(
                    st, int(data.target.epoch)
                )
            except Exception:
                correct_target = False
            try:
                correct_head = bytes(
                    data.beacon_block_root
                ) == get_block_root_at_slot(st, int(data.slot))
            except Exception:
                correct_head = False
            vm.on_attestation_included(
                hit,
                int(data.slot) // p.SLOTS_PER_EPOCH,
                delay,
                correct_head,
                correct_target,
            )

    async def _notify_new_payload(self, work, block, block_root):
        """engine_newPayload -> fork-choice ExecutionStatus. INVALID
        payloads abort the import (reference: verifyBlock invalid
        handling); SYNCING/ACCEPTED import optimistically."""
        from ..execution.engine import ExecutionPayloadStatus as EPS

        payload = block.body.execution_payload
        versioned_hashes = None
        if work.fork_seq >= ForkSeq.deneb:
            versioned_hashes = [
                b"\x01" + __import__("hashlib").sha256(bytes(c)).digest()[1:]
                for c in block.body.blob_kzg_commitments
            ]
        execution_requests = None
        if work.fork_seq >= ForkSeq.electra:
            # EIP-7685 type-prefixed encodings of non-empty request lists
            er = block.body.execution_requests
            ert = self.types.ExecutionRequests
            execution_requests = [
                bytes([prefix]) + ert.field_types[name].serialize(
                    getattr(er, name)
                )
                for prefix, name in (
                    (0, "deposits"),
                    (1, "withdrawals"),
                    (2, "consolidations"),
                )
                if len(getattr(er, name))
            ]
        from ..execution.engine import ExecutionEngineError

        try:
            st = await self.execution_engine.notify_new_payload(
                work.fork,
                payload,
                versioned_hashes=versioned_hashes,
                parent_root=bytes(block.parent_root),
                execution_requests=execution_requests,
            )
        except ExecutionEngineError as e:
            if getattr(e, "auth_failed", False):
                # Wrong JWT secret: retrying/degrading cannot help and
                # silently importing everything optimistically would
                # mask a fatal misconfiguration — fail the import
                # loudly (reference: AUTH_FAILED is surfaced, not
                # absorbed).
                raise ChainError(
                    "execution engine authentication failed — check "
                    f"the JWT secret: {e}"
                ) from e
            # Engine unreachable (or its breaker is open): degrade to
            # an optimistic import instead of failing the block — the
            # reference's ELERROR handling keeps the node following
            # the chain while the EL flaps, and fork choice marks the
            # block syncing so it is re-judged once the EL returns.
            return ExecutionStatus.syncing
        if st.status in (EPS.VALID,):
            return ExecutionStatus.valid
        if st.status in (EPS.INVALID, EPS.INVALID_BLOCK_HASH):
            raise ChainError(
                f"execution payload invalid: {st.validation_error}"
            )
        return ExecutionStatus.syncing

    async def notify_forkchoice_update(self, attributes=None):
        """engine_forkchoiceUpdated for the current head/finalized pair
        (importBlock.ts / prepareNextSlot fcU). Returns payload_id when
        attributes request a build."""
        if self.execution_engine is None:
            return None
        from ..execution.engine import ForkchoiceState

        head = self.get_or_regen_state(self.head_root)
        if head.fork_seq < ForkSeq.bellatrix:
            return None
        head_hash = bytes(
            head.state.latest_execution_payload_header.block_hash
        )
        try:
            fin = await self.get_state_async(
                self.finalized_checkpoint.root
            )
        except Exception:
            fin = None
        fin_hash = (
            bytes(fin.state.latest_execution_payload_header.block_hash)
            if fin is not None and fin.fork_seq >= ForkSeq.bellatrix
            else b"\x00" * 32
        )
        from ..execution.engine import ExecutionEngineError

        try:
            resp = await self.execution_engine.notify_forkchoice_update(
                head.fork,
                ForkchoiceState(head_hash, head_hash, fin_hash),
                attributes,
            )
        except ExecutionEngineError:
            # fcU is advisory: an unreachable engine must not crash the
            # import/prepare loops. Callers treat a None payload_id as
            # "no engine build available" and fall back locally.
            return None
        return resp.payload_id

    async def send_payload_attributes(self, slot: int, work):
        """fcU with payload attributes only — tells the EL to start
        building (the prepareNextSlot path). Returns payload_id."""
        from ..execution.engine import PayloadAttributes

        st = work.state
        withdrawals = None
        if work.fork_seq >= ForkSeq.capella:
            from ..statetransition.block import (
                BlockCtx,
                get_expected_withdrawals,
            )

            ctx = BlockCtx(self.cfg, st, self.types, work.fork_seq, False)
            withdrawals = get_expected_withdrawals(ctx)[0]
        attrs = PayloadAttributes(
            timestamp=st.genesis_time + slot * self.cfg.SECONDS_PER_SLOT,
            prev_randao=bytes(
                util.get_randao_mix(st, util.get_current_epoch(st))
            ),
            suggested_fee_recipient=b"\x00" * 20,
            withdrawals=withdrawals,
            parent_beacon_block_root=(
                self.head_root if work.fork_seq >= ForkSeq.deneb else None
            ),
        )
        return await self.notify_forkchoice_update(attrs)

    async def prepare_execution_payload(self, slot: int, work):
        """fcU with attributes + getPayload for block production
        (reference: prepareExecutionPayload, produceBlockBody.ts:373).
        Returns (payload, blobs_bundle|None, block_value) — the value
        weighs against builder bids in produceBlockV3's race."""
        from ..execution.engine import ExecutionEngineError

        payload_id = await self.send_payload_attributes(slot, work)
        if payload_id is None:
            return None, None, 0
        try:
            got = await self.execution_engine.get_payload(
                work.fork, payload_id
            )
        except ExecutionEngineError:
            # engine died between fcU and getPayload — report "no
            # engine payload" and let production fall back locally
            return None, None, 0
        return got.execution_payload, got.blobs_bundle, got.block_value

    def _persist_import(self, block_root, signed_block, work) -> None:
        """Write-through on import (importBlock.ts writeBlockInputToDb +
        head/meta updates)."""
        db = self.db
        db.block.put(block_root, (work.fork, signed_block))
        # per-block states are NOT persisted (only the anchor and the
        # archiver's checkpoint states are): resume rebuilds hot states
        # by replaying blocks, matching the reference's block-only
        # importBlock writes
        db.meta.put_raw("head_root", self.head_root)
        db.meta.put_int("latest_slot", int(signed_block.message.slot))
        jc = self.fork_choice.justified_checkpoint
        db.meta.put_raw("justified_root", jc.root)
        db.meta.put_int("justified_epoch", jc.epoch)

    def _is_timely(self, slot: int) -> bool:
        """Arrived within the first interval of its slot per wall clock
        (reference: importBlock.ts proposer-boost timeliness)."""
        import time

        from ..params import INTERVALS_PER_SLOT

        sec_into_slot = (
            time.time()
            - (self.genesis_time + slot * self.cfg.SECONDS_PER_SLOT)
        )
        cutoff = self.cfg.SECONDS_PER_SLOT / INTERVALS_PER_SLOT
        return 0 <= sec_into_slot < cutoff

    def _refresh_justified_balances(self) -> None:
        jr = self.fork_choice.justified_checkpoint.root
        if jr == self._justified_root_seen:
            return
        jview = self._states.get(jr)
        if jview is not None:
            epoch = self.fork_choice.justified_checkpoint.epoch
            reg = jview.state.validators
            self.fork_choice.set_justified_balances(
                [
                    v.effective_balance
                    if v.activation_epoch <= epoch < v.exit_epoch
                    else 0
                    for v in reg
                ]
            )
            self._justified_root_seen = jr

    # -- attestations ------------------------------------------------------

    async def on_attestation(self, attestation, committee) -> bool:
        """Validate an (already committee-resolved) attestation's vote
        and feed fork choice. Signature verification happens upstream
        (gossip batch path / block import)."""
        data = attestation.data
        if not self.fork_choice.has_block(bytes(data.beacon_block_root)):
            return False
        bits = list(attestation.aggregation_bits)
        indices = [int(v) for i, v in enumerate(committee) if bits[i]]
        self.fork_choice.on_attestation(
            indices, bytes(data.beacon_block_root), int(data.target.epoch)
        )
        return True

    # -- block production --------------------------------------------------

    def produce_block(
        self,
        slot: int,
        randao_reveal: bytes,
        attestations=None,
        graffiti: bytes = b"\x00" * 32,
        sync_aggregate=None,
        proposer_slashings=(),
        attester_slashings=(),
        voluntary_exits=(),
        bls_to_execution_changes=(),
        execution_payload=None,
        execution_payload_header=None,
        blobs=None,
        blob_kzg_commitments=None,
        work=None,
    ):
        """Assemble + run the unsigned block, returning (block, post_view).
        Reference: produceBlockWrapper/produceBlockBody (chain.ts:648,
        produceBlockBody.ts). deneb+: `blobs` (list of BYTES_PER_BLOB
        strings) get committed into body.blob_kzg_commitments; the
        caller wraps them into sidecars after signing
        (chain/blobs.blob_sidecars_from_block — the reference returns
        block contents from produceBlockV3 the same way).
        `execution_payload_header` (a builder bid's header,
        produceBlockBody.ts:192 blinded path) produces a
        BlindedBeaconBlock instead — mutually exclusive with
        `execution_payload`; `blob_kzg_commitments` sets the blinded
        body's commitments from the bid. `work` (a PRIVATE clone
        already advanced to `slot`) skips the re-advance — callers
        like produce_block_v3 already paid that epoch transition."""
        types = self.types
        if work is None:
            head = self.get_or_regen_state(self.head_root)
            work = _clone(head, types)
            process_slots(self.cfg, work, slot, types)
        st = work.state
        ns = types.by_fork[work.fork]
        blinded = execution_payload_header is not None
        assert not (blinded and execution_payload is not None)

        block = (
            ns.BlindedBeaconBlock if blinded else ns.BeaconBlock
        ).default()
        block.slot = slot
        block.proposer_index = util.get_beacon_proposer_index(
            st, electra=work.fork_seq >= ForkSeq.electra
        )
        block.parent_root = types.BeaconBlockHeader.hash_tree_root(
            st.latest_block_header
        )
        body = (
            ns.BlindedBeaconBlockBody if blinded else ns.BeaconBlockBody
        ).default()
        body.randao_reveal = randao_reveal
        body.eth1_data = st.eth1_data
        body.graffiti = graffiti
        body.attestations = list(attestations or [])
        body.proposer_slashings = list(proposer_slashings)
        body.attester_slashings = list(attester_slashings)
        body.voluntary_exits = list(voluntary_exits)
        if work.fork_seq >= ForkSeq.altair:
            if sync_aggregate is None:
                sync_aggregate = types.SyncAggregate.default()
                sync_aggregate.sync_committee_bits = [False] * preset().SYNC_COMMITTEE_SIZE
                sync_aggregate.sync_committee_signature = (
                    b"\xc0" + b"\x00" * 95
                )
            body.sync_aggregate = sync_aggregate
        if work.fork_seq >= ForkSeq.capella:
            body.bls_to_execution_changes = list(bls_to_execution_changes)
        if work.fork_seq >= ForkSeq.bellatrix:
            if blinded:
                body.execution_payload_header = execution_payload_header
            else:
                body.execution_payload = (
                    execution_payload
                    if execution_payload is not None
                    else self._build_dev_payload(work, slot)
                )
        if work.fork_seq >= ForkSeq.deneb:
            if blob_kzg_commitments is not None:
                body.blob_kzg_commitments = list(blob_kzg_commitments)
            elif blobs:
                from ..crypto import kzg as _kzg

                body.blob_kzg_commitments = [
                    _kzg.blob_to_kzg_commitment(b) for b in blobs
                ]
        block.body = body

        signed = (
            ns.SignedBlindedBeaconBlock if blinded else ns.SignedBeaconBlock
        ).default()
        signed.message = block
        state_transition(
            self.cfg,
            work,
            signed,
            types,
            verify_state_root=False,
            verify_proposer=False,
            verify_signatures=False,
        )
        block.state_root = work.hash_tree_root(types)
        return block, work

    def _build_dev_payload(self, work: BeaconStateView, slot: int):
        """Deterministic mock execution payload for dev chains
        (reference: ExecutionEngineMockBackend, execution/engine/mock.ts).
        Satisfies process_execution_payload's parent/randao/timestamp
        checks; block_hash is a fake chained hash."""
        from hashlib import sha256

        types = self.types
        st = work.state
        ns = types.by_fork[work.fork]
        payload = ns.ExecutionPayload.default()
        parent_hash = bytes(st.latest_execution_payload_header.block_hash)
        payload.parent_hash = parent_hash
        payload.prev_randao = bytes(
            util.get_randao_mix(st, util.get_current_epoch(st))
        )
        payload.timestamp = (
            st.genesis_time + slot * self.cfg.SECONDS_PER_SLOT
        )
        payload.block_number = slot
        payload.gas_limit = 30_000_000
        payload.block_hash = sha256(
            b"dev-exec" + slot.to_bytes(8, "little") + parent_hash
        ).digest()
        if work.fork_seq >= ForkSeq.capella:
            from ..statetransition.block import (
                BlockCtx,
                get_expected_withdrawals,
            )

            ctx = BlockCtx(self.cfg, st, types, work.fork_seq, False)
            payload.withdrawals = get_expected_withdrawals(ctx)[0]
        return payload

    # -- finality ----------------------------------------------------------

    @property
    def finalized_checkpoint(self) -> Checkpoint:
        return self.fork_choice.finalized_checkpoint

    @property
    def justified_checkpoint(self) -> Checkpoint:
        return self.fork_choice.justified_checkpoint

    async def close(self) -> None:
        await self.verifier.close()

"""Historical state regeneration off the hot path.

Reference analog: HistoricalStateRegen + its worker
(chain/historicalState/index.ts:19, worker.ts) — API queries for
long-finalized states replay from the state archive in a separate
thread so the main loop never blocks on minutes of replay.
"""

from __future__ import annotations

import asyncio

from ..statetransition import state_transition
from ..statetransition.slot import BeaconStateView, process_slots


class HistoricalStateError(Exception):
    pass


class HistoricalStateRegen:
    """Replays archived finalized history: nearest archived state at or
    below the target slot + archived blocks up to it."""

    def __init__(self, chain):
        self.chain = chain
        self.regens = 0
        self.blocks_replayed = 0

    async def get_state_at_slot(self, slot: int) -> BeaconStateView:
        return await asyncio.get_event_loop().run_in_executor(
            None, self._regen_sync, slot
        )

    def _regen_sync(self, slot: int) -> BeaconStateView:
        db = self.chain.db
        if db is None:
            raise HistoricalStateError("no database attached")
        base = None
        base_slot = None
        for s, (fork, state) in db.state_archive.entries(
            end=slot + 1, reverse=True, limit=1
        ):
            base = BeaconStateView(state=state, fork=fork)
            base_slot = s
        if base is None:
            # below the earliest archive: replay from the db anchor
            # (initBeaconState's anchor is always persisted)
            anchor_root = db.meta.get_raw("anchor_root")
            raw = (
                db.state.get_binary(anchor_root)
                if anchor_root is not None
                else None
            )
            if raw is not None:
                fork, state = db.state.decode_value(raw)
                if int(state.slot) <= slot:
                    base = BeaconStateView(state=state, fork=fork)
                    base_slot = int(state.slot)
        if base is None:
            raise HistoricalStateError(
                f"no archived state at or below slot {slot}"
            )
        from .chain import _clone

        work = _clone(base, self.chain.types)
        self.regens += 1
        if base_slot == slot:
            return work
        for s, (fork, block) in db.block_archive.entries(
            start=base_slot + 1, end=slot + 1
        ):
            process_slots(
                self.chain.cfg, work, int(block.message.slot),
                self.chain.types,
            )
            state_transition(
                self.chain.cfg,
                work,
                block,
                self.chain.types,
                verify_state_root=True,
                verify_proposer=False,
                verify_signatures=False,
            )
            self.blocks_replayed += 1
        if int(work.state.slot) < slot:
            process_slots(self.chain.cfg, work, slot, self.chain.types)
        return work

"""Chain event bus feeding the beacon events API (SSE).

Reference analog: ChainEventEmitter + the events route
(api/impl/events) — block import / head update / finality emit typed
events that SSE subscribers stream.
"""

from __future__ import annotations

import queue
import threading

TOPICS = (
    "head",
    "block",
    "finalized_checkpoint",
    "chain_reorg",
    "attestation",
)


class ChainEventEmitter:
    """Thread-safe fan-out: the chain emits on the asyncio loop; SSE
    handlers consume from server threads via per-subscriber queues."""

    def __init__(self, max_queued: int = 256):
        self._subs: list[tuple[set, queue.Queue]] = []
        self._lock = threading.Lock()
        self.max_queued = max_queued
        self.emitted = 0

    def subscribe(self, topics) -> queue.Queue:
        q: queue.Queue = queue.Queue(self.max_queued)
        with self._lock:
            self._subs.append((set(topics), q))
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            self._subs = [(t, s) for t, s in self._subs if s is not q]

    def emit(self, topic: str, data: dict) -> None:
        self.emitted += 1
        with self._lock:
            subs = list(self._subs)
        for topics, q in subs:
            if topic in topics:
                try:
                    q.put_nowait((topic, data))
                except queue.Full:
                    pass  # slow consumer: drop (SSE is lossy by design)

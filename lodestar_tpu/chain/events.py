"""Chain event bus feeding the beacon events API (SSE).

Reference analog: ChainEventEmitter + the events route
(api/impl/events) — block import / head update / finality emit typed
events that SSE subscribers stream.

Broadcast model (ISSUE 20): `emit` serializes each event to its SSE
wire frame ONCE and fans the bytes out to bounded per-subscriber
queues. A subscriber whose queue is full is EVICTED (its frames were
already being dropped — a wedged consumer never slows the emitter or
other subscribers) and the drop is counted per topic, never silent.
A subscriber cap bounds the fan-out itself; the REST server turns a
refused subscribe into a 503 + Retry-After.

Synchronous listeners (`add_listener`) ride the same emit path for
in-process consumers that must see every event without a queue — the
API response cache invalidates on head/finality through one.
"""

from __future__ import annotations

import json
import queue
import threading

TOPICS = (
    "head",
    "block",
    "finalized_checkpoint",
    "chain_reorg",
    "attestation",
)


def encode_sse_frame(topic: str, data: dict) -> bytes:
    """The SSE wire frame for one event — built once per emit, not
    once per subscriber."""
    return (f"event: {topic}\ndata: {json.dumps(data)}\n\n").encode()


class Subscription:
    """One SSE consumer: a topic filter and a bounded frame queue.

    `evicted` flips (under the emitter lock) when the queue overflowed
    and the emitter dropped the subscriber; the SSE handler checks it
    on its keep-alive tick and terminates the stream.
    """

    __slots__ = ("topics", "q", "evicted")

    def __init__(self, topics, max_queued: int):
        self.topics = set(topics)
        self.q: queue.Queue = queue.Queue(max_queued)
        self.evicted = False


class ChainEventEmitter:
    """Thread-safe fan-out: the chain emits on the asyncio loop; SSE
    handlers consume from server threads via per-subscriber queues."""

    def __init__(self, max_queued: int = 256, max_subscribers: int = 64):
        self._subs: list[Subscription] = []
        self._listeners: list = []
        self._lock = threading.Lock()
        self.max_queued = max_queued
        self.max_subscribers = max_subscribers
        self.emitted = 0
        # telemetry ledgers (lodestar_api_sse_* at scrape time)
        self.dropped: dict[str, int] = {}  # topic -> frames dropped
        self.evictions = 0
        self.subscribe_refusals = 0

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def subscribe(self, topics) -> Subscription | None:
        """Returns None when the subscriber cap is reached (the caller
        must refuse the stream, not queue it)."""
        with self._lock:
            if len(self._subs) >= self.max_subscribers:
                self.subscribe_refusals += 1
                return None
            sub = Subscription(topics, self.max_queued)
            self._subs.append(sub)
            return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            self._subs = [s for s in self._subs if s is not sub]

    def add_listener(self, fn) -> None:
        """Register a synchronous `fn(topic, data)` called inline on
        every emit (cache invalidation, tests). Exceptions are
        swallowed: a broken listener must not break block import."""
        with self._lock:
            self._listeners.append(fn)

    def emit(self, topic: str, data: dict) -> None:
        self.emitted += 1
        with self._lock:
            listeners = list(self._listeners)
            subs = [s for s in self._subs if topic in s.topics]
        for fn in listeners:
            try:
                fn(topic, data)
            except Exception:
                pass
        if not subs:
            return
        frame = encode_sse_frame(topic, data)  # serialize once
        evicted = []
        for sub in subs:
            try:
                sub.q.put_nowait(frame)
            except queue.Full:
                # slow consumer: count the drop and evict the
                # subscriber — the emitter never blocks, the event is
                # never silently lost from the accounting
                evicted.append(sub)
        if evicted:
            with self._lock:
                for sub in evicted:
                    self.dropped[topic] = self.dropped.get(topic, 0) + 1
                    if not sub.evicted:
                        sub.evicted = True
                        self.evictions += 1
                self._subs = [
                    s for s in self._subs if not s.evicted
                ]

"""Blob sidecar production + validation (deneb data availability).

Reference analog: chain/validation/blobSidecar.ts
(validateBlobSidecars: index bounds, header/block binding, KZG
commitment inclusion proof, batched KZG proof verification) and
produceBlock blob bundle assembly
(produceBlock/validateBlobsAndKzgCommitments.ts). KZG math:
crypto/kzg.py (c-kzg analog) — a full max-blobs block's batched
proof check is ONE random-lincomb verification whose three MSMs ride
a single device dispatch on the TPU Pippenger backend (ops/msm.py),
with host-C and pure-Python fallback tiers.
"""

from __future__ import annotations

from ..crypto import kzg
from ..params import preset
from ..ssz.proofs import (
    container_field_branch,
    is_valid_merkle_branch,
    merkle_branch,
)


class BlobError(ValueError):
    pass


def _commitment_list_layout(types, fork: str):
    body_t = types.by_fork[fork].BeaconBlockBody
    ct = body_t.field_types["blob_kzg_commitments"]
    list_depth = (ct.limit - 1).bit_length()
    field_idx = body_t.field_names.index("blob_kzg_commitments")
    field_depth = (len(body_t.fields) - 1).bit_length()
    return body_t, ct, list_depth, field_idx, field_depth


def inclusion_proof_gindex(types, fork: str, index: int) -> tuple[int, int]:
    """(path_index, depth) of commitment `index` under the body root:
    list chunks (list_depth) -> length mix-in (1) -> body field tree."""
    _, _, list_depth, field_idx, field_depth = _commitment_list_layout(
        types, fork
    )
    depth = list_depth + 1 + field_depth
    path = (field_idx << (list_depth + 1)) | index  # mix-in bit = 0
    return path, depth


def compute_inclusion_proof(types, fork: str, body, index: int) -> list[bytes]:
    """Sibling branch proving body.blob_kzg_commitments[index] against
    the body's hash tree root."""
    body_t, ct, list_depth, field_idx, _ = _commitment_list_layout(
        types, fork
    )
    comms = body.blob_kzg_commitments
    chunks = [ct.element_type.hash_tree_root(c) for c in comms]
    inner = merkle_branch(chunks, index, limit=ct.limit)
    length_leaf = len(comms).to_bytes(32, "little")
    _, field_branch, _ = container_field_branch(
        body_t, body, "blob_kzg_commitments"
    )
    return inner + [length_leaf] + field_branch


def verify_blob_sidecar_inclusion_proof(types, fork: str, sidecar) -> bool:
    """Spec verify_blob_sidecar_inclusion_proof."""
    _, ct, _, _, _ = _commitment_list_layout(types, fork)
    path, depth = inclusion_proof_gindex(types, fork, int(sidecar.index))
    leaf = ct.element_type.hash_tree_root(sidecar.kzg_commitment)
    return is_valid_merkle_branch(
        leaf,
        [bytes(b) for b in sidecar.kzg_commitment_inclusion_proof],
        depth,
        path,
        bytes(sidecar.signed_block_header.message.body_root),
    )


def blob_sidecars_from_block(
    types, fork: str, signed_block, blobs: list[bytes], proofs: list[bytes]
) -> list:
    """Producer side: wrap blobs into BlobSidecars with inclusion
    proofs (reference: beacon API publishBlock blob bundle split)."""
    ns = types.by_fork[fork]
    body = signed_block.message.body
    comms = body.blob_kzg_commitments
    if not (len(blobs) == len(proofs) == len(comms)):
        raise BlobError("blobs/proofs/commitments length mismatch")
    header = types.BeaconBlockHeader.default()
    header.slot = signed_block.message.slot
    header.proposer_index = signed_block.message.proposer_index
    header.parent_root = bytes(signed_block.message.parent_root)
    header.state_root = bytes(signed_block.message.state_root)
    header.body_root = ns.BeaconBlockBody.hash_tree_root(body)
    signed_header = types.SignedBeaconBlockHeader.default()
    signed_header.message = header
    signed_header.signature = bytes(signed_block.signature)
    out = []
    for i, (blob, proof, comm) in enumerate(zip(blobs, proofs, comms)):
        sc = ns.BlobSidecar.default()
        sc.index = i
        sc.blob = bytes(blob)
        sc.kzg_commitment = bytes(comm)
        sc.kzg_proof = bytes(proof)
        sc.signed_block_header = signed_header
        sc.kzg_commitment_inclusion_proof = compute_inclusion_proof(
            types, fork, body, i
        )
        out.append(sc)
    return out


def validate_blob_sidecars(
    types, fork: str, block_root: bytes, block, sidecars
) -> None:
    """Data-availability check for an imported block: every commitment
    must be covered by a sidecar bound to this block, with a valid
    inclusion proof and a valid (batched) KZG proof. Raises BlobError.
    Reference: validateBlobSidecars (chain/validation/blobSidecar.ts) +
    verifyBlocksDataAvailability (chain/blocks/)."""
    p = preset()
    comms = [bytes(c) for c in block.body.blob_kzg_commitments]
    if len(sidecars) != len(comms):
        raise BlobError(
            f"expected {len(comms)} sidecars, got {len(sidecars)}"
        )
    header_t = types.BeaconBlockHeader
    for i, sc in enumerate(sidecars):
        if int(sc.index) != i:
            raise BlobError(f"sidecar {i} has index {int(sc.index)}")
        if int(sc.index) >= p.MAX_BLOB_COMMITMENTS_PER_BLOCK:
            raise BlobError("sidecar index out of range")
        if bytes(sc.kzg_commitment) != comms[i]:
            raise BlobError(f"sidecar {i} commitment mismatch")
        hdr_root = header_t.hash_tree_root(sc.signed_block_header.message)
        if hdr_root != block_root:
            raise BlobError(f"sidecar {i} not bound to block")
        if not verify_blob_sidecar_inclusion_proof(types, fork, sc):
            raise BlobError(f"sidecar {i} inclusion proof invalid")
    if comms:
        ok = kzg.verify_blob_kzg_proof_batch(
            [bytes(sc.blob) for sc in sidecars],
            comms,
            [bytes(sc.kzg_proof) for sc in sidecars],
        )
        if not ok:
            raise BlobError("batched blob KZG proof verification failed")

"""DevNode: single-process dev chain with in-proc interop validators.

Reference analog: `lodestar dev` (cli/src/cmds/dev/) — instant-genesis
local chain where one process hosts the beacon chain and all validator
duties (propose, attest, sync-committee). Every block goes through the
FULL import pipeline: signature-set extraction -> batch verification on
the verifier service (TPU kernels) -> state transition -> fork choice.
This is SURVEY.md §7 step 4's minimum end-to-end slice.
"""

from __future__ import annotations

from ..crypto.bls.signature import aggregate_signatures, sign
from ..params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    ForkSeq,
    preset,
)
from ..ssz import uint64 as ssz_uint64
from ..statetransition import create_interop_genesis_state, interop_secret_key, util
from ..statetransition.block import compute_signing_root, get_domain
from ..config.beacon_config import compute_signing_root_from_roots
from .chain import BeaconChain
from .oppools import AggregatedAttestationPool


class DevNode:
    def __init__(
        self,
        cfg,
        types,
        n_validators: int,
        verifier=None,
        genesis_time: int = 0,
        verify_attestations: bool = True,
        db=None,
        blobs_per_block: int = 0,
    ):
        self.cfg = cfg
        self.types = types
        self.n = n_validators
        genesis = create_interop_genesis_state(
            cfg, types, n_validators, genesis_time=genesis_time
        )
        self.chain = BeaconChain(
            cfg, types, genesis, verifier=verifier, db=db
        )
        self.sks = {
            i: interop_secret_key(i) for i in range(n_validators)
        }
        self.att_pool = AggregatedAttestationPool(types)
        self.slot = genesis.state.slot
        self.verify_attestations = verify_attestations
        # deneb dev chains: commit this many deterministic blobs per
        # block (requires an active KZG trusted setup)
        self.blobs_per_block = blobs_per_block

    def _make_blobs(self, slot: int, scratch) -> list[bytes] | None:
        """Deterministic blobs for deneb+ dev blocks."""
        if not self.blobs_per_block or scratch.fork_seq < ForkSeq.deneb:
            return None
        from hashlib import sha256

        from ..crypto.kzg import BLS_MODULUS, FIELD_ELEMENTS_PER_BLOB

        out = []
        for bi in range(self.blobs_per_block):
            blob = bytearray()
            for i in range(FIELD_ELEMENTS_PER_BLOB):
                v = (
                    int.from_bytes(
                        sha256(
                            slot.to_bytes(8, "little")
                            + bi.to_bytes(4, "little")
                            + i.to_bytes(4, "little")
                        ).digest(),
                        "big",
                    )
                    % BLS_MODULUS
                )
                blob += v.to_bytes(32, "big")
            out.append(bytes(blob))
        return out

    # -- duties ----------------------------------------------------------

    def _sign_attestation(self, st, committee, data):
        types = self.types
        domain = get_domain(
            self.cfg, st, DOMAIN_BEACON_ATTESTER, int(data.target.epoch)
        )
        root = compute_signing_root(types.AttestationData, data, domain)
        sigs = [sign(self.sks[int(v)], root) for v in committee]
        att = types.Attestation.default()
        att.data = data
        att.aggregation_bits = [True] * len(committee)
        att.signature = aggregate_signatures(sigs)
        return att

    async def _attest_head(self) -> None:
        """All committees of the current slot attest to the head block
        (validator AttestationService analog, attestation.ts:35)."""
        types = self.types
        head_root = self.chain.head_root
        view = self.chain.get_state(head_root)
        st = view.state
        s = self.slot
        epoch = util.compute_epoch_at_slot(s)
        sh = util.get_shuffling(st, epoch)
        try:
            target_root = util.get_block_root(st, epoch)
        except ValueError:
            target_root = head_root  # epoch-start block is the head
        for ci, committee in enumerate(sh.committees_at_slot(s)):
            if not len(committee):
                continue
            data = types.AttestationData.default()
            data.slot = s
            data.index = ci
            data.beacon_block_root = head_root
            data.source = st.current_justified_checkpoint
            tgt = types.Checkpoint.default()
            tgt.epoch = epoch
            tgt.root = target_root
            data.target = tgt
            att = self._sign_attestation(st, committee, data)
            if self.verify_attestations:
                from ..statetransition.signature_sets import SignatureSet
                from ..crypto.bls.signature import aggregate_pubkeys

                domain = get_domain(
                    self.cfg, st, DOMAIN_BEACON_ATTESTER, epoch
                )
                root = compute_signing_root(
                    types.AttestationData, data, domain
                )
                pk = aggregate_pubkeys(
                    [bytes(st.validators[int(v)].pubkey) for v in committee]
                )
                ok = await self.chain.verifier.verify_signature_sets(
                    [SignatureSet(pk, root, bytes(att.signature))],
                    batchable=True,
                )
                if not ok:
                    raise RuntimeError("gossip attestation failed verify")
            self.att_pool.add(att)
            await self.chain.on_attestation(att, committee)

    def _sync_aggregate_for(self, parent_view, slot: int):
        """Sync committee signs the previous slot's block root
        (SyncCommitteeService analog)."""
        types = self.types
        st = parent_view.state
        if parent_view.fork_seq < ForkSeq.altair:
            return None
        prev_slot = max(slot, 1) - 1
        block_root = self.chain.head_root
        domain = get_domain(
            self.cfg,
            st,
            DOMAIN_SYNC_COMMITTEE,
            util.compute_epoch_at_slot(prev_slot),
        )
        root = compute_signing_root_from_roots(block_root, domain)
        pubkey2index = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        sigs = []
        bits = []
        for pk in st.current_sync_committee.pubkeys:
            idx = pubkey2index[bytes(pk)]
            sigs.append(sign(self.sks[idx], root))
            bits.append(True)
        sa = types.SyncAggregate.default()
        sa.sync_committee_bits = bits
        sa.sync_committee_signature = aggregate_signatures(sigs)
        return sa

    async def advance_slot(self) -> bytes:
        """One full slot: propose (with pooled attestations + sync
        aggregate), import through the verify pipeline, then attest."""
        self.slot += 1
        slot = self.slot
        types = self.types
        head = self.chain.get_or_regen_state(self.chain.head_root)

        # advance a scratch clone to compute proposer + domains
        from .chain import _clone
        from ..statetransition.slot import process_slots

        scratch = _clone(head, types)
        process_slots(self.cfg, scratch, slot, types)
        st = scratch.state
        proposer = util.get_beacon_proposer_index(
            st, electra=scratch.fork_seq >= ForkSeq.electra
        )
        sk = self.sks[proposer]
        epoch = util.get_current_epoch(st)
        randao_reveal = sign(
            sk,
            compute_signing_root(
                ssz_uint64, epoch, get_domain(self.cfg, st, DOMAIN_RANDAO)
            ),
        )
        attestations = self.att_pool.get_attestations_for_block(
            slot, state=st
        )
        sync_aggregate = self._sync_aggregate_for(scratch, slot)

        blobs = self._make_blobs(slot, scratch)
        block, post = self.chain.produce_block(
            slot,
            randao_reveal,
            attestations=attestations,
            sync_aggregate=sync_aggregate,
            blobs=blobs,
        )
        ns = types.by_fork[post.fork]
        signed = ns.SignedBeaconBlock.default()
        signed.message = block
        signed.signature = sign(
            sk,
            compute_signing_root(
                ns.BeaconBlock,
                block,
                get_domain(self.cfg, post.state, DOMAIN_BEACON_PROPOSER),
            ),
        )
        sidecars = None
        if blobs:
            from ..crypto import kzg as _kzg
            from .blobs import blob_sidecars_from_block

            proofs = [
                _kzg.compute_blob_kzg_proof(
                    b, bytes(c)
                )
                for b, c in zip(
                    blobs, block.body.blob_kzg_commitments
                )
            ]
            sidecars = blob_sidecars_from_block(
                types, post.fork, signed, blobs, proofs
            )
        # simulated clock: every self-produced block is at its slot start
        root = await self.chain.process_block(
            signed, is_timely=True, blob_sidecars=sidecars
        )
        await self._attest_head()
        self.att_pool.prune(slot)
        return root

    async def run_until(self, slot: int) -> None:
        while self.slot < slot:
            await self.advance_slot()

    async def close(self) -> None:
        await self.chain.close()

"""Gossip object validation.

Reference analog: beacon-node/src/chain/validation/ — per-type gossip
validators returning ACCEPT/IGNORE/REJECT, with the batched
attestation path (`validateGossipAttestationsSameAttData`,
attestation.ts:92) that feeds the TPU same-message kernel.
"""

from .attestation import (
    AttestationValidator,
    GossipAction,
    GossipValidationError,
)
from .aggregate import AggregateAndProofValidator
from .block import GossipBlockValidator
from .sync_committee import SyncCommitteeValidator

__all__ = [
    "AggregateAndProofValidator",
    "AttestationValidator",
    "GossipAction",
    "GossipBlockValidator",
    "GossipValidationError",
    "SyncCommitteeValidator",
]

"""Gossip object validation.

Reference analog: beacon-node/src/chain/validation/ — per-type gossip
validators returning ACCEPT/IGNORE/REJECT, with the batched
attestation path (`validateGossipAttestationsSameAttData`,
attestation.ts:92) that feeds the TPU same-message kernel.
"""

from .attestation import (
    AttestationValidator,
    GossipAction,
    GossipValidationError,
)

__all__ = [
    "AttestationValidator",
    "GossipAction",
    "GossipValidationError",
]

"""Gossip block pre-validation: cheap checks BEFORE full import.

Reference analog: chain/validation/block.ts (validateGossipBlock,
:27-174) — slot window, finalized ancestry, parent known, proposer
equivocation via SeenBlockProposers (seenBlockProposers.ts:11),
expected proposer index, and the proposer signature — all WITHOUT
running the state transition, so a DoS block costs one signature check
instead of a full import (round-3 verdict weak #6: the old handler ran
`chain.process_block` to decide ACCEPT/REJECT).
"""

from __future__ import annotations

from ...statetransition import util
from ...statetransition.signature_sets import proposer_signature_set
from ..seen_caches import SeenBlockProposers
from .attestation import GossipAction, GossipValidationError

MAXIMUM_GOSSIP_CLOCK_DISPARITY_SLOTS = 1


class GossipBlockValidator:
    """Owns the proposer-equivocation cache and the pre-import checks.
    ACCEPT means "forward + import"; the full import still runs its own
    complete signature/transition verification."""

    def __init__(self, cfg, types, chain, verifier):
        self.cfg = cfg
        self.types = types
        self.chain = chain
        self.verifier = verifier
        self.seen_proposers = SeenBlockProposers()
        self.clock_slot = 0
        # small memo of fork-advanced parent views keyed by
        # (parent_root, epoch): fork boundaries are rare, but spam AT
        # the boundary must not force a fresh epoch transition per
        # gossip message — and a one-entry memo would thrash when two
        # viable head candidates alternate (a one-block reorg)
        self._fork_view_cache: dict = {}
        self._fork_view_cache_max = 4

    def on_slot(self, slot: int) -> None:
        self.clock_slot = slot

    def prune(self, finalized_slot: int) -> None:
        self.seen_proposers.prune(finalized_slot)

    async def validate(self, signed_block, fork: str) -> GossipAction:
        """Raises GossipValidationError on IGNORE/REJECT. Mirrors
        validateGossipBlock's ordered conditions (block.ts:40-170)."""
        block = signed_block.message
        slot = int(block.slot)
        proposer = int(block.proposer_index)

        # [IGNORE] future slot beyond clock disparity (:44)
        if slot > self.clock_slot + MAXIMUM_GOSSIP_CLOCK_DISPARITY_SLOTS:
            raise GossipValidationError(
                GossipAction.IGNORE, f"future slot {slot}"
            )
        # [IGNORE] at or before the finalized slot (:52)
        fin_epoch = self.chain.fork_choice.finalized_checkpoint.epoch
        fin_slot = fin_epoch * util.preset().SLOTS_PER_EPOCH
        if slot <= fin_slot:
            raise GossipValidationError(
                GossipAction.IGNORE, "slot already finalized"
            )
        # [IGNORE] proposer equivocation: one block per (slot, proposer)
        # (:64 seenBlockProposers; equivocations go to slashing, not
        # the mesh)
        if self.seen_proposers.is_known(slot, proposer):
            raise GossipValidationError(
                GossipAction.IGNORE, "proposer already proposed this slot"
            )
        # [IGNORE] parent must be known (unknown-parent -> sync) (:80)
        parent = bytes(block.parent_root)
        if not self.chain.fork_choice.has_block(parent):
            raise GossipValidationError(
                GossipAction.IGNORE, "unknown parent"
            )
        # [REJECT] parent must descend from finalized (:88)
        if not self.chain.fork_choice.is_descendant_of_finalized(parent):
            raise GossipValidationError(
                GossipAction.REJECT, "parent not descendant of finalized"
            )
        # [REJECT] slot must be after the parent's (:96)
        parent_node = self.chain.fork_choice.proto.get_node(parent)
        parent_slot = parent_node.slot if parent_node else None
        if parent_slot is not None and slot <= parent_slot:
            raise GossipValidationError(
                GossipAction.REJECT, "slot not after parent"
            )
        # proposer index + signature against the parent's state
        # advanced to the block's epoch (:110-150)
        view = self.chain.get_state(parent) or self.chain.head_state
        state = view.state
        if proposer >= len(state.validators):
            raise GossipValidationError(
                GossipAction.REJECT, "unknown proposer index"
            )
        # When the parent state is still on the PREVIOUS fork (first
        # blocks after a fork boundary), advance a clone through the
        # fork upgrade first: get_domain reads state.fork, so the
        # un-upgraded version would REJECT valid blocks — and skipping
        # the checks would open a signature-free forwarding window.
        # An advance failure is a LOCAL error, not an attributable
        # message fault -> IGNORE, never REJECT (don't downscore the
        # relaying peers for our own state-regen trouble).
        sig_view = view
        if view.fork != fork:
            try:
                sig_view = self._fork_advanced_view(view, parent, slot)
            except Exception as e:
                raise GossipValidationError(
                    GossipAction.IGNORE,
                    f"fork-boundary state advance failed: {e}",
                ) from e
        # [REJECT] expected proposer (:160) — computed from the
        # (possibly fork-advanced) parent state's shuffling when the
        # epochs line up; a mismatched proposer is an equivocation
        # attempt
        try:
            expected = self._expected_proposer(sig_view, slot)
        except Exception:
            expected = None
        if expected is not None and expected != proposer:
            raise GossipValidationError(
                GossipAction.REJECT, "wrong proposer for slot"
            )
        # [REJECT] proposer signature (:150) through the TPU verifier.
        try:
            sig_set = self._proposer_set(sig_view, signed_block, fork)
        except Exception as e:
            raise GossipValidationError(
                GossipAction.REJECT,
                f"signature set build failed: {e}",
            ) from e
        ok = await self.verifier.verify_signature_sets(
            [sig_set], priority=True
        )
        if not ok:
            raise GossipValidationError(
                GossipAction.REJECT, "invalid proposer signature"
            )
        # double-observation after async verify (block.ts:64 re-check)
        if self.seen_proposers.is_known(slot, proposer):
            raise GossipValidationError(
                GossipAction.IGNORE, "proposer seen during verification"
            )
        self.seen_proposers.add(slot, proposer)
        return GossipAction.ACCEPT

    def _fork_advanced_view(self, view, parent_root: bytes, slot: int):
        """Clone of the parent state advanced (process_slots) to the
        first slot of the block's epoch, applying every fork upgrade on
        the way, so the proposer-signature domain is built from the
        block's fork. Memoized per (parent, epoch) — boundary spam must
        not buy an epoch transition per message."""
        epoch = util.compute_epoch_at_slot(slot)
        key = (parent_root, epoch)
        hit = self._fork_view_cache.get(key)
        if hit is not None:
            return hit
        from ...statetransition.slot import process_slots
        from ..chain import _clone

        scratch = _clone(view, self.types)
        target = max(
            epoch * util.preset().SLOTS_PER_EPOCH,
            int(view.state.slot),
        )
        process_slots(self.cfg, scratch, target, self.types)
        if len(self._fork_view_cache) >= self._fork_view_cache_max:
            self._fork_view_cache.pop(
                next(iter(self._fork_view_cache))
            )
        self._fork_view_cache[key] = scratch
        return scratch

    def _expected_proposer(self, view, slot: int) -> int | None:
        """Proposer for `slot` from the parent state, only when the
        parent state is already in the block's epoch (no per-gossip
        epoch transition — the full import recomputes exactly)."""
        state = view.state
        if util.compute_epoch_at_slot(
            slot
        ) != util.compute_epoch_at_slot(int(state.slot)):
            return None
        from ..chain import _clone
        from ...statetransition.slot import process_slots

        if int(state.slot) == slot:
            scratch = view
        else:
            scratch = _clone(view, self.types)
            process_slots(self.cfg, scratch, slot, self.types)
        from ...params import ForkSeq

        return util.get_beacon_proposer_index(
            scratch.state, electra=scratch.fork_seq >= ForkSeq.electra
        )

    def _proposer_set(self, view, signed_block, fork: str):
        """Proposer SignatureSet with the domain at the BLOCK's epoch
        (the parent state may be a fork behind)."""
        from ...params import DOMAIN_BEACON_PROPOSER
        from ...bls.api import SignatureSet
        from ...statetransition.block import (
            compute_signing_root,
            get_domain,
        )

        state = view.state
        block = signed_block.message
        epoch = util.compute_epoch_at_slot(int(block.slot))
        domain = get_domain(
            self.cfg, state, DOMAIN_BEACON_PROPOSER, epoch
        )
        block_t = self.types.by_fork[fork].BeaconBlock
        root = compute_signing_root(block_t, block, domain)
        return SignatureSet(
            bytes(state.validators[int(block.proposer_index)].pubkey),
            root,
            bytes(signed_block.signature),
        )

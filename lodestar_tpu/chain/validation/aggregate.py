"""Gossip aggregate-and-proof validation.

Reference analog: chain/validation/aggregateAndProof.ts
(validateGossipAggregateAndProof, :49) — the spec p2p conditions plus
THREE signature sets verified as one batch (:253):
selection proof (DOMAIN_SELECTION_PROOF over the slot), the
aggregator's AggregateAndProof signature
(DOMAIN_AGGREGATE_AND_PROOF), and the aggregate attestation itself
(DOMAIN_BEACON_ATTESTER, fast-aggregate-verify over the participant
pubkeys). All three ride the TPU verifier's batch path.
"""

from __future__ import annotations

import numpy as np

from ...bls import api as bls_api
from ...params import DOMAIN_AGGREGATE_AND_PROOF, DOMAIN_SELECTION_PROOF
from ...config.beacon_config import compute_signing_root_from_roots
from ...crypto.bls.signature import aggregate_pubkeys
from ...ssz import uint64 as ssz_uint64
from ...statetransition.block import compute_signing_root, get_domain
from ...validator.validator import is_aggregator
from ..seen_caches import SeenAggregators
from .attestation import (
    ATTESTATION_PROPAGATION_SLOT_RANGE,
    GossipAction,
    GossipValidationError,
)


class AggregateAndProofValidator:
    """Validates SignedAggregateAndProof from gossip or the API.

    Shares the attestation validator's resolved attData cache (target /
    committee / signing-root work is identical) and owns the
    SeenAggregators dedup cache."""

    def __init__(self, cfg, types, chain, verifier, att_validator):
        self.cfg = cfg
        self.types = types
        self.chain = chain
        self.verifier = verifier
        self.att_validator = att_validator  # reuses _resolve_att_data
        self.seen_aggregators = SeenAggregators()

    def on_slot(self, slot: int) -> None:
        pass  # seen cache prunes by finalized epoch via prune()

    def prune(self, finalized_epoch: int) -> None:
        self.seen_aggregators.prune(finalized_epoch)

    async def validate(self, signed_agg) -> GossipAction:
        """Raises GossipValidationError on IGNORE/REJECT; returns
        ACCEPT. Reference: validateAggregateAndProof (:101-260)."""
        agg_and_proof = signed_agg.message
        aggregate = agg_and_proof.aggregate
        data = aggregate.data
        slot = int(data.slot)
        agg_index = int(agg_and_proof.aggregator_index)
        target_epoch = int(data.target.epoch)
        index = int(data.index)

        # [IGNORE] propagation window (aggregateAndProof.ts:118)
        clock = self.att_validator.clock_slot
        if not (
            slot <= clock + 1
            and clock <= slot + ATTESTATION_PROPAGATION_SLOT_RANGE
        ):
            raise GossipValidationError(
                GossipAction.IGNORE, "outside propagation slot range"
            )
        # [IGNORE] one aggregate per (epoch, committee, aggregator)
        # (:151 seenAggregators)
        if self.seen_aggregators.is_known_agg(
            target_epoch, index, agg_index
        ):
            raise GossipValidationError(
                GossipAction.IGNORE, "aggregator already seen"
            )
        # [REJECT] must have participants (:143)
        bits = np.asarray(aggregate.aggregation_bits, bool)
        if bits.sum() == 0:
            raise GossipValidationError(
                GossipAction.REJECT, "empty aggregation bits"
            )
        # attData-level checks: target/head/committee resolution, shared
        # cache with the unaggregated path (raises IGNORE/REJECT)
        key = self.att_validator.att_data_key(data)
        entry = self.att_validator._resolve_att_data(data, key)
        committee = entry.committee
        # [REJECT] bits length must match the committee (:190)
        if len(bits) != len(committee):
            raise GossipValidationError(
                GossipAction.REJECT, "bits/committee length mismatch"
            )
        # [REJECT] aggregator must be in the committee (:196)
        if agg_index not in set(int(v) for v in committee):
            raise GossipValidationError(
                GossipAction.REJECT, "aggregator not in committee"
            )
        # [REJECT] selection proof must select the aggregator (:183)
        proof = bytes(agg_and_proof.selection_proof)
        if not is_aggregator(len(committee), proof):
            raise GossipValidationError(
                GossipAction.REJECT, "selection proof not aggregator"
            )

        view = self.chain.get_state(
            bytes(data.beacon_block_root)
        ) or self.chain.head_state
        state = view.state
        validators = state.validators
        if agg_index >= len(validators):
            raise GossipValidationError(
                GossipAction.REJECT, "unknown aggregator index"
            )
        agg_pubkey = bytes(validators[agg_index].pubkey)

        # the three signature sets (:253 getAggregateAndProofSigSets)
        sets = []
        # 1. selection proof over the slot
        sel_domain = get_domain(
            self.cfg, state, DOMAIN_SELECTION_PROOF, target_epoch
        )
        sets.append(
            bls_api.SignatureSet(
                agg_pubkey,
                compute_signing_root_from_roots(
                    ssz_uint64.hash_tree_root(slot), sel_domain
                ),
                proof,
            )
        )
        # 2. aggregator signature over AggregateAndProof
        ap_domain = get_domain(
            self.cfg, state, DOMAIN_AGGREGATE_AND_PROOF, target_epoch
        )
        sets.append(
            bls_api.SignatureSet(
                agg_pubkey,
                compute_signing_root(
                    self.types.AggregateAndProof, agg_and_proof, ap_domain
                ),
                bytes(signed_agg.signature),
            )
        )
        # 3. the aggregate itself: fast-aggregate-verify over the
        # participant pubkeys on the cached attData signing root
        participants = [
            int(committee[i]) for i in np.flatnonzero(bits)
        ]
        pubkeys = [bytes(validators[v].pubkey) for v in participants]
        try:
            agg_pk = aggregate_pubkeys(pubkeys)
        except Exception as e:
            raise GossipValidationError(
                GossipAction.REJECT, f"bad participant pubkey: {e}"
            ) from e
        sets.append(
            bls_api.SignatureSet(
                agg_pk, entry.signing_root, bytes(aggregate.signature)
            )
        )
        ok = await self.verifier.verify_signature_sets(sets)
        if not ok:
            raise GossipValidationError(
                GossipAction.REJECT, "invalid signature"
            )
        # re-check after the async verify (:151 double-observation)
        if self.seen_aggregators.is_known_agg(
            target_epoch, index, agg_index
        ):
            raise GossipValidationError(
                GossipAction.IGNORE, "aggregator seen during verification"
            )
        self.seen_aggregators.add_agg(target_epoch, index, agg_index)
        # feed fork choice with the aggregate's votes
        self.chain.fork_choice.on_attestation(
            participants, bytes(data.beacon_block_root), target_epoch
        )
        vm = getattr(self.chain, "validator_monitor", None)
        if vm is not None and vm.count:
            vm.on_aggregate_participation(participants, target_epoch)
        return GossipAction.ACCEPT

"""Gossip attestation validation — batched same-attData path.

Reference analog: chain/validation/attestation.ts —
`validateGossipAttestationsSameAttData` (:92) and
`validateAttestation` (:134-142): per-key checks run once and are
cached in `SeenAttestationDatas`; per-attestation work is only
bit/index resolution + dedup; signatures go to the verifier service as
ONE same-message batch (the north-star TPU workload). Failed batches
fan out per signature inside the verifier.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum

import numpy as np

from ...bls import api as bls_api
from ...params import (
    ATTESTATION_SUBNET_COUNT,
    DOMAIN_BEACON_ATTESTER,
    preset,
)
from ...statetransition import util
from ...statetransition.block import compute_signing_root, get_domain
from ..seen_caches import (
    AttDataCacheEntry,
    SeenAttestationDatas,
    SeenAttesters,
)

# gossip conditions (consensus spec p2p-interface.md)
ATTESTATION_PROPAGATION_SLOT_RANGE = 32


class GossipAction(str, Enum):
    ACCEPT = "ACCEPT"
    IGNORE = "IGNORE"
    REJECT = "REJECT"


class GossipValidationError(Exception):
    def __init__(self, action: GossipAction, reason: str):
        super().__init__(f"{action}: {reason}")
        self.action = action
        self.reason = reason


@dataclass
class AttestationValidationResult:
    action: GossipAction
    reason: str = ""
    validator_index: int | None = None


class AttestationValidator:
    """Owns the attestation seen caches and the batch validation flow.
    One instance per node, bound to a BeaconChain + verifier."""

    def __init__(self, cfg, types, chain, verifier):
        self.cfg = cfg
        self.types = types
        self.chain = chain
        self.verifier = verifier
        self.seen_attesters = SeenAttesters()
        self.seen_att_datas = SeenAttestationDatas()
        self.clock_slot = 0

    def on_slot(self, slot: int) -> None:
        self.clock_slot = slot
        self.seen_att_datas.on_slot(slot)

    def att_data_key(self, data) -> bytes:
        """The same-message grouping key: serialized AttestationData
        (reference: attDataBase64 peeked from raw gossip bytes)."""
        return self.types.AttestationData.serialize(data)

    # -- per-key resolution (cached) ------------------------------------

    def _resolve_att_data(self, data, key: bytes) -> AttDataCacheEntry:
        slot = int(data.slot)
        cached = self.seen_att_datas.get(slot, key)
        if cached is not None:
            return cached
        # [IGNORE] propagation window (with 1-slot clock disparity)
        if not (
            slot <= self.clock_slot + 1
            and self.clock_slot <= slot + ATTESTATION_PROPAGATION_SLOT_RANGE
        ):
            raise GossipValidationError(
                GossipAction.IGNORE, "outside propagation slot range"
            )
        # [REJECT] target epoch must match the slot's epoch
        target_epoch = int(data.target.epoch)
        if target_epoch != util.compute_epoch_at_slot(slot):
            raise GossipValidationError(
                GossipAction.REJECT, "target epoch != slot epoch"
            )
        # [IGNORE] head block must be known (else unknown-block sync)
        root = bytes(data.beacon_block_root)
        if not self.chain.fork_choice.has_block(root):
            raise GossipValidationError(
                GossipAction.IGNORE, "unknown beacon_block_root"
            )
        # [REJECT] block must descend from finalized checkpoint
        if not self.chain.fork_choice.is_descendant_of_finalized(root):
            raise GossipValidationError(
                GossipAction.REJECT, "not descendant of finalized"
            )
        # [REJECT] target must be an ancestor at the epoch start
        tgt_root = bytes(data.target.root)
        expected_tgt = self.chain.fork_choice.proto.ancestor_at_slot(
            root, target_epoch * preset().SLOTS_PER_EPOCH
        )
        if expected_tgt is not None and expected_tgt != tgt_root:
            raise GossipValidationError(
                GossipAction.REJECT, "target is not head's epoch ancestor"
            )
        # committee + signing root, once per key
        view = self.chain.get_state(root) or self.chain.head_state
        st = view.state
        shuffling = util.get_shuffling(st, target_epoch)
        committees = shuffling.committees_at_slot(slot)
        index = int(data.index)
        if index >= len(committees):
            raise GossipValidationError(
                GossipAction.REJECT, "committee index out of range"
            )
        committee = committees[index]
        domain = get_domain(
            self.cfg, st, DOMAIN_BEACON_ATTESTER, target_epoch
        )
        signing_root = compute_signing_root(
            self.types.AttestationData, data, domain
        )
        subnet = index % ATTESTATION_SUBNET_COUNT
        entry = AttDataCacheEntry(data, committee, signing_root, subnet)
        self.seen_att_datas.put(slot, key, entry)
        return entry

    # -- batch path -----------------------------------------------------

    async def validate_gossip_attestations_same_att_data(
        self, attestations: list
    ) -> list[AttestationValidationResult]:
        """Validate a chunk of single-bit attestations sharing one
        AttestationData. Returns per-attestation results; accepted ones
        have been fed to fork choice and the attestation pool is the
        caller's job (processor forwards accepts)."""
        if not attestations:
            return []
        key = self.att_data_key(attestations[0].data)
        out: list[AttestationValidationResult] = []
        try:
            entry = self._resolve_att_data(attestations[0].data, key)
        except GossipValidationError as e:
            return [
                AttestationValidationResult(e.action, e.reason)
                for _ in attestations
            ]

        committee = entry.committee
        pending = []  # (result-slot index, validator_index, att)
        for att in attestations:
            bits = np.asarray(att.aggregation_bits, bool)
            res = AttestationValidationResult(GossipAction.ACCEPT)
            out.append(res)
            # [REJECT] exactly one aggregation bit, matching committee len
            if len(bits) != len(committee) or bits.sum() != 1:
                res.action = GossipAction.REJECT
                res.reason = "not a single-bit attestation"
                continue
            vindex = int(committee[int(np.argmax(bits))])
            res.validator_index = vindex
            # [IGNORE] already seen this validator for the target epoch
            epoch = int(att.data.target.epoch)
            if self.seen_attesters.is_known(epoch, vindex):
                res.action = GossipAction.IGNORE
                res.reason = "already seen attester"
                continue
            pending.append((len(out) - 1, vindex, att))

        if not pending:
            return out

        view = self.chain.get_state(
            bytes(entry.data.beacon_block_root)
        ) or self.chain.head_state
        validators = view.state.validators
        sets = [
            bls_api.SameMessageSet(
                pubkey=bytes(validators[v].pubkey),
                signature=bytes(att.signature),
            )
            for _, v, att in pending
        ]
        verdicts = await self.verifier.verify_signature_sets_same_message(
            sets, entry.signing_root
        )
        for (slot_i, vindex, att), ok in zip(pending, verdicts):
            res = out[slot_i]
            if not ok:
                res.action = GossipAction.REJECT
                res.reason = "invalid signature"
                continue
            # double-observation check after async verify
            # (attestation.ts:155-165): another copy may have been
            # accepted while this batch was in flight
            epoch = int(att.data.target.epoch)
            if self.seen_attesters.is_known(epoch, vindex):
                res.action = GossipAction.IGNORE
                res.reason = "seen during verification"
                continue
            self.seen_attesters.add(epoch, vindex)
            self.chain.fork_choice.on_attestation(
                [vindex],
                bytes(att.data.beacon_block_root),
                epoch,
            )
        return out

"""Gossip validation for operation messages (exits, slashings,
bls-to-execution changes).

Reference analog: chain/validation/{voluntaryExit,proposerSlashing,
attesterSlashing,blsToExecutionChange}.ts — each op is fully validated
(structure, slashability, signatures) BEFORE entering the op pool or
being forwarded. Validation runs the spec processor against a clone of
the head state: exact spec semantics (including signature checks) at
the cost of one state clone per op — fine for these rare message types
(the hot attestation path has its own batched validator).
"""

from __future__ import annotations

from ...statetransition.block import BlockCtx, BlockProcessError


class OpValidationError(ValueError):
    pass


def _check(chain, fn, op) -> None:
    from ..chain import _clone

    head = chain.get_or_regen_state(chain.head_root)
    work = _clone(head, chain.types)
    ctx = BlockCtx(
        chain.cfg, work.state, chain.types, work.fork_seq, True
    )
    try:
        fn(ctx, op)
    except BlockProcessError as e:
        raise OpValidationError(str(e)) from e
    except (IndexError, KeyError, ValueError) as e:
        raise OpValidationError(f"malformed operation: {e!r}") from e


def validate_proposer_slashing(chain, slashing) -> None:
    from ...statetransition.block import process_proposer_slashing

    _check(chain, process_proposer_slashing, slashing)


def validate_attester_slashing(chain, slashing) -> None:
    from ...statetransition.block import process_attester_slashing

    _check(chain, process_attester_slashing, slashing)


def validate_voluntary_exit(chain, signed_exit) -> None:
    from ...statetransition.block import process_voluntary_exit

    _check(chain, process_voluntary_exit, signed_exit)


def validate_bls_change(chain, signed_change) -> None:
    from ...statetransition.block import process_bls_to_execution_change

    _check(chain, process_bls_to_execution_change, signed_change)

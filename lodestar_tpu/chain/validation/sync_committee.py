"""Gossip sync-committee message + contribution validation.

Reference analog: chain/validation/syncCommittee.ts
(validateSyncCommitteeSigOnly, :17) and
syncCommitteeContributionAndProof.ts (validateContributionAndProof,
:23) — slot currency, subnet position checks, first-seen dedup
(seenCommittee.ts / seenContributionAndProof.ts), and the signature
sets: one DOMAIN_SYNC_COMMITTEE set for a message; selection proof +
aggregator + aggregate for a contribution — all through the TPU
verifier batch path.
"""

from __future__ import annotations

import numpy as np

from ...bls import api as bls_api
from ...config.beacon_config import compute_signing_root_from_roots
from ...crypto.bls.signature import aggregate_pubkeys
from ...params import (
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    SYNC_COMMITTEE_SUBNET_COUNT,
    preset,
)
from ...statetransition import util
from ...statetransition.block import get_domain
from ...validator.validator import is_sync_committee_aggregator
from ..seen_caches import SeenSyncCommitteeMessages
from .attestation import GossipAction, GossipValidationError

MAXIMUM_GOSSIP_CLOCK_DISPARITY_SLOTS = 1


class SeenSyncContributions:
    """(slot, subcommittee, aggregator) dedup
    (seenContributionAndProof.ts:17)."""

    def __init__(self):
        self._by_slot: dict[int, set[tuple[int, int]]] = {}

    def is_known(self, slot: int, subnet: int, aggregator: int) -> bool:
        return (subnet, aggregator) in self._by_slot.get(slot, ())

    def add(self, slot: int, subnet: int, aggregator: int) -> None:
        self._by_slot.setdefault(slot, set()).add((subnet, aggregator))

    def prune(self, min_slot: int) -> None:
        for s in [s for s in self._by_slot if s < min_slot]:
            del self._by_slot[s]


class SyncCommitteeValidator:
    """Validates sync-committee messages and contributions against the
    head state's committee for the message slot's period."""

    def __init__(self, cfg, types, chain, verifier):
        self.cfg = cfg
        self.types = types
        self.chain = chain
        self.verifier = verifier
        self.seen_messages = SeenSyncCommitteeMessages()
        self.seen_contributions = SeenSyncContributions()
        self.clock_slot = 0

    def on_slot(self, slot: int) -> None:
        self.clock_slot = slot
        if slot > 3:
            self.seen_messages.prune(slot - 3)
            self.seen_contributions.prune(slot - 3)

    # -- shared helpers ---------------------------------------------------

    def _check_slot_current(self, slot: int) -> None:
        # [IGNORE] the message slot must be the current slot, with
        # clock disparity (syncCommittee.ts:35)
        if not (
            slot - MAXIMUM_GOSSIP_CLOCK_DISPARITY_SLOTS
            <= self.clock_slot
            <= slot + MAXIMUM_GOSSIP_CLOCK_DISPARITY_SLOTS
        ):
            raise GossipValidationError(
                GossipAction.IGNORE, "not the current slot"
            )

    def _committee_for_slot(self, slot: int):
        """(committee pubkeys, state) by the epoch(slot+1) period rule
        (getSyncCommitteeAtSlot analog)."""
        view = self.chain.head_state
        st = view.state
        per = preset().EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        epoch = util.compute_epoch_at_slot(slot + 1)
        state_period = util.get_current_epoch(st) // per
        period = epoch // per
        if period == state_period:
            committee = st.current_sync_committee
        elif period == state_period + 1:
            committee = st.next_sync_committee
        else:
            raise GossipValidationError(
                GossipAction.IGNORE, "slot outside sync-committee window"
            )
        return committee, st

    def _positions_of(self, committee, pubkey: bytes) -> list[int]:
        return [
            i
            for i, pk in enumerate(committee.pubkeys)
            if bytes(pk) == pubkey
        ]

    # -- message path (sync_committee_{subnet} topics) --------------------

    async def validate_message(self, msg, subnet: int) -> list[int]:
        """SyncCommitteeMessage gossip conditions + signature
        (syncCommittee.ts:17-80). Returns the validator's committee
        positions that fall on `subnet` (non-empty == ACCEPT) so the
        caller pools without re-deriving the committee."""
        slot = int(msg.slot)
        vindex = int(msg.validator_index)
        self._check_slot_current(slot)
        committee, st = self._committee_for_slot(slot)
        if vindex >= len(st.validators):
            raise GossipValidationError(
                GossipAction.REJECT, "unknown validator index"
            )
        pubkey = bytes(st.validators[vindex].pubkey)
        positions = self._positions_of(committee, pubkey)
        if not positions:
            raise GossipValidationError(
                GossipAction.REJECT, "validator not in sync committee"
            )
        # [REJECT] subnet must match one of the validator's positions
        # (syncCommittee.ts:55)
        sub_size = (
            preset().SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        )
        subnet_positions = [
            p for p in positions if p // sub_size == subnet
        ]
        if not subnet_positions:
            raise GossipValidationError(
                GossipAction.REJECT, "wrong subnet for validator"
            )
        # [IGNORE] first message per (slot, subnet, validator) (:47)
        if self.seen_messages.is_known(slot, subnet, vindex):
            raise GossipValidationError(
                GossipAction.IGNORE, "already seen this slot"
            )
        # signature over the block root at the message slot's domain
        epoch = util.compute_epoch_at_slot(slot)
        domain = get_domain(self.cfg, st, DOMAIN_SYNC_COMMITTEE, epoch)
        root = compute_signing_root_from_roots(
            bytes(msg.beacon_block_root), domain
        )
        ok = await self.verifier.verify_signature_sets(
            [bls_api.SignatureSet(pubkey, root, bytes(msg.signature))],
            batchable=True,
        )
        if not ok:
            raise GossipValidationError(
                GossipAction.REJECT, "invalid signature"
            )
        if self.seen_messages.is_known(slot, subnet, vindex):
            raise GossipValidationError(
                GossipAction.IGNORE, "seen during verification"
            )
        self.seen_messages.add(slot, subnet, vindex)
        return subnet_positions

    # -- contribution path (sync_committee_contribution_and_proof) --------

    async def validate_contribution(self, signed_cap) -> GossipAction:
        """SignedContributionAndProof gossip conditions + the three
        signature sets (syncCommitteeContributionAndProof.ts:23-130)."""
        cap = signed_cap.message
        contribution = cap.contribution
        slot = int(contribution.slot)
        subnet = int(contribution.subcommittee_index)
        agg_index = int(cap.aggregator_index)
        self._check_slot_current(slot)
        # [REJECT] subcommittee range (:40)
        if subnet >= SYNC_COMMITTEE_SUBNET_COUNT:
            raise GossipValidationError(
                GossipAction.REJECT, "subcommittee index out of range"
            )
        # [REJECT] non-empty participation (:47)
        bits = np.asarray(contribution.aggregation_bits, bool)
        if bits.sum() == 0:
            raise GossipValidationError(
                GossipAction.REJECT, "empty contribution"
            )
        # [IGNORE] first contribution per (slot, subnet, aggregator)
        if self.seen_contributions.is_known(slot, subnet, agg_index):
            raise GossipValidationError(
                GossipAction.IGNORE, "aggregator already seen"
            )
        committee, st = self._committee_for_slot(slot)
        if agg_index >= len(st.validators):
            raise GossipValidationError(
                GossipAction.REJECT, "unknown aggregator index"
            )
        agg_pubkey = bytes(st.validators[agg_index].pubkey)
        # [REJECT] aggregator in the sync committee (:62)
        if not self._positions_of(committee, agg_pubkey):
            raise GossipValidationError(
                GossipAction.REJECT, "aggregator not in sync committee"
            )
        # [REJECT] selection proof wins aggregation (:55)
        proof = bytes(cap.selection_proof)
        if not is_sync_committee_aggregator(proof):
            raise GossipValidationError(
                GossipAction.REJECT, "selection proof not aggregator"
            )
        sub_size = (
            preset().SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        )
        if len(bits) != sub_size:
            raise GossipValidationError(
                GossipAction.REJECT, "bits/subcommittee size mismatch"
            )
        epoch = util.compute_epoch_at_slot(slot)
        sets = []
        # 1. selection proof over SyncAggregatorSelectionData (:90)
        sd = self.types.SyncAggregatorSelectionData.default()
        sd.slot = slot
        sd.subcommittee_index = subnet
        sel_domain = get_domain(
            self.cfg, st, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch
        )
        sets.append(
            bls_api.SignatureSet(
                agg_pubkey,
                compute_signing_root_from_roots(
                    self.types.SyncAggregatorSelectionData.hash_tree_root(
                        sd
                    ),
                    sel_domain,
                ),
                proof,
            )
        )
        # 2. aggregator signature over ContributionAndProof (:100)
        cap_domain = get_domain(
            self.cfg, st, DOMAIN_CONTRIBUTION_AND_PROOF, epoch
        )
        sets.append(
            bls_api.SignatureSet(
                agg_pubkey,
                compute_signing_root_from_roots(
                    self.types.ContributionAndProof.hash_tree_root(cap),
                    cap_domain,
                ),
                bytes(signed_cap.signature),
            )
        )
        # 3. the contribution aggregate over the participants (:110)
        participants = [
            bytes(committee.pubkeys[subnet * sub_size + i])
            for i in np.flatnonzero(bits)
        ]
        try:
            agg_pk = aggregate_pubkeys(participants)
        except Exception as e:
            raise GossipValidationError(
                GossipAction.REJECT, f"bad participant pubkey: {e}"
            ) from e
        msg_domain = get_domain(
            self.cfg, st, DOMAIN_SYNC_COMMITTEE, epoch
        )
        sets.append(
            bls_api.SignatureSet(
                agg_pk,
                compute_signing_root_from_roots(
                    bytes(contribution.beacon_block_root), msg_domain
                ),
                bytes(contribution.signature),
            )
        )
        ok = await self.verifier.verify_signature_sets(sets)
        if not ok:
            raise GossipValidationError(
                GossipAction.REJECT, "invalid signature"
            )
        if self.seen_contributions.is_known(slot, subnet, agg_index):
            raise GossipValidationError(
                GossipAction.IGNORE, "seen during verification"
            )
        self.seen_contributions.add(slot, subnet, agg_index)
        return GossipAction.ACCEPT

"""Operation pools: attestations awaiting aggregation / block packing.

Reference analogs: AttestationPool (unaggregated, per-subnet,
opPools/attestationPool.ts:66), AggregatedAttestationPool (block
packing, aggregatedAttestationPool.ts:94 + MatchingDataAttestationGroup
:453), OpPool (slashings/exits/blsChanges, opPool.ts:33).
"""

from __future__ import annotations

from collections import defaultdict

from ..params import preset

SLOTS_RETAINED = 8  # attestationPool.ts SLOTS_RETAINED


class AttestationPool:
    """Unaggregated single attestations keyed by (slot, data root).
    `add` merges a single-bit attestation into the group's aggregate —
    the naive CPU aggregation the reference does per subnet; the TPU
    same-message batch path verifies them before they get here."""

    def __init__(self, types):
        self.types = types
        # (slot, data_root) -> {"data": AttestationData, "bits": list,
        #                        "sigs": {bit_index: signature}}
        self._groups: dict[tuple, dict] = {}

    def add(self, attestation, committee_len: int) -> None:
        data = attestation.data
        key = (
            int(data.slot),
            self.types.AttestationData.hash_tree_root(data),
        )
        g = self._groups.get(key)
        if g is None:
            g = {
                "data": data,
                "bits": [False] * committee_len,
                "sigs": {},
            }
            self._groups[key] = g
        bits = list(attestation.aggregation_bits)
        for i, b in enumerate(bits):
            if b and not g["bits"][i]:
                g["bits"][i] = True
                g["sigs"][i] = bytes(attestation.signature)

    def get_aggregate(self, slot: int, data_root: bytes):
        from ..crypto.bls.signature import aggregate_signatures

        g = self._groups.get((slot, data_root))
        if g is None or not g["sigs"]:
            return None
        agg = self.types.Attestation.default()
        agg.data = g["data"]
        agg.aggregation_bits = list(g["bits"])
        agg.signature = aggregate_signatures(list(g["sigs"].values()))
        return agg

    def iter_groups(self, slot: int):
        for (s, root), g in self._groups.items():
            if s == slot:
                yield root, g

    def prune(self, current_slot: int) -> None:
        cutoff = current_slot - SLOTS_RETAINED
        self._groups = {
            k: v for k, v in self._groups.items() if k[0] > cutoff
        }

    def __len__(self) -> int:
        return len(self._groups)


class AggregatedAttestationPool:
    """Aggregated attestations for block packing, grouped by data."""

    def __init__(self, types):
        self.types = types
        # (slot, data_root) -> list of {"bits": [...], "sig": bytes,
        #                               "data": AttestationData}
        self._groups: dict[tuple, list] = defaultdict(list)

    def __len__(self) -> int:
        # total pooled aggregates, not key count — the memory-bound SLO
        # (sim/assertions.op_pool_sizes) watches the entries that grow
        # without pruning, and one key can hold many aggregates
        return sum(len(v) for v in self._groups.values())

    def add(self, attestation) -> None:
        data = attestation.data
        key = (
            int(data.slot),
            self.types.AttestationData.hash_tree_root(data),
        )
        bits = list(attestation.aggregation_bits)
        cb = getattr(attestation, "committee_bits", None)
        cb = list(cb) if cb is not None else None
        group = self._groups[key]
        # electra+: aggregates for DIFFERENT committee selections share
        # the (slot, data_root) key (data.index is 0) but their
        # aggregation_bits index different validator sets — dedup and
        # subset pruning are only meaningful between aggregates with
        # the SAME committee_bits
        def same_committees(e):
            return e.get("committee_bits") == cb

        for existing in group:
            if same_committees(existing) and existing["bits"] == bits:
                return  # exact duplicate
        # keep only non-subset aggregates (MatchingDataAttestationGroup)
        group[:] = [
            e
            for e in group
            if not (same_committees(e) and _is_subset(e["bits"], bits))
        ]
        if not any(
            same_committees(e) and _is_subset(bits, e["bits"])
            for e in group
        ):
            group.append(
                {
                    "bits": bits,
                    "sig": bytes(attestation.signature),
                    "data": data,
                    # electra+ aggregates span committees; keep the
                    # bits so packing can rebuild them and the
                    # on-chain filter knows to stand down
                    "committee_bits": cb,
                }
            )

    def get_attestations_for_block(
        self, state_slot: int, max_atts=None, state=None
    ):
        """Best-coverage attestations includable at `state_slot`
        (aggregatedAttestationPool.getAttestationsForBlock). When the
        proposal's (slot-advanced) state is passed, aggregates whose
        attesters ALL already have their timely-target flag set on
        chain are skipped — the reference's notSeenValidatorsFn filter.
        Deriving "already included" from the proposal state (instead
        of subtracting at import) is reorg-safe: a reorg to a chain
        that never included an attestation automatically un-filters
        it."""
        p = preset()
        if max_atts is None:
            max_atts = p.MAX_ATTESTATIONS
        out = []
        # phase0 seen-bits maps, built at most once per pending list
        # for this packing pass (not per pooled entry — a full pool
        # late in an epoch would otherwise rescan every
        # PendingAttestation's bitlist per entry)
        seen_cache: dict = {}
        for (slot, _root), group in sorted(
            self._groups.items(), key=lambda kv: -kv[0][0]
        ):
            if not (
                slot + p.MIN_ATTESTATION_INCLUSION_DELAY <= state_slot
                and state_slot <= slot + p.SLOTS_PER_EPOCH
            ):
                continue
            for e in sorted(
                group, key=lambda e: -sum(e["bits"])
            ):
                if state is not None and self._fully_on_chain(
                    state, slot, e, seen_cache
                ):
                    continue
                a = self.types.Attestation.default()
                a.data = e["data"]
                a.aggregation_bits = list(e["bits"])
                a.signature = e["sig"]
                if e.get("committee_bits") is not None and hasattr(
                    a, "committee_bits"
                ):
                    a.committee_bits = list(e["committee_bits"])
                out.append(a)
                if len(out) >= max_atts:
                    return out
        return out

    @staticmethod
    def _fully_on_chain(
        state, att_slot: int, entry, seen_cache: dict | None = None
    ) -> bool:
        """True when every attester of a pooled aggregate is already
        represented on chain for the attestation's epoch in `state` —
        altair+ via the timely-target participation flag, phase0 via
        the PendingAttestation lists (the reference's phase0
        notSeenValidatorsFn; seen_cache memoizes the per-(slot, index)
        seen-bits maps across one packing pass). Fail-open: any lookup
        error keeps the attestation includable."""
        try:
            from ..statetransition import util as st_util
            from ..statetransition.util import TIMELY_TARGET_FLAG_INDEX

            if entry.get("committee_bits"):
                # electra aggregates: data.index is 0 and the bits span
                # EVERY committee selected by committee_bits — the
                # single-committee mapping below would derive the wrong
                # attesters and silently drop includable aggregates.
                # Don't filter until the electra offset mapping exists.
                return False
            p = preset()
            att_epoch = att_slot // p.SLOTS_PER_EPOCH
            state_epoch = int(state.slot) // p.SLOTS_PER_EPOCH
            if att_epoch == state_epoch:
                part = getattr(
                    state, "current_epoch_participation", None
                )
            elif att_epoch == state_epoch - 1:
                part = getattr(
                    state, "previous_epoch_participation", None
                )
            else:
                return False
            if part is None:
                # phase0: no participation flags, but the state's
                # PendingAttestation lists record exactly which
                # committee bit positions are already included for
                # each (slot, index) — compare bit-for-bit (positions
                # align: both index the same beacon committee).
                # Without this branch every phase0 block re-includes
                # the whole pool's last epoch of aggregates, which
                # inflates average inclusion delay ~1.7x.
                pend = getattr(
                    state,
                    "current_epoch_attestations"
                    if att_epoch == state_epoch
                    else "previous_epoch_attestations",
                    None,
                )
                if pend is None:
                    return False
                data = entry["data"]
                bits = list(entry["bits"])
                epoch_key = att_epoch == state_epoch
                if seen_cache is None:
                    seen_cache = {}
                if ("built", epoch_key) not in seen_cache:
                    # one sweep over the pending list builds the
                    # seen-bits union for EVERY (slot, index) at once
                    for pa in pend:
                        key = (
                            epoch_key,
                            int(pa.data.slot),
                            int(pa.data.index),
                        )
                        dst = seen_cache.setdefault(key, [])
                        pab = list(pa.aggregation_bits)
                        if len(pab) > len(dst):
                            dst.extend([False] * (len(pab) - len(dst)))
                        for i, b in enumerate(pab):
                            if b:
                                dst[i] = True
                    seen_cache[("built", epoch_key)] = True
                seen = seen_cache.get(
                    (epoch_key, att_slot, int(data.index)), []
                )
                if len(seen) < len(bits):
                    seen = seen + [False] * (len(bits) - len(seen))
                return bool(bits) and _is_subset(bits, seen)
            data = entry["data"]
            committee = st_util.get_shuffling(
                state, att_epoch
            ).committee(att_slot, int(data.index))
            bits = entry["bits"]
            attesters = [
                int(v)
                for i, v in enumerate(committee)
                if i < len(bits) and bits[i]
            ]
            if not attesters:
                return False
            flag = 1 << TIMELY_TARGET_FLAG_INDEX
            return all(int(part[v]) & flag for v in attesters)
        except Exception:
            return False

    def prune(self, current_slot: int) -> None:
        p = preset()
        cutoff = current_slot - p.SLOTS_PER_EPOCH
        self._groups = defaultdict(
            list,
            {k: v for k, v in self._groups.items() if k[0] > cutoff},
        )



def _is_subset(a: list[bool], b: list[bool]) -> bool:
    """True if every set bit of a is set in b."""
    return all((not x) or y for x, y in zip(a, b))


class SyncCommitteeMessagePool:
    """Per-subnet sync messages aggregated into contributions
    (syncCommitteeMessagePool.ts:36): key (slot, block_root, subnet),
    value = subcommittee bits + aggregate signature."""

    def __init__(self, types):
        self.types = types
        self._groups: dict[tuple, dict] = {}

    def add(
        self, slot: int, block_root: bytes, subnet: int,
        index_in_subcommittee: int, signature: bytes,
    ) -> None:
        from ..params import SYNC_COMMITTEE_SUBNET_COUNT, preset

        p = preset()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        key = (slot, bytes(block_root), subnet)
        g = self._groups.get(key)
        if g is None:
            g = {"bits": [False] * sub_size, "sigs": []}
            self._groups[key] = g
        if not g["bits"][index_in_subcommittee]:
            g["bits"][index_in_subcommittee] = True
            g["sigs"].append(bytes(signature))

    def get_contribution(self, slot: int, block_root: bytes, subnet: int):
        from ..crypto.bls.signature import aggregate_signatures

        g = self._groups.get((slot, bytes(block_root), subnet))
        if g is None or not g["sigs"]:
            return None
        return {
            "slot": slot,
            "beacon_block_root": bytes(block_root),
            "subcommittee_index": subnet,
            "aggregation_bits": list(g["bits"]),
            "signature": aggregate_signatures(g["sigs"]),
        }

    def prune(self, current_slot: int) -> None:
        self._groups = {
            k: v for k, v in self._groups.items() if k[0] >= current_slot - 2
        }


class SyncContributionAndProofPool:
    """Best contribution per (slot, root, subcommittee); merged into the
    block's SyncAggregate (syncContributionAndProofPool.ts:43)."""

    def __init__(self, types):
        self.types = types
        self._best: dict[tuple, dict] = {}

    def add(self, contribution: dict) -> None:
        key = (
            contribution["slot"],
            contribution["beacon_block_root"],
            contribution["subcommittee_index"],
        )
        cur = self._best.get(key)
        n = sum(contribution["aggregation_bits"])
        if cur is None or n > sum(cur["aggregation_bits"]):
            self._best[key] = contribution

    def get_sync_aggregate(self, slot: int, block_root: bytes):
        """Merge subcommittee contributions into one SyncAggregate."""
        from ..crypto.bls.signature import aggregate_signatures
        from ..params import SYNC_COMMITTEE_SUBNET_COUNT, preset

        p = preset()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        bits = [False] * p.SYNC_COMMITTEE_SIZE
        sigs = []
        for subnet in range(SYNC_COMMITTEE_SUBNET_COUNT):
            c = self._best.get((slot, bytes(block_root), subnet))
            if c is None:
                continue
            for i, b in enumerate(c["aggregation_bits"]):
                bits[subnet * sub_size + i] = b
            sigs.append(c["signature"])
        sa = self.types.SyncAggregate.default()
        sa.sync_committee_bits = bits
        if sigs:
            sa.sync_committee_signature = aggregate_signatures(sigs)
        else:
            sa.sync_committee_signature = b"\xc0" + b"\x00" * 95
        return sa

    def prune(self, current_slot: int) -> None:
        self._best = {
            k: v for k, v in self._best.items() if k[0] >= current_slot - 2
        }


class OpPool:
    """Slashings / exits / bls-to-execution changes awaiting inclusion
    (opPool.ts:33)."""

    def __init__(self, types):
        self.types = types
        self.proposer_slashings: dict[int, object] = {}
        self.attester_slashings: list = []
        self.voluntary_exits: dict[int, object] = {}
        self.bls_changes: dict[int, object] = {}

    def add_proposer_slashing(self, s) -> None:
        self.proposer_slashings[
            int(s.signed_header_1.message.proposer_index)
        ] = s

    def add_attester_slashing(self, s) -> None:
        self.attester_slashings.append(s)

    def add_voluntary_exit(self, e) -> None:
        self.voluntary_exits[int(e.message.validator_index)] = e

    def add_bls_change(self, c) -> None:
        self.bls_changes[int(c.message.validator_index)] = c

    def get_for_block(self, state):
        """Ops still valid against `state`, capped at block maxima."""
        from ..params import FAR_FUTURE_EPOCH

        p = preset()
        slashings = [
            s
            for i, s in self.proposer_slashings.items()
            if not state.validators[i].slashed
        ][: p.MAX_PROPOSER_SLASHINGS]
        att_slashings = self.attester_slashings[: p.MAX_ATTESTER_SLASHINGS]
        exits = [
            e
            for i, e in self.voluntary_exits.items()
            if state.validators[i].exit_epoch == FAR_FUTURE_EPOCH
        ][: p.MAX_VOLUNTARY_EXITS]
        changes = list(self.bls_changes.values())[
            : p.MAX_BLS_TO_EXECUTION_CHANGES
        ]
        return slashings, att_slashings, exits, changes

"""Prepare-next-slot scheduler.

Reference analog: PrepareNextSlotScheduler (chain/prepareNextSlot.ts:40)
— at ~2/3 into each slot, precompute the head state advanced to the
next slot (epoch transitions land here, OFF the block-arrival critical
path) and, with an execution engine attached, send fcU payload
attributes so the EL starts building.
"""

from __future__ import annotations

from ..statetransition.slot import process_slots


class PrepareNextSlotScheduler:
    def __init__(self, chain):
        self.chain = chain
        self.prepared: dict[bytes, object] = {}
        self.prepares = 0
        # slot -> expected proposer index, recorded at prepare time
        # (the advanced state is the only one that answers the
        # slot-seeded proposer exactly); consumed by the validator
        # monitor's missed-proposal detection
        self.expected_proposers: dict[int, int] = {}

    async def prepare(self, next_slot: int):
        """Advance a head-state clone to `next_slot` and cache it keyed
        by (head_root, slot); block import / production reuse it."""
        from .chain import _clone

        head_root = self.chain.head_root
        key = head_root + int(next_slot).to_bytes(8, "big")
        if key in self.prepared:
            return self.prepared[key]
        head = self.chain.get_or_regen_state(head_root)
        work = _clone(head, self.chain.types)
        process_slots(self.chain.cfg, work, next_slot, self.chain.types)
        self.prepared = {key: work}  # keep only the newest
        self.prepares += 1
        # warm the epoch shuffling memo off the critical path
        # (prepareNextSlot.ts:40 precomputeNextEpochTransition): at an
        # epoch boundary the first import would otherwise pay the full
        # registry shuffle inline
        try:
            from ..params import ForkSeq
            from ..statetransition import util as _util

            _util.get_shuffling(
                work.state, _util.get_current_epoch(work.state)
            )
            self.expected_proposers[int(next_slot)] = (
                _util.get_beacon_proposer_index(
                    work.state,
                    electra=work.fork_seq >= ForkSeq.electra,
                )
            )
            for old in sorted(self.expected_proposers)[:-4]:
                del self.expected_proposers[old]
        except Exception:
            pass
        if self.chain.execution_engine is not None:
            # fcU WITH payload attributes so the EL starts building the
            # next payload now (produceBlockBody then only getPayloads)
            try:
                from ..params import ForkSeq

                if work.fork_seq >= ForkSeq.bellatrix:
                    await self.chain.send_payload_attributes(
                        next_slot, work
                    )
            except Exception:
                pass  # EL hiccups must not break slot processing
        return work

    def take(self, head_root: bytes, slot: int):
        """Consume a prepared state if it matches (else None)."""
        key = bytes(head_root) + int(slot).to_bytes(8, "big")
        return self.prepared.pop(key, None)

"""State regeneration: replay blocks to rebuild evicted states.

Reference analog: QueuedStateRegenerator + StateRegenerator
(beacon-node/src/chain/regen/queued.ts:31, regen.ts:43) — a
single-concurrency, bounded queue that rebuilds the post-state of any
known block by replaying blocks from the nearest cached ancestor
state. Signatures are NOT re-verified during replay (they were
verified when each block was first imported — same contract as the
reference's regen pipeline).
"""

from __future__ import annotations

import asyncio

from ..statetransition import state_transition
from ..statetransition.slot import BeaconStateView, process_slots

MAX_REGEN_QUEUE = 256  # reference: queued.ts:14 maxLength
MAX_REPLAY_DEPTH = 8192  # hard sanity bound on replay chains


class RegenError(Exception):
    pass


class StateRegenerator:
    """Rebuilds block post-states by replay; one replay at a time.

    Callers (block import with an evicted parent state, API state
    queries, reprocess) queue through `get_state`; depth of pending
    work is bounded like the reference's JobItemQueue.
    """

    def __init__(self, chain):
        self.chain = chain
        self._lock = asyncio.Lock()
        # replay_sync is reachable both from the executor thread (via
        # get_state) and directly on the loop thread (via
        # chain.get_or_regen_state); a thread mutex serializes the
        # actual replay + cache mutation
        import threading

        self._mutex = threading.Lock()
        self._pending = 0
        # metrics-ish counters (reference: RegenFnName/RegenCaller)
        self.hits = 0
        self.replays = 0
        self.blocks_replayed = 0
        # lodestar_regen_* / lodestar_state_cache_* catalog family
        # (metrics/beacon.py m.regen) — wired by the node assembly
        self.metrics = None

    async def get_state(
        self, block_root: bytes, caller: str = "regen"
    ) -> BeaconStateView:
        """Post-state of `block_root`, from cache or by replay."""
        m = self.metrics
        if m is not None:
            m.requests_total.inc(caller=caller)
        cached = self.chain.get_state(block_root)
        if cached is not None:
            self.hits += 1
            if m is not None:
                m.state_cache_hits_total.inc()
            return cached
        if self._pending >= MAX_REGEN_QUEUE:
            raise RegenError("regen queue full")
        self._pending += 1
        try:
            async with self._lock:
                # a queued predecessor may have produced it already
                cached = self.chain.get_state(block_root)
                if cached is not None:
                    self.hits += 1
                    if m is not None:
                        m.state_cache_hits_total.inc()
                    return cached
                # counted here, after the re-check, so hit/miss
                # partition requests (a request served by a queued
                # predecessor's replay counts as a hit, not both)
                if m is not None:
                    m.state_cache_misses_total.inc()
                return await asyncio.get_event_loop().run_in_executor(
                    None, self.replay_sync, block_root
                )
        finally:
            self._pending -= 1

    # -- internals --------------------------------------------------------

    def _get_block(self, root: bytes):
        blk = self.chain.get_block(root)
        if blk is not None:
            return blk
        if self.chain.db is not None:
            got = self.chain.db.block.get(root)
            if got is not None:
                return got[1]
        return None

    def replay_sync(self, block_root: bytes) -> BeaconStateView:
        """Synchronous replay core (also the non-queued path for
        callers already off the event loop, e.g. block production)."""
        with self._mutex:
            return self._replay_locked(block_root)

    def _replay_locked(self, block_root: bytes) -> BeaconStateView:
        from .chain import _clone

        chain = self.chain
        cached = chain.get_state(block_root)
        if cached is not None:
            return cached
        path = []
        root = block_root
        while chain.get_state(root) is None:
            blk = self._get_block(root)
            if blk is None:
                raise RegenError(
                    f"cannot regen {block_root.hex()[:16]}: no block for "
                    f"ancestor {root.hex()[:16]}"
                )
            path.append(blk)
            root = bytes(blk.message.parent_root)
            if len(path) > MAX_REPLAY_DEPTH:
                raise RegenError("replay chain too deep")

        self.replays += 1
        if self.metrics is not None:
            self.metrics.replays_total.inc()
        work = _clone(chain.get_state(root), chain.types)
        for blk in reversed(path):
            process_slots(
                chain.cfg, work, int(blk.message.slot), chain.types
            )
            state_transition(
                chain.cfg,
                work,
                blk,
                chain.types,
                verify_state_root=True,
                verify_proposer=False,
                verify_signatures=False,
            )
            self.blocks_replayed += 1
            if self.metrics is not None:
                self.metrics.blocks_replayed_total.inc()
        chain._store_state(block_root, work)
        return work

"""Archiver: migrate hot chain data to finalized archives on finality.

Reference analog: chain/archiver/archiver.ts:20 +
FrequencyStateArchiveStrategy (strategies/frequencyStateArchiveStrategy
.ts:25): on each finalized-checkpoint advance, move finalized-canonical
blocks from the hot repo to the slot-indexed archive, persist the
finalized state, and drop non-canonical hot entries.
"""

from __future__ import annotations


class Archiver:
    def __init__(self, db, chain, state_archive_every_epochs: int = 1):
        self.db = db
        self.chain = chain
        self.state_archive_every_epochs = state_archive_every_epochs
        self._last_archived_epoch = -1

    def on_finalized(self, checkpoint) -> None:
        """checkpoint: forkchoice Checkpoint (epoch, root)."""
        db = self.db
        chain = self.chain
        fin_root = checkpoint.root
        proto = chain.fork_choice.proto
        node = proto.get_node(fin_root)
        if node is None:
            return
        # canonical finalized chain: finalized root and its ancestors
        canonical = []
        for n in proto.iter_chain(fin_root):
            canonical.append(n)
        # migrate hot blocks -> slot archive (skip if already archived)
        for n in canonical:
            raw = db.block.get_binary(n.block_root)
            if raw is None:
                continue
            fork, block = db.block.decode_value(raw)
            db.block_archive.put_with_indices(
                n.slot, fork, block, n.block_root
            )
            db.block.delete(n.block_root)
            db.state.delete(n.block_root)
        # persist the finalized checkpoint state
        if checkpoint.epoch - self._last_archived_epoch >= (
            self.state_archive_every_epochs
        ):
            view = chain.get_state(fin_root)
            if view is not None:
                db.state_archive.put_binary(
                    node.slot,
                    db.state_archive.encode_fork_value(
                        view.fork, view.state
                    ),
                )
                db.checkpoint_state.put_binary(
                    db.checkpoint_state.checkpoint_key(
                        checkpoint.epoch, fin_root
                    ),
                    db.checkpoint_state.encode_fork_value(
                        view.fork, view.state
                    ),
                )
                self._last_archived_epoch = checkpoint.epoch
        db.meta.put_raw("finalized_root", fin_root)
        db.meta.put_int("finalized_epoch", checkpoint.epoch)

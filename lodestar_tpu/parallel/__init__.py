"""Multi-chip sharding for the verification batch dimension.

Reference analog (SURVEY.md §2.2): the blst pool fans signature chunks
out to N-1 CPU worker threads round-robin
(chain/bls/multithread/index.ts:183-199). The TPU design replaces the
worker fan-out with SPMD: every batch-shaped crypto kernel in this
package broadcasts over a leading axis, so distributing work across
chips is a matter of placing that axis on a `Mesh` axis and letting
XLA insert the collectives (the log-depth aggregate/product reduction
trees in ops/curve.jac_sum and ops/pairing._fq12_masked_product become
ICI all-reduces). There is no NCCL/MPI analog to port — the "comm
backend" is jax.sharding over ICI/DCN (SURVEY.md §5.8).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

BATCH_AXIS = "batch"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D device mesh over the verify batch axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis; replicate limb axes."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree):
    """Place every array leaf of a batched pytree (JacPoint / Lv / Fq2
    tuples / bool masks) with its leading axis split over the mesh."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def replicate(mesh: Mesh, tree):
    r = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, r), tree)

"""Multi-chip sharding for the verification batch dimension.

Reference analog (SURVEY.md §2.2): the blst pool fans signature chunks
out to N-1 CPU worker threads round-robin
(chain/bls/multithread/index.ts:183-199). The TPU design replaces the
worker fan-out with SPMD: every batch-shaped crypto kernel in this
package broadcasts over a leading axis, so distributing work across
chips is a matter of placing that axis on a `Mesh` axis and letting
XLA insert the collectives (the log-depth aggregate/product reduction
trees in ops/curve.jac_sum and ops/pairing._fq12_masked_product become
ICI all-reduces). There is no NCCL/MPI analog to port — the "comm
backend" is jax.sharding over ICI/DCN (SURVEY.md §5.8).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

BATCH_AXIS = "batch"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D device mesh over the verify batch axis."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (BATCH_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis; replicate limb axes."""
    return NamedSharding(mesh, P(BATCH_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree):
    """Place every array leaf of a batched pytree (JacPoint / Lv / Fq2
    tuples / bool masks) with its leading axis split over the mesh."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def replicate(mesh: Mesh, tree):
    r = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, r), tree)


def whole_bucket_verify(mesh: Mesh, local_fn, n_args: int,
                        replicated_args: tuple = ()):
    """Whole-bucket SPMD verify wrapper (ISSUE 16).

    The auto-spmd mesh path shards the batch axis INSIDE one bucket's
    program, so XLA inserts ICI all-reduces into the aggregate and
    product reduction trees — several collectives per wave, each a
    latency wall. But the random-linear-combination batch-verify
    equation is SEPARABLE across disjoint subsets of sets: each chip
    can run the complete verify on the sub-bucket it owns and the
    batch verdict is just the AND of the per-chip verdicts. shard_map
    makes that explicit: `local_fn` (batch-shaped args -> () bool) is
    traced per shard with collective-free local shapes, and the ONLY
    collective in the whole program is one scalar `psum` of the
    per-chip bad counts at the final verdict.

    in_specs are pytree PREFIXES: P(batch) splits every array leaf's
    leading axis across the mesh; indices in `replicated_args` get P()
    (e.g. the shared same-message hash point). The caller places
    inputs with shard_batch/replicate to match.
    """
    from jax.experimental.shard_map import shard_map

    import jax.numpy as jnp

    in_specs = tuple(
        P() if i in replicated_args else P(BATCH_AXIS)
        for i in range(n_args)
    )

    def spmd(*args):
        ok = local_fn(*args)
        bad = jax.lax.psum(jnp.where(ok, 0, 1), BATCH_AXIS)
        return bad == 0

    # check_rep=False: the replication-type checker mis-infers the
    # carry replication of lax.scan bodies (the ladders and masked
    # products are scan-based) and rejects the program; the body is
    # collective-free by construction and the one explicit psum above
    # is the whole cross-shard story, so the check adds nothing here.
    return shard_map(
        spmd,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_rep=False,
    )

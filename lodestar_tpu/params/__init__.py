"""Spec parameters: presets, constants, fork names.

Reference analog: packages/params/src (setPreset.ts, forkName.ts, index.ts).
The active preset is selected via env ``LODESTAR_PRESET`` (same contract as
reference packages/params/src/setPreset.ts) or `set_active_preset()` before
any dependent module reads sizes.
"""

import os
from enum import IntEnum

from .presets import BeaconPreset, MAINNET_PRESET, MINIMAL_PRESET, PRESETS

__all__ = [
    "BeaconPreset",
    "MAINNET_PRESET",
    "MINIMAL_PRESET",
    "PRESETS",
    "ACTIVE_PRESET_NAME",
    "preset",
    "set_active_preset",
    "ForkName",
    "ForkSeq",
    "FORK_ORDER",
]


# ---------------------------------------------------------------------------
# Active preset (reference: params/src/setPreset.ts — env before import)
# ---------------------------------------------------------------------------

ACTIVE_PRESET_NAME = os.environ.get("LODESTAR_PRESET", "mainnet")
_active_preset = PRESETS[ACTIVE_PRESET_NAME]
_preset_frozen = False


def preset() -> BeaconPreset:
    """Return the active preset (freezes it on first use)."""
    global _preset_frozen
    _preset_frozen = True
    return _active_preset


def set_active_preset(name: str) -> None:
    global _active_preset, ACTIVE_PRESET_NAME
    if _preset_frozen and PRESETS[name] is not _active_preset:
        raise RuntimeError("preset already in use; set LODESTAR_PRESET before import")
    ACTIVE_PRESET_NAME = name
    _active_preset = PRESETS[name]


# ---------------------------------------------------------------------------
# Fork names / ordering (reference: params/src/forkName.ts)
# ---------------------------------------------------------------------------


class ForkName:
    phase0 = "phase0"
    altair = "altair"
    bellatrix = "bellatrix"
    capella = "capella"
    deneb = "deneb"
    electra = "electra"


class ForkSeq(IntEnum):
    phase0 = 0
    altair = 1
    bellatrix = 2
    capella = 3
    deneb = 4
    electra = 5


FORK_ORDER = [
    ForkName.phase0,
    ForkName.altair,
    ForkName.bellatrix,
    ForkName.capella,
    ForkName.deneb,
    ForkName.electra,
]


# ---------------------------------------------------------------------------
# Non-preset spec constants (reference: params/src/index.ts)
# ---------------------------------------------------------------------------

GENESIS_SLOT = 0
GENESIS_EPOCH = 0
FAR_FUTURE_EPOCH = 2**64 - 1
UINT64_MAX = 2**64 - 1

BASE_REWARDS_PER_EPOCH = 4
DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4
ENDIANNESS = "little"

# NOTE: SECONDS_PER_SLOT lives in ChainConfig (runtime-overridable), not here.
INTERVALS_PER_SLOT = 3

# Withdrawal prefixes
BLS_WITHDRAWAL_PREFIX = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"
COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"

# Domain types (spec: beacon-chain.md#domain-types)
DOMAIN_BEACON_PROPOSER = bytes.fromhex("00000000")
DOMAIN_BEACON_ATTESTER = bytes.fromhex("01000000")
DOMAIN_RANDAO = bytes.fromhex("02000000")
DOMAIN_DEPOSIT = bytes.fromhex("03000000")
DOMAIN_VOLUNTARY_EXIT = bytes.fromhex("04000000")
DOMAIN_SELECTION_PROOF = bytes.fromhex("05000000")
DOMAIN_AGGREGATE_AND_PROOF = bytes.fromhex("06000000")
DOMAIN_SYNC_COMMITTEE = bytes.fromhex("07000000")
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes.fromhex("08000000")
DOMAIN_CONTRIBUTION_AND_PROOF = bytes.fromhex("09000000")
DOMAIN_BLS_TO_EXECUTION_CHANGE = bytes.fromhex("0A000000")
DOMAIN_APPLICATION_MASK = bytes.fromhex("00000001")
DOMAIN_APPLICATION_BUILDER = bytes.fromhex("00000001")

# Participation flag indices (altair)
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
]

# Sync committee subnets (altair p2p)
SYNC_COMMITTEE_SUBNET_COUNT = 4
TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16
TARGET_AGGREGATORS_PER_COMMITTEE = 16
ATTESTATION_SUBNET_COUNT = 64

# Deneb blob constants
BYTES_PER_FIELD_ELEMENT = 32
BLOB_TX_TYPE = 0x03
VERSIONED_HASH_VERSION_KZG = b"\x01"

# Electra constants
UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
FULL_EXIT_REQUEST_AMOUNT = 0

# BLS (IETF BLS spec, ciphersuite used by Ethereum)
BLS_DST_SIG = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"
BLS_DST_POP = b"BLS_POP_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

"""SSZ composite types: vectors, lists, bitfields, containers.

Follows consensus-specs ssz/simple-serialize.md. Values are plain Python:
bytes for byte vectors/lists, list[bool] for bitfields, list for
vectors/lists, and generated attribute-style objects for containers.
"""

from __future__ import annotations

from typing import Any

from .core import (
    BYTES_PER_CHUNK,
    SSZType,
    merkleize,
    mix_in_length,
    pack_bytes,
)
from .basic import UintType, BooleanType
from . import cached
from .cached import SszVec

OFFSET_SIZE = 4


class SharedMutationError(RuntimeError):
    """Raised on in-place mutation of a value shared between clones.

    Cloning a state (ssz.cached.clone_value) shares flat-container list
    elements copy-on-write; writers must replace elements (or use
    statetransition.util.mut) instead of mutating through a shared ref.
    """


def _is_basic(t: SSZType) -> bool:
    return isinstance(t, (UintType, BooleanType))


def _serialize_sequence(element_types: list[SSZType], values: list[Any]) -> bytes:
    """Serialize a heterogeneous field/element sequence per the SSZ spec
    (fixed parts + offsets to variable parts)."""
    fixed_parts: list[bytes | None] = []
    variable_parts: list[bytes] = []
    for t, v in zip(element_types, values):
        if t.is_fixed_size():
            fixed_parts.append(t.serialize(v))
            variable_parts.append(b"")
        else:
            fixed_parts.append(None)
            variable_parts.append(t.serialize(v))
    fixed_length = sum(
        len(p) if p is not None else OFFSET_SIZE for p in fixed_parts
    )
    variable_offsets = []
    offset = fixed_length
    for vp in variable_parts:
        variable_offsets.append(offset)
        offset += len(vp)
    out = bytearray()
    for p, off in zip(fixed_parts, variable_offsets):
        if p is not None:
            out += p
        else:
            out += off.to_bytes(OFFSET_SIZE, "little")
    for vp in variable_parts:
        out += vp
    return bytes(out)


def _deserialize_sequence(
    element_types: list[SSZType], data: bytes
) -> list[Any]:
    """Inverse of _serialize_sequence for a known-length type sequence."""
    # First pass: compute fixed segment layout
    fixed_sizes: list[int | None] = [
        t.fixed_size() if t.is_fixed_size() else None for t in element_types
    ]
    fixed_length = sum(s if s is not None else OFFSET_SIZE for s in fixed_sizes)
    if len(data) < fixed_length:
        raise ValueError("SSZ: data shorter than fixed segment")
    if all(s is not None for s in fixed_sizes) and len(data) != fixed_length:
        raise ValueError("SSZ: trailing bytes after fixed-size value")
    pos = 0
    offsets: list[int] = []
    fixed_slices: list[bytes | None] = []
    for s in fixed_sizes:
        if s is not None:
            fixed_slices.append(data[pos : pos + s])
            pos += s
        else:
            off = int.from_bytes(data[pos : pos + OFFSET_SIZE], "little")
            offsets.append(off)
            fixed_slices.append(None)
            pos += OFFSET_SIZE
    # Validate offsets
    if offsets:
        if offsets[0] != fixed_length:
            raise ValueError(
                f"SSZ: first offset {offsets[0]} != fixed length {fixed_length}"
            )
        for a, b in zip(offsets, offsets[1:]):
            if b < a:
                raise ValueError("SSZ: decreasing offsets")
        if offsets[-1] > len(data):
            raise ValueError("SSZ: offset beyond data end")
    # Second pass: decode
    values: list[Any] = []
    var_idx = 0
    for t, fs in zip(element_types, fixed_slices):
        if fs is not None:
            values.append(t.deserialize(fs))
        else:
            start = offsets[var_idx]
            end = offsets[var_idx + 1] if var_idx + 1 < len(offsets) else len(data)
            values.append(t.deserialize(data[start:end]))
            var_idx += 1
    return values


# ---------------------------------------------------------------------------
# Byte vectors / lists
# ---------------------------------------------------------------------------


class ByteVectorType(SSZType):
    def __init__(self, length: int):
        self.length = length

    def __repr__(self) -> str:
        return f"ByteVector[{self.length}]"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.length

    def serialize(self, value: bytes) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: got {len(value)} bytes")
        return bytes(value)

    def deserialize(self, data: bytes) -> bytes:
        if len(data) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: got {len(data)} bytes")
        return bytes(data)

    def chunk_count(self) -> int:
        return (self.length + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK

    def hash_tree_root(self, value: bytes) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)))

    def default(self) -> bytes:
        return b"\x00" * self.length


class ByteListType(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"ByteList[{self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def min_size(self) -> int:
        return 0

    def max_size(self) -> int:
        return self.limit

    def serialize(self, value: bytes) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"ByteList[{self.limit}]: got {len(value)} bytes")
        return bytes(value)

    def deserialize(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise ValueError(f"ByteList[{self.limit}]: got {len(data)} bytes")
        return bytes(data)

    def chunk_count(self) -> int:
        return (self.limit + BYTES_PER_CHUNK - 1) // BYTES_PER_CHUNK

    def hash_tree_root(self, value: bytes) -> bytes:
        root = merkleize(pack_bytes(value), limit=self.chunk_count())
        return mix_in_length(root, len(value))

    def default(self) -> bytes:
        return b""


# ---------------------------------------------------------------------------
# Bitfields (values: list[bool])
# ---------------------------------------------------------------------------


def _bits_to_bytes(bits: list[bool]) -> bytes:
    out = bytearray((len(bits) + 7) // 8)
    for i, bit in enumerate(bits):
        if bit:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


def _bytes_to_bits(data: bytes, count: int) -> list[bool]:
    return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(count)]


class BitvectorType(SSZType):
    def __init__(self, length: int):
        if length <= 0:
            raise ValueError("Bitvector length must be > 0")
        self.length = length

    def __repr__(self) -> str:
        return f"Bitvector[{self.length}]"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return (self.length + 7) // 8

    def serialize(self, value: list[bool]) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"Bitvector[{self.length}]: got {len(value)} bits")
        return _bits_to_bytes(value)

    def deserialize(self, data: bytes) -> list[bool]:
        if len(data) != self.fixed_size():
            raise ValueError(f"Bitvector[{self.length}]: got {len(data)} bytes")
        # Excess bits in the last byte must be zero
        if self.length % 8:
            if data[-1] >> (self.length % 8):
                raise ValueError("Bitvector: non-zero padding bits")
        return _bytes_to_bits(data, self.length)

    def chunk_count(self) -> int:
        return (self.length + 255) // 256

    def hash_tree_root(self, value: list[bool]) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)), limit=self.chunk_count())

    def default(self) -> list[bool]:
        return [False] * self.length


class BitlistType(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"Bitlist[{self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def min_size(self) -> int:
        return 1

    def max_size(self) -> int:
        return (self.limit // 8) + 1

    def serialize(self, value: list[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"Bitlist[{self.limit}]: got {len(value)} bits")
        # delimiter bit marks the length
        bits = list(value) + [True]
        return _bits_to_bytes(bits)

    def deserialize(self, data: bytes) -> list[bool]:
        if len(data) == 0:
            raise ValueError("Bitlist: empty data")
        last = data[-1]
        if last == 0:
            raise ValueError("Bitlist: missing delimiter bit")
        bit_len = (len(data) - 1) * 8 + last.bit_length() - 1
        if bit_len > self.limit:
            raise ValueError(f"Bitlist[{self.limit}]: got {bit_len} bits")
        return _bytes_to_bits(data, bit_len)

    def chunk_count(self) -> int:
        return (self.limit + 255) // 256

    def hash_tree_root(self, value: list[bool]) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"Bitlist[{self.limit}]: got {len(value)} bits")
        root = merkleize(pack_bytes(_bits_to_bytes(value)), limit=self.chunk_count())
        return mix_in_length(root, len(value))

    def default(self) -> list[bool]:
        return []


# ---------------------------------------------------------------------------
# Vector / List
# ---------------------------------------------------------------------------


class VectorType(SSZType):
    def __init__(self, element_type: SSZType, length: int):
        if length <= 0:
            raise ValueError("Vector length must be > 0")
        self.element_type = element_type
        self.length = length

    def __repr__(self) -> str:
        return f"Vector[{self.element_type!r}, {self.length}]"

    def is_fixed_size(self) -> bool:
        return self.element_type.is_fixed_size()

    def fixed_size(self) -> int:
        return self.element_type.fixed_size() * self.length

    def min_size(self) -> int:
        et = self.element_type
        if et.is_fixed_size():
            return self.fixed_size()
        return self.length * (OFFSET_SIZE + et.min_size())

    def max_size(self) -> int:
        et = self.element_type
        if et.is_fixed_size():
            return self.fixed_size()
        return self.length * (OFFSET_SIZE + et.max_size())

    def serialize(self, value: list) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"Vector[{self.length}]: got {len(value)} elements")
        if self.element_type.is_fixed_size():
            return b"".join(self.element_type.serialize(v) for v in value)
        return _serialize_sequence([self.element_type] * self.length, list(value))

    def deserialize(self, data: bytes) -> list:
        et = self.element_type
        if et.is_fixed_size():
            es = et.fixed_size()
            if len(data) != es * self.length:
                raise ValueError("Vector: wrong byte length")
            return SszVec(
                et.deserialize(data[i * es : (i + 1) * es])
                for i in range(self.length)
            )
        return SszVec(_deserialize_sequence([et] * self.length, data))

    def chunk_count(self) -> int:
        if _is_basic(self.element_type):
            return (self.length * self.element_type.fixed_size() + 31) // 32
        return self.length

    def hash_tree_root(self, value: list) -> bytes:
        if len(value) != self.length:
            raise ValueError(f"Vector[{self.length}]: got {len(value)} elements")
        et = self.element_type
        if _is_basic(et):
            return cached.basic_seq_root(et, value, self.chunk_count())
        return cached.composite_seq_root(et, value, self.chunk_count())

    def default(self) -> list:
        return SszVec(
            self.element_type.default() for _ in range(self.length)
        )


class ListType(SSZType):
    def __init__(self, element_type: SSZType, limit: int):
        self.element_type = element_type
        self.limit = limit

    def __repr__(self) -> str:
        return f"List[{self.element_type!r}, {self.limit}]"

    def is_fixed_size(self) -> bool:
        return False

    def min_size(self) -> int:
        return 0

    def max_size(self) -> int:
        et = self.element_type
        per = et.fixed_size() if et.is_fixed_size() else OFFSET_SIZE + et.max_size()
        return per * self.limit

    def serialize(self, value: list) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"List[{self.limit}]: got {len(value)} elements")
        et = self.element_type
        if et.is_fixed_size():
            return b"".join(et.serialize(v) for v in value)
        return _serialize_sequence([et] * len(value), list(value))

    def deserialize(self, data: bytes) -> list:
        et = self.element_type
        if et.is_fixed_size():
            es = et.fixed_size()
            if es == 0 or len(data) % es:
                raise ValueError("List: byte length not a multiple of element size")
            n = len(data) // es
            if n > self.limit:
                raise ValueError(f"List[{self.limit}]: got {n} elements")
            return SszVec(
                et.deserialize(data[i * es : (i + 1) * es]) for i in range(n)
            )
        if len(data) == 0:
            return SszVec()
        # element count from the first offset
        first = int.from_bytes(data[:OFFSET_SIZE], "little")
        if first % OFFSET_SIZE or first == 0:
            raise ValueError("List: invalid first offset")
        n = first // OFFSET_SIZE
        if n > self.limit:
            raise ValueError(f"List[{self.limit}]: got {n} elements")
        return SszVec(_deserialize_sequence([et] * n, data))

    def chunk_count(self) -> int:
        if _is_basic(self.element_type):
            return (self.limit * self.element_type.fixed_size() + 31) // 32
        return self.limit

    def hash_tree_root(self, value: list) -> bytes:
        if len(value) > self.limit:
            raise ValueError(f"List[{self.limit}]: got {len(value)} elements")
        et = self.element_type
        if _is_basic(et):
            root = cached.basic_seq_root(et, value, self.chunk_count())
        else:
            root = cached.composite_seq_root(et, value, self.chunk_count())
        return mix_in_length(root, len(value))

    def default(self) -> list:
        return SszVec()


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


class ContainerValue:
    """Attribute-style value for ContainerType; generated per container.

    Carries a version counter `_v` bumped on every field write and a
    root cache `_hc` — the hooks the incremental hashTreeRoot layer
    (cached.py) uses to skip re-hashing unchanged subtrees.
    """

    _type: "ContainerType"
    __slots__ = ("_v", "_hc", "_shared")

    def __init__(self, **kwargs):
        object.__setattr__(self, "_shared", False)
        object.__setattr__(self, "_v", 0)
        for name in self._type.field_names:
            if name in kwargs:
                setattr(self, name, kwargs.pop(name))
            else:
                setattr(self, name, self._type.field_types[name].default())
        if kwargs:
            raise TypeError(f"unknown fields {sorted(kwargs)} for {self._type.name}")

    def __setattr__(self, name, value):
        try:
            if self._shared:
                raise SharedMutationError(
                    f"in-place mutation of {self._type.name} shared "
                    "between cloned states; use copy-on-write "
                    "(statetransition.util.mut / replace the element)"
                )
            ver = self._v
        except AttributeError:
            object.__setattr__(self, "_shared", False)
            ver = 0
        object.__setattr__(self, name, value)
        object.__setattr__(self, "_v", ver + 1)

    def __eq__(self, other) -> bool:
        if not isinstance(other, ContainerValue) or other._type is not self._type:
            return NotImplemented
        return all(
            getattr(self, n) == getattr(other, n) for n in self._type.field_names
        )

    def __repr__(self) -> str:
        inner = ", ".join(
            f"{n}={getattr(self, n)!r}" for n in self._type.field_names[:4]
        )
        more = "..." if len(self._type.field_names) > 4 else ""
        return f"{self._type.name}({inner}{more})"

    def copy(self):
        return self._type.value_class(
            **{n: getattr(self, n) for n in self._type.field_names}
        )


class ContainerType(SSZType):
    def __init__(self, name: str, fields: list[tuple[str, SSZType]]):
        if not fields:
            raise ValueError("Container must have at least one field")
        self.name = name
        self.fields = list(fields)
        self.field_names = [n for n, _ in fields]
        self.field_types = dict(fields)
        self._types_list = [t for _, t in fields]
        self.value_class = type(
            name,
            (ContainerValue,),
            {"_type": self, "__slots__": tuple(self.field_names)},
        )
        self._fixed = all(t.is_fixed_size() for t in self._types_list)
        self._flat = None  # lazy: all fields hold immutable Python values

    def is_flat(self) -> bool:
        """True when every field value is an immutable Python object
        (int/bool/bytes) — then the value's version counter alone
        certifies its cached root (no deep mutation possible)."""
        if self._flat is None:
            self._flat = all(
                isinstance(
                    t, (UintType, BooleanType, ByteVectorType, ByteListType)
                )
                for t in self._types_list
            )
        return self._flat

    def __repr__(self) -> str:
        return f"Container[{self.name}]"

    def __call__(self, **kwargs) -> ContainerValue:
        return self.value_class(**kwargs)

    def is_fixed_size(self) -> bool:
        return self._fixed

    def fixed_size(self) -> int:
        if not self._fixed:
            raise ValueError(f"{self.name} is variable-size")
        return sum(t.fixed_size() for t in self._types_list)

    def min_size(self) -> int:
        return sum(
            t.fixed_size() if t.is_fixed_size() else OFFSET_SIZE + t.min_size()
            for t in self._types_list
        )

    def max_size(self) -> int:
        return sum(
            t.fixed_size() if t.is_fixed_size() else OFFSET_SIZE + t.max_size()
            for t in self._types_list
        )

    def serialize(self, value: ContainerValue) -> bytes:
        values = [getattr(value, n) for n in self.field_names]
        return _serialize_sequence(self._types_list, values)

    def deserialize(self, data: bytes) -> ContainerValue:
        values = _deserialize_sequence(self._types_list, data)
        return self.value_class(**dict(zip(self.field_names, values)))

    def chunk_count(self) -> int:
        return len(self.fields)

    def hash_tree_root(self, value: ContainerValue) -> bytes:
        hc = getattr(value, "_hc", None)
        if self.is_flat():
            ver = getattr(value, "_v", None)
            if hc is not None and hc[0] == ver:
                return hc[1]
            chunks = [
                t.hash_tree_root(getattr(value, n)) for n, t in self.fields
            ]
            root = merkleize(chunks)
            try:
                object.__setattr__(value, "_hc", (ver, root))
            except AttributeError:
                pass
            return root
        # non-flat: child roots recompute cheaply through their own
        # caches; memoize the merkle step on the child-root blob
        chunks = [
            t.hash_tree_root(getattr(value, n)) for n, t in self.fields
        ]
        blob = b"".join(chunks)
        if hc is not None and hc[0] == blob:
            return hc[1]
        root = merkleize(chunks)
        try:
            object.__setattr__(value, "_hc", (blob, root))
        except AttributeError:
            pass
        return root

    def default(self) -> ContainerValue:
        return self.value_class()

"""Incremental hashTreeRoot: value-attached merkle caches.

Reference analog: @chainsafe/persistent-merkle-tree + ssz ViewDU
(SURVEY.md §2.1) — the reference keeps states as tree-backed views so a
block import re-hashes only changed subtrees. This framework keeps plain
Python values (the state transition mutates them in place), so the
equivalent is built from three pieces:

  - `ContainerValue` carries a version counter bumped on every field
    write (composite.py); "flat" containers (all fields immutable
    Python values — e.g. Validator) cache their root keyed on that
    version, making the per-element root an O(1) lookup when unchanged.
  - `SszVec` (a list subclass produced by List/Vector deserialize and
    default) carries a `_VecCache`: the packed leaf-chunk blob, the
    element references/versions it was computed from, and the resulting
    root. Re-hashing polls element identity+version, recomputes only
    dirty leaf chunks, and re-merkleizes through the native batched
    SHA-NI hasher (csrc/sha256_merkle.c) — the as-sha256 analog.
  - `clone_value` structurally copies a value *with* its caches (new
    element objects, warm roots), replacing O(state) serialize +
    deserialize cloning (reference: state.clone() on ViewDU trees).

The dominant costs of a naive hash — per-element SSZ serialization and
SHA over every chunk — are thus paid only for elements that actually
changed; the remaining cost is an identity/version poll over big lists
plus a native re-merkleize of their (cached) chunk blobs.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .core import merkleize, next_pow_of_two, zero_hash

# Element classification for list/vector caching:
_K_IMMUT = 0  # element values are immutable (int/bool/bytes): identity poll
_K_FLAT = 1  # flat containers: identity + version poll
_K_OTHER = 2  # deep-mutable (nested lists/bitfields): always recompute

# Dirty-index sets are capped; structural ops or overflow fall back to a
# full poll (still cheap — the chunk blob is cached).
_MAX_DIRTY = 8192


class SszVec(list):
    """List that carries a merkle cache and tracks element writes.

    Produced by ListType/VectorType deserialize()/default(). Behaves as
    a plain list; only the hashing layer looks at the extra slots.
    """

    __slots__ = ("_dirty", "_hc", "_aux", "_cols", "_cols_dirty")

    def __init__(self, *args):
        super().__init__(*args)
        self._dirty = None  # None = unknown/all; else set of indices
        self._hc = None
        self._aux = None  # opaque consumer tag (e.g. pubkey-map watermark)
        # columnar cache (RegistryArrays): arrays dict + rows stale
        # since it was built; carried through clones (elements shared)
        self._cols = None
        self._cols_dirty: set = set()

    # -- index writes (tracked) --
    def __setitem__(self, idx, val):
        list.__setitem__(self, idx, val)
        if isinstance(idx, int):
            i = idx if idx >= 0 else idx + len(self)
            self._note(i)
            self.note_cols(i)
        else:
            self._dirty = None
            self._cols = None

    def note_cols(self, i: int) -> None:
        """Mark row i stale for the columnar cache. Called by
        __setitem__ and by statetransition.util.mut for in-place
        mutations of already-private elements."""
        if self._cols is not None:
            d = self._cols_dirty
            if len(d) >= 65536:
                self._cols = None
                d.clear()
            else:
                d.add(i)

    def _note(self, i: int) -> None:
        d = self._dirty
        if d is not None:
            if len(d) >= _MAX_DIRTY:
                self._dirty = None
            else:
                d.add(i)

    # -- structural ops (cache-invalidating) --
    def _structural(self):
        self._dirty = None
        self._cols = None
        self._cols_dirty.clear()

    def append(self, v):
        list.append(self, v)
        self._structural()

    def extend(self, it):
        list.extend(self, it)
        self._structural()

    def insert(self, i, v):
        list.insert(self, i, v)
        self._structural()

    def pop(self, i=-1):
        out = list.pop(self, i)
        self._structural()
        return out

    def remove(self, v):
        list.remove(self, v)
        self._structural()

    def clear(self):
        list.clear(self)
        self._structural()

    def __delitem__(self, i):
        list.__delitem__(self, i)
        self._structural()

    def sort(self, **kw):
        list.sort(self, **kw)
        self._structural()

    def reverse(self):
        list.reverse(self)
        self._structural()

    def __iadd__(self, it):
        list.__iadd__(self, it)
        self._structural()
        return self

    def __imul__(self, k):
        list.__imul__(self, k)
        self._structural()
        return self

    def copy(self):
        return SszVec(self)

    def __reduce__(self):  # pickle without the caches
        return (SszVec, (list(self),))


class _VecCache:
    __slots__ = ("etype", "n", "chunks", "root", "refs", "vers")

    def __init__(self, etype, n, chunks, root, refs, vers):
        self.etype = etype  # element SSZType the cache was built for
        self.n = n  # element count
        self.chunks = chunks  # bytearray: packed leaf chunks
        self.root = root  # merkle root over chunks (pre length-mix)
        self.refs = refs  # element object refs at last hash (or None)
        self.vers = vers  # element versions (flat containers) or None


def elem_kind(et) -> int:
    from . import composite as c
    from .basic import BooleanType, UintType

    if isinstance(et, (UintType, BooleanType, c.ByteVectorType, c.ByteListType)):
        return _K_IMMUT
    if isinstance(et, c.ContainerType) and et.is_flat():
        return _K_FLAT
    return _K_OTHER


def _merkleize_blob(blob: bytes, count: int, limit: int | None) -> bytes:
    """Merkle root of `count` chunks given as one packed byte blob."""
    if limit is None:
        limit = next_pow_of_two(count)
    else:
        limit = next_pow_of_two(limit)
    depth = (limit - 1).bit_length() if limit > 1 else 0
    if count == 0:
        return zero_hash(depth)
    from ..crypto import sha256_batch

    if count >= 8 and sha256_batch.available():
        return sha256_batch.merkleize_packed(bytes(blob), count, depth)
    chunks = [bytes(blob[i * 32 : (i + 1) * 32]) for i in range(count)]
    return merkleize(chunks, limit=limit)


# ---------------------------------------------------------------------------
# Basic-element sequences (uint*/boolean): packed chunk blob caching
# ---------------------------------------------------------------------------

_NP_DTYPES = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _fast_pack(et, value: list) -> bytes:
    """Packed little-endian bytes of a basic-element sequence."""
    size = et.fixed_size()
    dt = _NP_DTYPES.get(size)
    if dt is not None and value:
        try:
            arr = np.asarray(value, dtype=dt)
            # numpy wraps out-of-range silently only via explicit casts;
            # asarray from python ints raises OverflowError — desired.
            return arr.tobytes()
        except (OverflowError, TypeError, ValueError):
            pass
    return b"".join(et.serialize(v) for v in value)


def basic_seq_root(et, value: list, limit_chunks: int | None) -> bytes:
    """Root of a uint/boolean sequence with chunk-blob caching."""
    esize = et.fixed_size()
    per = 32 // esize
    n = len(value)
    nchunks = (n + per - 1) // per
    cache = value._hc if isinstance(value, SszVec) else None
    dirty = value._dirty if isinstance(value, SszVec) else None

    if (
        cache is not None
        and cache.etype is et
        and cache.n == n
        and dirty is not None
    ):
        if not dirty:
            return cache.root
        blob = cache.chunks
        for ci in {i // per for i in dirty}:
            seg = _fast_pack(et, value[ci * per : (ci + 1) * per])
            blob[ci * 32 : ci * 32 + len(seg)] = seg
        cache.root = _merkleize_blob(blob, nchunks, limit_chunks)
        value._dirty = set()
        return cache.root

    raw = _fast_pack(et, value)
    pad = (-len(raw)) % 32
    blob = bytearray(raw + b"\x00" * pad)
    if cache is not None and cache.etype is et and cache.n == n and blob == cache.chunks:
        root = cache.root
    else:
        root = _merkleize_blob(blob, nchunks, limit_chunks)
    if isinstance(value, SszVec):
        value._hc = _VecCache(et, n, blob, root, None, None)
        value._dirty = set()
    return root


# ---------------------------------------------------------------------------
# Composite-element sequences: per-element root caching + identity poll
# ---------------------------------------------------------------------------


def composite_seq_root(et, value: list, limit_chunks: int | None) -> bytes:
    """Root of a sequence of composite elements.

    Flat-container and immutable elements are polled by identity (and
    version); only dirty element roots are recomputed, and the chunk
    blob re-merkleizes natively. Deep-mutable elements always recompute
    (their own sub-caches absorb the cost).
    """
    kind = elem_kind(et)
    n = len(value)
    cache = value._hc if isinstance(value, SszVec) else None

    if (
        kind != _K_OTHER
        and cache is not None
        and cache.etype is et
        and cache.n == n
        and cache.refs is not None
    ):
        refs = cache.refs
        vers = cache.vers
        chunks = cache.chunks
        if kind == _K_IMMUT:
            dirty = [i for i in range(n) if value[i] is not refs[i]]
        else:
            dirty = [
                i
                for i in range(n)
                if value[i] is not refs[i] or value[i]._v != vers[i]
            ]
        if not dirty:
            return cache.root
        for i in dirty:
            e = value[i]
            chunks[i * 32 : (i + 1) * 32] = et.hash_tree_root(e)
            refs[i] = e
            if vers is not None:
                vers[i] = e._v
        cache.root = _merkleize_blob(chunks, n, limit_chunks)
        if isinstance(value, SszVec):
            value._dirty = set()
        return cache.root

    roots = [et.hash_tree_root(e) for e in value]
    blob = bytearray(b"".join(roots))
    root = _merkleize_blob(blob, n, limit_chunks)
    if isinstance(value, SszVec) and kind != _K_OTHER:
        vers = [e._v for e in value] if kind == _K_FLAT else None
        value._hc = _VecCache(et, n, blob, root, list(value), vers)
        value._dirty = set()
    return root


# ---------------------------------------------------------------------------
# Structural clone preserving caches
# ---------------------------------------------------------------------------


def clone_value(t, v: Any) -> Any:
    """Deep-copy an SSZ value so mutations to either side are invisible
    to the other, preserving warm hash caches (the reference analog is
    ViewDU state.clone() — O(1) there via structural sharing; here a
    structural copy whose re-hash cost after cloning is ~zero)."""
    from . import composite as c
    from .basic import BooleanType, UintType

    if isinstance(t, (UintType, BooleanType, c.ByteVectorType, c.ByteListType)):
        return v  # immutable
    if isinstance(t, (c.BitvectorType, c.BitlistType)):
        return list(v)
    if isinstance(t, (c.ListType, c.VectorType)):
        et = t.element_type
        kind = elem_kind(et)
        if kind == _K_IMMUT:
            out = SszVec(v)
            out._aux = getattr(v, "_aux", None)
        elif kind == _K_FLAT:
            # copy-on-write: share the element objects and freeze them.
            # Writers must replace elements (statetransition.util.mut);
            # ContainerValue.__setattr__ enforces it. This makes state
            # cloning O(list) instead of O(elements x fields) — the
            # ViewDU structural-sharing analog.
            for e in v:
                object.__setattr__(e, "_shared", True)
            out = SszVec(v)
            # element identity is preserved, so consumer tags keyed on
            # list contents (pubkey-map watermark) remain valid
            out._aux = getattr(v, "_aux", None)
            # the columnar cache stays valid across clones (same
            # elements); pending stale rows carry over
            out._cols = getattr(v, "_cols", None)
            out._cols_dirty = set(getattr(v, "_cols_dirty", ()) or ())
        else:
            out = SszVec(clone_value(et, e) for e in v)
        old = v._hc if isinstance(v, SszVec) else None
        if old is not None and old.etype is et and old.n == len(out):
            refs = vers = None
            if old.refs is not None:
                # valid only if the old cache was in sync with v; poll
                # cheaply: identity of old refs vs v's elements
                in_sync = all(a is b for a, b in zip(old.refs, v)) and (
                    old.vers is None
                    or all(e._v == ver for e, ver in zip(v, old.vers))
                )
                if in_sync:
                    refs = list(out)
                    vers = (
                        [e._v for e in out] if kind == _K_FLAT else None
                    )
                elif kind != _K_OTHER:
                    refs = None
            dirty_clean = isinstance(v, SszVec) and v._dirty == set()
            if old.refs is not None and refs is not None:
                out._hc = _VecCache(
                    et, old.n, bytearray(old.chunks), old.root, refs, vers
                )
                out._dirty = set()
            elif old.refs is None and dirty_clean:
                # basic-element cache: blob validity == empty dirty set
                out._hc = _VecCache(
                    et, old.n, bytearray(old.chunks), old.root, None, None
                )
                out._dirty = set()
        return out
    if isinstance(t, c.ContainerType):
        new = t.value_class.__new__(t.value_class)
        for name, ft in t.fields:
            object.__setattr__(new, name, clone_value(ft, getattr(v, name)))
        object.__setattr__(new, "_v", 0)
        hc = getattr(v, "_hc", None)
        if hc is not None:
            if t.is_flat():
                if hc[0] == v._v:
                    object.__setattr__(new, "_hc", (0, hc[1]))
            else:
                object.__setattr__(new, "_hc", hc)
        return new
    # unknown/basic union types: fall back to serde round-trip
    return t.deserialize(t.serialize(v))

"""SSZ core: type protocol + merkleization primitives.

Reference analog: @chainsafe/ssz (packages/types dep — SURVEY.md §2.1) and
@chainsafe/persistent-merkle-tree. This is a fresh implementation of the SSZ
spec (simple-serialize.md + merkleization). Hashing uses hashlib's C SHA-256;
batched tree hashing is delegated to lodestar_tpu.crypto.sha256_batch when
available (csrc/sha256 native extension), falling back to hashlib.
"""

from __future__ import annotations

from hashlib import sha256
from typing import Any

BYTES_PER_CHUNK = 32
ZERO_CHUNK = b"\x00" * BYTES_PER_CHUNK

# zero_hash(i) = root of a zero subtree of depth i
_ZERO_HASHES: list[bytes] = [ZERO_CHUNK]
for _ in range(64):
    _ZERO_HASHES.append(sha256(_ZERO_HASHES[-1] + _ZERO_HASHES[-1]).digest())


def zero_hash(depth: int) -> bytes:
    return _ZERO_HASHES[depth]


def hash_nodes(a: bytes, b: bytes) -> bytes:
    return sha256(a + b).digest()


def _hash_layer(layer: list[bytes]) -> list[bytes]:
    return [sha256(layer[i] + layer[i + 1]).digest() for i in range(0, len(layer), 2)]


def next_pow_of_two(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


_NATIVE_MIN_CHUNKS = 8
_native = None  # lazy: None = untried, False = unavailable


def _native_hasher():
    global _native
    if _native is None:
        try:
            from ..crypto import sha256_batch

            _native = sha256_batch if sha256_batch.available() else False
        except Exception:
            _native = False
    return _native


def merkleize(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks, padding with zero subtrees to `limit` leaves.

    limit=None pads to next_pow_of_two(len(chunks)). Large inputs go
    through the native batched hasher (csrc/sha256_merkle.c, the
    as-sha256 analog); small ones stay on hashlib.
    """
    count = len(chunks)
    if limit is None:
        limit = next_pow_of_two(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        limit = next_pow_of_two(limit)
    depth = (limit - 1).bit_length() if limit > 1 else 0
    if count == 0:
        return zero_hash(depth)
    if count >= _NATIVE_MIN_CHUNKS:
        native = _native_hasher()
        if native:
            return native.merkleize_packed(b"".join(chunks), count, depth)
    layer = list(chunks)
    for level in range(depth):
        if len(layer) % 2 == 1:
            layer.append(zero_hash(level))
        layer = _hash_layer(layer)
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little")).digest()


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return sha256(root + selector.to_bytes(32, "little")).digest()


def pack_bytes(data: bytes) -> list[bytes]:
    """Pack raw bytes into 32-byte chunks (right-padded with zeros)."""
    n = len(data)
    if n % BYTES_PER_CHUNK:
        data = data + b"\x00" * (BYTES_PER_CHUNK - n % BYTES_PER_CHUNK)
    return [data[i : i + BYTES_PER_CHUNK] for i in range(0, len(data), BYTES_PER_CHUNK)]


class SSZType:
    """Base of all SSZ type descriptors.

    A type descriptor knows how to serialize/deserialize/merkleize plain
    Python values (ints, bool, bytes, lists, container objects).
    """

    # -- sizing --
    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        """Byte size, only valid when is_fixed_size()."""
        raise NotImplementedError

    def min_size(self) -> int:
        return self.fixed_size() if self.is_fixed_size() else 0

    def max_size(self) -> int:
        return self.fixed_size() if self.is_fixed_size() else 2**32 - 1

    # -- serde --
    def serialize(self, value: Any) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes) -> Any:
        raise NotImplementedError

    # -- merkleization --
    def hash_tree_root(self, value: Any) -> bytes:
        raise NotImplementedError

    # -- defaults / validation --
    def default(self) -> Any:
        raise NotImplementedError

    def chunk_count(self) -> int:
        return 1

    # convenience
    def equals(self, a: Any, b: Any) -> bool:
        return self.serialize(a) == self.serialize(b)

    def from_hex(self, s: str) -> Any:
        return self.deserialize(bytes.fromhex(s.removeprefix("0x")))

"""SSZ: SimpleSerialize types, serialization and merkleization.

Reference analog: @chainsafe/ssz v0.18 (SURVEY.md §2.1). Own implementation
of the consensus-specs SSZ spec. Incremental/cached merkleization lives on
top of these primitives (see lodestar_tpu.ssz.cached)."""

from .core import (
    SSZType,
    merkleize,
    mix_in_length,
    mix_in_selector,
    pack_bytes,
    zero_hash,
    hash_nodes,
    next_pow_of_two,
)
from .basic import uint8, uint16, uint32, uint64, uint128, uint256, boolean, UintType, BooleanType
from .composite import (
    ByteVectorType,
    ByteListType,
    BitvectorType,
    BitlistType,
    VectorType,
    ListType,
    ContainerType,
    ContainerValue,
)

# Common aliases matching spec names
Bytes4 = ByteVectorType(4)
Bytes20 = ByteVectorType(20)
Bytes32 = ByteVectorType(32)
Bytes48 = ByteVectorType(48)
Bytes96 = ByteVectorType(96)

Root = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96

__all__ = [
    "SSZType",
    "merkleize",
    "mix_in_length",
    "mix_in_selector",
    "pack_bytes",
    "zero_hash",
    "hash_nodes",
    "next_pow_of_two",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "uint128",
    "uint256",
    "boolean",
    "UintType",
    "BooleanType",
    "ByteVectorType",
    "ByteListType",
    "BitvectorType",
    "BitlistType",
    "VectorType",
    "ListType",
    "ContainerType",
    "ContainerValue",
    "Bytes4",
    "Bytes20",
    "Bytes32",
    "Bytes48",
    "Bytes96",
    "Root",
    "BLSPubkey",
    "BLSSignature",
]

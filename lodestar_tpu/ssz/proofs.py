"""SSZ merkle proofs (single-leaf branches by generalized index).

Reference analog: @chainsafe/persistent-merkle-tree's proof API used by
the light-client server (chain/lightClient/proofs.ts): a branch is the
sibling hashes from a leaf chunk up to the root; verification is the
spec's is_valid_merkle_branch. Containers expose their field roots so
branches compose across nesting levels (e.g. finalized_checkpoint.root
inside BeaconState).
"""

from __future__ import annotations

from hashlib import sha256

from .core import next_pow_of_two, zero_hash


def _hash(a: bytes, b: bytes) -> bytes:
    return sha256(a + b).digest()


def merkle_branch(chunks: list[bytes], index: int, limit: int | None = None) -> list[bytes]:
    """Sibling path for chunks[index] in the padded chunk tree —
    bottom-up order, length = tree depth."""
    count = len(chunks)
    if limit is None:
        limit = next_pow_of_two(count)
    else:
        limit = next_pow_of_two(limit)
    depth = (limit - 1).bit_length() if limit > 1 else 0
    layer = list(chunks)
    branch = []
    idx = index
    for level in range(depth):
        sib = idx ^ 1
        if sib < len(layer):
            branch.append(layer[sib])
        else:
            branch.append(zero_hash(level))
        # next layer
        nxt = []
        if len(layer) % 2 == 1:
            layer = layer + [zero_hash(level)]
        for i in range(0, len(layer), 2):
            nxt.append(_hash(layer[i], layer[i + 1]))
        layer = nxt
        idx //= 2
    return branch


def is_valid_merkle_branch(
    leaf: bytes, branch: list[bytes], depth: int, index: int, root: bytes
) -> bool:
    """Spec is_valid_merkle_branch."""
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = _hash(branch[i], value)
        else:
            value = _hash(value, branch[i])
    return value == root


def container_field_roots(container_type, value) -> list[bytes]:
    """Per-field hash tree roots of a container value (the container's
    chunk layer)."""
    return [
        t.hash_tree_root(getattr(value, n))
        for n, t in container_type.fields
    ]


def container_field_branch(
    container_type, value, field_name: str
) -> tuple[bytes, list[bytes], int]:
    """(leaf, branch, field_index) proving `field_name` against the
    container's hash tree root."""
    chunks = container_field_roots(container_type, value)
    idx = container_type.field_names.index(field_name)
    return chunks[idx], merkle_branch(chunks, idx), idx


def concat_branches(
    inner_branch: list[bytes],
    inner_index: int,
    inner_depth: int,
    outer_branch: list[bytes],
    outer_index: int,
) -> tuple[list[bytes], int]:
    """Compose a proof of X inside F with a proof of F inside S into a
    proof of X inside S: branch = inner + outer, generalized index
    stacks the paths."""
    return (
        inner_branch + outer_branch,
        (outer_index << inner_depth) | inner_index,
    )

"""SSZ basic types: unsigned integers and boolean."""

from __future__ import annotations

from .core import SSZType, merkleize, pack_bytes


class UintType(SSZType):
    def __init__(self, byte_length: int):
        if byte_length not in (1, 2, 4, 8, 16, 32):
            raise ValueError(f"invalid uint byte length {byte_length}")
        self.byte_length = byte_length
        self.bits = byte_length * 8
        self._max = (1 << self.bits) - 1

    def __repr__(self) -> str:
        return f"uint{self.bits}"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return self.byte_length

    def serialize(self, value: int) -> bytes:
        if not 0 <= value <= self._max:
            raise ValueError(f"uint{self.bits} out of range: {value}")
        return int(value).to_bytes(self.byte_length, "little")

    def deserialize(self, data: bytes) -> int:
        if len(data) != self.byte_length:
            raise ValueError(f"uint{self.bits}: expected {self.byte_length} bytes, got {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value: int) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)))

    def default(self) -> int:
        return 0


class BooleanType(SSZType):
    def __repr__(self) -> str:
        return "boolean"

    def is_fixed_size(self) -> bool:
        return True

    def fixed_size(self) -> int:
        return 1

    def serialize(self, value: bool) -> bytes:
        if value not in (True, False, 0, 1):
            raise ValueError(f"invalid boolean {value!r}")
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise ValueError(f"invalid boolean encoding {data.hex()}")

    def hash_tree_root(self, value: bool) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)))

    def default(self) -> bool:
        return False


uint8 = UintType(1)
uint16 = UintType(2)
uint32 = UintType(4)
uint64 = UintType(8)
uint128 = UintType(16)
uint256 = UintType(32)
boolean = BooleanType()

"""Validator: clock-driven duty orchestration.

Reference analog: validator/src/validator.ts:82 + duty services
(services/attestation.ts:35 per-slot flow: attest at 1/3 slot,
aggregate at 2/3 slot; services/block.ts:64 propose at slot start).
The api is pluggable: `InProcessApi` binds to a chain directly (the
`lodestar dev` shape); an HTTP ApiClient binding slots in for a real
separated VC.
"""

from __future__ import annotations

from ..params import ForkSeq, preset
from ..statetransition import util
from .store import ValidatorStore


class InProcessApi:
    """Duck-typed beacon api over an in-process chain (test/dev mode;
    the reference's equivalent seam is the REST api the VC talks to)."""

    def __init__(self, cfg, types, chain):
        self.cfg = cfg
        self.types = types
        self.chain = chain

    def head_state(self):
        return self.chain.head_state

    def produce_block(self, slot: int, randao_reveal: bytes, attestations):
        block, post = self.chain.produce_block(
            slot, randao_reveal, attestations=attestations
        )
        return block, post.fork

    async def publish_block(self, signed_block):
        await self.chain.process_block(signed_block, is_timely=True)

    def attestation_data(self, slot: int, committee_index: int):
        chain = self.chain
        types = self.types
        head_root = chain.head_root
        st = chain.get_state(head_root).state
        epoch = util.compute_epoch_at_slot(slot)
        try:
            target_root = util.get_block_root(st, epoch)
        except ValueError:
            target_root = head_root
        data = types.AttestationData.default()
        data.slot = slot
        data.index = committee_index
        data.beacon_block_root = head_root
        data.source = st.current_justified_checkpoint
        tgt = types.Checkpoint.default()
        tgt.epoch = epoch
        tgt.root = target_root
        data.target = tgt
        return data

    async def publish_attestation(self, attestation, committee):
        await self.chain.on_attestation(attestation, committee)


class Validator:
    """Runs duties for a set of validator indices against an api."""

    def __init__(self, api, store: ValidatorStore, att_pool=None):
        self.api = api
        self.store = store
        self.types = store.types
        self.att_pool = att_pool
        self.blocks_proposed = 0
        self.attestations_published = 0

    # -- block duty ------------------------------------------------------

    async def run_block_duties(self, slot: int) -> bytes | None:
        """Propose if one of our validators owns the slot
        (BlockProposingService.runBlockTasks)."""
        view = self.api.head_state()
        st = view.state
        from ..chain.chain import _clone
        from ..statetransition.slot import process_slots

        scratch = _clone(view, self.types)
        process_slots(self.api.cfg, scratch, slot, self.types)
        proposer = util.get_beacon_proposer_index(
            scratch.state, electra=scratch.fork_seq >= ForkSeq.electra
        )
        if not self.store.has_validator(proposer):
            return None
        epoch = slot // preset().SLOTS_PER_EPOCH
        randao = self.store.sign_randao(proposer, epoch)
        atts = (
            self.att_pool.get_attestations_for_block(slot)
            if self.att_pool is not None
            else []
        )
        block, fork = self.api.produce_block(slot, randao, atts)
        signed = self.store.sign_block(proposer, block, fork)
        await self.api.publish_block(signed)
        self.blocks_proposed += 1
        ns = self.types.by_fork[fork]
        return ns.BeaconBlock.hash_tree_root(block)

    # -- attestation duty -------------------------------------------------

    async def run_attestation_duties(self, slot: int) -> int:
        """All owned validators in this slot's committees attest
        (AttestationService: one attestation data per committee, signed
        per validator)."""
        view = self.api.head_state()
        st = view.state
        epoch = util.compute_epoch_at_slot(slot)
        sh = util.get_shuffling(st, epoch)
        published = 0
        for ci, committee in enumerate(sh.committees_at_slot(slot)):
            owned = [
                (pos, int(v))
                for pos, v in enumerate(committee)
                if self.store.has_validator(int(v))
            ]
            if not owned:
                continue
            data = self.api.attestation_data(slot, ci)
            for pos, vindex in owned:
                sig = self.store.sign_attestation(vindex, data)
                att = self.types.Attestation.default()
                att.data = data
                bits = [False] * len(committee)
                bits[pos] = True
                att.aggregation_bits = bits
                att.signature = sig
                await self.api.publish_attestation(att, committee)
                if self.att_pool is not None:
                    self.att_pool.add(att)
                published += 1
        self.attestations_published += published
        return published

    async def on_slot(self, slot: int) -> None:
        await self.run_block_duties(slot)
        await self.run_attestation_duties(slot)

"""Validator: clock-driven duty orchestration.

Reference analog: validator/src/validator.ts:82 + duty services
(services/attestation.ts:35 per-slot flow: attest at 1/3 slot,
aggregate at 2/3 slot with selection proofs; services/syncCommittee.ts
sync messages + contributions; services/block.ts:64 propose at slot
start). The api is pluggable: `InProcessApi` binds to a chain directly
(the `lodestar dev` shape); `HttpApi` adapts the REST ApiClient for
the separated-VC topology the reference normally deploys.
"""

from __future__ import annotations

from hashlib import sha256

from ..params import (
    SYNC_COMMITTEE_SUBNET_COUNT,
    TARGET_AGGREGATORS_PER_COMMITTEE,
    TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    ForkSeq,
    preset,
)
from ..statetransition import util
from .store import ValidatorStore


def is_aggregator(committee_len: int, selection_proof: bytes) -> bool:
    """Spec is_aggregator (util/aggregator.ts
    isAggregatorFromCommitteeLength)."""
    modulo = max(
        1, committee_len // TARGET_AGGREGATORS_PER_COMMITTEE
    )
    return (
        int.from_bytes(sha256(selection_proof).digest()[:8], "little")
        % modulo
        == 0
    )


def is_sync_committee_aggregator(selection_proof: bytes) -> bool:
    """Spec is_sync_committee_aggregator."""
    p = preset()
    modulo = max(
        1,
        p.SYNC_COMMITTEE_SIZE
        // SYNC_COMMITTEE_SUBNET_COUNT
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    return (
        int.from_bytes(sha256(selection_proof).digest()[:8], "little")
        % modulo
        == 0
    )


async def _res(x):
    """Await duck-typed api results: HttpApi methods are async
    (executor-offloaded REST), InProcessApi's are plain sync."""
    import inspect

    if inspect.isawaitable(x):
        return await x
    return x


class InProcessApi:
    """Duck-typed beacon api over an in-process chain (test/dev mode;
    the reference's equivalent seam is the REST api the VC talks to)."""

    def __init__(self, cfg, types, chain):
        self.cfg = cfg
        self.types = types
        self.chain = chain

    def head_state(self):
        return self.chain.head_state

    def produce_block(self, slot: int, randao_reveal: bytes, attestations):
        sync_aggregate = None
        if self.contrib_pool is not None:
            view = self.chain.head_state
            if view.fork_seq >= ForkSeq.altair:
                # the block includes the previous slot's contributions
                # signing the then-head (produceBlockBody syncAggregate)
                sync_aggregate = self.contrib_pool.get_sync_aggregate(
                    slot - 1, self.chain.head_root
                )
        block, post = self.chain.produce_block(
            slot,
            randao_reveal,
            attestations=attestations,
            sync_aggregate=sync_aggregate,
        )
        return block, post.fork

    async def publish_block(self, signed_block, fork: str | None = None):
        await self.chain.process_block(signed_block, is_timely=True)

    def attestation_data(self, slot: int, committee_index: int):
        chain = self.chain
        types = self.types
        head_root = chain.head_root
        st = chain.get_state(head_root).state
        epoch = util.compute_epoch_at_slot(slot)
        try:
            target_root = util.get_block_root(st, epoch)
        except ValueError:
            target_root = head_root
        data = types.AttestationData.default()
        data.slot = slot
        data.index = committee_index
        data.beacon_block_root = head_root
        data.source = st.current_justified_checkpoint
        tgt = types.Checkpoint.default()
        tgt.epoch = epoch
        tgt.root = target_root
        data.target = tgt
        return data

    async def publish_attestation(self, attestation, committee):
        await self.chain.on_attestation(attestation, committee)
        if self.unagg_pool is not None:
            self.unagg_pool.add(attestation, len(committee))

    # aggregation + sync-committee seams (duck-typed with HttpApi)

    unagg_pool = None  # set by tests/devnode for aggregation flow
    sync_msg_pool = None
    contrib_pool = None

    def get_aggregated_attestation(self, slot: int, data_root: bytes):
        if self.unagg_pool is None:
            return None
        return self.unagg_pool.get_aggregate(slot, data_root)

    async def publish_aggregate_and_proof(self, signed_agg):
        pass  # in-process: the pool already holds the aggregate

    def get_sync_committee_duties(self, epoch: int, indices):
        st = self.chain.head_state.state
        view = self.chain.head_state
        if view.fork_seq < ForkSeq.altair:
            return []
        # honor the epoch's sync-committee period (current/next)
        per = preset().EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        state_period = util.get_current_epoch(st) // per
        period = epoch // per
        if period == state_period:
            committee = st.current_sync_committee
        elif period == state_period + 1:
            committee = st.next_sync_committee
        else:
            return []
        wanted = set(indices)
        pk2i = {
            bytes(v.pubkey): i for i, v in enumerate(st.validators)
        }
        duties: dict[int, list[int]] = {}
        for pos, pk in enumerate(committee.pubkeys):
            vi = pk2i.get(bytes(pk))
            if vi is not None and vi in wanted:
                duties.setdefault(vi, []).append(pos)
        return [
            {"validator_index": vi, "positions": positions}
            for vi, positions in duties.items()
        ]

    async def submit_sync_committee_message(
        self, slot: int, block_root: bytes, validator_index: int,
        position: int, signature: bytes,
    ):
        if self.sync_msg_pool is None:
            return
        p = preset()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        self.sync_msg_pool.add(
            slot,
            block_root,
            position // sub_size,
            position % sub_size,
            signature,
        )

    def produce_sync_contribution(
        self, slot: int, subcommittee_index: int, block_root: bytes
    ):
        if self.sync_msg_pool is None:
            return None
        return self.sync_msg_pool.get_contribution(
            slot, block_root, subcommittee_index
        )

    async def publish_contribution_and_proof(self, signed_cap):
        if self.contrib_pool is None:
            return
        c = signed_cap.message.contribution
        self.contrib_pool.add(
            {
                "slot": int(c.slot),
                "beacon_block_root": bytes(c.beacon_block_root),
                "subcommittee_index": int(c.subcommittee_index),
                "aggregation_bits": [
                    bool(b) for b in c.aggregation_bits
                ],
                "signature": bytes(c.signature),
            }
        )

    def head_root(self) -> bytes:
        return self.chain.head_root

    def proposer_for_slot(self, slot: int) -> int:
        from ..chain.chain import _clone
        from ..statetransition.slot import process_slots

        scratch = _clone(self.chain.head_state, self.types)
        process_slots(self.cfg, scratch, slot, self.types)
        return util.get_beacon_proposer_index(
            scratch.state,
            electra=scratch.fork_seq >= ForkSeq.electra,
        )

    def committees_at_slot(self, slot: int) -> list:
        st = self.chain.head_state.state
        epoch = util.compute_epoch_at_slot(slot)
        sh = util.get_shuffling(st, epoch)
        return [
            [int(v) for v in committee]
            for committee in sh.committees_at_slot(slot)
        ]


class HttpApi:
    """The same duck-typed seam over the REST ApiClient — the
    separated-VC topology (reference: the VC always talks REST,
    validator.ts + api client). All duty inputs come from public
    endpoints; no direct chain access."""

    def __init__(self, client, cfg, types):
        self.client = client
        self.cfg = cfg
        self.types = types

    async def _call(self, operation_id, params=None, body=None):
        """The urllib ApiClient blocks up to its timeout; run every
        REST round-trip in the default executor so slow beacon
        responses cannot starve the duty loop past its 1/3- and
        2/3-slot windows (ADVICE r3)."""
        import asyncio
        import functools

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None,
            functools.partial(
                self.client.call, operation_id, params, body
            ),
        )

    async def proposer_for_slot(self, slot: int) -> int:
        epoch = slot // preset().SLOTS_PER_EPOCH
        duties = await self._call(
            "getProposerDuties", {"epoch": epoch}
        )
        for d in duties:
            if int(d["slot"]) == slot:
                return int(d["validator_index"])
        raise RuntimeError(f"no proposer duty for slot {slot}")

    async def committees_at_slot(self, slot: int) -> list:
        out = await self._call(
            "getEpochCommittees",
            {"state_id": "head", "slot": slot},
        )
        return [
            [int(v) for v in c["validators"]]
            for c in sorted(out, key=lambda c: int(c["index"]))
        ]

    async def head_root(self) -> bytes:
        got = await self._call("getBlockRoot", {"block_id": "head"})
        return bytes.fromhex(got["root"].removeprefix("0x"))

    async def produce_block(self, slot: int, randao_reveal: bytes, attestations):
        from ..api.json_codec import from_json

        got = await self._call(
            "produceBlockV2",
            {
                "slot": slot,
                "randao_reveal": "0x" + randao_reveal.hex(),
            },
        )
        fork = got["version"]
        block = from_json(
            self.types.by_fork[fork].BeaconBlock, got["data"]
        )
        return block, fork

    async def publish_block(self, signed_block, fork: str | None = None):
        from ..api.json_codec import to_json

        assert fork is not None, "HttpApi.publish_block needs the fork"
        await self._call(
            "publishBlock",
            body=to_json(
                self.types.by_fork[fork].SignedBeaconBlock,
                signed_block,
            ),
        )

    async def attestation_data(self, slot: int, committee_index: int):
        from ..api.json_codec import from_json

        got = await self._call(
            "produceAttestationData",
            {"slot": slot, "committee_index": committee_index},
        )
        return from_json(self.types.AttestationData, got)

    async def publish_attestation(self, attestation, committee):
        from ..api.json_codec import to_json

        await self._call(
            "submitPoolAttestations",
            body=[to_json(self.types.Attestation, attestation)],
        )

    async def get_aggregated_attestation(self, slot: int, data_root: bytes):
        from ..api.json_codec import from_json

        from ..api import ApiError

        try:
            got = await self._call(
                "getAggregatedAttestation",
                {
                    "slot": slot,
                    "attestation_data_root": "0x" + data_root.hex(),
                },
            )
        except ApiError:
            return None
        return from_json(self.types.Attestation, got)

    async def publish_aggregate_and_proof(self, signed_agg):
        from ..api.json_codec import to_json

        await self._call(
            "publishAggregateAndProofs",
            body=[
                to_json(
                    self.types.SignedAggregateAndProof, signed_agg
                )
            ],
        )

    async def get_sync_committee_duties(self, epoch: int, indices):
        duties = await self._call(
            "getSyncCommitteeDuties",
            {"epoch": epoch},
            body=[str(i) for i in indices],
        )
        return [
            {
                "validator_index": int(d["validator_index"]),
                "positions": [
                    int(p)
                    for p in d["validator_sync_committee_indices"]
                ],
            }
            for d in duties
        ]

    async def submit_sync_committee_message(
        self, slot, block_root, validator_index, position, signature
    ):
        await self._call(
            "submitPoolSyncCommitteeSignatures",
            body=[
                {
                    "slot": str(slot),
                    "beacon_block_root": "0x" + bytes(block_root).hex(),
                    "validator_index": str(validator_index),
                    "signature": "0x" + bytes(signature).hex(),
                }
            ],
        )

    async def produce_sync_contribution(
        self, slot: int, subcommittee_index: int, block_root: bytes
    ):
        from ..api import ApiError

        try:
            got = await self._call(
                "produceSyncCommitteeContribution",
                {
                    "slot": slot,
                    "subcommittee_index": subcommittee_index,
                    "beacon_block_root": "0x" + bytes(block_root).hex(),
                },
            )
        except ApiError:
            return None
        from ..utils.bits import hex_to_bits

        sub_size = (
            preset().SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        )
        bits = hex_to_bits(got["aggregation_bits"], sub_size)
        return {
            "slot": int(got["slot"]),
            "beacon_block_root": bytes.fromhex(
                got["beacon_block_root"].removeprefix("0x")
            ),
            "subcommittee_index": int(got["subcommittee_index"]),
            "aggregation_bits": bits,
            "signature": bytes.fromhex(
                got["signature"].removeprefix("0x")
            ),
        }

    async def publish_contribution_and_proof(self, signed_cap):
        from ..utils.bits import bits_to_hex

        c = signed_cap.message.contribution
        packed_hex = bits_to_hex([bool(b) for b in c.aggregation_bits])
        await self._call(
            "publishContributionAndProofs",
            body=[
                {
                    "message": {
                        "aggregator_index": str(
                            int(signed_cap.message.aggregator_index)
                        ),
                        "contribution": {
                            "slot": str(int(c.slot)),
                            "beacon_block_root": "0x"
                            + bytes(c.beacon_block_root).hex(),
                            "subcommittee_index": str(
                                int(c.subcommittee_index)
                            ),
                            "aggregation_bits": "0x" + packed_hex,
                            "signature": "0x"
                            + bytes(c.signature).hex(),
                        },
                        "selection_proof": "0x"
                        + bytes(
                            signed_cap.message.selection_proof
                        ).hex(),
                    },
                    "signature": "0x"
                    + bytes(signed_cap.signature).hex(),
                }
            ],
        )


class Validator:
    """Runs duties for a set of validator indices against an api."""

    def __init__(self, api, store: ValidatorStore, att_pool=None):
        self.api = api
        self.store = store
        self.types = store.types
        self.att_pool = att_pool
        self.blocks_proposed = 0
        self.attestations_published = 0
        self.aggregates_published = 0
        self.sync_messages_published = 0
        self.sync_contributions_published = 0
        # per-slot/epoch duty memos: the attest + aggregate phases (and
        # message + contribution phases) share identical duty data; one
        # fetch per slot avoids doubled REST round-trips over HttpApi
        self._committees_memo: tuple = (None, None)
        self._sync_duties_memo: tuple = (None, None)

    async def _committees(self, slot: int) -> list:
        if self._committees_memo[0] != slot:
            self._committees_memo = (
                slot,
                await _res(self.api.committees_at_slot(slot)),
            )
        return self._committees_memo[1]

    async def _sync_duties(self, epoch: int) -> list:
        if self._sync_duties_memo[0] != epoch:
            self._sync_duties_memo = (
                epoch,
                await _res(
                    self.api.get_sync_committee_duties(
                        epoch, self.store.indices()
                    )
                ),
            )
        return self._sync_duties_memo[1]

    # -- block duty ------------------------------------------------------

    async def run_block_duties(self, slot: int) -> bytes | None:
        """Propose if one of our validators owns the slot
        (BlockProposingService.runBlockTasks)."""
        proposer = await _res(self.api.proposer_for_slot(slot))
        if not self.store.has_validator(proposer):
            return None
        epoch = slot // preset().SLOTS_PER_EPOCH
        randao = self.store.sign_randao(proposer, epoch)
        atts = (
            self.att_pool.get_attestations_for_block(slot)
            if self.att_pool is not None
            else []
        )
        block, fork = await _res(
            self.api.produce_block(slot, randao, atts)
        )
        signed = self.store.sign_block(proposer, block, fork)
        await self.api.publish_block(signed, fork)
        self.blocks_proposed += 1
        ns = self.types.by_fork[fork]
        return ns.BeaconBlock.hash_tree_root(block)

    # -- attestation duty -------------------------------------------------

    async def run_attestation_duties(self, slot: int) -> int:
        """All owned validators in this slot's committees attest
        (AttestationService: one attestation data per committee, signed
        per validator)."""
        published = 0
        for ci, committee in enumerate(await self._committees(slot)):
            owned = [
                (pos, int(v))
                for pos, v in enumerate(committee)
                if self.store.has_validator(int(v))
            ]
            if not owned:
                continue
            data = await _res(self.api.attestation_data(slot, ci))
            for pos, vindex in owned:
                sig = self.store.sign_attestation(vindex, data)
                att = self.types.Attestation.default()
                att.data = data
                bits = [False] * len(committee)
                bits[pos] = True
                att.aggregation_bits = bits
                att.signature = sig
                await self.api.publish_attestation(att, committee)
                if self.att_pool is not None:
                    self.att_pool.add(att)
                published += 1
        self.attestations_published += published
        return published

    # -- aggregation duty (2/3 slot; attestation.ts:35) -------------------

    async def run_aggregation_duties(self, slot: int) -> int:
        """Owned validators that win aggregator selection publish
        SignedAggregateAndProof for their committee's best aggregate
        (AttestationService aggregation phase + jobItem selection)."""
        epoch = util.compute_epoch_at_slot(slot)
        published = 0
        for ci, committee in enumerate(await self._committees(slot)):
            owned = [
                int(v)
                for v in committee
                if self.store.has_validator(int(v))
            ]
            if not owned:
                continue
            data = await _res(self.api.attestation_data(slot, ci))
            data_root = self.types.AttestationData.hash_tree_root(data)
            for vindex in owned:
                proof = self.store.sign_selection_proof(vindex, slot)
                if not is_aggregator(len(committee), proof):
                    continue
                agg = await _res(
                    self.api.get_aggregated_attestation(
                        slot, bytes(data_root)
                    )
                )
                if agg is None:
                    continue
                aap = self.types.AggregateAndProof.default()
                aap.aggregator_index = vindex
                aap.aggregate = agg
                aap.selection_proof = proof
                sig = self.store.sign_aggregate_and_proof(
                    vindex, aap, epoch
                )
                signed = self.types.SignedAggregateAndProof.default()
                signed.message = aap
                signed.signature = sig
                await self.api.publish_aggregate_and_proof(signed)
                published += 1
        self.aggregates_published += published
        return published

    # -- sync committee duties (syncCommittee.ts:24) ----------------------

    async def run_sync_committee_duties(self, slot: int) -> int:
        """Sync-committee messages for the head at this slot.

        Duty committee selection follows the spec's epoch(slot+1) rule
        (getSyncCommitteeSignatureSet / compute_sync_committee_period
        on slot+1): at the final slot of a period the message must be
        produced against the INCOMING committee (ADVICE r3)."""
        epoch = util.compute_epoch_at_slot(slot + 1)
        duties = await self._sync_duties(epoch)
        if not duties:
            return 0
        head = await _res(self.api.head_root())
        published = 0
        for duty in duties:
            vi = int(duty["validator_index"])
            sig = self.store.sign_sync_committee_message(
                vi, slot, head
            )
            for pos in duty["positions"]:
                await self.api.submit_sync_committee_message(
                    slot, head, vi, int(pos), sig
                )
            published += 1
        self.sync_messages_published += published
        return published

    async def run_sync_contribution_duties(self, slot: int) -> int:
        """2/3-slot contribution phase: selection-proof winners wrap
        the best subcommittee contribution into a
        SignedContributionAndProof (syncCommittee.ts contribution
        flow). Committee by the epoch(slot+1) rule, as for messages."""
        epoch = util.compute_epoch_at_slot(slot + 1)
        duties = await self._sync_duties(epoch)
        if not duties:
            return 0
        p = preset()
        sub_size = p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
        head = await _res(self.api.head_root())
        published = 0
        for duty in duties:
            vi = int(duty["validator_index"])
            subnets = {
                int(pos) // sub_size for pos in duty["positions"]
            }
            for subnet in subnets:
                proof = self.store.sign_sync_selection_data(
                    vi, slot, subnet
                )
                if not is_sync_committee_aggregator(proof):
                    continue
                contrib = await _res(self.api.produce_sync_contribution(
                    slot, subnet, head
                ))
                if contrib is None:
                    continue
                c = self.types.SyncCommitteeContribution.default()
                c.slot = contrib["slot"]
                c.beacon_block_root = contrib["beacon_block_root"]
                c.subcommittee_index = contrib["subcommittee_index"]
                c.aggregation_bits = contrib["aggregation_bits"]
                c.signature = contrib["signature"]
                cap = self.types.ContributionAndProof.default()
                cap.aggregator_index = vi
                cap.contribution = c
                cap.selection_proof = proof
                sig = self.store.sign_contribution_and_proof(vi, cap)
                signed = (
                    self.types.SignedContributionAndProof.default()
                )
                signed.message = cap
                signed.signature = sig
                await self.api.publish_contribution_and_proof(signed)
                published += 1
        self.sync_contributions_published += published
        return published

    async def on_slot(self, slot: int) -> None:
        """Full per-slot duty flow: propose at slot start, attest +
        sync messages at 1/3, aggregate + contribute at 2/3
        (attestation.ts:35, syncCommittee.ts:24)."""
        await self.run_block_duties(slot)
        await self.run_attestation_duties(slot)
        await self.run_sync_committee_duties(slot)
        await self.run_aggregation_duties(slot)
        await self.run_sync_contribution_duties(slot)

"""Keymanager REST API server (validator client side).

Reference analog: the keymanager server the validator command hosts
(cli/src/cmds/validator keymanager flags; routes from
api/src/keymanager): bearer-token-authenticated
GET/POST/DELETE /eth/v1/keystores backed by the Keymanager logic.
"""

from __future__ import annotations

import json
import secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .keymanager import Keymanager


class KeymanagerServer:
    def __init__(
        self,
        keymanager: Keymanager,
        pubkey_to_index,
        host: str = "127.0.0.1",
        port: int = 0,
        token: str | None = None,
    ):
        self.km = keymanager
        self.pubkey_to_index = pubkey_to_index
        self.host = host
        self.port = port
        # the reference writes an api-token file the operator passes to
        # clients; same contract here
        self.token = token or secrets.token_hex(32)
        self._httpd = None
        self._thread = None

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, status, obj):
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _authed(self) -> bool:
                import hmac

                auth = self.headers.get("Authorization", "")
                return hmac.compare_digest(
                    auth.encode(), f"Bearer {server.token}".encode()
                )

            def _body(self):
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                return json.loads(raw) if raw else {}

            def do_GET(self):
                if not self._authed():
                    self._json(401, {"message": "missing bearer token"})
                    return
                if self.path == "/eth/v1/keystores":
                    self._json(200, {"data": server.km.list_keys()})
                    return
                self._json(404, {"message": "not found"})

            def do_POST(self):
                if not self._authed():
                    self._json(401, {"message": "missing bearer token"})
                    return
                if self.path == "/eth/v1/keystores":
                    try:
                        body = self._body()
                        keystores = [
                            json.loads(k) if isinstance(k, str) else k
                            for k in body["keystores"]
                        ]
                        res = server.km.import_keystores(
                            keystores,
                            body["passwords"],
                            server.pubkey_to_index,
                        )
                    except (KeyError, ValueError, TypeError) as e:
                        self._json(400, {"message": repr(e)})
                        return
                    self._json(200, {"data": res})
                    return
                self._json(404, {"message": "not found"})

            def do_DELETE(self):
                if not self._authed():
                    self._json(401, {"message": "missing bearer token"})
                    return
                if self.path == "/eth/v1/keystores":
                    try:
                        body = self._body()
                        pubkeys = [
                            bytes.fromhex(
                                str(p).removeprefix("0x")
                            )
                            for p in body["pubkeys"]
                        ]
                    except (KeyError, ValueError, TypeError) as e:
                        self._json(400, {"message": repr(e)})
                        return
                    self._json(
                        200, {"data": server.km.delete_keys(pubkeys)}
                    )
                    return
                self._json(404, {"message": "not found"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

"""Validator client package.

Reference analog: packages/validator — `Validator` (src/validator.ts:82)
orchestrating duty services over a REST api client, `ValidatorStore`
(services/validatorStore.ts:149) holding keys + signing every object
type behind slashing protection (src/slashingProtection/index.ts:31,
EIP-3076 interchange) and doppelganger gating
(services/doppelgangerService.ts:39).
"""

from .slashing_protection import (
    InterchangeError,
    SlashingProtection,
    SlashingProtectionError,
)
from .store import ValidatorStore
from .validator import HttpApi, InProcessApi, Validator
from .doppelganger import DoppelgangerService, DoppelgangerStatus

__all__ = [
    "InterchangeError",
    "SlashingProtection",
    "SlashingProtectionError",
    "ValidatorStore",
    "Validator",
    "InProcessApi",
    "HttpApi",
    "DoppelgangerService",
    "DoppelgangerStatus",
]

"""Remote (external) signer client.

Reference analog: externalSignerClient
(validator/src/util/externalSignerClient.ts) — the web3signer-style
REST API: GET /upcheck, GET /api/v1/eth2/publicKeys, and
POST /api/v1/eth2/sign/{pubkey} with a typed signing request carrying
the signing root and fork info; the signer owns the keys.
"""

from __future__ import annotations

import asyncio
import json
import urllib.error
import urllib.request


class ExternalSignerError(Exception):
    pass


class ExternalSignerClient:
    def __init__(self, base_url: str, timeout: float = 5.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    async def _call(self, method: str, path: str, body=None):
        def _do():
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(
                self.base_url + path,
                data=data,
                method=method,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                return json.loads(raw) if raw else None

        try:
            return await asyncio.get_event_loop().run_in_executor(
                None, _do
            )
        except urllib.error.HTTPError as e:
            raise ExternalSignerError(
                f"{path}: HTTP {e.code} {e.read()[:200]!r}"
            ) from e
        except (urllib.error.URLError, OSError) as e:
            raise ExternalSignerError(f"{path}: {e}") from e

    async def upcheck(self) -> bool:
        try:
            await self._call("GET", "/upcheck")
            return True
        except ExternalSignerError:
            return False

    async def public_keys(self) -> list[bytes]:
        out = await self._call("GET", "/api/v1/eth2/publicKeys")
        return [bytes.fromhex(pk.removeprefix("0x")) for pk in out]

    async def sign(
        self,
        pubkey: bytes,
        signing_root: bytes,
        sign_type: str = "BEACON_BLOCK",
        extra: dict | None = None,
    ) -> bytes:
        body = {
            "type": sign_type,
            "signingRoot": "0x" + bytes(signing_root).hex(),
        }
        if extra:
            body.update(extra)
        out = await self._call(
            "POST", f"/api/v1/eth2/sign/0x{bytes(pubkey).hex()}", body
        )
        sig = out["signature"] if isinstance(out, dict) else out
        return bytes.fromhex(sig.removeprefix("0x"))


class MockExternalSigner:
    """In-process web3signer double backed by local secret keys (the
    reference tests run a mocked signer server the same way)."""

    def __init__(self, sks: dict[bytes, int]):
        # pubkey bytes -> sk int
        self.sks = dict(sks)
        self.requests: list = []

    async def upcheck(self) -> bool:
        return True

    async def public_keys(self) -> list[bytes]:
        return list(self.sks)

    async def sign(self, pubkey, signing_root, sign_type="BEACON_BLOCK",
                   extra=None) -> bytes:
        from ..crypto.bls.signature import sign as bls_sign

        sk = self.sks.get(bytes(pubkey))
        if sk is None:
            raise ExternalSignerError("unknown pubkey")
        self.requests.append((sign_type, bytes(signing_root)))
        # web3signer signs the 32-byte signing root directly
        return bls_sign(sk, bytes(signing_root))

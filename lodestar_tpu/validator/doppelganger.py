"""Doppelganger protection.

Reference analog: validator/src/services/doppelgangerService.ts:39 —
newly started validators stay silent for DEFAULT_REMAINING_DETECTION_
EPOCHS, watching the network for their own indices attesting; any
liveness hit means another instance runs the same keys and the client
shuts down rather than self-slash.
"""

from __future__ import annotations

from enum import Enum

REMAINING_DETECTION_EPOCHS = 1


class DoppelgangerStatus(str, Enum):
    verified_safe = "VerifiedSafe"
    unverified = "Unverified"
    doppelganger_detected = "DoppelgangerDetected"


class DoppelgangerService:
    def __init__(self, liveness_fn=None, process_shutdown_fn=None):
        """liveness_fn(epoch, indices) -> set of indices seen live on
        the network (api.validator.getLiveness in the reference)."""
        self.liveness_fn = liveness_fn or (lambda epoch, idxs: set())
        self.process_shutdown_fn = process_shutdown_fn
        self._registered: dict[int, int] = {}  # index -> epoch registered
        self._detected: set[int] = set()

    def register(self, index: int, current_epoch: int) -> None:
        self._registered.setdefault(index, current_epoch)

    def status(self, index: int, current_epoch: int) -> DoppelgangerStatus:
        if index in self._detected:
            return DoppelgangerStatus.doppelganger_detected
        start = self._registered.get(index)
        if start is None:
            return DoppelgangerStatus.unverified
        if current_epoch - start > REMAINING_DETECTION_EPOCHS:
            return DoppelgangerStatus.verified_safe
        return DoppelgangerStatus.unverified

    def is_signing_safe(self, index: int, current_epoch: int) -> bool:
        return (
            self.status(index, current_epoch)
            == DoppelgangerStatus.verified_safe
        )

    def on_epoch(self, epoch: int) -> None:
        """Run a liveness check for validators still in detection."""
        pending = [
            i
            for i, start in self._registered.items()
            if epoch - start <= REMAINING_DETECTION_EPOCHS
            and i not in self._detected
        ]
        if not pending:
            return
        live = self.liveness_fn(epoch, pending)
        if live:
            self._detected.update(live)
            if self.process_shutdown_fn is not None:
                self.process_shutdown_fn(
                    f"doppelganger detected for indices {sorted(live)}"
                )

"""ValidatorStore: keys + signing for every duty object.

Reference analog: validator/src/services/validatorStore.ts:149 — holds
signers, computes domains/signing roots, and gates every block and
attestation signature behind slashing protection and doppelganger
status.
"""

from __future__ import annotations

from ..config.beacon_config import compute_signing_root_from_roots
from ..crypto.bls.signature import sign, sk_to_pk
from ..params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    preset,
)
from ..ssz import uint64 as ssz_uint64
from .doppelganger import DoppelgangerService
from .slashing_protection import SlashingProtection


class ValidatorStore:
    def __init__(
        self,
        beacon_cfg,
        types,
        secret_keys: dict[int, int],  # validator index -> sk
        slashing_protection: SlashingProtection | None = None,
        doppelganger: DoppelgangerService | None = None,
    ):
        self.beacon_cfg = beacon_cfg
        self.types = types
        self.sks = dict(secret_keys)
        self.pubkeys = {i: sk_to_pk(sk) for i, sk in self.sks.items()}
        self.slashing_protection = (
            slashing_protection or SlashingProtection()
        )
        self.doppelganger = doppelganger

    def has_validator(self, index: int) -> bool:
        return index in self.sks

    def indices(self) -> list[int]:
        return sorted(self.sks)

    def _check_doppelganger(self, index: int, epoch: int) -> None:
        if self.doppelganger is not None and not (
            self.doppelganger.is_signing_safe(index, epoch)
        ):
            raise RuntimeError(
                f"validator {index} not verified safe (doppelganger)"
            )

    # -- signing ---------------------------------------------------------

    def sign_block(self, index: int, block, fork_name: str):
        """Signs full AND blinded blocks (validatorStore.ts
        signBlock over allForks.FullOrBlindedBeaconBlock): the blinded
        root equals the full root, so slashing protection and the
        domain are identical — only the SSZ type differs."""
        epoch = int(block.slot) // preset().SLOTS_PER_EPOCH
        self._check_doppelganger(index, epoch)
        ns = self.types.by_fork[fork_name]
        blinded = hasattr(block.body, "execution_payload_header")
        block_t = ns.BlindedBeaconBlock if blinded else ns.BeaconBlock
        root = block_t.hash_tree_root(block)
        domain = self.beacon_cfg.get_domain(DOMAIN_BEACON_PROPOSER, epoch)
        signing_root = compute_signing_root_from_roots(root, domain)
        self.slashing_protection.check_and_insert_block_proposal(
            self.pubkeys[index], int(block.slot), signing_root
        )
        signed = (
            ns.SignedBlindedBeaconBlock if blinded else ns.SignedBeaconBlock
        ).default()
        signed.message = block
        signed.signature = sign(self.sks[index], signing_root)
        return signed

    def sign_attestation(self, index: int, data):
        epoch = int(data.target.epoch)
        self._check_doppelganger(index, epoch)
        domain = self.beacon_cfg.get_domain(DOMAIN_BEACON_ATTESTER, epoch)
        root = self.types.AttestationData.hash_tree_root(data)
        signing_root = compute_signing_root_from_roots(root, domain)
        self.slashing_protection.check_and_insert_attestation(
            self.pubkeys[index],
            int(data.source.epoch),
            epoch,
            signing_root,
        )
        return sign(self.sks[index], signing_root)

    def sign_randao(self, index: int, epoch: int) -> bytes:
        domain = self.beacon_cfg.get_domain(DOMAIN_RANDAO, epoch)
        root = ssz_uint64.hash_tree_root(epoch)
        return sign(
            self.sks[index], compute_signing_root_from_roots(root, domain)
        )

    def sign_selection_proof(self, index: int, slot: int) -> bytes:
        epoch = slot // preset().SLOTS_PER_EPOCH
        domain = self.beacon_cfg.get_domain(DOMAIN_SELECTION_PROOF, epoch)
        root = ssz_uint64.hash_tree_root(slot)
        return sign(
            self.sks[index], compute_signing_root_from_roots(root, domain)
        )

    def sign_aggregate_and_proof(self, index: int, agg_and_proof, epoch):
        domain = self.beacon_cfg.get_domain(
            DOMAIN_AGGREGATE_AND_PROOF, epoch
        )
        root = self.types.AggregateAndProof.hash_tree_root(agg_and_proof)
        return sign(
            self.sks[index], compute_signing_root_from_roots(root, domain)
        )

    def sign_sync_committee_message(
        self, index: int, slot: int, block_root: bytes
    ) -> bytes:
        epoch = slot // preset().SLOTS_PER_EPOCH
        domain = self.beacon_cfg.get_domain(DOMAIN_SYNC_COMMITTEE, epoch)
        return sign(
            self.sks[index],
            compute_signing_root_from_roots(bytes(block_root), domain),
        )

    def sign_sync_selection_data(
        self, index: int, slot: int, subcommittee_index: int
    ) -> bytes:
        """Sync-committee aggregator selection proof
        (validatorStore.ts signSyncCommitteeSelectionProof)."""
        from ..params import DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF

        epoch = slot // preset().SLOTS_PER_EPOCH
        domain = self.beacon_cfg.get_domain(
            DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch
        )
        sd = self.types.SyncAggregatorSelectionData.default()
        sd.slot = slot
        sd.subcommittee_index = subcommittee_index
        root = self.types.SyncAggregatorSelectionData.hash_tree_root(sd)
        return sign(
            self.sks[index], compute_signing_root_from_roots(root, domain)
        )

    def sign_contribution_and_proof(self, index: int, cap) -> bytes:
        """validatorStore.ts signContributionAndProof."""
        from ..params import DOMAIN_CONTRIBUTION_AND_PROOF

        epoch = int(cap.contribution.slot) // preset().SLOTS_PER_EPOCH
        domain = self.beacon_cfg.get_domain(
            DOMAIN_CONTRIBUTION_AND_PROOF, epoch
        )
        root = self.types.ContributionAndProof.hash_tree_root(cap)
        return sign(
            self.sks[index], compute_signing_root_from_roots(root, domain)
        )

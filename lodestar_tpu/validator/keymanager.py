"""Keymanager: EIP-2335 keystores + the keymanager REST API surface.

Reference analog: the keymanager API served by the validator client
(cli/src/cmds/validator keymanager server; api/src/keymanager routes):
list/import/delete local keystores, with slashing-protection data
riding delete/import (EIP-3076). Keystore crypto is EIP-2335: scrypt or
pbkdf2 KDF + AES-128-CTR (pure-Python AES in crypto/aes.py — 32-byte
payloads, perf-irrelevant) + NFKD/control-stripped password
normalization, so keystores interoperate with every other EIP-2335
tool. Legacy round-2 "xor-sha256" keystores remain decryptable.
"""

from __future__ import annotations

import json
import os
import secrets
import unicodedata
from hashlib import pbkdf2_hmac, scrypt, sha256

from ..crypto.aes import aes128_ctr
from ..crypto.bls.signature import sk_from_bytes, sk_to_bytes, sk_to_pk


class KeystoreError(ValueError):
    pass


def normalize_password(password: str) -> bytes:
    """EIP-2335 password processing: NFKD-normalize, strip C0/C1 control
    codes and DEL, encode UTF-8."""
    nfkd = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c
        for c in nfkd
        if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F)
    )
    return stripped.encode("utf-8")


def _stream(key16: bytes, iv: bytes, n: int) -> bytes:
    """Keystream for the LEGACY xor-sha256 cipher stage (round-2
    keystores): SHA-256 counter mode over (key, iv)."""
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += sha256(
            key16 + iv + counter.to_bytes(8, "big")
        ).digest()
        counter += 1
    return bytes(out[:n])


def _derive(kdf: dict, password: bytes) -> bytes:
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return scrypt(
            password,
            salt=salt,
            n=params["n"],
            r=params["r"],
            p=params["p"],
            dklen=params["dklen"],
            maxmem=256 * 1024 * 1024,
        )
    if kdf["function"] == "pbkdf2":
        return pbkdf2_hmac(
            "sha256", password, salt, params["c"], params["dklen"]
        )
    raise KeystoreError(f"unsupported kdf {kdf['function']}")


def create_keystore(
    sk: int, password: str, path: str = "m/12381/3600/0/0/0",
    kdf: str = "pbkdf2",
) -> dict:
    """EIP-2335-shaped keystore json for a BLS secret key."""
    salt = secrets.token_bytes(32)
    iv = secrets.token_bytes(16)
    if kdf == "scrypt":
        kdf_mod = {
            "function": "scrypt",
            "params": {
                "dklen": 32, "n": 2**14, "r": 8, "p": 1,
                "salt": salt.hex(),
            },
            "message": "",
        }
    else:
        kdf_mod = {
            "function": "pbkdf2",
            "params": {
                "dklen": 32, "c": 2**15, "prf": "hmac-sha256",
                "salt": salt.hex(),
            },
            "message": "",
        }
    dk = _derive(kdf_mod, normalize_password(password))
    secret = sk_to_bytes(sk)
    cipher_text = aes128_ctr(dk[:16], iv, secret)
    checksum = sha256(dk[16:32] + cipher_text).digest()
    return {
        "version": 4,
        "uuid": secrets.token_hex(16),
        "path": path,
        "pubkey": sk_to_pk(sk).hex(),
        "crypto": {
            "kdf": kdf_mod,
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": checksum.hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_text.hex(),
            },
        },
    }


def decrypt_keystore(keystore: dict, password: str) -> int:
    crypto = keystore["crypto"]
    cipher_fn = crypto["cipher"]["function"]
    if cipher_fn not in ("aes-128-ctr", "xor-sha256"):
        raise KeystoreError(f"unsupported cipher {cipher_fn}")
    # Legacy round-2 keystores derived from the raw UTF-8 password
    # (no EIP-2335 normalization) — keep them decryptable.
    pw_bytes = (
        normalize_password(password)
        if cipher_fn == "aes-128-ctr"
        else password.encode()
    )
    dk = _derive(crypto["kdf"], pw_bytes)
    cipher_text = bytes.fromhex(crypto["cipher"]["message"])
    checksum = sha256(dk[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("wrong password (checksum mismatch)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    if cipher_fn == "aes-128-ctr":
        secret = aes128_ctr(dk[:16], iv, cipher_text)
    else:  # legacy round-2 keystores
        secret = bytes(
            a ^ b
            for a, b in zip(
                cipher_text, _stream(dk[:16], iv, len(cipher_text))
            )
        )
    return sk_from_bytes(secret)


class Keymanager:
    """The keymanager API's business logic (list/import/delete),
    bound to a ValidatorStore and a slashing-protection db."""

    def __init__(self, store, slashing_protection=None):
        self.store = store
        self.slashing = slashing_protection

    def list_keys(self) -> list[dict]:
        out = []
        for idx in self.store.indices():
            out.append(
                {
                    "validating_pubkey": "0x"
                    + sk_to_pk(self.store.sks[idx]).hex(),
                    "derivation_path": "",
                    "readonly": False,
                }
            )
        return out

    def import_keystores(
        self, keystores: list[dict], passwords: list[str],
        pubkey_to_index,
    ) -> list[dict]:
        """pubkey_to_index: fn(pubkey bytes) -> validator index | None
        (the registry binding)."""
        results = []
        for ks, pw in zip(keystores, passwords):
            try:
                sk = decrypt_keystore(ks, pw)
                pk = sk_to_pk(sk)
                idx = pubkey_to_index(pk)
                if idx is None:
                    results.append(
                        {"status": "error", "message": "unknown pubkey"}
                    )
                    continue
                dup = idx in self.store.sks
                self.store.sks[idx] = sk
                self.store.pubkeys[idx] = pk
                results.append(
                    {"status": "duplicate" if dup else "imported"}
                )
            except KeystoreError as e:
                results.append({"status": "error", "message": str(e)})
        return results

    def delete_keys(self, pubkeys: list[bytes]) -> list[dict]:
        """Returns per-key status + the EIP-3076 interchange for the
        deleted keys (the caller MUST persist it before re-importing
        elsewhere — reference: keymanager deleteKeystores)."""
        by_pk = {
            sk_to_pk(sk): idx for idx, sk in self.store.sks.items()
        }
        results = []
        for pk in pubkeys:
            idx = by_pk.pop(bytes(pk), None)  # pop: dup requests -> not_found
            if idx is None:
                results.append({"status": "not_found"})
                continue
            del self.store.sks[idx]
            self.store.pubkeys.pop(idx, None)
            entry = {"status": "deleted"}
            if self.slashing is not None:
                entry["slashing_protection"] = (
                    self.slashing.export_interchange()
                )
            results.append(entry)
        return results

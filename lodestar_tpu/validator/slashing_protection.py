"""Slashing protection: min-max surround vote DB + EIP-3076 interchange.

Reference analog: validator/src/slashingProtection/ — attestation
protection via min/max source-target tracking
(attestation/attestationByTarget.ts + minMaxSurround/), block
protection by slot, and the EIP-3076 JSON interchange format
(interchange/formats/completeV4.ts). The rules enforced:
  - never sign two different blocks at the same slot
  - never sign an attestation whose target is <= a previously signed
    target (double vote) unless identical
  - never sign an attestation that surrounds or is surrounded by a
    previous one
"""

from __future__ import annotations

import json
from dataclasses import dataclass


class SlashingProtectionError(Exception):
    pass


class InterchangeError(Exception):
    pass


@dataclass
class SignedAttestationRecord:
    source_epoch: int
    target_epoch: int
    signing_root: bytes | None = None


@dataclass
class SignedBlockRecord:
    slot: int
    signing_root: bytes | None = None


class SlashingProtection:
    """Per-pubkey signing history over a KV-ish store (dict or db
    controller). The reference persists to LevelDB; this accepts any
    mapping-like store and keeps an in-memory index."""

    def __init__(self, genesis_validators_root: bytes = b"\x00" * 32):
        self.genesis_validators_root = genesis_validators_root
        self._atts: dict[bytes, list[SignedAttestationRecord]] = {}
        self._blocks: dict[bytes, dict[int, SignedBlockRecord]] = {}

    # -- blocks ---------------------------------------------------------

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes | None = None
    ) -> None:
        blocks = self._blocks.setdefault(bytes(pubkey), {})
        existing = blocks.get(slot)
        if existing is not None:
            if (
                existing.signing_root is not None
                and signing_root is not None
                and existing.signing_root == signing_root
            ):
                return  # identical re-sign is safe
            raise SlashingProtectionError(
                f"double block proposal at slot {slot}"
            )
        # lower-bound rule: refuse slots at or below the minimum known
        # slot when history exists (EIP-3076 semantics)
        if blocks and slot < min(blocks):
            raise SlashingProtectionError(
                f"block slot {slot} below protection lower bound"
            )
        blocks[slot] = SignedBlockRecord(slot, signing_root)

    # -- attestations ----------------------------------------------------

    def check_and_insert_attestation(
        self,
        pubkey: bytes,
        source_epoch: int,
        target_epoch: int,
        signing_root: bytes | None = None,
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source after target")
        history = self._atts.setdefault(bytes(pubkey), [])
        for rec in history:
            # double vote: same target, different data
            if rec.target_epoch == target_epoch:
                if (
                    rec.signing_root is not None
                    and signing_root is not None
                    and rec.signing_root == signing_root
                    and rec.source_epoch == source_epoch
                ):
                    return
                raise SlashingProtectionError(
                    f"double vote at target {target_epoch}"
                )
            # surround checks
            if (
                source_epoch < rec.source_epoch
                and target_epoch > rec.target_epoch
            ):
                raise SlashingProtectionError(
                    "new attestation surrounds a previous one"
                )
            if (
                source_epoch > rec.source_epoch
                and target_epoch < rec.target_epoch
            ):
                raise SlashingProtectionError(
                    "new attestation is surrounded by a previous one"
                )
        # monotonic lower bound (pruned histories keep only min epochs)
        if history:
            min_target = min(r.target_epoch for r in history)
            if target_epoch < min_target:
                raise SlashingProtectionError(
                    "target below protection lower bound"
                )
        history.append(
            SignedAttestationRecord(source_epoch, target_epoch, signing_root)
        )

    # -- EIP-3076 interchange -------------------------------------------

    def export_interchange(self) -> dict:
        pubkeys = set(self._atts) | set(self._blocks)
        data = []
        for pk in sorted(pubkeys):
            data.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": [
                        {
                            "slot": str(b.slot),
                            **(
                                {"signing_root": "0x" + b.signing_root.hex()}
                                if b.signing_root
                                else {}
                            ),
                        }
                        for b in sorted(
                            self._blocks.get(pk, {}).values(),
                            key=lambda b: b.slot,
                        )
                    ],
                    "signed_attestations": [
                        {
                            "source_epoch": str(a.source_epoch),
                            "target_epoch": str(a.target_epoch),
                            **(
                                {"signing_root": "0x" + a.signing_root.hex()}
                                if a.signing_root
                                else {}
                            ),
                        }
                        for a in sorted(
                            self._atts.get(pk, []),
                            key=lambda a: a.target_epoch,
                        )
                    ],
                }
            )
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x"
                + self.genesis_validators_root.hex(),
            },
            "data": data,
        }

    def import_interchange(self, obj: dict | str) -> int:
        if isinstance(obj, str):
            obj = json.loads(obj)
        meta = obj.get("metadata", {})
        if meta.get("interchange_format_version") not in ("4", "5"):
            raise InterchangeError("unsupported interchange version")
        gvr = meta.get("genesis_validators_root", "")
        if (
            gvr
            and bytes.fromhex(gvr[2:]) != self.genesis_validators_root
            and self.genesis_validators_root != b"\x00" * 32
        ):
            raise InterchangeError("genesis_validators_root mismatch")
        n = 0
        for entry in obj.get("data", []):
            pk = bytes.fromhex(entry["pubkey"][2:])
            for b in entry.get("signed_blocks", []):
                rec = SignedBlockRecord(
                    int(b["slot"]),
                    bytes.fromhex(b["signing_root"][2:])
                    if "signing_root" in b
                    else None,
                )
                self._blocks.setdefault(pk, {})[rec.slot] = rec
                n += 1
            for a in entry.get("signed_attestations", []):
                self._atts.setdefault(pk, []).append(
                    SignedAttestationRecord(
                        int(a["source_epoch"]),
                        int(a["target_epoch"]),
                        bytes.fromhex(a["signing_root"][2:])
                        if "signing_root" in a
                        else None,
                    )
                )
                n += 1
        return n

"""ctypes binding for the native batched SHA-256 merkleizer.

Reference analog: @chainsafe/as-sha256's batch hash entry points
(SURVEY.md §2.1). Compiles csrc/sha256_merkle.c once per machine into
a cached shared object (no pip deps; cc toolchain is baked in) and
exposes:

  - hash64_batch(data: bytes[64*n]) -> bytes[32*n]
  - merkleize(chunks: bytes, count, limit) -> 32-byte root

Falls back silently (AVAILABLE=False) when no compiler is present;
ssz.core keeps its hashlib path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from hashlib import sha256
from pathlib import Path

_SRC = Path(__file__).resolve().parents[2] / "csrc" / "sha256_merkle.c"
_LIB_DIR = Path(
    os.environ.get(
        "LODESTAR_TPU_NATIVE_DIR",
        Path.home() / ".cache" / "lodestar_tpu" / "native",
    )
)

_lib = None
AVAILABLE = False

# zero_hashes[i] = root of a zero subtree of depth i. 65 entries: SSZ
# list limits reach depth 40+ (VALIDATOR_REGISTRY_LIMIT = 2^40), match
# ssz.core's 64-deep table.
_ZERO = [b"\x00" * 32]
for _ in range(64):
    _ZERO.append(sha256(_ZERO[-1] + _ZERO[-1]).digest())
_ZERO_BUF = b"".join(_ZERO)


def _build() -> Path | None:
    try:
        _LIB_DIR.mkdir(parents=True, exist_ok=True)
        src_mtime = int(_SRC.stat().st_mtime)
        lib_path = _LIB_DIR / f"sha256_merkle_{src_mtime}.so"
        if lib_path.exists():
            return lib_path
        with tempfile.TemporaryDirectory() as td:
            tmp = Path(td) / "lib.so"
            subprocess.run(
                [
                    os.environ.get("CC", "cc"),
                    "-O3",
                    "-shared",
                    "-fPIC",
                    str(_SRC),
                    "-o",
                    str(tmp),
                ],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, lib_path)
        return lib_path
    except Exception:
        return None


def _load():
    global _lib, AVAILABLE
    if _lib is not None or AVAILABLE:
        return _lib
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
        lib.hash64_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.hash_small_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.merkle_root.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.c_size_t,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
        ]
        _lib = lib
        AVAILABLE = True
        return lib
    except Exception:
        return None


def hash64_batch(data: bytes) -> bytes:
    """Hash n concatenated 64-byte inputs -> n concatenated digests."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native sha256 hasher unavailable (no compiler?)")
    n = len(data) // 64
    out = ctypes.create_string_buffer(32 * n)
    lib.hash64_batch(data, out, n)
    return out.raw


def hash_small_batch(data: bytes, msg_len: int) -> bytes:
    """Hash n concatenated fixed-length (<= 55 byte) messages -> n
    concatenated 32-byte digests. One padded SHA-256 block per message
    (the swap-or-not decision-hash shape: 37 bytes)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native sha256 hasher unavailable (no compiler?)")
    if msg_len > 55:
        raise ValueError("msg_len > 55 needs multi-block hashing")
    n = len(data) // msg_len
    out = ctypes.create_string_buffer(32 * n)
    lib.hash_small_batch(data, msg_len, out, n)
    return out.raw


def merkleize_packed(chunks: bytes, count: int, depth: int) -> bytes:
    """Merkle root of `count` 32-byte chunks padded with zero subtrees
    to depth `depth` (depth <= 64)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native sha256 hasher unavailable (no compiler?)")
    if depth > 64:
        raise ValueError("depth > 64")
    scratch = ctypes.create_string_buffer(32 * (count + 1))
    out = ctypes.create_string_buffer(32)
    lib.merkle_root(chunks, count, depth, _ZERO_BUF, scratch, out)
    return out.raw


def available() -> bool:
    _load()
    return AVAILABLE

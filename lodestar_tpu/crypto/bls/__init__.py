"""Pure-Python BLS12-381 oracle: fields, curves, pairing, hash-to-curve,
signatures (Ethereum ciphersuite).

Validated against: the reference's interop deposit KAT
(beacon-node/test/e2e/interop/genesisState.test.ts — byte-exact signature
match with @chainsafe/blst), RFC 9380 expand_message_xmd vectors, known
generator encodings, and algebraic pairing laws. Serves as the correctness
oracle for the TPU kernels in lodestar_tpu/ops.
"""

from . import curve, fields, hash_to_curve, pairing, signature
from .signature import (
    BlsError,
    aggregate_pubkeys,
    aggregate_signatures,
    aggregate_verify,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    keygen,
    sign,
    sk_from_bytes,
    sk_to_bytes,
    sk_to_pk,
    verify,
    verify_multiple_aggregate_signatures,
)

__all__ = [
    "curve", "fields", "hash_to_curve", "pairing", "signature",
    "BlsError", "aggregate_pubkeys", "aggregate_signatures",
    "aggregate_verify", "eth_fast_aggregate_verify", "fast_aggregate_verify",
    "keygen", "sign", "sk_from_bytes", "sk_to_bytes", "sk_to_pk", "verify",
    "verify_multiple_aggregate_signatures",
]

"""BLS12-381 field tower: Fq, Fq2, Fq6, Fq12 (pure-Python oracle).

Reference analog: the blst C library's field arithmetic (@chainsafe/blst —
SURVEY.md §2.1). This oracle anchors correctness for the TPU kernels in
lodestar_tpu/ops/.

Representation (performance-minded plain data, no classes):
  Fq   = int in [0, P)
  Fq2  = (c0, c1)            # c0 + c1*u,  u^2 = -1
  Fq6  = (a0, a1, a2)        # over Fq2,   v^3 = XI = 1 + u
  Fq12 = (b0, b1)            # over Fq6,   w^2 = v
"""

from __future__ import annotations

# field modulus
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
# subgroup order
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
# BLS parameter x (negative)
X = -0xD201000000010000

FQ2_ONE = (1, 0)
FQ2_ZERO = (0, 0)
XI = (1, 1)  # 1 + u, the Fq6 non-residue

# ---------------------------------------------------------------------------
# Fq
# ---------------------------------------------------------------------------


def fq_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("Fq inverse of 0")
    return pow(a, P - 2, P)


def fq_sqrt(a: int) -> int | None:
    """sqrt in Fq (P ≡ 3 mod 4); None if non-square."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a else None


# ---------------------------------------------------------------------------
# Fq2 = Fq[u]/(u^2+1)
# ---------------------------------------------------------------------------


def fq2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a):
    return (-a[0] % P, -a[1] % P)


def fq2_conj(a):
    return (a[0], -a[1] % P)


def fq2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # Karatsuba: (a0+a1)(b0+b1) - t0 - t1
    t2 = (a0 + a1) * (b0 + b1) - t0 - t1
    return ((t0 - t1) % P, t2 % P)


def fq2_sqr(a):
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fq2_mul_fq(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fq2_inv(a):
    a0, a1 = a
    d = fq_inv((a0 * a0 + a1 * a1) % P)
    return (a0 * d % P, -a1 * d % P)


def fq2_pow(a, e: int):
    if e < 0:
        return fq2_pow(fq2_inv(a), -e)
    result = FQ2_ONE
    base = a
    while e:
        if e & 1:
            result = fq2_mul(result, base)
        base = fq2_sqr(base)
        e >>= 1
    return result


def fq2_sgn0(a) -> int:
    """RFC 9380 sgn0 for m=2 (lexicographic)."""
    s0 = a[0] & 1
    z0 = a[0] == 0
    s1 = a[1] & 1
    return s0 | (z0 & s1)


def fq2_sqrt(a):
    """sqrt in Fq2; None if non-square (algorithm for p ≡ 3 mod 4)."""
    if a == FQ2_ZERO:
        return FQ2_ZERO
    c1 = (P - 3) // 4
    a1 = fq2_pow(a, c1)
    alpha = fq2_mul(fq2_sqr(a1), a)
    x0 = fq2_mul(a1, a)
    if alpha == (P - 1, 0):  # alpha == -1
        cand = (-x0[1] % P, x0[0])  # u * x0
    else:
        b = fq2_pow(fq2_add(FQ2_ONE, alpha), (P - 1) // 2)
        cand = fq2_mul(b, x0)
    return cand if fq2_sqr(cand) == a else None


# ---------------------------------------------------------------------------
# Fq6 = Fq2[v]/(v^3 - XI)
# ---------------------------------------------------------------------------


def _mul_by_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1)u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a, b):
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a, b):
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a):
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    c0 = fq2_add(
        t0,
        _mul_by_xi(
            fq2_sub(
                fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2
            )
        ),
    )
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    c1 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
        _mul_by_xi(t2),
    )
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fq2_add(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fq6_sqr(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    # v * (a0 + a1 v + a2 v^2) = xi*a2 + a0 v + a1 v^2
    return (_mul_by_xi(a[2]), a[0], a[1])


def fq6_inv(a):
    a0, a1, a2 = a
    c0 = fq2_sub(fq2_sqr(a0), _mul_by_xi(fq2_mul(a1, a2)))
    c1 = fq2_sub(_mul_by_xi(fq2_sqr(a2)), fq2_mul(a0, a1))
    c2 = fq2_sub(fq2_sqr(a1), fq2_mul(a0, a2))
    t = fq2_add(
        fq2_add(fq2_mul(a0, c0), _mul_by_xi(fq2_mul(a2, c1))),
        _mul_by_xi(fq2_mul(a1, c2)),
    )
    ti = fq2_inv(t)
    return (fq2_mul(c0, ti), fq2_mul(c1, ti), fq2_mul(c2, ti))


# ---------------------------------------------------------------------------
# Fq12 = Fq6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

FQ12_ONE = (FQ6_ONE, FQ6_ZERO)
FQ12_ZERO = (FQ6_ZERO, FQ6_ZERO)


def fq12_add(a, b):
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a, b):
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_neg(a):
    return (fq6_neg(a[0]), fq6_neg(a[1]))


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sqr(a):
    return fq12_mul(a, a)


def fq12_inv(a):
    a0, a1 = a
    t = fq6_inv(fq6_sub(fq6_sqr(a0), fq6_mul_by_v(fq6_sqr(a1))))
    return (fq6_mul(a0, t), fq6_neg(fq6_mul(a1, t)))


def fq12_conj(a):
    """Conjugation a0 - a1 w (the q^6 Frobenius); inverse on the cyclotomic
    subgroup."""
    return (a[0], fq6_neg(a[1]))


def fq12_pow(a, e: int):
    if e < 0:
        return fq12_pow(fq12_inv(a), -e)
    result = FQ12_ONE
    base = a
    while e:
        if e & 1:
            result = fq12_mul(result, base)
        base = fq12_sqr(base)
        e >>= 1
    return result


# ---------------------------------------------------------------------------
# Frobenius: x -> x^p, computed via coefficient conjugation + constants.
# Constants derived at import time (no hardcoded tables to mis-remember).
# ---------------------------------------------------------------------------

# gamma_1[i] = XI^(i*(p-1)/6) in Fq2, i = 0..5
_G1 = [fq2_pow(XI, i * (P - 1) // 6) for i in range(6)]


def fq6_frobenius(a):
    # (a0 + a1 v + a2 v^2)^p = a0~ + a1~ g2 v + a2~ g4 v^2
    return (
        fq2_conj(a[0]),
        fq2_mul(fq2_conj(a[1]), _G1[2]),
        fq2_mul(fq2_conj(a[2]), _G1[4]),
    )


def fq12_frobenius(a):
    a0, a1 = a
    f0 = fq6_frobenius(a0)
    # (a1 w)^p = a1^p * w^(p-1) * w, and w^(p-1) = XI^((p-1)/6) in Fq2,
    # so the whole w-part is scaled by gamma_1[1] (fq6_frobenius already
    # applied the per-coefficient v^j gammas).
    f1 = fq6_frobenius(a1)
    f1 = (
        fq2_mul(f1[0], _G1[1]),
        fq2_mul(f1[1], _G1[1]),
        fq2_mul(f1[2], _G1[1]),
    )
    return (f0, f1)


def fq12_frobenius_n(a, n: int):
    for _ in range(n % 12):
        a = fq12_frobenius(a)
    return a


# Cyclotomic squaring (Granger–Scott) is a future optimization for the
# final-exponentiation hard part; the oracle favors obviously-correct code.
fq12_cyclotomic_sqr = fq12_sqr

"""BLS signatures over BLS12-381 (minimal-pubkey-size, Ethereum ciphersuite
BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_).

Reference analog: @chainsafe/blst's SecretKey/PublicKey/Signature API surface
(used at chain/bls/maybeBatch.ts:1, chain/bls/multithread/jobItem.ts:1) and
IETF draft-irtf-cfrg-bls-signature. This is the host-side oracle; batched
verification on TPU lives in lodestar_tpu/ops with identical semantics.
"""

from __future__ import annotations

import os

from ...params import BLS_DST_SIG
from . import curve as C
from . import pairing as PR
from .fields import R
from .hash_to_curve import hash_to_g2


class BlsError(ValueError):
    pass


def sk_from_bytes(data: bytes) -> int:
    """32-byte big-endian scalar; must be in [1, r)."""
    if len(data) != 32:
        raise BlsError("secret key must be 32 bytes")
    sk = int.from_bytes(data, "big")
    if not 0 < sk < R:
        raise BlsError("secret key out of range")
    return sk


def sk_to_bytes(sk: int) -> bytes:
    return sk.to_bytes(32, "big")


def keygen(ikm: bytes | None = None) -> int:
    """Random secret key in [1, r). Deterministic derivation from IKM
    (EIP-2333 HKDF) lives in the keystore layer; passing ikm here is an
    error rather than a silent ignore. A 48-byte draw mod r keeps the
    distribution uniform to ~2^-125."""
    if ikm is not None:
        raise BlsError("deterministic keygen not supported here; use the keystore layer")
    while True:
        candidate = int.from_bytes(os.urandom(48), "big") % R
        if candidate:
            return candidate


def sk_to_pk(sk: int) -> bytes:
    return C.g1_to_bytes(C.g1_mul(C.G1_GEN, sk))


def sign(sk: int, msg: bytes, dst: bytes = BLS_DST_SIG) -> bytes:
    h = hash_to_g2(msg, dst)
    return C.g2_to_bytes(C.g2_mul(h, sk))


def _pk_point(pk: bytes):
    pt = C.g1_from_bytes(pk)
    if pt is None:
        raise BlsError("public key is the identity")
    return pt


def verify(pk: bytes, msg: bytes, sig: bytes, dst: bytes = BLS_DST_SIG) -> bool:
    """Core verify. Malformed inputs return False (blst-compatible at the
    IBlsVerifier seam — chain/bls/maybeBatch.ts:17-44 catches and rejects)."""
    try:
        pk_pt = _pk_point(pk)
        sig_pt = C.g2_from_bytes(sig)
    except (BlsError, ValueError):
        return False
    if sig_pt is None:
        return False
    h = hash_to_g2(msg, dst)
    # e(pk, H(m)) == e(g1, sig)  <=>  e(-g1, sig) * e(pk, H(m)) == 1
    return PR.pairing_product_is_one(
        [(C.g1_neg(C.G1_GEN), sig_pt), (pk_pt, h)]
    )


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    if not sigs:
        raise BlsError("cannot aggregate empty signature list")
    acc = None
    for s in sigs:
        pt = C.g2_from_bytes(s)
        acc = C.g2_add(acc, pt)
    return C.g2_to_bytes(acc)


def aggregate_pubkeys(pks: list[bytes]) -> bytes:
    if not pks:
        raise BlsError("cannot aggregate empty pubkey list")
    acc = None
    for pk in pks:
        acc = C.g1_add(acc, _pk_point(pk))
    return C.g1_to_bytes(acc)


def fast_aggregate_verify(
    pks: list[bytes], msg: bytes, sig: bytes, dst: bytes = BLS_DST_SIG
) -> bool:
    """All signers signed the same message (aggregate pubkeys first)."""
    if not pks:
        return False
    try:
        agg = aggregate_pubkeys(pks)
    except (BlsError, ValueError):
        return False
    return verify(agg, msg, sig, dst)


def aggregate_verify(
    pks: list[bytes], msgs: list[bytes], sig: bytes, dst: bytes = BLS_DST_SIG
) -> bool:
    """Distinct messages: prod e(pk_i, H(m_i)) == e(g1, sig)."""
    if not pks or len(pks) != len(msgs):
        return False
    try:
        sig_pt = C.g2_from_bytes(sig)
        if sig_pt is None:
            return False
        pairs = [(C.g1_neg(C.G1_GEN), sig_pt)]
        for pk, msg in zip(pks, msgs):
            pairs.append((_pk_point(pk), hash_to_g2(msg, dst)))
    except (BlsError, ValueError):
        return False
    return PR.pairing_product_is_one(pairs)


def verify_multiple_aggregate_signatures(
    sets: list[tuple[bytes, bytes, bytes]], dst: bytes = BLS_DST_SIG
) -> bool:
    """Batch verify [(pk, msg, sig)] with a random linear combination —
    blst verifyMultipleAggregateSignatures semantics (the reference's
    batchable path, chain/bls/maybeBatch.ts:29-38).

    prod_i e(r_i * pk_i, H(m_i)) * e(-g1, sum_i r_i * sig_i) == 1
    """
    if not sets:
        return True
    try:
        pairs = []
        sig_acc = None
        for pk, msg, sig in sets:
            r = int.from_bytes(os.urandom(8), "big") | 1  # nonzero 64-bit
            pk_pt = _pk_point(pk)
            sig_pt = C.g2_from_bytes(sig)
            if sig_pt is None:
                return False
            pairs.append((C.g1_mul(pk_pt, r), hash_to_g2(msg, dst)))
            sig_acc = C.g2_add(sig_acc, C.g2_mul(sig_pt, r))
        pairs.append((C.g1_neg(C.G1_GEN), sig_acc))
    except (BlsError, ValueError):
        return False
    return PR.pairing_product_is_one(pairs)


def eth_fast_aggregate_verify(
    pks: list[bytes], msg: bytes, sig: bytes, dst: bytes = BLS_DST_SIG
) -> bool:
    """Spec eth_fast_aggregate_verify: empty pubkeys + infinity sig -> True
    (sync committee edge case)."""
    G2_INFINITY = b"\xc0" + b"\x00" * 95
    if not pks and sig == G2_INFINITY:
        return True
    return fast_aggregate_verify(pks, msg, sig, dst)

"""Hash-to-curve for G2: BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fq2, m=2, L=64) ->
simplified SWU on the 3-isogenous curve E2' -> 3-isogeny map to E2 ->
cofactor clearing (Budroni–Pintore via the psi endomorphism, curve.py).

Constants validated structurally in tests (isogeny output must satisfy the
E2 curve equation; SSWU output the E2' equation) and end-to-end by the
interop DepositData signature KAT from the reference repo
(beacon-node/test/e2e/interop/genesisState.test.ts).
"""

from __future__ import annotations

from hashlib import sha256

from . import fields as F
from .fields import P
from .curve import g2_clear_cofactor, g2_add

# SSWU curve E2': y^2 = x^3 + A'x + B'
A_PRIME = (0, 240)  # 240 * u
B_PRIME = (1012, 1012)  # 1012 * (1 + u)
Z_SSWU = (-2 % P, -1 % P)  # -(2 + u)

L_FIELD = 64  # bytes per field element draw (ceil((381 + 128)/8))


# ---------------------------------------------------------------------------
# expand_message_xmd (RFC 9380 §5.3.1) with SHA-256
# ---------------------------------------------------------------------------


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    b_in_bytes = 32  # SHA-256 output
    r_in_bytes = 64  # SHA-256 block size
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter overflow")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * r_in_bytes
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = sha256(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        blocks.append(sha256(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int) -> list:
    """hash_to_field with m=2, L=64 (RFC 9380 §5.2)."""
    len_in_bytes = count * 2 * L_FIELD
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            offset = L_FIELD * (j + i * 2)
            tv = uniform[offset : offset + L_FIELD]
            coords.append(int.from_bytes(tv, "big") % P)
        out.append((coords[0], coords[1]))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU map on E2' (RFC 9380 §6.6.2, straightforward variant)
# ---------------------------------------------------------------------------


def map_to_curve_sswu(u):
    """u in Fq2 -> point on E2' (affine)."""
    # tv1 = 1 / (Z^2 u^4 + Z u^2), with the tv1 == 0 exception
    u2 = F.fq2_sqr(u)
    z_u2 = F.fq2_mul(Z_SSWU, u2)
    tv = F.fq2_add(F.fq2_sqr(z_u2), z_u2)
    if tv == F.FQ2_ZERO:
        # exceptional case: x1 = B / (Z * A)
        x1 = F.fq2_mul(B_PRIME, F.fq2_inv(F.fq2_mul(Z_SSWU, A_PRIME)))
    else:
        tv1 = F.fq2_inv(tv)
        # x1 = (-B/A) * (1 + tv1)
        x1 = F.fq2_mul(
            F.fq2_mul(F.fq2_neg(B_PRIME), F.fq2_inv(A_PRIME)),
            F.fq2_add(F.FQ2_ONE, tv1),
        )
    def g(x):
        return F.fq2_add(F.fq2_mul(F.fq2_add(F.fq2_sqr(x), A_PRIME), x), B_PRIME)

    gx1 = g(x1)
    y1 = F.fq2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = F.fq2_mul(z_u2, x1)
        gx2 = g(x2)
        y2 = F.fq2_sqrt(gx2)
        if y2 is None:
            raise AssertionError("SSWU: neither gx1 nor gx2 square (impossible)")
        x, y = x2, y2
    if F.fq2_sgn0(u) != F.fq2_sgn0(y):
        y = F.fq2_neg(y)
    return (x, y)


# ---------------------------------------------------------------------------
# 3-isogeny E2' -> E2 (RFC 9380 Appendix E.3)
# ---------------------------------------------------------------------------

_K1 = [
    (
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    (
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    (
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
]
_K2 = [
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    (
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    (1, 0),  # monic x^2 term
]
_K3 = [
    (
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    (
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    (
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    (
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
]
_K4 = [
    (
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    (
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    (
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    (1, 0),  # monic x^3 term
]


def _horner(coeffs, x):
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = F.fq2_add(F.fq2_mul(acc, x), c)
    return acc


def iso_map_g2(pt):
    """Apply the 3-isogeny E2' -> E2."""
    x, y = pt
    x_num = _horner(_K1, x)
    x_den = _horner(_K2, x)
    y_num = _horner(_K3, x)
    y_den = _horner(_K4, x)
    xo = F.fq2_mul(x_num, F.fq2_inv(x_den))
    yo = F.fq2_mul(y, F.fq2_mul(y_num, F.fq2_inv(y_den)))
    return (xo, yo)


# ---------------------------------------------------------------------------
# hash_to_curve
# ---------------------------------------------------------------------------


def hash_to_g2(msg: bytes, dst: bytes):
    """Full hash_to_curve: returns a point in G2 (r-torsion). Native
    backend when available; `hash_to_g2_py` is the pure oracle."""
    from . import native

    if native.available():
        return native.hash_to_g2(msg, dst)
    return hash_to_g2_py(msg, dst)


def hash_to_g2_py(msg: bytes, dst: bytes):
    from .curve import _Fq2Ops, _add

    u0, u1 = hash_to_field_fq2(msg, dst, 2)
    q0 = iso_map_g2(map_to_curve_sswu(u0))
    q1 = iso_map_g2(map_to_curve_sswu(u1))
    # pure-python add (not the native-dispatching g2_add): this function
    # is the independent oracle for the native backend's tests
    return g2_clear_cofactor(_add(_Fq2Ops, q0, q1))

"""ctypes bindings for the native BLS12-381 backend (csrc/bls381.c).

Reference analog: the node-gyp binding layer of @chainsafe/blst —
prebuilt native crypto behind a narrow byte-oriented API. Points cross
the boundary as affine big-endian bytes (G1 96B, G2 192B, all-zero =
infinity); ints<->bytes conversion helpers keep the pure-Python oracle
(fields/curve/pairing modules) interchangeable for differential tests.

Set LODESTAR_TPU_NO_NATIVE=1 to force the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path

_SRC = Path(__file__).resolve().parents[3] / "csrc" / "bls381.c"
_HDR = Path(__file__).resolve().parents[3] / "csrc" / "bls381_constants.h"
_LIB_DIR = Path(
    os.environ.get(
        "LODESTAR_TPU_NATIVE_DIR",
        Path.home() / ".cache" / "lodestar_tpu" / "native",
    )
)

_lib = None
_load_failed = False


def available() -> bool:
    if os.environ.get("LODESTAR_TPU_NO_NATIVE") == "1":
        return False
    return _load() is not None


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        _LIB_DIR.mkdir(parents=True, exist_ok=True)
        mtime = int(_SRC.stat().st_mtime) ^ int(_HDR.stat().st_mtime)
        path = _LIB_DIR / f"bls381_{mtime}.so"
        if not path.exists():
            with tempfile.TemporaryDirectory() as td:
                tmp = Path(td) / "lib.so"
                subprocess.run(
                    [
                        os.environ.get("CC", "cc"),
                        "-O2",
                        "-shared",
                        "-fPIC",
                        str(_SRC),
                        "-o",
                        str(tmp),
                    ],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, path)
        lib = ctypes.CDLL(str(path))
        for name, res in (
            ("blsn_g1_decompress", ctypes.c_int),
            ("blsn_g2_decompress", ctypes.c_int),
            ("blsn_g1_subgroup_check", ctypes.c_int),
            ("blsn_g2_subgroup_check", ctypes.c_int),
            ("blsn_pairing_product_is_one", ctypes.c_int),
            ("blsn_miller_loop", ctypes.c_int),
            ("blsn_g1_msm", ctypes.c_int),
        ):
            getattr(lib, name).restype = res
        _lib = lib
    except Exception:
        _load_failed = True
        _lib = None
    return _lib


# --- int-tuple <-> byte codecs (oracle interop) -------------------------


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 96
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def g1_from_bytes_affine(b: bytes):
    if b == b"\x00" * 96:
        return None
    return (
        int.from_bytes(b[:48], "big"),
        int.from_bytes(b[48:], "big"),
    )


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = pt
    return (
        x1.to_bytes(48, "big")
        + x0.to_bytes(48, "big")
        + y1.to_bytes(48, "big")
        + y0.to_bytes(48, "big")
    )


def g2_from_bytes_affine(b: bytes):
    if b == b"\x00" * 192:
        return None
    x1, x0, y1, y0 = (
        int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)
    )
    return ((x0, x1), (y0, y1))


# --- API ---------------------------------------------------------------


class NativeError(ValueError):
    pass


def g1_decompress(compressed: bytes):
    """48B -> affine ints with on-curve + subgroup checks; None for the
    (valid-encoding) identity; raises NativeError for bad points."""
    if len(compressed) != 48:
        raise NativeError("G1 compressed point must be 48 bytes")
    lib = _load()
    out = ctypes.create_string_buffer(96)
    rc = lib.blsn_g1_decompress(compressed, out)
    if rc == 2:
        return None
    if rc != 1:
        raise NativeError("invalid G1 point")
    return g1_from_bytes_affine(out.raw)


def g2_decompress(compressed: bytes):
    if len(compressed) != 96:
        raise NativeError("G2 compressed point must be 96 bytes")
    lib = _load()
    out = ctypes.create_string_buffer(192)
    rc = lib.blsn_g2_decompress(compressed, out)
    if rc == 2:
        return None
    if rc != 1:
        raise NativeError("invalid G2 point")
    return g2_from_bytes_affine(out.raw)


def hash_to_g2(message: bytes, dst: bytes):
    lib = _load()
    out = ctypes.create_string_buffer(192)
    lib.blsn_hash_to_g2(message, len(message), dst, len(dst), out)
    return g2_from_bytes_affine(out.raw)


def pairing_product_is_one(pairs) -> bool:
    """pairs: [(g1_pt, g2_pt)] as oracle int tuples."""
    lib = _load()
    g1s = b"".join(g1_to_bytes(p) for p, _ in pairs)
    g2s = b"".join(g2_to_bytes(q) for _, q in pairs)
    rc = lib.blsn_pairing_product_is_one(g1s, g2s, len(pairs))
    if rc < 0:
        raise NativeError("invalid pairing input")
    return rc == 1


def g1_msm(pts, scalars) -> "tuple | None":
    """Pippenger multi-scalar multiplication: sum_i scalars[i]*pts[i].
    pts: list of oracle int tuples (None = infinity); scalars: ints."""
    lib = _load()
    n = len(pts)
    buf = b"".join(g1_to_bytes(p) for p in pts)
    sc = b"".join((int(k) % R_ORDER).to_bytes(32, "big") for k in scalars)
    out = ctypes.create_string_buffer(96)
    if lib.blsn_g1_msm(buf, sc, n, out) != 1:
        raise NativeError("invalid G1 point in MSM")
    return g1_from_bytes_affine(out.raw)


R_ORDER = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001


def g1_mul(pt, k: int):
    lib = _load()
    out = ctypes.create_string_buffer(96)
    lib.blsn_g1_mul(
        g1_to_bytes(pt), (k % (1 << 256)).to_bytes(32, "big"), out
    )
    return g1_from_bytes_affine(out.raw)


def g2_mul(pt, k: int):
    lib = _load()
    out = ctypes.create_string_buffer(192)
    lib.blsn_g2_mul(
        g2_to_bytes(pt), (k % (1 << 256)).to_bytes(32, "big"), out
    )
    return g2_from_bytes_affine(out.raw)


def g1_add(a, b):
    lib = _load()
    out = ctypes.create_string_buffer(96)
    if lib.blsn_g1_add(g1_to_bytes(a), g1_to_bytes(b), out) != 1:
        raise NativeError("invalid G1 point in add")
    return g1_from_bytes_affine(out.raw)


def g2_add(a, b):
    lib = _load()
    out = ctypes.create_string_buffer(192)
    if lib.blsn_g2_add(g2_to_bytes(a), g2_to_bytes(b), out) != 1:
        raise NativeError("invalid G2 point in add")
    return g2_from_bytes_affine(out.raw)


def g1_compress(pt) -> bytes:
    lib = _load()
    out = ctypes.create_string_buffer(48)
    lib.blsn_g1_compress(g1_to_bytes(pt), out)
    return out.raw


def g1_subgroup_check(pt) -> bool:
    """On-curve + r-subgroup membership (native does both)."""
    if pt is None:
        return True
    return bool(_load().blsn_g1_subgroup_check(g1_to_bytes(pt)))


def g2_subgroup_check(pt) -> bool:
    if pt is None:
        return True
    return bool(_load().blsn_g2_subgroup_check(g2_to_bytes(pt)))

"""BLS12-381 optimal ate pairing (pure-Python oracle).

Strategy: correctness-first. G2 points are untwisted into E(Fq12) and the
Miller loop uses generic affine line functions over Fq12 (slope via field
division), so the code mirrors the textbook definition. The TPU kernels in
lodestar_tpu/ops use the fast projective formulas and are differential-
tested against this oracle.

Untwist for the M-twist E': y^2 = x^3 + 4*XI with Fq12 = Fq6[w]/(w^2 - v),
Fq6 = Fq2[v]/(v^3 - XI):  (x', y') -> (x'/w^2, y'/w^3), which lands on
E: y^2 = x^3 + 4 over Fq12.
"""

from __future__ import annotations

from . import fields as F
from .fields import P, R, X, FQ12_ONE

# w^2 = v  as an Fq12 element: (0 + 1*v + 0*v^2, 0)
_W2 = ((F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO), F.FQ6_ZERO)
# w^3 = v*w: (0, 0 + 1*v + 0*v^2)
_W3 = (F.FQ6_ZERO, (F.FQ2_ZERO, F.FQ2_ONE, F.FQ2_ZERO))
_W2_INV = F.fq12_inv(_W2)
_W3_INV = F.fq12_inv(_W3)


def _fq_to_fq12(a: int):
    return (((a, 0), F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


def _fq2_to_fq12(a):
    return ((a, F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


def untwist(q):
    """Map a point on the twist E'(Fq2) to E(Fq12)."""
    if q is None:
        return None
    x, y = q
    return (
        F.fq12_mul(_fq2_to_fq12(x), _W2_INV),
        F.fq12_mul(_fq2_to_fq12(y), _W3_INV),
    )


def embed_g1(p):
    if p is None:
        return None
    return (_fq_to_fq12(p[0]), _fq_to_fq12(p[1]))


def _line(p1, p2, t):
    """Evaluate the line through p1,p2 (E(Fq12) affine) at point t."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = F.fq12_mul(F.fq12_sub(y2, y1), F.fq12_inv(F.fq12_sub(x2, x1)))
    elif y1 == y2:
        three_x1_sq = F.fq12_mul(_fq_to_fq12(3), F.fq12_sqr(x1))
        m = F.fq12_mul(three_x1_sq, F.fq12_inv(F.fq12_mul(_fq_to_fq12(2), y1)))
    else:
        # vertical line
        return F.fq12_sub(xt, x1)
    return F.fq12_sub(
        F.fq12_mul(m, F.fq12_sub(xt, x1)), F.fq12_sub(yt, y1)
    )


def _add_fq12(p1, p2):
    """Affine addition on E(Fq12)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 != y2:
            return None
        three_x1_sq = F.fq12_mul(_fq_to_fq12(3), F.fq12_sqr(x1))
        m = F.fq12_mul(three_x1_sq, F.fq12_inv(F.fq12_mul(_fq_to_fq12(2), y1)))
    else:
        m = F.fq12_mul(F.fq12_sub(y2, y1), F.fq12_inv(F.fq12_sub(x2, x1)))
    x3 = F.fq12_sub(F.fq12_sub(F.fq12_sqr(m), x1), x2)
    y3 = F.fq12_sub(F.fq12_mul(m, F.fq12_sub(x1, x3)), y1)
    return (x3, y3)


def miller_loop(p, q):
    """f_{|X|,Q}(P) with the BLS12 sign fix (X < 0 -> invert)."""
    if p is None or q is None:
        return FQ12_ONE
    pe = embed_g1(p)
    qe = untwist(q)
    f = FQ12_ONE
    r_pt = qe
    n = -X  # |x|, positive
    for bit in bin(n)[3:]:  # MSB already consumed (r_pt = qe)
        f = F.fq12_mul(F.fq12_sqr(f), _line(r_pt, r_pt, pe))
        r_pt = _add_fq12(r_pt, r_pt)
        if bit == "1":
            f = F.fq12_mul(f, _line(r_pt, qe, pe))
            r_pt = _add_fq12(r_pt, qe)
    # X < 0: f_{-n} = 1/f_n (up to vertical lines killed by final exp)
    return F.fq12_inv(f)


def final_exponentiation(f):
    """f^((p^12-1)/r): easy part via Frobenius/conjugation, hard part as a
    plain square-and-multiply (oracle simplicity; the exponent is public)."""
    # easy: f^(p^6-1) = conj(f) * f^-1 ; then ^(p^2+1)
    t = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    t = F.fq12_mul(F.fq12_frobenius_n(t, 2), t)
    # hard: t^((p^4 - p^2 + 1) // r)
    return F.fq12_pow(t, (P**4 - P**2 + 1) // R)


def pairing(p, q):
    """e(P, Q) for P in G1, Q in G2 (affine tuples)."""
    return final_exponentiation(miller_loop(p, q))


def pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, with one shared final exponentiation.
    Dispatches to the native backend (csrc/bls381.c) when available;
    `pairing_product_is_one_py` is the pure oracle for differential
    tests."""
    from . import native

    if native.available():
        return native.pairing_product_is_one(pairs)
    return pairing_product_is_one_py(pairs)


def pairing_product_is_one_py(pairs) -> bool:
    f = FQ12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        f = F.fq12_mul(f, miller_loop(p, q))
    return final_exponentiation(f) == FQ12_ONE

"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

E1:  y^2 = x^3 + 4
E2:  y^2 = x^3 + 4(1+u)   (M-twist)

Points are affine tuples (x, y) with None as infinity. Scalar muls go
through Jacobian coordinates. Serialization follows the ZCash/blst format
used by Ethereum (compressed, flag bits in the MSBs of the first byte).
"""

from __future__ import annotations

from . import fields as F
from .fields import P, R

# Generators (standard, from the BLS12-381 spec)
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

H1 = 0x396C8C005555E1568C00AAAB0000AAAB  # G1 cofactor

_B1 = 4
_B2 = (4, 4)  # 4(1+u)


class _FqOps:
    add = staticmethod(lambda a, b: (a + b) % P)
    sub = staticmethod(lambda a, b: (a - b) % P)
    neg = staticmethod(lambda a: -a % P)
    mul = staticmethod(lambda a, b: a * b % P)
    sqr = staticmethod(lambda a: a * a % P)
    inv = staticmethod(F.fq_inv)
    mul_int = staticmethod(lambda a, k: a * k % P)
    zero = 0
    one = 1


class _Fq2Ops:
    add = staticmethod(F.fq2_add)
    sub = staticmethod(F.fq2_sub)
    neg = staticmethod(F.fq2_neg)
    mul = staticmethod(F.fq2_mul)
    sqr = staticmethod(F.fq2_sqr)
    inv = staticmethod(F.fq2_inv)
    mul_int = staticmethod(F.fq2_mul_fq)
    zero = F.FQ2_ZERO
    one = F.FQ2_ONE


def _on_curve(ops, pt, b) -> bool:
    if pt is None:
        return True
    x, y = pt
    return ops.sqr(y) == ops.add(ops.mul(ops.sqr(x), x), b)


def _neg(ops, pt):
    if pt is None:
        return None
    return (pt[0], ops.neg(pt[1]))


def _add(ops, p1, p2):
    """Affine addition (oracle simplicity over speed)."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == ops.neg(y2):
            return None
        # doubling
        m = ops.mul(ops.mul_int(ops.sqr(x1), 3), ops.inv(ops.mul_int(y1, 2)))
    else:
        m = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(m), x1), x2)
    y3 = ops.sub(ops.mul(m, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _mul(ops, pt, k: int):
    if k < 0:
        return _mul(ops, _neg(ops, pt), -k)
    result = None
    addend = pt
    while k:
        if k & 1:
            result = _add(ops, result, addend)
        addend = _add(ops, addend, addend)
        k >>= 1
    return result


# -- public G1 ---------------------------------------------------------------


def _native():
    from . import native

    return native if native.available() else None


def g1_add(p1, p2):
    n = _native()
    if n is not None:
        return n.g1_add(p1, p2)
    return _add(_FqOps, p1, p2)


def g1_neg(p):
    return _neg(_FqOps, p)


def g1_mul(p, k: int):
    k = k % R if p is not None and k >= 0 else k
    n = _native()
    if n is not None and k >= 0:
        return n.g1_mul(p, k)
    return _mul(_FqOps, p, k)


def g1_is_on_curve(p) -> bool:
    return _on_curve(_FqOps, p, _B1)


def g1_in_subgroup(p) -> bool:
    n = _native()
    if n is not None and p is not None:
        # the native check validates on-curve itself
        return n.g1_subgroup_check(p)
    return g1_is_on_curve(p) and _mul(_FqOps, p, R) is None


# -- public G2 ---------------------------------------------------------------


def g2_add(p1, p2):
    n = _native()
    if n is not None:
        return n.g2_add(p1, p2)
    return _add(_Fq2Ops, p1, p2)


def g2_neg(p):
    return _neg(_Fq2Ops, p)


def g2_mul(p, k: int):
    k = k % R if p is not None and k >= 0 else k
    n = _native()
    if n is not None and k >= 0:
        return n.g2_mul(p, k)
    return _mul(_Fq2Ops, p, k)


def g2_is_on_curve(p) -> bool:
    return _on_curve(_Fq2Ops, p, _B2)


def g2_in_subgroup(p) -> bool:
    n = _native()
    if n is not None and p is not None:
        return n.g2_subgroup_check(p)
    return g2_is_on_curve(p) and _mul(_Fq2Ops, p, R) is None


# ---------------------------------------------------------------------------
# ψ endomorphism on E2 (untwist-Frobenius-twist) — used for fast cofactor
# clearing (Budroni–Pintore) in hash-to-curve.
# Constants derived at import: psi_x = 1/XI^((p-1)/3), psi_y = 1/XI^((p-1)/2)
# ---------------------------------------------------------------------------

_PSI_X = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 3))
_PSI_Y = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 2))


def g2_psi(p):
    if p is None:
        return None
    x, y = p
    return (
        F.fq2_mul(F.fq2_conj(x), _PSI_X),
        F.fq2_mul(F.fq2_conj(y), _PSI_Y),
    )


def g2_clear_cofactor(p):
    """Budroni–Pintore fast cofactor clearing:
    h_eff * P = [x^2 - x - 1]P + [x - 1]ψ(P) + ψ^2([2]P),  x = BLS parameter.
    """
    x = F.X
    t1 = _mul(_Fq2Ops, p, x * x - x - 1)
    t2 = _mul(_Fq2Ops, g2_psi(p), x - 1)
    t3 = g2_psi(g2_psi(_add(_Fq2Ops, p, p)))
    return _add(_Fq2Ops, _add(_Fq2Ops, t1, t2), t3)


# ---------------------------------------------------------------------------
# Serialization (ZCash format, as used by blst / Ethereum)
# ---------------------------------------------------------------------------

_C_FLAG = 0x80  # compressed
_I_FLAG = 0x40  # infinity
_S_FLAG = 0x20  # y is the lexicographically larger root


def g1_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(48)
        out[0] = _C_FLAG | _I_FLAG
        return bytes(out)
    x, y = p
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= _C_FLAG
    if y > (P - 1) // 2:
        out[0] |= _S_FLAG
    return bytes(out)


def g1_from_bytes(data: bytes):
    """Decompress + validate (on-curve and subgroup)."""
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    n = _native()
    if n is not None:
        try:
            return n.g1_decompress(data)
        except n.NativeError as e:
            raise ValueError(str(e)) from e
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("uncompressed G1 not supported")
    if flags & _I_FLAG:
        if any(data[1:]) or flags & _S_FLAG or data[0] != (_C_FLAG | _I_FLAG):
            raise ValueError("invalid infinity encoding")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (x * x * x + _B1) % P
    y = F.fq_sqrt(y2)
    if y is None:
        raise ValueError("G1 x not on curve")
    if bool(flags & _S_FLAG) != (y > (P - 1) // 2):
        y = -y % P
    pt = (x, y)
    if not g1_in_subgroup(pt):
        raise ValueError("G1 point not in subgroup")
    return pt


def g2_to_bytes(p) -> bytes:
    if p is None:
        out = bytearray(96)
        out[0] = _C_FLAG | _I_FLAG
        return bytes(out)
    (x0, x1), (y0, y1) = p
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= _C_FLAG
    # sign from y1 unless zero, else y0 (lexicographic on (y1, y0))
    if y1 > (P - 1) // 2 or (y1 == 0 and y0 > (P - 1) // 2):
        out[0] |= _S_FLAG
    return bytes(out)


def g2_from_bytes(data: bytes):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    n = _native()
    if n is not None:
        try:
            return n.g2_decompress(data)
        except n.NativeError as e:
            raise ValueError(str(e)) from e
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("uncompressed G2 not supported")
    if flags & _I_FLAG:
        if any(data[1:]) or flags & _S_FLAG or data[0] != (_C_FLAG | _I_FLAG):
            raise ValueError("invalid infinity encoding")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), _B2)
    y = F.fq2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x not on curve")
    y0, y1 = y
    big = y1 > (P - 1) // 2 or (y1 == 0 and y0 > (P - 1) // 2)
    if bool(flags & _S_FLAG) != big:
        y = F.fq2_neg(y)
    pt = (x, y)
    if not g2_in_subgroup(pt):
        raise ValueError("G2 point not in subgroup")
    return pt

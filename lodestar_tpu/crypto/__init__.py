"""Host-side cryptography: BLS12-381 oracle, hashing utilities.

Reference analog: the native L0 crypto deps (@chainsafe/blst, c-kzg,
@chainsafe/as-sha256 — SURVEY.md §2.1).
"""

"""KZG polynomial commitments for EIP-4844 blob sidecars.

Reference analog: the `c-kzg` native library loaded at node startup
(beacon-node: node/nodejs.ts:162-165 initCKZG/loadEthereumTrustedSetup)
and used by blob validation (chain/validation/blobSidecar.ts) and block
production (produceBlock/validateBlobsAndKzgCommitments.ts). Fresh
implementation of consensus-specs deneb/polynomial-commitments.md.

The multi-scalar multiplications — the pairing-heavy core of both the
4096-point Lagrange lincombs and the batch-verify random lincombs —
run on a THREE-TIER backend (`set_msm_backend` /
LODESTAR_TPU_KZG_MSM_BACKEND; per-path dispatch counters mirror the
BLS verifier's):

  1. **device** — the TPU bucketed Pippenger (`ops/msm.py`): batched
     limb tensors, one dispatch for a whole blob batch's lincombs. The
     default "auto" mode routes here on a TPU host once the rung's
     compile is warm (the kernels warm registry, kind "msm");
  2. **native** — the host C Pippenger (csrc/bls381.c `blsn_g1_msm`),
     the cold-rung / off-TPU fallback and the differential oracle;
  3. **oracle** — the pure-Python double-and-add lincomb, always
     available, the last-resort tier and the slow reference.

Other group arithmetic stays on native-with-oracle-fallback.
Scalar-field (Fr) arithmetic is plain Python ints with Montgomery
batch inversion — except the batch-verify barycentric evaluations,
which ride a TWO-TIER backend (`set_fr_backend` /
LODESTAR_TPU_KZG_FR_BACKEND): **device** dispatches every blob's
4096-point evaluation + Montgomery batch inversion as ONE limb-kernel
program (`ops/fr.py`, bit-exact vs the ints; z-equals-root blobs are
special-cased on host exactly like the Python path), **python** is
the oracle below; "auto" routes to the device on a TPU host and
falls back (counted) on any device error.

Trusted setup: `load_trusted_setup(path)` reads the standard JSON
format ({"g1_lagrange": [...48B hex...], "g2_monomial": [...]}), so the
ceremony output used in production drops in. For tests/dev,
`dev_trusted_setup()` generates an **INSECURE** setup from a known
secret tau (the whole point of the ceremony is that tau is unknown —
never use the dev setup outside tests), cached on disk after first
generation.
"""

from __future__ import annotations

import json
import os
from concurrent import futures
from hashlib import sha256
from pathlib import Path

from . import bls as _bls  # noqa: F401  (package init side effects)
from .bls import curve as oc
from .bls import native

BLS_MODULUS = (
    0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
)
PRIMITIVE_ROOT_OF_UNITY = 7
BYTES_PER_FIELD_ELEMENT = 32
FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_BLOB = BYTES_PER_FIELD_ELEMENT * FIELD_ELEMENTS_PER_BLOB

FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"

G1_POINT_AT_INFINITY_COMPRESSED = b"\xc0" + b"\x00" * 47


class KzgError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Fr helpers
# ---------------------------------------------------------------------------


def _fr_inv(a: int) -> int:
    return pow(a, BLS_MODULUS - 2, BLS_MODULUS)


def _fr_batch_inv(xs: list[int]) -> list[int]:
    """Montgomery trick: one inversion + 3n multiplications."""
    n = len(xs)
    prefix = [1] * (n + 1)
    for i, x in enumerate(xs):
        if x == 0:
            raise KzgError("division by zero in batch inversion")
        prefix[i + 1] = prefix[i] * x % BLS_MODULUS
    inv = _fr_inv(prefix[n])
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv % BLS_MODULUS
        inv = inv * xs[i] % BLS_MODULUS
    return out


def _bit_reversal_permutation(seq: list) -> list:
    n = len(seq)
    bits = n.bit_length() - 1
    assert 1 << bits == n, "length must be a power of two"
    return [seq[int(format(i, f"0{bits}b")[::-1], 2)] for i in range(n)]


def compute_roots_of_unity(order: int = FIELD_ELEMENTS_PER_BLOB) -> list[int]:
    assert (BLS_MODULUS - 1) % order == 0
    root = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // order, BLS_MODULUS)
    out = [1]
    for _ in range(order - 1):
        out.append(out[-1] * root % BLS_MODULUS)
    return out


_ROOTS_BRP: list[int] | None = None


def _roots_brp() -> list[int]:
    global _ROOTS_BRP
    if _ROOTS_BRP is None:
        _ROOTS_BRP = _bit_reversal_permutation(
            compute_roots_of_unity(FIELD_ELEMENTS_PER_BLOB)
        )
    return _ROOTS_BRP


# ---------------------------------------------------------------------------
# Group helpers (native with oracle fallback); points as oracle tuples
# ---------------------------------------------------------------------------


# oc.* auto-dispatches to the native backend when available


def _g1_decompress(b: bytes):
    """48B compressed -> point (on-curve + subgroup checked inside)."""
    return oc.g1_from_bytes(bytes(b))


def _g1_compress(pt) -> bytes:
    return oc.g1_to_bytes(pt)


_g1_add = oc.g1_add
_g1_mul = oc.g1_mul
_g2_add = oc.g2_add
_g2_mul = oc.g2_mul


# --- three-tier MSM backend (device / native / oracle) ---------------------

MSM_BACKENDS = ("auto", "device", "native", "oracle")

_msm_backend = os.environ.get("LODESTAR_TPU_KZG_MSM_BACKEND", "auto")
if _msm_backend not in MSM_BACKENDS:
    raise ValueError(
        f"LODESTAR_TPU_KZG_MSM_BACKEND={_msm_backend!r} not in "
        f"{MSM_BACKENDS}"
    )

# per-path dispatch counters (the BLS verifier's dispatch_by_path
# discipline): one entry per _g1_lincomb_many call, by the tier that
# served it; device_fallbacks counts auto-mode dispatches that WANTED
# the device but found the rung cold (or the dispatch erroring) and
# fell back to a host tier. Sampled at scrape by
# bind_kzg_collectors (lodestar_kzg_* series).
_MSM_DISPATCH: dict[str, int] = {"device": 0, "native": 0, "oracle": 0}
_MSM_DEVICE_FALLBACKS = 0
_BATCH_HIST = None  # bound lodestar_kzg_batch_verify_blobs histogram

# node-wide device executor (device/executor.py): when wired, device
# MSM/Fr dispatches ride its BULK lane — they queue behind pending
# deadline (gossip verdict) work at every wave boundary, and under
# overload the executor sheds them (bounded bulk queue) so this module
# falls back to its host tier instead of piling onto the chip.
_EXECUTOR = None


def set_executor(executor) -> None:
    """Install (or clear, with None) the node DeviceExecutor this
    module's device dispatches route through as bulk-class jobs."""
    global _EXECUTOR
    _EXECUTOR = executor


def _submit_bulk(fn):
    """Run a device dispatch, through the executor's bulk lane when
    one is wired. Returns (served, result): served=False means the
    executor SHED the job — the caller rides its host fallback tier
    (counted as a device fallback, like any other device miss).
    Dispatch exceptions propagate to the caller's existing handler."""
    ex = _EXECUTOR
    if ex is None:
        return True, fn()
    fut = ex.submit("bulk", fn)
    if fut is None:
        return False, None
    try:
        return True, fut.result()
    except futures.CancelledError:
        # executor closed under us (node shutdown): treat like a
        # shed — the host tier still answers the caller
        return False, None


# device fault domain (device/health.py): while the tracker
# quarantines the device, MSM/Fr ride their host tiers (counted per
# client on lodestar_device_failover_dispatches_total), and dispatch
# exceptions route through the error taxonomy instead of a blanket
# swallow — programming errors re-raise as the bugs they are.
_HEALTH = None
_LOG = None


def set_health_tracker(tracker) -> None:
    """Install (or clear, with None) the DeviceHealthTracker this
    module's device tiers consult before dispatching."""
    global _HEALTH
    _HEALTH = tracker


def _klog():
    global _LOG
    if _LOG is None:
        from ..logger import get_logger

        _LOG = get_logger("kzg")
    return _LOG


def _device_blocked(client: str) -> bool:
    """True while the health tracker quarantines the device path.
    Counts the failed-over dispatch and logs once per state
    transition (not per call — a quarantined node sees thousands)."""
    h = _HEALTH
    if h is None or h.device_allowed():
        return False
    if h.note_failover(client):
        _klog().warn(
            "device quarantined: dispatches riding host tier",
            {"client": client, "state": h.state.value},
        )
    return True


def _report_device_fault(e: BaseException, client: str) -> None:
    """Taxonomy routing for a device-dispatch exception: classify,
    report to the tracker, log once per transition. PROGRAMMING
    errors (TypeError/KeyError from our own code) re-raise — they
    must surface as the bugs they are, not masquerade as device
    flakiness absorbed by a fallback counter."""
    from ..device.health import classify_device_error

    kind = classify_device_error(e)
    if kind == "programming":
        raise e
    h = _HEALTH
    if h is not None:
        h.record_fault(kind, client=client)
        if h.should_log(client):
            _klog().warn(
                "device dispatch failed; host tier serves",
                {"client": client, "kind": kind, "err": repr(e)},
            )


def msm_backend() -> str:
    """The live MSM backend mode."""
    return _msm_backend


def set_msm_backend(name: str) -> None:
    global _msm_backend
    if name not in MSM_BACKENDS:
        raise ValueError(
            f"unknown kzg msm backend {name!r}; want {MSM_BACKENDS}"
        )
    _msm_backend = name


def msm_path_counts() -> dict:
    """Snapshot of the per-path dispatch counters (tests, /metrics)."""
    return dict(_MSM_DISPATCH, device_fallbacks=_MSM_DEVICE_FALLBACKS)


def bind_kzg_collectors(metrics) -> None:
    """Wire the m.kzg registry namespace (metrics/beacon.py) to sample
    the module counters at scrape — the addCollect pattern every other
    service uses (node.py)."""
    global _BATCH_HIST
    _BATCH_HIST = getattr(metrics, "batch_verify_blobs", None)
    metrics.msm_dispatch_total.add_collect(
        lambda g: [
            g.set(v, path=p) for p, v in _MSM_DISPATCH.items()
        ]
    )
    metrics.msm_device_fallback_total.add_collect(
        lambda g: g.set(_MSM_DEVICE_FALLBACKS)
    )
    metrics.fr_dispatch_total.add_collect(
        lambda g: [
            g.set(v, path=p) for p, v in _FR_DISPATCH.items()
        ]
    )
    metrics.fr_device_fallback_total.add_collect(
        lambda g: g.set(_FR_DEVICE_FALLBACKS)
    )


# --- two-tier Fr backend (device / python) ---------------------------------

FR_BACKENDS = ("auto", "device", "python")

_fr_backend = os.environ.get("LODESTAR_TPU_KZG_FR_BACKEND", "auto")
if _fr_backend not in FR_BACKENDS:
    raise ValueError(
        f"LODESTAR_TPU_KZG_FR_BACKEND={_fr_backend!r} not in "
        f"{FR_BACKENDS}"
    )

# per-path counters for the batch-verify barycentric evaluations,
# mirroring the MSM tier's discipline: one entry per
# _evaluate_polynomials_batch call by the tier that served it;
# fr_device_fallbacks counts dispatches that wanted the device but
# errored and fell back to the Python ints.
_FR_DISPATCH: dict[str, int] = {"device": 0, "python": 0}
_FR_DEVICE_FALLBACKS = 0
_FR_ROOTS_DEV = None  # cached device limb array of the brp'd domain


def fr_backend() -> str:
    """The live Fr-evaluation backend mode."""
    return _fr_backend


def set_fr_backend(name: str) -> None:
    global _fr_backend
    if name not in FR_BACKENDS:
        raise ValueError(
            f"unknown kzg fr backend {name!r}; want {FR_BACKENDS}"
        )
    _fr_backend = name


def fr_path_counts() -> dict:
    """Snapshot of the Fr-evaluation dispatch counters."""
    return dict(_FR_DISPATCH, device_fallbacks=_FR_DEVICE_FALLBACKS)


def _fr_roots_dev():
    global _FR_ROOTS_DEV
    if _FR_ROOTS_DEV is None:
        import jax.numpy as jnp

        from ..ops import fr as _fr

        _FR_ROOTS_DEV = jnp.asarray(_fr.fr_from_ints(_roots_brp()))
    return _FR_ROOTS_DEV


def _evaluate_polynomials_batch(
    polys: list[list[int]], zs: list[int]
) -> list[int]:
    """ys for m (poly, z) pairs — the batch-verify evaluation seam.
    The device tier packs every z-outside-the-domain evaluation into
    ONE ops/fr barycentric dispatch (the Montgomery batch inversion
    runs on device too); z-equals-root blobs read the coefficient on
    host exactly like the Python oracle. Any device error falls back
    to the Python ints (counted), never fails the caller."""
    global _FR_DEVICE_FALLBACKS
    mode = _fr_backend
    use_device = mode == "device"
    if mode == "auto":
        import jax

        use_device = jax.default_backend() == "tpu"
    if use_device and _device_blocked("kzg_fr"):
        use_device = False  # quarantined: Python ints serve exactly
    if use_device:
        roots = _roots_brp()
        ys: list[int | None] = [None] * len(zs)
        live = []
        for i, (p, z) in enumerate(zip(polys, zs)):
            if z in roots:
                ys[i] = p[roots.index(z)]
            else:
                live.append(i)
        try:
            served = True
            if live:

                def _dispatch():
                    import jax.numpy as jnp
                    import numpy as np

                    from ..ops import fr as _fr

                    pd = jnp.asarray(
                        np.stack(
                            [_fr.fr_from_ints(polys[i]) for i in live]
                        )
                    )
                    zd = jnp.asarray(
                        _fr.fr_from_ints([zs[i] for i in live])
                    )
                    return _fr.fr_to_ints(
                        _fr.eval_barycentric_batch(
                            pd, _fr_roots_dev(), zd
                        )
                    )

                # bulk-class dispatch: behind pending gossip verdicts
                # at the wave boundary; a shed rides the Python tier
                served, out = _submit_bulk(_dispatch)
                if served:
                    for i, y in zip(live, out):
                        ys[i] = y
            if served:
                _FR_DISPATCH["device"] += 1
                return ys
            _FR_DEVICE_FALLBACKS += 1
        except Exception as e:
            # taxonomy (device/health.py): classify + report; a
            # programming error re-raises inside, everything else
            # stays a counted fallback onto the Python ints
            _FR_DEVICE_FALLBACKS += 1
            _report_device_fault(e, "kzg_fr")
    _FR_DISPATCH["python"] += 1
    return [
        evaluate_polynomial_in_evaluation_form(p, z)
        for p, z in zip(polys, zs)
    ]


def _device_msm_ready(n: int) -> bool:
    """Should auto mode route an n-point lincomb to the device? Only
    on a TPU host, and only once the rung's compile is warm — a cold
    rung rides the host C path (counted as a fallback) the way the
    BLS verifier's host_fallback_when_cold keeps cold buckets off
    multi-minute compiles."""
    global _MSM_DEVICE_FALLBACKS
    import jax

    if jax.default_backend() != "tpu":
        return False
    from ..ops import msm as _msm

    if _msm.msm_is_warm(_msm.msm_rung(n)):
        return True
    _MSM_DEVICE_FALLBACKS += 1
    return False


def _resolve_msm_path(n: int) -> str:
    mode = _msm_backend
    if mode == "device":
        return "device"
    if mode == "oracle":
        return "oracle"
    if mode == "native":
        return "native" if native.available() else "oracle"
    if _device_msm_ready(n):
        return "device"
    return "native" if native.available() else "oracle"


def _g1_lincomb_many(tasks):
    """Batched lincombs: [(points, scalars), ...] -> [point | None].
    On the device tier every task rides ONE dispatch (batch axis over
    lincombs — ops/msm.g1_msm_many); host tiers loop. A device error
    falls back to the host tiers (counted), never fails the caller."""
    global _MSM_DEVICE_FALLBACKS
    if not tasks:
        return []
    for pts, ks in tasks:
        assert len(pts) == len(ks)
    path = _resolve_msm_path(max(len(p) for p, _ in tasks))
    if path == "device" and _device_blocked("kzg_msm"):
        # quarantined: the host tiers serve bit-exactly (the
        # differential suite proves device == native == oracle)
        path = "native" if native.available() else "oracle"
    if path == "device":
        from ..ops import msm as _msm

        try:
            # bulk-class dispatch (device/executor.py): queues behind
            # pending gossip verdicts; an admission-control shed
            # falls back to the host tiers like any device miss
            served, out = _submit_bulk(
                lambda: _msm.g1_msm_many(tasks)
            )
            if served:
                _MSM_DISPATCH["device"] += 1
                return out
            _MSM_DEVICE_FALLBACKS += 1
            path = "native" if native.available() else "oracle"
        except Exception as e:
            # taxonomy (device/health.py): classify + report; a
            # programming error re-raises inside, device kinds stay
            # counted fallbacks onto the host tiers
            _MSM_DEVICE_FALLBACKS += 1
            _report_device_fault(e, "kzg_msm")
            path = "native" if native.available() else "oracle"
    if path == "native":
        _MSM_DISPATCH["native"] += 1
        return [native.g1_msm(pts, ks) for pts, ks in tasks]
    _MSM_DISPATCH["oracle"] += 1
    out = []
    for pts, ks in tasks:
        acc = None
        for p, s in zip(pts, ks):
            acc = oc.g1_add(acc, oc.g1_mul(p, s % BLS_MODULUS))
        out.append(acc)
    return out


def _g1_lincomb(points, scalars):
    """sum_i scalars[i] * points[i] through the three-tier backend."""
    return _g1_lincomb_many([(points, scalars)])[0]


def _pairings_one(pairs) -> bool:
    if native.available():
        return native.pairing_product_is_one(pairs)
    from .pairing import pairing_product_is_one as _oc_pairs

    return _oc_pairs(pairs)


# ---------------------------------------------------------------------------
# Trusted setup
# ---------------------------------------------------------------------------


class TrustedSetup:
    """g1_lagrange_brp: blob-width lagrange-basis G1 points, bit-reversal
    permuted (the order polynomials-in-evaluation-form use);
    g2_monomial_1: tau*G2."""

    def __init__(self, g1_lagrange: list, g2_monomial: list):
        if len(g1_lagrange) != FIELD_ELEMENTS_PER_BLOB:
            raise KzgError(
                f"setup has {len(g1_lagrange)} G1 points, "
                f"need {FIELD_ELEMENTS_PER_BLOB}"
            )
        if len(g2_monomial) < 2:
            raise KzgError("setup needs >= 2 G2 monomial points")
        self.g1_lagrange_brp = _bit_reversal_permutation(g1_lagrange)
        self.g2_monomial_1 = g2_monomial[1]


_ACTIVE_SETUP: TrustedSetup | None = None


def load_trusted_setup(path: str | os.PathLike) -> TrustedSetup:
    """Load + activate a setup in the standard JSON format."""
    data = json.loads(Path(path).read_text())
    g1 = [
        _g1_decompress(bytes.fromhex(h.removeprefix("0x")))
        for h in data["g1_lagrange"]
    ]
    g2 = [
        oc.g2_from_bytes(bytes.fromhex(h.removeprefix("0x")))
        for h in data["g2_monomial"][:2]
    ]
    setup = TrustedSetup(g1, g2)
    activate_trusted_setup(setup)
    return setup


def activate_trusted_setup(setup: TrustedSetup) -> None:
    global _ACTIVE_SETUP
    _ACTIVE_SETUP = setup


def _setup() -> TrustedSetup:
    if _ACTIVE_SETUP is None:
        activate_trusted_setup(dev_trusted_setup())
    return _ACTIVE_SETUP


_DEV_TAU_SEED = b"lodestar_tpu INSECURE dev trusted setup tau v1"


def dev_trusted_setup(cache_dir: str | None = None) -> TrustedSetup:
    """Generate (or load the cached) **INSECURE** dev setup.

    tau is derived from a public seed, so anyone can forge proofs
    against this setup — tests and dev chains only. Production must
    `load_trusted_setup` with the ceremony output.
    """
    d = Path(
        cache_dir
        or os.environ.get(
            "LODESTAR_TPU_NATIVE_DIR",
            Path.home() / ".cache" / "lodestar_tpu" / "native",
        )
    )
    d.mkdir(parents=True, exist_ok=True)
    cache = d / f"dev_trusted_setup_{FIELD_ELEMENTS_PER_BLOB}.json"
    if cache.exists():
        try:
            data = json.loads(cache.read_text())
            g1 = [oc_from_hex(h) for h in data["g1_lagrange"]]
            g2 = [g2_from_json(v) for v in data["g2_monomial"]]
            return TrustedSetup(g1, g2)
        except (ValueError, KeyError, OSError):
            # corrupt/stale cache data (bad JSON, missing keys, bad
            # hex/point bytes, unreadable file): regenerate below.
            # Anything else — a programming error in the parse path —
            # re-raises instead of silently burning the cache.
            cache.unlink()

    tau = int.from_bytes(sha256(_DEV_TAU_SEED).digest(), "big") % BLS_MODULUS
    n = FIELD_ELEMENTS_PER_BLOB
    roots = compute_roots_of_unity(n)
    # L_i(tau) = w^i * (tau^n - 1) / (n * (tau - w^i))
    tau_n_minus_1 = (pow(tau, n, BLS_MODULUS) - 1) % BLS_MODULUS
    denoms = _fr_batch_inv([(tau - w) % BLS_MODULUS for w in roots])
    n_inv = _fr_inv(n)
    scalars = [
        w * tau_n_minus_1 % BLS_MODULUS * d % BLS_MODULUS * n_inv % BLS_MODULUS
        for w, d in zip(roots, denoms)
    ]
    g1 = [_g1_mul(oc.G1_GEN, s) for s in scalars]
    g2 = [oc.G2_GEN, _g2_mul(oc.G2_GEN, tau)]
    cache.write_text(
        json.dumps(
            {
                "g1_lagrange": [oc_to_hex(p) for p in g1],
                "g2_monomial": [g2_to_json_val(p) for p in g2],
            }
        )
    )
    return TrustedSetup(g1, g2)


def oc_to_hex(p) -> str:
    return native.g1_to_bytes(p).hex()


def oc_from_hex(h: str):
    return native.g1_from_bytes_affine(bytes.fromhex(h))


def g2_to_json_val(p) -> str:
    return native.g2_to_bytes(p).hex()


def g2_from_json(h: str):
    return native.g2_from_bytes_affine(bytes.fromhex(h))


# ---------------------------------------------------------------------------
# Blob <-> polynomial
# ---------------------------------------------------------------------------


def bytes_to_bls_field(b: bytes) -> int:
    x = int.from_bytes(b, "big")
    if x >= BLS_MODULUS:
        raise KzgError("field element >= BLS modulus")
    return x


def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(sha256(data).digest(), "big") % BLS_MODULUS


def blob_to_polynomial(blob: bytes) -> list[int]:
    if len(blob) != BYTES_PER_BLOB:
        raise KzgError(f"blob must be {BYTES_PER_BLOB} bytes")
    return [
        bytes_to_bls_field(blob[i * 32 : (i + 1) * 32])
        for i in range(FIELD_ELEMENTS_PER_BLOB)
    ]


def _validate_g1(b: bytes):
    """48B compressed -> point, with curve+subgroup checks."""
    if len(b) != 48:
        raise KzgError("compressed G1 must be 48 bytes")
    try:
        return _g1_decompress(bytes(b))
    except Exception as e:
        raise KzgError(f"invalid G1 point: {e}") from e


# ---------------------------------------------------------------------------
# Core spec functions
# ---------------------------------------------------------------------------


def blob_to_kzg_commitment(blob: bytes) -> bytes:
    poly = blob_to_polynomial(blob)
    return _g1_compress(_g1_lincomb(_setup().g1_lagrange_brp, poly))


def evaluate_polynomial_in_evaluation_form(poly: list[int], z: int) -> int:
    """Barycentric evaluation over the brp'd domain."""
    width = FIELD_ELEMENTS_PER_BLOB
    roots = _roots_brp()
    if z in roots:
        return poly[roots.index(z)]
    inv = _fr_batch_inv([(z - w) % BLS_MODULUS for w in roots])
    acc = 0
    for p_i, w, iv in zip(poly, roots, inv):
        acc = (acc + p_i * w % BLS_MODULUS * iv) % BLS_MODULUS
    zn_minus_1 = (pow(z, width, BLS_MODULUS) - 1) % BLS_MODULUS
    return acc * zn_minus_1 % BLS_MODULUS * _fr_inv(width) % BLS_MODULUS


def compute_kzg_proof_impl(poly: list[int], z: int) -> tuple[bytes, int]:
    """Proof that poly(z) == y; returns (proof48, y)."""
    roots = _roots_brp()
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    numers = [(p - y) % BLS_MODULUS for p in poly]
    if z in roots:
        m = roots.index(z)
        # quotient value at the domain point itself
        # (compute_quotient_eval_within_domain)
        q = [0] * FIELD_ELEMENTS_PER_BLOB
        inv = _fr_batch_inv(
            [
                (w - z) % BLS_MODULUS if i != m else 1
                for i, w in enumerate(roots)
            ]
        )
        qm = 0
        z_inv = _fr_inv(z)
        for i, (num, w, iv) in enumerate(zip(numers, roots, inv)):
            if i == m:
                continue
            q[i] = num * iv % BLS_MODULUS
            # spec compute_quotient_eval_within_domain:
            # += (p_i - y) * w_i / (z * (z - w_i)) — note (z - w_i)
            qm = (
                qm
                - num * w % BLS_MODULUS * iv % BLS_MODULUS * z_inv
            ) % BLS_MODULUS
        q[m] = qm
    else:
        inv = _fr_batch_inv([(w - z) % BLS_MODULUS for w in roots])
        q = [n * iv % BLS_MODULUS for n, iv in zip(numers, inv)]
    proof = _g1_compress(_g1_lincomb(_setup().g1_lagrange_brp, q))
    return proof, y


def compute_kzg_proof(blob: bytes, z_bytes: bytes) -> tuple[bytes, bytes]:
    poly = blob_to_polynomial(blob)
    proof, y = compute_kzg_proof_impl(poly, bytes_to_bls_field(z_bytes))
    return proof, int(y).to_bytes(32, "big")


def verify_kzg_proof(
    commitment_bytes: bytes, z_bytes: bytes, y_bytes: bytes, proof_bytes: bytes
) -> bool:
    return verify_kzg_proof_impl(
        _validate_g1(commitment_bytes),
        bytes_to_bls_field(z_bytes),
        bytes_to_bls_field(y_bytes),
        _validate_g1(proof_bytes),
    )


def verify_kzg_proof_impl(commitment, z: int, y: int, proof) -> bool:
    """e(C - y*G1, -G2) * e(proof, tau*G2 - z*G2) == 1."""
    s = _setup()
    p_minus_y = _g1_add(commitment, _g1_mul(oc.G1_GEN, (-y) % BLS_MODULUS))
    x_minus_z = _g2_add(
        s.g2_monomial_1, _g2_mul(oc.G2_GEN, (-z) % BLS_MODULUS)
    )
    return _pairings_one(
        [(p_minus_y, oc.g2_neg(oc.G2_GEN)), (proof, x_minus_z)]
    )


def compute_challenge(blob: bytes, commitment_bytes: bytes) -> int:
    # KZG_ENDIANNESS='big' (deneb polynomial-commitments spec; c-kzg
    # writes the 16-byte degree big-endian)
    degree = FIELD_ELEMENTS_PER_BLOB.to_bytes(16, "big")
    return hash_to_bls_field(
        FIAT_SHAMIR_PROTOCOL_DOMAIN + degree + blob + commitment_bytes
    )


def compute_blob_kzg_proof(blob: bytes, commitment_bytes: bytes) -> bytes:
    _validate_g1(commitment_bytes)
    z = compute_challenge(blob, commitment_bytes)
    proof, _ = compute_kzg_proof_impl(blob_to_polynomial(blob), z)
    return proof


def verify_blob_kzg_proof(
    blob: bytes, commitment_bytes: bytes, proof_bytes: bytes
) -> bool:
    commitment = _validate_g1(commitment_bytes)
    z = compute_challenge(blob, commitment_bytes)
    y = evaluate_polynomial_in_evaluation_form(blob_to_polynomial(blob), z)
    return verify_kzg_proof_impl(commitment, z, y, _validate_g1(proof_bytes))


def verify_blob_kzg_proof_batch(
    blobs: list[bytes],
    commitment_bytes_list: list[bytes],
    proof_bytes_list: list[bytes],
) -> bool:
    """Random-linear-combination batch verification (spec
    verify_kzg_proof_batch): one 2-pairing check for n blobs, the
    three verification lincombs batched into ONE device dispatch on
    the device MSM tier. The length check comes first — a
    proofs/commitments mismatch must raise, not be zip-truncated into
    a verdict about a batch nobody submitted — and the empty batch
    short-circuits True without touching the trusted setup."""
    n = len(blobs)
    if not (n == len(commitment_bytes_list) == len(proof_bytes_list)):
        raise KzgError(
            f"batch length mismatch: {n} blobs, "
            f"{len(commitment_bytes_list)} commitments, "
            f"{len(proof_bytes_list)} proofs"
        )
    if n == 0:
        return True
    if _BATCH_HIST is not None:
        _BATCH_HIST.observe(n)
    commitments = [_validate_g1(c) for c in commitment_bytes_list]
    proofs = [_validate_g1(p) for p in proof_bytes_list]
    zs, polys = [], []
    for blob, cb in zip(blobs, commitment_bytes_list):
        zs.append(compute_challenge(blob, cb))
        polys.append(blob_to_polynomial(blob))
    # the whole batch's barycentric math in one device dispatch on
    # the Fr device tier (python tier loops the oracle)
    ys = _evaluate_polynomials_batch(polys, zs)
    # Fiat-Shamir the whole statement into one scalar; use its powers
    data = RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
    data += FIELD_ELEMENTS_PER_BLOB.to_bytes(8, "big")
    data += n.to_bytes(8, "big")
    for cb, z, y, pb in zip(commitment_bytes_list, zs, ys, proof_bytes_list):
        data += bytes(cb) + z.to_bytes(32, "big") + y.to_bytes(32, "big")
        data += bytes(pb)
    r = hash_to_bls_field(data)
    r_powers = [pow(r, i, BLS_MODULUS) for i in range(n)]

    c_minus_y = [
        _g1_add(c, _g1_mul(oc.G1_GEN, (-y) % BLS_MODULUS))
        for c, y in zip(commitments, ys)
    ]
    proof_lincomb, proof_z_lincomb, c_minus_y_lincomb = _g1_lincomb_many(
        [
            (proofs, r_powers),
            (
                proofs,
                [rp * z % BLS_MODULUS for rp, z in zip(r_powers, zs)],
            ),
            (c_minus_y, r_powers),
        ]
    )
    lhs = _g1_add(c_minus_y_lincomb, proof_z_lincomb)
    return _pairings_one(
        [
            (lhs, oc.g2_neg(oc.G2_GEN)),
            (proof_lincomb, _setup().g2_monomial_1),
        ]
    )

"""Device/compiler telemetry: the JAX/XLA execution layer on /metrics.

The perf program lives in the execution layer — multi-minute stage
compiles, the persistent compilation cache (utils/jaxcache.py), the
bucket ladder + `warmup_ingest()`, the vpu/mxu backend switch — yet a
retrace storm, a cold persistent cache, or a warmup that never
finishes all look identical to "the TPU is slow" from the outside.
This module makes the layer first-class, the way production batched-
accelerator systems (Orca/vLLM-style continuous batching, PAPERS.md)
treat compile-cache and device-utilization telemetry as table stakes:

  * compile & cache tracking — `jax.monitoring` listeners route
    backend-compile durations and persistent-cache hit/miss events
    into per-stage counters; instrumented wrappers around the
    `bls/kernels.py` jit entry points attribute each compile to its
    pipeline stage and detect RETRACES (the same entry point
    recompiling for an argument signature it already served — the
    signature of a `jax.clear_caches()` / limb-backend-switch storm);
  * device runtime — per-stage dispatch wall time always, optional
    dispatch-to-ready deltas (`timing="sync"`) fed into histograms
    and attached as device-side child spans under the block-import
    trace (metrics/tracing.py); live-buffer/HBM accounting via
    `Device.memory_stats()` with a `jax.live_arrays()` fallback for
    backends that expose none (CPU); host<->device transfer byte
    accounting at the verifier's dispatch/readback seams;
  * on-demand capture — `profiler_capture()` runs `jax.profiler` for
    a bounded window (one capture at a time) behind the
    `POST /eth/v1/lodestar/device_trace` admin route, mirroring the
    reference's write_profile/write_heapdump ops routes.

The singleton (`install()` / `get_telemetry()`) exists only once a
node or test asks for it: with no telemetry installed every hook in
the kernels is a single attribute check, so benches and tools measure
the uninstrumented pipeline unless they opt in.
"""

from __future__ import annotations

import contextlib
import threading
import time

# jax.monitoring event names this module consumes (the stable names
# jax has emitted since 0.4.x; unknown events are ignored, so a jax
# upgrade degrades to "no data", never to an error).
EV_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
EV_TRACE = "/jax/core/compile/jaxpr_trace_duration"
EV_CACHE_HIT = "/jax/compilation_cache/cache_hits"
EV_CACHE_MISS = "/jax/compilation_cache/cache_misses"
EV_CACHE_RETRIEVAL = "/jax/compilation_cache/cache_retrieval_time_sec"

TIMING_MODES = ("off", "dispatch", "sync")

# stage label used when a compile fires outside any instrumented
# stage scope (ad-hoc jit in tools, tests, warmup glue)
OTHER_STAGE = "other"

_TELEMETRY: "DeviceTelemetry | None" = None
_LISTENERS_INSTALLED = False
_INSTALL_LOCK = threading.Lock()

# persistent-cache setup errors recorded before any telemetry exists
# (utils/jaxcache.enable runs at bls.kernels import time); absorbed by
# the next install()
_PENDING_CACHE_ERRORS = 0

_capture_lock = threading.Lock()


class CaptureBusyError(RuntimeError):
    """A profiler capture is already running (one at a time)."""


def get_telemetry() -> "DeviceTelemetry | None":
    return _TELEMETRY


def set_telemetry(t: "DeviceTelemetry | None") -> "DeviceTelemetry | None":
    """Swap the module singleton (tests install a fresh instance so
    counter assertions never see another test's compiles)."""
    global _TELEMETRY
    _TELEMETRY = t
    return t


def install(metrics=None, timing: str | None = None) -> "DeviceTelemetry":
    """Create (or return) the process singleton, register the
    jax.monitoring listeners once, and bind the registry namespace.
    Listeners are global and permanent — they route through the
    CURRENT singleton, so swapping instances re-targets them."""
    global _TELEMETRY, _PENDING_CACHE_ERRORS
    with _INSTALL_LOCK:
        if _TELEMETRY is None:
            _TELEMETRY = DeviceTelemetry()
        if _PENDING_CACHE_ERRORS:
            _TELEMETRY.cache_errors += _PENDING_CACHE_ERRORS
            _PENDING_CACHE_ERRORS = 0
        if timing is not None:
            _TELEMETRY.set_timing(timing)
        if metrics is not None:
            _TELEMETRY.bind(metrics)
        _install_listeners()
        return _TELEMETRY


def _install_listeners() -> None:
    global _LISTENERS_INSTALLED
    if _LISTENERS_INSTALLED:
        return
    from jax import monitoring

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    _LISTENERS_INSTALLED = True


def _on_event(name: str, **kwargs) -> None:
    t = _TELEMETRY
    if t is None:
        return
    if name == EV_CACHE_HIT:
        t.on_cache_hit()
    elif name == EV_CACHE_MISS:
        t.on_cache_miss()
    elif name.startswith("/jax/compilation_cache/") and "error" in name:
        t.on_cache_error()


def _on_duration(name: str, secs: float, **kwargs) -> None:
    t = _TELEMETRY
    if t is None:
        return
    if name == EV_BACKEND_COMPILE:
        t.on_backend_compile(secs)
    elif name == EV_CACHE_RETRIEVAL:
        t.on_cache_retrieval(secs)


def record_cache_error() -> None:
    """Persistent-cache setup/IO failure (utils/jaxcache.enable). Works
    before install(): early errors park in a module counter the next
    install() absorbs, so a cold-cache node is diagnosable even when
    the failure happened at import time."""
    global _PENDING_CACHE_ERRORS
    t = _TELEMETRY
    if t is not None:
        t.on_cache_error()
    else:
        _PENDING_CACHE_ERRORS += 1


def tree_nbytes(*trees) -> int:
    """Total array bytes across pytrees (device dispatch payloads)."""
    import jax

    total = 0
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def record_transfer(direction: str, *trees) -> None:
    """Host<->device transfer accounting ('h2d' / 'd2h') for the
    given dispatch payload pytrees. The byte walk only runs when
    telemetry is installed — an uninstrumented bench pays one None
    check per dispatch, nothing more."""
    t = _TELEMETRY
    if t is not None:
        t.on_transfer(direction, tree_nbytes(*trees))


class DeviceTelemetry:
    """Counters + per-stage timing for the XLA execution layer.

    Plain-dict counters guarded by one lock (increments come from
    executor threads, the warmup thread, and monitoring listeners);
    the registry bridges them at scrape time via add_collect, the
    same pattern as BlsVerifierMetrics (bls/verifier.py)."""

    def __init__(self, timing: str = "dispatch"):
        self.set_timing(timing)
        self._lock = threading.Lock()
        # compile & cache
        self.compiles: dict[str, int] = {}
        self.compile_seconds: dict[str, float] = {}
        self.retraces: dict[str, int] = {}
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_errors = 0
        self.cache_retrieval_seconds = 0.0
        # device runtime
        self.dispatch_count: dict[str, int] = {}
        self.dispatch_seconds: dict[str, float] = {}
        self.device_count: dict[str, int] = {}
        self.device_seconds: dict[str, float] = {}
        self.transfer_bytes = {"h2d": 0, "d2h": 0}
        self.backend_switches = 0
        # input buffers donated to the fused stage programs
        # (bls/kernels donate_argnums; stays 0 off-TPU where donation
        # is disarmed — the gauge must not claim reuse XLA ignored)
        self.donated_buffer_reuses = 0
        # on-demand capture
        self.trace_captures = 0
        self.trace_capture_active = False
        self.last_trace_dir: str | None = None
        # retrace detection state: per stage, the argument signatures
        # (shapes+dtypes) this entry point has already served
        self._seen: dict[str, set] = {}
        self._frames = threading.local()
        # bound registry histograms (metrics/beacon.py m.device), None
        # until a node binds them
        self._hist_dispatch = None
        self._hist_device = None

    # -- configuration --------------------------------------------------

    def set_timing(self, timing: str) -> None:
        if timing not in TIMING_MODES:
            raise ValueError(
                f"device timing {timing!r} not in {TIMING_MODES}"
            )
        self.timing = timing

    @property
    def enabled(self) -> bool:
        return self.timing != "off"

    def bind(self, metrics) -> None:
        """Attach the m.device namespace so stage timings observe into
        real registry histograms (counters stay internal — node.py
        bridges them with add_collect like every other service)."""
        self._hist_dispatch = getattr(
            metrics, "stage_dispatch_seconds", None
        )
        self._hist_device = getattr(metrics, "stage_device_seconds", None)

    # -- monitoring listener sinks --------------------------------------

    def _frame_stack(self) -> list:
        stack = getattr(self._frames, "stack", None)
        if stack is None:
            stack = self._frames.stack = []
        return stack

    def current_stage(self) -> str | None:
        stack = self._frame_stack()
        return stack[-1]["stage"] if stack else None

    def on_backend_compile(self, secs: float) -> None:
        stack = self._frame_stack()
        stage = stack[-1]["stage"] if stack else OTHER_STAGE
        if stack:
            stack[-1]["compiled"] = True
        with self._lock:
            self.compiles[stage] = self.compiles.get(stage, 0) + 1
            self.compile_seconds[stage] = (
                self.compile_seconds.get(stage, 0.0) + secs
            )

    def on_cache_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def on_cache_miss(self) -> None:
        with self._lock:
            self.cache_misses += 1

    def on_cache_error(self) -> None:
        with self._lock:
            self.cache_errors += 1

    def on_cache_retrieval(self, secs: float) -> None:
        with self._lock:
            self.cache_retrieval_seconds += secs

    def on_transfer(self, direction: str, nbytes: int) -> None:
        with self._lock:
            self.transfer_bytes[direction] = (
                self.transfer_bytes.get(direction, 0) + int(nbytes)
            )

    def note_donation(self, n: int) -> None:
        """n input buffers handed to a fused dispatch with
        donate_argnums armed (their device memory is reusable for the
        program's outputs — the double-buffered pipeline's HBM bound)."""
        with self._lock:
            self.donated_buffer_reuses += int(n)

    def note_backend_switch(self) -> None:
        """A limb-backend switch dropped every cached trace
        (ops/limbs.set_backend): the next dispatch per (stage, shape)
        recompiles, which the retrace counters will show — this
        counter names the cause next to the symptom."""
        with self._lock:
            self.backend_switches += 1

    # -- stage instrumentation ------------------------------------------

    @contextlib.contextmanager
    def stage_scope(self, stage: str):
        """Attribute backend compiles fired inside the block to
        `stage` (thread-local — compile runs on the dispatch thread)."""
        stack = self._frame_stack()
        frame = {"stage": stage, "compiled": False}
        stack.append(frame)
        try:
            yield frame
        finally:
            stack.pop()

    def timed_call(self, stage: str, fn, args, kwargs):
        t0 = time.perf_counter()
        with self.stage_scope(stage) as frame:
            out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        sig = _args_signature(args, kwargs)
        with self._lock:
            seen = self._seen.setdefault(stage, set())
            if frame["compiled"] and sig in seen:
                self.retraces[stage] = self.retraces.get(stage, 0) + 1
            seen.add(sig)
            self.dispatch_count[stage] = (
                self.dispatch_count.get(stage, 0) + 1
            )
            self.dispatch_seconds[stage] = (
                self.dispatch_seconds.get(stage, 0.0) + dt
            )
        if self._hist_dispatch is not None:
            self._hist_dispatch.observe(dt, stage=stage)
        if self.timing == "sync":
            self._block_and_time(stage, out)
        return out

    def _block_and_time(self, stage: str, out) -> None:
        """Dispatch-to-ready delta: wait for the stage's outputs and
        record the device-side time, attaching it as a child span when
        a block-import trace is active on this task/thread."""
        import jax

        from .tracing import child_span

        with child_span(f"device:{stage}"):
            t0 = time.perf_counter()
            try:
                jax.block_until_ready(out)
            except Exception:
                return
            dt = time.perf_counter() - t0
        with self._lock:
            self.device_count[stage] = self.device_count.get(stage, 0) + 1
            self.device_seconds[stage] = (
                self.device_seconds.get(stage, 0.0) + dt
            )
        if self._hist_device is not None:
            self._hist_device.observe(dt, stage=stage)

    # -- scrape-time snapshots ------------------------------------------

    def snapshot_compiles(self):
        with self._lock:
            return (
                dict(self.compiles),
                dict(self.compile_seconds),
                dict(self.retraces),
            )

    def snapshot_transfers(self) -> dict[str, int]:
        with self._lock:
            return dict(self.transfer_bytes)

    def snapshot_stage_seconds(self):
        """(dispatch_seconds, device_seconds) cumulative per-stage
        copies taken under the lock — the drift monitor
        (device/autotune.py) diffs consecutive snapshots into
        per-window stage shares against the COVERAGE.md budget."""
        with self._lock:
            return dict(self.dispatch_seconds), dict(self.device_seconds)


def _args_signature(args, kwargs) -> tuple:
    """Cheap structural signature of a call: shapes + dtypes of array
    leaves, values of hashable scalars. Two calls with equal
    signatures hit the same jit executable — so a backend compile on
    an already-seen signature is a RETRACE."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(leaf, "dtype", ""))))
        else:
            try:
                hash(leaf)
                sig.append(leaf)
            except TypeError:
                sig.append(type(leaf).__name__)
    return tuple(sig)


def instrument_stage(stage: str, fn):
    """Wrap a jit entry point: attribute its compiles to `stage`,
    detect retraces, time dispatches (and, in 'sync' mode, device
    readiness). A single attribute check when no telemetry is
    installed or timing is off."""

    def wrapper(*args, **kwargs):
        t = _TELEMETRY
        if t is None or not t.enabled:
            return fn(*args, **kwargs)
        return t.timed_call(stage, fn, args, kwargs)

    wrapper.__name__ = getattr(fn, "__name__", stage)
    wrapper.__qualname__ = wrapper.__name__
    wrapper.__wrapped__ = fn
    wrapper.stage = stage
    return wrapper


def bind_collectors(metrics, telemetry: "DeviceTelemetry", verifier=None):
    """Wire the m.device registry namespace (metrics/beacon.py) to
    sample this telemetry instance at scrape time — the addCollect
    pattern every other service uses (node.py). `verifier` supplies
    the dispatch-queue depth when it exposes `in_flight_waves`."""
    dtel = telemetry

    # One collect fn populates each related gauge GROUP: the registry
    # renders metrics in registration order (metrics/beacon.py keeps
    # each group contiguous), so a fn hung on the group's first gauge
    # may set the later ones — one snapshot per scrape, not one per
    # gauge.
    def _compiles(g):
        comp, secs, retr = dtel.snapshot_compiles()
        for s, c in comp.items():
            g.set(c, stage=s)
        for s, v in secs.items():
            metrics.compile_seconds_total.set(v, stage=s)
        for s, v in retr.items():
            metrics.retraces_total.set(v, stage=s)

    metrics.compiles_total.add_collect(_compiles)
    metrics.persistent_cache_hits_total.add_collect(
        lambda g: g.set(dtel.cache_hits)
    )
    metrics.persistent_cache_misses_total.add_collect(
        lambda g: g.set(dtel.cache_misses)
    )
    metrics.persistent_cache_errors_total.add_collect(
        lambda g: g.set(dtel.cache_errors)
    )
    metrics.cache_retrieval_seconds_total.add_collect(
        lambda g: g.set(dtel.cache_retrieval_seconds)
    )
    metrics.transfer_bytes_total.add_collect(
        lambda g: [
            g.set(v, direction=d)
            for d, v in dtel.snapshot_transfers().items()
        ]
    )
    metrics.backend_switches_total.add_collect(
        lambda g: g.set(dtel.backend_switches)
    )
    metrics.trace_captures_total.add_collect(
        lambda g: g.set(dtel.trace_captures)
    )
    metrics.trace_capture_active.add_collect(
        lambda g: g.set(1 if dtel.trace_capture_active else 0)
    )

    def _warmup(g):
        # warmup progress derives from the kernels' warm registry;
        # imported lazily so nodes without the device verifier never
        # pull the kernel stack just to serve a scrape. The eligible
        # set honors the VERIFIER's ingest gate when it carries an
        # override (ingest_min_bucket=512 must not leave the gauge
        # stuck at 2/3 waiting on a 256 bucket it will never warm).
        from ..bls import kernels as _bk

        gate = None
        gate_fn = getattr(verifier, "_ingest_gate", None)
        if gate_fn is not None:
            gate = gate_fn()
        for kind, (warm, elig) in _bk.warmup_progress(gate).items():
            g.set((warm / elig) if elig else 1.0, pipeline=kind)
            metrics.warmup_warm_buckets.set(warm, pipeline=kind)
            metrics.warmup_eligible_buckets.set(elig, pipeline=kind)
        # the KZG MSM workload (ops/msm.py) rides the same warm
        # registry under its own pipeline label and rung set
        from ..ops import msm as _msm

        mw, me = _msm.warmup_progress()
        g.set((mw / me) if me else 1.0, pipeline="msm")
        metrics.warmup_warm_buckets.set(mw, pipeline="msm")
        metrics.warmup_eligible_buckets.set(me, pipeline="msm")

    metrics.warmup_progress.add_collect(_warmup)

    def _memory(g):
        for row in device_memory_snapshot():
            g.set(row["bytes_in_use"] or 0, device=str(row["id"]))
            if row["bytes_limit"] is not None:
                metrics.device_bytes_limit.set(
                    row["bytes_limit"], device=str(row["id"])
                )

    metrics.device_bytes_in_use.add_collect(_memory)

    def _live(g):
        n, total = live_buffer_stats()
        g.set(n)
        metrics.live_buffer_bytes.set(total)

    metrics.live_buffers.add_collect(_live)
    if verifier is not None and hasattr(verifier, "in_flight_waves"):
        metrics.dispatch_queue_depth.add_collect(
            lambda g: g.set(verifier.in_flight_waves)
        )
    # overlapped-pipeline observability (ISSUE 16): occupancy and the
    # host-prep seconds the overlap hid come from the verifier's wave
    # accounting; donated-buffer reuse from the kernels' dispatches
    if verifier is not None and hasattr(verifier, "pipeline_occupancy"):
        metrics.pipeline_occupancy.add_collect(
            lambda g: g.set(verifier.pipeline_occupancy())
        )
        metrics.prep_overlap_hidden_seconds_total.add_collect(
            lambda g: g.set(verifier.metrics.prep_overlap_hidden_s)
        )
    metrics.donated_buffer_reuse_total.add_collect(
        lambda g: g.set(dtel.donated_buffer_reuses)
    )


# -- device memory ----------------------------------------------------------


def device_memory_snapshot() -> list[dict]:
    """Per-device memory stats. TPU/GPU backends report allocator
    stats through `Device.memory_stats()`; backends that return None
    (CPU) fall back to summing the live jax.Arrays committed to the
    device — the readback-free analog the dashboards need."""
    import jax

    try:
        devices = jax.devices()
    except Exception:
        return []
    live_by_device: dict | None = None
    rows = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        row = {
            "id": int(d.id),
            "platform": str(d.platform),
            "kind": str(getattr(d, "device_kind", "")),
            "bytes_in_use": None,
            "bytes_limit": None,
            "source": "memory_stats",
        }
        if stats:
            row["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            limit = stats.get("bytes_limit")
            row["bytes_limit"] = int(limit) if limit is not None else None
        else:
            if live_by_device is None:
                live_by_device = _live_bytes_by_device()
            row["bytes_in_use"] = live_by_device.get(d.id, 0)
            row["source"] = "live_arrays"
        rows.append(row)
    return rows


def _live_bytes_by_device() -> dict[int, int]:
    """Live jax.Array bytes per device id (sharded arrays split their
    footprint evenly across the devices holding them)."""
    import jax

    out: dict[int, int] = {}
    try:
        arrays = jax.live_arrays()
    except Exception:
        return out
    for a in arrays:
        try:
            nbytes = int(getattr(a, "nbytes", 0) or 0)
            devs = list(a.devices())
        except Exception:
            continue
        share = nbytes // max(1, len(devs))
        for dev in devs:
            out[dev.id] = out.get(dev.id, 0) + share
    return out


def live_buffer_stats() -> tuple[int, int]:
    """(count, total bytes) of live jax.Arrays in the process."""
    import jax

    try:
        arrays = jax.live_arrays()
    except Exception:
        return 0, 0
    n, total = 0, 0
    for a in arrays:
        n += 1
        total += int(getattr(a, "nbytes", 0) or 0)
    return n, total


# -- on-demand profiler capture ---------------------------------------------


def profiler_capture(
    duration_ms: float, out_dir: str | None = None
) -> dict:
    """Run jax.profiler for `duration_ms` and return the trace
    directory. BLOCKING (callers run it in an executor); one capture
    at a time — a second concurrent request raises CaptureBusyError
    instead of corrupting the global profiler session."""
    import os
    import tempfile

    import jax

    t = _TELEMETRY
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusyError("a device trace capture is already running")
    try:
        if t is not None:
            t.trace_capture_active = True
        if out_dir is None:
            out_dir = tempfile.mkdtemp(prefix="lodestar_device_trace_")
        else:
            os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(max(0.0, duration_ms) / 1000.0)
        finally:
            jax.profiler.stop_trace()
        if t is not None:
            with t._lock:
                t.trace_captures += 1
            t.last_trace_dir = out_dir
        return {"trace_dir": out_dir, "duration_ms": float(duration_ms)}
    finally:
        if t is not None:
            t.trace_capture_active = False
        _capture_lock.release()

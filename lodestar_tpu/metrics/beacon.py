"""The lodestar metric catalog (TPU edition).

Reference analog: beacon-node/src/metrics/metrics/lodestar.ts — in
particular the `lodestar_bls_thread_pool_*` family (:403-506), kept
name-compatible so the reference's Grafana dashboard
(dashboards/lodestar_bls_thread_pool.json) scrapes unchanged. "Worker"
here means the TPU device pipeline behind the verifier service; the
queue metrics expose the verifier's buffered-job queue, which BASELINE
requires to "never back up".
"""

from __future__ import annotations

from types import SimpleNamespace

from .registry import RegistryMetricCreator


def create_lodestar_metrics(reg: RegistryMetricCreator) -> SimpleNamespace:
    m = SimpleNamespace()

    # -- bls verifier service (north star) ------------------------------
    b = SimpleNamespace()
    m.bls_thread_pool = b
    b.success_jobs_signature_sets_count = reg.counter(
        "lodestar_bls_thread_pool_success_jobs_signature_sets_count",
        "Count of total verified signature sets",
    )
    b.error_jobs_signature_sets_count = reg.counter(
        "lodestar_bls_thread_pool_error_jobs_signature_sets_count",
        "Count of total error-ed signature sets",
    )
    b.job_wait_time = reg.histogram(
        "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
        "Time from job added to the queue to starting the job in seconds",
        buckets=(0.01, 0.02, 0.05, 0.1, 0.3, 1),
    )
    b.queue_length = reg.gauge(
        "lodestar_bls_thread_pool_queue_length",
        "Count of total verifier queue length",
    )
    b.jobs_started_total = reg.counter(
        "lodestar_bls_thread_pool_jobs_started_total",
        "Count of total jobs started in the verifier, jobs include 1+ sets",
    )
    b.job_groups_started_total = reg.counter(
        "lodestar_bls_thread_pool_job_groups_started_total",
        "Count of total job groups (device dispatches) started",
    )
    b.sig_sets_started_total = reg.counter(
        "lodestar_bls_thread_pool_sig_sets_started_total",
        "Count of total signature sets started",
    )
    b.batch_retries_total = reg.counter(
        "lodestar_bls_thread_pool_batch_retries_total",
        "Count of total batches that failed and had to be verified again",
    )
    b.batch_sigs_success_total = reg.counter(
        "lodestar_bls_thread_pool_batch_sigs_success_total",
        "Count of signature sets verified successfully in batches",
    )
    b.same_message_jobs_retries_total = reg.counter(
        "lodestar_bls_thread_pool_same_message_jobs_retries_total",
        "Count of same-message jobs that failed and re-verified per set",
    )
    b.same_message_sets_retries_total = reg.counter(
        "lodestar_bls_thread_pool_same_message_sets_retries_total",
        "Count of same-message sets re-verified individually",
    )
    b.time_seconds_sum = reg.counter(
        "lodestar_bls_thread_pool_time_seconds_sum",
        "Total time spent verifying signature sets on the device",
    )
    b.sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_sig_sets_total",
        "Count of total signature sets",
    )
    b.prioritized_sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_prioritized_sig_sets_total",
        "Count of total prioritized signature sets",
    )
    b.batchable_sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_batchable_sig_sets_total",
        "Count of total batchable signature sets",
    )

    # -- TPU verifier wave pipeline (no reference analog: the device
    # replaces the worker pool; these drive
    # dashboards/lodestar_tpu_bls_verifier.json) ------------------------
    tv = SimpleNamespace()
    m.tpu_verifier = tv
    tv.queue_length = reg.gauge(
        "lodestar_tpu_verifier_queue_length",
        "Jobs waiting for the next device wave",
    )
    tv.waves_total = reg.gauge(
        "lodestar_tpu_verifier_waves_total",
        "Total device waves dispatched",
    )
    tv.buckets_dispatched_total = reg.gauge(
        "lodestar_tpu_verifier_buckets_dispatched_total",
        "Total device buckets dispatched",
    )
    tv.wave_sets_total = reg.gauge(
        "lodestar_tpu_verifier_wave_sets_total",
        "Total signature sets carried by device waves",
    )
    tv.last_wave_sets = reg.gauge(
        "lodestar_tpu_verifier_last_wave_sets",
        "Signature sets in the most recent wave",
    )
    tv.last_wave_duration_seconds = reg.gauge(
        "lodestar_tpu_verifier_last_wave_duration_seconds",
        "Dispatch-to-verdict latency of the most recent wave",
    )
    tv.device_time_seconds_total = reg.gauge(
        "lodestar_tpu_verifier_device_time_seconds_total",
        "Cumulative wall time waves spent in flight on the device",
    )
    tv.batch_sigs_success_total = reg.gauge(
        "lodestar_tpu_verifier_batch_sigs_success_total",
        "Signature sets verified successfully in device batches",
    )
    tv.batch_retries_total = reg.gauge(
        "lodestar_tpu_verifier_batch_retries_total",
        "Failed waves re-verified per job/per set",
    )
    # continuous batching (rolling gossip bucket, bls/verifier.py):
    # per-bucket-size and per-path dispatch counters prove trickle
    # traffic coalesces into device-ingest buckets; the latency
    # quantiles track the submit-to-verdict SLO the rolling bucket's
    # deadline flush bounds
    tv.dispatch_by_bucket_total = reg.gauge(
        "lodestar_tpu_verifier_dispatch_by_bucket_total",
        "Device bucket dispatches by padded bucket size",
        label_names=("bucket",),
    )
    tv.dispatch_by_path_total = reg.gauge(
        "lodestar_tpu_verifier_dispatch_by_path_total",
        "Bucket dispatches by path (ingest / host / host_cold)",
        label_names=("path",),
    )
    tv.rolling_flush_total = reg.gauge(
        "lodestar_tpu_verifier_rolling_flush_total",
        "Rolling-bucket flushes by trigger (full / deadline / merged)",
        label_names=("reason",),
    )
    tv.rolling_bucket_sets = reg.gauge(
        "lodestar_tpu_verifier_rolling_bucket_sets",
        "Signature sets currently held by the rolling bucket",
    )
    tv.host_invalid_jobs_total = reg.gauge(
        "lodestar_tpu_verifier_host_invalid_jobs_total",
        "Jobs failed up front by host-path signature pre-validation",
    )
    tv.verify_latency_p50_seconds = reg.gauge(
        "lodestar_tpu_verifier_verify_latency_p50_seconds",
        "p50 submit-to-verdict latency of verify_signature_sets jobs",
    )
    tv.verify_latency_p99_seconds = reg.gauge(
        "lodestar_tpu_verifier_verify_latency_p99_seconds",
        "p99 submit-to-verdict latency of verify_signature_sets jobs",
    )
    tv.same_message_latency_p50_seconds = reg.gauge(
        "lodestar_tpu_verifier_same_message_latency_p50_seconds",
        "p50 submit-to-verdict latency of same-message groups",
    )
    tv.same_message_latency_p99_seconds = reg.gauge(
        "lodestar_tpu_verifier_same_message_latency_p99_seconds",
        "p99 submit-to-verdict latency of same-message groups",
    )

    # -- gossip ingest --------------------------------------------------
    g = SimpleNamespace()
    m.gossip = g
    g.queue_length = reg.gauge(
        "lodestar_gossip_validation_queue_length",
        "Current count of items in the gossip validation queue",
        label_names=("topic",),
    )
    g.queue_dropped_total = reg.counter(
        "lodestar_gossip_validation_queue_dropped_jobs_total",
        "Total gossip jobs dropped for queue overflow",
        label_names=("topic",),
    )
    g.queue_job_time = reg.histogram(
        "lodestar_gossip_validation_queue_job_time_seconds",
        "Time to process a gossip job",
        label_names=("topic",),
    )
    g.queue_wait_time = reg.histogram(
        "lodestar_gossip_validation_queue_job_wait_time_seconds",
        "Queue wait time of a gossip job",
        label_names=("topic",),
        buckets=(0.01, 0.05, 0.1, 0.5, 1, 5),
    )
    g.accept_total = reg.counter(
        "lodestar_gossip_validation_accept_total",
        "Gossip objects accepted",
        label_names=("topic",),
    )
    g.ignore_total = reg.counter(
        "lodestar_gossip_validation_ignore_total",
        "Gossip objects ignored",
        label_names=("topic",),
    )
    g.reject_total = reg.counter(
        "lodestar_gossip_validation_reject_total",
        "Gossip objects rejected",
        label_names=("topic",),
    )

    # -- chain / block import -------------------------------------------
    c = SimpleNamespace()
    m.chain = c
    c.block_import_time = reg.histogram(
        "lodestar_block_import_seconds",
        "Full block import pipeline time",
        buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 5),
    )
    c.state_transition_time = reg.histogram(
        "lodestar_state_transition_seconds",
        "State transition time per block",
        buckets=(0.01, 0.05, 0.1, 0.5, 1, 2),
    )
    c.epoch_transition_time = reg.histogram(
        "lodestar_epoch_transition_seconds",
        "Epoch transition time",
        buckets=(0.05, 0.1, 0.5, 1, 5),
    )
    c.head_slot = reg.gauge(
        "beacon_head_slot", "Slot of the current chain head"
    )
    c.finalized_epoch = reg.gauge(
        "beacon_finalized_epoch", "Current finalized epoch"
    )
    c.current_justified_epoch = reg.gauge(
        "beacon_current_justified_epoch", "Current justified epoch"
    )

    # -- block-import span tracing (metrics/tracing.py bridge) ----------
    t = SimpleNamespace()
    m.tracing = t
    # total import time reuses the chain histogram (the tracer is its
    # one observer — per-slot trace root duration)
    t.import_seconds = c.block_import_time
    t.stage_seconds = reg.histogram(
        "lodestar_block_import_stage_seconds",
        "Per-stage block-import pipeline time"
        " (tracing.BLOCK_IMPORT_STAGES)",
        label_names=("stage",),
        buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2),
    )
    t.span_seconds = reg.histogram(
        "lodestar_tracing_span_seconds",
        "Nested trace spans by name (Tracer.span / child_span)",
        label_names=("name",),
        buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1),
    )
    t.slow_traces_total = reg.counter(
        "lodestar_block_import_slow_traces_total",
        "Block imports at or above the slow-slot threshold"
        " (ring-buffered for the admin debug route)",
    )
    t.trace_buffer_size = reg.gauge(
        "lodestar_block_import_trace_buffer_size",
        "Slow traces currently held in the ring buffer",
    )

    # -- db -------------------------------------------------------------
    d = SimpleNamespace()
    m.db = d
    d.read_req_total = reg.counter(
        "lodestar_db_read_req_total",
        "Total db read requests",
        label_names=("bucket",),
    )
    d.write_req_total = reg.counter(
        "lodestar_db_write_req_total",
        "Total db write requests",
        label_names=("bucket",),
    )

    # -- network / peers (peerManager.ts, metrics/lodestar.ts peers) -----
    n = SimpleNamespace()
    m.network = n
    n.peers = reg.gauge(
        "libp2p_peers", "Number of connected peers"
    )
    n.peers_by_direction = reg.gauge(
        "lodestar_peers_by_direction_count",
        "Connected peers by connection direction",
        label_names=("direction",),
    )
    n.peer_disconnects_total = reg.counter(
        "lodestar_peer_disconnects_total",
        "Total peer disconnections",
        label_names=("reason",),
    )
    n.peers_banned_total = reg.counter(
        "lodestar_peers_banned_total", "Total peers banned by score"
    )
    n.gossip_mesh_peers = reg.gauge(
        "lodestar_gossip_mesh_peers_by_type_count",
        "Gossipsub mesh size per topic",
        label_names=("type",),
    )
    n.gossip_messages_published_total = reg.counter(
        "lodestar_gossip_published_messages_total",
        "Gossip messages published",
        label_names=("topic",),
    )
    n.gossip_messages_received_total = reg.counter(
        "lodestar_gossip_received_messages_total",
        "Gossip messages received",
        label_names=("topic",),
    )
    # gossip mesh health (sampled from GossipNode counters)
    n.gossip_duplicates_total = reg.gauge(
        "lodestar_gossip_duplicates_received_total",
        "Gossip frames dropped as already-seen duplicates",
    )
    n.gossip_mesh_grafts_total = reg.gauge(
        "lodestar_gossip_mesh_grafts_total",
        "Peers grafted into gossip meshes",
    )
    n.gossip_mesh_prunes_total = reg.gauge(
        "lodestar_gossip_mesh_prunes_total",
        "Peers pruned out of gossip meshes",
    )
    n.gossip_forwarded_total = reg.gauge(
        "lodestar_gossip_forwarded_messages_total",
        "Validated gossip messages forwarded to the mesh",
    )
    n.gossip_peer_score = reg.gauge(
        "lodestar_gossip_peer_score",
        "Gossip peer score summary across connected peers",
        label_names=("stat",),
    )
    n.reqresp_outgoing_requests_total = reg.counter(
        "beacon_reqresp_outgoing_requests_total",
        "ReqResp requests sent",
        label_names=("method",),
    )
    n.reqresp_incoming_requests_total = reg.counter(
        "beacon_reqresp_incoming_requests_total",
        "ReqResp requests served",
        label_names=("method",),
    )
    n.reqresp_outgoing_errors_total = reg.counter(
        "beacon_reqresp_outgoing_errors_total",
        "ReqResp requests failed",
        label_names=("method",),
    )

    # -- sync (sync.ts, range.ts, backfill.ts) ---------------------------
    s = SimpleNamespace()
    m.sync = s
    s.status = reg.gauge(
        "lodestar_sync_status",
        "Sync mode: 0 stalled, 1 syncing-finalized, 2 syncing-head, 3 synced",
    )
    s.range_blocks_imported_total = reg.counter(
        "lodestar_sync_range_blocks_imported_total",
        "Blocks imported by range sync",
    )
    s.range_batches_total = reg.counter(
        "lodestar_sync_range_batches_total",
        "Range-sync batches processed",
        label_names=("result",),
    )
    s.unknown_block_requests_total = reg.counter(
        "lodestar_sync_unknown_block_requests_total",
        "UnknownBlockSync fetch attempts",
    )
    s.backfill_blocks_total = reg.counter(
        "lodestar_sync_backfill_blocks_total",
        "Blocks verified and stored by backfill sync",
    )

    # -- regen + state caches (regen/queued.ts, stateCache/) -------------
    r = SimpleNamespace()
    m.regen = r
    r.requests_total = reg.counter(
        "lodestar_regen_queue_requests_total",
        "State regen requests",
        label_names=("caller",),
    )
    r.replays_total = reg.counter(
        "lodestar_regen_replays_total", "State replays executed"
    )
    r.blocks_replayed_total = reg.counter(
        "lodestar_regen_blocks_replayed_total",
        "Blocks re-executed during state regen",
    )
    r.state_cache_hits_total = reg.counter(
        "lodestar_state_cache_hits_total", "Block-state cache hits"
    )
    r.state_cache_misses_total = reg.counter(
        "lodestar_state_cache_misses_total",
        "Block-state cache misses (fell through to replay)",
    )
    r.state_cache_size = reg.gauge(
        "lodestar_state_cache_size", "Cached block states"
    )
    r.checkpoint_cache_size = reg.gauge(
        "lodestar_cp_state_cache_size", "Cached checkpoint states"
    )
    r.queue_length = reg.gauge(
        "lodestar_regen_queue_length",
        "State-regen requests currently queued or replaying",
    )
    r.cp_cache_hits_total = reg.gauge(
        "lodestar_cp_state_cache_hits_total",
        "Checkpoint-state cache hits (memory or reload)",
    )
    r.cp_cache_misses_total = reg.gauge(
        "lodestar_cp_state_cache_misses_total",
        "Checkpoint-state cache misses",
    )
    r.cp_cache_spills_total = reg.gauge(
        "lodestar_cp_state_cache_spills_total",
        "Checkpoint states spilled to disk on memory-bound eviction",
    )
    r.cp_cache_reloads_total = reg.gauge(
        "lodestar_cp_state_cache_reloads_total",
        "Checkpoint states reloaded from the disk spill",
    )

    # -- op pools (opPools/) ---------------------------------------------
    o = SimpleNamespace()
    m.op_pool = o
    o.attestation_pool_size = reg.gauge(
        "lodestar_oppool_attestation_pool_size",
        "Aggregated attestations pooled for block inclusion",
    )
    o.unagg_attestation_pool_size = reg.gauge(
        "lodestar_oppool_unaggregated_attestation_pool_size",
        "Unaggregated attestations pooled per subnet",
    )
    o.sync_committee_message_pool_size = reg.gauge(
        "lodestar_oppool_sync_committee_message_pool_size",
        "Pooled sync-committee message groups",
    )
    o.sync_contribution_pool_size = reg.gauge(
        "lodestar_oppool_sync_contribution_and_proof_pool_size",
        "Pooled sync contributions",
    )
    o.voluntary_exit_pool_size = reg.gauge(
        "lodestar_oppool_voluntary_exit_pool_size",
        "Pooled voluntary exits",
    )
    o.attester_slashing_pool_size = reg.gauge(
        "lodestar_oppool_attester_slashing_pool_size",
        "Pooled attester slashings",
    )
    o.proposer_slashing_pool_size = reg.gauge(
        "lodestar_oppool_proposer_slashing_pool_size",
        "Pooled proposer slashings",
    )
    o.bls_to_execution_change_pool_size = reg.gauge(
        "lodestar_oppool_bls_to_execution_change_pool_size",
        "Pooled BLS-to-execution changes",
    )

    # -- REST api (rest/activeSockets.ts, server metrics) ----------------
    a = SimpleNamespace()
    m.api = a
    a.requests_total = reg.counter(
        "lodestar_api_rest_requests_total",
        "REST api requests",
        label_names=("operation",),
    )
    a.errors_total = reg.counter(
        "lodestar_api_rest_errors_total",
        "REST api error responses",
        label_names=("operation",),
    )
    a.response_time = reg.histogram(
        "lodestar_api_rest_response_time_seconds",
        "REST api handler time",
        label_names=("operation",),
        buckets=(0.001, 0.01, 0.05, 0.25, 1, 5),
    )
    # serving fault domain (api/overload.py, ISSUE 20): sampled from
    # the ServingOverload / ChainEventEmitter ledgers at scrape time
    # via bind_api_collectors — the REST analog of the device
    # executor's shed accounting
    a.sheds_total = reg.gauge(
        "lodestar_api_sheds_total",
        "REST requests refused by admission control, by QoS class "
        "and reason (rate_limited / queue_deadline / brownout / "
        "pool_backlog / sse_subscriber_cap)",
        label_names=("cls", "reason"),
    )
    a.inflight = reg.gauge(
        "lodestar_api_inflight_requests",
        "Admitted REST requests currently holding a concurrency slot",
        label_names=("cls",),
    )
    a.brownout_state = reg.gauge(
        "lodestar_api_brownout_state",
        "Per-class brownout breaker state "
        "(0=closed 1=open 2=half_open)",
        label_names=("cls",),
    )
    a.response_cache_total = reg.gauge(
        "lodestar_api_response_cache_total",
        "Head-keyed response cache outcomes (hit / miss / stale)",
        label_names=("result",),
    )
    a.request_timeouts_total = reg.gauge(
        "lodestar_api_request_timeouts_total",
        "Async-bridge timeouts: loop-side task cancelled, 504 served",
    )
    a.sse_subscribers = reg.gauge(
        "lodestar_api_sse_subscribers",
        "Live SSE event-stream subscribers",
    )
    a.sse_dropped_total = reg.gauge(
        "lodestar_api_sse_dropped_total",
        "SSE frames dropped on full subscriber queues, by topic",
        label_names=("topic",),
    )
    a.sse_evictions_total = reg.gauge(
        "lodestar_api_sse_evictions_total",
        "Slow SSE consumers evicted by the broadcast emitter",
    )

    # -- eth1 / execution (eth1/, execution/) ----------------------------
    e = SimpleNamespace()
    m.execution = e
    e.engine_requests_total = reg.counter(
        "lodestar_execution_engine_http_requests_total",
        "Engine API calls",
        label_names=("method",),
    )
    e.engine_errors_total = reg.counter(
        "lodestar_execution_engine_http_errors_total",
        "Engine API failures",
        label_names=("method",),
    )
    e.eth1_deposits_followed = reg.gauge(
        "lodestar_eth1_deposit_count", "Deposit logs followed"
    )
    e.eth1_blocks_followed = reg.gauge(
        "lodestar_eth1_followed_blocks_count",
        "Eth1 headers in the vote-candidate window",
    )

    # -- fork choice ----------------------------------------------------
    fc = SimpleNamespace()
    m.forkchoice = fc
    fc.nodes = reg.gauge(
        "lodestar_forkchoice_nodes_count",
        "Proto-array node count",
    )
    fc.indices = reg.gauge(
        "lodestar_forkchoice_indices_count",
        "Proto-array index map size",
    )
    fc.find_head_total = reg.counter(
        "lodestar_forkchoice_find_head_total",
        "Times find-head recomputed the best descendant",
    )
    fc.reorg_total = reg.counter(
        "lodestar_forkchoice_reorg_total",
        "Head changes to a non-descendant of the previous head",
        label_names=("depth",),
    )
    fc.votes = reg.gauge(
        "lodestar_forkchoice_validated_attestation_datas",
        "Tracked vote records",
    )

    # -- eth1 / deposits ------------------------------------------------
    e1 = SimpleNamespace()
    m.eth1 = e1
    e1.deposit_tree_size = reg.gauge(
        "lodestar_eth1_deposit_tree_size",
        "Leaves in the deposit tree",
    )
    e1.followed_block_number = reg.gauge(
        "lodestar_eth1_latest_followed_block_number",
        "Latest eth1 block the tracker has processed logs through",
    )
    e1.update_errors_total = reg.counter(
        "lodestar_eth1_update_errors_total",
        "Failed eth1 follow iterations",
    )

    # -- light-client server --------------------------------------------
    lcs = SimpleNamespace()
    m.lightclient_server = lcs
    lcs.best_updates = reg.gauge(
        "lodestar_lightclient_server_best_updates_count",
        "Sync-committee periods with a best LightClientUpdate",
    )
    lcs.latest_finality_slot = reg.gauge(
        "lodestar_lightclient_server_finality_update_slot",
        "Attested slot of the latest finality update",
    )
    lcs.latest_optimistic_slot = reg.gauge(
        "lodestar_lightclient_server_optimistic_update_slot",
        "Attested slot of the latest optimistic update",
    )

    # -- reqresp --------------------------------------------------------
    rr = SimpleNamespace()
    m.reqresp = rr
    rr.outgoing_requests_total = reg.counter(
        "lodestar_reqresp_outgoing_requests_total",
        "Outgoing reqresp requests",
        label_names=("protocol",),
    )
    rr.incoming_requests_total = reg.counter(
        "lodestar_reqresp_incoming_requests_total",
        "Incoming reqresp requests served",
        label_names=("protocol",),
    )
    rr.request_errors_total = reg.counter(
        "lodestar_reqresp_outgoing_errors_total",
        "Outgoing requests that errored",
        label_names=("protocol",),
    )
    rr.rate_limited_total = reg.counter(
        "lodestar_reqresp_rate_limited_total",
        "Inbound requests dropped by the GRCA rate limiter",
    )
    rr.request_time = reg.histogram(
        "lodestar_reqresp_request_time_seconds",
        "Outgoing reqresp round-trip time per protocol",
        label_names=("protocol",),
        buckets=(0.01, 0.05, 0.1, 0.5, 1, 5, 10),
    )

    # -- device / XLA compiler telemetry (metrics/device.py) -------------
    # The execution layer the perf program lives in: stage compiles,
    # retrace storms, the persistent compilation cache, ingest warmup,
    # HBM/live-buffer footprint, host<->device transfer volume, and
    # the on-demand jax.profiler capture. Drives
    # dashboards/lodestar_tpu_device.json.
    dv = SimpleNamespace()
    m.device = dv
    dv.compiles_total = reg.gauge(
        "lodestar_jax_compiles_total",
        "XLA backend compiles by pipeline stage",
        label_names=("stage",),
    )
    dv.compile_seconds_total = reg.gauge(
        "lodestar_jax_compile_seconds_total",
        "Cumulative XLA backend-compile seconds by pipeline stage",
        label_names=("stage",),
    )
    dv.retraces_total = reg.gauge(
        "lodestar_jax_retraces_total",
        "Stage entry points recompiling an argument signature they"
        " already served (retrace storm detector)",
        label_names=("stage",),
    )
    dv.persistent_cache_hits_total = reg.gauge(
        "lodestar_jax_persistent_cache_hits_total",
        "Compiles served from the persistent XLA compilation cache",
    )
    dv.persistent_cache_misses_total = reg.gauge(
        "lodestar_jax_persistent_cache_misses_total",
        "Compiles the persistent XLA compilation cache could not serve",
    )
    dv.persistent_cache_errors_total = reg.gauge(
        "lodestar_jax_persistent_cache_errors_total",
        "Persistent-cache setup/IO failures (cold-cache node detector,"
        " utils/jaxcache.py)",
    )
    dv.cache_retrieval_seconds_total = reg.gauge(
        "lodestar_jax_persistent_cache_retrieval_seconds_total",
        "Cumulative time spent loading compiled artifacts from the"
        " persistent cache",
    )
    dv.warmup_progress = reg.gauge(
        "lodestar_jax_warmup_progress",
        "Ingest warmup progress per pipeline: warm_buckets /"
        " eligible_buckets (bls/kernels.warmup_ingest)",
        label_names=("pipeline",),
    )
    dv.warmup_warm_buckets = reg.gauge(
        "lodestar_jax_warmup_warm_buckets",
        "Ingest bucket sizes whose compile is warm, per pipeline",
        label_names=("pipeline",),
    )
    dv.warmup_eligible_buckets = reg.gauge(
        "lodestar_jax_warmup_eligible_buckets",
        "Ingest-eligible bucket sizes (the warmup target), per pipeline",
        label_names=("pipeline",),
    )
    dv.stage_dispatch_seconds = reg.histogram(
        "lodestar_jax_stage_dispatch_seconds",
        "Wall time of each instrumented stage call (trace + lower +"
        " compile-or-load + enqueue; async dispatch excludes device"
        " execution)",
        label_names=("stage",),
        buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 1, 10, 60, 600),
    )
    dv.stage_device_seconds = reg.histogram(
        "lodestar_jax_stage_device_seconds",
        "Dispatch-to-ready device time per stage (block_until_ready"
        " deltas; only populated with --device-timing sync)",
        label_names=("stage",),
        buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5),
    )
    dv.device_bytes_in_use = reg.gauge(
        "lodestar_jax_device_bytes_in_use",
        "Device memory in use (allocator stats on TPU/GPU; live-buffer"
        " fallback on CPU backends)",
        label_names=("device",),
    )
    dv.device_bytes_limit = reg.gauge(
        "lodestar_jax_device_bytes_limit",
        "Device memory capacity where the backend reports one",
        label_names=("device",),
    )
    dv.live_buffers = reg.gauge(
        "lodestar_jax_live_buffers",
        "Live jax.Array count in the process",
    )
    dv.live_buffer_bytes = reg.gauge(
        "lodestar_jax_live_buffer_bytes",
        "Total bytes held by live jax.Arrays",
    )
    dv.transfer_bytes_total = reg.gauge(
        "lodestar_jax_transfer_bytes_total",
        "Host<->device transfer bytes at the verifier's dispatch and"
        " readback seams",
        label_names=("direction",),
    )
    dv.dispatch_queue_depth = reg.gauge(
        "lodestar_jax_dispatch_queue_depth",
        "Device waves dispatched and not yet finalized"
        " (TpuBlsVerifier.in_flight_waves)",
    )
    dv.pipeline_occupancy = reg.gauge(
        "lodestar_jax_pipeline_occupancy",
        "Fraction of wall time with >=1 device wave in flight"
        " (TpuBlsVerifier overlapped pipeline; 1.0 = device never"
        " idles between buckets)",
    )
    dv.prep_overlap_hidden_seconds_total = reg.gauge(
        "lodestar_jax_prep_overlap_hidden_seconds_total",
        "Host wave-prep seconds spent while another wave was in"
        " flight — the latency the depth>1 pipeline hid",
    )
    dv.donated_buffer_reuse_total = reg.gauge(
        "lodestar_jax_donated_buffer_reuse_total",
        "Input buffers donated to fused stage dispatches"
        " (donate_argnums; armed on TPU only, honest 0 elsewhere)",
    )
    dv.backend_switches_total = reg.gauge(
        "lodestar_jax_backend_switches_total",
        "Limb-backend switches that dropped every cached jit trace"
        " (ops/limbs.set_backend)",
    )
    dv.trace_captures_total = reg.gauge(
        "lodestar_jax_device_trace_captures_total",
        "On-demand jax.profiler captures served by"
        " POST /eth/v1/lodestar/device_trace",
    )
    dv.trace_capture_active = reg.gauge(
        "lodestar_jax_device_trace_active",
        "1 while an on-demand profiler capture is running",
    )

    # -- device auto-tuner (device/autotune.py) --------------------------
    # The feedback loop from the device telemetry above back into the
    # live knobs (limb backend, ingest gate, ladder top, latency
    # budget). Drives the "Auto-tuner" row of
    # dashboards/lodestar_tpu_device.json.
    at = SimpleNamespace()
    m.autotune = at
    at.runs_total = reg.gauge(
        "lodestar_autotune_runs_total",
        "Autotune runs applied (startup + drift re-tunes)",
    )
    at.retunes_total = reg.gauge(
        "lodestar_autotune_retunes_total",
        "Drift-triggered re-tunes applied by the drift monitor",
    )
    at.retunes_blocked_total = reg.gauge(
        "lodestar_autotune_retunes_blocked_total",
        "Drift re-tunes deferred because the verifier was not"
        " quiescent (never mid-wave)",
    )
    at.candidates_measured_total = reg.gauge(
        "lodestar_autotune_candidates_measured_total",
        "Candidate grid points micro-benchmarked",
    )
    at.last_duration_seconds = reg.gauge(
        "lodestar_autotune_last_duration_seconds",
        "Wall time of the most recent tune (persistent cache makes"
        " repeat starts near-free)",
    )
    at.best_sets_per_sec = reg.gauge(
        "lodestar_autotune_best_sets_per_sec",
        "Best probe throughput measured by the most recent tune",
    )
    at.selected = reg.gauge(
        "lodestar_autotune_selected",
        "Numeric knob values the tuner applied (ingest_min_bucket /"
        " ladder_top / latency_budget_ms / msm_window / pipeline_depth)",
        label_names=("knob",),
    )
    at.config_info = reg.gauge(
        "lodestar_autotune_config_info",
        "Active tuned configuration as an info series (value 1;"
        " backend + mode + decision source in labels)",
        label_names=("backend", "mode", "source"),
    )
    at.stage_share = reg.gauge(
        "lodestar_autotune_stage_share",
        "Observed per-stage share of device time in the last drift"
        " window (compare against lodestar_autotune_stage_budget_share)",
        label_names=("stage",),
    )
    at.stage_budget_share = reg.gauge(
        "lodestar_autotune_stage_budget_share",
        "Budgeted per-stage share from the COVERAGE.md device stage"
        " budget table",
        label_names=("stage",),
    )
    at.drift_windows = reg.gauge(
        "lodestar_autotune_drift_windows",
        "Consecutive windows each stage has been outside its budget"
        " share (re-tune fires at the configured streak)",
        label_names=("stage",),
    )

    # -- device executor (device/executor.py) ----------------------------
    # The node-wide QoS scheduler in front of the chip: per-class
    # queue depth / completion / latency, admission-control sheds,
    # deadline-lane deferrals, maintenance aging, and the drain
    # primitive that replaced hold_intake. Drives the "Device
    # executor" row of dashboards/lodestar_tpu_device.json.
    dx = SimpleNamespace()
    m.device_executor = dx
    dx.sheds_total = reg.gauge(
        "lodestar_device_sheds_total",
        "Device work shed by class and reason: executor admission"
        " control (queue_full / drain / closed) plus client-intake"
        " refusals the processor routes through note_shed (overload"
        " is visible here, never a silent drop)",
        label_names=("cls", "reason"),
    )
    dx.queue_depth = reg.gauge(
        "lodestar_device_executor_queue_depth",
        "Jobs queued in the executor per QoS class"
        " (deadline / bulk / maintenance)",
        label_names=("cls",),
    )
    dx.completed_total = reg.gauge(
        "lodestar_device_executor_completed_total",
        "Executor jobs completed per QoS class",
        label_names=("cls",),
    )
    dx.latency_p50 = reg.gauge(
        "lodestar_device_executor_latency_p50_seconds",
        "Median submit-to-completion latency per QoS class",
        label_names=("cls",),
    )
    dx.latency_p99 = reg.gauge(
        "lodestar_device_executor_latency_p99_seconds",
        "p99 submit-to-completion latency per QoS class",
        label_names=("cls",),
    )
    dx.deadline_deferrals_total = reg.gauge(
        "lodestar_device_executor_deadline_deferrals_total",
        "Wave boundaries where queued bulk/maintenance work was"
        " deferred because a deadline client had work pending",
    )
    dx.maintenance_aged_total = reg.gauge(
        "lodestar_device_executor_maintenance_aged_total",
        "Maintenance jobs promoted over queued bulk by the aging"
        " policy (bulk never starves maintenance forever)",
    )
    dx.maintenance_yields_total = reg.gauge(
        "lodestar_device_executor_maintenance_yields_total",
        "maintenance_checkpoint() calls that actually yielded the"
        " device to pending deadline work (warmup between compiles,"
        " autotune between candidate probes)",
    )
    dx.drains_total = reg.gauge(
        "lodestar_device_executor_drains_total",
        "Executor drains that reached device-quiet (the re-tune"
        " window that replaced hold_intake)",
    )
    dx.drains_blocked_total = reg.gauge(
        "lodestar_device_executor_drains_blocked_total",
        "Executor drains that timed out before device-quiet (the"
        " re-tune stays pending; never fires mid-wave)",
    )
    dx.intake_open = reg.gauge(
        "lodestar_device_executor_intake_open",
        "1 while the executor admits work; 0 during a drain or"
        " after close",
    )
    dx.close_timeouts_total = reg.gauge(
        "lodestar_device_executor_close_timeouts_total",
        "close(timeout_s) calls that timed out joining the worker (a"
        " hung running job): close returned anyway with queued"
        " futures cancelled and the hang counted here",
    )

    # -- device health (device/health.py fault domain) -------------------
    # The accelerator's fault domain: the ONLINE/DEGRADED/QUARANTINED/
    # PROBING state machine, wave-watchdog trips, node-wide host
    # failover accounting, and probe-driven reinstatement. Drives the
    # "Device fault domain" rows of
    # dashboards/lodestar_tpu_device.json.
    dh = SimpleNamespace()
    m.device_health = dh
    dh.state = reg.gauge(
        "lodestar_device_health_state",
        "Device health state: 0=online 1=degraded 2=quarantined"
        " 3=probing (device/health.py HEALTH_STATE_INDEX)",
    )
    dh.watchdog_trips_total = reg.gauge(
        "lodestar_device_watchdog_trips_total",
        "Wave-watchdog deadline overruns by QoS class: the dispatch"
        " was abandoned, its future failed with DeviceTimeout, and a"
        " replacement worker took the queues",
        label_names=("cls",),
    )
    dh.failover_dispatches_total = reg.gauge(
        "lodestar_device_failover_dispatches_total",
        "Dispatches served by a host tier because the device path was"
        " quarantined, by client (bls / kzg_msm / kzg_fr) — verdicts"
        " stay bit-identical on the host oracle",
        label_names=("client",),
    )
    dh.probe_total = reg.gauge(
        "lodestar_device_probe_total",
        "Known-answer reinstatement probes by outcome"
        " (success / failure); N consecutive successes reopen the"
        " device path and re-kick warmup",
        label_names=("outcome",),
    )
    dh.faults_total = reg.gauge(
        "lodestar_device_faults_total",
        "Device faults recorded by taxonomy kind (oom / compile /"
        " device_lost / timeout / unknown); programming errors"
        " re-raise at the call site and never land here",
        label_names=("kind",),
    )
    dh.quarantines_total = reg.gauge(
        "lodestar_device_quarantines_total",
        "Times the health breaker opened (node-wide failover to the"
        " host tiers; warmup/autotune suspended)",
    )
    dh.reinstatements_total = reg.gauge(
        "lodestar_device_reinstatements_total",
        "Times a probe sequence reopened the device path (warmup"
        " re-kicked for whatever went cold)",
    )

    # -- kzg / data availability (crypto/kzg.py three-tier MSM) ----------
    # The second device workload: blob-batch KZG verification routes
    # its lincombs through the device Pippenger MSM (ops/msm.py) with
    # host-C and pure-Python fallback tiers. Drives the "KZG / DA"
    # panels of dashboards/lodestar_tpu_device.json.
    kz = SimpleNamespace()
    m.kzg = kz
    kz.msm_dispatch_total = reg.gauge(
        "lodestar_kzg_msm_dispatch_total",
        "KZG MSM lincomb dispatches by backend tier"
        " (device / native / oracle)",
        label_names=("path",),
    )
    kz.msm_device_fallback_total = reg.gauge(
        "lodestar_kzg_msm_device_fallback_total",
        "KZG MSM dispatches that wanted the device tier but fell back"
        " to a host tier (cold rung or device error)",
    )
    kz.fr_dispatch_total = reg.gauge(
        "lodestar_kzg_fr_dispatch_total",
        "KZG batch-verify barycentric evaluations by Fr backend tier"
        " (device limb kernels / python ints)",
        label_names=("path",),
    )
    kz.fr_device_fallback_total = reg.gauge(
        "lodestar_kzg_fr_device_fallback_total",
        "KZG Fr evaluations that wanted the device tier but fell"
        " back to the Python ints (device error)",
    )
    kz.batch_verify_blobs = reg.histogram(
        "lodestar_kzg_batch_verify_blobs",
        "Blobs per verify_blob_kzg_proof_batch call (peak-DA blocks"
        " land at max blobs per block)",
        buckets=(1, 2, 4, 6, 9, 16, 32),
    )

    # -- simulation fault injection (sim/faults.py) ----------------------
    # Delivered-fault accounting for the scenario fleet: every fault an
    # injector actually fired, by kind. Scenario SLOs assert on these
    # so a run whose scheduled fault never fired fails instead of
    # passing vacuously (sim/scenarios.py).
    sf = SimpleNamespace()
    m.sim = sf
    sf.injected_faults_total = reg.gauge(
        "lodestar_sim_injected_faults_total",
        "Faults actually delivered by the sim injectors, by kind"
        " (gossip_drop/delay/duplicate, engine_error, relay_outage,"
        " late_block, equivocating_block, node_kill/restart, ...) —"
        " sampled from the scenario's FaultRegistry at scrape",
        label_names=("kind",),
    )

    # -- clock / event loop (nodeJsMetrics.ts analog) --------------------
    k = SimpleNamespace()
    m.clock = k
    k.slot = reg.gauge("beacon_clock_slot", "Wall-clock slot")
    k.epoch = reg.gauge("beacon_clock_epoch", "Wall-clock epoch")
    k.event_loop_lag = reg.histogram(
        "lodestar_event_loop_lag_seconds",
        "Observed asyncio loop scheduling lag",
        buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1),
    )
    return m

"""The lodestar metric catalog (TPU edition).

Reference analog: beacon-node/src/metrics/metrics/lodestar.ts — in
particular the `lodestar_bls_thread_pool_*` family (:403-506), kept
name-compatible so the reference's Grafana dashboard
(dashboards/lodestar_bls_thread_pool.json) scrapes unchanged. "Worker"
here means the TPU device pipeline behind the verifier service; the
queue metrics expose the verifier's buffered-job queue, which BASELINE
requires to "never back up".
"""

from __future__ import annotations

from types import SimpleNamespace

from .registry import RegistryMetricCreator


def create_lodestar_metrics(reg: RegistryMetricCreator) -> SimpleNamespace:
    m = SimpleNamespace()

    # -- bls verifier service (north star) ------------------------------
    b = SimpleNamespace()
    m.bls_thread_pool = b
    b.success_jobs_signature_sets_count = reg.counter(
        "lodestar_bls_thread_pool_success_jobs_signature_sets_count",
        "Count of total verified signature sets",
    )
    b.error_jobs_signature_sets_count = reg.counter(
        "lodestar_bls_thread_pool_error_jobs_signature_sets_count",
        "Count of total error-ed signature sets",
    )
    b.job_wait_time = reg.histogram(
        "lodestar_bls_thread_pool_queue_job_wait_time_seconds",
        "Time from job added to the queue to starting the job in seconds",
        buckets=(0.01, 0.02, 0.05, 0.1, 0.3, 1),
    )
    b.queue_length = reg.gauge(
        "lodestar_bls_thread_pool_queue_length",
        "Count of total verifier queue length",
    )
    b.jobs_started_total = reg.counter(
        "lodestar_bls_thread_pool_jobs_started_total",
        "Count of total jobs started in the verifier, jobs include 1+ sets",
    )
    b.job_groups_started_total = reg.counter(
        "lodestar_bls_thread_pool_job_groups_started_total",
        "Count of total job groups (device dispatches) started",
    )
    b.sig_sets_started_total = reg.counter(
        "lodestar_bls_thread_pool_sig_sets_started_total",
        "Count of total signature sets started",
    )
    b.batch_retries_total = reg.counter(
        "lodestar_bls_thread_pool_batch_retries_total",
        "Count of total batches that failed and had to be verified again",
    )
    b.batch_sigs_success_total = reg.counter(
        "lodestar_bls_thread_pool_batch_sigs_success_total",
        "Count of signature sets verified successfully in batches",
    )
    b.same_message_jobs_retries_total = reg.counter(
        "lodestar_bls_thread_pool_same_message_jobs_retries_total",
        "Count of same-message jobs that failed and re-verified per set",
    )
    b.same_message_sets_retries_total = reg.counter(
        "lodestar_bls_thread_pool_same_message_sets_retries_total",
        "Count of same-message sets re-verified individually",
    )
    b.time_seconds_sum = reg.counter(
        "lodestar_bls_thread_pool_time_seconds_sum",
        "Total time spent verifying signature sets on the device",
    )
    b.sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_sig_sets_total",
        "Count of total signature sets",
    )
    b.prioritized_sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_prioritized_sig_sets_total",
        "Count of total prioritized signature sets",
    )
    b.batchable_sig_sets_total = reg.counter(
        "lodestar_bls_thread_pool_batchable_sig_sets_total",
        "Count of total batchable signature sets",
    )

    # -- gossip ingest --------------------------------------------------
    g = SimpleNamespace()
    m.gossip = g
    g.queue_length = reg.gauge(
        "lodestar_gossip_validation_queue_length",
        "Current count of items in the gossip validation queue",
        label_names=("topic",),
    )
    g.queue_dropped_total = reg.counter(
        "lodestar_gossip_validation_queue_dropped_jobs_total",
        "Total gossip jobs dropped for queue overflow",
        label_names=("topic",),
    )
    g.queue_job_time = reg.histogram(
        "lodestar_gossip_validation_queue_job_time_seconds",
        "Time to process a gossip job",
        label_names=("topic",),
    )
    g.queue_wait_time = reg.histogram(
        "lodestar_gossip_validation_queue_job_wait_time_seconds",
        "Queue wait time of a gossip job",
        label_names=("topic",),
        buckets=(0.01, 0.05, 0.1, 0.5, 1, 5),
    )
    g.accept_total = reg.counter(
        "lodestar_gossip_validation_accept_total",
        "Gossip objects accepted",
        label_names=("topic",),
    )
    g.ignore_total = reg.counter(
        "lodestar_gossip_validation_ignore_total",
        "Gossip objects ignored",
        label_names=("topic",),
    )
    g.reject_total = reg.counter(
        "lodestar_gossip_validation_reject_total",
        "Gossip objects rejected",
        label_names=("topic",),
    )

    # -- chain / block import -------------------------------------------
    c = SimpleNamespace()
    m.chain = c
    c.block_import_time = reg.histogram(
        "lodestar_block_import_seconds",
        "Full block import pipeline time",
        buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 5),
    )
    c.state_transition_time = reg.histogram(
        "lodestar_state_transition_seconds",
        "State transition time per block",
        buckets=(0.01, 0.05, 0.1, 0.5, 1, 2),
    )
    c.epoch_transition_time = reg.histogram(
        "lodestar_epoch_transition_seconds",
        "Epoch transition time",
        buckets=(0.05, 0.1, 0.5, 1, 5),
    )
    c.head_slot = reg.gauge(
        "beacon_head_slot", "Slot of the current chain head"
    )
    c.finalized_epoch = reg.gauge(
        "beacon_finalized_epoch", "Current finalized epoch"
    )
    c.current_justified_epoch = reg.gauge(
        "beacon_current_justified_epoch", "Current justified epoch"
    )

    # -- db -------------------------------------------------------------
    d = SimpleNamespace()
    m.db = d
    d.read_req_total = reg.counter(
        "lodestar_db_read_req_total",
        "Total db read requests",
        label_names=("bucket",),
    )
    d.write_req_total = reg.counter(
        "lodestar_db_write_req_total",
        "Total db write requests",
        label_names=("bucket",),
    )
    return m

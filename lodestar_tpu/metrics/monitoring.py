"""Remote monitoring: periodic client-stats push.

Reference analog: MonitoringService (monitoring/service.ts:37) —
derives a beaconcha.in-schema JSON snapshot from local metrics and
POSTs it to a remote endpoint on an interval (properties.ts,
clientStats.ts define the schema mapping).
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.error
import urllib.request


CLIENT_NAME = "lodestar-tpu"
CLIENT_VERSION = "0.2.0"


def collect_client_stats(chain=None, verifier_metrics=None, process_start=None):
    """One snapshot in the client-stats (beaconcha.in) schema — the
    general + beaconnode sections the reference emits."""
    now_ms = int(time.time() * 1000)
    general = {
        "version": 1,
        "timestamp": now_ms,
        "process": "beaconnode",
        "client_name": CLIENT_NAME,
        "client_version": CLIENT_VERSION,
        "sync_eth2_fallback_configured": False,
        "sync_eth2_fallback_connected": False,
    }
    if process_start is not None:
        general["cpu_process_seconds_total"] = int(
            time.time() - process_start
        )
    if chain is not None:
        head = chain.fork_choice.proto.get_node(chain.head_root)
        general.update(
            {
                "sync_beacon_head_slot": head.slot if head else 0,
                "sync_eth2_synced": True,
                "slasher_active": False,
            }
        )
    if verifier_metrics is not None:
        general["bls_verifier_sets_verified"] = getattr(
            verifier_metrics, "sig_sets_total", 0
        )
    return general


def collect_validator_stats(chain=None):
    """Validator-process entry (clientStats.ts "validator" schema) fed
    from the ValidatorMonitor's last epoch rollup: remote monitoring
    sees sync-committee participation and inclusion-distance, not just
    node liveness. None when no validators are monitored."""
    vm = getattr(chain, "validator_monitor", None) if chain else None
    if vm is None or not vm.count:
        return None
    stats = {
        "version": 1,
        "timestamp": int(time.time() * 1000),
        "process": "validator",
        "client_name": CLIENT_NAME,
        "client_version": CLIENT_VERSION,
        "validator_total": vm.count,
        "validator_active": vm.count,
    }
    agg = vm.last_epoch_stats
    if agg:
        stats.update(
            {
                "epoch": agg["epoch"],
                "attestation_hits": agg["attestation_hits"],
                "attestation_misses": agg["attestation_misses"],
                "attestation_avg_inclusion_delay": agg[
                    "avg_inclusion_delay"
                ],
                "attestation_max_inclusion_delay": agg[
                    "max_inclusion_delay"
                ],
                "sync_committee_members": agg["sync_members"],
                "sync_committee_hits": agg["sync_hits"],
                "sync_committee_misses": agg["sync_misses"],
                "blocks_proposed": agg["blocks_proposed"],
                "blocks_missed": agg["blocks_missed"],
            }
        )
    return stats


class MonitoringService:
    """Push loop (service.ts:37): POST stats every `interval_s`."""

    def __init__(
        self,
        endpoint: str,
        chain=None,
        interval_s: float = 60.0,
        collect=collect_client_stats,
    ):
        self.endpoint = endpoint
        self.chain = chain
        self.interval_s = interval_s
        self._collect = collect
        self._task = None
        self._start = time.time()
        self.pushes_ok = 0
        self.pushes_failed = 0

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.push_once()
            except Exception:
                # a single bad round must not kill the push task
                self.pushes_failed += 1
            await asyncio.sleep(self.interval_s)

    async def push_once(self) -> bool:
        try:
            batch = [
                self._collect(
                    chain=self.chain, process_start=self._start
                )
            ]
            vstats = collect_validator_stats(self.chain)
            if vstats is not None:
                batch.append(vstats)
            body = json.dumps(batch).encode()
        except Exception:
            self.pushes_failed += 1
            return False

        def _post():
            req = urllib.request.Request(
                self.endpoint,
                data=body,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                return 200 <= resp.status < 300

        try:
            ok = await asyncio.get_event_loop().run_in_executor(None, _post)
        except (urllib.error.URLError, OSError):
            ok = False
        if ok:
            self.pushes_ok += 1
        else:
            self.pushes_failed += 1
        return ok

"""Prometheus scrape endpoint.

Reference analog: getHttpMetricsServer
(beacon-node/src/metrics/server/http.ts:23) — a tiny HTTP server
serving /metrics with the registry exposition. stdlib http.server in a
daemon thread; scrape cost is sampled into its own histogram like the
reference's scrape_time metric.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    def __init__(self, registry, host: str = "127.0.0.1", port: int = 8008):
        self.registry = registry
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.scrape_time = None  # optional Histogram

    def start(self) -> int:
        registry = self.registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                t0 = time.perf_counter()
                body = registry.expose().encode()
                if server.scrape_time is not None:
                    server.scrape_time.observe(time.perf_counter() - t0)
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass  # no stderr spam per scrape

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

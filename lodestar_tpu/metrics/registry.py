"""Prometheus-style metric primitives and registry.

Reference analog: prom-client as used through
`RegistryMetricCreator` (beacon-node/src/metrics/utils/
registryMetricCreator.ts:20) and the typed wrappers in
metrics/utils/{counter,gauge,histogram}.ts. Same semantics: labelled
counters/gauges/histograms, a registry that renders the text
exposition format, and helper sugar (`timer()` context managers on
histograms, child handles per label set).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_label_value(v) -> str:
    # text-format spec: label values escape backslash, double-quote AND
    # newline (a raw \n would terminate the sample line mid-value and
    # corrupt the whole scrape)
    return (
        str(v)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def _escape_help(text) -> str:
    # HELP lines escape backslash and newline only (spec: "escaping")
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    parts = [
        '%s="%s"' % (n, _escape_label_value(v))
        for n, v in zip(names, values)
    ]
    return "{" + ",".join(parts) + "}"


@dataclass
class _MetricBase:
    name: str
    help: str
    label_names: tuple = ()

    def __post_init__(self):
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> tuple:
        try:
            return tuple(labels[n] for n in self.label_names)
        except KeyError as e:
            raise ValueError(
                f"metric {self.name} missing label {e}"
            ) from None


class Counter(_MetricBase):
    """Monotonic counter, optionally labelled."""

    def __post_init__(self):
        super().__post_init__()
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def get(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def collect(self) -> str:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} counter",
        ]
        values = self._values or ({(): 0.0} if not self.label_names else {})
        for k, v in sorted(values.items()):
            lines.append(
                f"{self.name}{_fmt_labels(self.label_names, k)} {_fmt_value(v)}"
            )
        return "\n".join(lines)


class Gauge(_MetricBase):
    """Settable value; supports a collect callback for sampled gauges
    (reference: addCollect on queue-length gauges)."""

    def __post_init__(self):
        super().__post_init__()
        self._values: dict[tuple, float] = {}
        self._collect_fns: list = []

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def get(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def add_collect(self, fn) -> None:
        """fn(gauge) runs at scrape time to sample a live value."""
        self._collect_fns.append(fn)

    def collect(self) -> str:
        for fn in self._collect_fns:
            fn(self)
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        values = self._values or ({(): 0.0} if not self.label_names else {})
        for k, v in sorted(values.items()):
            lines.append(
                f"{self.name}{_fmt_labels(self.label_names, k)} {_fmt_value(v)}"
            )
        return "\n".join(lines)


DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60,
)


class Histogram(_MetricBase):
    """Cumulative-bucket histogram with observe() and timer()."""

    def __init__(self, name, help, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            if k not in self._counts:
                self._counts[k] = [0] * len(self.buckets)
                self._sums[k] = 0.0
                self._totals[k] = 0
            counts = self._counts[k]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[k] += value
            self._totals[k] += 1

    class _Timer:
        def __init__(self, hist, labels):
            self.hist, self.labels = hist, labels

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.hist.observe(
                time.perf_counter() - self.t0, **self.labels
            )
            return False

    def timer(self, **labels) -> "_Timer":
        return Histogram._Timer(self, labels)

    def get_count(self, **labels) -> int:
        return self._totals.get(self._key(labels), 0)

    def get_sum(self, **labels) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def collect(self) -> str:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        keys = self._counts or ({(): [0] * len(self.buckets)} if not self.label_names else {})
        for k in sorted(keys):
            counts = self._counts.get(k, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                lbl = _fmt_labels(
                    self.label_names + ("le",), k + (_fmt_value(b),)
                )
                lines.append(f"{self.name}_bucket{lbl} {counts[i]}")
            lbl_inf = _fmt_labels(self.label_names + ("le",), k + ("+Inf",))
            lines.append(
                f"{self.name}_bucket{lbl_inf} {self._totals.get(k, 0)}"
            )
            base = _fmt_labels(self.label_names, k)
            lines.append(
                f"{self.name}_sum{base} {_fmt_value(self._sums.get(k, 0.0))}"
            )
            lines.append(f"{self.name}_count{base} {self._totals.get(k, 0)}")
        return "\n".join(lines)


class MetricsRegistry:
    """Holds metrics; renders the full text exposition."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str):
        return self._metrics.get(name)

    def expose(self) -> str:
        return (
            "\n".join(m.collect() for m in self._metrics.values()) + "\n"
        )


class RegistryMetricCreator(MetricsRegistry):
    """Factory + registry in one (registryMetricCreator.ts:20)."""

    def counter(self, name, help, label_names=()) -> Counter:
        return self.register(Counter(name, help, tuple(label_names)))

    def gauge(self, name, help, label_names=()) -> Gauge:
        return self.register(Gauge(name, help, tuple(label_names)))

    def histogram(
        self, name, help, label_names=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self.register(
            Histogram(name, help, tuple(label_names), buckets)
        )

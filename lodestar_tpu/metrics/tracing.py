"""Span tracing: nestable sync/async spans over the block-import path.

Reference analog: the reference breaks the import pipeline into timed
sub-histograms scattered through chain/blocks/* (verifyBlock.ts and
importBlock.ts each observe their own `lodestar_block_*_seconds`
series); committee-consensus measurement work (arXiv:2302.00418) shows
the signature path only becomes tunable once per-stage timing is
first-class. This module makes the whole pipeline first-class: one
trace per imported block covering gossip receive -> decode ->
sig-verify -> DA -> engine notify -> state transition -> forkchoice ->
db write, every stage bridged to labelled histograms on the registry,
with a bounded ring buffer of recent slow traces served by the
`/eth/v1/lodestar/block_import_traces` admin route (api/impl.py).

Spans nest: `Tracer.span()` attaches to the innermost open span via a
contextvar, so work dispatched with `asyncio.ensure_future` inside a
stage (the BLS verifier job, bls/verifier.py) lands as a child of that
stage in the trace tree — contextvars copy at task creation, which is
exactly the propagation OpenTelemetry's asyncio integration relies on.

The clock is injectable so tests drive deterministic durations.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time

# The canonical per-slot block-import stages, in pipeline order.
# ImportTrace.finish() guarantees every one is present (0.0 when the
# stage did not run: pre-deneb DA, no engine attached, no db, direct
# non-gossip imports).
BLOCK_IMPORT_STAGES = (
    "gossip_receive",
    "decode",
    "sig_verify",
    "da",
    "engine_notify",
    "state_transition",
    "forkchoice",
    "db_write",
)

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "lodestar_tpu_current_span", default=None
)


def current_span() -> "Span | None":
    """The innermost open span of the calling task, if any."""
    return _current_span.get()


@contextlib.contextmanager
def child_span(name: str):
    """Open a span under the calling task's current span; no-op when
    no trace is active. The zero-coupling hook for deep subsystems
    (the BLS verifier) that must not depend on a tracer instance."""
    parent = _current_span.get()
    if parent is None:
        yield None
        return
    span = Span(name, clock=parent._clock, tracer=parent._tracer)
    span.start(parent)
    try:
        yield span
    finally:
        span.end()


def attach_completed_span(name: str, duration: float) -> "Span | None":
    """Attach an already-finished interval of known duration under the
    calling task's current span; no-op when no trace is active.

    The hook for work measured elsewhere — the BLS verifier learns its
    wave's device time only when the wave finalizes, after the jobs'
    `bls_verify_job` spans are already current, so the device interval
    is backdated ([now - duration, now]) and grafted in. Bridges to
    the span_seconds histogram like any other span."""
    parent = _current_span.get()
    if parent is None or duration <= 0.0:
        return None
    span = Span(name, clock=parent._clock, tracer=parent._tracer)
    span.parent = parent
    parent.children.append(span)
    now = parent._clock()
    span.t0 = now - float(duration)
    span.t1 = now
    if span._tracer is not None:
        span._tracer._on_span_end(span)
    return span


class Span:
    """One timed interval; children nest through the contextvar."""

    __slots__ = (
        "name",
        "t0",
        "t1",
        "children",
        "parent",
        "bridge",
        "_clock",
        "_tracer",
        "_token",
    )

    def __init__(self, name: str, clock=None, tracer=None, bridge=True):
        self.name = name
        self._clock = clock or time.perf_counter
        self._tracer = tracer
        self.bridge = bridge
        self.t0 = None
        self.t1 = None
        self.children: list[Span] = []
        self.parent: Span | None = None
        self._token = None

    def start(self, parent: "Span | None" = None) -> "Span":
        self.t0 = self._clock()
        self.parent = parent
        if parent is not None:
            parent.children.append(self)
        self._token = _current_span.set(self)
        return self

    def end(self) -> float:
        """Close the span; returns its duration (idempotent)."""
        if self.t1 is None:
            self.t1 = self._clock()
            if self._token is not None:
                try:
                    _current_span.reset(self._token)
                except ValueError:
                    # closed from a different context (task finished
                    # elsewhere): the copied context dies with the task
                    pass
                self._token = None
            if self._tracer is not None:
                self._tracer._on_span_end(self)
        return self.duration

    @property
    def duration(self) -> float:
        if self.t0 is None:
            return 0.0
        end = self.t1 if self.t1 is not None else self._clock()
        return max(0.0, end - self.t0)

    def __enter__(self) -> "Span":
        if self.t0 is None:
            self.start(_current_span.get())
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "duration_ms": round(self.duration * 1000.0, 3),
            "children": [c.to_dict() for c in self.children],
        }


class TraceBuffer:
    """Bounded ring of finished trace dicts (oldest evicted first)."""

    def __init__(self, maxlen: int = 64):
        self.maxlen = max(1, int(maxlen))
        self._items: list[dict] = []
        self._lock = threading.Lock()
        self.added_total = 0

    def add(self, item: dict) -> None:
        with self._lock:
            self._items.append(item)
            self.added_total += 1
            while len(self._items) > self.maxlen:
                self._items.pop(0)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._items)

    def __len__(self) -> int:
        return len(self._items)


class ImportTrace:
    """Per-block trace: the eight canonical stages plus any nested
    spans opened while a stage is current.

    Stages accumulate — `add_stage` called twice for one name sums the
    durations (the state-transition stage covers both the pre-state
    slot advance and the block transition, separated in code by the
    signature dispatch)."""

    def __init__(self, tracer: "Tracer", slot: int, t0: float | None = None):
        self.tracer = tracer
        self.slot = int(slot)
        self.root = Span("block_import", clock=tracer.clock)
        # t0 lets the gossip path backdate the trace to frame receipt
        # so gossip_receive/decode count into the total
        self.root.t0 = tracer.clock() if t0 is None else t0
        self.stages: dict[str, float] = {}
        self._stage_spans: dict[str, Span] = {}
        self.error: str | None = None
        self.block_root: bytes | None = None
        self._finished = False

    def begin_stage(self, name: str) -> Span:
        """Open a stage span (contextvar current until `.end()`), so
        spans opened meanwhile — including in tasks spawned now —
        nest under it."""
        # stage durations go to stage_seconds (trace finish), not the
        # generic span_seconds bridge — bridge=False avoids the double
        # observation while still letting children bridge
        span = Span(
            name, clock=self.tracer.clock, tracer=self.tracer,
            bridge=False,
        )
        span.start(self.root)
        self._stage_spans[name] = span
        return span

    def end_stage(self, span: Span) -> None:
        self.add_stage(span.name, span.end())

    @contextlib.contextmanager
    def stage(self, name: str):
        span = self.begin_stage(name)
        try:
            yield span
        finally:
            self.end_stage(span)

    def add_stage(self, name: str, duration: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + max(
            0.0, float(duration)
        )

    def finish(self, block_root: bytes | None = None, error=None) -> dict:
        """Close the trace: default missing canonical stages to 0,
        bridge every stage to the labelled histogram, record the slow
        ones into the ring buffer. Idempotent."""
        if self._finished:
            return {}
        self._finished = True
        if block_root is not None:
            self.block_root = bytes(block_root)
        if error is not None:
            self.error = str(error)
        self.root.end()
        # close any stage span left open by an aborted import so its
        # children stop attributing new work here
        for span in self._stage_spans.values():
            if span.t1 is None:
                self.add_stage(span.name, span.end())
        for name in BLOCK_IMPORT_STAGES:
            self.stages.setdefault(name, 0.0)
        return self.tracer._on_trace_finish(self)

    def to_dict(self) -> dict:
        total = self.root.duration
        stages = []
        for name in BLOCK_IMPORT_STAGES:
            entry = {
                "stage": name,
                "duration_ms": round(
                    self.stages.get(name, 0.0) * 1000.0, 3
                ),
            }
            span = self._stage_spans.get(name)
            if span is not None and span.children:
                entry["children"] = [
                    c.to_dict() for c in span.children
                ]
            stages.append(entry)
        # non-canonical stages (future instrumentation) ride along
        for name, dur in self.stages.items():
            if name not in BLOCK_IMPORT_STAGES:
                stages.append(
                    {
                        "stage": name,
                        "duration_ms": round(dur * 1000.0, 3),
                    }
                )
        return {
            "slot": self.slot,
            "block_root": (
                "0x" + self.block_root.hex()
                if self.block_root is not None
                else None
            ),
            "total_ms": round(total * 1000.0, 3),
            "stages": stages,
            "error": self.error,
            "timestamp": time.time(),
        }


class Tracer:
    """Factory + sink: spans, block-import traces, histogram bridge,
    and the slow-trace ring buffer.

    `metrics` is the `m.tracing` namespace from
    metrics/beacon.create_lodestar_metrics (stage_seconds /
    span_seconds / import_seconds / slow_traces_total) or None for an
    unbridged tracer (unit tests). `clock` is injectable."""

    def __init__(
        self,
        metrics=None,
        clock=None,
        slow_ms: float = 500.0,
        buffer_size: int = 64,
    ):
        self.metrics = metrics
        self.clock = clock or time.perf_counter
        self.slow_ms = float(slow_ms)
        self.buffer = TraceBuffer(buffer_size)

    def span(self, name: str) -> Span:
        """Context manager: a span nested under the caller's current
        span (or a new root)."""
        return Span(name, clock=self.clock, tracer=self)

    def block_import_trace(
        self, slot: int, t0: float | None = None
    ) -> ImportTrace:
        return ImportTrace(self, slot, t0=t0)

    # -- sinks ----------------------------------------------------------

    def _on_span_end(self, span: Span) -> None:
        if self.metrics is not None and span.bridge:
            self.metrics.span_seconds.observe(
                span.duration, name=span.name
            )

    def _on_trace_finish(self, trace: ImportTrace) -> dict:
        total = trace.root.duration
        if self.metrics is not None:
            self.metrics.import_seconds.observe(total)
            for name, dur in trace.stages.items():
                self.metrics.stage_seconds.observe(dur, stage=name)
        item = trace.to_dict()
        if total * 1000.0 >= self.slow_ms or trace.error is not None:
            self.buffer.add(item)
            if self.metrics is not None:
                self.metrics.slow_traces_total.inc()
        return item


class _NullSpan:
    """Inert span for the untraced path."""

    name = "null"
    children = ()

    def end(self) -> float:
        return 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _NullTrace:
    """No-op ImportTrace so instrumented code needs no None guards."""

    _span = _NullSpan()

    def begin_stage(self, name):
        return self._span

    def end_stage(self, span):
        pass

    @contextlib.contextmanager
    def stage(self, name):
        yield self._span

    def add_stage(self, name, duration):
        pass

    def finish(self, block_root=None, error=None):
        return {}


NULL_TRACE = _NullTrace()

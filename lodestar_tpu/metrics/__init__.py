"""Metrics: prometheus-style registry, metric types, HTTP exposition.

Reference analog: packages/beacon-node/src/metrics/ —
`RegistryMetricCreator` (utils/registryMetricCreator.ts:20), the
lodestar metric catalog (metrics/lodestar.ts, bls pool at :403-506),
and the prom-client HTTP server (server/http.ts:23). Implemented
natively (no prom-client dependency): metric objects render the
Prometheus text exposition format themselves.
"""

from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RegistryMetricCreator,
)
from .server import MetricsServer
from .beacon import create_lodestar_metrics
from .tracing import BLOCK_IMPORT_STAGES, Span, TraceBuffer, Tracer

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "RegistryMetricCreator",
    "MetricsServer",
    "create_lodestar_metrics",
    "Tracer",
    "Span",
    "TraceBuffer",
    "BLOCK_IMPORT_STAGES",
]

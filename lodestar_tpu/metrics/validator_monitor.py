"""Per-validator performance monitor.

Reference analog: createValidatorMonitor
(metrics/validatorMonitor.ts:255) — the beacon node tracks registered
local validators' attestation inclusion/correctness and proposals,
exposing per-epoch summaries and prometheus series so operators see
liveness/effectiveness without trusting external explorers.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..params import preset


@dataclass
class _EpochSummary:
    attestation_seen: bool = False
    attestation_inclusion_delay: int | None = None
    attestation_correct_head: bool = False
    attestation_correct_target: bool = False
    blocks_proposed: int = 0


@dataclass
class _MonitoredValidator:
    index: int
    summaries: dict[int, _EpochSummary] = field(default_factory=dict)

    def summary(self, epoch: int) -> _EpochSummary:
        s = self.summaries.get(epoch)
        if s is None:
            s = self.summaries[epoch] = _EpochSummary()
            # bound memory: keep the newest few epochs, but never the
            # one just requested (old-epoch events arrive via reorg /
            # unknown-block imports)
            for old in sorted(self.summaries)[:-4]:
                if old != epoch:
                    del self.summaries[old]
        return s


class ValidatorMonitor:
    def __init__(self, registry=None):
        self.validators: dict[int, _MonitoredValidator] = {}
        if registry is not None:
            reg = registry
            self._m_att_hit = reg.counter(
                "validator_monitor_prev_epoch_on_chain_attester_hit_total",
                "Attestations included on chain for monitored validators",
            )
            self._m_att_miss = reg.counter(
                "validator_monitor_prev_epoch_on_chain_attester_miss_total",
                "Missed attestations for monitored validators",
            )
            self._m_proposals = reg.counter(
                "validator_monitor_beacon_block_total",
                "Blocks proposed by monitored validators",
            )
        else:
            self._m_att_hit = self._m_att_miss = self._m_proposals = None

    def register_local_validator(self, index: int) -> None:
        self.validators.setdefault(index, _MonitoredValidator(index))

    # -- event feeds (called from block import) ---------------------------

    def on_block_imported(self, block) -> None:
        idx = int(block.proposer_index)
        mv = self.validators.get(idx)
        if mv is None:
            return
        epoch = int(block.slot) // preset().SLOTS_PER_EPOCH
        mv.summary(epoch).blocks_proposed += 1
        if self._m_proposals is not None:
            self._m_proposals.inc()

    def on_attestation_included(
        self,
        attester_indices,
        attestation_epoch: int,
        inclusion_delay: int,
        correct_head: bool,
        correct_target: bool,
    ) -> None:
        for idx in attester_indices:
            mv = self.validators.get(int(idx))
            if mv is None:
                continue
            s = mv.summary(attestation_epoch)
            s.attestation_seen = True
            if (
                s.attestation_inclusion_delay is None
                or inclusion_delay < s.attestation_inclusion_delay
            ):
                s.attestation_inclusion_delay = inclusion_delay
            s.attestation_correct_head |= correct_head
            s.attestation_correct_target |= correct_target

    def on_epoch_summary(self, prev_epoch: int) -> dict:
        """Roll up the previous epoch (validatorMonitor's per-epoch
        processing); returns {index: summary} and bumps counters."""
        out = {}
        for idx, mv in self.validators.items():
            s = mv.summary(prev_epoch)
            out[idx] = s
            if self._m_att_hit is not None:
                if s.attestation_seen:
                    self._m_att_hit.inc()
                else:
                    self._m_att_miss.inc()
        return out

"""Per-validator performance monitor.

Reference analog: createValidatorMonitor
(metrics/validatorMonitor.ts:255) — the beacon node tracks registered
local validators across every duty surface: unaggregated/aggregated
attestations seen on gossip, on-chain inclusion (delay + head/target
correctness), block proposals (and misses against the expected
proposer), sync-committee messages and their on-chain inclusion, and
per-epoch balance deltas. Rolled up per epoch into prometheus series
(labeled by validator index, as the reference's `index` label) and a
structured summary operators can log/alert on.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..params import preset

HISTORY_EPOCHS = 4  # summaries kept per validator (reference keeps 3+)


@dataclass
class _EpochSummary:
    # attestations
    attestation_seen_gossip: int = 0  # unaggregated copies seen
    attestation_seen_aggregate: int = 0  # included in seen aggregates
    attestation_included: bool = False
    attestation_inclusion_delay: int | None = None
    attestation_correct_head: bool = False
    attestation_correct_target: bool = False
    # proposals
    blocks_proposed: int = 0
    blocks_missed: int = 0
    # sync committee
    sync_committee_member: bool = False
    sync_messages_seen: int = 0
    sync_signatures_included: int = 0
    # balances (gwei)
    balance: int | None = None
    balance_delta: int | None = None


@dataclass
class _MonitoredValidator:
    index: int
    pubkey: bytes | None = None
    summaries: dict[int, _EpochSummary] = field(default_factory=dict)

    def summary(self, epoch: int) -> _EpochSummary:
        s = self.summaries.get(epoch)
        if s is None:
            s = self.summaries[epoch] = _EpochSummary()
            # bound memory: keep the newest few epochs, but never the
            # one just requested (old-epoch events arrive via reorg /
            # unknown-block imports)
            for old in sorted(self.summaries)[:-HISTORY_EPOCHS]:
                if old != epoch:
                    del self.summaries[old]
        return s


class ValidatorMonitor:
    def __init__(self, registry=None, logger=None):
        self.validators: dict[int, _MonitoredValidator] = {}
        self.log = logger
        # last on_epoch_summary rollup, aggregated across the monitored
        # set — consumed by the client-stats push
        # (metrics/monitoring.py) and the aggregate log line
        self.last_epoch_stats: dict | None = None
        # validator indices whose per-index inclusion-distance series
        # has been emitted (so outage epochs zero it instead of
        # leaving the last healthy value on the dashboard)
        self._incl_indices_emitted: set[int] = set()
        if registry is not None:
            reg = registry
            self._m_att_hit = reg.counter(
                "validator_monitor_prev_epoch_on_chain_attester_hit_total",
                "Attestations included on chain for monitored validators",
            )
            self._m_att_miss = reg.counter(
                "validator_monitor_prev_epoch_on_chain_attester_miss_total",
                "Missed attestations for monitored validators",
            )
            self._m_head_hit = reg.counter(
                "validator_monitor_prev_epoch_on_chain_head_attester_hit_total",
                "Included attestations voting the correct head",
            )
            self._m_target_hit = reg.counter(
                "validator_monitor_prev_epoch_on_chain_target_attester_hit_total",
                "Included attestations voting the correct target",
            )
            self._m_inclusion_delay = reg.histogram(
                "validator_monitor_prev_epoch_attestation_inclusion_delay",
                "Best inclusion delay of monitored attestations",
                buckets=(1, 2, 3, 5, 8, 16, 32),
            )
            self._m_gossip_unagg = reg.counter(
                "validator_monitor_unaggregated_attestation_total",
                "Monitored validators' attestations seen on gossip",
                label_names=("src",),
            )
            self._m_proposals = reg.counter(
                "validator_monitor_beacon_block_total",
                "Blocks proposed by monitored validators",
            )
            self._m_proposals_missed = reg.counter(
                "validator_monitor_validator_block_miss_total",
                "Expected proposals a monitored validator missed",
            )
            self._m_sync_seen = reg.counter(
                "validator_monitor_sync_committee_message_total",
                "Sync-committee messages seen from monitored validators",
            )
            self._m_sync_included = reg.counter(
                "validator_monitor_sync_signature_in_block_total",
                "Monitored sync signatures included in imported blocks",
            )
            self._m_balance = reg.gauge(
                "validator_monitor_balance_gwei",
                "Latest observed balance of a monitored validator",
                label_names=("index",),
            )
            self._m_sync_hit_rate = reg.gauge(
                "validator_monitor_sync_committee_hit_rate",
                "Per-epoch fraction of slots a monitored sync-committee"
                " member's signature landed in imported blocks",
                label_names=("index",),
            )
            # full-depth rollup series (validatorMonitor.ts
            # onceEveryEndOfEpoch family): per-epoch miss counters for
            # head/target votes and sync participation, plus aggregate
            # rates + the inclusion-distance average that makes an
            # inclusion-delay regression (the r5 1.74-slot bug class)
            # alarm-able from one series
            self._m_head_miss = reg.counter(
                "validator_monitor_prev_epoch_on_chain_head_attester_miss_total",
                "Included attestations voting a wrong head",
            )
            self._m_target_miss = reg.counter(
                "validator_monitor_prev_epoch_on_chain_target_attester_miss_total",
                "Included attestations voting a wrong target",
            )
            self._m_sync_hits = reg.counter(
                "validator_monitor_prev_epoch_sync_committee_hits_total",
                "Sync signatures of monitored committee members that"
                " landed in imported blocks",
            )
            self._m_sync_misses = reg.counter(
                "validator_monitor_prev_epoch_sync_committee_misses_total",
                "Slots a monitored sync-committee member's signature"
                " missed imported blocks",
            )
            self._m_att_hit_rate = reg.gauge(
                "validator_monitor_prev_epoch_attestation_hit_rate",
                "Fraction of monitored validators whose attestation was"
                " included for the previous epoch",
            )
            self._m_head_rate = reg.gauge(
                "validator_monitor_prev_epoch_head_correctness_rate",
                "Fraction of included monitored attestations voting the"
                " correct head",
            )
            self._m_target_rate = reg.gauge(
                "validator_monitor_prev_epoch_target_correctness_rate",
                "Fraction of included monitored attestations voting the"
                " correct target",
            )
            self._m_incl_avg = reg.gauge(
                "validator_monitor_prev_epoch_inclusion_distance_avg",
                "Mean best inclusion distance of monitored attestations"
                " for the previous epoch (healthy chain: ~1.0)",
            )
            self._m_incl_by_index = reg.gauge(
                "validator_monitor_prev_epoch_inclusion_distance",
                "Best inclusion distance per monitored validator",
                label_names=("index",),
            )
            self._m_proposal_hit_rate = reg.gauge(
                "validator_monitor_prev_epoch_proposal_hit_rate",
                "Proposals made / proposals expected for monitored"
                " validators in the previous epoch",
            )
            self._m_count = reg.gauge(
                "validator_monitor_validators",
                "Validators registered with the monitor",
            )
            self._m_count.add_collect(
                lambda g: g.set(len(self.validators))
            )
        else:
            self._m_att_hit = self._m_att_miss = None
            self._m_head_hit = self._m_target_hit = None
            self._m_inclusion_delay = None
            self._m_gossip_unagg = None
            self._m_proposals = self._m_proposals_missed = None
            self._m_sync_seen = self._m_sync_included = None
            self._m_balance = None
            self._m_sync_hit_rate = None
            self._m_head_miss = self._m_target_miss = None
            self._m_sync_hits = self._m_sync_misses = None
            self._m_att_hit_rate = None
            self._m_head_rate = self._m_target_rate = None
            self._m_incl_avg = self._m_incl_by_index = None
            self._m_proposal_hit_rate = None
            self._m_count = None

    # -- registration -----------------------------------------------------

    def register_local_validator(
        self, index: int, pubkey: bytes | None = None
    ) -> None:
        mv = self.validators.setdefault(
            index, _MonitoredValidator(index)
        )
        if pubkey is not None:
            mv.pubkey = bytes(pubkey)

    @property
    def count(self) -> int:
        return len(self.validators)

    # -- event feeds ------------------------------------------------------

    def on_block_imported(self, block) -> None:
        idx = int(block.proposer_index)
        mv = self.validators.get(idx)
        if mv is None:
            return
        epoch = int(block.slot) // preset().SLOTS_PER_EPOCH
        mv.summary(epoch).blocks_proposed += 1
        if self._m_proposals is not None:
            self._m_proposals.inc()

    def on_missed_block(self, proposer_index: int, slot: int) -> None:
        """Expected proposer produced nothing for `slot`
        (validatorMonitor registerBeaconBlock miss path)."""
        mv = self.validators.get(int(proposer_index))
        if mv is None:
            return
        epoch = int(slot) // preset().SLOTS_PER_EPOCH
        mv.summary(epoch).blocks_missed += 1
        if self._m_proposals_missed is not None:
            self._m_proposals_missed.inc()

    def on_gossip_attestation(self, validator_index: int, epoch: int) -> None:
        """Unaggregated attestation from a monitored validator seen on
        gossip (registerUnaggregatedAttestation)."""
        mv = self.validators.get(int(validator_index))
        if mv is None:
            return
        mv.summary(int(epoch)).attestation_seen_gossip += 1
        if self._m_gossip_unagg is not None:
            self._m_gossip_unagg.inc(src="gossip")

    def on_aggregate_participation(
        self, attester_indices, epoch: int
    ) -> None:
        """Monitored validators covered by a seen aggregate
        (registerAggregatedAttestation)."""
        for idx in attester_indices:
            mv = self.validators.get(int(idx))
            if mv is not None:
                mv.summary(int(epoch)).attestation_seen_aggregate += 1

    def on_attestation_included(
        self,
        attester_indices,
        attestation_epoch: int,
        inclusion_delay: int,
        correct_head: bool,
        correct_target: bool,
    ) -> None:
        for idx in attester_indices:
            mv = self.validators.get(int(idx))
            if mv is None:
                continue
            s = mv.summary(attestation_epoch)
            s.attestation_included = True
            if (
                s.attestation_inclusion_delay is None
                or inclusion_delay < s.attestation_inclusion_delay
            ):
                s.attestation_inclusion_delay = inclusion_delay
            s.attestation_correct_head |= correct_head
            s.attestation_correct_target |= correct_target

    def on_sync_committee_membership(
        self, member_indices, epoch: int
    ) -> None:
        """Record which monitored validators sit in the current sync
        committee for `epoch`, so the epoch rollup can report a hit
        RATE (included / expected slots) instead of a bare count."""
        for idx in member_indices:
            mv = self.validators.get(int(idx))
            if mv is not None:
                mv.summary(int(epoch)).sync_committee_member = True

    def on_sync_committee_message(
        self, validator_index: int, slot: int
    ) -> None:
        mv = self.validators.get(int(validator_index))
        if mv is None:
            return
        epoch = int(slot) // preset().SLOTS_PER_EPOCH
        mv.summary(epoch).sync_messages_seen += 1
        if self._m_sync_seen is not None:
            self._m_sync_seen.inc()

    def on_sync_aggregate_included(
        self, participant_indices, slot: int
    ) -> None:
        """Monitored validators present in an imported block's
        SyncAggregate (registerSyncAggregateInBlock)."""
        epoch = int(slot) // preset().SLOTS_PER_EPOCH
        for idx in participant_indices:
            mv = self.validators.get(int(idx))
            if mv is None:
                continue
            mv.summary(epoch).sync_signatures_included += 1
            if self._m_sync_included is not None:
                self._m_sync_included.inc()

    def on_balances(self, state, epoch: int) -> None:
        """Record monitored validators' balances for the epoch
        (registerValidatorStatuses balance tracking)."""
        balances = state.balances
        n = len(balances)
        for idx, mv in self.validators.items():
            if idx >= n:
                continue
            bal = int(balances[idx])
            s = mv.summary(epoch)
            prev = mv.summaries.get(epoch - 1)
            s.balance = bal
            if prev is not None and prev.balance is not None:
                s.balance_delta = bal - prev.balance
            if self._m_balance is not None:
                self._m_balance.set(bal, index=str(idx))

    # -- epoch rollup -----------------------------------------------------

    def on_epoch_summary(self, prev_epoch: int) -> dict:
        """Roll up the previous epoch (validatorMonitor's
        onceEveryEndOfEpoch); returns {index: summary}, bumps the
        prometheus series (per-validator + aggregates), records
        `last_epoch_stats` for the client-stats push, and logs one
        structured line per validator plus one aggregate line when a
        logger is attached."""
        slots = preset().SLOTS_PER_EPOCH
        out = {}
        agg = {
            "epoch": prev_epoch,
            "validators": len(self.validators),
            "attestation_hits": 0,
            "attestation_misses": 0,
            "head_hits": 0,
            "target_hits": 0,
            "inclusion_delays": [],
            "sync_members": 0,
            "sync_hits": 0,
            "sync_misses": 0,
            "blocks_proposed": 0,
            "blocks_missed": 0,
        }
        for idx, mv in self.validators.items():
            s = mv.summary(prev_epoch)
            out[idx] = s
            if s.attestation_included:
                agg["attestation_hits"] += 1
                if s.attestation_correct_head:
                    agg["head_hits"] += 1
                if s.attestation_correct_target:
                    agg["target_hits"] += 1
                if s.attestation_inclusion_delay is not None:
                    agg["inclusion_delays"].append(
                        s.attestation_inclusion_delay
                    )
            else:
                agg["attestation_misses"] += 1
            sync_hits = sync_misses = 0
            if s.sync_committee_member:
                agg["sync_members"] += 1
                sync_hits = s.sync_signatures_included
                sync_misses = max(0, slots - sync_hits)
                agg["sync_hits"] += sync_hits
                agg["sync_misses"] += sync_misses
            agg["blocks_proposed"] += s.blocks_proposed
            agg["blocks_missed"] += s.blocks_missed
            if self._m_att_hit is not None:
                if s.attestation_included:
                    self._m_att_hit.inc()
                    if s.attestation_correct_head:
                        self._m_head_hit.inc()
                    else:
                        self._m_head_miss.inc()
                    if s.attestation_correct_target:
                        self._m_target_hit.inc()
                    else:
                        self._m_target_miss.inc()
                    if s.attestation_inclusion_delay is not None:
                        self._m_inclusion_delay.observe(
                            s.attestation_inclusion_delay
                        )
                        self._m_incl_by_index.set(
                            s.attestation_inclusion_delay,
                            index=str(idx),
                        )
                        self._incl_indices_emitted.add(idx)
                else:
                    self._m_att_miss.inc()
                    if idx in self._incl_indices_emitted:
                        # zero a previously-emitted series so a
                        # validator going dark doesn't keep showing
                        # its last healthy distance (per-index analog
                        # of the aggregate-gauge reset below); never-
                        # included validators get no series at all
                        self._m_incl_by_index.set(0, index=str(idx))
                if s.sync_committee_member:
                    self._m_sync_hits.inc(sync_hits)
                    self._m_sync_misses.inc(sync_misses)
            if (
                self._m_sync_hit_rate is not None
                and s.sync_committee_member
            ):
                self._m_sync_hit_rate.set(
                    s.sync_signatures_included / slots,
                    index=str(idx),
                )
            if self.log is not None:
                self.log.info(
                    "validator epoch summary",
                    {
                        "index": idx,
                        "epoch": prev_epoch,
                        "att_included": s.attestation_included,
                        "incl_delay": s.attestation_inclusion_delay,
                        "head_ok": s.attestation_correct_head,
                        "target_ok": s.attestation_correct_target,
                        "gossip_seen": s.attestation_seen_gossip,
                        "agg_seen": s.attestation_seen_aggregate,
                        "proposed": s.blocks_proposed,
                        "missed": s.blocks_missed,
                        "sync_member": s.sync_committee_member,
                        "sync_seen": s.sync_messages_seen,
                        "sync_included": s.sync_signatures_included,
                        "balance": s.balance,
                        "delta": s.balance_delta,
                    },
                )
        delays = agg.pop("inclusion_delays")
        agg["avg_inclusion_delay"] = (
            sum(delays) / len(delays) if delays else None
        )
        agg["max_inclusion_delay"] = max(delays) if delays else None
        self.last_epoch_stats = agg
        hits, misses = agg["attestation_hits"], agg["attestation_misses"]
        if self._m_att_hit_rate is not None and (hits or misses):
            # always re-set the aggregate gauges — a zero-hit epoch
            # (total inclusion outage) must drive them to 0, not leave
            # the previous healthy values alarming nothing
            self._m_att_hit_rate.set(hits / (hits + misses))
            self._m_head_rate.set(
                agg["head_hits"] / hits if hits else 0.0
            )
            self._m_target_rate.set(
                agg["target_hits"] / hits if hits else 0.0
            )
            self._m_incl_avg.set(
                agg["avg_inclusion_delay"] if delays else 0.0
            )
            expected = agg["blocks_proposed"] + agg["blocks_missed"]
            if expected:
                self._m_proposal_hit_rate.set(
                    agg["blocks_proposed"] / expected
                )
        if self.log is not None and self.validators:
            self.log.info("validator monitor epoch rollup", dict(agg))
        return out

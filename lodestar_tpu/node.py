"""BeaconNode: full node assembly.

Reference analog: BeaconNode.init (beacon-node/src/node/nodejs.ts:143)
— wires db -> metrics -> chain -> network processor -> sync -> api ->
metrics server around one asyncio loop, with graceful close in reverse
order; plus the NodeNotifier status line (notifier.ts).
"""

from __future__ import annotations

import asyncio

from .api.impl import BeaconApiImpl
from .api.server import BeaconRestApiServer
from .chain.chain import BeaconChain
from .chain.oppools import AggregatedAttestationPool, OpPool
from .chain.validation import AttestationValidator
from .config.beacon_config import BeaconConfig
from .db.beacon import BeaconDb
from .lightclient import LightClientServer
from .logger import get_logger
from .metrics import (
    MetricsServer,
    RegistryMetricCreator,
    create_lodestar_metrics,
)
from .network.processor import NetworkProcessor
from .network.reqresp import InProcessTransport, ReqResp
from .params import preset
from .sync import RangeSync, SyncServer


class BeaconNode:
    def __init__(
        self,
        cfg,
        types,
        anchor_state_view=None,
        db: BeaconDb | None = None,
        verifier=None,
        api_port: int = 0,
        metrics_port: int | None = None,
        peer_id: str = "node",
        transport: InProcessTransport | None = None,
        logger=None,
    ):
        self.cfg = cfg
        self.types = types
        self.log = logger or get_logger("node")
        self.metrics_registry = RegistryMetricCreator()
        self.metrics = create_lodestar_metrics(self.metrics_registry)
        self.db = db
        self.anchor = anchor_state_view
        self.verifier = verifier
        self.api_port = api_port
        self.metrics_port = metrics_port
        self.peer_id = peer_id
        self.transport = transport or InProcessTransport()
        self.chain: BeaconChain | None = None
        self.api_server = None
        self.metrics_server = None
        self.processor = None
        self.range_sync = None
        self.att_pool = None
        self.op_pool = None

    @classmethod
    async def init(cls, **kwargs) -> "BeaconNode":
        """Assemble and start all services (nodejs.ts:143-300)."""
        node = cls(**kwargs)
        log = node.log
        # chain: resume from db when it has an anchor, else fresh
        if node.anchor is None:
            if node.db is None:
                raise ValueError("need anchor_state_view or a db to resume")
            log.info("resuming chain from db")
            node.chain = await BeaconChain.from_db(
                node.cfg, node.types, node.db, verifier=node.verifier
            )
        else:
            node.chain = BeaconChain(
                node.cfg,
                node.types,
                node.anchor,
                verifier=node.verifier,
                db=node.db,
            )
        gvr = bytes(
            node.chain.head_state.state.genesis_validators_root
        )
        node.beacon_cfg = BeaconConfig(node.cfg, gvr)
        node.chain.light_client_server = LightClientServer(
            node.cfg, node.types, node.chain
        )
        node.att_pool = AggregatedAttestationPool(node.types)
        node.op_pool = OpPool(node.types)
        # gossip ingest
        validator = AttestationValidator(
            node.cfg, node.types, node.chain, node.chain.verifier
        )
        node.attestation_validator = validator
        node.processor = NetworkProcessor(
            node.chain,
            validator,
            node.chain.verifier,
            att_pool=node.att_pool,
            metrics=node.metrics,
        )
        node.processor.start()
        # reqresp server + range sync client
        node.reqresp = ReqResp(node.peer_id, node.transport)
        SyncServer(node.chain, node.beacon_cfg, node.types).register(
            node.reqresp
        )
        node.range_sync = RangeSync(
            node.chain, node.beacon_cfg, node.types, node.reqresp
        )
        # REST API
        impl = BeaconApiImpl(node.cfg, node.types, node.chain, node)
        node.api_server = BeaconRestApiServer(
            impl, port=node.api_port, loop=asyncio.get_event_loop()
        )
        port = node.api_server.start()
        log.info("rest api listening", {"port": port})
        # metrics
        if node.metrics_port is not None:
            node.metrics_server = MetricsServer(
                node.metrics_registry, port=node.metrics_port
            )
            mport = node.metrics_server.start()
            log.info("metrics listening", {"port": mport})
        head = node.chain.fork_choice.proto.get_node(node.chain.head_root)
        log.info(
            "node ready",
            {
                "head_slot": head.slot if head else 0,
                "finalized_epoch": node.chain.finalized_checkpoint.epoch,
                "validators": len(node.chain.head_state.state.validators),
            },
        )
        return node

    def notify_status(self) -> None:
        """NodeNotifier one-liner (notifier.ts)."""
        head = self.chain.fork_choice.proto.get_node(self.chain.head_root)
        self.log.info(
            "status",
            {
                "slot": head.slot if head else 0,
                "head": self.chain.head_root,
                "finalized": self.chain.finalized_checkpoint.epoch,
                "justified": self.chain.justified_checkpoint.epoch,
                "queue": 0
                if self.processor is None
                else len(self.processor.att_queue),
            },
        )
        c = self.metrics.chain
        c.head_slot.set(head.slot if head else 0)
        c.finalized_epoch.set(self.chain.finalized_checkpoint.epoch)
        c.current_justified_epoch.set(
            self.chain.justified_checkpoint.epoch
        )

    async def close(self) -> None:
        """Reverse-order shutdown (graceful SIGINT path)."""
        if self.api_server is not None:
            self.api_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.processor is not None:
            await self.processor.stop()
        if self.chain is not None:
            await self.chain.close()
        if self.db is not None:
            self.db.close()

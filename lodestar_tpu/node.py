"""BeaconNode: full node assembly.

Reference analog: BeaconNode.init (beacon-node/src/node/nodejs.ts:143)
— wires db -> metrics -> chain -> network processor -> sync -> api ->
metrics server around one asyncio loop, with graceful close in reverse
order; plus the NodeNotifier status line (notifier.ts).
"""

from __future__ import annotations

import asyncio

from .api.impl import BeaconApiImpl
from .api.server import BeaconRestApiServer
from .chain.chain import BeaconChain
from .chain.oppools import AggregatedAttestationPool, OpPool
from .chain.validation import AttestationValidator
from .config.beacon_config import BeaconConfig
from .db.beacon import BeaconDb
from .lightclient import LightClientServer
from .logger import get_logger
from .metrics import (
    MetricsServer,
    RegistryMetricCreator,
    create_lodestar_metrics,
)
from .network.processor import NetworkProcessor
from .network.reqresp import InProcessTransport, ReqResp
from .params import ForkSeq, preset
from .sync import RangeSync, SyncServer


class BeaconNode:
    def __init__(
        self,
        cfg,
        types,
        anchor_state_view=None,
        db: BeaconDb | None = None,
        verifier=None,
        api_port: int = 0,
        api_workers: int = 16,
        metrics_port: int | None = None,
        peer_id: str = "node",
        transport: InProcessTransport | None = None,
        logger=None,
        # -- wire stack (None = in-process transport only) --
        tcp_port: int | None = None,
        udp_port: int = 0,
        bootnodes: list[tuple[str, int]] | None = None,
        # isolation is the production default, matching the
        # reference's useWorker=true (network/options.ts:36)
        network_isolated: bool = True,
        # -- execution layer --
        execution_url: str | None = None,
        jwt_secret: bytes | None = None,
        eth1_provider=None,
        builder_url: str | None = None,
        # -- kzg --
        trusted_setup_path: str | None = None,
        # -- monitoring --
        monitoring_endpoint: str | None = None,
        monitored_validators: list[int] | None = None,
        # -- checkpoint sync (initBeaconState.ts) --
        checkpoint_sync_url: str | None = None,
        wss_state_root: bytes | None = None,
        # -- bls verifier warmup (bls/kernels.warmup_ingest) --
        bls_warmup: bool = True,
        # -- block-import span tracing (metrics/tracing.py) --
        # imports slower than this land in the slow-trace ring buffer
        # behind /eth/v1/lodestar/block_import_traces; 0 records every
        # import (debugging / sims)
        trace_slow_slot_ms: float = 500.0,
        trace_buffer_size: int = 64,
        # -- device telemetry (metrics/device.py) --
        # "dispatch" times stage calls + attributes compiles/retraces;
        # "sync" adds block_until_ready deltas (serializes the host
        # against each stage — debugging, not steady-state); "off"
        # reduces every kernel hook to one attribute check
        device_timing: str = "dispatch",
        # POST /eth/v1/lodestar/device_trace capture-length ceiling
        device_trace_max_ms: float = 5000.0,
        device_trace_dir: str | None = None,
        # -- device auto-tuning (device/autotune.py) --
        # "startup": micro-bench the candidate grid once at init and
        # apply the winner through the live setters; "adaptive" adds
        # the drift monitor (budget-share watch + bounded re-tunes);
        # "off" leaves every knob wherever env/CLI put it
        autotune: str = "off",
        autotune_budget_ms: float = 30_000.0,
        autotune_grid: str | None = None,
        autotune_artifact: str | None = "AUTOTUNE.json",
        # -- node-wide device executor (device/executor.py) --
        # QoS-classed scheduling for every accelerator client:
        # deadline (gossip verdicts) ahead of bulk (blob batches)
        # at every wave boundary, maintenance (warmup / autotune)
        # aged so bulk can't starve it, bounded per-class queues
        # shedding bulk/maintenance under overload
        device_executor: bool = True,
        executor_bulk_queue: int = 64,
        executor_maintenance_queue: int = 32,
        executor_aging_ms: float = 2000.0,
        # -- device fault domain (device/health.py) --
        # wave watchdog + error taxonomy + circuit-broken host
        # failover + live probe reinstatement. Watchdog deadlines
        # (multiples of the fused stage budget) arm only on a real
        # accelerator: CPU dispatches legitimately dwarf the budget
        device_health: bool = True,
        health_probe_interval_s: float = 5.0,
    ):
        self.cfg = cfg
        self.types = types
        self.log = logger or get_logger("node")
        self.metrics_registry = RegistryMetricCreator()
        self.metrics = create_lodestar_metrics(self.metrics_registry)
        from .resilience import create_resilience_metrics

        # retry counters + breaker/engine-state gauges on /metrics
        self.resilience_metrics = create_resilience_metrics(
            self.metrics_registry
        )
        self.db = db
        self.anchor = anchor_state_view
        self.verifier = verifier
        self.api_port = api_port
        self.api_workers = api_workers
        self.metrics_port = metrics_port
        self.peer_id = peer_id
        self.transport = transport or InProcessTransport()
        self.chain: BeaconChain | None = None
        self.api_server = None
        self.loop_lag_probe = None
        self.metrics_server = None
        self.processor = None
        self.range_sync = None
        self.att_pool = None
        self.op_pool = None
        self.tcp_port = tcp_port
        self.udp_port = udp_port
        self.network_isolated = network_isolated
        self.bootnodes = bootnodes or []
        self.execution_url = execution_url
        self.jwt_secret = jwt_secret
        self.eth1_provider = eth1_provider
        self.builder_url = builder_url
        self.trusted_setup_path = trusted_setup_path
        self.monitoring_endpoint = monitoring_endpoint
        self.monitored_validators = monitored_validators or []
        self.checkpoint_sync_url = checkpoint_sync_url
        self.wss_state_root = wss_state_root
        self.bls_warmup = bls_warmup
        self.device_trace_max_ms = device_trace_max_ms
        self.device_trace_dir = device_trace_dir
        if autotune not in ("off", "startup", "adaptive"):
            raise ValueError(
                f"autotune mode {autotune!r} not in"
                " ('off', 'startup', 'adaptive')"
            )
        self.autotune_mode = autotune
        self.autotune_budget_ms = autotune_budget_ms
        self.autotune_grid = autotune_grid
        self.autotune_artifact = autotune_artifact
        self.autotuner = None
        self.drift_monitor = None
        self._drift_task: asyncio.Task | None = None
        self.device_executor_enabled = device_executor
        self.executor_bulk_queue = executor_bulk_queue
        self.executor_maintenance_queue = executor_maintenance_queue
        self.executor_aging_ms = executor_aging_ms
        self.executor = None
        self.device_health_enabled = device_health
        self.health_probe_interval_s = health_probe_interval_s
        self.health_tracker = None
        self._probe_task: asyncio.Task | None = None
        # device/compiler telemetry: singleton installed here so the
        # jax.monitoring listeners and the kernels' instrumented stage
        # wrappers route into THIS node's registry
        from .metrics import device as _device_telemetry

        self.device_telemetry = _device_telemetry.install(
            metrics=self.metrics.device, timing=device_timing
        )
        from .metrics import Tracer

        self.tracer = Tracer(
            metrics=self.metrics.tracing,
            slow_ms=trace_slow_slot_ms,
            buffer_size=trace_buffer_size,
        )
        self.metrics.tracing.trace_buffer_size.add_collect(
            lambda g: g.set(len(self.tracer.buffer))
        )
        self.network = None
        self.builder = None
        self.monitoring = None
        self.unknown_block_sync = None
        self.backfill = None
        self.historical = None
        self.reprocess = None
        self.prepare_next_slot = None
        self.checkpoint_states = None
        self.clock = None
        self._altair_topics_on = False
        self._prepare_tasks: set = set()

    def _monitor_slot_tick(self, slot: int) -> None:
        """Validator-monitor wall-clock duties: missed-proposal
        detection for the previous slot, and per-epoch balance capture
        + rollup at epoch starts (validatorMonitor onceEverySlot /
        onceEveryEndOfEpoch)."""
        vm = self.chain.validator_monitor
        if vm is None or not vm.count:
            return
        p = preset()
        try:
            prev = slot - 1
            if prev > 0:
                head = self.chain.fork_choice.proto.get_node(
                    self.chain.head_root
                )
                if head is not None and head.slot < prev:
                    # no canonical block at prev: was one of ours due?
                    # The proposer was recorded when the next-slot
                    # scheduler prepared prev's state (slot-seeded, so
                    # only the advanced state answers exactly)
                    pns = self.prepare_next_slot
                    proposer = (
                        pns.expected_proposers.get(prev)
                        if pns is not None
                        else None
                    )
                    if proposer is not None:
                        vm.on_missed_block(proposer, prev)
            if slot % p.SLOTS_PER_EPOCH == 0 and slot > 0:
                epoch = slot // p.SLOTS_PER_EPOCH
                st = self.chain.head_state.state
                vm.on_balances(st, epoch - 1)
                sc = getattr(st, "current_sync_committee", None)
                if sc is not None:
                    from .statetransition.util import PubkeyIndexView

                    pk2i = PubkeyIndexView(st)
                    members = [
                        i
                        for i in (
                            pk2i.get(bytes(pk)) for pk in sc.pubkeys
                        )
                        if i is not None and i in vm.validators
                    ]
                    if members:
                        vm.on_sync_committee_membership(
                            members, epoch - 1
                        )
                vm.on_epoch_summary(epoch - 1)
        except Exception:
            pass  # monitoring must never break the clock tick

    def _maybe_subscribe_altair_topics(self, epoch: int) -> None:
        """Sync-committee + LC update topics exist from altair
        (gossip/interface.ts:24-69). Called at assembly AND on every
        slot tick so a node started pre-altair subscribes when the
        fork activates, not only at restart."""
        if self._altair_topics_on or self.network is None:
            return
        from .statetransition.slot import fork_at_epoch

        head_seq = self.chain.head_state.fork_seq
        clock_fork = fork_at_epoch(self.cfg, epoch)
        if head_seq >= ForkSeq.altair or ForkSeq[clock_fork] >= ForkSeq.altair:
            self.network.subscribe_sync_committee_topics()
            self.network.subscribe_light_client_topics(
                self.chain.light_client_server
            )
            self._altair_topics_on = True

    @classmethod
    async def init(cls, **kwargs) -> "BeaconNode":
        """Assemble and start all services (nodejs.ts:143-300)."""
        node = cls(**kwargs)
        log = node.log
        # checkpoint sync: fetch the anchor from a trusted endpoint
        # (initBeaconState.ts checkpoint-sync path) — takes precedence
        # over genesis but not over a resumable db
        if (
            node.anchor is None
            and node.checkpoint_sync_url is not None
            and (node.db is None or node.db.meta.get_raw("head_root") is None)
        ):
            import functools

            from .sync.checkpoint import fetch_checkpoint_state

            if node.wss_state_root is None:
                # the endpoint's state is trusted wholesale (fork/clock
                # checks only) — surface the trade-off at runtime, not
                # just in docs (ADVICE r3)
                log.warn(
                    "checkpoint sync WITHOUT --wss-state-root: trusting "
                    "the endpoint's state unverified",
                    {"url": node.checkpoint_sync_url},
                )
            # blocking urllib fetch off the event loop
            node.anchor = await asyncio.get_running_loop().run_in_executor(
                None,
                functools.partial(
                    fetch_checkpoint_state,
                    node.checkpoint_sync_url,
                    node.cfg,
                    node.types,
                    expected_root=node.wss_state_root,
                ),
            )
            log.info(
                "checkpoint sync anchor fetched",
                {
                    "url": node.checkpoint_sync_url,
                    "slot": int(node.anchor.state.slot),
                    "fork": node.anchor.fork,
                },
            )
        # chain: resume from db when it has an anchor, else fresh
        if node.anchor is None:
            if node.db is None:
                raise ValueError("need anchor_state_view or a db to resume")
            log.info("resuming chain from db")
            node.chain = await BeaconChain.from_db(
                node.cfg, node.types, node.db, verifier=node.verifier
            )
        else:
            node.chain = BeaconChain(
                node.cfg,
                node.types,
                node.anchor,
                verifier=node.verifier,
                db=node.db,
            )
        # block-import span tracing: every import now produces the
        # per-stage trace; slow slots are ring-buffered for the admin
        # debug route (api/impl.get_block_import_traces)
        node.chain.tracer = node.tracer
        node.chain.regen.metrics = node.metrics.regen
        # node-wide device executor: the QoS scheduler every
        # accelerator client joins. Constructed BEFORE autotune and
        # warmup so both run as maintenance-class clients from their
        # very first dispatch: the verifier registers its deadline
        # probes, kzg's MSM/Fr device tiers ride the bulk lane, the
        # warmup thread yields between compiles, and the drift
        # monitor's re-tune becomes an executor drain (zero
        # hold_intake calls).
        if node.device_executor_enabled:
            from .bls import kernels as _kernels
            from .crypto import kzg as _kzg_exec
            from .device import executor as _dexec

            node.executor = _dexec.DeviceExecutor(
                queue_bounds={
                    "bulk": node.executor_bulk_queue,
                    "maintenance": node.executor_maintenance_queue,
                },
                aging_ms=node.executor_aging_ms,
            )
            if hasattr(node.chain.verifier, "attach_executor"):
                node.chain.verifier.attach_executor(node.executor)
            _kernels.set_maintenance_gate(
                node.executor.maintenance_checkpoint
            )
            _kzg_exec.set_executor(node.executor)
            _dexec.bind_executor_collectors(
                node.metrics.device_executor, node.executor
            )
            log.info(
                "device executor up",
                {
                    "bulk_queue": node.executor_bulk_queue,
                    "maintenance_queue": (
                        node.executor_maintenance_queue
                    ),
                    "aging_ms": node.executor_aging_ms,
                },
            )
        # device fault domain: one tracker is the single source of
        # truth every accelerator client consults. Wired AFTER the
        # executor (the watchdog + probe ride it) and BEFORE autotune
        # (a tune against a quarantined device must suspend).
        if node.device_health_enabled:
            from .bls import kernels as _kernels_h
            from .crypto import kzg as _kzg_h
            from .device import health as _health

            warm_kick = None
            if node.bls_warmup and hasattr(
                node.chain.verifier, "start_warmup"
            ):
                warm_kick = node.chain.verifier.start_warmup
            node.health_tracker = _health.DeviceHealthTracker(
                warmup_kick=warm_kick,
                logger=get_logger("device-health"),
            )
            import jax as _jax

            on_accel = _jax.default_backend() != "cpu"
            # warmup suspends while quarantined; kzg MSM/Fr ride
            # their host tiers; the verifier's buckets short-circuit
            # to the bit-identical host oracle
            _kernels_h.set_health_gate(
                node.health_tracker.device_allowed
            )
            _kzg_h.set_health_tracker(node.health_tracker)
            if hasattr(node.chain.verifier, "attach_health"):
                node.chain.verifier.attach_health(
                    node.health_tracker,
                    # None adopts the fused-budget deadline; 0 leaves
                    # the wave watchdog unarmed (CPU backends)
                    wave_timeout_s=None if on_accel else 0,
                )
            if node.executor is not None:
                node.executor.set_health_tracker(
                    node.health_tracker,
                    deadlines=(
                        _health.default_watchdog_deadlines()
                        if on_accel
                        else None
                    ),
                )
            node.health_tracker.set_probe(
                _health.make_device_probe(executor=node.executor)
            )
            node._probe_task = asyncio.ensure_future(
                node._health_probe_loop()
            )
            _health.bind_health_collectors(
                node.metrics.device_health, node.health_tracker
            )
            log.info(
                "device fault domain up",
                {
                    "watchdog_armed": on_accel,
                    "probe_interval_s": (
                        node.health_probe_interval_s
                    ),
                },
            )
        # device auto-tuning: close the telemetry->knobs loop. The
        # startup tune micro-benches the candidate grid through the
        # persistent compilation cache and applies the winner via the
        # real setters BEFORE traffic arrives; adaptive mode adds the
        # drift monitor (budget-share watch, quiescence-gated bounded
        # re-tunes). Runs in an executor: the probes block on device
        # work and must not stall the event loop during assembly.
        # Ordered BEFORE warmup so the background warmup compiles the
        # TUNED gate/ladder eligibility, not rungs about to change.
        if node.autotune_mode != "off":
            from .device import autotune as _autotune

            node.autotuner = _autotune.DeviceAutotuner(
                verifier=node.chain.verifier,
                budget_ms=node.autotune_budget_ms,
                grid=_autotune.parse_grid(node.autotune_grid),
                artifact_path=node.autotune_artifact,
                mode=node.autotune_mode,
                logger=get_logger("autotune"),
                executor=node.executor,
                health=node.health_tracker,
            )
            await asyncio.get_running_loop().run_in_executor(
                None, node.autotuner.tune
            )
            if node.autotune_mode == "adaptive":
                node.drift_monitor = _autotune.DriftMonitor(
                    node.autotuner,
                    node.device_telemetry,
                    verifier=node.chain.verifier,
                    executor=node.executor,
                    health=node.health_tracker,
                )
                node._drift_task = asyncio.ensure_future(
                    node.drift_monitor.run()
                )
            _autotune.bind_autotune_collectors(
                node.metrics.autotune,
                node.autotuner,
                monitor=node.drift_monitor,
            )
        # pre-warm the device-ingest compiles (every eligible ladder
        # rung at the — possibly just tuned — gate) on a background
        # thread through the persistent cache so steady-state gossip
        # never pays a cold multi-minute XLA compile; until a size is
        # warm the verifier serves it from the host path
        # (host_fallback_when_cold)
        if node.bls_warmup and hasattr(
            node.chain.verifier, "start_warmup"
        ):
            if node.chain.verifier.start_warmup() is not None:
                log.info("bls ingest warmup started in background")
        gvr = bytes(
            node.chain.head_state.state.genesis_validators_root
        )
        node.beacon_cfg = BeaconConfig(node.cfg, gvr)
        node.chain.light_client_server = LightClientServer(
            node.cfg, node.types, node.chain
        )
        # kzg trusted setup (initCKZG + loadEthereumTrustedSetup,
        # nodejs.ts:162-165): dev setup unless a ceremony file is given
        from .crypto import kzg as _kzg

        if node.trusted_setup_path is not None:
            _kzg.load_trusted_setup(node.trusted_setup_path)
            log.info("trusted setup loaded",
                     {"path": str(node.trusted_setup_path)})
        elif (
            node.cfg.DENEB_FORK_EPOCH != 2**64 - 1
            and node.cfg.CONFIG_NAME not in ("minimal", "dev")
        ):
            # The dev setup's tau derives from a public seed — anyone can
            # forge blob proofs against it. A deneb+ production network
            # must run the ceremony setup (ref always loads it at startup,
            # nodejs.ts:162-165).
            log.warn(
                "INSECURE: no --trusted-setup given on a deneb-enabled "
                "network; falling back to the DEV trusted setup whose tau "
                "is publicly derivable. Blob KZG proofs can be FORGED. "
                "Provide the Ethereum KZG ceremony file for production.",
                {"config": node.cfg.CONFIG_NAME},
            )
        # pre-warm the device MSM rungs the DA path dispatches (blob
        # batch-verify + blob-width lincombs) on a background thread —
        # only where the auto backend will actually route them (TPU);
        # until a rung is warm, lincombs ride the host C Pippenger
        # (counted as lodestar_kzg_msm_device_fallback_total)
        import jax as _jax

        if (
            node.bls_warmup
            and _kzg.msm_backend() in ("auto", "device")
            and _jax.default_backend() == "tpu"
        ):
            import threading

            from .ops import msm as _msm

            threading.Thread(
                target=_msm.warmup_msm,
                name="kzg-msm-warmup",
                daemon=True,
            ).start()
            log.info("kzg msm warmup started in background")
        # execution engine (engine API over JSON-RPC + JWT), wrapped in
        # the resilience layer: classified retries in the RPC client,
        # engine-state tracking + fail-fast breaker around every call
        if node.execution_url is not None:
            from .execution.http import ExecutionEngineHttp, JsonRpcHttpClient
            from .execution.engine import ResilientEngine
            from .resilience import bind_breaker, bind_engine_tracker

            rpc = JsonRpcHttpClient(
                node.execution_url,
                jwt_secret=node.jwt_secret,
                retries=2,
                metrics=node.resilience_metrics,
            )
            engine = ResilientEngine(
                ExecutionEngineHttp(rpc, types=node.types)
            )
            bind_breaker(engine.breaker, node.resilience_metrics)
            bind_engine_tracker(engine.tracker, node.resilience_metrics)
            node.chain.execution_engine = engine
            node.chain.trusted_execution = False
            log.info("execution engine attached",
                     {"url": node.execution_url})
        # eth1 deposit tracker
        if node.eth1_provider is not None:
            from .eth1 import Eth1DepositDataTracker

            node.chain.eth1 = Eth1DepositDataTracker(
                node.cfg, node.types, node.eth1_provider
            )
        # external builder (MEV-boost relay) behind the fault-
        # inspection-window circuit breaker
        if node.builder_url is not None:
            from .execution.builder import ExecutionBuilderHttp
            from .resilience import bind_breaker

            node.builder = ExecutionBuilderHttp(
                node.builder_url, node.types,
                metrics=node.resilience_metrics,
            )
            bind_breaker(
                node.builder.circuit_breaker, node.resilience_metrics
            )
        # chain auxiliaries
        from .chain.historical import HistoricalStateRegen
        from .chain.prepare_next_slot import PrepareNextSlotScheduler
        from .chain.reprocess import ReprocessController
        from .chain.state_cache import CheckpointStateCache
        from .metrics.validator_monitor import ValidatorMonitor

        node.checkpoint_states = CheckpointStateCache(
            node.types, db=node.db
        )
        node.historical = HistoricalStateRegen(node.chain)
        node.reprocess = ReprocessController(node.chain)
        node.prepare_next_slot = PrepareNextSlotScheduler(node.chain)
        vm = ValidatorMonitor(node.metrics_registry)
        for idx in node.monitored_validators:
            vm.register_local_validator(idx)
        node.chain.validator_monitor = vm
        if node.monitoring_endpoint is not None:
            from .metrics.monitoring import MonitoringService

            node.monitoring = MonitoringService(
                node.monitoring_endpoint, chain=node.chain
            )
            node.monitoring.start()
        node.att_pool = AggregatedAttestationPool(node.types)
        node.op_pool = OpPool(node.types)
        from .chain.oppools import (
            AttestationPool,
            SyncCommitteeMessagePool,
            SyncContributionAndProofPool,
        )

        # unaggregated per-subnet pool feeding getAggregatedAttestation
        # (attestationPool.ts:66) + the sync-committee pools
        node.unagg_pool = AttestationPool(node.types)
        node.sync_msg_pool = SyncCommitteeMessagePool(node.types)
        node.contrib_pool = SyncContributionAndProofPool(node.types)
        # gossip ingest
        validator = AttestationValidator(
            node.cfg, node.types, node.chain, node.chain.verifier
        )
        node.attestation_validator = validator
        from .chain.validation import (
            AggregateAndProofValidator,
            GossipBlockValidator,
            SyncCommitteeValidator,
        )

        node.aggregate_validator = AggregateAndProofValidator(
            node.cfg, node.types, node.chain, node.chain.verifier,
            validator,
        )
        node.block_validator = GossipBlockValidator(
            node.cfg, node.types, node.chain, node.chain.verifier
        )
        node.sync_validator = SyncCommitteeValidator(
            node.cfg, node.types, node.chain, node.chain.verifier
        )
        node.processor = NetworkProcessor(
            node.chain,
            validator,
            node.chain.verifier,
            att_pool=node.att_pool,
            metrics=node.metrics,
            aggregate_validator=node.aggregate_validator,
            block_validator=node.block_validator,
            sync_validator=node.sync_validator,
            unagg_pool=node.unagg_pool,
            sync_msg_pool=node.sync_msg_pool,
            contrib_pool=node.contrib_pool,
            executor=node.executor,
        )
        node.processor.start()
        # wall-clock slot driver: the gossip validators' slot-window
        # checks and seen-cache pruning track real time (the reference
        # clock feeds every validator via chain.clock). Without this
        # clock_slot would stay 0 and every gossip block past slot 1
        # would be IGNOREd as a future slot.
        from .chain.clock import Clock

        node.clock = Clock(
            node.cfg, int(node.chain.head_state.state.genesis_time)
        )

        def _on_clock_slot(slot: int) -> None:
            validator.on_slot(slot)
            node.block_validator.on_slot(slot)
            node.sync_validator.on_slot(slot)
            fin = node.chain.fork_choice.finalized_checkpoint
            node.aggregate_validator.prune(int(fin.epoch))
            node.block_validator.prune(
                int(fin.epoch) * preset().SLOTS_PER_EPOCH
            )
            node._maybe_subscribe_altair_topics(
                slot // preset().SLOTS_PER_EPOCH
            )
            node._monitor_slot_tick(slot)
            # precompute next slot's state + payload attributes + epoch
            # shuffling off the critical path (prepareNextSlot.ts).
            # Only when the wall clock tracks the head: a node behind
            # (syncing, or a dev chain whose genesis_time is synthetic)
            # must not advance a clone across thousands of empty slots
            if node.prepare_next_slot is not None:
                head = node.chain.fork_choice.proto.get_node(
                    node.chain.head_root
                )
                if (
                    head is not None
                    and 0 <= slot + 1 - head.slot
                    <= preset().SLOTS_PER_EPOCH
                ):
                    task = asyncio.ensure_future(
                        node.prepare_next_slot.prepare(slot + 1)
                    )
                    node._prepare_tasks.add(task)
                    task.add_done_callback(node._prepare_tasks.discard)

        node.clock.on_slot(_on_clock_slot)
        _on_clock_slot(node.clock.current_slot)
        node.clock.start()
        # wire stack: real TCP/UDP network when a port is requested,
        # else the in-process transport (tests, embedded use)
        if node.tcp_port is not None:
            from .network.facade import Network
            from .sync import BackfillSync, UnknownBlockSync

            node.network = Network(
                node.chain,
                node.beacon_cfg,
                node.types,
                processor=node.processor,
                peer_id=node.peer_id,
                isolated=node.network_isolated,
            )
            node.network.op_pool = node.op_pool
            await node.network.start(
                tcp_port=node.tcp_port, udp_port=node.udp_port
            )
            node._maybe_subscribe_altair_topics(
                node.clock.current_epoch
            )
            for host, port in node.bootnodes:
                node.network.discovery.add_bootnode(host, port)
            node.reqresp = node.network.reqresp
            node.unknown_block_sync = UnknownBlockSync(
                node.chain, node.beacon_cfg, node.network.reqresp
            )
            node.backfill = BackfillSync(
                node.chain,
                node.beacon_cfg,
                node.types,
                node.network.reqresp,
                node.chain.verifier,
            )
            log.info(
                "network listening",
                {
                    "tcp": node.network.host.port,
                    "udp": node.network.discovery.record.udp_port,
                },
            )
        else:
            node.reqresp = ReqResp(node.peer_id, node.transport)
        def _metadata():
            # seq_number bumps on subnet changes (MetadataController,
            # network/metadata.ts:34); attnets = live subscription set
            net = node.network
            if net is None:
                return (0, set(), set())
            return (
                net.metadata_seq,
                set(net.subscribed_subnets),
                set(),
            )

        SyncServer(
            node.chain,
            node.beacon_cfg,
            node.types,
            metadata_fn=_metadata,
        ).register(node.reqresp)
        node.range_sync = RangeSync(
            node.chain, node.beacon_cfg, node.types, node.reqresp
        )
        if node.network is not None:
            # feed every connected peer into the sync components and
            # head-check it (BeaconSync's status-driven mode switch,
            # sync.ts:19): behind a peer -> range sync toward its head
            main_loop = asyncio.get_running_loop()

            def _on_new_peer(peer_id: str) -> None:
                # fires on the network-core thread under isolation —
                # marshal the chain-side bookkeeping to the chain loop
                def _add() -> None:
                    node.range_sync.add_peer(peer_id)
                    node.unknown_block_sync.add_peer(peer_id)
                    node.backfill.add_peer(peer_id)
                    asyncio.ensure_future(node._head_check(peer_id))

                try:
                    running = asyncio.get_running_loop()
                except RuntimeError:
                    running = None
                if running is main_loop:
                    _add()
                else:
                    main_loop.call_soon_threadsafe(_add)

            node.network.peer_manager.on_new_peer = _on_new_peer
            node.network.on_unknown_parent = (
                node.unknown_block_sync.on_unknown_block
            )
        # REST API behind the serving fault domain (api/overload.py):
        # bounded pool + per-class admission, brownout ladder fed by
        # the loop-lag probe, and the head-keyed response cache
        # invalidated straight off the chain event bus
        from .api.overload import (
            LoopLagProbe,
            ServingOverload,
            bind_api_collectors,
        )

        impl = BeaconApiImpl(node.cfg, node.types, node.chain, node)
        overload = ServingOverload(pool_workers=node.api_workers)
        overload.cache.attach(node.chain.events)
        node.api_server = BeaconRestApiServer(
            impl,
            port=node.api_port,
            loop=asyncio.get_event_loop(),
            overload=overload,
            metrics=node.metrics.api,
        )
        node.loop_lag_probe = LoopLagProbe(
            overload.ladder,
            histogram=node.metrics.clock.event_loop_lag,
        )
        node.loop_lag_probe.start(asyncio.get_event_loop())
        bind_api_collectors(
            node.metrics.api, overload, node.chain.events
        )
        port = node.api_server.start()
        log.info("rest api listening", {"port": port})
        # metrics: sampled gauges collect live values at scrape time
        # (reference addCollect pattern, registryMetricCreator.ts)
        mm = node.metrics
        if node.network is not None:
            mm.network.peers.add_collect(
                lambda g: g.set(len(node.network.host.conns))
            )
            mm.network.gossip_mesh_peers.add_collect(
                lambda g: [
                    g.set(len(peers), type=topic.rsplit("/", 2)[-2])
                    for topic, peers in node.network.gossip.mesh.items()
                ]
            )
            # gossip mesh health: duplicates / graft-prune churn /
            # forward volume / peer-score spread, sampled at scrape
            gos = node.network.gossip
            mm.network.gossip_duplicates_total.add_collect(
                lambda g: g.set(gos.duplicates_received)
            )
            mm.network.gossip_mesh_grafts_total.add_collect(
                lambda g: g.set(gos.grafts_total)
            )
            mm.network.gossip_mesh_prunes_total.add_collect(
                lambda g: g.set(gos.prunes_total)
            )
            mm.network.gossip_forwarded_total.add_collect(
                lambda g: g.set(gos.messages_forwarded)
            )

            def _score_stats(g):
                # zero when no peers remain — stale last-known scores
                # would mask a total peer loss on the dashboard
                vals = [sc.value for sc in gos.scores.values()] or [0.0]
                g.set(min(vals), stat="min")
                g.set(max(vals), stat="max")
                g.set(sum(vals) / len(vals), stat="avg")

            mm.network.gossip_peer_score.add_collect(_score_stats)
        mm.regen.state_cache_size.add_collect(
            lambda g: g.set(len(node.chain._states))
        )
        mm.regen.queue_length.add_collect(
            lambda g: g.set(node.chain.regen._pending)
        )
        cps = node.checkpoint_states
        mm.regen.checkpoint_cache_size.add_collect(
            lambda g: g.set(len(cps._mem))
        )
        mm.regen.cp_cache_hits_total.add_collect(
            lambda g: g.set(cps.hits)
        )
        mm.regen.cp_cache_misses_total.add_collect(
            lambda g: g.set(cps.misses)
        )
        mm.regen.cp_cache_spills_total.add_collect(
            lambda g: g.set(cps.spills)
        )
        mm.regen.cp_cache_reloads_total.add_collect(
            lambda g: g.set(cps.reloads)
        )
        mm.op_pool.attestation_pool_size.add_collect(
            lambda g: g.set(
                sum(len(v) for v in node.att_pool._groups.values())
            )
        )
        mm.op_pool.unagg_attestation_pool_size.add_collect(
            lambda g: g.set(
                sum(len(v) for v in node.unagg_pool._groups.values())
            )
        )
        mm.op_pool.sync_committee_message_pool_size.add_collect(
            lambda g: g.set(len(node.sync_msg_pool._groups))
        )
        mm.op_pool.sync_contribution_pool_size.add_collect(
            lambda g: g.set(len(node.contrib_pool._best))
        )
        mm.op_pool.voluntary_exit_pool_size.add_collect(
            lambda g: g.set(len(node.op_pool.voluntary_exits))
        )
        mm.op_pool.attester_slashing_pool_size.add_collect(
            lambda g: g.set(len(node.op_pool.attester_slashings))
        )
        mm.op_pool.proposer_slashing_pool_size.add_collect(
            lambda g: g.set(len(node.op_pool.proposer_slashings))
        )
        mm.op_pool.bls_to_execution_change_pool_size.add_collect(
            lambda g: g.set(len(node.op_pool.bls_changes))
        )
        def _wall_slot(g):
            import time as _t

            gt = node.chain.genesis_time
            sps = node.cfg.SECONDS_PER_SLOT
            slot = max(0, int((_t.time() - gt) // sps))
            g.set(slot)

        # bridge the verifier service's wave stats into the registry
        # (dashboards/lodestar_tpu_bls_verifier.json panels)
        vm = getattr(node.chain.verifier, "metrics", None)
        if vm is not None:
            tv = mm.tpu_verifier
            tv.queue_length.add_collect(
                lambda g: g.set(vm.queue_length)
            )
            tv.waves_total.add_collect(lambda g: g.set(vm.waves))
            tv.buckets_dispatched_total.add_collect(
                lambda g: g.set(vm.buckets_dispatched)
            )
            tv.wave_sets_total.add_collect(
                lambda g: g.set(vm.wave_sets_total)
            )
            tv.last_wave_sets.add_collect(
                lambda g: g.set(vm.last_wave_sets)
            )
            tv.last_wave_duration_seconds.add_collect(
                lambda g: g.set(vm.last_wave_duration_s)
            )
            tv.device_time_seconds_total.add_collect(
                lambda g: g.set(vm.total_device_time_s)
            )
            tv.batch_sigs_success_total.add_collect(
                lambda g: g.set(vm.batch_sigs_success)
            )
            tv.batch_retries_total.add_collect(
                lambda g: g.set(vm.batch_retries)
            )
            tv.dispatch_by_bucket_total.add_collect(
                lambda g: [
                    g.set(c, bucket=str(b))
                    for b, c in sorted(
                        vm.snapshot_dispatch()[0].items()
                    )
                ]
            )
            tv.dispatch_by_path_total.add_collect(
                lambda g: [
                    g.set(c, path=p)
                    for p, c in vm.snapshot_dispatch()[1].items()
                ]
            )
            tv.rolling_flush_total.add_collect(
                lambda g: [
                    g.set(c, reason=r)
                    for r, c in vm.rolling_flushes.items()
                ]
            )
            tv.rolling_bucket_sets.add_collect(
                lambda g: g.set(vm.rolling_sets)
            )
            tv.host_invalid_jobs_total.add_collect(
                lambda g: g.set(vm.host_invalid_jobs)
            )
            tv.verify_latency_p50_seconds.add_collect(
                lambda g: g.set(vm.verify_latency.quantile(0.5))
            )
            tv.verify_latency_p99_seconds.add_collect(
                lambda g: g.set(vm.verify_latency.quantile(0.99))
            )
            tv.same_message_latency_p50_seconds.add_collect(
                lambda g: g.set(
                    vm.same_message_latency.quantile(0.5)
                )
            )
            tv.same_message_latency_p99_seconds.add_collect(
                lambda g: g.set(
                    vm.same_message_latency.quantile(0.99)
                )
            )
        # device / XLA compiler telemetry: compile + cache counters,
        # warmup progress, memory, transfers — sampled at scrape from
        # the telemetry singleton (dashboards/lodestar_tpu_device.json)
        from .metrics import device as _dm

        _dm.bind_collectors(
            mm.device,
            node.device_telemetry,
            verifier=node.chain.verifier,
        )
        # kzg / DA MSM backend counters (crypto/kzg.py three tiers)
        from .crypto import kzg as _kzg_metrics

        _kzg_metrics.bind_kzg_collectors(mm.kzg)
        # fork choice / eth1 / light-client server sampled gauges
        mm.forkchoice.nodes.add_collect(
            lambda g: g.set(len(node.chain.fork_choice.proto.nodes))
        )
        mm.forkchoice.indices.add_collect(
            lambda g: g.set(len(node.chain.fork_choice.proto.indices))
        )
        mm.forkchoice.votes.add_collect(
            lambda g: g.set(len(node.chain.fork_choice.votes))
        )
        node.chain.fork_choice.metrics = mm.forkchoice
        if getattr(node.chain, "eth1", None) is not None:
            node.chain.eth1.metrics = mm.eth1
            mm.eth1.deposit_tree_size.add_collect(
                lambda g: g.set(len(node.chain.eth1.tree))
            )
        lcs = node.chain.light_client_server
        if lcs is not None:
            mm.lightclient_server.best_updates.add_collect(
                lambda g: g.set(len(lcs.best_update_by_period))
            )
            mm.lightclient_server.latest_finality_slot.add_collect(
                lambda g: g.set(
                    int(
                        lcs.latest_finality_update.attested_header.beacon.slot
                    )
                    if lcs.latest_finality_update is not None
                    else 0
                )
            )
            mm.lightclient_server.latest_optimistic_slot.add_collect(
                lambda g: g.set(
                    int(
                        lcs.latest_optimistic_update.attested_header.beacon.slot
                    )
                    if lcs.latest_optimistic_update is not None
                    else 0
                )
            )
        if node.reqresp is not None:
            node.reqresp.metrics = mm.reqresp
        mm.clock.slot.add_collect(_wall_slot)
        mm.clock.epoch.add_collect(
            lambda g: g.set(
                max(
                    0,
                    int(
                        (__import__("time").time() - node.chain.genesis_time)
                        // node.cfg.SECONDS_PER_SLOT
                    ),
                )
                // preset().SLOTS_PER_EPOCH
            )
        )
        if node.metrics_port is not None:
            node.metrics_server = MetricsServer(
                node.metrics_registry, port=node.metrics_port
            )
            mport = node.metrics_server.start()
            log.info("metrics listening", {"port": mport})
        head = node.chain.fork_choice.proto.get_node(node.chain.head_root)
        log.info(
            "node ready",
            {
                "head_slot": head.slot if head else 0,
                "finalized_epoch": node.chain.finalized_checkpoint.epoch,
                "validators": len(node.chain.head_state.state.validators),
            },
        )
        return node

    async def _head_check(self, peer_id: str) -> None:
        """Status handshake a fresh peer; range-sync toward its head
        when we're behind (sync.ts head/range mode switch)."""
        try:
            remote = await self.range_sync.status_handshake(peer_id)
            local = self.chain.fork_choice.proto.get_node(
                self.chain.head_root
            )
            local_slot = local.slot if local else 0
            if int(remote.head_slot) > local_slot:
                await self.range_sync.sync_to(int(remote.head_slot))
        except Exception:
            self.network.peer_manager.penalize(
                peer_id, "reqresp error"
            )

    def notify_status(self) -> None:
        """NodeNotifier one-liner (notifier.ts)."""
        head = self.chain.fork_choice.proto.get_node(self.chain.head_root)
        self.log.info(
            "status",
            {
                "slot": head.slot if head else 0,
                "head": self.chain.head_root,
                "finalized": self.chain.finalized_checkpoint.epoch,
                "justified": self.chain.justified_checkpoint.epoch,
                "queue": 0
                if self.processor is None
                else len(self.processor.att_queue),
            },
        )
        c = self.metrics.chain
        c.head_slot.set(head.slot if head else 0)
        c.finalized_epoch.set(self.chain.finalized_checkpoint.epoch)
        c.current_justified_epoch.set(
            self.chain.justified_checkpoint.epoch
        )

    async def _health_probe_loop(self) -> None:
        """Reinstatement driver: while the device path is closed, run
        the maintenance-class known-answer probe on the tracker's
        backoff schedule. The probe blocks on device work, so it runs
        in an executor thread; the tracker itself decides whether a
        probe is due (breaker backoff), this loop only supplies the
        cadence."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.health_probe_interval_s)
            tracker = self.health_tracker
            if tracker is None or tracker.device_allowed():
                continue
            try:
                await loop.run_in_executor(None, tracker.maybe_probe)
            except Exception as e:  # the loop must outlive any probe
                self.log.warn(
                    "device health probe loop error", {"err": repr(e)}
                )

    async def close(self) -> None:
        """Reverse-order shutdown (graceful SIGINT path)."""
        if self._drift_task is not None:
            self._drift_task.cancel()
            self._drift_task = None
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None
        if self.health_tracker is not None:
            # detach the module-level health hooks (other nodes or
            # tests in this process must not consult a dead tracker)
            from .bls import kernels as _kernels_health
            from .crypto import kzg as _kzg_health

            _kernels_health.set_health_gate(None)
            _kzg_health.set_health_tracker(None)
            self.health_tracker = None
        if self.executor is not None:
            # detach the module-level hooks FIRST (other nodes or
            # tests in this process must not route through a closed
            # executor), then stop the worker — queued bulk futures
            # cancel and their callers ride the host tiers
            from .bls import kernels as _kernels
            from .crypto import kzg as _kzg_exec

            _kernels.set_maintenance_gate(None)
            _kzg_exec.set_executor(None)
            self.executor.close()
            self.executor = None
        if self.clock is not None:
            self.clock.stop()
        if self.monitoring is not None:
            await self.monitoring.stop()
        if getattr(self, "loop_lag_probe", None) is not None:
            self.loop_lag_probe.stop()
            self.loop_lag_probe = None
        if self.api_server is not None:
            self.api_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.network is not None:
            await self.network.stop()
        if self.processor is not None:
            await self.processor.stop()
        if self.chain is not None:
            await self.chain.close()
        if self.db is not None:
            self.db.close()

"""BLS verification subsystem: signature-set model, TPU batch kernels,
and the IBlsVerifier-compatible service (reference: chain/bls/,
SURVEY.md §2.3 — the designated TPU-acceleration target)."""

from .api import SameMessageSet, SignatureSet
from .verifier import OracleBlsVerifier, TpuBlsVerifier

__all__ = [
    "SameMessageSet",
    "SignatureSet",
    "OracleBlsVerifier",
    "TpuBlsVerifier",
]

"""TPU batch-verification kernels for BLS signatures.

Reference analog: the blst entry points Lodestar's BLS pool calls
(SURVEY.md §2.3): `verifyMultipleAggregateSignatures` (random
linear-combination batch verify, chain/bls/maybeBatch.ts:17) and
`aggregateWithRandomness` (same-message aggregation,
chain/bls/multithread/jobItem.ts:73 — the measured main-thread
bottleneck, ~2 min/epoch on CPU). Both become staged device programs:
64-bit random-weighted scalar ladders, a log-depth aggregate tree, a
batched Miller loop, and one shared final exponentiation.

The pipeline is jitted in stages rather than as one program: XLA's
compile time punishes one giant graph superlinearly, the final-exp
stage has batch-independent shape () so it compiles exactly once, and
`jax.jit` caches each stage per input shape. Callers pad to a bucket
size and pass a mask (SURVEY.md §7 hard part 2: padded static shapes
avoid recompiles); the persistent disk cache (utils/jaxcache.py) makes
later processes start warm. All stages broadcast over a leading batch
axis that lodestar_tpu/parallel shards across chips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls import curve as oc
from ..ops import curve as C
from ..ops import fq, pairing, tower
from ..ops import limbs as L
from ..utils import jaxcache

RAND_BITS = 64  # blst's randomness width for batch verify

# The fused kernels compile multi-minute programs; every entry point in
# this module must hit the persistent cache, so enable it at import.
jaxcache.enable()


def _g1_neg_gen(batch=()):
    """-G1 generator as canonical device coords."""
    x, y = oc.g1_neg(oc.G1_GEN)
    return (
        L.normalize(L.const(x, batch)),
        L.normalize(L.const(y, batch)),
    )


def _to_affine(ops, p: C.JacPoint):
    """Jacobian -> affine on device via one batched Fermat inversion.
    Infinity slots yield garbage coords — callers mask them."""
    if ops is C.FQ_OPS:
        zinv = fq.inv(p.z)
        zinv2 = fq.sqr(zinv)
        return fq.mul(p.x, zinv2), fq.mul(p.y, fq.mul(zinv2, zinv))
    zinv = tower.fq2_inv(p.z)
    zinv2 = tower.fq2_sqr(zinv)
    x = tower.fq2_mul(p.x, zinv2)
    y = tower.fq2_mul(p.y, tower.fq2_mul(zinv2, zinv))
    return C.FQ2_OPS.norm(x), C.FQ2_OPS.norm(y)


# --- fused whole-pipeline kernels ------------------------------------------
#
# Round-1 ran the pipeline as six separate jitted stages with eager glue
# (concats, normalize chains, constants) between them. Profiling on the
# real chip showed the staged compute at ~3 ms total but the eager glue
# at ~1 s: every eager op is a separate host->device dispatch over the
# tunnel. Fusing the whole verify into ONE jitted program removes all of
# it; jit caches per (batch-shape, limb-profile) and the persistent
# compile cache (utils/jaxcache.py) keeps later processes warm.


@jax.jit
def _fused_verify_batch(pk: C.JacPoint, hx, hy, sig: C.JacPoint, bits, mask):
    """Device program for run_verify_batch: random-weighted ladders,
    masked G2 aggregation, one batched Miller loop over n+1 pairs, one
    shared final exponentiation. Returns a scalar bool."""
    rpk = C.scalar_mul(C.FQ_OPS, pk.x, pk.y, bits, pk.inf)
    rsig = C.scalar_mul(C.FQ2_OPS, sig.x, sig.y, bits, sig.inf)
    rsig = C.jac_select(
        C.FQ2_OPS, mask, rsig, C.jac_infinity(C.FQ2_OPS, mask.shape)
    )
    s = C.jac_sum(C.FQ2_OPS, rsig)
    s_aff = _to_affine(C.FQ2_OPS, s)
    rpk_aff = _to_affine(C.FQ_OPS, rpk)
    ngx, ngy = _g1_neg_gen((1,))
    px = _cat_fq(rpk_aff[0], ngx)
    py = _cat_fq(rpk_aff[1], ngy)
    qx = _cat_fq2((hx[0], hx[1]), s_aff[0])
    qy = _cat_fq2((hy[0], hy[1]), s_aff[1])
    full_mask = jnp.concatenate([mask, jnp.asarray([True])])
    f = pairing.miller_loop(px, py, qx, qy)
    prod = pairing._fq12_masked_product(f, full_mask)
    return pairing.fq12_is_one(pairing.final_exponentiation(prod))


@jax.jit
def _fused_verify_same_message(
    pk: C.JacPoint, hx, hy, sig: C.JacPoint, bits, mask
):
    """Device program for run_verify_same_message: both MSMs + a
    2-pair pairing check fused (aggregateWithRandomness on device)."""
    rpk = C.scalar_mul(C.FQ_OPS, pk.x, pk.y, bits, pk.inf)
    rsig = C.scalar_mul(C.FQ2_OPS, sig.x, sig.y, bits, sig.inf)
    rpk = C.jac_select(
        C.FQ_OPS, mask, rpk, C.jac_infinity(C.FQ_OPS, mask.shape)
    )
    rsig = C.jac_select(
        C.FQ2_OPS, mask, rsig, C.jac_infinity(C.FQ2_OPS, mask.shape)
    )
    apk_aff = _to_affine(C.FQ_OPS, C.jac_sum(C.FQ_OPS, rpk))
    asig_aff = _to_affine(C.FQ2_OPS, C.jac_sum(C.FQ2_OPS, rsig))
    ngx, ngy = _g1_neg_gen((1,))
    px = _cat_fq(apk_aff[0], ngx)
    py = _cat_fq(apk_aff[1], ngy)
    qx = _cat_fq2((hx[0], hx[1]), asig_aff[0])
    qy = _cat_fq2((hy[0], hy[1]), asig_aff[1])
    pair_mask = jnp.asarray([True, True])
    f = pairing.miller_loop(px, py, qx, qy)
    prod = pairing._fq12_masked_product(f, pair_mask)
    return pairing.fq12_is_one(pairing.final_exponentiation(prod))


# --- host-orchestrated kernels --------------------------------------------


def run_verify_batch(pk: C.JacPoint, h, sig: C.JacPoint, rand_bits, mask) -> bool:
    """Random-linear-combination batch verify of n (pk, msg, sig) sets:

      prod_i e(r_i*pk_i, H_i) * e(-g1, sum_i r_i*sig_i) == 1

    pk: G1 affine batch (n,); h: (hx, hy) G2 Fq2 batches (n,);
    sig: G2 affine batch (n,). rand_bits: (n, RAND_BITS) bool MSB-first,
    r_i != 0. mask: (n,) bool — False slots are padding. Reference:
    blst verifyMultipleAggregateSignatures (maybeBatch.ts:17-44); a
    batch failure means callers retry per set (index.ts:552-563).
    """
    jaxcache.enable()
    if not np.any(np.asarray(mask)):
        return True  # all-padding call is vacuously true
    return bool(
        _fused_verify_batch(pk, h[0], h[1], sig, rand_bits, mask)
    )


def run_verify_same_message(pk: C.JacPoint, h, sig: C.JacPoint, rand_bits, mask) -> bool:
    """Same-message batch verify: n (pk_i, sig_i) on ONE message H:

      e(sum r_i*pk_i, H) * e(-g1, sum r_i*sig_i) == 1

    `aggregateWithRandomness` + one pairing check fused on device — the
    reference computes the MSMs on the main thread (jobItem.ts:60-75),
    its documented scaling limit. h: (hx, hy) with batch shape (1,).
    """
    jaxcache.enable()
    if not np.any(np.asarray(mask)):
        return True
    return bool(
        _fused_verify_same_message(pk, h[0], h[1], sig, rand_bits, mask)
    )


# --- small helpers ---------------------------------------------------------


def _cat_fq(a: L.Lv, b: L.Lv) -> L.Lv:
    a, b = L.normalize(a), L.normalize(b)
    return L.Lv(jnp.concatenate([a.v, b.v], 0), a.lo, a.hi)


def _cat_fq2(a, b):
    return (_cat_fq(a[0], b[0]), _cat_fq(a[1], b[1]))


def bucket_size(n: int, buckets=(4, 8, 16, 32, 64, 128)) -> int:
    """Smallest bucket >= n (reference chunks at <=128 sets/job,
    chain/bls/multithread/index.ts:48-56)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]

"""TPU batch-verification kernels for BLS signatures.

Reference analog: the blst entry points Lodestar's BLS pool calls
(SURVEY.md §2.3): `verifyMultipleAggregateSignatures` (random
linear-combination batch verify, chain/bls/maybeBatch.ts:17) and
`aggregateWithRandomness` (same-message aggregation,
chain/bls/multithread/jobItem.ts:73 — the measured main-thread
bottleneck, ~2 min/epoch on CPU). Both become staged device programs:
64-bit random-weighted scalar ladders, a log-depth aggregate tree, a
batched Miller loop, and one shared final exponentiation.

The pipeline used to be jitted in eight stages (XLA's compile time
punishes one giant graph superlinearly), which kept each compile small
but cost ~2 ms of host dispatch glue per seam — ~16 ms per wave. The
default composition is now the FUSED one (ISSUE 16): each wave runs
≤3 jit programs — prepare (ingest decompress + hash-to-G2 + ladders +
assembly), pairing (miller + product), and final-exp+verdict (batch
shape (), compiled once for every bucket size) — with
`jax.named_scope` regions preserving per-sub-stage attribution inside
the fused graphs and the persistent disk cache (utils/jaxcache.py) +
background warmup amortizing the bigger compiles. The per-stage
programs remain as the differential oracle, the rollback lever
(`LODESTAR_TPU_FUSED_STAGES=0` / `set_fused_stages(False)`), and the
CPU-emulation default (the fused graphs take XLA's single-core
compiler many minutes, so fusion defaults on only for TPU). Callers
pad to a bucket size and pass a mask (SURVEY.md §7 hard part 2: padded
static shapes avoid recompiles). All stages broadcast over a leading
batch axis; the whole-bucket mesh entries (`run_verify_*_mesh`) shard
it so each chip owns whole sub-buckets and the only collective is one
verdict psum.
"""

from __future__ import annotations

import functools
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..crypto.bls import curve as oc
from ..metrics import device as _telemetry
from ..ops import curve as C
from ..ops import fq, pairing, tower
from ..ops import limbs as L
from ..utils import jaxcache

RAND_BITS = 64  # blst's randomness width for batch verify

# The fused kernels compile multi-minute programs; every entry point in
# this module must hit the persistent cache, so enable it at import.
jaxcache.enable()


def _g1_neg_gen(batch=()):
    """-G1 generator as canonical device coords."""
    x, y = oc.g1_neg(oc.G1_GEN)
    return (
        L.normalize(L.const(x, batch)),
        L.normalize(L.const(y, batch)),
    )


def _to_affine(ops, p: C.JacPoint):
    """Jacobian -> affine on device via one batched Fermat inversion.
    Infinity slots yield garbage coords — callers mask them."""
    if ops is C.FQ_OPS:
        zinv = fq.inv(p.z)
        zinv2 = fq.sqr(zinv)
        return fq.mul(p.x, zinv2), fq.mul(p.y, fq.mul(zinv2, zinv))
    zinv = tower.fq2_inv(p.z)
    zinv2 = tower.fq2_sqr(zinv)
    x = tower.fq2_mul(p.x, zinv2)
    y = tower.fq2_mul(p.y, tower.fq2_mul(zinv2, zinv))
    return C.FQ2_OPS.norm(x), C.FQ2_OPS.norm(y)


# Performance state: see COVERAGE.md's "Device stage budget" table for
# the LIVE per-stage numbers (that file is re-measured every round;
# this module's comments are not). The stage split below is the part
# that stays true by construction.
#
# --- staged device programs ------------------------------------------------
#
# Round-1 ran six jitted stages with EAGER glue between them (concats,
# normalizes, constants) — ~1 s of per-op host->device dispatches over
# the tunnel per verify. Round-2 first fused everything into ONE jit,
# which removed the glue but exploded XLA compile time (>10 min on the
# real chip; the driver's bench timed out). Measured per-piece compile
# on the chip: ladders ~9 s, unrolled jac_sum tree ~30 s, Miller loop
# ~94 s, product+final-exp ~357 s. The per-stage split below — all
# glue inside a stage, ~1 ms dispatch between stages — with scan-based
# reductions (curve.jac_sum_scan, pairing._fq12_masked_product,
# pairing._pow_u) keeps each compile bounded, and the final-exp stage
# has batch shape () so it compiles exactly once for every bucket
# size. The DEFAULT composition is now the 3-program fused one (see
# the fused-stage section below): each stage body lives in an
# un-jitted `*_impl` the fused programs, the per-stage programs, and
# the whole-bucket mesh programs all share, so the two compositions
# cannot drift and the per-stage path stays available as the
# differential oracle + rollback (`LODESTAR_TPU_FUSED_STAGES=0`).

# Fused vs per-stage composition knob. The fused programs are the
# bigger compiles the round-2 comment above warns about; on TPU the
# persistent cache + background warmup pay them once per host. On the
# CPU emulation backend XLA's single-core compile of the fused graphs
# runs to many minutes (the slow-compile alarm fires), so the default
# there stays per-stage; an explicit LODESTAR_TPU_FUSED_STAGES=1 (or
# set_fused_stages(True)) still opts in anywhere.
_FUSED_STAGES = (
    os.environ["LODESTAR_TPU_FUSED_STAGES"] != "0"
    if "LODESTAR_TPU_FUSED_STAGES" in os.environ
    else jax.default_backend() == "tpu"
)


def fused_stages_on() -> bool:
    """Whether waves dispatch the fused ≤3-program composition."""
    return _FUSED_STAGES


def set_fused_stages(on: bool) -> None:
    """Flip the fused/per-stage composition at runtime (both program
    families can coexist in the jit caches; no invalidation needed)."""
    global _FUSED_STAGES
    _FUSED_STAGES = bool(on)


def _prepare_batch_impl(pk: C.JacPoint, hx, hy, sig: C.JacPoint, bits, mask):
    """Random-weighted ladders + masked G2 aggregation + batched
    affine conversion + pairing-input assembly (n+1 pairs). On TPU the
    G2 ladder (the expensive one) runs as the fused Pallas kernel
    (ops/pallas_ladder.py: 160 ms vs scan at batch 2048)."""
    if jax.default_backend() == "tpu" and bits.ndim == 2:
        from ..ops import pallas_ladder as PL

        rpk = PL.g1_scalar_mul(pk.x, pk.y, bits, pk.inf)
        rsig = PL.g2_scalar_mul(sig.x, sig.y, bits, sig.inf)
    else:
        rpk = C.scalar_mul(C.FQ_OPS, pk.x, pk.y, bits, pk.inf)
        rsig = C.scalar_mul(C.FQ2_OPS, sig.x, sig.y, bits, sig.inf)
    rsig = C.jac_select(
        C.FQ2_OPS, mask, rsig, C.jac_infinity(C.FQ2_OPS, mask.shape)
    )
    if jax.default_backend() == "tpu" and bits.ndim == 2:
        from ..ops import pallas_pairing as PP

        s = PP.g2_sum(rsig)
    else:
        s = C.jac_sum_scan(C.FQ2_OPS, rsig)
    s_aff = _to_affine(C.FQ2_OPS, s)
    rpk_aff = _to_affine(C.FQ_OPS, rpk)
    ngx, ngy = _g1_neg_gen((1,))
    px = _cat_fq(rpk_aff[0], ngx)
    py = _cat_fq(rpk_aff[1], ngy)
    qx = _cat_fq2((hx[0], hx[1]), s_aff[0])
    qy = _cat_fq2((hy[0], hy[1]), s_aff[1])
    full_mask = jnp.concatenate([mask, jnp.asarray([True])])
    return px, py, qx, qy, full_mask


_stage_prepare_batch = jax.jit(_prepare_batch_impl)


# Device ingest is gated by bucket size: each ingest stage is a
# multi-minute XLA compile per bucket size, so compiling it for the
# tiny 4..128 retry buckets would multiply warmup cost for no
# throughput (small buckets are host-prep-affordable: 128 sets x
# ~2.5 ms). The gate is a KNOB (LODESTAR_TPU_INGEST_MIN_BUCKET /
# set_ingest_min_bucket): the default admits the mid {256, 512}
# buckets the verifier's rolling gossip accumulator flushes, whose
# compiles warmup_ingest() pre-warms in the background through the
# persistent cache (utils/jaxcache.py). Tests lower it further to
# exercise the device path on small CPU batches.
INGEST_MIN_BUCKET = int(
    os.environ.get("LODESTAR_TPU_INGEST_MIN_BUCKET", 256)
)


def ingest_min_bucket() -> int:
    """The live device-ingest gate (module attr so tests can patch)."""
    return INGEST_MIN_BUCKET


def set_ingest_min_bucket(n: int, rewarm: bool = True) -> None:
    """Move the device-ingest gate at runtime.

    LOWERING the gate makes rungs eligible whose ingest pipelines were
    never compiled: warmup_progress() recomputes eligibility from the
    live gate at every scrape (so the `lodestar_jax_warmup_*` gauges
    drop honestly instead of reporting the old, fully-warm set), and —
    when a warmup ran in this process — the newly eligible COLD rungs
    are re-warmed on the background thread, otherwise a cold-fallback
    verifier would route them host_cold forever (nothing else marks a
    size warm without a live ingest dispatch, which the fallback
    prevents). rewarm=False skips the kick (tests, tools that manage
    warmup themselves)."""
    global INGEST_MIN_BUCKET
    old = INGEST_MIN_BUCKET
    INGEST_MIN_BUCKET = int(n)
    if not rewarm or INGEST_MIN_BUCKET >= old:
        return
    if not _WARMUP_STARTED:
        # no warmup policy in this process (bench/test/mesh node):
        # kicking multi-minute compiles behind a setter would be rude
        return
    newly = tuple(
        b
        for b in default_warmup_sizes(INGEST_MIN_BUCKET)
        if b < old and not ingest_is_warm(b)
    )
    if newly:
        warmup_ingest(newly)


def _g2_sqrt_impl(sig_x, sig_sign):
    """Ingest sub-stage 1: y from the curve equation + QR flag + spec
    sign selection (shared impl: ops/ingest.g2_sqrt_with_sign). Split
    from the subgroup check so each per-stage compiled graph stays
    small (compile time is superlinear in op count — an early fused
    ingest stage compiled >58 min on the chip; the fused composition
    below re-pays that once through the persistent cache)."""
    from ..ops import ingest

    return ingest.g2_sqrt_with_sign(sig_x, sig_sign)


def _g2_subgroup_impl(x, y, is_qr, mask):
    """Ingest sub-stage 2: psi subgroup check; returns the point and
    the combined validity conjunction (padding auto-valid)."""
    from ..ops import ingest

    q = C.jac_from_affine(C.FQ2_OPS, x, y)
    valid = jnp.logical_and(
        is_qr, ingest.g2_in_subgroup(q, mask.shape)
    )
    return q, jnp.all(jnp.logical_or(valid, ~mask))


def _g2_decompress_impl(sig_x, sig_sign, mask):
    x, y, is_qr = _g2_sqrt_impl(sig_x, sig_sign)
    return _g2_subgroup_impl(x, y, is_qr, mask)


def _sswu_iso_impl(u0, u1):
    """Ingest sub-stage 3: both SSWU maps + isogeny + point add
    (shared impl: ops/ingest.sswu_iso_sum)."""
    from ..ops import ingest

    return ingest.sswu_iso_sum(u0, u1)


def _cofactor_impl(s, mask):
    """Ingest sub-stage 4: psi cofactor clearing + affine conversion."""
    from ..ops import ingest

    h = ingest.g2_clear_cofactor(s, mask.shape)
    return _to_affine(C.FQ2_OPS, h)


def _hash_to_g2_impl(u0, u1, mask):
    return _cofactor_impl(_sswu_iso_impl(u0, u1), mask)


_stage_g2_sqrt = jax.jit(_g2_sqrt_impl)
_stage_g2_subgroup = jax.jit(_g2_subgroup_impl)
_stage_sswu_iso = jax.jit(_sswu_iso_impl)
_stage_cofactor = jax.jit(_cofactor_impl)


def _stage_g2_decompress(sig_x, sig_sign, mask):
    x, y, is_qr = _stage_g2_sqrt(sig_x, sig_sign)
    return _stage_g2_subgroup(x, y, is_qr, mask)


def _stage_hash_to_g2(u0, u1, mask):
    return _stage_cofactor(_stage_sswu_iso(u0, u1), mask)


@jax.jit
def _stage_final_with_valid(prod, all_valid):
    """Final exponentiation AND the ingest validity conjunction.
    Calls the UNINSTRUMENTED final-exp impl: this body runs at trace
    time inside its own jit, and routing it through the telemetry
    wrapper would record the tracer's call as a dispatch and poison
    the retrace detector's seen-signature set for stage 'final'."""
    return jnp.logical_and(_final_expo_impl(prod), all_valid)


def run_verify_batch_ingest_async(
    pk: C.JacPoint, sig_x, sig_sign, u0, u1, rand_bits, mask
):
    """Batch verify with device-side ingestion; returns the device ()
    bool WITHOUT readback (see run_verify_batch_async). Default: the
    fused 3-program composition (prepare / pairing / final). With
    fused stages off, composes the per-stage programs so each compiled
    artifact stays small."""
    jaxcache.enable()
    if _FUSED_STAGES:
        _note_donation(_INGEST_BATCH_DONATED + _PAIRING_DONATED)
        px, py, qx, qy, pair_mask, all_valid = _fused_ingest_batch(
            pk, sig_x, sig_sign, u0, u1, rand_bits, mask
        )
        prod = _fused_pairing(px, py, qx, qy, pair_mask)
        return _stage_final_with_valid(prod, all_valid)
    sig, all_valid = _stage_g2_decompress(sig_x, sig_sign, mask)
    hx, hy = _stage_hash_to_g2(u0, u1, mask)
    px, py, qx, qy, pair_mask = _stage_prepare_batch(
        pk, hx, hy, sig, rand_bits, mask
    )
    f = _stage_miller(px, py, qx, qy)
    prod = _stage_product(f, pair_mask)
    return _stage_final_with_valid(prod, all_valid)


def run_verify_same_message_ingest_async(
    pk: C.JacPoint, h, sig_x, sig_sign, rand_bits, mask
):
    """Same-message verify with device-side signature decompression
    (the message is hashed once on host — amortized across the whole
    group by the attData-keyed queue)."""
    jaxcache.enable()
    if _FUSED_STAGES:
        _note_donation(_INGEST_SAME_MSG_DONATED + _PAIRING_DONATED)
        px, py, qx, qy, pair_mask, all_valid = (
            _fused_ingest_same_message(
                pk, h[0], h[1], sig_x, sig_sign, rand_bits, mask
            )
        )
        prod = _fused_pairing(px, py, qx, qy, pair_mask)
        return _stage_final_with_valid(prod, all_valid)
    sig, all_valid = _stage_g2_decompress(sig_x, sig_sign, mask)
    px, py, qx, qy, pair_mask = _stage_prepare_same_message(
        pk, h[0], h[1], sig, rand_bits, mask
    )
    f = _stage_miller(px, py, qx, qy)
    prod = _stage_product(f, pair_mask)
    return _stage_final_with_valid(prod, all_valid)


def _prepare_same_message_impl(
    pk: C.JacPoint, hx, hy, sig: C.JacPoint, bits, mask
):
    """Both random-weighted MSMs (aggregateWithRandomness on device —
    the reference's measured main-thread bottleneck, jobItem.ts:60-75)
    + pairing-input assembly (2 pairs)."""
    if jax.default_backend() == "tpu" and bits.ndim == 2:
        from ..ops import pallas_ladder as PL

        rpk = PL.g1_scalar_mul(pk.x, pk.y, bits, pk.inf)
        rsig = PL.g2_scalar_mul(sig.x, sig.y, bits, sig.inf)
    else:
        rpk = C.scalar_mul(C.FQ_OPS, pk.x, pk.y, bits, pk.inf)
        rsig = C.scalar_mul(C.FQ2_OPS, sig.x, sig.y, bits, sig.inf)
    rpk = C.jac_select(
        C.FQ_OPS, mask, rpk, C.jac_infinity(C.FQ_OPS, mask.shape)
    )
    rsig = C.jac_select(
        C.FQ2_OPS, mask, rsig, C.jac_infinity(C.FQ2_OPS, mask.shape)
    )
    apk_aff = _to_affine(C.FQ_OPS, C.jac_sum_scan(C.FQ_OPS, rpk))
    asig_aff = _to_affine(C.FQ2_OPS, C.jac_sum_scan(C.FQ2_OPS, rsig))
    ngx, ngy = _g1_neg_gen((1,))
    px = _cat_fq(apk_aff[0], ngx)
    py = _cat_fq(apk_aff[1], ngy)
    qx = _cat_fq2((hx[0], hx[1]), asig_aff[0])
    qy = _cat_fq2((hy[0], hy[1]), asig_aff[1])
    return px, py, qx, qy, jnp.asarray([True, True])


_stage_prepare_same_message = jax.jit(_prepare_same_message_impl)


def _miller_impl(px, py, qx, qy):
    """Miller loop body with the Pallas/XLA split resolved at TRACE
    time — shared by the per-stage jit, the fused pairing program,
    and the whole-bucket mesh programs."""
    if _pallas_pairing_on():
        from ..ops import pallas_pairing as PP

        return PP.miller_loop(px, py, qx, qy)
    return pairing.miller_loop(px, py, qx, qy)


def _product_impl(f, mask):
    if _pallas_pairing_on():
        from ..ops import pallas_pairing as PP

        return PP.fq12_masked_product(f, mask)
    return pairing._fq12_masked_product(f, mask)


def _final_expo_impl(prod):
    if _pallas_pairing_on():
        from ..ops import pallas_pairing as PP

        return pairing.fq12_is_one(PP.final_exponentiation(prod))
    return pairing.fq12_is_one(pairing.final_exponentiation(prod))


_stage_miller_xla = jax.jit(pairing.miller_loop)
_stage_product_xla = jax.jit(pairing._fq12_masked_product)


@jax.jit
def _stage_product_pallas(f, mask):
    from ..ops import pallas_pairing as PP

    return PP.fq12_masked_product(f, mask)


def _stage_product(f, mask):
    """Masked pairing-product reduction: lane-halving VMEM kernel on
    TPU for big buckets, XLA scan+tree elsewhere."""
    if _pallas_pairing_on():
        return _stage_product_pallas(f, mask)
    return _stage_product_xla(f, mask)


def _pallas_pairing_on() -> bool:
    """The fused Miller/final-exp kernels run only on real TPUs (the
    XLA scan path stays as CPU fallback + differential oracle)."""
    return jax.default_backend() == "tpu"


@jax.jit
def _stage_miller_pallas(px, py, qx, qy):
    from ..ops import pallas_pairing as PP

    return PP.miller_loop(px, py, qx, qy)


def _stage_miller(px, py, qx, qy):
    """Miller loop: VMEM-resident Pallas ladder on TPU (the round-3
    device-time wall — 63 scan steps round-tripping the Fq12 state
    through HBM), XLA scan elsewhere."""
    if _pallas_pairing_on():
        return _stage_miller_pallas(px, py, qx, qy)
    return _stage_miller_xla(px, py, qx, qy)


@jax.jit
def _stage_final_xla(prod):
    return pairing.fq12_is_one(pairing.final_exponentiation(prod))


@jax.jit
def _stage_final_pallas(prod):
    from ..ops import pallas_pairing as PP

    return pairing.fq12_is_one(PP.final_exponentiation(prod))


def _stage_final(prod):
    """Shared final exponentiation + ==1 test. Batch shape () — one
    compile serves every bucket size."""
    if _pallas_pairing_on():
        return _stage_final_pallas(prod)
    return _stage_final_xla(prod)


# --- fused stage programs ---------------------------------------------------
#
# The ≤3-program wave composition (ISSUE 16): prepare (decompress +
# hash-to-G2 + ladders + aggregation + pairing-input assembly),
# pairing (miller + product), final (batch shape (), shared with the
# per-stage path). Each fused body is a composition of the SAME
# un-jitted `*_impl` functions the per-stage programs jit, wrapped in
# `jax.named_scope` regions so profiler captures keep per-sub-stage
# attribution inside the fused graphs. Input buffers are DONATED to
# the fused programs on TPU (`donate_argnums`): a wave's limb tensors
# are built fresh per dispatch and never reused by the host, so XLA
# may reuse their device memory for outputs — which is what lets the
# double-buffered verifier keep depth>1 waves in flight without 2x
# peak HBM. Donation is skipped off-TPU where the CPU backend ignores
# it with a per-dispatch warning.

_DONATION_ARMED = jax.default_backend() == "tpu"
# donated argument positions per fused entry (the big per-wave limb
# tensors; small masks/signs stay undonated)
_INGEST_BATCH_DONATE = (0, 1, 3, 4, 5)  # pk, sig_x, u0, u1, bits
_INGEST_SAME_MSG_DONATE = (0, 2, 5)  # pk, sig_x, bits
_PAIRING_DONATE = (0, 1, 2, 3)  # px, py, qx, qy
_INGEST_BATCH_DONATED = len(_INGEST_BATCH_DONATE)
_INGEST_SAME_MSG_DONATED = len(_INGEST_SAME_MSG_DONATE)
_PAIRING_DONATED = len(_PAIRING_DONATE)


def _donate(*argnums):
    return argnums if _DONATION_ARMED else ()


def donation_armed() -> bool:
    """Whether fused dispatches donate their input buffers (TPU)."""
    return _DONATION_ARMED


def _note_donation(n: int) -> None:
    """Count donated-buffer reuse opportunities handed to XLA (feeds
    lodestar_jax_donated_buffer_reuse_total; honest 0 off-TPU)."""
    if _DONATION_ARMED:
        t = _telemetry.get_telemetry()
        if t is not None:
            t.note_donation(n)


def _fused_ingest_batch_fn(pk, sig_x, sig_sign, u0, u1, bits, mask):
    with jax.named_scope("g2_decompress"):
        sig, all_valid = _g2_decompress_impl(sig_x, sig_sign, mask)
    with jax.named_scope("hash_to_g2"):
        hx, hy = _hash_to_g2_impl(u0, u1, mask)
    with jax.named_scope("prepare"):
        px, py, qx, qy, pair_mask = _prepare_batch_impl(
            pk, hx, hy, sig, bits, mask
        )
    return px, py, qx, qy, pair_mask, all_valid


def _fused_ingest_same_message_fn(
    pk, hx, hy, sig_x, sig_sign, bits, mask
):
    with jax.named_scope("g2_decompress"):
        sig, all_valid = _g2_decompress_impl(sig_x, sig_sign, mask)
    with jax.named_scope("prepare"):
        px, py, qx, qy, pair_mask = _prepare_same_message_impl(
            pk, hx, hy, sig, bits, mask
        )
    return px, py, qx, qy, pair_mask, all_valid


def _fused_pairing_fn(px, py, qx, qy, pair_mask):
    with jax.named_scope("miller"):
        f = _miller_impl(px, py, qx, qy)
    with jax.named_scope("product"):
        return _product_impl(f, pair_mask)


_fused_ingest_batch = jax.jit(
    _fused_ingest_batch_fn, donate_argnums=_donate(*_INGEST_BATCH_DONATE)
)
_fused_ingest_same_message = jax.jit(
    _fused_ingest_same_message_fn,
    donate_argnums=_donate(*_INGEST_SAME_MSG_DONATE),
)
_fused_pairing = jax.jit(
    _fused_pairing_fn, donate_argnums=_donate(*_PAIRING_DONATE)
)


# --- device telemetry instrumentation --------------------------------------
#
# Every jit entry point of the pipeline is wrapped so the telemetry
# layer (metrics/device.py) can attribute backend compiles to a stage,
# detect retraces (a compile for an argument signature the entry point
# already served — the fingerprint of a clear_caches / backend-switch
# storm), and time dispatches. With no telemetry installed each
# wrapper is a single attribute check, so benches and tools measure
# the bare pipeline unless they opt in. Only HOST-side entry points
# are wrapped; a stage that another stage calls from INSIDE a jit
# must call an UN-instrumented impl (_final_expo_impl, the fused
# bodies' sub-stage impls) or the tracer's call would be recorded as
# a dispatch. The fused programs are instrumented under the 3-row
# stage names of COVERAGE.md's re-cut budget table: "prepare" (both
# fused ingest entries — distinct arg signatures keep the retrace
# detector honest), "pairing", "final" (shared with the per-stage
# path).

_fused_ingest_batch = _telemetry.instrument_stage(
    "prepare", _fused_ingest_batch
)
_fused_ingest_same_message = _telemetry.instrument_stage(
    "prepare", _fused_ingest_same_message
)
_fused_pairing = _telemetry.instrument_stage("pairing", _fused_pairing)

_stage_prepare_batch = _telemetry.instrument_stage(
    "prepare_batch", _stage_prepare_batch
)
_stage_prepare_same_message = _telemetry.instrument_stage(
    "prepare_same_message", _stage_prepare_same_message
)
_stage_g2_sqrt = _telemetry.instrument_stage("g2_sqrt", _stage_g2_sqrt)
_stage_g2_subgroup = _telemetry.instrument_stage(
    "g2_subgroup", _stage_g2_subgroup
)
_stage_sswu_iso = _telemetry.instrument_stage("sswu_iso", _stage_sswu_iso)
_stage_cofactor = _telemetry.instrument_stage("cofactor", _stage_cofactor)
_stage_miller = _telemetry.instrument_stage("miller", _stage_miller)
_stage_product = _telemetry.instrument_stage("product", _stage_product)
_stage_final = _telemetry.instrument_stage("final", _stage_final)
_stage_final_with_valid = _telemetry.instrument_stage(
    "final", _stage_final_with_valid
)


def _run_pipeline(prepare, pk, h, sig, rand_bits, mask):
    px, py, qx, qy, pair_mask = prepare(
        pk, h[0], h[1], sig, rand_bits, mask
    )
    if _FUSED_STAGES:
        # host-prepped waves still land in ≤3 programs: per-stage
        # prepare + fused pairing + final
        _note_donation(_PAIRING_DONATED)
        prod = _fused_pairing(px, py, qx, qy, pair_mask)
        return _stage_final(prod)
    f = _stage_miller(px, py, qx, qy)
    prod = _stage_product(f, pair_mask)
    return _stage_final(prod)


# --- host-orchestrated kernels --------------------------------------------


def run_verify_batch_async(
    pk: C.JacPoint, h, sig: C.JacPoint, rand_bits, mask
):
    """Like run_verify_batch but returns the device () bool WITHOUT
    reading it back. Through the tunneled TPU a fresh-result readback
    costs ~100 ms (measured; dispatches are ~0.1 ms), so callers that
    can batch verdicts submit many verifies and read once — the same
    amortization the reference's 100 ms gossip buffering makes
    (index.ts:59-74)."""
    jaxcache.enable()
    return _run_pipeline(_stage_prepare_batch, pk, h, sig, rand_bits, mask)


def run_verify_batch(pk: C.JacPoint, h, sig: C.JacPoint, rand_bits, mask) -> bool:
    """Random-linear-combination batch verify of n (pk, msg, sig) sets:

      prod_i e(r_i*pk_i, H_i) * e(-g1, sum_i r_i*sig_i) == 1

    pk: G1 affine batch (n,); h: (hx, hy) G2 Fq2 batches (n,);
    sig: G2 affine batch (n,). rand_bits: (n, RAND_BITS) bool MSB-first,
    r_i != 0. mask: (n,) bool — False slots are padding. Reference:
    blst verifyMultipleAggregateSignatures (maybeBatch.ts:17-44); a
    batch failure means callers retry per set (index.ts:552-563).
    """
    jaxcache.enable()
    if not np.any(np.asarray(mask)):
        return True  # all-padding call is vacuously true
    return bool(
        _run_pipeline(_stage_prepare_batch, pk, h, sig, rand_bits, mask)
    )


def run_verify_same_message(pk: C.JacPoint, h, sig: C.JacPoint, rand_bits, mask) -> bool:
    """Same-message batch verify: n (pk_i, sig_i) on ONE message H:

      e(sum r_i*pk_i, H) * e(-g1, sum r_i*sig_i) == 1

    `aggregateWithRandomness` + one pairing check fused on device — the
    reference computes the MSMs on the main thread (jobItem.ts:60-75),
    its documented scaling limit. h: (hx, hy) with batch shape (1,).
    """
    jaxcache.enable()
    if not np.any(np.asarray(mask)):
        return True
    return bool(
        _run_pipeline(
            _stage_prepare_same_message, pk, h, sig, rand_bits, mask
        )
    )


# --- whole-bucket mesh programs ---------------------------------------------
#
# Multi-chip verify where each chip owns WHOLE sub-buckets (ISSUE 16):
# the local body below is the same `*_impl` composition as the fused
# single-chip programs, traced per shard by parallel.whole_bucket_verify
# with collective-free local shapes; the only collective in the whole
# program is one () psum at the verdict. Programs are cached per
# (kind, mesh) — jit also specializes on shardings, so these are
# distinct executables from the single-host ones and mesh verifiers
# never consult the warm registry (see the warmup section).


def _verify_batch_local(pk, hx, hy, sig, bits, mask):
    """Per-shard collective-free batch verify (host-hashed path)."""
    px, py, qx, qy, pair_mask = _prepare_batch_impl(
        pk, hx, hy, sig, bits, mask
    )
    f = _miller_impl(px, py, qx, qy)
    return _final_expo_impl(_product_impl(f, pair_mask))


def _verify_same_message_local(pk, hx, hy, sig_x, sig_sign, bits, mask):
    """Per-shard same-message verify; the (1,)-batch hash point is
    replicated (every shard pairs its aggregate against the same H)."""
    sig, all_valid = _g2_decompress_impl(sig_x, sig_sign, mask)
    px, py, qx, qy, pair_mask = _prepare_same_message_impl(
        pk, hx, hy, sig, bits, mask
    )
    f = _miller_impl(px, py, qx, qy)
    ok = _final_expo_impl(_product_impl(f, pair_mask))
    return jnp.logical_and(ok, all_valid)


def _verify_ingest_local(pk, sig_x, sig_sign, u0, u1, bits, mask):
    """Per-shard verify with device-side ingest (decompress + hash)."""
    sig, all_valid = _g2_decompress_impl(sig_x, sig_sign, mask)
    hx, hy = _hash_to_g2_impl(u0, u1, mask)
    px, py, qx, qy, pair_mask = _prepare_batch_impl(
        pk, hx, hy, sig, bits, mask
    )
    f = _miller_impl(px, py, qx, qy)
    ok = _final_expo_impl(_product_impl(f, pair_mask))
    return jnp.logical_and(ok, all_valid)


_MESH_LOCALS = {
    "batch": (_verify_batch_local, 6, ()),
    "same_message": (_verify_same_message_local, 7, (1, 2)),
    "ingest_batch": (_verify_ingest_local, 7, ()),
}


@functools.lru_cache(maxsize=8)
def _mesh_program(kind: str, mesh):
    from .. import parallel

    local, n_args, repl = _MESH_LOCALS[kind]
    return jax.jit(
        parallel.whole_bucket_verify(mesh, local, n_args, repl)
    )


def _run_mesh(kind, mesh, *args):
    return _mesh_program(kind, mesh)(*args)


# one stage for all three kinds: the kind string enters the retrace
# detector's signature, so per-kind compiles stay distinguishable
_run_mesh = _telemetry.instrument_stage("mesh_verify", _run_mesh)


def run_verify_batch_mesh(mesh, pk, h, sig, rand_bits, mask):
    """Whole-bucket mesh batch verify; returns the device () bool
    without readback. Batch args must be placed with
    parallel.shard_batch (leading axis divisible by the mesh size)."""
    jaxcache.enable()
    return _run_mesh("batch", mesh, pk, h[0], h[1], sig, rand_bits, mask)


def run_verify_same_message_mesh(mesh, pk, h, sig_x, sig_sign, rand_bits, mask):
    jaxcache.enable()
    return _run_mesh(
        "same_message", mesh, pk, h[0], h[1], sig_x, sig_sign, rand_bits, mask
    )


def run_verify_batch_ingest_mesh(mesh, pk, sig_x, sig_sign, u0, u1, rand_bits, mask):
    jaxcache.enable()
    return _run_mesh(
        "ingest_batch", mesh, pk, sig_x, sig_sign, u0, u1, rand_bits, mask
    )


# --- small helpers ---------------------------------------------------------


def _cat_fq(a: L.Lv, b: L.Lv) -> L.Lv:
    a, b = L.normalize(a), L.normalize(b)
    return L.Lv(jnp.concatenate([a.v, b.v], 0), a.lo, a.hi)


def _cat_fq2(a, b):
    return (_cat_fq(a[0], b[0]), _cat_fq(a[1], b[1]))


# The one bucket ladder: retry-chunk rungs (<=128, reference job
# granularity), the rolling-accumulator ingest rungs {256, 512}, and
# the bulk-wave TOP rung. bucket_size, default_warmup_sizes, and the
# verifier's warmup all derive from the LIVE tuple — add a rung and
# warmup covers it automatically. The top rung is a KNOB
# (set_ladder_top): the device autotuner (device/autotune.py) may
# trade the 2048 bulk bucket for 1024 on hosts where the bigger
# compile/dispatch does not pay for its padding.
_MID_RUNGS = (4, 8, 16, 32, 64, 128, 256, 512)
LADDER_TOPS = (1024, 2048)  # autotune-selectable top rungs
BUCKET_LADDER = _MID_RUNGS + (2048,)


def ladder_top() -> int:
    """The live top (bulk-wave) bucket rung."""
    return BUCKET_LADDER[-1]


def set_ladder_top(n: int, rewarm: bool = True) -> None:
    """Swap the bulk-wave top rung of the ladder. Sizes that fall out
    of the ladder are dropped from the warm registry — they can no
    longer be dispatched, and counting them warm would overstate the
    `lodestar_jax_warmup_*` gauges. An INCOMING top rung was never
    compiled: when a warmup policy exists in this process, kick the
    background warmup for every cold ingest-eligible rung, or a
    cold-fallback verifier would route the bulk bucket host_cold
    forever (nothing else warms a size the fallback never
    dispatches). rewarm=False defers that to a caller that re-warms
    once for a whole batch of knob changes (autotune.apply_config)."""
    global BUCKET_LADDER
    n = int(n)
    if n < _MID_RUNGS[-1]:
        raise ValueError(
            f"ladder top {n} below the largest mid rung {_MID_RUNGS[-1]}"
        )
    BUCKET_LADDER = tuple(b for b in _MID_RUNGS if b < n) + (n,)
    live = set(BUCKET_LADDER)
    stale = {k for k in _INGEST_WARM if k[1] not in live}
    _INGEST_WARM.difference_update(stale)
    if rewarm and _WARMUP_STARTED:
        newly = tuple(
            b for b in default_warmup_sizes() if not ingest_is_warm(b)
        )
        if newly:
            warmup_ingest(newly)


def bucket_size(n: int, buckets=None) -> int:
    """Smallest bucket >= n. Small sizes mirror the reference's <=128
    sets/job chunks (chain/bls/multithread/index.ts:48-56). The mid
    sizes {256, 512} are the device-ingest-eligible rungs the
    verifier's rolling gossip accumulator flushes into — without them
    the ladder jumped 128 -> 2048 and steady-state trickle traffic
    either rode the slow host decompress/hash path or paid 16x
    padding. Above 512 whole waves pack into one top-rung device
    bucket (per-op device cost is batch-flat to ~2048, so padding
    there is nearly free; each extra bucket size is an extra
    multi-minute XLA compile, pre-warmed by warmup_ingest). `buckets`
    defaults to the LIVE ladder so a set_ladder_top() retune is seen
    by every later call."""
    if buckets is None:
        buckets = BUCKET_LADDER
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


# --- ingest warmup ----------------------------------------------------------
#
# Each ingest bucket size is its own multi-minute XLA compile (per
# stage, per shape). A node that waits for the first gossip lull to
# pay that compile stalls its verify pipeline, so the verifier can
# (a) pre-warm the ingest sizes on a background thread at start and
# (b) route buckets whose compile is still cold to the host
# decompress/hash path (TpuBlsVerifier host_fallback_when_cold). The
# registry below tracks which (pipeline, size) pairs are warm — the
# batch and same-message ingest paths are DISTINCT jit programs, so a
# dispatch on one must not mark the other's cold compile as warm. A
# pair also becomes warm the first time a live dispatch completes at
# it. Marks describe the UNSHARDED single-host executables: jit also
# specializes on input shardings, so mesh verifiers never consult the
# registry (TpuBlsVerifier.start_warmup disables their cold fallback
# and they dispatch directly, paying each size's compile inline once).

_INGEST_WARM: set[tuple[str, int]] = set()
_WARMUP_LOCK = threading.Lock()
_WARMUP_THREAD: threading.Thread | None = None
# has warmup_ingest ever run in this process? Gates the automatic
# re-warm on live retunes (set_ingest_min_bucket / backend switches):
# processes that never opted into warmup never get background compiles
# sprung on them by a knob change.
_WARMUP_STARTED = False
# sizes requested while a warmup thread was already running; the
# thread drains this set before exiting (guarded by _WARMUP_LOCK)
_WARMUP_WANT: set[int] = set()

# maintenance gate (device/executor.py): the node wires this to the
# executor's maintenance_checkpoint so the warmup thread YIELDS the
# device between compiles whenever a deadline client (live gossip)
# has work pending — node-start warmup no longer races live traffic.
# None = no executor: warmup runs back-to-back, the pre-executor
# behavior (tests, tools, standalone verifiers).
_MAINT_GATE = None


def set_maintenance_gate(gate) -> None:
    """Install (or clear, with None) the between-compiles yield hook
    called by warmup_ingest's warm loop."""
    global _MAINT_GATE
    _MAINT_GATE = gate


def _maintenance_checkpoint() -> None:
    """Invoke the installed maintenance gate, tolerating any failure:
    yielding is an optimization — a broken gate must never kill the
    warmup thread (a size left cold rides the host fallback forever)."""
    gate = _MAINT_GATE
    if gate is not None:
        try:
            gate()
        except Exception:
            pass


# health gate (device/health.py): the node wires this to the health
# tracker's device_allowed so warmup SUSPENDS while the device is
# quarantined — a compile storm is the last thing a sick chip needs,
# and the probes need the device to themselves. Sizes left cold are
# re-warmed by the reinstatement warmup kick. None = no tracker:
# warmup runs unconditionally (tests, tools, standalone verifiers).
_HEALTH_GATE = None


def set_health_gate(gate) -> None:
    """Install (or clear, with None) the device-allowed predicate
    consulted before each warmup compile."""
    global _HEALTH_GATE
    _HEALTH_GATE = gate


def _device_dispatch_allowed() -> bool:
    """Consult the installed health gate, tolerating any failure (a
    broken gate must never block warmup — fail open, like the
    maintenance gate fails silent)."""
    gate = _HEALTH_GATE
    if gate is None:
        return True
    try:
        return bool(gate())
    except Exception:
        return True


def ingest_is_warm(b: int, kind: str = "batch") -> bool:
    return (kind, b) in _INGEST_WARM


def mark_ingest_warm(b: int, kind: str = "batch") -> None:
    _INGEST_WARM.add((kind, b))




# generation counter for the warm registry: invalidation bumps it so
# a warmup dispatch that STARTED under the previous generation (its
# executable died with the cache clear) cannot land a stale mark when
# it completes. The check-and-mark and the bump-and-clear each run
# under the lock, or a mark could slip in between them.
_WARM_GEN = 0
_WARM_GEN_LOCK = threading.Lock()


def invalidate_ingest_warm(rewarm: bool = True) -> None:
    """Drop every warm mark. Called when a limb-backend switch clears
    the jit caches (ops/limbs.set_backend): the compiled executables
    the marks described are gone, and a cold-fallback verifier
    trusting a stale mark would dispatch a live bucket straight into
    the recompile the mark claimed was paid. When a warmup ran in this
    process, re-warm the eligible rungs in the background (persistent
    cache makes a switch back near-free). The registry also carries
    the KZG MSM rung marks (kind "msm", ops/msm.py) whose executables
    the same cache clear killed — their rewarm is kicked through the
    msm module's own warmup policy, or the DA workload would ride the
    host fallback for the rest of the process."""
    global _WARM_GEN
    with _WARM_GEN_LOCK:
        _WARM_GEN += 1
        _INGEST_WARM.clear()
    if rewarm and _WARMUP_STARTED:
        warmup_ingest()
    if rewarm:
        import sys

        m = sys.modules.get("lodestar_tpu.ops.msm")
        if m is not None:
            m.rewarm_async()


WARMUP_PIPELINES = ("batch", "same_message")


def warmup_progress(gate: int | None = None) -> dict[str, tuple[int, int]]:
    """Per-pipeline warmup progress: {pipeline: (warm, eligible)}.
    Feeds the `lodestar_jax_warmup_*` gauges (metrics/device.py) so a
    warmup that never finishes is visible instead of looking like a
    slow TPU (cold sizes ride the host fallback forever)."""
    sizes = default_warmup_sizes(gate)
    return {
        kind: (
            sum(1 for b in sizes if (kind, b) in _INGEST_WARM),
            len(sizes),
        )
        for kind in WARMUP_PIPELINES
    }


def default_warmup_sizes(gate: int | None = None) -> tuple[int, ...]:
    """Every ingest-eligible rung of the ladder (gate defaults to the
    module knob; verifiers pass their own override)."""
    if gate is None:
        gate = ingest_min_bucket()
    return tuple(b for b in BUCKET_LADDER if b >= gate)


def _warm_one(b: int, same_message: bool) -> None:
    """Compile (or load from the persistent cache) the ingest pipeline
    for bucket size b by running one padded dispatch to completion."""
    import jax.numpy as jnp

    from ..ops import tower
    from . import api

    from ..crypto.bls.signature import sign, sk_to_pk

    msg = b"\x5a" * 32
    sig = sign(7, msg)
    xc0, xc1, s_sign, ok = api.parse_signature(sig)
    assert ok
    pk = api.decompress_pubkey(sk_to_pk(7))
    draws = api.message_draws(msg)
    pk_dev = C.g1_batch_from_ints([pk] * b)
    sig_x = tower.fq2_from_ints([(xc0, xc1)] * b)
    sig_sign = jnp.asarray([s_sign] * b)
    bits = C.scalars_to_bits([3] * b, RAND_BITS)
    mask = jnp.asarray([True] * b)
    if same_message:
        h = api.message_to_g2(msg)
        h_dev = C.g2_batch_from_ints([h])
        out = run_verify_same_message_ingest_async(
            pk_dev, (h_dev.x, h_dev.y), sig_x, sig_sign, bits, mask
        )
    else:
        u0 = tower.fq2_from_ints([draws[0]] * b)
        u1 = tower.fq2_from_ints([draws[1]] * b)
        out = run_verify_batch_ingest_async(
            pk_dev, sig_x, sig_sign, u0, u1, bits, mask
        )
    if not bool(out):  # blocks until the compile + run completes
        raise RuntimeError(f"ingest warmup verify failed at bucket {b}")


def warmup_ingest(
    sizes: tuple[int, ...] | None = None,
    block: bool = False,
    same_message: bool = True,
) -> threading.Thread | None:
    """Pre-compile the device-ingest pipeline for the given bucket
    sizes (default: every ingest-eligible rung) on a background
    thread, marking each size warm as it completes. The persistent
    compilation cache (utils/jaxcache.py) makes this a disk load on
    every process after the first. Idempotent; block=True runs
    synchronously (tests, tools)."""
    global _WARMUP_THREAD, _WARMUP_STARTED
    jaxcache.enable()
    _WARMUP_STARTED = True
    want = tuple(sizes) if sizes is not None else default_warmup_sizes()

    def warm_one_marked(b, kind, log, msg):
        """One warmup dispatch + mark, generation-guarded: if the
        registry was invalidated while the dispatch ran (a backend
        switch killed the executable this compile produced), the
        stale mark must NOT land — the size re-warms on the next
        kick under the new generation instead."""
        gen = _WARM_GEN
        try:
            _warm_one(b, same_message=(kind == "same_message"))
            with _WARM_GEN_LOCK:
                if _WARM_GEN == gen:
                    mark_ingest_warm(b, kind)
        except Exception as e:
            # warmup is an optimization: the size stays cold and the
            # verifier keeps its host fallback — but say so, or the
            # node silently runs degraded forever
            log.warn(msg, {"bucket": b, "err": repr(e)})

    def warm_sizes(seq, log):
        for b in sorted(set(seq)):
            if not _device_dispatch_allowed():
                # device quarantined (device/health.py): suspend —
                # the remaining sizes stay cold and the reinstatement
                # warmup kick re-runs this loop when the device comes
                # back. Warming THROUGH a quarantine would race the
                # known-answer probes for a chip being judged.
                log.warn(
                    "warmup suspended: device path quarantined",
                    {"remaining": sorted(set(seq))},
                )
                return
            if not ingest_is_warm(b, "batch"):
                # yield the device to pending deadline work before
                # each compile (maintenance-class discipline,
                # device/executor.py) — a multi-second compile must
                # not start in front of a queued gossip wave
                _maintenance_checkpoint()
                # only the batch pipeline becomes warm here — the
                # same-message program is a different compile
                warm_one_marked(
                    b,
                    "batch",
                    log,
                    "ingest warmup failed; bucket stays on host path",
                )
            if same_message and not ingest_is_warm(b, "same_message"):
                _maintenance_checkpoint()
                warm_one_marked(
                    b,
                    "same_message",
                    log,
                    "same-message ingest warmup failed",
                )

    def run():
        global _WARMUP_THREAD
        from ..logger import get_logger

        log = get_logger("bls-warmup")
        warm_sizes(want, log)
        # drain sizes enqueued while this thread ran (a live retune —
        # gate lowered or backend switched — kicks warmup again; the
        # request must not be lost just because a thread was active).
        # The emptiness check and the thread deregistration happen
        # under ONE lock hold: an enqueue serialized before it is
        # drained here; one after it sees no live thread and spawns.
        while True:
            with _WARMUP_LOCK:
                extra = sorted(_WARMUP_WANT)
                _WARMUP_WANT.clear()
                if not extra:
                    if _WARMUP_THREAD is threading.current_thread():
                        _WARMUP_THREAD = None
                    return
            warm_sizes(extra, log)

    if block:
        run()
        return None
    with _WARMUP_LOCK:
        if _WARMUP_THREAD is not None and _WARMUP_THREAD.is_alive():
            _WARMUP_WANT.update(want)
            return _WARMUP_THREAD
        _WARMUP_THREAD = threading.Thread(
            target=run, name="bls-ingest-warmup", daemon=True
        )
        _WARMUP_THREAD.start()
        return _WARMUP_THREAD

"""TPU-backed BLS verifier service — the reference's north-star seam.

Reference analog: `IBlsVerifier` + `BlsMultiThreadWorkerPool`
(chain/bls/interface.ts:25-68, chain/bls/multithread/index.ts:113,
SURVEY.md §2.3). The pool's contract is kept exactly:

  - `verify_signature_sets(sets, batchable, priority)` — batchable sets
    are buffered up to MAX_BUFFER_WAIT_MS / MAX_BUFFERED_SIGS and merged
    with other callers' work (index.ts:59-74, 320-339); jobs are packed
    to <= MAX_SIGNATURE_SETS_PER_JOB sets (index.ts:48-56, 519-534);
    a failed batch is re-verified set-by-set so one bad signature only
    fails its own caller (interface.ts:4-12, worker.ts:88-103).
  - `verify_signature_sets_same_message(sets, message)` — random-
    weighted aggregation + one pairing check; on failure, per-signature
    retry fan-out (jobItem.ts:96-125, index.ts:552-563).
  - `can_accept_work()` — backpressure for the gossip processor
    (index.ts:149-155, network/processor/index.ts).

What changes vs the reference: the N-1 worker threads and their 5 ms
postMessage round-trip are replaced by one async dispatch queue in
front of jitted TPU kernels (bls/kernels.py); `aggregateWithRandomness`
— the reference's measured main-thread bottleneck (jobItem.ts:60-70) —
runs inside the device program instead of on the host.
"""

from __future__ import annotations

import asyncio
import secrets
import time
from dataclasses import dataclass, field

import jax.numpy as jnp

from ..crypto.bls import curve as oc
from ..ops import curve as C
from . import api, kernels

MAX_BUFFER_WAIT_MS = 100  # index.ts:74
MAX_BUFFERED_SIGS = 32  # index.ts:65
MAX_SIGNATURE_SETS_PER_JOB = 128  # index.ts:56
QUEUE_MAX_LENGTH = 512  # canAcceptWork threshold, index.ts:149-155


def _rand_scalars(n: int):
    """Nonzero 64-bit blinding scalars (blst batch-verify width)."""
    return [secrets.randbits(kernels.RAND_BITS) | 1 for _ in range(n)]


@dataclass
class _PreparedSet:
    pk: tuple  # affine G1 ints
    h: tuple  # affine G2 ints (hashed message)
    sig: tuple | None  # affine G2 ints, None = invalid/identity


@dataclass
class _Job:
    sets: list
    future: asyncio.Future
    batchable: bool
    enqueued_at: float = 0.0


class BlsVerifierMetrics:
    """Counter names mirror lodestar_bls_thread_pool_* so the reference
    Grafana dashboard maps 1:1 (metrics/metrics/lodestar.ts:403-506)."""

    def __init__(self):
        self.job_groups_started = 0
        self.jobs_started = 0
        self.sig_sets_started = 0
        self.batch_retries = 0
        self.batch_sigs_success = 0
        self.same_message_retries = 0
        self.queue_length = 0
        self.total_job_wait_s = 0.0
        self.total_device_time_s = 0.0


class TpuBlsVerifier:
    """`IBlsVerifier` over TPU pairing kernels."""

    def __init__(
        self,
        max_buffer_wait_ms: int = MAX_BUFFER_WAIT_MS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        queue_max: int = QUEUE_MAX_LENGTH,
    ):
        self.metrics = BlsVerifierMetrics()
        self._max_wait = max_buffer_wait_ms / 1000.0
        self._max_buffered = max_buffered_sigs
        self._max_sets_per_job = MAX_SIGNATURE_SETS_PER_JOB
        self._queue_max = queue_max
        self._buffer: list[_Job] = []
        self._buffer_task: asyncio.Task | None = None
        # priority queue: (priority_class, seq) keeps FIFO within class;
        # priority jobs jump the queue (reference jobs.unshift,
        # chain/bls/interface.ts:19-22)
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._seq = 0
        self._runner: asyncio.Task | None = None
        self._closed = False

    # -- IBlsVerifier surface ------------------------------------------

    def can_accept_work(self) -> bool:
        return (
            not self._closed
            and self._queue.qsize() + len(self._buffer) < self._queue_max
        )

    async def verify_signature_sets(
        self,
        sets: list[api.SignatureSet],
        batchable: bool = False,
        priority: bool = False,
    ) -> bool:
        """True iff every set verifies. Malformed points -> False
        (maybeBatch.ts:17-44 semantics)."""
        self._ensure_runner()
        try:
            prepared = [self._prepare(s) for s in sets]
        except api.InvalidPointError:
            return False
        if any(p.sig is None for p in prepared):
            return False
        fut = asyncio.get_event_loop().create_future()
        job = _Job(prepared, fut, batchable)
        self.metrics.sig_sets_started += len(prepared)
        if batchable and len(prepared) < self._max_buffered:
            self._buffer.append(job)
            buffered = sum(len(j.sets) for j in self._buffer)
            if buffered >= self._max_buffered:
                self._flush_buffer()
            elif self._buffer_task is None:
                self._buffer_task = asyncio.ensure_future(
                    self._flush_after_wait()
                )
        else:
            self._enqueue([job], priority)
        return await fut

    async def verify_signature_sets_same_message(
        self, sets: list[api.SameMessageSet], message: bytes
    ) -> list[bool]:
        """Per-set verdicts for k (pubkey, signature) pairs on one
        message (jobItem.ts:50-92)."""
        self._ensure_runner()
        h = api.message_to_g2(message)
        prepared = []
        valid = []
        for s in sets:
            try:
                pk = api.decompress_pubkey(s.pubkey)
                sig = api.decompress_signature(s.signature)
            except api.InvalidPointError:
                pk, sig = None, None
            prepared.append((pk, sig))
            valid.append(pk is not None and sig is not None)
        live = [i for i, v in enumerate(valid) if v]
        if not live:
            return [False] * len(sets)
        results = [False] * len(sets)
        ok = await self._run_same_message(
            [prepared[i] for i in live], h
        )
        if ok:
            for i in live:
                results[i] = True
            return results
        # batch failed: per-signature retry fan-out (index.ts:552-563)
        self.metrics.same_message_retries += 1
        singles = await asyncio.gather(
            *(
                self._run_batch(
                    [_PreparedSet(prepared[i][0], h, prepared[i][1])]
                )
                for i in live
            )
        )
        for i, r in zip(live, singles):
            results[i] = r
        return results

    async def close(self):
        """Reject all pending work (the reference rejects queued jobs on
        worker termination, index.ts:311-318) and stop the runner."""
        self._closed = True
        if self._buffer_task:
            self._buffer_task.cancel()
            self._buffer_task = None
        err = RuntimeError("BLS verifier closed")
        for j in self._buffer:
            if not j.future.done():
                j.future.set_exception(err)
        self._buffer = []
        while not self._queue.empty():
            _, _, jobs = self._queue.get_nowait()
            for j in jobs:
                if not j.future.done():
                    j.future.set_exception(err)
        if self._runner:
            self._runner.cancel()
            self._runner = None

    # -- internals ------------------------------------------------------

    def _prepare(self, s: api.SignatureSet) -> _PreparedSet:
        pk = api.decompress_pubkey(s.pubkey)
        h = api.message_to_g2(s.message)
        sig = api.decompress_signature(s.signature)
        return _PreparedSet(pk, h, sig)

    def _ensure_runner(self):
        if self._closed:
            # the reference rejects work after termination (index.ts:311-318)
            raise RuntimeError("BLS verifier closed")
        if self._runner is None or self._runner.done():
            self._runner = asyncio.ensure_future(self._run_loop())

    def _enqueue(self, jobs: list[_Job], priority: bool = False):
        self.metrics.job_groups_started += 1
        now = time.monotonic()
        for j in jobs:
            j.enqueued_at = now
        self._seq += 1
        self._queue.put_nowait((0 if priority else 1, self._seq, jobs))
        self.metrics.queue_length = self._queue.qsize()

    def _flush_buffer(self):
        if self._buffer_task:
            self._buffer_task.cancel()
            self._buffer_task = None
        jobs, self._buffer = self._buffer, []
        if jobs:
            self._enqueue(jobs)

    async def _flush_after_wait(self):
        try:
            await asyncio.sleep(self._max_wait)
        except asyncio.CancelledError:
            return
        self._buffer_task = None
        self._flush_buffer()

    async def _run_loop(self):
        while not self._closed:
            _, _, jobs = await self._queue.get()
            self.metrics.queue_length = self._queue.qsize()
            t0 = time.monotonic()
            for j in jobs:
                self.metrics.total_job_wait_s += t0 - j.enqueued_at
            try:
                await self._execute_job_group(jobs)
            except asyncio.CancelledError:
                err = RuntimeError("BLS verifier closed")
                for j in jobs:
                    if not j.future.done():
                        j.future.set_exception(err)
                raise
            except Exception as e:  # defensive: fail the waiters
                for j in jobs:
                    if not j.future.done():
                        j.future.set_exception(e)
            self.metrics.total_device_time_s += time.monotonic() - t0

    async def _execute_job_group(self, jobs: list[_Job]):
        """Pack jobs into <=128-set chunks; verify each chunk as one
        random-lincomb batch; failed chunks retry per set
        (prepareWork/runJob, index.ts:357-534)."""
        # greedy packing preserving job boundaries
        chunks: list[list[_Job]] = []
        cur: list[_Job] = []
        cur_n = 0
        for j in jobs:
            n = len(j.sets)
            if cur and cur_n + n > self._max_sets_per_job:
                chunks.append(cur)
                cur, cur_n = [], 0
            cur.append(j)
            cur_n += n
        if cur:
            chunks.append(cur)
        for chunk in chunks:
            self.metrics.jobs_started += 1
            all_sets = [s for j in chunk for s in j.sets]
            ok = await self._run_batch(all_sets)
            if ok:
                self.metrics.batch_sigs_success += len(all_sets)
                for j in chunk:
                    if not j.future.done():
                        j.future.set_result(True)
                continue
            if len(chunk) == 1 and len(all_sets) == 1:
                if not chunk[0].future.done():
                    chunk[0].future.set_result(False)
                continue
            # batch failed: isolate per job, then per set (worker.ts:88-103)
            self.metrics.batch_retries += 1
            for j in chunk:
                verdicts = await asyncio.gather(
                    *(self._run_batch([s]) for s in j.sets)
                )
                if not j.future.done():
                    j.future.set_result(all(verdicts))

    async def _run_batch(self, sets: list[_PreparedSet]) -> bool:
        """Verify a list of sets as random-lincomb batches. Lists larger
        than one device bucket are split and AND-ed — a single job may
        legitimately exceed the per-call cap (e.g. a 64-block sync batch
        carries ~8,000 sets, index.ts:51)."""
        cap = self._max_sets_per_job
        if len(sets) > cap:
            parts = [
                sets[i : i + cap] for i in range(0, len(sets), cap)
            ]
            verdicts = await asyncio.gather(
                *(self._run_batch(p) for p in parts)
            )
            return all(verdicts)
        n = len(sets)
        b = kernels.bucket_size(n)
        pad = b - n
        pks = [s.pk for s in sets] + [oc.G1_GEN] * pad
        hs = [s.h for s in sets] + [oc.G2_GEN] * pad
        sigs = [s.sig for s in sets] + [oc.G2_GEN] * pad
        rand = _rand_scalars(b)
        pk_dev = C.g1_batch_from_ints(pks)
        h_dev = C.g2_batch_from_ints(hs)
        sig_dev = C.g2_batch_from_ints(sigs)
        bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
        mask = jnp.asarray([True] * n + [False] * pad)
        ok = await asyncio.get_event_loop().run_in_executor(
            None,
            lambda: kernels.run_verify_batch(
                pk_dev, (h_dev.x, h_dev.y), sig_dev, bits, mask
            ),
        )
        return ok

    async def _run_same_message(self, pairs, h) -> bool:
        """One fused aggregate+pairing check; splits above the device
        cap and ANDs (random weights keep each part sound)."""
        cap = self._max_sets_per_job
        if len(pairs) > cap:
            parts = [
                pairs[i : i + cap] for i in range(0, len(pairs), cap)
            ]
            verdicts = await asyncio.gather(
                *(self._run_same_message(p, h) for p in parts)
            )
            return all(verdicts)
        n = len(pairs)
        b = kernels.bucket_size(n)
        pad = b - n
        pks = [p for p, _ in pairs] + [oc.G1_GEN] * pad
        sigs = [s for _, s in pairs] + [oc.G2_GEN] * pad
        rand = _rand_scalars(b)
        pk_dev = C.g1_batch_from_ints(pks)
        sig_dev = C.g2_batch_from_ints(sigs)
        h_dev = C.g2_batch_from_ints([h])  # batch (1,)
        bits = C.scalars_to_bits(rand, kernels.RAND_BITS)
        mask = jnp.asarray([True] * n + [False] * pad)
        ok = await asyncio.get_event_loop().run_in_executor(
            None,
            lambda: kernels.run_verify_same_message(
                pk_dev, (h_dev.x, h_dev.y), sig_dev, bits, mask
            ),
        )
        return ok


class OracleBlsVerifier:
    """Single-threaded oracle-backed verifier — same interface, used in
    tests and as the differential reference (reference analog:
    BlsSingleThreadVerifier, chain/bls/singleThread.ts:8)."""

    def can_accept_work(self) -> bool:
        return True

    async def verify_signature_sets(
        self, sets, batchable=False, priority=False
    ) -> bool:
        from ..crypto.bls import pairing as op

        try:
            for s in sets:
                pk = api.decompress_pubkey(s.pubkey)
                h = api.message_to_g2(s.message)
                sig = api.decompress_signature(s.signature)
                if sig is None:
                    return False
                ok = op.pairing_product_is_one(
                    [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
                )
                if not ok:
                    return False
            return True
        except api.InvalidPointError:
            return False

    async def verify_signature_sets_same_message(self, sets, message):
        from ..crypto.bls import pairing as op

        h = api.message_to_g2(message)
        out = []
        for s in sets:
            try:
                pk = api.decompress_pubkey(s.pubkey)
                sig = api.decompress_signature(s.signature)
            except api.InvalidPointError:
                out.append(False)
                continue
            if sig is None:
                out.append(False)
                continue
            out.append(
                op.pairing_product_is_one(
                    [(pk, h), (oc.g1_neg(oc.G1_GEN), sig)]
                )
            )
        return out

    async def close(self):
        pass
